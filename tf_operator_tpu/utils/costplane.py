"""The device cost plane (ISSUE 20): compile ledger, HBM accountant,
step-time sentinel.

The observability stack watches every dispatch (DispatchLedger), every
request (autopsy) and every pod (telemetry federation) — this module
lights up the DEVICE plane underneath them:

``CompileLedger``
  Every ``jax.jit``/``pallas_call`` entry point in the hot paths
  (batching admission width classes, paged step/retire/swap/migrate/
  draft/verify programs, ``train_steps`` K classes, fused-BN variants)
  registers its compiles here with the TRIGGER that caused them (the
  width/K/pow2 class string), the abstract input shapes, the observed
  compile wall and the owning trace id.  Exported as
  ``compile_total{program,trigger}`` / ``compile_seconds{program}``
  plus a bounded event ring (``GET /debug/compiles``).

  Honesty note on the wall: ``wrap()`` times the FIRST call of each
  wrapped program instance — trace + compile + the first execution —
  because jax gives no portable hook between trace and execute.  That
  is the wall the serving loop actually stalls for on a cache miss, so
  it is the number an operator cares about; it is labeled
  ``first_call_seconds`` in the ring to keep the claim exact.
  ``note()`` registers a compile class with no wall at all (used where
  the callee compiles internally, e.g. the fused-BN ``pallas_call``
  variants, and re-measuring would mean double-compiling).

``HBMAccountant``
  A per-device ledger of the big allocations — weights, optimizer
  state, KV arena, swap staging, compiled-program temp peak (via
  ``compiled.memory_analysis()`` where a backend provides it) —
  exported as ``hbm_component_bytes{device,component}`` with
  ``hbm_device_limit_bytes{device}`` / ``hbm_headroom_bytes{device}``
  and a ``GET /debug/memory`` snapshot that also reports COVERAGE:
  accounted bytes vs what the backend says is live
  (``device.memory_stats()`` where available, the ``jax.live_arrays``
  sum as the CPU fallback).  The CPU-smoke acceptance pin is
  coverage >= 0.95 — an accountant that loses track of memory is
  worse than none.

``StepTimeSentinel``
  Rolling p50/p99 over the last ``window`` observations of each
  wall-clock signal (``decode.window``, ``train_sync``), with the
  reference quantiles FROZEN from the first ``warmup`` observations.
  The drift gauge is ``rolling_p50 / reference_p50`` — the median, not
  the tail, so CI-box p99 jitter cannot false-positive the
  ``step-time-regression`` stock rule (the p99 gauges are exported for
  humans; the rule binds the drift ratio).  Pure host arithmetic:
  ``observe()`` is on the no-hot-sync lint's scanned set
  (tests/test_lint_no_hot_sync.py) because it runs inside the decode
  window and the train loop.

``CostPlane`` bundles the three over ONE metrics registry; the process
global ``default_costplane`` rides ``utils.metrics.default_metrics``
like every other default_* singleton.  Independently of any instance,
a module-level process counter sums EVERY recorded compile —
tests/conftest.py writes it into benchmarks/SUITE_RECORD.json at
session end and benchmarks/check_tier_budget.py reddens on a >25%
regression, so width-class fragmentation can never creep in silently.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "CompileLedger",
    "HBMAccountant",
    "StepTimeSentinel",
    "CostPlane",
    "default_costplane",
    "process_compile_count",
    "abstract_shapes",
    "tree_device_bytes",
]

# -- process-wide compile counter (conftest / check_tier_budget) ----------

_process_lock = threading.Lock()
_process_compiles = 0


def _count_process_compile() -> None:
    global _process_compiles
    with _process_lock:
        _process_compiles += 1


def process_compile_count() -> int:
    """Total compiles recorded by EVERY CompileLedger instance in this
    process since import — the suite-record number."""

    with _process_lock:
        return _process_compiles


# -- shape / byte helpers -------------------------------------------------

_SHORT_DTYPE = {
    "float32": "f32", "float16": "f16", "bfloat16": "bf16",
    "float64": "f64", "int32": "i32", "int64": "i64", "int8": "i8",
    "uint32": "u32", "uint8": "u8", "bool": "pred",
}


def _describe_leaf(leaf) -> Optional[str]:
    """'f32[4,128]' for an array-ish leaf, None for scalars/None."""

    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return None
    name = _SHORT_DTYPE.get(str(dtype), str(dtype))
    return f"{name}[{','.join(str(int(s)) for s in shape)}]"


def abstract_shapes(args, kwargs=None, limit: int = 12) -> List[str]:
    """The abstract input signature of a call: the first ``limit``
    array leaves as dtype[shape] strings (+ an elision marker).  Pure
    metadata — never touches device values."""

    import jax

    leaves = jax.tree_util.tree_leaves((args, kwargs or {}))
    out: List[str] = []
    for leaf in leaves:
        desc = _describe_leaf(leaf)
        if desc is not None:
            out.append(desc)
        if len(out) >= limit:
            out.append(f"...+{max(0, len(leaves) - limit)} leaves")
            break
    return out


def tree_device_bytes(tree) -> Dict[str, int]:
    """Per-device byte footprint of a pytree of jax arrays (host
    metadata only: ``nbytes`` / ``devices()``, never a transfer).
    Sharded leaves split their bytes evenly across their device set —
    exact for the even shardings the mesh builders produce."""

    import jax

    out: Dict[str, int] = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            continue
        try:
            devs = list(leaf.devices())
        except Exception:
            devs = []
        if not devs:
            out["host"] = out.get("host", 0) + int(nbytes)
            continue
        share = int(nbytes) // len(devs)
        for d in devs:
            key = str(d)
            out[key] = out.get(key, 0) + share
    return out


# -- (a) the compile ledger -----------------------------------------------


class CompileLedger:
    """Attributed compile registry + ``compile_total{program,trigger}``
    / ``compile_seconds{program}`` emission + the bounded event ring
    behind ``GET /debug/compiles``."""

    def __init__(self, metrics=None, ring: int = 256):
        self._lock = threading.Lock()
        self._metrics = metrics
        self._ring: collections.deque = collections.deque(maxlen=int(ring))
        self._total = 0
        self._seq = 0

    @property
    def metrics(self):
        if self._metrics is None:
            from tf_operator_tpu.utils.metrics import default_metrics

            self._metrics = default_metrics
        return self._metrics

    def record(self, program: str, trigger: str = "",
               seconds: float = 0.0, shapes: Optional[List[str]] = None,
               trace_id: Optional[str] = None) -> Dict[str, Any]:
        """Register ONE compile.  ``seconds`` is the first-call wall
        (0.0 for ``note()``-style registrations where no honest wall
        exists)."""

        if trace_id is None:
            try:
                from tf_operator_tpu.utils.trace import current_trace_id

                trace_id = current_trace_id() or ""
            except Exception:
                trace_id = ""
        with self._lock:
            self._seq += 1
            event = {
                "seq": self._seq,
                "program": program,
                "trigger": trigger,
                "shapes": list(shapes or []),
                "first_call_seconds": round(float(seconds), 6),
                "trace_id": trace_id,
                "when": time.time(),
            }
            self._ring.append(event)
            self._total += 1
        _count_process_compile()
        m = self.metrics
        if m is not None:
            m.inc("compile_total", program=program, trigger=trigger)
            m.observe_histogram("compile_seconds", seconds, program=program)
        return event

    def note(self, program: str, trigger: str = "", **kw) -> Dict[str, Any]:
        """Register a compile class whose wall cannot be measured
        without double-compiling (internal ``pallas_call`` lowerings):
        counted and attributed, wall honestly absent (0.0)."""

        return self.record(program, trigger, seconds=0.0, **kw)

    def wrap(self, fn, program: str, trigger: str = ""):
        """Return ``fn`` instrumented so its FIRST call registers one
        compile (wall = trace+compile+first execution; see module
        docstring).  One wrap per jit-cache entry: the caller's cache
        miss IS the compile event."""

        state = {"done": False}
        lock = threading.Lock()

        def timed(*args, **kwargs):
            if state["done"]:
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            dt = time.perf_counter() - t0
            with lock:
                first, state["done"] = (not state["done"]), True
            if first:
                self.record(
                    program, trigger, seconds=dt,
                    shapes=abstract_shapes(args, kwargs),
                )
            return out

        timed.__wrapped__ = fn
        return timed

    def total(self) -> int:
        with self._lock:
            return self._total

    def snapshot(self, limit: Optional[int] = None) -> Dict[str, Any]:
        """The /debug/compiles payload: totals by program+trigger and
        the newest-first event ring (bounded)."""

        with self._lock:
            events = list(self._ring)
        events.reverse()
        if limit is not None:
            events = events[: max(0, int(limit))]
        by_program: Dict[str, Dict[str, Any]] = {}
        m = self.metrics
        if m is not None:
            for labels, v in m.counter_series("compile_total").items():
                lab = dict(labels)
                prog = lab.get("program", "?")
                slot = by_program.setdefault(
                    prog, {"total": 0, "byTrigger": {}}
                )
                slot["total"] += int(v)
                trig = lab.get("trigger", "")
                slot["byTrigger"][trig] = (
                    slot["byTrigger"].get(trig, 0) + int(v)
                )
        return {
            "total": self._total,
            "processTotal": process_compile_count(),
            "byProgram": by_program,
            "events": events,
        }


# -- (b) the HBM accountant -----------------------------------------------

#: the closed component taxonomy — tests/test_costplane.py and the
#: lint both-ways pin key off this tuple; an unknown component string
#: is a programming error, not a new category
HBM_COMPONENTS = (
    "weights",
    "optimizer",
    "kv_arena",
    "swap_staging",
    "program_tmp",
    "other",
)


class HBMAccountant:
    """Per-device byte ledger of the big allocations, with coverage
    against backend-reported live bytes (see module docstring)."""

    def __init__(self, metrics=None, limit_bytes: Optional[int] = None):
        self._lock = threading.Lock()
        self._metrics = metrics
        #: (device, component) -> bytes
        self._components: Dict[tuple, int] = {}
        env = os.environ.get("TPUJOB_DEVICE_LIMIT_BYTES", "")
        self._limit_override = (
            int(limit_bytes) if limit_bytes is not None
            else (int(env) if env.isdigit() else None)
        )

    @property
    def metrics(self):
        if self._metrics is None:
            from tf_operator_tpu.utils.metrics import default_metrics

            self._metrics = default_metrics
        return self._metrics

    @staticmethod
    def _default_device() -> str:
        try:
            import jax

            return str(jax.devices()[0])
        except Exception:
            return "host"

    def set_component(self, component: str, nbytes: int,
                      device: str = "") -> None:
        """Set (not add) one component's bytes on one device.  Callers
        on hot paths pass host-computed ints only."""

        if component not in HBM_COMPONENTS:
            raise ValueError(
                f"unknown HBM component {component!r} "
                f"(taxonomy: {HBM_COMPONENTS})"
            )
        dev = device or self._default_device()
        with self._lock:
            self._components[(dev, component)] = int(nbytes)
        self._emit(dev)

    def add_component(self, component: str, nbytes: int,
                      device: str = "") -> None:
        """Accumulate into a component (several pools sharing one
        accountant each add their arena)."""

        if component not in HBM_COMPONENTS:
            raise ValueError(f"unknown HBM component {component!r}")
        dev = device or self._default_device()
        with self._lock:
            key = (dev, component)
            self._components[key] = self._components.get(key, 0) + int(nbytes)
        self._emit(dev)

    def register_tree(self, component: str, tree) -> None:
        """Account a pytree of device arrays (weights, optimizer state,
        KV arena) under ``component``, split per device."""

        per_dev = tree_device_bytes(tree)
        if not per_dev:
            per_dev = {self._default_device(): 0}
        for dev, nbytes in per_dev.items():
            self.add_component(component, nbytes, device=dev)

    def note_compiled(self, program: str, compiled) -> Optional[int]:
        """Fold a compiled program's temp peak into ``program_tmp``
        via ``compiled.memory_analysis()`` — best-effort: the CPU
        backend has no analysis and returns None (the component then
        reads 0 and the coverage contract doesn't include temps)."""

        try:
            ana = compiled.memory_analysis()
            tmp = int(getattr(ana, "temp_size_in_bytes", 0) or 0)
        except Exception:
            return None
        with self._lock:
            dev = self._default_device()
            key = (dev, "program_tmp")
            # temp buffers are not cumulative: programs reuse the same
            # scratch HBM, so the ledger keeps the PEAK across programs
            self._components[key] = max(self._components.get(key, 0), tmp)
        self._emit(dev)
        return tmp

    # -- backend truth ----------------------------------------------------

    @staticmethod
    def backend_bytes() -> Dict[str, Optional[int]]:
        """What the backend says is live per device:
        ``memory_stats()['bytes_in_use']`` where supported, else the
        ``jax.live_arrays`` sum (CPU), else None (unknown)."""

        out: Dict[str, Optional[int]] = {}
        try:
            import jax

            devices = list(jax.devices())
        except Exception:
            return out
        fallback = [d for d in devices]
        for d in devices:
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if stats and "bytes_in_use" in stats:
                out[str(d)] = int(stats["bytes_in_use"])
                fallback.remove(d)
        if fallback:
            live: Dict[str, int] = {}
            try:
                import jax

                arrays = list(jax.live_arrays())
            except Exception:
                # backend can't enumerate live arrays: the fallback
                # devices read None below — unknown stays unknown
                arrays = []
            # live_arrays() enumerates every ArrayImpl, including the
            # constituent single-device arrays a composite built via
            # make_array_from_single_device_arrays keeps alive (orbax
            # restores look like this) — same buffer, counted twice.
            # Dedupe by backing buffer pointer so the fallback measures
            # memory, not object count.
            seen_bufs: set = set()
            for arr in arrays:
                try:
                    devs = list(arr.devices())
                except Exception:
                    continue
                try:
                    ptr = arr.unsafe_buffer_pointer()
                except Exception:
                    ptr = id(arr)
                if ptr in seen_bufs:
                    continue
                seen_bufs.add(ptr)
                nb = getattr(arr, "nbytes", 0) or 0
                for dv in devs:
                    live[str(dv)] = (
                        live.get(str(dv), 0) + int(nb) // len(devs)
                    )
            for d in fallback:
                out[str(d)] = live.get(str(d))
        return out

    def device_limit(self, device: str) -> Optional[int]:
        """The device's byte capacity: the explicit override (ctor or
        TPUJOB_DEVICE_LIMIT_BYTES) wins, else the backend's
        ``bytes_limit``, else None (CPU: unknown is unknown — the
        headroom gauge is simply not emitted rather than invented)."""

        if self._limit_override is not None:
            return self._limit_override
        try:
            import jax

            for d in jax.devices():
                if str(d) == device:
                    stats = d.memory_stats() or {}
                    lim = stats.get("bytes_limit")
                    return int(lim) if lim else None
        except Exception:
            # no backend / no memory_stats on this platform: unknown
            # is unknown — the headroom gauge is simply not emitted
            return None
        return None

    def _emit(self, device: str) -> None:
        m = self.metrics
        if m is None:
            return
        with self._lock:
            comps = {
                c: b for (d, c), b in self._components.items()
                if d == device
            }
        accounted = 0
        for comp, nbytes in sorted(comps.items()):
            accounted += nbytes
            m.set(
                "hbm_component_bytes", float(nbytes),
                device=device, component=comp,
            )
        limit = self.device_limit(device)
        if limit is not None:
            m.set("hbm_device_limit_bytes", float(limit), device=device)
            m.set(
                "hbm_headroom_bytes", float(limit - accounted),
                device=device,
            )

    def snapshot(self) -> Dict[str, Any]:
        """The /debug/memory payload: per-device component table,
        accounted total, backend-reported live bytes, limit/headroom
        and the coverage ratio the CPU smoke pins at >= 0.95."""

        with self._lock:
            comps = dict(self._components)
        backend = self.backend_bytes()
        devices = sorted(
            {d for d, _ in comps} | set(backend.keys())
        )
        out_devices = []
        for dev in devices:
            table = {
                c: b for (d, c), b in comps.items() if d == dev
            }
            accounted = sum(table.values())
            live = backend.get(dev)
            limit = self.device_limit(dev)
            coverage = (
                round(accounted / live, 4) if live else None
            )
            out_devices.append({
                "device": dev,
                "components": {
                    c: table.get(c, 0) for c in HBM_COMPONENTS
                },
                "accounted_bytes": accounted,
                "backend_bytes": live,
                "limit_bytes": limit,
                "headroom_bytes": (
                    limit - accounted if limit is not None else None
                ),
                "coverage": coverage,
            })
        # worst headroom first (unknown-limit devices sink to the end):
        # the `tpujob top` sort order is the wire's sort order
        out_devices.sort(
            key=lambda d: (
                d["headroom_bytes"] is None,
                d["headroom_bytes"] if d["headroom_bytes"] is not None
                else -d["accounted_bytes"],
            )
        )
        return {
            "devices": out_devices,
            "accounted_bytes": sum(
                d["accounted_bytes"] for d in out_devices
            ),
        }


# -- (c) the step-time sentinel -------------------------------------------


class StepTimeSentinel:
    """Rolling-quantile drift detector over wall-clock signals (see
    module docstring).  ``observe`` / ``_quantiles`` are scanned by the
    no-hot-sync lint: pure host arithmetic, no device traffic, no
    ``float()`` coercion of anything that could be a device value."""

    def __init__(self, metrics=None, window: int = 128, warmup: int = 16):
        self._lock = threading.Lock()
        self._metrics = metrics
        self.window = max(8, int(window))
        self.warmup = max(4, int(warmup))
        self._samples: Dict[str, collections.deque] = {}
        self._reference: Dict[str, tuple] = {}  # signal -> (p50, p99)
        self._count: Dict[str, int] = {}

    @property
    def metrics(self):
        if self._metrics is None:
            from tf_operator_tpu.utils.metrics import default_metrics

            self._metrics = default_metrics
        return self._metrics

    @staticmethod
    def _quantiles(ordered) -> tuple:
        n = len(ordered)
        p50 = ordered[min(n - 1, (n - 1) // 2)]
        p99 = ordered[min(n - 1, (99 * (n - 1)) // 100)]
        return p50, p99

    def observe(self, signal: str, seconds) -> None:
        """One window wall.  Freezes the reference quantiles at the
        ``warmup``-th observation; after that every call refreshes the
        p50/p99 gauges and the drift ratio (rolling_p50 / ref_p50)."""

        with self._lock:
            dq = self._samples.get(signal)
            if dq is None:
                dq = collections.deque(maxlen=self.window)
                self._samples[signal] = dq
                self._count[signal] = 0
            dq.append(seconds)
            self._count[signal] += 1
            n = self._count[signal]
            ordered = sorted(dq)
            p50, p99 = self._quantiles(ordered)
            if n == self.warmup and signal not in self._reference:
                eps = 1e-9
                self._reference[signal] = (
                    p50 if p50 > eps else eps,
                    p99 if p99 > eps else eps,
                )
            ref = self._reference.get(signal)
        m = self.metrics
        if m is not None:
            m.set("step_time_p50_seconds", p50, signal=signal)
            m.set("step_time_p99_seconds", p99, signal=signal)
            if ref is not None:
                m.set(
                    "step_time_drift_ratio", p50 / ref[0], signal=signal
                )

    def reference(self, signal: str) -> Optional[tuple]:
        with self._lock:
            return self._reference.get(signal)

    def reset(self, signal: Optional[str] = None) -> None:
        """Drop state (all signals, or one) — the re-baseline hook a
        deliberate fleet change (new model, new K) uses so the drift
        gauge compares against the NEW steady state."""

        with self._lock:
            if signal is None:
                self._samples.clear()
                self._reference.clear()
                self._count.clear()
                return
            self._samples.pop(signal, None)
            self._reference.pop(signal, None)
            self._count.pop(signal, None)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out = {}
            for sig, dq in self._samples.items():
                ordered = sorted(dq)
                p50, p99 = self._quantiles(ordered) if ordered else (0, 0)
                ref = self._reference.get(sig)
                out[sig] = {
                    "observations": self._count.get(sig, 0),
                    "p50_seconds": p50,
                    "p99_seconds": p99,
                    "reference_p50_seconds": ref[0] if ref else None,
                    "reference_p99_seconds": ref[1] if ref else None,
                    "drift_ratio": (
                        round(p50 / ref[0], 4) if ref else None
                    ),
                }
            return out


# -- the bundle + process global ------------------------------------------


class CostPlane:
    """One metrics registry, three ledgers — what a serving process or
    the operator wires through its planes."""

    def __init__(self, metrics=None, ring: int = 256,
                 sentinel_window: int = 128, sentinel_warmup: int = 16,
                 limit_bytes: Optional[int] = None):
        self.compiles = CompileLedger(metrics=metrics, ring=ring)
        self.hbm = HBMAccountant(metrics=metrics, limit_bytes=limit_bytes)
        self.sentinel = StepTimeSentinel(
            metrics=metrics, window=sentinel_window, warmup=sentinel_warmup
        )

    def snapshot(self) -> Dict[str, Any]:
        return {
            "compiles": self.compiles.snapshot(limit=32),
            "memory": self.hbm.snapshot(),
            "stepTime": self.sentinel.snapshot(),
        }


default_costplane = CostPlane()
