"""Observability + helpers (SURVEY.md §5): structured logs, events, metrics."""
