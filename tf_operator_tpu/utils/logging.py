"""Job-scoped structured logging.

Parity: the reference's logrus loggers with job/replica fields
(SURVEY.md §2 "Utilities": LoggerForJob/LoggerForPod).  Stdlib logging
with a key=value suffix; ``--json-log`` equivalent via ``configure``.
"""

from __future__ import annotations

import json
import logging
import sys
from typing import Any

_root = logging.getLogger("tpujob")


def configure(level: int = logging.INFO, json_log: bool = False) -> None:
    handler = logging.StreamHandler(sys.stderr)
    if json_log:
        handler.setFormatter(_JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s")
        )
    _root.handlers[:] = [handler]
    _root.setLevel(level)


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": self.formatTime(record),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        for k, v in getattr(record, "fields", {}).items():
            out[k] = v
        return json.dumps(out)


class FieldLogger:
    def __init__(self, logger: logging.Logger, **fields: Any):
        self._logger = logger
        self._fields = fields

    def _fmt(self, msg: str) -> str:
        suffix = " ".join(f"{k}={v}" for k, v in self._fields.items())
        return f"{msg} [{suffix}]" if suffix else msg

    def debug(self, msg: str, *a: Any) -> None:
        self._logger.debug(self._fmt(msg), *a, extra={"fields": self._fields})

    def info(self, msg: str, *a: Any) -> None:
        self._logger.info(self._fmt(msg), *a, extra={"fields": self._fields})

    def warning(self, msg: str, *a: Any) -> None:
        self._logger.warning(self._fmt(msg), *a, extra={"fields": self._fields})

    def error(self, msg: str, *a: Any) -> None:
        self._logger.error(self._fmt(msg), *a, extra={"fields": self._fields})


def logger_for_job(namespace: str, name: str) -> FieldLogger:
    return FieldLogger(_root, job=f"{namespace}/{name}")


def logger_for_replica(namespace: str, job: str, rtype: str, index: int) -> FieldLogger:
    return FieldLogger(_root, job=f"{namespace}/{job}", replica=f"{rtype}-{index}")
