"""Exit-code semantics for RestartPolicy.EXIT_CODE.

Parity: ``IsRetryableExitCode`` (SURVEY.md §2 "Exit-code semantics",
expected upstream ``pkg/util/train/train_util.go``): exit codes 1–127 are
permanent (user error — bad flags, assertion, OOM-killed python), 128+
are retryable (signal-terminated: 130 SIGINT, 137 SIGKILL/OOM-score kill,
143 SIGTERM — typically infrastructure, e.g. preemption).  SURVEY flags
the exact split as [U]; this convention is encoded here and in the tests.
"""


def is_retryable_exit_code(exit_code: int) -> bool:
    return exit_code > 127
