"""Distributed tracing: one trace id from apiserver request to
training step (SURVEY.md §5 "span logging", executable).

Until now the repo's observability was counters/gauges
(``utils/metrics.py``) plus a slow-sync warn log — aggregate health,
but no way to see *where* one slow sync or one wedged job spent its
time.  This module is the request-scoped half:

- **Span**: a named, timed operation with attributes, point-in-time
  events, and an ok/error status.  Context-manager; an exception
  leaving the block marks the span failed with the exception type.
- **Tracer**: mints ids and propagates the current span through
  ``contextvars`` (thread- and asyncio-safe), so code deep in a call
  stack parents its spans correctly without threading a span argument
  through every signature.  Ids are a session prefix + counter from a
  seedable RNG — seeded tracers are fully deterministic, which is what
  lets tests assert exact trace ids with no wall-clock/random flake.
- **TraceStore**: bounded in-memory buffer of finished spans grouped
  by trace id, with *tail sampling*: when the cap forces eviction, the
  oldest trace that is neither errored nor slow goes first, so the
  traces an operator actually wants (failures, latency outliers)
  survive load.  JSONL export for offline tooling.

Propagation contract (the wire half): HTTP carries the trace in two
headers, ``x-trace-id`` and ``x-parent-span-id``
(``inject_headers``/``extract_headers``).  Every client attempt span
in ``backend/kube.http_json`` injects them; ``backend/kubesim``'s
apiserver adopts an incoming trace id (minting one otherwise), records
a server-side request span — tagged with any injected fault — and
echoes ``x-trace-id`` on EVERY response, so one id stitches:

  operator API request → informer event delivery → workqueue
  enqueue/dequeue (queue-latency span) → reconcile sync with child
  spans per plan step → every backend HTTP attempt (tagged with its
  retry number) → the sim apiserver's server spans → leader-election
  transitions → training-harness step spans.

In-process (tests, ``--backend kube-sim``) client and server share the
process-global ``default_tracer``, so ``/traces/<id>`` on the operator
API returns the complete waterfall including the apiserver's own
spans.  Across real processes each side keeps its own store and the
shared trace id links their JSONL exports.
"""

from __future__ import annotations

import contextvars
import json
import random
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

#: the wire contract: trace id + parent span id request/response headers
TRACE_HEADER = "x-trace-id"
PARENT_HEADER = "x-parent-span-id"

#: the contextvar carrying the active span (shared by all tracers:
#: "the current operation" is a property of the execution context, not
#: of whichever tracer started it)
_current_span: contextvars.ContextVar[Optional["Span"]] = (
    contextvars.ContextVar("tpujob-current-span", default=None)
)


class Span:
    """One named, timed operation inside a trace.

    Use as a context manager (the normal path — exceptions mark the
    span errored and always end it) or call ``end()`` explicitly.
    ``end()`` is idempotent: long-lived streaming handlers end their
    span once the response is committed and a later duplicate end is
    a no-op.
    """

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "kind",
        "start_unix", "start_mono", "duration", "attributes", "events",
        "status", "status_message", "_tracer", "_ctx_token",
    )

    def __init__(
        self,
        tracer: "Tracer",
        trace_id: str,
        span_id: str,
        parent_id: Optional[str],
        name: str,
        kind: str = "internal",
        attributes: Optional[Dict[str, Any]] = None,
        start_mono: Optional[float] = None,
    ):
        self._tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind  # internal | client | server | producer
        self.start_unix = time.time()
        self.start_mono = (
            time.monotonic() if start_mono is None else float(start_mono)
        )
        self.duration: Optional[float] = None
        self.attributes: Dict[str, Any] = dict(attributes or {})
        self.events: List[Dict[str, Any]] = []
        self.status = "ok"
        self.status_message = ""
        self._ctx_token: Optional[contextvars.Token] = None

    # -- recording ----------------------------------------------------------

    def set_attribute(self, key: str, value: Any) -> "Span":
        self.attributes[key] = value
        return self

    def add_event(self, name: str, **attrs: Any) -> "Span":
        self.events.append(
            {"name": name, "offset": time.monotonic() - self.start_mono,
             **attrs}
        )
        return self

    def set_error(self, message: str) -> "Span":
        self.status = "error"
        self.status_message = str(message)[:200]
        return self

    def end(self, end_mono: Optional[float] = None) -> None:
        if self.duration is not None:
            return  # idempotent
        end = time.monotonic() if end_mono is None else float(end_mono)
        self.duration = max(0.0, end - self.start_mono)
        self._tracer._finish(self)

    # -- context management -------------------------------------------------

    def __enter__(self) -> "Span":
        self._ctx_token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._ctx_token is not None:
            _current_span.reset(self._ctx_token)
            self._ctx_token = None
        if exc is not None and self.status == "ok":
            self.set_error(f"{type(exc).__name__}: {exc}")
        self.end()
        return False  # never swallow

    # -- export -------------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "kind": self.kind,
            "startUnix": self.start_unix,
            "startMono": self.start_mono,
            "duration": self.duration,
            "attributes": dict(self.attributes),
            "events": list(self.events),
            "status": self.status,
            "statusMessage": self.status_message,
        }


class TraceStore:
    """Bounded store of FINISHED spans grouped by trace id, with tail
    sampling: eviction prefers dropping ok-and-fast traces, so error
    and slow traces survive until only protected traces remain (then
    oldest-first keeps memory bounded regardless).

    Knobs:
      - ``max_traces``: total traces retained;
      - ``max_spans_per_trace``: per-trace span cap — overflow spans
        are dropped and counted in the trace's ``droppedSpans`` so a
        truncated waterfall says so;
      - ``slow_seconds``: a trace with any span at least this long is
        "slow" and protected from preferential eviction.
    """

    def __init__(
        self,
        max_traces: int = 256,
        max_spans_per_trace: int = 512,
        slow_seconds: float = 1.0,
    ):
        self.max_traces = max(1, int(max_traces))
        self.max_spans_per_trace = int(max_spans_per_trace)
        self.slow_seconds = float(slow_seconds)
        self._lock = threading.Lock()
        #: trace id -> {"spans": [dict], "error": bool, "slow": bool,
        #:              "dropped": int, "first_unix": float}
        self._traces: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()

    def add(self, span: Span) -> None:
        self.add_dict(span.to_dict())

    def add_dict(self, d: Dict[str, Any]) -> None:
        """Fold one FINISHED span in exported-dict form (the shape
        ``Span.to_dict``/JSONL export emits) — the cross-process path:
        the telemetry scraper stitches pod-side spans into the
        operator's store through this, so ``/traces/<id>`` shows one
        reconcile→boot→train waterfall even though the training spans
        finished in another process."""

        trace_id = d.get("traceId")
        if not trace_id:
            return
        with self._lock:
            t = self._traces.get(trace_id)
            if t is None:
                t = {
                    "spans": [], "error": False, "slow": False,
                    "dropped": 0, "first_unix": d.get("startUnix", 0.0),
                }
                self._traces[trace_id] = t
                self._evict_locked(keep=trace_id)
            if len(t["spans"]) >= self.max_spans_per_trace:
                t["dropped"] += 1
            else:
                t["spans"].append(dict(d))
            if d.get("status") == "error":
                t["error"] = True
            duration = d.get("duration")
            if duration is not None and duration >= self.slow_seconds:
                t["slow"] = True

    def _evict_locked(self, keep: str) -> None:
        # ``keep`` is the just-inserted trace: it has no spans yet, so
        # it is never error/slow — without the exemption, a store full
        # of protected traces would evict every NEW trace at insertion
        # and wedge on its first max_traces errors forever
        while len(self._traces) > self.max_traces:
            victim = None
            for tid, t in self._traces.items():  # insertion = age order
                if tid != keep and not (t["error"] or t["slow"]):
                    victim = tid
                    break
            if victim is None:  # everything else protected: oldest goes
                victim = next(
                    tid for tid in self._traces if tid != keep
                )
            del self._traces[victim]

    # -- reads --------------------------------------------------------------

    def trace(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            t = self._traces.get(trace_id)
            if t is None:
                return None
            return {
                "traceId": trace_id,
                "error": t["error"],
                "slow": t["slow"],
                "droppedSpans": t["dropped"],
                "spans": [dict(s) for s in t["spans"]],
            }

    def summaries(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Newest-first trace summaries for list endpoints/dashboards."""

        with self._lock:
            items = list(self._traces.items())
        out = []
        for tid, t in reversed(items[-limit * 2:] if limit else items):
            spans = t["spans"]
            root = next(
                (s for s in spans if not s["parentId"]),
                spans[0] if spans else None,
            )
            total = max(
                (s["duration"] for s in spans if s["duration"] is not None),
                default=0.0,
            )
            out.append({
                "traceId": tid,
                "root": root["name"] if root else "?",
                "startUnix": t["first_unix"],
                "spanCount": len(spans),
                "droppedSpans": t["dropped"],
                "duration": total,
                "error": t["error"],
                "slow": t["slow"],
                "queueLatency": next(
                    (s["duration"] for s in spans
                     if s["name"] == "queue.wait"), None,
                ),
            })
            if limit and len(out) >= limit:
                break
        return out

    def export_jsonl(self, fileobj) -> int:
        """One finished span per line; returns the line count."""

        with self._lock:
            spans = [
                s for t in self._traces.values() for s in t["spans"]
            ]
        for s in spans:
            fileobj.write(json.dumps(s) + "\n")
        return len(spans)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


class Tracer:
    """Span factory + contextvars propagation + id minting.

    ``seed`` makes the id sequence fully deterministic (tests pin
    exact ids); unseeded tracers get a random session prefix so two
    processes' ids cannot collide.
    """

    def __init__(
        self,
        store: Optional[TraceStore] = None,
        seed: Optional[int] = None,
    ):
        self.store = store if store is not None else TraceStore()
        rng = random.Random(seed)
        self._prefix = f"{rng.getrandbits(32):08x}"
        self._lock = threading.Lock()
        self._counter = 0
        #: optional sink called with every finished span (exporters)
        self.on_finish: Optional[Callable[[Span], None]] = None

    def _next_id(self, tag: str) -> str:
        with self._lock:
            self._counter += 1
            return f"{tag}{self._prefix}{self._counter:06x}"

    def mint_trace_id(self) -> str:
        """A fresh trace id WITHOUT starting a span — the serving
        pool's request identity when a request arrives with no
        incoming trace context (ISSUE 11: every request gets a
        first-class id at submit; the HTTP path adopts ``x-trace-id``
        instead).  Same id space as span-rooted traces, so the later
        lifecycle spans join it exactly like a remote trace."""

        return self._next_id("t")

    # -- span creation ------------------------------------------------------

    def start_span(
        self,
        name: str,
        *,
        kind: str = "internal",
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        attributes: Optional[Dict[str, Any]] = None,
        start_mono: Optional[float] = None,
        root: bool = False,
    ) -> Span:
        """New span: child of the context's current span by default;
        ``root=True`` forces a fresh trace; explicit ``trace_id`` joins
        a remote trace (``parent_id`` from the wire, when sent)."""

        parent = None if root else _current_span.get()
        if trace_id is None:
            if parent is not None:
                trace_id = parent.trace_id
                parent_id = parent.span_id
            else:
                trace_id = self._next_id("t")
        elif parent_id is None and parent is not None and (
            parent.trace_id == trace_id
        ):
            parent_id = parent.span_id
        return Span(
            self, trace_id, self._next_id("s"), parent_id, name,
            kind=kind, attributes=attributes, start_mono=start_mono,
        )

    def span(self, name: str, **kw) -> Span:
        """``with tracer.span("pod.create") as sp:`` — the convenience
        spelling of start_span (the Span is its own context manager)."""

        return self.start_span(name, **kw)

    def _finish(self, span: Span) -> None:
        self.store.add(span)
        if self.on_finish is not None:
            self.on_finish(span)

    # -- context reads ------------------------------------------------------

    @staticmethod
    def current_span() -> Optional[Span]:
        return _current_span.get()

    @staticmethod
    def current_trace_id() -> Optional[str]:
        span = _current_span.get()
        return span.trace_id if span is not None else None


def current_trace_id() -> Optional[str]:
    """Module-level shorthand for exemplar linkage (metrics, logs)."""

    return Tracer.current_trace_id()


# -- wire propagation -------------------------------------------------------


def inject_headers(
    headers: Dict[str, str], span: Optional[Span] = None
) -> Dict[str, str]:
    """Stamp the active (or given) span's trace context into request
    headers; a no-op when nothing is being traced."""

    span = span if span is not None else _current_span.get()
    if span is not None:
        headers[TRACE_HEADER] = span.trace_id
        headers[PARENT_HEADER] = span.span_id
    return headers


def extract_headers(headers) -> Tuple[Optional[str], Optional[str]]:
    """(trace_id, parent_span_id) from an incoming request's headers
    (any mapping with a case-insensitive ``get``, e.g. http.client's)."""

    get = headers.get
    return get(TRACE_HEADER), get(PARENT_HEADER)


#: process-global default (mirrors utils.metrics.default_metrics):
#: in-process client+server share it, so one store holds the whole
#: waterfall; components accept an override for seeded-deterministic
#: tests
default_tracer = Tracer()
