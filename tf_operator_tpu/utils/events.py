"""Event recorder — the user-visible audit trail.

Parity: Kubernetes Events emitted on the TFJob (SURVEY.md §5
"Metrics / logging / observability": created/succeeded/failed/restarted).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import List


@dataclass
class Event:
    object_key: str  # "<ns>/<job>"
    type: str  # "Normal" | "Warning"
    reason: str  # e.g. "SuccessfulCreatePod", "JobFailed"
    message: str
    timestamp: float = field(default_factory=time.time)


class EventRecorder:
    def __init__(self, max_events: int = 10_000):
        self._lock = threading.Lock()
        self._events: List[Event] = []
        self._max = max_events

    def event(self, object_key: str, etype: str, reason: str, message: str) -> None:
        with self._lock:
            self._events.append(Event(object_key, etype, reason, message))
            if len(self._events) > self._max:
                del self._events[: len(self._events) - self._max]

    def for_object(self, object_key: str) -> List[Event]:
        with self._lock:
            return [e for e in self._events if e.object_key == object_key]

    def all(self) -> List[Event]:
        with self._lock:
            return list(self._events)
