"""SLO alert engine: the layer that CONSUMES the metrics PR 5 emits.

PR 5 finished the instrument panel — labeled counter/gauge/histogram
families, serving SLO histograms, a flight recorder, a stall watchdog —
but nothing evaluated them: an operator had to eyeball /metrics to know
a job was unhealthy.  This module closes the observe→act gap with a
declarative rule set evaluated on the shared registry:

- :class:`BurnRateRule` — Google-SRE multi-window multi-burn-rate over
  a labeled histogram family: "p99 of serve_request_seconds{route=} must
  stay under T" becomes an error budget (the fraction of requests
  allowed over T), and the rule fires only when the budget is burning
  faster than ``burn_threshold`` over BOTH a short and a long window —
  the short window for detection latency, the long one so a single
  latency blip cannot page.
- :class:`ThresholdRule` — plain predicates over counters and gauges:
  counter increase over a window (watchdog stalls, circuit-breaker
  opens), gauge level (admission queue depth), and gauge AGE for
  staleness signals (seconds since ``checkpoint_last_success_unix``).

Each rule runs an alert lifecycle state machine::

    inactive -> pending -> firing -> resolved -> inactive
                   \\________/          (breach cleared)
                (breach must hold for ``for_seconds``)

evaluated by :meth:`AlertEngine.evaluate_once` — pure enough for tests
to drive with synthetic clocks — or by a background evaluator thread
(:meth:`AlertEngine.start`, the watchdog pattern).  Everything here is
HOST-side arithmetic over registry snapshots; nothing touches the
device, so the training/serving no-hot-sync invariants are unaffected.

On the pending→firing transition the engine:

- increments ``alerts_fired_total{rule=}`` and sets
  ``alert_state{rule=}`` to 2 (0 inactive, 1 pending, 2 firing),
- warn-logs the breach with its measured value,
- dumps the flight recorder ONCE per episode (the same
  once-per-episode contract as the watchdog) so the black box captures
  the window *around* the violation,
- invokes every :meth:`AlertEngine.subscribe` callback — the
  controller uses this to re-enqueue jobs so the ``Degraded``
  condition lands in ``TPUJob.status`` promptly.

Cumulative-to-windowed: Prometheus-style families are monotonic
cumulative series, so windowed rates come from a bounded per-rule
history of (timestamp, cumulative-value) samples recorded at each
evaluation tick; the increase over a window is the difference against
the newest sample at least ``window`` old.  A window with less than
``MIN_COVERAGE`` of its span observed never breaches — you cannot
claim a one-hour burn from thirty seconds of data, and a cold start
must not page.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from tf_operator_tpu.utils.logging import FieldLogger, _root

#: alert lifecycle states, in order of escalation
STATES = ("inactive", "pending", "firing", "resolved")

#: numeric alert_state{rule=} gauge values
_STATE_VALUE = {"inactive": 0.0, "pending": 1.0, "firing": 2.0, "resolved": 0.0}

#: a window never breaches until this fraction of its span is covered
#: by recorded history (cold-start false-positive guard)
MIN_COVERAGE = 0.5

#: ThresholdRule kinds
THRESHOLD_KINDS = ("counter_increase", "gauge", "gauge_age")


@dataclass
class BurnRateRule:
    """Multi-window burn-rate rule over one labeled histogram family.

    The SLO: at least ``objective_ratio`` of observations must be
    <= ``objective_le`` seconds (``objective_le`` should be a bucket
    bound of the family — the straddling bucket is otherwise counted
    as bad, i.e. conservatively).  The error budget is
    ``1 - objective_ratio``; the burn rate over a window is
    (bad fraction in window) / budget, and the rule breaches when the
    burn exceeds ``burn_threshold`` on BOTH windows.
    """

    name: str
    family: str
    objective_le: float
    objective_ratio: float = 0.99
    #: label filter: a series participates when these items are a
    #: subset of its labels; {} aggregates every series of the family
    labels: Dict[str, str] = field(default_factory=dict)
    #: (short, long) window seconds, strictly increasing
    windows: Tuple[float, float] = (300.0, 3600.0)
    burn_threshold: float = 6.0
    for_seconds: float = 0.0
    severity: str = "page"

    @property
    def kind(self) -> str:
        return "burn_rate"

    @property
    def metric(self) -> str:  # the lint gate's uniform accessor
        return self.family


@dataclass
class ThresholdRule:
    """Predicate over a counter or gauge family.

    Kinds:
      ``counter_increase`` — sum of matching series' increase over
        ``window`` seconds > ``threshold``;
      ``gauge``     — worst (max) matching gauge level > ``threshold``;
      ``gauge_age`` — ``now - value`` of the OLDEST matching gauge
        > ``threshold`` where the gauge holds a unix timestamp
        (e.g. ``checkpoint_last_success_unix``); an unset/zero gauge
        never breaches — "no checkpoint configured" is not "stale".

    ``window`` applies to ``counter_increase`` ONLY; gauge kinds
    evaluate the instantaneous registry snapshot (a gauge/age already
    IS a level, not a rate) — use ``for_seconds`` for dwell.
    """

    name: str
    metric: str
    kind: str = "counter_increase"
    labels: Dict[str, str] = field(default_factory=dict)
    threshold: float = 0.0
    window: float = 600.0
    for_seconds: float = 0.0
    severity: str = "ticket"


def validate_rule(rule) -> None:
    """Raise ValueError on a malformed rule — called by the engine at
    construction and by tests/test_alert_rules_lint.py on the default
    set, so a bad rule fails the process at boot, not silently at the
    first evaluation."""

    if not getattr(rule, "name", ""):
        raise ValueError("rule has no name")
    pre = f"rule {rule.name!r}: "
    if not rule.metric:
        raise ValueError(pre + "empty metric/family")
    if not isinstance(rule.labels, dict):
        raise ValueError(pre + "labels must be a dict")
    if rule.for_seconds < 0 or not math.isfinite(rule.for_seconds):
        raise ValueError(pre + f"bad for_seconds {rule.for_seconds!r}")
    if isinstance(rule, BurnRateRule):
        if not (0.0 < rule.objective_ratio < 1.0):
            raise ValueError(
                pre + f"objective_ratio {rule.objective_ratio!r} not in (0,1)"
            )
        if not (math.isfinite(rule.objective_le) and rule.objective_le > 0):
            raise ValueError(pre + f"bad objective_le {rule.objective_le!r}")
        if len(rule.windows) != 2:
            raise ValueError(pre + "windows must be (short, long)")
        s, l = rule.windows
        if not (0 < s < l) or not math.isfinite(l):
            raise ValueError(
                pre + f"windows must be ordered finite positives, got {rule.windows}"
            )
        if not (math.isfinite(rule.burn_threshold) and rule.burn_threshold > 0):
            raise ValueError(pre + f"bad burn_threshold {rule.burn_threshold!r}")
    elif isinstance(rule, ThresholdRule):
        if rule.kind not in THRESHOLD_KINDS:
            raise ValueError(pre + f"unknown kind {rule.kind!r}")
        if not math.isfinite(rule.threshold):
            raise ValueError(pre + f"bad threshold {rule.threshold!r}")
        if rule.kind == "counter_increase" and not (
            math.isfinite(rule.window) and rule.window > 0
        ):
            raise ValueError(pre + f"bad window {rule.window!r}")
    else:
        raise ValueError(pre + f"unknown rule type {type(rule).__name__}")


def default_rules(
    short: float = 300.0, long: float = 3600.0
) -> List[Any]:
    """The stock rule set over the PR-5 families.  ``short``/``long``
    parameterize every burn window (and the counter windows) so tests
    and sims can shrink the whole set coherently.

    Renaming any metric these reference without updating them here
    fails tests/test_alert_rules_lint.py — a rule can never silently
    orphan.
    """

    return [
        # -- user-facing serving SLOs (serve_lm + batching pool) -------
        BurnRateRule(
            "serve-request-latency-burn",
            family="serve_request_seconds",
            objective_le=10.0, objective_ratio=0.99,
            labels={"route": "/generate"},
            windows=(short, long), burn_threshold=6.0,
            severity="page",
        ),
        BurnRateRule(
            "serve-queue-wait-burn",
            family="serve_queue_wait_seconds",
            objective_le=2.5, objective_ratio=0.95,
            windows=(short, long), burn_threshold=6.0,
            severity="page",
        ),
        BurnRateRule(
            "serve-ttft-burn",
            family="serve_ttft_seconds",
            objective_le=5.0, objective_ratio=0.95,
            windows=(short, long), burn_threshold=6.0,
            severity="page",
        ),
        # -- control-plane SLO (operator job API) ----------------------
        BurnRateRule(
            "api-request-latency-burn",
            family="api_request_seconds",
            objective_le=1.0, objective_ratio=0.99,
            windows=(short, long), burn_threshold=6.0,
            severity="ticket",
        ),
        # -- threshold rules over PR-1/PR-5 health counters ------------
        ThresholdRule(
            "watchdog-stall",
            metric="watchdog_stall_total",
            kind="counter_increase", threshold=0.0, window=long,
            severity="page",
        ),
        ThresholdRule(
            "api-client-circuit-open",
            metric="api_client_circuit_open_total",
            kind="counter_increase", threshold=0.0, window=short,
            severity="ticket",
        ),
        ThresholdRule(
            "admission-queue-depth",
            metric="serve_admission_queue_depth",
            kind="gauge", threshold=64.0,
            severity="ticket",
        ),
        ThresholdRule(
            # paged-serving memory headroom (ISSUE 8): the arena is
            # nearly exhausted — admission is about to gate on blocks
            # free.  Since ISSUE 10 the gauge is (in-use + queued
            # demand)/usable refreshed per decode window, so a burst
            # ramps through 0.9 instead of step-functioning past it.
            # Worst replica drives it (gauge kind takes the max
            # matching level); the stock serving autoscaling policy
            # binds the same family so the alert and the scale-up act
            # on one number
            "kv-blocks-pressure",
            metric="kv_blocks_pressure",
            kind="gauge", threshold=0.9,
            severity="ticket",
        ),
        ThresholdRule(
            # sustained mid-decode preemption (ISSUE 12): the paged
            # pool's budget-on-demand oversubscription is losing its
            # gamble often enough that seats are thrashing through the
            # host swap arena — interactive TTFT is about to burn.
            # The stock serving autoscaling policy binds this rule so
            # sustained swapping scales replicas OUT before the SLO
            # pages; a handful of preemptions per window is the
            # mechanism working as designed and stays quiet.
            "serve-preemption-rate",
            metric="serve_preemptions_total",
            kind="counter_increase", threshold=8.0, window=short,
            severity="ticket",
        ),
        ThresholdRule(
            # cross-pod fabric peer health (ISSUE 17): a remote prefix
            # pull died at the socket (connect refused / mid-body
            # reset).  The pull path already fell back to recompute —
            # requests still succeed — so this tickets rather than
            # pages, but a peer that stays dead means every shared
            # prefix is being recomputed and the fleet hit rate is
            # quietly zero.  Scoped to reason="peer_dead": index 404s
            # (stale catalog) and corrupt payloads are normal churn the
            # content hash absorbs.
            "fabric-peer-unreachable",
            metric="kv_fabric_pull_failures_total",
            kind="counter_increase", threshold=0.0, window=short,
            labels={"reason": "peer_dead"},
            severity="ticket",
        ),
        ThresholdRule(
            "checkpoint-stale",
            metric="checkpoint_last_success_unix",
            kind="gauge_age", threshold=1800.0,
            severity="ticket",
        ),
        ThresholdRule(
            # fleet-queue starvation (ISSUE 16): some gang has been
            # parked in the scheduler queue longer than the threshold.
            # The gauge holds the STABLE queued-since stamp per queued
            # job (controller/scheduler.py clears it on admit), so
            # gauge_age measures the oldest wait directly; an empty
            # queue never breaches.  This is the observe half whose act
            # half is the scheduler's own age-boost — if this fires,
            # the boost isn't winning against the high-priority churn
            # and a human (or the autoscaler shrinking someone) has to
            # make room.
            "gang-queue-stall",
            metric="scheduler_queued_since_unix",
            kind="gauge_age", threshold=900.0,
            severity="ticket",
        ),
        ThresholdRule(
            # device cost plane (ISSUE 20): the compile ledger is
            # registering compiles faster than any healthy steady
            # state explains — a width-class/K-class thrash is
            # recompiling the fleet and every cache miss stalls its
            # serving window for the full trace+compile wall.  The
            # threshold clears a normal pool boot (admission widths +
            # step + retire ≈ a handful) so only a SUSTAINED storm
            # inside the short window fires; the autoscaler refuses
            # to scale while this fires (scaling a recompiling fleet
            # just multiplies the recompiles).
            "compile-storm",
            metric="compile_total",
            kind="counter_increase", threshold=8.0, window=short,
            severity="page",
        ),
        ThresholdRule(
            # device cost plane (ISSUE 20): the step-time sentinel's
            # drift ratio — rolling p50 of the decode.window /
            # train_sync wall over the warmup-frozen reference p50.
            # 1.5 means the median window is 50% slower than the
            # baseline this process established at startup: a real
            # regression (new code path, chip contention, silent
            # de-fusion), not tail jitter — the p50, unlike the p99,
            # does not false-positive on a noisy CI box (the clean
            # soak pins that).  Gauge kind takes the worst signal.
            "step-time-regression",
            metric="step_time_drift_ratio",
            kind="gauge", threshold=1.5,
            severity="ticket",
        ),
    ]


class Alert:
    """Runtime state of one rule: the lifecycle machine plus the last
    measured value — what /alerts serializes."""

    __slots__ = (
        "rule", "state", "since", "pending_since", "firing_since",
        "episodes", "value", "message",
    )

    def __init__(self, rule):
        self.rule = rule
        self.state = "inactive"
        self.since = 0.0
        self.pending_since: Optional[float] = None
        self.firing_since: Optional[float] = None
        self.episodes = 0
        self.value: Dict[str, float] = {}
        self.message = ""

    def to_dict(self) -> Dict[str, Any]:
        r = self.rule
        out: Dict[str, Any] = {
            "name": r.name,
            "kind": r.kind,
            "metric": r.metric,
            "labels": dict(r.labels),
            "severity": r.severity,
            "state": self.state,
            "since": self.since,
            "episodes": self.episodes,
            "value": dict(self.value),
            "message": self.message,
        }
        if isinstance(r, BurnRateRule):
            out["objectiveLe"] = r.objective_le
            out["objectiveRatio"] = r.objective_ratio
            out["windows"] = list(r.windows)
            out["burnThreshold"] = r.burn_threshold
        else:
            out["threshold"] = r.threshold
            if r.kind == "counter_increase":  # see ThresholdRule: gauge
                out["window"] = r.window      # kinds have no window
        return out


class AlertEngine:
    """Evaluate a rule set against a metrics registry.

    ``evaluate_once(now)`` is the whole engine (tests drive it with a
    synthetic clock); ``start()`` runs it on a daemon thread every
    ``interval`` seconds.  ``now`` is a unix timestamp — gauge_age
    rules compare it against wall-clock gauges, so synthetic clocks
    must be unix-shaped.
    """

    def __init__(
        self,
        rules: Optional[List[Any]] = None,
        metrics=None,
        recorder=None,
        interval: float = 5.0,
        resolved_hold: float = 300.0,
    ):
        rules = list(rules) if rules is not None else default_rules()
        seen = set()
        for r in rules:
            validate_rule(r)
            if r.name in seen:
                raise ValueError(f"duplicate rule name {r.name!r}")
            seen.add(r.name)
        if metrics is None:
            from tf_operator_tpu.utils.metrics import default_metrics

            metrics = default_metrics
        self.metrics = metrics
        self._recorder = recorder
        self.interval = float(interval)
        self.resolved_hold = float(resolved_hold)
        self._lock = threading.Lock()
        self._alerts: Dict[str, Alert] = {r.name: Alert(r) for r in rules}
        #: rule name -> deque[(unix, cumulative sample)] — burn rules
        #: sample (bad_cum, total_cum); counter rules sample the summed
        #: counter.  Bounded by pruning past the rule's longest window.
        self._history: Dict[str, deque] = {r.name: deque() for r in rules}
        self._callbacks: List[Callable[[Alert, str, str], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._log = FieldLogger(_root, component="alerts")
        #: flight-recorder dump paths, newest last (tests read it)
        self.dumps: List[str] = []

    # -- reads --------------------------------------------------------------

    def alerts(self) -> List[Alert]:
        with self._lock:
            return list(self._alerts.values())

    def firing(self) -> List[Alert]:
        with self._lock:
            return [a for a in self._alerts.values() if a.state == "firing"]

    def alert(self, name: str) -> Optional[Alert]:
        """The live Alert for one rule name (None = not registered) —
        the autoscaler's signal-binding read."""

        with self._lock:
            return self._alerts.get(name)

    def snapshot(self) -> Dict[str, Any]:
        """The /alerts JSON body: every alert, firing first."""

        items = sorted(
            (a.to_dict() for a in self.alerts()),
            key=lambda d: (-_STATE_VALUE[d["state"]], d["name"]),
        )
        return {
            "alerts": items,
            "firing": sorted(
                d["name"] for d in items if d["state"] == "firing"
            ),
        }

    def subscribe(self, fn: Callable[[Alert, str, str], None]) -> None:
        """``fn(alert, old_state, new_state)`` on every transition.
        Called from the evaluator thread — keep it cheap and non-raising
        (exceptions are logged and swallowed; the engine must outlive
        its consumers)."""

        with self._lock:
            self._callbacks.append(fn)

    def unsubscribe(self, fn: Callable[[Alert, str, str], None]) -> None:
        """Detach a subscribe()d callback (no-op if absent).  Consumers
        sharing a long-lived engine (the process-global
        ``default_engine``) MUST detach on shutdown or the engine pins
        them alive and keeps invoking them forever."""

        with self._lock:
            try:
                self._callbacks.remove(fn)
            except ValueError:
                pass

    # -- evaluation ---------------------------------------------------------

    def evaluate_once(self, now: Optional[float] = None) -> List[str]:
        """One sweep: measure every rule, run the state machines.
        Returns the names that transitioned this sweep."""

        now = time.time() if now is None else float(now)
        transitioned: List[str] = []
        with self._lock:
            alerts = list(self._alerts.values())
        self.metrics.inc("alert_evaluations_total")
        for alert in alerts:
            try:
                breach, value, msg = self._measure(alert.rule, now)
            except Exception as e:  # noqa: BLE001 - engine outlives rule bugs
                self._log.error(
                    "alert rule %s evaluation failed: %s: %s",
                    alert.rule.name, type(e).__name__, e,
                )
                continue
            alert.value = value
            if self._step_state(alert, breach, msg, now):
                transitioned.append(alert.rule.name)
            # written every sweep, not just on transitions: the series
            # existing at all is the scrape-level signal "the engine is
            # evaluating this rule" — absent() checks must be able to
            # tell a quiet engine from one that never started
            self.metrics.set(
                "alert_state", _STATE_VALUE[alert.state], rule=alert.rule.name
            )
        return transitioned

    def _step_state(self, alert: Alert, breach: bool, msg: str, now: float) -> bool:
        old = alert.state
        rule = alert.rule
        if breach:
            alert.message = msg
            if old == "resolved":
                # flap absorption: a breach returning inside
                # resolved_hold re-enters firing as the SAME episode —
                # no dwell, but also no new dump / Warning-path
                # episode / alerts_fired_total increment.  Without
                # this, a signal oscillating around its threshold with
                # for_seconds=0 would mint an episode (and a full
                # recorder disk dump) every other evaluation tick.
                alert.state = "firing"
                alert.since = now
                self._log.warning(
                    "alert %s re-entered firing (same episode)", rule.name
                )
            else:
                if old == "inactive":
                    alert.state = "pending"
                    alert.pending_since = now
                    alert.since = now
                if alert.state == "pending" and (
                    now - (alert.pending_since or now) >= rule.for_seconds
                ):
                    alert.state = "firing"
                    alert.firing_since = now
                    alert.since = now
                    alert.episodes += 1
                    self._on_firing(alert, msg)
        else:
            if old == "pending":
                alert.state = "inactive"
                alert.since = now
                alert.pending_since = None
                # /alerts must not keep serving a breach message on a
                # rule that went back to inactive
                alert.message = ""
            elif old == "firing":
                alert.state = "resolved"
                alert.since = now
                alert.message = ""
                self._log.info(
                    "alert %s resolved after %.1fs",
                    rule.name, now - (alert.firing_since or now),
                )
                self.metrics.inc("alerts_resolved_total", rule=rule.name)
            elif (
                old == "resolved"
                and now - alert.since >= self.resolved_hold
            ):
                alert.state = "inactive"
                alert.since = now
        changed = alert.state != old
        if changed:
            with self._lock:  # snapshot: subscribe/unsubscribe race
                callbacks = list(self._callbacks)
            for fn in callbacks:
                try:
                    fn(alert, old, alert.state)
                except Exception as e:  # noqa: BLE001 - see subscribe()
                    self._log.error(
                        "alert callback failed for %s: %s: %s",
                        rule.name, type(e).__name__, e,
                    )
        return changed

    def _on_firing(self, alert: Alert, msg: str) -> None:
        rule = alert.rule
        self.metrics.inc("alerts_fired_total", rule=rule.name)
        self._log.warning(
            "ALERT FIRING: %s (%s, severity=%s) — %s",
            rule.name, rule.kind, rule.severity, msg,
        )
        recorder = self._recorder
        if recorder is None:
            from tf_operator_tpu.utils.flight import default_recorder

            recorder = default_recorder
        # once-per-episode black-box dump (the watchdog contract): the
        # rings captured here hold the window AROUND the violation
        recorder.snapshot_metrics(label=f"alert:{rule.name}")
        recorder.record_log(
            "WARNING", "alerts", f"alert {rule.name} firing: {msg}",
            fields={"rule": rule.name, "value": dict(alert.value)},
        )
        path = recorder.dump(reason=f"alert-{rule.name.replace('/', '_')}")
        if path:
            self.dumps.append(path)
            # bounded path list: a long-lived engine must not be a
            # memory-growth vector (file creation itself is already
            # rate-limited to one per genuine episode — see the
            # resolved-state flap absorption in _step_state)
            del self.dumps[:-64]
            self._log.warning("flight recorder dumped to %s", path)

    # -- measurement --------------------------------------------------------

    def _measure(self, rule, now: float):
        """(breach, value-dict, message) for one rule at ``now``."""

        if isinstance(rule, BurnRateRule):
            return self._measure_burn(rule, now)
        if rule.kind == "counter_increase":
            total = self._sum_series(
                self.metrics.counter_series(rule.metric), rule.labels
            )
            self._push(rule.name, now, total, rule.window)
            inc, elapsed = self._increase(rule.name, now, rule.window)
            # no MIN_COVERAGE here: an event-counter increase between
            # any two samples inside the window is real regardless of
            # how much of the window history covers — stall/circuit
            # counters move rarely and a coverage gate would hide the
            # first episode after boot
            breach = elapsed > 0 and inc > rule.threshold
            return (
                breach,
                {"increase": inc},
                f"{rule.metric} increased {inc:g} in {elapsed:.0f}s "
                f"(> {rule.threshold:g})",
            )
        if rule.kind == "gauge":
            series = self._match(
                self.metrics.gauge_series(rule.metric), rule.labels
            )
            level = max((v for _, v in series), default=0.0)
            return (
                level > rule.threshold,
                {"level": level},
                f"{rule.metric} at {level:g} (> {rule.threshold:g})",
            )
        # gauge_age: stalest matching timestamp gauge
        series = [
            (lbl, v)
            for lbl, v in self._match(
                self.metrics.gauge_series(rule.metric), rule.labels
            )
            if v > 0
        ]
        if not series:
            return False, {"age": 0.0}, ""
        age = max(now - v for _, v in series)
        return (
            age > rule.threshold,
            {"age": age},
            f"{rule.metric} is {age:.0f}s old (> {rule.threshold:g}s)",
        )

    def _measure_burn(self, rule: BurnRateRule, now: float):
        bad, total = self._burn_sample(rule)
        self._push(rule.name, now, (bad, total), rule.windows[1])
        budget = 1.0 - rule.objective_ratio
        burns: List[float] = []
        covered = True
        for w in rule.windows:
            (d_bad, d_total), elapsed = self._increase2(rule.name, now, w)
            if not elapsed or elapsed < w * MIN_COVERAGE:
                covered = False
            frac = (d_bad / d_total) if d_total > 0 else 0.0
            burns.append(frac / budget)
        value = {
            "burnShort": round(burns[0], 3),
            "burnLong": round(burns[1], 3),
        }
        breach = covered and all(b > rule.burn_threshold for b in burns)
        msg = (
            f"{rule.family}{rule.labels or ''} burning error budget at "
            f"{burns[0]:.1f}x/{burns[1]:.1f}x over {rule.windows[0]:g}s/"
            f"{rule.windows[1]:g}s (threshold {rule.burn_threshold:g}x, "
            f"objective p{rule.objective_ratio * 100:g} <= {rule.objective_le:g}s)"
        )
        return breach, value, msg

    def _burn_sample(self, rule: BurnRateRule) -> Tuple[float, float]:
        """Aggregate (bad_cum, total_cum) over the family's matching
        series: bad = observations ABOVE objective_le (the straddling
        bucket counts as bad — conservative)."""

        bad = total = 0.0
        for labels, (bks, counts, _sum, n) in self.metrics.histogram_raw(
            rule.family
        ).items():
            if not self._labels_match(labels, rule.labels):
                continue
            good = 0
            for i, b in enumerate(bks):
                if b <= rule.objective_le:
                    good += counts[i]
                else:
                    break
            bad += n - good
            total += n
        return bad, total

    # -- history helpers ----------------------------------------------------

    def _push(self, name: str, now: float, sample, max_window: float) -> None:
        hist = self._history[name]
        hist.append((now, sample))
        horizon = now - max_window - 2 * max(self.interval, 1.0)
        while hist and hist[0][0] < horizon:
            hist.popleft()

    def _baseline(self, name: str, now: float, window: float):
        """Newest sample at least ``window`` old; else the oldest."""

        hist = self._history[name]
        if len(hist) < 2:
            return None
        target = now - window
        best = None
        for t, v in hist:
            if t <= target:
                best = (t, v)
            else:
                break
        return best if best is not None else hist[0]

    def _increase(self, name: str, now: float, window: float):
        base = self._baseline(name, now, window)
        if base is None:
            return 0.0, 0.0
        t0, v0 = base
        t1, v1 = self._history[name][-1]
        return max(0.0, v1 - v0), t1 - t0

    def _increase2(self, name: str, now: float, window: float):
        base = self._baseline(name, now, window)
        if base is None:
            return (0.0, 0.0), 0.0
        t0, (b0, n0) = base
        t1, (b1, n1) = self._history[name][-1]
        return (max(0.0, b1 - b0), max(0.0, n1 - n0)), t1 - t0

    @staticmethod
    def _labels_match(series_labels: Tuple[Tuple[str, str], ...], want: Dict[str, str]) -> bool:
        d = dict(series_labels)
        return all(d.get(k) == str(v) for k, v in want.items())

    def _match(self, series: Dict, want: Dict[str, str]):
        return [
            (lbl, v) for lbl, v in series.items()
            if self._labels_match(lbl, want)
        ]

    def _sum_series(self, series: Dict, want: Dict[str, str]) -> float:
        return sum(v for _, v in self._match(series, want))

    # -- evaluator thread ---------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "AlertEngine":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="alert-evaluator"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.evaluate_once()
            except Exception as e:  # noqa: BLE001 - the engine must outlive bugs
                self._log.error(
                    "alert sweep failed: %s: %s", type(e).__name__, e
                )


#: process-global default (mirrors metrics/tracer/flight/watchdog
#: defaults): the kubesim debug endpoint and any binary that doesn't
#: build its own engine read this instance.  NOT started — evaluation
#: is opt-in (``default_engine.start()`` or the operator/serving boot).
default_engine = AlertEngine()
