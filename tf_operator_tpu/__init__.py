"""tf_operator_tpu — a TPU-native distributed-training job orchestrator.

A ground-up rebuild of the capabilities of ``u2takey/tf-operator`` (the
kubeflow TFJob operator: a Go Kubernetes control plane that launches and
tracks distributed TensorFlow training jobs), re-designed TPU-first:

- declarative job specs (chief / ps / worker / evaluator replicas, plus a
  first-class ``TPU_SLICE`` replica type whose unit of allocation is an
  atomic slice),
- a level-triggered reconciler with gang (all-or-nothing) slice admission,
  restart/success/cleanup policies and condition-based status,
- cluster-bootstrap env injection: the reference's ``TF_CONFIG`` generator
  *and* its TPU twin (``jax.distributed`` coordinator + megascale env so
  workloads run XLA collectives over ICI/DCN),
- pluggable cluster backends (in-proc fake for tests, local subprocess
  backend, a real-cluster interface),
- and the TPU-side training stack the reference's examples imply: Flax
  models (mnist, ResNet-50, BERT, T5), pjit/shard_map parallelism
  (dp/fsdp/tp/sp + ring attention), and Pallas kernels for hot ops.

Reference parity map: see SURVEY.md at the repo root.  The reference mount
was empty at build time (see SURVEY.md provenance warning); parity targets
are cited against SURVEY.md sections rather than reference file:line.
"""

__version__ = "0.1.0"

from tf_operator_tpu.api.types import (  # noqa: F401
    CleanPodPolicy,
    JobConditionType,
    PodPhase,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    RunPolicy,
    SuccessPolicy,
    TPUJob,
    TPUJobSpec,
    TPUJobStatus,
)
