"""Tier-1 reconciler tests against the fake cluster (SURVEY.md §4).

The cluster is a data structure: jobs are submitted, the queue is drained
inline, pod phases are fabricated, and assertions check created/deleted
pods, injected env, and condition transitions — mirroring the reference's
fake-clientset controller tests.
"""

import time
import json

import pytest

from tests.testutil import harness, new_job, pod_name
from tf_operator_tpu.api.types import (
    CleanPodPolicy,
    JobConditionType,
    PodPhase,
    ReplicaType,
    RestartPolicy,
    SuccessPolicy,
)
from tf_operator_tpu.controller.reconciler import ReconcilerConfig


def submit(store, controller, job):
    stored = store.create(job)
    controller.sync_until_quiet()
    return stored


def get_status(store, job):
    return store.get(job.metadata.namespace, job.metadata.name).status


class TestHappyPath:
    def test_pods_and_services_created(self):
        store, backend, c = harness()
        job = submit(store, c, new_job(chief=1, ps=2, worker=4))
        assert len(backend.created_pods) == 7
        assert len(backend.created_services) == 7
        pod = backend.get_pod("default", "job-worker-2")
        assert pod is not None
        assert pod.replica_type is ReplicaType.WORKER
        assert pod.replica_index == 2
        assert pod.metadata.owner_uid == job.metadata.uid

    def test_created_condition_and_start_time(self):
        store, backend, c = harness()
        job = submit(store, c, new_job(worker=1))
        st = get_status(store, job)
        assert st.has_condition(JobConditionType.CREATED)
        assert st.start_time is not None

    def test_running_then_succeeded_with_chief(self):
        store, backend, c = harness()
        job = submit(store, c, new_job(chief=1, worker=2))
        backend.run_all("default")
        c.sync_until_quiet()
        st = get_status(store, job)
        assert st.has_condition(JobConditionType.RUNNING)
        assert st.replica_statuses[ReplicaType.WORKER].active == 2

        backend.succeed_pod("default", "job-chief-0")
        c.sync_until_quiet()
        st = get_status(store, job)
        assert st.has_condition(JobConditionType.SUCCEEDED)
        assert not st.has_condition(JobConditionType.RUNNING)
        assert st.completion_time is not None

    def test_clean_pod_policy_running_deletes_workers(self):
        store, backend, c = harness()
        submit(store, c, new_job(chief=1, worker=2))
        backend.run_all("default")
        c.sync_until_quiet()
        backend.succeed_pod("default", "job-chief-0")
        c.sync_until_quiet()
        # default CleanPodPolicy=Running: still-running workers deleted
        assert "default/job-worker-0" in backend.deleted_pods
        assert "default/job-worker-1" in backend.deleted_pods
        # chief already terminal: kept
        assert "default/job-chief-0" not in backend.deleted_pods

    def test_clean_pod_policy_none_keeps_everything(self):
        store, backend, c = harness()
        job = new_job(chief=1, worker=1)
        job.spec.run_policy.clean_pod_policy = CleanPodPolicy.NONE
        submit(store, c, job)
        backend.run_all("default")
        backend.succeed_pod("default", "job-chief-0")
        c.sync_until_quiet()
        assert backend.deleted_pods == []

    def test_clean_pod_policy_all(self):
        store, backend, c = harness()
        job = new_job(chief=1, worker=1)
        job.spec.run_policy.clean_pod_policy = CleanPodPolicy.ALL
        submit(store, c, job)
        backend.run_all("default")
        backend.succeed_pod("default", "job-worker-0")
        backend.succeed_pod("default", "job-chief-0")
        c.sync_until_quiet()
        assert "default/job-chief-0" in backend.deleted_pods
        assert "default/job-worker-0" in backend.deleted_pods


class TestEnvInjection:
    def test_tf_config_content(self):
        # no-PS job: the dense config with true indices
        store, backend, c = harness()
        submit(store, c, new_job(chief=1, worker=2))
        pod = backend.get_pod("default", "job-worker-1")
        cfg = json.loads(pod.main_container().env["TF_CONFIG"])
        assert cfg["task"] == {"type": "worker", "index": 1}
        assert cfg["cluster"]["chief"] == ["job-chief-0.default.svc:2222"]
        assert cfg["cluster"]["worker"] == [
            "job-worker-0.default.svc:2222",
            "job-worker-1.default.svc:2222",
        ]
        assert cfg["environment"] == "cloud"

    def test_tf_config_ps_topology_sparse(self):
        # PS jobs inject the SPARSE variant for workers (the TF
        # parameter-server convention — bootstrap/tpu_env.worker_env):
        # full chief/ps lists, own-entry worker list as index 0; PS
        # pods keep the dense view
        store, backend, c = harness()
        submit(store, c, new_job(chief=1, ps=1, worker=2))
        cfg = json.loads(
            backend.get_pod("default", "job-worker-1").main_container().env["TF_CONFIG"]
        )
        assert cfg["task"] == {"type": "worker", "index": 0}
        assert cfg["cluster"]["chief"] == ["job-chief-0.default.svc:2222"]
        assert cfg["cluster"]["ps"] == ["job-ps-0.default.svc:2222"]
        assert cfg["cluster"]["worker"] == ["job-worker-1.default.svc:2222"]
        ps_cfg = json.loads(
            backend.get_pod("default", "job-ps-0").main_container().env["TF_CONFIG"]
        )
        assert ps_cfg["task"] == {"type": "ps", "index": 0}
        assert len(ps_cfg["cluster"]["worker"]) == 2

    def test_tpu_env_coordinator_and_process_ids(self):
        store, backend, c = harness()
        submit(store, c, new_job(chief=1, worker=2))
        # chief is process 0; workers follow
        env0 = backend.get_pod("default", "job-chief-0").main_container().env
        env2 = backend.get_pod("default", "job-worker-1").main_container().env
        assert env0["TPUJOB_PROCESS_ID"] == "0"
        assert env2["TPUJOB_PROCESS_ID"] == "2"
        assert env0["TPUJOB_NUM_PROCESSES"] == "3"
        assert env2["TPUJOB_COORDINATOR_ADDRESS"] == "job-chief-0.default.svc:8476"

    def test_user_env_wins(self):
        store, backend, c = harness()
        job = new_job(worker=1)
        job.spec.replica_specs[ReplicaType.WORKER].template.containers[0].env = {
            "TF_CONFIG": "user-override"
        }
        submit(store, c, job)
        pod = backend.get_pod("default", "job-worker-0")
        assert pod.main_container().env["TF_CONFIG"] == "user-override"

    def test_multislice_megascale_env(self):
        store, backend, c = harness()
        submit(store, c, new_job(tpu_slice=2, tpu_topology="v5e-4"))
        env = backend.get_pod("default", "job-tpuslice-1").main_container().env
        assert env["MEGASCALE_NUM_SLICES"] == "2"
        assert env["MEGASCALE_SLICE_ID"] == "1"
        assert env["TPU_WORKER_ID"] == "0"
        assert env["TPU_WORKER_HOSTNAMES"] == "job-tpuslice-1.default.svc"


class TestSuccessPolicies:
    def test_worker0_success_default_policy(self):
        store, backend, c = harness()
        job = submit(store, c, new_job(worker=3))
        backend.run_all("default")
        backend.succeed_pod("default", "job-worker-0")
        c.sync_until_quiet()
        assert get_status(store, job).has_condition(JobConditionType.SUCCEEDED)

    def test_worker1_success_does_not_finish_default_policy(self):
        store, backend, c = harness()
        job = submit(store, c, new_job(worker=3))
        backend.run_all("default")
        backend.succeed_pod("default", "job-worker-1")
        c.sync_until_quiet()
        assert not get_status(store, job).has_condition(JobConditionType.SUCCEEDED)

    def test_all_workers_policy(self):
        store, backend, c = harness()
        job = new_job(worker=2)
        job.spec.success_policy = SuccessPolicy.ALL_WORKERS
        submit(store, c, job)
        backend.run_all("default")
        backend.succeed_pod("default", "job-worker-0")
        c.sync_until_quiet()
        assert not get_status(store, job).has_condition(JobConditionType.SUCCEEDED)
        backend.succeed_pod("default", "job-worker-1")
        c.sync_until_quiet()
        assert get_status(store, job).has_condition(JobConditionType.SUCCEEDED)


class TestRestartPolicies:
    def test_never_policy_fails_job(self):
        store, backend, c = harness()
        job = submit(store, c, new_job(worker=2, restart_policy=RestartPolicy.NEVER))
        backend.run_all("default")
        backend.fail_pod("default", "job-worker-1", exit_code=1)
        c.sync_until_quiet()
        st = get_status(store, job)
        assert st.has_condition(JobConditionType.FAILED)
        assert st.condition(JobConditionType.FAILED).reason == "ReplicaFailed"

    def test_on_failure_restarts(self):
        store, backend, c = harness()
        job = submit(store, c, new_job(worker=1, restart_policy=RestartPolicy.ON_FAILURE))
        backend.run_all("default")
        backend.fail_pod("default", "job-worker-0", exit_code=1)
        c.sync_until_quiet()
        st = get_status(store, job)
        assert not st.has_condition(JobConditionType.FAILED)
        assert st.restart_count == 1
        # pod was deleted and recreated with the same name
        assert backend.deleted_pods.count("default/job-worker-0") == 1
        assert backend.created_pods.count("default/job-worker-0") == 2

    def test_exit_code_retryable(self):
        store, backend, c = harness()
        job = submit(store, c, new_job(worker=1, restart_policy=RestartPolicy.EXIT_CODE))
        backend.run_all("default")
        backend.fail_pod("default", "job-worker-0", exit_code=137)  # SIGKILL
        c.sync_until_quiet()
        st = get_status(store, job)
        assert not st.has_condition(JobConditionType.FAILED)
        assert st.restart_count == 1

    def test_exit_code_permanent(self):
        store, backend, c = harness()
        job = submit(store, c, new_job(worker=1, restart_policy=RestartPolicy.EXIT_CODE))
        backend.run_all("default")
        backend.fail_pod("default", "job-worker-0", exit_code=1)
        c.sync_until_quiet()
        assert get_status(store, job).has_condition(JobConditionType.FAILED)

    def test_backoff_limit_exceeded(self):
        store, backend, c = harness()
        job = new_job(worker=1, restart_policy=RestartPolicy.ON_FAILURE)
        job.spec.run_policy.backoff_limit = 2
        job = submit(store, c, job)
        for _ in range(2):
            backend.run_all("default")
            backend.fail_pod("default", "job-worker-0", exit_code=1)
            c.sync_until_quiet()
        st = get_status(store, job)
        assert not st.has_condition(JobConditionType.FAILED)
        assert st.restart_count == 2
        backend.run_all("default")
        backend.fail_pod("default", "job-worker-0", exit_code=1)
        c.sync_until_quiet()
        st = get_status(store, job)
        assert st.has_condition(JobConditionType.FAILED)
        assert st.condition(JobConditionType.FAILED).reason == "BackoffLimitExceeded"

    def test_restarting_condition_set(self):
        store, backend, c = harness()
        job = submit(store, c, new_job(worker=2, restart_policy=RestartPolicy.ON_FAILURE))
        backend.run_all("default")
        backend.fail_pod("default", "job-worker-0", exit_code=1)
        c.sync_until_quiet()
        st = get_status(store, job)
        # Restarting was set at some point during the chain; after the
        # replacement pod lands the job may be Running again
        types = [cond.type for cond in st.conditions]
        assert JobConditionType.RESTARTING in types


class TestDeadline:
    def test_active_deadline_fails_job(self, monkeypatch):
        store, backend, c = harness()
        job = new_job(worker=1)
        job.spec.run_policy.active_deadline_seconds = 60
        job = submit(store, c, job)
        # time-travel: pretend the job started 61s ago
        st = get_status(store, job)
        st.start_time -= 61
        store.update_status("default", "job", st)
        c.sync_until_quiet()
        st = get_status(store, job)
        assert st.has_condition(JobConditionType.FAILED)
        assert st.condition(JobConditionType.FAILED).reason == "DeadlineExceeded"


class TestTTL:
    def test_ttl_deletes_job_after_finish(self):
        store, backend, c = harness()
        job = new_job(worker=1)
        job.spec.run_policy.ttl_seconds_after_finished = 0
        submit(store, c, job)
        backend.run_all("default")
        backend.succeed_pod("default", "job-worker-0")
        c.sync_until_quiet()
        assert store.get("default", "job") is None
        # owner GC removed the pod too
        assert backend.get_pod("default", "job-worker-0") is None


class TestJobDeletion:
    def test_delete_gcs_pods_and_services(self):
        store, backend, c = harness()
        submit(store, c, new_job(worker=2))
        store.delete("default", "job")
        c.sync_until_quiet()
        assert backend.list_pods("default") == []
        assert backend.list_services("default") == []


class TestDynamicWorkers:
    def test_scale_in_deletes_high_indices(self):
        store, backend, c = harness()
        stored = submit(store, c, new_job(worker=4))
        stored.spec.replica_specs[ReplicaType.WORKER].replicas = 2
        store.update_spec(stored)
        c.sync_until_quiet()
        assert "default/job-worker-3" in backend.deleted_pods
        assert "default/job-worker-2" in backend.deleted_pods
        assert backend.get_pod("default", "job-worker-1") is not None

    def test_scale_out_creates_new_indices(self):
        store, backend, c = harness()
        stored = submit(store, c, new_job(worker=1))
        stored.spec.replica_specs[ReplicaType.WORKER].replicas = 3
        store.update_spec(stored)
        c.sync_until_quiet()
        assert backend.get_pod("default", "job-worker-2") is not None


class TestScaleRegression:
    def test_scale_to_zero_resets_replica_status(self):
        store, backend, c = harness()
        stored = submit(store, c, new_job(worker=4))
        backend.run_all("default")
        c.sync_until_quiet()
        assert get_status(store, stored).replica_statuses[ReplicaType.WORKER].active == 4
        stored = store.get("default", "job")
        stored.spec.replica_specs[ReplicaType.WORKER].replicas = 0
        store.update_spec(stored)
        c.sync_until_quiet()
        assert get_status(store, stored).replica_statuses[ReplicaType.WORKER].active == 0
        assert backend.list_pods("default") == []

    def test_scale_in_deletes_services_too(self):
        store, backend, c = harness()
        stored = submit(store, c, new_job(worker=4))
        stored.spec.replica_specs[ReplicaType.WORKER].replicas = 2
        store.update_spec(stored)
        c.sync_until_quiet()
        names = {s.metadata.name for s in backend.list_services("default")}
        assert names == {"job-worker-0", "job-worker-1"}

    def test_gang_group_resized_on_scale(self):
        store, backend, c = harness()
        job = new_job(worker=2)
        job.spec.enable_gang_scheduling = True
        stored = submit(store, c, job)
        assert backend.get_pod_group("default", "job").min_member == 2
        stored = store.get("default", "job")
        stored.spec.replica_specs[ReplicaType.WORKER].replicas = 8
        store.update_spec(stored)
        c.sync_until_quiet()
        assert backend.get_pod_group("default", "job").min_member == 8


class TestMixedSliceWorkerSuccess:
    def test_worker0_alone_is_not_enough_with_slices(self):
        store, backend, c = harness()
        job = submit(store, c, new_job(worker=1, tpu_slice=2, tpu_topology="v5e-4"))
        backend.run_all("default")
        backend.succeed_pod("default", "job-worker-0")
        c.sync_until_quiet()
        st = get_status(store, job)
        assert not st.has_condition(JobConditionType.SUCCEEDED)
        backend.succeed_pod("default", "job-tpuslice-0")
        backend.succeed_pod("default", "job-tpuslice-1")
        c.sync_until_quiet()
        assert get_status(store, job).has_condition(JobConditionType.SUCCEEDED)


class TestExpectationsRace:
    """The informer-lag race (SURVEY.md §5 "Race detection"): with manual
    watch delivery the cache lags writes; a second sync before delivery
    must not double-create."""

    def test_no_double_create_while_cache_lags(self):
        store, backend, c = harness(delivery="manual")
        store.create(new_job(worker=2))
        c.sync_until_quiet()  # first sync: creates 2 pods, 0 events delivered
        assert len(backend.created_pods) == 2
        # adversarial second sync with stale (empty) cache
        c.reconciler.sync("default/job")
        assert len(backend.created_pods) == 2  # expectations blocked it
        # deliver events; sync again; still exactly 2
        backend.pump()
        c.sync_until_quiet()
        assert len(backend.created_pods) == 2
        assert c.pod_exp.satisfied("default/job")

    def test_partial_delivery_still_blocks(self):
        store, backend, c = harness(delivery="manual")
        store.create(new_job(worker=3))
        c.sync_until_quiet()
        assert len(backend.created_pods) == 3
        backend.pump(1)  # only one ADDED event arrives
        c.reconciler.sync("default/job")
        assert len(backend.created_pods) == 3
        backend.pump()
        c.sync_until_quiet()
        assert len(backend.created_pods) == 3

    def test_services_share_the_guard(self):
        store, backend, c = harness(delivery="manual")
        store.create(new_job(worker=1))
        c.sync_until_quiet()
        assert len(backend.created_services) == 1
        c.reconciler.sync("default/job")
        assert len(backend.created_services) == 1

    def test_phase_change_invisible_until_pumped(self):
        """Watch events snapshot objects: a phase mutation in the backend
        must not leak into the informer cache through aliasing."""

        store, backend, c = harness(delivery="manual")
        store.create(new_job(worker=1))
        c.sync_until_quiet()
        backend.pump()  # deliver ADDED events
        c.sync_until_quiet()
        backend.run_all("default")
        backend.fail_pod("default", "job-worker-0", exit_code=1)
        # events NOT pumped: cache must still see the pod as Pending
        cached = c.cache.list_pods("default")[0]
        assert cached.phase is PodPhase.PENDING
        backend.pump()
        cached = c.cache.list_pods("default")[0]
        assert cached.phase is PodPhase.FAILED


class TestEvents:
    def test_audit_trail(self):
        store, backend, c = harness()
        submit(store, c, new_job(worker=1))
        backend.run_all("default")
        backend.succeed_pod("default", "job-worker-0")
        c.sync_until_quiet()
        reasons = [e.reason for e in c.recorder.for_object("default/job")]
        assert "JobCreated" in reasons
        assert "SuccessfulCreatePod" in reasons
        assert "JobSucceeded" in reasons


class TestSyncSpans:
    def test_sync_duration_histogram_and_outcome_counters(self):
        """SURVEY.md §5 span logging: every sync lands in the duration
        histogram and the result counter; both surface in /metrics
        exposition (VERDICT r2 item 6)."""

        store, backend, c = harness()
        submit(store, c, new_job(worker=1))
        backend.run_all("default")
        backend.succeed_pod("default", "job-worker-0")
        c.sync_until_quiet()
        h = c.metrics.histogram("tpujob_sync_duration_seconds")
        assert h["count"] >= 3  # create/run/succeed syncs at minimum
        assert h["sum"] > 0
        assert c.metrics.counter("tpujob_syncs_total", result="ok") == h["count"]
        text = c.metrics.exposition()
        assert 'tpujob_sync_duration_seconds_bucket{le="+Inf"}' in text
        assert "tpujob_sync_duration_seconds_count" in text

    def test_slow_sync_warns(self, caplog):
        import logging

        from tf_operator_tpu.controller.reconciler import ReconcilerConfig

        store, backend, c = harness(
            config=ReconcilerConfig(slow_sync_warn_seconds=0.0)
        )
        with caplog.at_level(logging.WARNING):
            submit(store, c, new_job(worker=1))
        assert any("slow sync" in r.message for r in caplog.records)

    def test_sync_error_counted(self):
        store, backend, c = harness()
        store.create(new_job(worker=1))
        # sabotage the backend: first create_pod raises
        orig = backend.create_pod
        calls = {"n": 0}

        def flaky(pod):
            if calls["n"] == 0:
                calls["n"] += 1
                raise RuntimeError("injected")
            return orig(pod)

        backend.create_pod = flaky
        c.sync_until_quiet()
        assert c.metrics.counter("tpujob_syncs_total", result="error") >= 1
        # the rate-limited retry (base delay ~5ms) recovers the job
        deadline = time.time() + 5
        while time.time() < deadline and not backend.list_pods("default"):
            time.sleep(0.01)
            c.sync_until_quiet()
        assert len(backend.list_pods("default")) == 1


class TestInformerResync:
    """SharedInformer resync parity (SURVEY.md §5): a periodic full
    re-list heals lost watch events — without it, a single dropped
    event strands a job until an unrelated event arrives."""

    def test_lost_phase_event_healed(self):
        store, backend, c = harness(delivery="manual")
        job = submit(store, c, new_job(worker=1))
        backend.pump()  # deliver pod ADD
        c.sync_until_quiet()
        backend.run_all("default")
        backend.succeed_pod("default", "job-worker-0")
        # the MODIFIED events are LOST (never pumped)
        backend._pending_events.clear()
        c.sync_until_quiet()
        assert not get_status(store, job).has_condition(JobConditionType.SUCCEEDED)

        # resync re-lists authoritative state and re-enqueues
        assert c.resync() >= 1
        c.sync_until_quiet()
        assert get_status(store, job).has_condition(JobConditionType.SUCCEEDED)

    def test_lost_delete_event_healed(self):
        store, backend, c = harness(delivery="manual")
        job = submit(store, c, new_job(worker=1))
        backend.pump()
        c.sync_until_quiet()
        # pod vanishes without a watch event (external deletion)
        with backend._lock:
            backend._pods.pop("default/job-worker-0")
        backend._pending_events.clear()
        c.sync_until_quiet()
        assert c.cache.list_pods("default") != []  # cache is stale

        c.resync()
        c.sync_until_quiet()
        # cache healed; reconciler recreated the missing index...
        names = {p.metadata.name for p in backend.list_pods("default")}
        assert "job-worker-0" in names

    def test_resync_metric_and_periodic_loop(self):
        store, backend, c = harness()
        submit(store, c, new_job(worker=1))
        n = c.resync()
        assert n >= 1
        assert c.metrics.counter("tpujob_resyncs_total") == 1.0

    def test_resync_cleans_up_vanished_job_objects(self):
        """Job gone from the store + DELETED event lost: resync drops it
        from the cache and the next sync GCs its pods."""

        store, backend, c = harness(delivery="manual")
        job = submit(store, c, new_job(worker=1))
        backend.pump()
        c.sync_until_quiet()
        # delete the job but lose every event after the store emit: the
        # jobstore emits synchronously, so simulate the loss by putting
        # the stale job object back into the cache
        store.delete("default", "job")
        backend._pending_events.clear()
        c.cache.jobs[job.key] = job
        c.queue.forget(job.key)

        c.resync()
        c.sync_until_quiet()
        assert backend.list_pods("default") == []
