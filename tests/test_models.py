"""Transformer family (BERT / GPT / T5) on the virtual mesh: logical
shardings resolve, train steps run, losses decrease, tp/sp really shard."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# default-tier exclusion (full-model train-step compiles); see README 'Tests run in two tiers'
pytestmark = pytest.mark.slow
from jax.sharding import PartitionSpec as P

from tf_operator_tpu.models import (
    bert_tiny,
    gpt_tiny,
    mlm_loss,
    lm_loss,
    seq2seq_loss,
    t5_tiny,
)
from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

VOCAB = 128


def _ids(rng, b, s, vocab=VOCAB):
    return jnp.asarray(rng.randint(0, vocab, size=(b, s)))


def _spec_axes(sharding):
    return [a for a in sharding.spec if a is not None]


def test_bert_logical_sharding_and_training():
    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    rng = np.random.RandomState(0)
    ids = _ids(rng, 8, 16)
    labels = jnp.where(jnp.asarray(rng.rand(8, 16)) < 0.15, ids, -100)
    batch = {"input_ids": ids, "labels": labels}
    model = bert_tiny(vocab_size=VOCAB, max_len=32)
    tr = Trainer(
        model,
        TrainerConfig(learning_rate=1e-3),
        mesh,
        mlm_loss,
        batch,
        init_args=(ids,),
        shardings="logical",
    )
    # tp really shards the MLP wi kernel (embed, mlp) -> (fsdp?, tp)
    wi = tr.state.params["bert"]["layer_0"]["mlp"]["wi"]["kernel"]
    leaf = getattr(wi, "value", wi)
    assert "tp" in _spec_axes(leaf.sharding)
    first = tr.train_step(tr.shard_batch(batch))
    for _ in range(4):
        last = tr.train_step(tr.shard_batch(batch))
    assert float(last["loss"]) < float(first["loss"])


def test_gpt_ring_attention_sp_training():
    mesh = make_mesh({"dp": 2, "sp": 4})
    rng = np.random.RandomState(1)
    ids = _ids(rng, 4, 64)
    batch = {"input_ids": ids}
    model = gpt_tiny(vocab_size=VOCAB, max_len=64, mesh=mesh)
    assert model.cfg.sp_enabled
    tr = Trainer(
        model,
        TrainerConfig(learning_rate=1e-3),
        mesh,
        lm_loss,
        batch,
        init_args=(ids,),
        shardings="logical",
    )
    first = tr.train_step(tr.shard_batch(batch))
    for _ in range(4):
        last = tr.train_step(tr.shard_batch(batch))
    assert float(last["loss"]) < float(first["loss"])


def test_gpt_sp_matches_no_sp():
    """Ring-attention training (sp=4) must match plain attention (sp=1)
    numerically — same model, same data, same init.

    Tolerance: cross-mesh-shape comparison drifts up to ~2e-3 relative
    from XLA's per-layout fusion choices alone (see the note in
    tests/test_ulysses.py::test_gpt_ulysses_matches_no_sp); 5e-3 still
    catches real schedule/wiring bugs."""

    rng = np.random.RandomState(2)
    ids = _ids(rng, 8, 32)
    batch = {"input_ids": ids}
    losses = {}
    for label, shape in {"nosp": {"dp": 8}, "sp": {"dp": 2, "sp": 4}}.items():
        mesh = make_mesh(shape)
        model = gpt_tiny(vocab_size=VOCAB, max_len=32, mesh=mesh, dropout=0.0)
        tr = Trainer(
            model,
            TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
            mesh,
            lm_loss,
            batch,
            init_args=(ids,),
            shardings="logical",
            seed=7,
        )
        ms = [float(tr.train_step(tr.shard_batch(batch))["loss"]) for _ in range(3)]
        losses[label] = ms
    np.testing.assert_allclose(losses["nosp"], losses["sp"], rtol=5e-3, atol=5e-3)


def test_t5_training_step():
    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    rng = np.random.RandomState(3)
    enc = _ids(rng, 8, 12)
    dec = _ids(rng, 8, 10)
    tgt = _ids(rng, 8, 10)
    batch = {"encoder_ids": enc, "decoder_ids": dec, "targets": tgt}
    model = t5_tiny(vocab_size=VOCAB)
    tr = Trainer(
        model,
        TrainerConfig(learning_rate=1e-3),
        mesh,
        seq2seq_loss,
        batch,
        init_args=(enc, dec),
        shardings="logical",
    )
    first = tr.train_step(tr.shard_batch(batch))
    for _ in range(4):
        last = tr.train_step(tr.shard_batch(batch))
    assert float(last["loss"]) < float(first["loss"])
    assert np.isfinite(float(last["loss"]))


def test_bert_attention_mask_respected():
    """Padding positions must not change unmasked positions' hidden
    states (pre-LN encoder, mask broadcast check)."""

    rng = np.random.RandomState(4)
    ids = _ids(rng, 8, 16)
    m = jnp.ones((8, 16), jnp.int32).at[:, 12:].set(0)
    model = bert_tiny(vocab_size=VOCAB, max_len=32, dropout=0.0)
    variables = model.init(jax.random.PRNGKey(0), ids, train=False)
    a = model.apply(variables, ids, attention_mask=m, train=False)
    ids2 = ids.at[:, 12:].set(7)  # change padded tokens
    b = model.apply(variables, ids2, attention_mask=m, train=False)
    np.testing.assert_allclose(
        np.asarray(a[:, :12], np.float32), np.asarray(b[:, :12], np.float32), atol=1e-5
    )


def test_space_to_depth_stem_matches_conv7():
    """The s2d stem's kernel transform must be exact: same [7,7,3,F]
    parameter, same output as the plain 7x7/stride-2 conv (locks the
    pad/reshape/transpose in models/resnet._SpaceToDepthStem)."""

    from tf_operator_tpu.models.resnet import _SpaceToDepthStem

    rng = jax.random.PRNGKey(7)
    x = jax.random.normal(rng, (2, 56, 56, 3), jnp.float32)
    stem = _SpaceToDepthStem(16, dtype=jnp.float32)
    variables = stem.init(rng, x)
    kernel = variables["params"]["kernel"]

    y_s2d = stem.apply(variables, x)
    y_ref = jax.lax.conv_general_dilated(
        x, kernel, (2, 2), ((3, 3), (3, 3)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    assert y_s2d.shape == y_ref.shape
    np.testing.assert_allclose(np.asarray(y_s2d), np.asarray(y_ref), atol=1e-5)


def test_resnet_bn_fold_matches_eval_pass():
    """ISSUE 14 satellite: the eval-mode BN-fold path.  A TRAINED
    resnet's variables folded through fold_batchnorm produce the same
    logits as the stock eval pass (running stats, train=False), at f32
    exactly and at bf16 within rounding; the folded model refuses
    train=True (no live statistics to fold)."""

    import pytest as _pytest

    from tf_operator_tpu.models import fold_batchnorm, resnet18
    from tf_operator_tpu.parallel.trainer import batchnorm_cross_entropy_loss

    r = np.random.RandomState(0)
    batch = {
        "image": jnp.asarray(r.rand(8, 32, 32, 3), jnp.float32),
        "label": jnp.asarray(r.randint(0, 10, size=(8,))),
    }
    trainer = Trainer(
        resnet18(num_classes=10, width=8, dtype=jnp.float32),
        TrainerConfig(optimizer="sgd", learning_rate=0.1),
        make_mesh({"dp": 1}, devices=jax.devices()[:1]),
        batchnorm_cross_entropy_loss,
        batch,
    )
    for _ in range(2):  # real running stats, not init zeros/ones
        trainer.train_step(batch)
    variables = {
        "params": jax.device_get(trainer.state.params),
        **jax.device_get(trainer.state.model_state),
    }
    model = trainer.model
    ref = model.apply(variables, batch["image"], train=False)
    folded = resnet18(num_classes=10, width=8, dtype=jnp.float32, bn_fold=True)
    out = folded.apply(fold_batchnorm(variables), batch["image"], train=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    # folded params really dropped the BN scopes and grew conv biases
    fp = fold_batchnorm(variables)["params"]
    assert "bn_init" not in fp and "bias" in fp["conv_init"]
    with _pytest.raises(ValueError, match="eval-mode"):
        folded.apply(fold_batchnorm(variables), batch["image"], train=True)


def test_resnet_s2d_stem_trains():
    """resnet18(stem=space_to_depth) runs a train step (stem variant is
    exercised through the full Trainer path, not just the module)."""

    from tf_operator_tpu.models import resnet18
    from tf_operator_tpu.parallel.trainer import batchnorm_cross_entropy_loss

    r = np.random.RandomState(0)
    batch = {
        "image": jnp.asarray(r.rand(8, 64, 64, 3), jnp.float32),
        "label": jnp.asarray(r.randint(0, 10, size=(8,))),
    }
    trainer = Trainer(
        resnet18(num_classes=10, stem="space_to_depth"),
        TrainerConfig(optimizer="sgd", learning_rate=0.1),
        make_mesh({"dp": 1}, devices=jax.devices()[:1]),
        batchnorm_cross_entropy_loss,
        batch,
    )
    metrics = trainer.train_step(batch)
    assert np.isfinite(float(metrics["loss"]))
