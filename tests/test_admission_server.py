"""Server-side admission (ISSUE 5 satellite, VERDICT r5 next #9).

Three layers, all tested here:
1. kubesim's POST path validates TPUJob objects (the admission
   webhook's seat): garbage gets the real apiserver's 422 Invalid.
2. Informer ingestion validates anyway (``kubejobs._decode``): a
   webhook-less apiserver (``MiniApiServer(admission=False)``) CAN
   store garbage, and the operator must survive it.
3. The reconciler marks such a job Failed/InvalidSpec + Warning event
   and never reconciles it — no pods, ever.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from tf_operator_tpu.api.types import JobConditionType
from tf_operator_tpu.backend.kubejobs import _decode


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, json.loads(resp.read())


GARBAGE_UNPARSEABLE = {
    "apiVersion": "tpujob.dist/v1",
    "kind": "TPUJob",
    "metadata": {"name": "garbage-types", "namespace": "default"},
    "spec": {"tpuReplicaSpecs": {"Bogus": {"replicas": 1}}},
}

GARBAGE_INVALID = {
    "apiVersion": "tpujob.dist/v1",
    "kind": "TPUJob",
    "metadata": {"name": "garbage-empty", "namespace": "default"},
    "spec": {"tpuReplicaSpecs": {}},  # parses; fails validation
}


class TestDecodeIngestionAdmission:
    def test_unparseable_object_becomes_invalid_skeleton(self):
        job = _decode(GARBAGE_UNPARSEABLE)
        assert job.invalid_reason and "Bogus" in job.invalid_reason
        assert job.key == "default/garbage-types"

    def test_semantically_invalid_object_flagged(self):
        job = _decode(GARBAGE_INVALID)
        assert job.invalid_reason and "replica" in job.invalid_reason

    def test_valid_object_roundtrips_clean(self):
        from tests.testutil import new_job
        from tf_operator_tpu.api.defaults import set_defaults
        from tf_operator_tpu.api.serde import job_to_dict

        job = new_job(name="ok", worker=1)
        set_defaults(job)
        out = _decode(job_to_dict(job))
        assert out.invalid_reason is None
        assert out.key == "default/ok"

    def test_invalid_flag_survives_deepcopy(self):
        job = _decode(GARBAGE_INVALID)
        assert job.deepcopy().invalid_reason == job.invalid_reason

    def test_status_preserved_on_invalid_object(self):
        """Re-ingesting an invalid object that already carries our
        Failed mark must see is_terminal() — one mark, then silence."""

        obj = dict(GARBAGE_INVALID)
        obj["status"] = {
            "conditions": [{
                "type": "Failed", "status": "True",
                "reason": "InvalidSpec", "message": "x",
            }]
        }
        job = _decode(obj)
        assert job.invalid_reason
        assert job.is_terminal()


@pytest.mark.slow
class TestKubesimAdmission:
    def test_post_garbage_rejected_422(self):
        from tf_operator_tpu.backend.kubesim import MiniApiServer

        sim = MiniApiServer().start()  # admission on by default
        try:
            for garbage in (GARBAGE_UNPARSEABLE, GARBAGE_INVALID):
                with pytest.raises(urllib.error.HTTPError) as e:
                    _post(
                        f"{sim.url}/apis/tpujob.dist/v1/namespaces/default/tpujobs",
                        garbage,
                    )
                assert e.value.code == 422
                body = json.loads(e.value.read())
                assert body["reason"] == "Invalid"
            # valid objects still land (the HA-test manifest shape)
            status, _ = _post(
                f"{sim.url}/apis/tpujob.dist/v1/namespaces/default/tpujobs",
                {
                    "apiVersion": "tpujob.dist/v1",
                    "kind": "TPUJob",
                    "metadata": {"name": "ok", "namespace": "default"},
                    "spec": {
                        "tpuReplicaSpecs": {
                            "Worker": {
                                "replicas": 1,
                                "template": {"spec": {"containers": [{
                                    "name": "tensorflow",
                                    "command": ["python", "-c", "pass"],
                                }]}},
                            }
                        }
                    },
                },
            )
            assert status == 201
        finally:
            sim.stop()

    def test_update_verbs_also_admitted(self):
        """A real admission webhook intercepts UPDATE too: PUT with a
        garbage spec — and a PATCH that corrupts spec — must 422, while
        status-only patches land even on inadmissible objects (the
        informer backstop's Failed mark must never be refused)."""

        from tf_operator_tpu.backend.kubesim import MiniApiServer

        valid = {
            "apiVersion": "tpujob.dist/v1",
            "kind": "TPUJob",
            "metadata": {"name": "upd", "namespace": "default"},
            "spec": {"tpuReplicaSpecs": {"Worker": {
                "replicas": 1,
                "template": {"spec": {"containers": [{
                    "name": "tensorflow", "command": ["python", "-c", "pass"],
                }]}},
            }}},
        }
        sim = MiniApiServer().start()
        base = f"{sim.url}/apis/tpujob.dist/v1/namespaces/default/tpujobs"
        try:
            status, _ = _post(base, valid)
            assert status == 201

            def send(method, payload):
                req = urllib.request.Request(
                    f"{base}/upd", data=json.dumps(payload).encode(),
                    method=method,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=10) as resp:
                    return resp.status

            bad = dict(valid)
            bad["spec"] = {"tpuReplicaSpecs": {}}
            for method in ("PUT", "PATCH"):
                with pytest.raises(urllib.error.HTTPError) as e:
                    send(method, bad if method == "PUT"
                         else {"spec": {"tpuReplicaSpecs": {}}})
                assert e.value.code == 422, method
            # status-only patch: always admitted
            assert send("PATCH", {"status": {"conditions": [{
                "type": "Failed", "status": "True",
                "reason": "InvalidSpec", "message": "x",
            }]}}) == 200
        finally:
            sim.stop()

    def test_out_of_band_garbage_marked_failed_never_reconciled(self):
        """The acceptance e2e: POST garbage straight to a webhook-less
        kubesim; the operator marks it Failed/InvalidSpec with a
        Warning event and never creates a pod for it."""

        from tf_operator_tpu.backend.kube import KubeBackend
        from tf_operator_tpu.backend.kubejobs import KubeJobStore
        from tf_operator_tpu.backend.kubesim import MiniApiServer
        from tf_operator_tpu.controller.controller import TPUJobController
        from tf_operator_tpu.controller.reconciler import ReconcilerConfig

        sim = MiniApiServer(admission=False).start()
        store = KubeJobStore(sim.url)
        backend = KubeBackend(sim.url)
        controller = TPUJobController(
            store, backend, config=ReconcilerConfig(resolver=backend.resolver)
        )
        controller.run(threadiness=2)
        try:
            status, _ = _post(
                f"{sim.url}/apis/tpujob.dist/v1/namespaces/default/tpujobs",
                GARBAGE_UNPARSEABLE,
            )
            assert status == 201  # no webhook: garbage lands in the store

            deadline = time.time() + 20.0
            job = None
            while time.time() < deadline:
                job = store.get("default", "garbage-types")
                if job is not None and job.status.has_condition(
                    JobConditionType.FAILED
                ):
                    break
                time.sleep(0.1)
            assert job is not None and job.status.has_condition(
                JobConditionType.FAILED
            ), "operator never marked the invalid job Failed"
            cond = job.status.condition(JobConditionType.FAILED)
            assert cond.reason == "InvalidSpec"
            assert "Bogus" in cond.message

            events = controller.recorder.for_object("default/garbage-types")
            assert any(
                e.reason == "InvalidSpec" and e.type == "Warning"
                for e in events
            )
            # never reconciled: no pods now, and none later
            time.sleep(1.0)
            assert backend.list_pods("default") == []
            assert controller.metrics.counter("tpujob_invalid_total") >= 1.0
        finally:
            controller.stop()
            backend.close()
            store.close()
            sim.stop()
