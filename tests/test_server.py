"""Operator binary surface tests: HTTP job API, metrics/health endpoints,
client CLI, and leader election (SURVEY.md §2 "Operator entrypoint",
"Metrics"; §1 L5/L9)."""

import json
import os
import sys
import urllib.request

import pytest

from tests.testutil import harness, new_job
from tf_operator_tpu.api.serde import job_to_dict
from tf_operator_tpu.cmd.leader import FileLease
from tf_operator_tpu.server.api import ApiServer


@pytest.fixture
def api():
    store, backend, controller = harness()
    server = ApiServer(store, backend, controller.metrics, controller.recorder)
    server.start()
    yield store, backend, controller, f"http://127.0.0.1:{server.port}"
    server.stop()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        body = r.read().decode()
    try:
        return json.loads(body)
    except ValueError:
        return body


def _post(url, payload):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read().decode())


class TestApiServer:
    def test_healthz_and_metrics(self, api):
        _, _, _, base = api
        assert _get(f"{base}/healthz").startswith("ok")
        assert isinstance(_get(f"{base}/metrics"), str)

    def test_dashboard_served_at_root(self, api):
        _, _, _, base = api
        page = _get(f"{base}/")
        assert "<title>tpu-operator</title>" in page
        # the page drives the same API the CLI uses
        assert "/apis/v1/tpujobs" in page

    def test_submit_reconcile_status_roundtrip(self, api):
        store, backend, controller, base = api
        manifest = job_to_dict(new_job("web", chief=1, worker=2))
        created = _post(f"{base}/apis/v1/namespaces/default/tpujobs", manifest)
        assert created["metadata"]["name"] == "web"

        controller.sync_until_quiet()
        pods = _get(f"{base}/apis/v1/namespaces/default/tpujobs/web/pods")["items"]
        assert len(pods) == 3

        backend.run_all("default")
        controller.sync_until_quiet()
        backend.succeed_pod("default", "web-chief-0")
        controller.sync_until_quiet()

        job = _get(f"{base}/apis/v1/namespaces/default/tpujobs/web")
        types = [
            c["type"] for c in job["status"]["conditions"] if c["status"] == "True"
        ]
        assert "Succeeded" in types

        events = _get(f"{base}/apis/v1/namespaces/default/tpujobs/web/events")
        assert any(e["reason"] == "JobSucceeded" for e in events["items"])

        listing = _get(f"{base}/apis/v1/tpujobs")["items"]
        assert [j["metadata"]["name"] for j in listing] == ["web"]

    def test_dashboard_write_path(self, api):
        """The dashboard can create and delete jobs (SURVEY.md §2
        "Dashboard: list/create/delete TFJobs" — the write half VERDICT
        r3 named as the last §2 partial).  Drives the exact requests the
        page's submitJob()/deleteJob() issue: a YAML body POSTed with
        Content-Type application/yaml, then DELETE on the job URL."""

        import yaml

        store, backend, controller, base = api
        page = _get(f"{base}/")
        # the page carries the write-path UI, not just the table
        assert "submitJob" in page and "deleteJob" in page
        assert "confirm(" in page  # delete asks before acting

        manifest = yaml.safe_dump(job_to_dict(new_job("from-ui", worker=2)))
        req = urllib.request.Request(
            f"{base}/apis/v1/namespaces/default/tpujobs",
            data=manifest.encode(),
            method="POST",
            headers={"Content-Type": "application/yaml"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
            created = json.loads(r.read().decode())
        assert created["metadata"]["name"] == "from-ui"
        controller.sync_until_quiet()
        assert len(backend.list_pods("default", {})) == 2

        # the new job renders in the listing the page polls
        listing = _get(f"{base}/apis/v1/tpujobs")["items"]
        assert [j["metadata"]["name"] for j in listing] == ["from-ui"]

        req = urllib.request.Request(
            f"{base}/apis/v1/namespaces/default/tpujobs/from-ui",
            method="DELETE",
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        assert store.get("default", "from-ui") is None

    def test_post_garbage_yaml_rejected_422(self, api):
        _, _, _, base = api
        req = urllib.request.Request(
            f"{base}/apis/v1/namespaces/default/tpujobs",
            data=b"just a string, not a mapping",
            method="POST",
            headers={"Content-Type": "application/yaml"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 422

    def test_debug_stacks(self, api):
        """SURVEY.md §5: the reference serves Go pprof on the monitoring
        port; /debug/stacks is the equivalent hang-diagnosis surface."""

        _, _, _, base = api
        dump = _get(f"{base}/debug/stacks")
        assert "--- thread" in dump
        # the serving thread's own frame is visible in the dump
        assert "do_GET" in dump

    def test_invalid_manifest_rejected_422(self, api):
        _, _, _, base = api
        bad = {"apiVersion": "tpujob.dist/v1", "kind": "TPUJob",
               "metadata": {"name": "bad"}, "spec": {"replicaSpecs": {}}}
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{base}/apis/v1/namespaces/default/tpujobs", bad)
        assert ei.value.code == 422

    def test_duplicate_409_and_missing_404(self, api):
        store, _, _, base = api
        manifest = job_to_dict(new_job("dup", worker=1))
        _post(f"{base}/apis/v1/namespaces/default/tpujobs", manifest)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{base}/apis/v1/namespaces/default/tpujobs", manifest)
        assert ei.value.code == 409
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/apis/v1/namespaces/default/tpujobs/ghost")
        assert ei.value.code == 404

    def test_delete(self, api):
        store, _, controller, base = api
        manifest = job_to_dict(new_job("gone", worker=1))
        _post(f"{base}/apis/v1/namespaces/default/tpujobs", manifest)
        req = urllib.request.Request(
            f"{base}/apis/v1/namespaces/default/tpujobs/gone", method="DELETE"
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        assert store.get("default", "gone") is None


class TestTpujobCli:
    def test_submit_list_describe_delete(self, api, tmp_path, capsys):
        store, backend, controller, base = api
        from tf_operator_tpu.cmd import tpujob

        manifest = job_to_dict(new_job("cli", chief=1, worker=1))
        path = tmp_path / "job.yaml"
        import yaml

        path.write_text(yaml.safe_dump(manifest))

        assert tpujob.main(["--server", base, "submit", "-f", str(path)]) == 0
        controller.sync_until_quiet()
        backend.run_all("default")
        controller.sync_until_quiet()
        backend.succeed_pod("default", "cli-chief-0")
        controller.sync_until_quiet()

        assert tpujob.main(["--server", base, "list"]) == 0
        out = capsys.readouterr().out
        assert "cli" in out and "Succeeded" in out

        assert tpujob.main(["--server", base, "describe", "cli"]) == 0
        out = capsys.readouterr().out
        assert "JobSucceeded" in out

        assert tpujob.main(["--server", base, "delete", "cli"]) == 0
        assert store.get("default", "cli") is None


class TestLeaderElection:
    def test_single_holder(self, tmp_path):
        path = str(tmp_path / "lease.lock")
        a = FileLease(path, "a")
        b = FileLease(path, "b")
        assert a.try_acquire()
        assert a.is_leader
        assert not b.try_acquire()
        assert b.holder() == "a"
        a.release()
        assert b.try_acquire()
        assert b.holder() == "b"
        b.release()

    def test_lock_released_on_process_death(self, tmp_path):
        import subprocess

        path = str(tmp_path / "lease.lock")
        code = (
            "import sys; sys.path.insert(0, %r); "
            "from tf_operator_tpu.cmd.leader import FileLease; "
            "l = FileLease(%r, 'child'); assert l.try_acquire(); print('held', flush=True)"
            % (os.getcwd(), path)
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True, timeout=30
        )
        assert "held" in proc.stdout
        # child exited: kernel released the flock; we can acquire now
        me = FileLease(path, "parent")
        assert me.try_acquire()
        me.release()


class TestNamespaceScoping:
    def test_scoped_server_rejects_other_namespaces(self):
        store, backend, controller = harness()
        server = ApiServer(
            store, backend, controller.metrics, controller.recorder,
            namespace="team-a",
        )
        server.start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            manifest = job_to_dict(new_job("scoped", worker=1))
            _post(f"{base}/apis/v1/namespaces/team-a/tpujobs", manifest)
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"{base}/apis/v1/namespaces/team-b/tpujobs", manifest)
            assert ei.value.code == 403
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{base}/apis/v1/namespaces/team-b/tpujobs")
            assert ei.value.code == 403
            # the cross-namespace listing is scoped too
            items = _get(f"{base}/apis/v1/tpujobs")["items"]
            assert [j["metadata"]["namespace"] for j in items] == ["team-a"]
        finally:
            server.stop()


class TestOperatorBinary:
    def test_version_flag(self, capsys):
        from tf_operator_tpu.cmd import operator

        assert operator.main(["--version"]) == 0
        assert "tpu-operator" in capsys.readouterr().out

    @pytest.mark.parametrize("backend", ["fake", "local"])
    def test_boots_serves_and_stops(self, backend, tmp_path):
        import subprocess

        proc = subprocess.Popen(
            [
                sys.executable, "-m", "tf_operator_tpu.cmd.operator",
                "--backend", backend, "--monitoring-port", "0",
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=os.getcwd(),
        )
        try:
            line = proc.stdout.readline()
            assert "listening on" in line
            port = int(line.rsplit(":", 1)[1])
            assert _get(f"http://127.0.0.1:{port}/healthz").startswith("ok")
        finally:
            proc.terminate()
            proc.wait(timeout=10)

    def test_standby_serves_health_without_leadership(self, tmp_path):
        """--leader-elect gates only the controller; /healthz serves on
        the standby (liveness probes must not kill it)."""

        import subprocess

        lease_path = str(tmp_path / "lease.lock")
        holder = FileLease(lease_path, "test-holder")
        assert holder.try_acquire()
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "tf_operator_tpu.cmd.operator",
                "--backend", "fake", "--monitoring-port", "0",
                "--leader-elect", "--lease-file", lease_path,
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=os.getcwd(),
        )
        try:
            line = proc.stdout.readline()
            port = int(line.rsplit(":", 1)[1])
            # standby (we hold the lease) still serves health + metrics
            assert _get(f"http://127.0.0.1:{port}/healthz").startswith("ok")
            assert holder.holder() == "test-holder"
        finally:
            proc.terminate()
            proc.wait(timeout=10)
            holder.release()


class TestSummariesEndpoint:
    def test_metrics_series_served_from_annotation(self, api, tmp_path):
        """mnist_with_summaries parity (VERDICT r2 item 5): the job's
        step series (written as JSON-lines by the Trainer) is served at
        /apis/.../metrics via the summary-dir annotation."""

        from tf_operator_tpu.utils.summaries import (
            ANNOTATION_SUMMARY_DIR,
            SummaryWriter,
        )

        store, backend, c, base = api
        sdir = str(tmp_path / "series")
        with SummaryWriter(sdir, process_id=0) as w:
            for step in range(1, 4):
                w.write(step, loss=1.0 / step, accuracy=0.3 * step)
        with SummaryWriter(sdir, process_id=1) as w:
            w.write(2, loss=0.55)

        job = new_job("summarized", worker=1)
        job.metadata.annotations[ANNOTATION_SUMMARY_DIR] = sdir
        store.create(job)
        c.sync_until_quiet()

        items = _get(f"{base}/apis/v1/namespaces/default/tpujobs/summarized/metrics")[
            "items"
        ]
        assert [m["step"] for m in items] == [1, 2, 2, 3]
        assert items[0]["loss"] == 1.0
        assert any(m.get("accuracy") for m in items)

    def test_metrics_empty_without_annotation(self, api):
        store, backend, c, base = api
        store.create(new_job("plain", worker=1))
        c.sync_until_quiet()
        items = _get(f"{base}/apis/v1/namespaces/default/tpujobs/plain/metrics")[
            "items"
        ]
        assert items == []

    @pytest.mark.slow
    def test_trainer_writes_series(self, tmp_path):
        """The Trainer emits the series every summary_every steps."""

        import jax
        import jax.numpy as jnp
        import numpy as np

        from tf_operator_tpu.models import MnistCNN
        from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
        from tf_operator_tpu.parallel.trainer import cross_entropy_loss
        from tf_operator_tpu.utils.summaries import SummaryWriter, read_series

        r = np.random.RandomState(0)
        batch = {
            "image": jnp.asarray(r.rand(8, 28, 28, 1), jnp.float32),
            "label": jnp.asarray(r.randint(0, 10, size=(8,))),
        }
        sdir = str(tmp_path / "s")
        writer = SummaryWriter(sdir)
        trainer = Trainer(
            MnistCNN(),
            TrainerConfig(optimizer="sgd", learning_rate=0.05, summary_every=2),
            make_mesh({"dp": 1}, devices=jax.devices()[:1]),
            cross_entropy_loss,
            batch,
            summary_writer=writer,
        )
        for _ in range(6):
            trainer.train_step(batch)
        writer.close()
        series = read_series(sdir)
        assert [m["step"] for m in series] == [2, 4, 6]
        assert all("loss" in m and "accuracy" in m for m in series)
        # steps_per_sec appears once a previous interval exists
        assert "steps_per_sec" in series[-1]


class TestDeployStory:
    """Operator config file + deployment launcher (VERDICT r2 item 4,
    SURVEY.md §2 "Deploy manifests" / §1 L6)."""

    def _write_config(self, tmp_path, **over):
        import yaml

        cfg = {
            "apiVersion": "tpujob.dist/v1",
            "kind": "OperatorConfig",
            "backend": "fake",
            "threadiness": 2,
            "monitoringPort": 0,
            "jsonLog": True,
        }
        cfg.update(over)
        path = tmp_path / "operator.yaml"
        path.write_text(yaml.safe_dump(cfg))
        return str(path)

    def test_config_parsing_and_flag_precedence(self, tmp_path):
        from tf_operator_tpu.cmd.operator import build_parser, load_operator_config

        path = self._write_config(tmp_path, namespace="prod", threadiness=7)
        cfg = load_operator_config(path)
        assert cfg == {
            "backend": "fake",
            "namespace": "prod",
            "threadiness": 7,
            "monitoring_port": 0,
            "json_log": True,
        }
        parser = build_parser()
        parser.set_defaults(**cfg)
        # explicit CLI flag beats the file; file beats built-in default
        args = parser.parse_args(["--threadiness", "9"])
        assert args.threadiness == 9
        assert args.namespace == "prod"
        assert args.backend == "fake"

    def test_unknown_config_key_rejected(self, tmp_path):
        import yaml

        from tf_operator_tpu.cmd.operator import load_operator_config

        path = tmp_path / "bad.yaml"
        path.write_text(yaml.safe_dump({"kind": "OperatorConfig", "treadiness": 4}))
        with pytest.raises(ValueError, match="treadiness"):
            load_operator_config(str(path))

    def test_operator_boots_from_config_file(self, tmp_path):
        import subprocess

        path = self._write_config(tmp_path)
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "tf_operator_tpu.cmd.operator",
                "--config", path,
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=os.getcwd(),
        )
        try:
            line = proc.stdout.readline()
            assert "listening on" in line
            port = int(line.rsplit(":", 1)[1])
            assert _get(f"http://127.0.0.1:{port}/healthz").startswith("ok")
            # the job API works through the manifest-booted operator
            created = _post(
                f"http://127.0.0.1:{port}/apis/v1/namespaces/default/tpujobs",
                job_to_dict(new_job("from-config", worker=1)),
            )
            assert created["metadata"]["name"] == "from-config"
        finally:
            proc.terminate()
            proc.wait(timeout=10)

    def test_deployment_requires_leader_elect_for_replicas(self, tmp_path):
        import yaml

        from tf_operator_tpu.cmd.deploy import load_deployment

        path = tmp_path / "dep.yaml"
        path.write_text(
            yaml.safe_dump(
                {"kind": "OperatorDeployment", "replicas": 2, "config": {}}
            )
        )
        with pytest.raises(ValueError, match="leaderElect"):
            load_deployment(str(path))

    @pytest.mark.slow
    def test_deploy_launcher_restarts_crashed_replica(self, tmp_path):
        """The launcher is the Deployment-controller analogue: kill the
        single replica, it comes back."""

        import subprocess
        import time as _t
        import yaml

        from tf_operator_tpu.backend.local import _free_port

        # OS-assigned port: a fixed 18931 collided across parallel
        # pytest workers (the round-3 lesson writ small)
        port = _free_port()
        path = tmp_path / "dep.yaml"
        path.write_text(
            yaml.safe_dump(
                {
                    "apiVersion": "tpujob.dist/v1",
                    "kind": "OperatorDeployment",
                    "replicas": 1,
                    "config": {
                        "backend": "fake",
                        "monitoringPort": port,
                        "leaseFile": str(tmp_path / "lease.lock"),
                    },
                }
            )
        )
        launcher = subprocess.Popen(
            [sys.executable, "-m", "tf_operator_tpu.cmd.deploy", str(path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=os.getcwd(),
        )
        try:
            # 90s: a jax-importing operator boot can take >30s on a
            # machine already running 4 parallel test workers
            def wait_health(timeout=90):
                deadline = _t.time() + timeout
                while _t.time() < deadline:
                    try:
                        if _get(f"http://127.0.0.1:{port}/healthz").startswith("ok"):
                            return True
                    except Exception:
                        _t.sleep(0.2)
                return False

            assert wait_health(), "replica never became healthy"
            # kill OUR child, identified from the launcher's own
            # "replica N pid P" line (never a host-wide pgrep)
            pid = None
            deadline = _t.time() + 10
            while pid is None and _t.time() < deadline:
                line = launcher.stdout.readline()
                if line.startswith("replica 0 pid "):
                    pid = int(line.rsplit(" ", 1)[1])
            assert pid is not None, "launcher never announced its child pid"
            os.kill(pid, 9)
            _t.sleep(0.5)
            assert wait_health(), "replica was not restarted after crash"
        finally:
            launcher.terminate()
            launcher.wait(timeout=15)
