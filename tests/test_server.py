"""Operator binary surface tests: HTTP job API, metrics/health endpoints,
client CLI, and leader election (SURVEY.md §2 "Operator entrypoint",
"Metrics"; §1 L5/L9)."""

import json
import os
import sys
import urllib.request

import pytest

from tests.testutil import harness, new_job
from tf_operator_tpu.api.serde import job_to_dict
from tf_operator_tpu.cmd.leader import FileLease
from tf_operator_tpu.server.api import ApiServer


@pytest.fixture
def api():
    store, backend, controller = harness()
    server = ApiServer(store, backend, controller.metrics, controller.recorder)
    server.start()
    yield store, backend, controller, f"http://127.0.0.1:{server.port}"
    server.stop()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        body = r.read().decode()
    try:
        return json.loads(body)
    except ValueError:
        return body


def _post(url, payload):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read().decode())


class TestApiServer:
    def test_healthz_and_metrics(self, api):
        _, _, _, base = api
        assert _get(f"{base}/healthz").startswith("ok")
        assert isinstance(_get(f"{base}/metrics"), str)

    def test_dashboard_served_at_root(self, api):
        _, _, _, base = api
        page = _get(f"{base}/")
        assert "<title>tpu-operator</title>" in page
        # the page drives the same API the CLI uses
        assert "/apis/v1/tpujobs" in page

    def test_submit_reconcile_status_roundtrip(self, api):
        store, backend, controller, base = api
        manifest = job_to_dict(new_job("web", chief=1, worker=2))
        created = _post(f"{base}/apis/v1/namespaces/default/tpujobs", manifest)
        assert created["metadata"]["name"] == "web"

        controller.sync_until_quiet()
        pods = _get(f"{base}/apis/v1/namespaces/default/tpujobs/web/pods")["items"]
        assert len(pods) == 3

        backend.run_all("default")
        controller.sync_until_quiet()
        backend.succeed_pod("default", "web-chief-0")
        controller.sync_until_quiet()

        job = _get(f"{base}/apis/v1/namespaces/default/tpujobs/web")
        types = [
            c["type"] for c in job["status"]["conditions"] if c["status"] == "True"
        ]
        assert "Succeeded" in types

        events = _get(f"{base}/apis/v1/namespaces/default/tpujobs/web/events")
        assert any(e["reason"] == "JobSucceeded" for e in events["items"])

        listing = _get(f"{base}/apis/v1/tpujobs")["items"]
        assert [j["metadata"]["name"] for j in listing] == ["web"]

    def test_invalid_manifest_rejected_422(self, api):
        _, _, _, base = api
        bad = {"apiVersion": "tpujob.dist/v1", "kind": "TPUJob",
               "metadata": {"name": "bad"}, "spec": {"replicaSpecs": {}}}
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{base}/apis/v1/namespaces/default/tpujobs", bad)
        assert ei.value.code == 422

    def test_duplicate_409_and_missing_404(self, api):
        store, _, _, base = api
        manifest = job_to_dict(new_job("dup", worker=1))
        _post(f"{base}/apis/v1/namespaces/default/tpujobs", manifest)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{base}/apis/v1/namespaces/default/tpujobs", manifest)
        assert ei.value.code == 409
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/apis/v1/namespaces/default/tpujobs/ghost")
        assert ei.value.code == 404

    def test_delete(self, api):
        store, _, controller, base = api
        manifest = job_to_dict(new_job("gone", worker=1))
        _post(f"{base}/apis/v1/namespaces/default/tpujobs", manifest)
        req = urllib.request.Request(
            f"{base}/apis/v1/namespaces/default/tpujobs/gone", method="DELETE"
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        assert store.get("default", "gone") is None


class TestTpujobCli:
    def test_submit_list_describe_delete(self, api, tmp_path, capsys):
        store, backend, controller, base = api
        from tf_operator_tpu.cmd import tpujob

        manifest = job_to_dict(new_job("cli", chief=1, worker=1))
        path = tmp_path / "job.yaml"
        import yaml

        path.write_text(yaml.safe_dump(manifest))

        assert tpujob.main(["--server", base, "submit", "-f", str(path)]) == 0
        controller.sync_until_quiet()
        backend.run_all("default")
        controller.sync_until_quiet()
        backend.succeed_pod("default", "cli-chief-0")
        controller.sync_until_quiet()

        assert tpujob.main(["--server", base, "list"]) == 0
        out = capsys.readouterr().out
        assert "cli" in out and "Succeeded" in out

        assert tpujob.main(["--server", base, "describe", "cli"]) == 0
        out = capsys.readouterr().out
        assert "JobSucceeded" in out

        assert tpujob.main(["--server", base, "delete", "cli"]) == 0
        assert store.get("default", "cli") is None


class TestLeaderElection:
    def test_single_holder(self, tmp_path):
        path = str(tmp_path / "lease.lock")
        a = FileLease(path, "a")
        b = FileLease(path, "b")
        assert a.try_acquire()
        assert a.is_leader
        assert not b.try_acquire()
        assert b.holder() == "a"
        a.release()
        assert b.try_acquire()
        assert b.holder() == "b"
        b.release()

    def test_lock_released_on_process_death(self, tmp_path):
        import subprocess

        path = str(tmp_path / "lease.lock")
        code = (
            "import sys; sys.path.insert(0, %r); "
            "from tf_operator_tpu.cmd.leader import FileLease; "
            "l = FileLease(%r, 'child'); assert l.try_acquire(); print('held', flush=True)"
            % (os.getcwd(), path)
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True, timeout=30
        )
        assert "held" in proc.stdout
        # child exited: kernel released the flock; we can acquire now
        me = FileLease(path, "parent")
        assert me.try_acquire()
        me.release()


class TestNamespaceScoping:
    def test_scoped_server_rejects_other_namespaces(self):
        store, backend, controller = harness()
        server = ApiServer(
            store, backend, controller.metrics, controller.recorder,
            namespace="team-a",
        )
        server.start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            manifest = job_to_dict(new_job("scoped", worker=1))
            _post(f"{base}/apis/v1/namespaces/team-a/tpujobs", manifest)
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"{base}/apis/v1/namespaces/team-b/tpujobs", manifest)
            assert ei.value.code == 403
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{base}/apis/v1/namespaces/team-b/tpujobs")
            assert ei.value.code == 403
            # the cross-namespace listing is scoped too
            items = _get(f"{base}/apis/v1/tpujobs")["items"]
            assert [j["metadata"]["namespace"] for j in items] == ["team-a"]
        finally:
            server.stop()


class TestOperatorBinary:
    def test_version_flag(self, capsys):
        from tf_operator_tpu.cmd import operator

        assert operator.main(["--version"]) == 0
        assert "tpu-operator" in capsys.readouterr().out

    @pytest.mark.parametrize("backend", ["fake", "local"])
    def test_boots_serves_and_stops(self, backend, tmp_path):
        import subprocess

        proc = subprocess.Popen(
            [
                sys.executable, "-m", "tf_operator_tpu.cmd.operator",
                "--backend", backend, "--monitoring-port", "0",
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=os.getcwd(),
        )
        try:
            line = proc.stdout.readline()
            assert "listening on" in line
            port = int(line.rsplit(":", 1)[1])
            assert _get(f"http://127.0.0.1:{port}/healthz").startswith("ok")
        finally:
            proc.terminate()
            proc.wait(timeout=10)

    def test_standby_serves_health_without_leadership(self, tmp_path):
        """--leader-elect gates only the controller; /healthz serves on
        the standby (liveness probes must not kill it)."""

        import subprocess

        lease_path = str(tmp_path / "lease.lock")
        holder = FileLease(lease_path, "test-holder")
        assert holder.try_acquire()
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "tf_operator_tpu.cmd.operator",
                "--backend", "fake", "--monitoring-port", "0",
                "--leader-elect", "--lease-file", lease_path,
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=os.getcwd(),
        )
        try:
            line = proc.stdout.readline()
            port = int(line.rsplit(":", 1)[1])
            # standby (we hold the lease) still serves health + metrics
            assert _get(f"http://127.0.0.1:{port}/healthz").startswith("ok")
            assert holder.holder() == "test-holder"
        finally:
            proc.terminate()
            proc.wait(timeout=10)
            holder.release()
