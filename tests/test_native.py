"""Native (C++) runtime tests beyond the shared contract suite
(tests/test_runtime_core.py): golden TF_CONFIG equality against the
Python generator, multi-threaded queue stress, and a full controller
run backed by the native engine."""

import json
import threading

import pytest

from tf_operator_tpu import native
from tf_operator_tpu.api.types import JobConditionType, ReplicaType
from tf_operator_tpu.bootstrap import cluster_spec
from tests.testutil import new_job

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native runtime unavailable: {native.load_error()}"
)


def make_job(name, replicas):
    return new_job(name, **replicas)


def _python_tf_config(job, rtype, index, sparse=False):
    """The pure-Python generator, bypassing the native fast path."""

    cluster = cluster_spec.gen_cluster_spec(job, cluster_spec.dns_resolver)
    if sparse and rtype in (ReplicaType.WORKER, ReplicaType.EVALUATOR):
        own = cluster[rtype.lower_name][index]
        cluster[rtype.lower_name] = [own]
        task_index = 0
    else:
        task_index = index
    return json.dumps(
        {
            "cluster": cluster,
            "task": {"type": rtype.lower_name, "index": task_index},
            "environment": "cloud",
        },
        sort_keys=True,
    )


class TestNativeTFConfig:
    @pytest.mark.parametrize(
        "replicas",
        [
            {"worker": 1},
            {"chief": 1, "worker": 2},
            {"chief": 1, "ps": 2, "worker": 4},
            {"chief": 1, "ps": 2, "worker": 4, "evaluator": 1},
        ],
    )
    def test_byte_identical_to_python(self, replicas):
        job = make_job("golden", replicas=replicas)
        for rtype in job.spec.ordered_types():
            n = int(job.spec.replica_specs[rtype].replicas or 0)
            for idx in range(n):
                want = _python_tf_config(job, rtype, idx)
                got = cluster_spec.gen_tf_config(job, rtype, idx)
                assert got == want, f"{rtype}[{idx}]"

    def test_sparse_variant_matches(self):
        job = make_job("sparse", replicas={"chief": 1, "ps": 2, "worker": 3})
        for idx in range(3):
            want = _python_tf_config(job, ReplicaType.WORKER, idx, sparse=True)
            got = cluster_spec.gen_tf_config(
                job, ReplicaType.WORKER, idx, sparse=True
            )
            assert got == want
        # non-worker roles keep dense spec + own index under sparse
        want = _python_tf_config(job, ReplicaType.PS, 1, sparse=True)
        got = cluster_spec.gen_tf_config(job, ReplicaType.PS, 1, sparse=True)
        assert got == want

    def test_parses_as_valid_tf_config(self):
        job = make_job("parse", replicas={"chief": 1, "worker": 2})
        cfg = json.loads(cluster_spec.gen_tf_config(job, ReplicaType.WORKER, 1))
        assert cfg["task"] == {"index": 1, "type": "worker"}
        assert cfg["cluster"]["worker"][1].startswith("parse-worker-1.")
        assert cfg["environment"] == "cloud"

    def test_bad_inputs_raise(self):
        with pytest.raises(ValueError):
            native.gen_tf_config_native("j", "ns", "worker=oops", "worker", 0)
        with pytest.raises(ValueError):
            native.gen_tf_config_native("j", "ns", "worker=2:0", "worker", 0)
        # partial-parse garbage must be rejected, not silently truncated
        with pytest.raises(ValueError):
            native.gen_tf_config_native("j", "ns", "worker=2x:2222", "worker", 0)
        with pytest.raises(ValueError):
            native.gen_tf_config_native("j", "ns", "worker=2:2222zz", "worker", 0)
        # JSON-unsafe names must fall back (no escaping in the native path)
        with pytest.raises(ValueError):
            native.gen_tf_config_native('a"b', "ns", "worker=1:2222", "worker", 0)

    def test_huge_delay_parks_not_fires(self):
        # seconds→ticks overflow must clamp, not fire immediately
        q = native.NativeWorkQueue()
        q.add_after("never", 1e18)
        assert q.get(0) is None
        assert len(q) == 1
        q.add("now")
        assert q.get(1e18) == "now"


class TestNativeQueueStress:
    def test_many_producers_consumers_no_loss_no_dup(self):
        q = native.NativeWorkQueue()
        n_keys = 200
        seen = {}
        lock = threading.Lock()
        done = threading.Event()

        def consumer():
            while not done.is_set():
                key = q.get(0.05)
                if key is None:
                    continue
                with lock:
                    seen[key] = seen.get(key, 0) + 1
                q.done(key)

        consumers = [threading.Thread(target=consumer) for _ in range(4)]
        for t in consumers:
            t.start()

        def producer(start):
            for i in range(start, n_keys, 4):
                q.add(f"key-{i}")

        producers = [threading.Thread(target=producer, args=(s,)) for s in range(4)]
        for t in producers:
            t.start()
        for t in producers:
            t.join()

        deadline = threading.Event()
        for _ in range(200):
            with lock:
                if len(seen) == n_keys:
                    break
            deadline.wait(0.05)
        done.set()
        for t in consumers:
            t.join(timeout=2.0)
        assert len(seen) == n_keys
        # dedup may legitimately coalesce adds, but every key processed >= 1
        assert all(v >= 1 for v in seen.values())

    def test_concurrent_expectations(self):
        e = native.NativeExpectations()
        e.expect_creations("k", 100)

        def observe():
            for _ in range(25):
                e.creation_observed("k")

        threads = [threading.Thread(target=observe) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert e.satisfied("k")
        assert e.pending("k") == (0, 0)


class TestControllerOnNativeEngine:
    def test_job_reaches_succeeded(self):
        from tf_operator_tpu.backend.fake import FakeCluster
        from tf_operator_tpu.backend.jobstore import JobStore
        from tf_operator_tpu.controller.controller import TPUJobController

        store = JobStore()
        backend = FakeCluster(delivery="sync")
        c = TPUJobController(store, backend, use_native=True)
        assert c.native
        job = store.create(make_job("native-e2e", replicas={"chief": 1, "worker": 2}))
        c.sync_until_quiet()
        backend.run_all("default")
        c.sync_until_quiet()
        backend.succeed_pod("default", "native-e2e-chief-0")
        c.sync_until_quiet()
        st = store.get("default", "native-e2e").status
        assert st.has_condition(JobConditionType.SUCCEEDED)


class TestOversizedKey:
    def test_oversized_key_dropped_not_wedged(self):
        """A >4095-byte key is dropped (logged, not raised — an
        exception would kill the controller worker thread) and the next
        valid key is served in the same call (round-1 advisor finding:
        the queue must never livelock on a corrupt head)."""

        from tf_operator_tpu.native import NativeWorkQueue

        wq = NativeWorkQueue()
        wq.add("x" * 5000)
        wq.add("ns/ok")
        assert wq.get(timeout=0.0) == "ns/ok"
        wq.done("ns/ok")
        assert wq.get(timeout=0.0) is None

    def test_drop_front_guarded_against_valid_keys(self):
        """drop_front only pops a genuinely oversized front: a worker
        that lost the -2 race must not discard a valid key."""

        from tf_operator_tpu.native import NativeWorkQueue

        wq = NativeWorkQueue()
        wq.add("ns/valid")
        assert wq._lib.tpuop_wq_drop_front(wq._h, 4095) == 0
        assert wq.get(timeout=0.0) == "ns/valid"
