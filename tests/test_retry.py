"""backend/retry.py unit contract: backoff shape, retry-on rules,
Retry-After honoring, deadline budget, circuit breaker transitions,
and the status-returning (cmd/leader.py) result path.

All tests inject fake sleep/clock/rng so they are instant and
deterministic.
"""

import random

import pytest

from tf_operator_tpu.backend.base import NotFoundError
from tf_operator_tpu.backend.kube import ApiError, GoneError
from tf_operator_tpu.backend.retry import (
    CircuitBreaker,
    CircuitOpenError,
    RetryPolicy,
)
from tf_operator_tpu.utils.metrics import Metrics


class FakeClock:
    def __init__(self):
        self.now = 0.0
        self.sleeps = []

    def sleep(self, s):
        self.sleeps.append(s)
        self.now += s

    def __call__(self):
        return self.now


def make_policy(**kw):
    clock = FakeClock()
    kw.setdefault("rng", random.Random(42))
    policy = RetryPolicy(sleep=clock.sleep, clock=clock, **kw)
    return policy, clock


class Flaky:
    """Raises the scripted errors in order, then returns 'ok'."""

    def __init__(self, *errors):
        self.errors = list(errors)
        self.calls = 0

    def __call__(self):
        self.calls += 1
        if self.errors:
            raise self.errors.pop(0)
        return "ok"


class TestBackoffShape:
    def test_full_jitter_within_exponential_caps(self):
        policy, _ = make_policy(base_delay=0.1, max_delay=1.0)
        for attempt in range(8):
            cap = min(0.1 * 2**attempt, 1.0)
            for _ in range(20):
                d = policy.backoff(attempt)
                assert 0.0 <= d <= cap

    def test_seeded_rng_replays(self):
        p1, _ = make_policy(rng=random.Random(7))
        p2, _ = make_policy(rng=random.Random(7))
        assert [p1.backoff(i) for i in range(5)] == [
            p2.backoff(i) for i in range(5)
        ]


class TestRetryRules:
    def test_retries_5xx_and_429_then_succeeds(self):
        for status in (429, 500, 502, 503, 504):
            policy, _ = make_policy()
            fn = Flaky(ApiError(status, "boom"), ApiError(status, "boom"))
            assert policy.call(fn) == "ok"
            assert fn.calls == 3

    def test_semantic_statuses_never_retry(self):
        for err in (NotFoundError("x"), GoneError(410, "")):
            policy, clock = make_policy()
            fn = Flaky(err)
            with pytest.raises(type(err)):
                policy.call(fn)
            assert fn.calls == 1
            assert clock.sleeps == []

    def test_network_errors_retry(self):
        policy, _ = make_policy()
        fn = Flaky(ConnectionResetError(), ConnectionRefusedError())
        assert policy.call(fn) == "ok"
        assert fn.calls == 3

    def test_gives_up_after_max_attempts_with_original_error(self):
        policy, _ = make_policy(max_attempts=3)
        fn = Flaky(*[ApiError(503, "x")] * 10)
        with pytest.raises(ApiError) as ei:
            policy.call(fn)
        assert ei.value.status == 503  # the underlying error, unwrapped
        assert fn.calls == 3

    def test_metrics_counters_and_last_error_gauge(self):
        m = Metrics()
        policy, _ = make_policy()
        policy.call(Flaky(ApiError(503, "x")), client="c1", metrics=m)
        assert m.counter("api_client_retries_total", client="c1") == 1
        assert m.counter(
            "api_client_errors_total", client="c1", error="ApiError"
        ) == 1
        assert m.gauge("api_client_last_error_unixtime", client="c1") > 0
        with pytest.raises(ApiError):
            policy, _ = make_policy(max_attempts=2)
            policy.call(
                Flaky(*[ApiError(503, "x")] * 5), client="c1", metrics=m
            )
        assert m.counter("api_client_giveups_total", client="c1") == 1


class TestRetryAfterAndDeadline:
    def test_retry_after_floors_the_delay(self):
        policy, clock = make_policy(base_delay=0.001, max_delay=0.01)
        err = ApiError(429, "slow down")
        err.retry_after = 0.7
        policy.call(Flaky(err))
        assert clock.sleeps == [0.7]  # floored above the jittered value

    def test_retry_after_is_capped(self):
        policy, clock = make_policy(retry_after_cap=1.5)
        err = ApiError(503, "")
        err.retry_after = 3600.0  # hostile/buggy server
        policy.call(Flaky(err))
        assert clock.sleeps[0] <= 1.5

    def test_deadline_budget_stops_retrying(self):
        policy, clock = make_policy(
            max_attempts=100, base_delay=1.0, max_delay=1.0, deadline=2.5
        )
        fn = Flaky(*[ApiError(503, "x")] * 100)
        with pytest.raises(ApiError):
            policy.call(fn)
        assert clock.now <= 2.5
        assert fn.calls < 100


class TestResultPath:
    """cmd/leader.py's client returns (status, obj) instead of raising."""

    def test_retryable_status_result_retries_then_returns(self):
        policy, _ = make_policy()
        results = [(503, {}), (503, {}), (200, {"ok": True})]
        out = policy.call(
            lambda: results.pop(0),
            retryable_result=lambda res: res[0] in (429, 500, 502, 503, 504),
        )
        assert out == (200, {"ok": True})

    def test_budget_exhausted_returns_last_result_not_raise(self):
        policy, _ = make_policy(max_attempts=2)
        out = policy.call(
            lambda: (503, {}),
            retryable_result=lambda res: res[0] == 503,
        )
        assert out == (503, {})  # caller keeps its own status handling

    def test_float_verdict_floors_sleep_at_retry_after(self):
        """A status client can surface the server's Retry-After as the
        verdict; the next sleep is floored at it, like the exception
        path honoring ApiError.retry_after."""

        policy, clock = make_policy(base_delay=0.001, max_delay=0.01)
        results = [(429, {}, 0.8), (200, {}, None)]
        out = policy.call(
            lambda: results.pop(0),
            retryable_result=lambda res: (
                (res[2] or True) if res[0] == 429 else False
            ),
        )
        assert out == (200, {}, None)
        assert clock.sleeps == [0.8]

    def test_retry_after_zero_verdict_still_retries(self):
        """Retry-After: 0 is legal HTTP ('retry immediately'); the
        falsy 0.0 verdict must still mean retry, not success."""

        policy, clock = make_policy()
        results = [(429, {}, 0.0), (200, {}, None)]
        out = policy.call(
            lambda: results.pop(0),
            retryable_result=lambda res: (
                (res[2] if res[2] is not None else True)
                if res[0] == 429 else False
            ),
        )
        assert out == (200, {}, None)

    def test_semantic_status_returns_immediately(self):
        policy, clock = make_policy()
        calls = []
        out = policy.call(
            lambda: calls.append(1) or (409, {}),
            retryable_result=lambda res: res[0] in (429, 500),
        )
        assert out == (409, {})
        assert len(calls) == 1
        assert clock.sleeps == []


class TestCircuitBreaker:
    def test_opens_after_consecutive_failures_then_fails_fast_behind_probe(self):
        clock = FakeClock()
        br = CircuitBreaker(failure_threshold=3, probe_timeout=5.0, clock=clock)
        policy, pclock = make_policy(max_attempts=1)
        m = Metrics()
        for _ in range(3):
            with pytest.raises(ApiError):
                policy.call(
                    Flaky(*[ApiError(503, "x")] * 3), breaker=br, metrics=m
                )
        assert br.state == "open"  # tripped, probe slot free
        assert br.allow()  # this caller takes the probe slot...
        assert br.state == "half-open"  # trial in flight
        with pytest.raises(CircuitOpenError):
            # ...so a concurrent caller fails fast
            policy.call(lambda: "ok", breaker=br, metrics=m)
        assert m.counter("api_client_circuit_open_total", client="api") == 1

    def test_first_call_after_recovery_closes_with_zero_latency(self):
        """The apiserver-outage property: once the server is back, the
        very first call goes straight through and closes the circuit —
        no reset-window of refused service after recovery."""

        br = CircuitBreaker(failure_threshold=2)
        policy, _ = make_policy(max_attempts=1)
        for _ in range(2):
            with pytest.raises(ApiError):
                policy.call(Flaky(ApiError(503, "x")), breaker=br)
        assert br.state == "open"
        assert policy.call(lambda: "ok", breaker=br) == "ok"
        assert br.state == "closed"

    def test_probe_failure_keeps_circuit_open(self):
        br = CircuitBreaker(failure_threshold=2)
        br.record_failure()
        br.record_failure()
        assert br.allow()  # probe
        br.record_failure()
        assert br.state == "open"  # still tripped; next probe may try
        assert br.allow()

    def test_stuck_probe_slot_reclaimed_after_timeout(self):
        clock = FakeClock()
        br = CircuitBreaker(failure_threshold=1, probe_timeout=5.0, clock=clock)
        br.record_failure()
        assert br.allow()  # probe taken, never recorded (thread died)
        assert not br.allow()
        assert br.state == "half-open"  # stuck probe counts as in flight
        clock.now += 5.0
        assert br.allow()  # slot reclaimed

    def test_semantic_error_counts_as_server_alive(self):
        br = CircuitBreaker(failure_threshold=2)
        policy, _ = make_policy(max_attempts=1)
        for _ in range(5):
            with pytest.raises(NotFoundError):
                policy.call(Flaky(NotFoundError("x")), breaker=br)
        assert br.state == "closed"  # 404s are answers, not outages
