"""Golden tests for bootstrap env generation (SURVEY.md §7 step 4):
hand-written expected TF_CONFIG JSON / TPU env compared byte-for-byte —
the crown-jewel semantics."""

import json

from tests.testutil import new_job
from tf_operator_tpu.api.defaults import set_defaults
from tf_operator_tpu.api.types import ReplicaType
from tf_operator_tpu.bootstrap.cluster_spec import (
    coordinator_replica,
    gen_cluster_spec,
    gen_tf_config,
)
from tf_operator_tpu.bootstrap.tpu_env import gen_tpu_env, worker_env


def mkjob(**kw):
    return set_defaults(new_job(**kw))


class TestTFConfig:
    def test_golden_ps_worker_chief(self):
        job = mkjob(chief=1, ps=2, worker=2)
        got = json.loads(gen_tf_config(job, ReplicaType.WORKER, 1))
        expected = {
            "cluster": {
                "chief": ["job-chief-0.default.svc:2222"],
                "ps": ["job-ps-0.default.svc:2222", "job-ps-1.default.svc:2222"],
                "worker": [
                    "job-worker-0.default.svc:2222",
                    "job-worker-1.default.svc:2222",
                ],
            },
            "task": {"type": "worker", "index": 1},
            "environment": "cloud",
        }
        assert got == expected

    def test_golden_sparse_worker(self):
        job = mkjob(ps=1, worker=3)
        got = json.loads(gen_tf_config(job, ReplicaType.WORKER, 2, sparse=True))
        assert got["cluster"]["worker"] == ["job-worker-2.default.svc:2222"]
        assert got["cluster"]["ps"] == ["job-ps-0.default.svc:2222"]
        assert got["task"] == {"type": "worker", "index": 0}

    def test_deterministic_serialisation(self):
        job = mkjob(chief=1, worker=1)
        assert gen_tf_config(job, ReplicaType.WORKER, 0) == gen_tf_config(
            job, ReplicaType.WORKER, 0
        )

    def test_custom_port_respected(self):
        from tf_operator_tpu.api.types import DEFAULT_PORT_NAME

        job = new_job(worker=2)
        main = job.spec.replica_specs[ReplicaType.WORKER].template.containers[0]
        from tf_operator_tpu.api.types import Port

        main.ports.append(Port(name=DEFAULT_PORT_NAME, container_port=7777))
        set_defaults(job)
        spec = gen_cluster_spec(job)
        assert spec["worker"] == [
            "job-worker-0.default.svc:7777",
            "job-worker-1.default.svc:7777",
        ]


class TestCoordinatorSelection:
    def test_chief_wins(self):
        assert coordinator_replica(mkjob(chief=1, worker=4)) is ReplicaType.CHIEF

    def test_slice_beats_worker(self):
        job = mkjob(worker=2, tpu_slice=1)
        assert coordinator_replica(job) is ReplicaType.TPU_SLICE

    def test_worker_fallback(self):
        assert coordinator_replica(mkjob(worker=2)) is ReplicaType.WORKER


class TestTPUEnv:
    def test_golden_worker_only_job(self):
        job = mkjob(worker=2)
        env = gen_tpu_env(job, ReplicaType.WORKER, 1)
        assert env == {
            "TPUJOB_NAME": "job",
            "TPUJOB_COORDINATOR_ADDRESS": "job-worker-0.default.svc:8476",
            "TPUJOB_NUM_PROCESSES": "2",
            "TPUJOB_PROCESS_ID": "1",
            "TPUJOB_REPLICA_TYPE": "worker",
            "TPUJOB_REPLICA_INDEX": "1",
        }

    def test_process_ids_stable_and_coordinator_first(self):
        job = mkjob(chief=1, ps=1, worker=2)
        ids = {}
        for rtype, idx in [
            (ReplicaType.CHIEF, 0),
            (ReplicaType.PS, 0),
            (ReplicaType.WORKER, 0),
            (ReplicaType.WORKER, 1),
        ]:
            ids[(rtype, idx)] = int(gen_tpu_env(job, rtype, idx)["TPUJOB_PROCESS_ID"])
        assert ids[(ReplicaType.CHIEF, 0)] == 0
        assert len(set(ids.values())) == 4  # all distinct
        assert gen_tpu_env(job, ReplicaType.CHIEF, 0)["TPUJOB_NUM_PROCESSES"] == "4"

    def test_single_slice_has_no_megascale(self):
        job = mkjob(tpu_slice=1, tpu_topology="v5e-16")
        env = gen_tpu_env(job, ReplicaType.TPU_SLICE, 0)
        assert "MEGASCALE_NUM_SLICES" not in env
        assert env["TPU_WORKER_ID"] == "0"

    def test_multislice_golden(self):
        # v5e-4 = single host per slice: pod index == slice id
        job = mkjob(tpu_slice=2, tpu_topology="v5e-4")
        env = gen_tpu_env(job, ReplicaType.TPU_SLICE, 1)
        assert env["MEGASCALE_COORDINATOR_ADDRESS"] == "job-tpuslice-0.default.svc"
        assert env["MEGASCALE_NUM_SLICES"] == "2"
        assert env["MEGASCALE_SLICE_ID"] == "1"
        # intra-slice vars must describe only THIS slice's hosts — naming
        # other slices would contradict the MEGASCALE topology
        assert env["TPU_WORKER_ID"] == "0"
        assert env["TPU_WORKER_HOSTNAMES"] == "job-tpuslice-1.default.svc"

    def test_multihost_slice_expansion_golden(self):
        """The multi-host expansion contract (bootstrap/tpu_env.py):
        v5e-16 = 4 host VMs per slice → 4 pods per slice.  Pod s*4+h is
        host h of slice s; its worker id is h and its hostname list
        covers exactly its own slice's 4 pods."""

        job = mkjob(tpu_slice=2, tpu_topology="v5e-16")
        assert job.spec.pod_count(ReplicaType.TPU_SLICE) == 8
        # pod 5 = slice 1, host 1
        env = gen_tpu_env(job, ReplicaType.TPU_SLICE, 5)
        assert env["MEGASCALE_NUM_SLICES"] == "2"
        assert env["MEGASCALE_SLICE_ID"] == "1"
        assert env["TPU_WORKER_ID"] == "1"
        assert env["TPU_WORKER_HOSTNAMES"] == ",".join(
            f"job-tpuslice-{p}.default.svc" for p in (4, 5, 6, 7)
        )
        # every pod is its own JAX process: 8 distinct ids, 8 processes
        assert env["TPUJOB_NUM_PROCESSES"] == "8"
        ids = {
            int(gen_tpu_env(job, ReplicaType.TPU_SLICE, p)["TPUJOB_PROCESS_ID"])
            for p in range(8)
        }
        assert ids == set(range(8))
        # explicit override beats the topology-derived host count
        job2 = mkjob(tpu_slice=1, tpu_topology="v5e-16")
        job2.spec.replica_specs[ReplicaType.TPU_SLICE].hosts_per_replica = 2
        assert job2.spec.pod_count(ReplicaType.TPU_SLICE) == 2

    def test_worker_env_combines_both(self):
        job = mkjob(chief=1, worker=1)
        env = worker_env(job, ReplicaType.WORKER, 0)
        assert "TF_CONFIG" in env and "TPUJOB_PROCESS_ID" in env
        env2 = worker_env(job, ReplicaType.WORKER, 0, tf_config=False)
        assert "TF_CONFIG" not in env2

    def test_worker_env_ps_topology_injects_sparse(self):
        """PS jobs inject the sparse variant for workers: full chief/ps
        lists, own-entry-only worker list as index 0 (the TF
        sparse-cluster convention); chief and PS keep the full view."""

        import json

        job = mkjob(chief=1, ps=2, worker=3)
        cfg = json.loads(worker_env(job, ReplicaType.WORKER, 2)["TF_CONFIG"])
        assert len(cfg["cluster"]["ps"]) == 2
        assert len(cfg["cluster"]["chief"]) == 1
        assert cfg["cluster"]["worker"] == ["job-worker-2.default.svc:2222"]
        assert cfg["task"] == {"type": "worker", "index": 0}
        ps_cfg = json.loads(worker_env(job, ReplicaType.PS, 1)["TF_CONFIG"])
        assert len(ps_cfg["cluster"]["worker"]) == 3
        assert ps_cfg["task"] == {"type": "ps", "index": 1}
        # no PS replicas → dense config, true index
        dense = json.loads(
            worker_env(mkjob(chief=1, worker=3), ReplicaType.WORKER, 2)["TF_CONFIG"]
        )
        assert len(dense["cluster"]["worker"]) == 3
        assert dense["task"] == {"type": "worker", "index": 2}
