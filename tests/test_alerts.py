"""SLO alert engine (ISSUE 6 tentpole): burn-rate math, threshold
kinds, the alert lifecycle state machine, the once-per-episode flight
dump, the controller health rollup into TPUJob.status, and the
/alerts + /slo read surfaces on the operator API."""

import json
import time
import urllib.request

import pytest

from tests.testutil import harness, new_job
from tf_operator_tpu.api.serde import job_from_dict, job_to_dict
from tf_operator_tpu.api.types import JobConditionType, PodPhase
from tf_operator_tpu.utils.alerts import (
    AlertEngine,
    BurnRateRule,
    ThresholdRule,
    default_rules,
    validate_rule,
)
from tf_operator_tpu.utils.flight import FlightRecorder
from tf_operator_tpu.utils.metrics import SLO_BUCKETS, Metrics

T0 = 1_700_000_000.0  # synthetic unix clock base


def burn_rule(**kw):
    kw.setdefault("name", "burn")
    kw.setdefault("family", "lat_seconds")
    kw.setdefault("objective_le", 0.05)
    kw.setdefault("objective_ratio", 0.9)
    kw.setdefault("windows", (2.0, 8.0))
    kw.setdefault("burn_threshold", 3.0)
    return BurnRateRule(**kw)


class TestRuleValidation:
    def test_default_rules_validate(self):
        for r in default_rules():
            validate_rule(r)

    @pytest.mark.parametrize(
        "bad",
        [
            dict(objective_ratio=1.0),
            dict(objective_ratio=0.0),
            dict(objective_le=float("inf")),
            dict(windows=(8.0, 2.0)),  # unordered
            dict(windows=(2.0, float("inf"))),
            dict(burn_threshold=0.0),
            dict(burn_threshold=float("nan")),
            dict(for_seconds=-1.0),
        ],
    )
    def test_bad_burn_rules_rejected(self, bad):
        with pytest.raises(ValueError):
            validate_rule(burn_rule(**bad))

    @pytest.mark.parametrize(
        "bad",
        [
            dict(kind="nope"),
            dict(threshold=float("nan")),
            dict(window=0.0),
            dict(metric=""),
        ],
    )
    def test_bad_threshold_rules_rejected(self, bad):
        kw = dict(name="t", metric="x_total")
        kw.update(bad)
        with pytest.raises(ValueError):
            validate_rule(ThresholdRule(**kw))

    def test_duplicate_rule_names_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            AlertEngine([burn_rule(), burn_rule()], metrics=Metrics())


class TestBurnRateLifecycle:
    def _engine(self, m, **rule_kw):
        return AlertEngine(
            [burn_rule(**rule_kw)], metrics=m, recorder=FlightRecorder()
        )

    def test_good_traffic_never_breaches(self):
        m = Metrics()
        eng = self._engine(m)
        for i in range(20):
            m.observe_histogram("lat_seconds", 0.01)
            eng.evaluate_once(T0 + i)
        (a,) = eng.alerts()
        assert a.state == "inactive" and a.episodes == 0

    def test_full_lifecycle_pending_firing_resolved_inactive(self):
        m = Metrics()
        eng = self._engine(m, for_seconds=2.0)
        eng.resolved_hold = 60.0
        # warm up: enough good history to cover both windows
        t = T0
        for i in range(10):
            m.observe_histogram("lat_seconds", 0.01)
            eng.evaluate_once(t + i)
        (a,) = eng.alerts()
        assert a.state == "inactive"
        # violate: every observation over the objective
        t = T0 + 10
        for _ in range(20):
            m.observe_histogram("lat_seconds", 1.0)
        eng.evaluate_once(t)
        assert a.state == "pending"  # breach seen, for_seconds dwell
        for _ in range(20):
            m.observe_histogram("lat_seconds", 1.0)
        eng.evaluate_once(t + 1)
        assert a.state == "pending"
        for _ in range(20):
            m.observe_histogram("lat_seconds", 1.0)
        eng.evaluate_once(t + 2.5)  # dwell elapsed
        assert a.state == "firing" and a.episodes == 1
        assert m.counter("alerts_fired_total", rule="burn") == 1.0
        assert m.gauge("alert_state", rule="burn") == 2.0
        # recover: good traffic until the bad samples age out of both
        # windows
        t = T0 + 13
        for i in range(12):
            for _ in range(100):
                m.observe_histogram("lat_seconds", 0.01)
            eng.evaluate_once(t + i)
        assert a.state == "resolved"
        assert m.counter("alerts_resolved_total", rule="burn") == 1.0
        # resolved decays to inactive after resolved_hold
        eng.evaluate_once(t + 12 + 61.0)
        assert a.state == "inactive"

    def test_no_traffic_is_not_a_breach(self):
        m = Metrics()
        eng = self._engine(m)
        for i in range(20):
            eng.evaluate_once(T0 + i)
        (a,) = eng.alerts()
        assert a.state == "inactive"

    def test_short_burst_does_not_fire_long_window(self):
        """Multi-window: a burst breaching only the short window (long
        window still dominated by good traffic) must not fire."""

        m = Metrics()
        eng = self._engine(m, windows=(1.0, 16.0))
        t = T0
        for i in range(16):
            for _ in range(100):
                m.observe_histogram("lat_seconds", 0.01)
            eng.evaluate_once(t + i)
        # a 1-evaluation burst of 20 bad vs 1500 good in the long window
        for _ in range(20):
            m.observe_histogram("lat_seconds", 1.0)
        eng.evaluate_once(t + 16)
        (a,) = eng.alerts()
        assert a.state == "inactive", a.value

    def test_cold_start_coverage_guard(self):
        """All-bad traffic from the first sample: no firing until at
        least half of the LONG window has observed history."""

        m = Metrics()
        eng = self._engine(m, windows=(2.0, 8.0), for_seconds=0.0)
        for t in (T0, T0 + 1.0):  # long window only 12% covered
            for _ in range(50):
                m.observe_histogram("lat_seconds", 1.0)
            eng.evaluate_once(t)
        (a,) = eng.alerts()
        assert a.state == "inactive"
        for _ in range(50):
            m.observe_histogram("lat_seconds", 1.0)
        eng.evaluate_once(T0 + 5.0)  # > half of 8s covered
        assert a.state in ("pending", "firing")

    def test_label_filter_scopes_the_family(self):
        m = Metrics()
        eng = AlertEngine(
            [burn_rule(labels={"route": "/generate"})],
            metrics=m, recorder=FlightRecorder(),
        )
        t = T0
        for i in range(10):
            # the violating traffic is on ANOTHER route
            m.observe_histogram("lat_seconds", 5.0, route="/other")
            m.observe_histogram("lat_seconds", 0.01, route="/generate")
            eng.evaluate_once(t + i)
        (a,) = eng.alerts()
        assert a.state == "inactive"


class TestThresholdRules:
    def test_counter_increase_fires_and_resolves(self):
        m = Metrics()
        eng = AlertEngine(
            [ThresholdRule("stalls", "watchdog_stall_total",
                           kind="counter_increase", threshold=0.0,
                           window=10.0)],
            metrics=m, recorder=FlightRecorder(),
        )
        eng.evaluate_once(T0)
        eng.evaluate_once(T0 + 1)
        (a,) = eng.alerts()
        assert a.state == "inactive"
        m.inc("watchdog_stall_total", heartbeat="train.x")
        eng.evaluate_once(T0 + 2)
        assert a.state == "firing" and a.value["increase"] == 1.0
        # the increase ages out of the window -> resolved
        eng.evaluate_once(T0 + 15)
        eng.evaluate_once(T0 + 16)
        assert a.state == "resolved"

    def test_gauge_level_rule(self):
        m = Metrics()
        eng = AlertEngine(
            [ThresholdRule("depth", "serve_admission_queue_depth",
                           kind="gauge", threshold=8.0)],
            metrics=m, recorder=FlightRecorder(),
        )
        m.set("serve_admission_queue_depth", 3.0, model="m")
        eng.evaluate_once(T0)
        (a,) = eng.alerts()
        assert a.state == "inactive"
        m.set("serve_admission_queue_depth", 20.0, model="m")
        eng.evaluate_once(T0 + 1)
        assert a.state == "firing" and a.value["level"] == 20.0
        m.set("serve_admission_queue_depth", 0.0, model="m")
        eng.evaluate_once(T0 + 2)
        assert a.state == "resolved"

    def test_gauge_age_rule_skips_unset_gauge(self):
        m = Metrics()
        eng = AlertEngine(
            [ThresholdRule("ckpt", "checkpoint_last_success_unix",
                           kind="gauge_age", threshold=60.0)],
            metrics=m, recorder=FlightRecorder(),
        )
        eng.evaluate_once(T0)  # gauge never set: not a breach
        (a,) = eng.alerts()
        assert a.state == "inactive"
        m.set("checkpoint_last_success_unix", T0 - 300.0)
        eng.evaluate_once(T0 + 1)
        assert a.state == "firing" and a.value["age"] > 60.0
        m.set("checkpoint_last_success_unix", T0 + 1)
        eng.evaluate_once(T0 + 2)
        assert a.state == "resolved"


class TestFiringSideEffects:
    def _firing_engine(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPUJOB_FLIGHT_DIR", str(tmp_path))
        m = Metrics()
        rec = FlightRecorder()
        rec.attach_metrics(m)
        eng = AlertEngine(
            [ThresholdRule("stalls", "watchdog_stall_total",
                           kind="counter_increase", threshold=0.0,
                           window=30.0)],
            metrics=m, recorder=rec,
        )
        eng.evaluate_once(T0)
        m.inc("watchdog_stall_total", heartbeat="x")
        eng.evaluate_once(T0 + 1)
        return m, eng

    def test_flight_recorder_dumped_once_per_episode(self, tmp_path, monkeypatch):
        m, eng = self._firing_engine(tmp_path, monkeypatch)
        (a,) = eng.alerts()
        assert a.state == "firing"
        assert len(eng.dumps) == 1
        # the dump names the alert and carries the firing log record
        records = [
            json.loads(line)
            for line in open(eng.dumps[0]).read().splitlines()
        ]
        assert records[0]["reason"] == "alert-stalls"
        logs = [r for r in records if r["type"] == "log"]
        assert any("alert stalls firing" in r["message"] for r in logs)
        # still firing on later sweeps: no second dump this episode
        m.inc("watchdog_stall_total", heartbeat="x")
        eng.evaluate_once(T0 + 2)
        assert a.state == "firing" and len(eng.dumps) == 1

    def test_quiet_rules_still_export_alert_state(self):
        """alert_state{rule=} series must exist after one sweep even
        when nothing ever breaches — scrape-side absent() checks need
        to tell 'engine evaluating, all quiet' from 'never started'."""

        m = Metrics()
        eng = AlertEngine(
            [ThresholdRule("stalls", "watchdog_stall_total",
                           kind="counter_increase", threshold=0.0,
                           window=30.0)],
            metrics=m, recorder=FlightRecorder(),
        )
        eng.evaluate_once(T0)
        assert m.gauge("alert_state", rule="stalls") == 0.0
        assert (("rule", "stalls"),) in m.gauge_series("alert_state")

    def test_pending_flap_back_to_inactive_clears_message(self):
        """pending -> inactive must drop the breach message: /alerts
        serving an inactive rule with an active-sounding message
        misleads pollers that read message rather than state."""

        m = Metrics()
        eng = AlertEngine(
            [ThresholdRule("stalls", "watchdog_stall_total",
                           kind="counter_increase", threshold=0.0,
                           window=5.0, for_seconds=10.0)],
            metrics=m, recorder=FlightRecorder(),
        )
        eng.evaluate_once(T0)
        m.inc("watchdog_stall_total")
        eng.evaluate_once(T0 + 1)
        (a,) = eng.alerts()
        assert a.state == "pending" and a.message
        eng.evaluate_once(T0 + 8)  # increase ages out before the dwell
        assert a.state == "inactive" and a.message == ""

    def test_flap_reentry_from_resolved_is_same_episode(
        self, tmp_path, monkeypatch
    ):
        """A breach returning while the alert sits in resolved_hold
        re-enters firing WITHOUT a new episode: no second recorder
        dump, no alerts_fired_total increment — a signal oscillating
        around its threshold must not dump the black box (and mint a
        Warning episode) every other evaluation tick."""

        m, eng = self._firing_engine(tmp_path, monkeypatch)
        (a,) = eng.alerts()
        assert a.state == "firing" and a.episodes == 1
        # increase ages out of the 30s window -> resolved
        eng.evaluate_once(T0 + 35)
        assert a.state == "resolved"
        # breach returns inside resolved_hold -> firing, SAME episode
        m.inc("watchdog_stall_total", heartbeat="x")
        eng.evaluate_once(T0 + 36)
        assert a.state == "firing"
        assert a.episodes == 1
        assert len(eng.dumps) == 1
        assert m.counter("alerts_fired_total", rule="stalls") == 1.0

    def test_subscriber_sees_every_transition(self, tmp_path, monkeypatch):
        seen = []
        m = Metrics()
        eng = AlertEngine(
            [ThresholdRule("stalls", "watchdog_stall_total",
                           kind="counter_increase", threshold=0.0,
                           window=5.0)],
            metrics=m, recorder=FlightRecorder(),
        )
        eng.subscribe(lambda a, old, new: seen.append((old, new)))
        eng.evaluate_once(T0)
        m.inc("watchdog_stall_total")
        eng.evaluate_once(T0 + 1)
        eng.evaluate_once(T0 + 10)
        # for_seconds=0 collapses inactive->pending->firing into one
        # sweep; subscribers see one callback per sweep with the final
        # state
        assert seen == [("inactive", "firing"), ("firing", "resolved")]

    def test_unsubscribe_detaches_callback(self):
        """Consumers sharing a long-lived engine (the process-global
        default) must be able to detach on shutdown — subscribe with
        no removal would pin them alive forever."""

        seen = []
        m = Metrics()
        eng = AlertEngine(
            [ThresholdRule("stalls", "watchdog_stall_total",
                           kind="counter_increase", threshold=0.0,
                           window=5.0)],
            metrics=m, recorder=FlightRecorder(),
        )
        cb = lambda a, old, new: seen.append((old, new))  # noqa: E731
        eng.subscribe(cb)
        eng.unsubscribe(cb)
        eng.unsubscribe(cb)  # idempotent on an absent callback
        eng.evaluate_once(T0)
        m.inc("watchdog_stall_total")
        eng.evaluate_once(T0 + 1)
        assert seen == []

    def test_evaluator_thread_starts_and_stops(self):
        eng = AlertEngine(
            [ThresholdRule("t", "x_total", kind="counter_increase",
                           window=5.0)],
            metrics=Metrics(), recorder=FlightRecorder(), interval=0.01,
        )
        eng.start()
        assert eng.running
        deadline = time.time() + 2.0
        while (
            eng.metrics.counter("alert_evaluations_total") < 2
            and time.time() < deadline
        ):
            time.sleep(0.01)
        eng.stop()
        assert not eng.running
        assert eng.metrics.counter("alert_evaluations_total") >= 2


class TestHealthRollup:
    def _running_job(self, alerts, m):
        from tf_operator_tpu.backend.fake import FakeCluster
        from tf_operator_tpu.backend.jobstore import JobStore
        from tf_operator_tpu.controller.controller import TPUJobController

        store = JobStore()
        backend = FakeCluster(delivery="sync")
        c = TPUJobController(store, backend, metrics=m, alerts=alerts)
        job = new_job(name="hj", worker=1)
        store.create(job)
        c.sync_until_quiet()
        backend.set_pod_phase("default", "hj-worker-0", PodPhase.RUNNING)
        c.sync_until_quiet()
        return store, backend, c

    def test_degraded_condition_and_health_block_roundtrip(self):
        m = Metrics()
        eng = AlertEngine(
            [ThresholdRule("stalls", "watchdog_stall_total",
                           kind="counter_increase", threshold=0.0,
                           window=60.0)],
            metrics=m, recorder=FlightRecorder(),
        )
        store, backend, c = self._running_job(eng, m)
        job = store.get("default", "hj")
        assert job.status.observed_health["firingAlerts"] == []
        assert not job.status.has_condition(JobConditionType.DEGRADED)

        t = time.time()
        eng.evaluate_once(t)
        m.inc("watchdog_stall_total", heartbeat="train.x")
        eng.evaluate_once(t + 1)
        c.sync_until_quiet()
        job = store.get("default", "hj")
        assert job.status.has_condition(JobConditionType.DEGRADED)
        deg = job.status.condition(JobConditionType.DEGRADED)
        assert deg.reason == "HealthDegraded" and "stalls" in deg.message
        health = job.status.observed_health
        assert health["firingAlerts"] == ["stalls"]
        assert health["stallCount"] == 1
        # still Running: Degraded is health, not phase
        assert job.status.has_condition(JobConditionType.RUNNING)
        events = [
            (e.type, e.reason) for e in c.recorder.for_object("default/hj")
        ]
        assert ("Warning", "HealthDegraded") in events
        # one Warning per episode, not per sync
        c.reconciler.config.health_refresh_seconds = 0.0
        c.sync_until_quiet()
        events = [
            (e.type, e.reason) for e in c.recorder.for_object("default/hj")
        ]
        assert events.count(("Warning", "HealthDegraded")) == 1

        # the wire shape round-trips (kube-backed stores serialize it)
        j2 = job_from_dict(job_to_dict(job))
        assert j2.status.observed_health == job.status.observed_health
        assert j2.status.has_condition(JobConditionType.DEGRADED)

        # resolve: condition clears + Normal event
        eng.evaluate_once(t + 70)
        eng.evaluate_once(t + 71)
        c.sync_until_quiet()
        job = store.get("default", "hj")
        assert not job.status.has_condition(JobConditionType.DEGRADED)
        events = [
            (e.type, e.reason) for e in c.recorder.for_object("default/hj")
        ]
        assert ("Normal", "SLORecovered") in events

    def test_slo_violation_reason_for_burn_rules(self):
        m = Metrics()
        eng = AlertEngine(
            [burn_rule(windows=(1.0, 4.0))],
            metrics=m, recorder=FlightRecorder(),
        )
        store, backend, c = self._running_job(eng, m)
        t = time.time()
        for i in range(6):
            for _ in range(30):
                m.observe_histogram("lat_seconds", 1.0)
            eng.evaluate_once(t + i)
        assert [a.rule.name for a in eng.firing()] == ["burn"]
        c.sync_until_quiet()
        job = store.get("default", "hj")
        deg = job.status.condition(JobConditionType.DEGRADED)
        assert deg is not None and deg.status
        assert deg.reason == "SLOViolation"

    def test_rollup_throttle_prevents_status_churn(self):
        m = Metrics()
        eng = AlertEngine([], metrics=m, recorder=FlightRecorder())
        store, backend, c = self._running_job(eng, m)
        job = store.get("default", "hj")
        stamp = job.status.observed_health["updatedAt"]
        # immediate re-syncs inside the refresh window must not touch
        # the block (each touch would be a status write + watch event)
        c.sync_until_quiet()
        c.sync_until_quiet()
        job = store.get("default", "hj")
        assert job.status.observed_health["updatedAt"] == stamp

    def test_stale_summary_series_reports_no_throughput(self, tmp_path):
        """throughputStepsPerSec is LIVE health: a trainer that hung
        hours ago still has a healthy-looking last-20 summary window,
        and the rollup must not report that historical rate under a
        fresh updatedAt."""

        import json as _json

        from tf_operator_tpu.utils.summaries import ANNOTATION_SUMMARY_DIR

        m = Metrics()
        eng = AlertEngine([], metrics=m, recorder=FlightRecorder())
        store, backend, c = self._running_job(eng, m)
        job = store.get("default", "hj")
        job.metadata.annotations[ANNOTATION_SUMMARY_DIR] = str(tmp_path)

        def write_series(t_last):
            with open(tmp_path / "metrics-0.jsonl", "w") as f:
                for i in range(5):
                    f.write(_json.dumps(
                        {"step": i * 10, "time": t_last - (4 - i) * 2.0}
                    ) + "\n")

        # wedged: newest record far beyond the staleness bound
        write_series(time.time() - 7200)
        assert c.reconciler._recent_throughput(job) is None
        # live: same shape, recent tail -> 10 steps / 2s
        write_series(time.time())
        assert c.reconciler._recent_throughput(job) == 5.0

    def test_failed_job_does_not_retain_degraded(self):
        """A job that fails WHILE alerts are firing must end Failed
        with Degraded cleared — the same-sync rollup must not re-mark
        a terminal job (it would stay Degraded forever)."""

        from tf_operator_tpu.api.types import RestartPolicy
        from tf_operator_tpu.backend.fake import FakeCluster
        from tf_operator_tpu.backend.jobstore import JobStore
        from tf_operator_tpu.controller.controller import TPUJobController

        m = Metrics()
        eng = AlertEngine(
            [ThresholdRule("stalls", "watchdog_stall_total",
                           kind="counter_increase", threshold=0.0,
                           window=600.0)],
            metrics=m, recorder=FlightRecorder(),
        )
        store = JobStore()
        backend = FakeCluster(delivery="sync")
        c = TPUJobController(store, backend, metrics=m, alerts=eng)
        job = new_job(name="fj", worker=1,
                      restart_policy=RestartPolicy.NEVER)
        store.create(job)
        c.sync_until_quiet()
        backend.set_pod_phase("default", "fj-worker-0", PodPhase.RUNNING)
        c.sync_until_quiet()
        t = time.time()
        eng.evaluate_once(t)
        m.inc("watchdog_stall_total")
        eng.evaluate_once(t + 1)
        c.sync_until_quiet()
        assert store.get("default", "fj").status.has_condition(
            JobConditionType.DEGRADED
        )
        # fail while the alert is STILL firing
        backend.set_pod_phase(
            "default", "fj-worker-0", PodPhase.FAILED, exit_code=1
        )
        c.sync_until_quiet()
        job = store.get("default", "fj")
        assert job.status.has_condition(JobConditionType.FAILED)
        assert not job.status.has_condition(JobConditionType.DEGRADED)
        # the observedHealth block is LIVE health and goes with it — a
        # terminal job must not keep reporting its last firing alerts
        # (describe would print them as current forever)
        assert job.status.observed_health == {}

    def test_degraded_message_tracks_growing_firing_set(self):
        """A second rule joining the episode (same reason) must update
        the condition MESSAGE without a second Warning event."""

        m = Metrics()
        eng = AlertEngine(
            [
                ThresholdRule("stalls", "watchdog_stall_total",
                              kind="counter_increase", threshold=0.0,
                              window=600.0),
                ThresholdRule("circuit", "api_client_circuit_open_total",
                              kind="counter_increase", threshold=0.0,
                              window=600.0),
            ],
            metrics=m, recorder=FlightRecorder(),
        )
        store, backend, c = self._running_job(eng, m)
        t = time.time()
        eng.evaluate_once(t)
        m.inc("watchdog_stall_total")
        eng.evaluate_once(t + 1)
        c.sync_until_quiet()
        deg = store.get("default", "hj").status.condition(
            JobConditionType.DEGRADED
        )
        assert "stalls" in deg.message and "circuit" not in deg.message
        transition_stamp = deg.last_transition_time
        m.inc("api_client_circuit_open_total", client="x")
        eng.evaluate_once(t + 2)
        c.sync_until_quiet()
        deg = store.get("default", "hj").status.condition(
            JobConditionType.DEGRADED
        )
        assert deg.status and "circuit" in deg.message
        # k8s convention: lastTransitionTime moves on status/reason
        # flips only — "degraded since X" must survive a rule joining
        # the same episode (message-only update)
        assert deg.last_transition_time == transition_stamp
        assert deg.last_update_time >= transition_stamp
        events = [
            (e.type, e.reason) for e in c.recorder.for_object("default/hj")
        ]
        assert events.count(("Warning", "HealthDegraded")) == 1

    def test_invalid_spec_clears_degraded(self):
        """The InvalidSpec terminal path must clear Degraded like the
        other terminal paths — an invalid job never syncs again, so a
        live-health condition left True would be pinned forever."""

        m = Metrics()
        eng = AlertEngine(
            [ThresholdRule("stalls", "watchdog_stall_total",
                           kind="counter_increase", threshold=0.0,
                           window=600.0)],
            metrics=m, recorder=FlightRecorder(),
        )
        store, backend, c = self._running_job(eng, m)
        t = time.time()
        eng.evaluate_once(t)
        m.inc("watchdog_stall_total")
        eng.evaluate_once(t + 1)
        c.sync_until_quiet()
        assert store.get("default", "hj").status.has_condition(
            JobConditionType.DEGRADED
        )
        # an out-of-band write corrupts the spec: the informer ingests
        # an invalid skeleton that PRESERVES the old status (and with
        # it the Degraded condition)
        with c.cache._lock:
            c.cache.jobs["default/hj"].invalid_reason = "corrupted spec"
        c._enqueue("default/hj")
        c.sync_until_quiet()
        job = store.get("default", "hj")
        failed = job.status.condition(JobConditionType.FAILED)
        assert failed is not None and failed.reason == "InvalidSpec"
        assert not job.status.has_condition(JobConditionType.DEGRADED)

    def test_alert_transition_reenqueue_scoped_to_firing(self):
        """Only transitions entering/leaving ``firing`` can change the
        rollup (it reads firing()); pending flaps and resolved decay
        must not trigger full-cache sweeps.  stop() detaches the
        controller's subscriber from the (shared) engine."""

        m = Metrics()
        eng = AlertEngine([], metrics=m, recorder=FlightRecorder())
        store, backend, c = self._running_job(eng, m)
        alert = type("A", (), {})()  # the handler ignores the alert arg
        for old, new in (
            ("inactive", "pending"), ("pending", "inactive"),
            ("resolved", "inactive"),
        ):
            c._on_alert_transition(alert, old, new)
        assert len(c.queue) == 0
        c._on_alert_transition(alert, "pending", "firing")
        assert len(c.queue) == 1
        c.stop()
        assert c._on_alert_transition not in eng._callbacks

    def test_terminal_job_clears_degraded(self):
        m = Metrics()
        eng = AlertEngine(
            [ThresholdRule("stalls", "watchdog_stall_total",
                           kind="counter_increase", threshold=0.0,
                           window=600.0)],
            metrics=m, recorder=FlightRecorder(),
        )
        store, backend, c = self._running_job(eng, m)
        t = time.time()
        eng.evaluate_once(t)
        m.inc("watchdog_stall_total")
        eng.evaluate_once(t + 1)
        c.sync_until_quiet()
        assert store.get("default", "hj").status.has_condition(
            JobConditionType.DEGRADED
        )
        backend.set_pod_phase(
            "default", "hj-worker-0", PodPhase.SUCCEEDED, exit_code=0
        )
        c.sync_until_quiet()
        job = store.get("default", "hj")
        assert job.status.has_condition(JobConditionType.SUCCEEDED)
        assert not job.status.has_condition(JobConditionType.DEGRADED)


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        body = r.read().decode()
    try:
        return json.loads(body)
    except ValueError:
        return body


class TestApiSurfaces:
    @pytest.fixture
    def api(self):
        from tf_operator_tpu.server.api import ApiServer

        store, backend, controller = harness()
        engine = AlertEngine(
            default_rules(), metrics=controller.metrics,
            recorder=FlightRecorder(),
        )
        server = ApiServer(
            store, backend, controller.metrics, controller.recorder,
            alerts=engine,
        )
        server.start()
        yield controller, engine, f"http://127.0.0.1:{server.port}"
        server.stop()

    def test_alerts_endpoint_serves_engine_state(self, api):
        controller, engine, base = api
        snap = _get(f"{base}/alerts")
        assert snap["firing"] == []
        names = {a["name"] for a in snap["alerts"]}
        assert "watchdog-stall" in names
        for a in snap["alerts"]:
            assert a["state"] == "inactive"
        # fire one and re-read: firing sorts first
        t = time.time()
        engine.evaluate_once(t)
        controller.metrics.inc("watchdog_stall_total", heartbeat="x")
        engine.evaluate_once(t + 1)
        snap = _get(f"{base}/alerts")
        assert snap["firing"] == ["watchdog-stall"]
        assert snap["alerts"][0]["name"] == "watchdog-stall"
        assert snap["alerts"][0]["state"] == "firing"

    def test_slo_endpoint_matches_serving_contract(self, api):
        controller, engine, base = api
        _get(f"{base}/healthz")  # generates an api_request_seconds sample
        slo = _get(f"{base}/slo")
        assert set(slo["histograms"]) == {
            "api_request_seconds",
            "tpujob_sync_duration_seconds",
            "workqueue_queue_latency_seconds",
        }
        rows = slo["histograms"]["api_request_seconds"]
        assert rows, "healthz request not observed"
        row = next(r for r in rows if r.get("route") == "healthz")
        assert row["method"] == "GET" and row["count"] >= 1
        assert "p99_le" in row and "p50_le" in row
        assert "workqueue_depth" in slo["gauges"]

    def test_kubesim_serves_alerts_route(self):
        from tf_operator_tpu.backend.kubesim import MiniApiServer

        sim = MiniApiServer().start()
        try:
            snap = _get(f"{sim.url}/alerts")
            assert "alerts" in snap and "firing" in snap
        finally:
            sim.stop()
