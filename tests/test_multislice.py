"""Multi-slice training (ISSUE 14): slice-aware mesh, hierarchical DCN
gradient all-reduce, and slice-loss elastic re-shard.

Fast tier: mesh axis→fabric mapping, DCN refusal, the 1-slice
degenerate, grad-sync plan routing/byte accounting, and the static gate
pinning that `parallel/trainer.py` routes multi-slice grad sync through
`parallel/collectives.py` (no raw cross-slice psum reintroduced).

Slow tier (compiles): hierarchical-vs-flat psum numerics property test
inside shard_map, trainer A/B allclose on the 2-slice simulated mesh
(fsdp auto-rule AND logical shardings, per-step and fused-scan paths),
and the slice-loss chaos leg — a kubesim-semantics capacity shrink
kills a whole slice's gang, the stock slice policy sheds to the
survivor topology (checkpoint-gated), the trainer restores the 2-slice
checkpoint onto the 1-slice survivor mesh and trains on, and the job
ends Succeeded after capacity returns.
"""

import ast
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tf_operator_tpu.parallel import collectives
from tf_operator_tpu.parallel.mesh import (
    AXIS_DP,
    FABRIC_DCN,
    FABRIC_ICI,
    make_mesh,
    mesh_axis_links,
    slice_count,
)

# ---------------------------------------------------------------- fast tier


class TestSliceAwareMesh:
    def test_axis_fabric_mapping_two_slices(self):
        mesh = make_mesh({"dp": 2, "fsdp": 4}, slices=2)
        links = mesh_axis_links(mesh)
        assert links["dp"] == FABRIC_DCN
        for ax in ("pp", "fsdp", "ep", "sp", "tp"):
            assert links[ax] == FABRIC_ICI, (ax, links)
        assert slice_count(mesh) == 2

    def test_dp_coordinate_selects_the_slice(self):
        """The layout contract itself: dp coordinate j lives on slice
        j // (dp/S) — contiguous device groups on sim worlds (the
        operator's pod numbering: pod index = slice*H + host)."""

        mesh = make_mesh({"dp": 4, "fsdp": 2}, slices=2)
        ids = np.array([d.id for d in mesh.devices.flat]).reshape(4, 2)
        # slice 0 owns devices 0-3, slice 1 owns 4-7; fsdp neighbours
        # stay inside one slice
        assert set(ids[:2].ravel()) == {0, 1, 2, 3}
        assert set(ids[2:].ravel()) == {4, 5, 6, 7}

    def test_refuses_model_axis_across_dcn(self):
        with pytest.raises(ValueError, match="model axis"):
            make_mesh({"dp": 1, "fsdp": 8}, slices=2)
        with pytest.raises(ValueError, match="tp"):
            make_mesh({"dp": 2, "tp": 4}, slices=4)
        with pytest.raises(ValueError, match="slices"):
            make_mesh({"dp": 8}, slices=3)

    def test_one_slice_degenerate_is_todays_mesh(self):
        a = make_mesh({"dp": 2, "fsdp": 4}, slices=1)
        b = make_mesh({"dp": 2, "fsdp": 4})
        assert (a.devices == b.devices).all()
        assert slice_count(a) == 1
        assert set(mesh_axis_links(a).values()) == {FABRIC_ICI}

    def test_env_detection(self, monkeypatch):
        """MEGASCALE_NUM_SLICES (the operator-injected var,
        bootstrap/tpu_env.gen_tpu_env) drives the default slices."""

        from tf_operator_tpu.bootstrap.tpu_env import detected_slice_topology

        monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
        monkeypatch.setenv("MEGASCALE_SLICE_ID", "1")
        assert detected_slice_topology() == (2, 1)
        mesh = make_mesh({"dp": 2, "fsdp": 4})  # slices auto-detected
        assert slice_count(mesh) == 2
        assert mesh_axis_links(mesh)["dp"] == FABRIC_DCN
        monkeypatch.delenv("MEGASCALE_NUM_SLICES")
        monkeypatch.delenv("MEGASCALE_SLICE_ID")
        assert detected_slice_topology() == (1, None)


class TestGradSyncPlan:
    def _mesh(self):
        return make_mesh({"dp": 2, "fsdp": 4}, slices=2)

    def test_routing_and_byte_accounting(self):
        mesh = self._mesh()
        tree = {
            "big": jnp.zeros((256, 128)),          # replicated -> bucket
            "odd": jnp.zeros((7,)),                # padding case
            "sharded": jnp.zeros((64, 16)),        # fsdp-sharded -> direct
        }
        shardings = {
            "big": NamedSharding(mesh, P()),
            "odd": NamedSharding(mesh, P()),
            "sharded": NamedSharding(mesh, P("fsdp", None)),
        }
        plan = collectives.build_grad_sync_plan(tree, shardings, mesh)
        led = plan.ledger()
        assert led["intra_slice_size"] == 4
        # acceptance: cross-slice bytes <= (1/intra_slice_size + eps)
        # of the topology-blind full-width baseline
        assert plan.dcn_bytes_ratio <= 1 / 4 + 1e-3, led
        # the sharded leaf is its own fragment (no bucket), the two
        # replicated leaves fuse into one bucket -> 2 cross-slice
        # collectives, not 3
        assert led["buckets"] == 1
        assert led["dcn_collectives_per_step"] == 2
        # blind baseline counts every gradient byte at full width
        total = sum(v.size * 4 for v in tree.values())
        assert led["flat_dcn_bytes_per_step"] == total
        # same-mesh flat baseline: sharded leaves already move only
        # their fragment there (ZeRO does the work), replicated leaves
        # still cross at full width — so the hierarchy's win vs the
        # flat program comes from the bucketed leaves alone
        flat_mesh = (
            tree["big"].size * 4
            + tree["odd"].size * 4
            + tree["sharded"].size * 4 // 4
        )
        assert led["flat_mesh_dcn_bytes_per_step"] == flat_mesh
        assert plan.dcn_bytes_ratio_vs_flat_mesh <= 1.0 + 1e-6
        assert plan.dcn_bytes_ratio_vs_flat_mesh >= plan.dcn_bytes_ratio

    def test_bucket_capacity_splits(self):
        mesh = self._mesh()
        tree = {f"p{i}": jnp.zeros((1024,)) for i in range(8)}  # 4 KiB each
        plan = collectives.build_grad_sync_plan(
            tree, None, mesh, bucket_bytes=8192
        )
        assert len(plan.buckets) == 4  # two leaves per 8 KiB bucket
        assert plan.dcn_bytes_ratio == pytest.approx(0.25, abs=1e-6)

    def test_pure_dp_mesh_degenerates_to_flat_width(self):
        """No intra-slice axes -> no fragment to scatter: hierarchical
        == flat byte-wise (documented: the DCN win needs intra-slice
        width)."""

        mesh = make_mesh({"dp": 8}, slices=2)
        plan = collectives.build_grad_sync_plan(
            {"w": jnp.zeros((128,))}, None, mesh
        )
        assert plan.n_ici == 1
        assert plan.dcn_bytes_ratio == 1.0


PKG = pathlib.Path(__file__).resolve().parent.parent / "tf_operator_tpu"


class TestTrainerRoutesThroughCollectives:
    """Static gate (ISSUE 14 satellite): the trainer's multi-slice grad
    sync must go through parallel/collectives.py — a raw full-width
    cross-slice psum must not quietly come back."""

    def _tree(self):
        return ast.parse((PKG / "parallel" / "trainer.py").read_text())

    def test_trainer_builds_and_applies_the_plan(self):
        src = (PKG / "parallel" / "trainer.py").read_text()
        assert "build_grad_sync_plan" in src, (
            "trainer no longer builds a collectives.GradSyncPlan for "
            "multi-slice meshes"
        )
        tree = self._tree()
        hier = next(
            (
                n
                for n in ast.walk(tree)
                if isinstance(n, ast.FunctionDef)
                and n.name == "_step_body_hierarchical"
            ),
            None,
        )
        assert hier is not None, (
            "trainer lost its hierarchical step body — multi-slice "
            "grad sync would ride a flat psum again"
        )
        applies = [
            n
            for n in ast.walk(hier)
            if isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "apply"
            and isinstance(n.func.value, ast.Name)
            and n.func.value.id == "plan"
        ]
        assert applies, "hierarchical body does not call plan.apply(grads)"

    def test_no_raw_gradient_psum_in_trainer(self):
        """Gradient-width collectives (psum / psum_scatter) are
        collectives.py's business.  pmean stays allowed in trainer.py —
        it carries scalars and small BN statistics, not gradients."""

        banned = []
        for n in ast.walk(self._tree()):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in ("psum", "psum_scatter", "all_reduce")
            ):
                banned.append(f"line {n.lineno}: {n.func.attr}")
        assert not banned, (
            "raw cross-slice reduction in parallel/trainer.py (route it "
            "through parallel/collectives.py): " + ", ".join(banned)
        )

    def test_step_body_branches_on_the_plan(self):
        src = (PKG / "parallel" / "trainer.py").read_text()
        assert "self.grad_sync_plan is not None" in src


# ---------------------------------------------------------------- slow tier


@pytest.mark.slow
class TestHierarchicalPsumNumerics:
    def test_allclose_against_flat_psum_property(self):
        """Property test: random trees (odd sizes, mixed sharded/
        replicated leaves, several bucket capacities) reduced by
        psum_hierarchical match jax.lax.psum exactly on the 2-slice
        simulated mesh."""

        from tf_operator_tpu.utils.jax_compat import shard_map_partial_auto

        mesh = make_mesh({"dp": 2, "fsdp": 4}, slices=2)
        auto = frozenset(set(mesh.axis_names) - {AXIS_DP})
        rng = np.random.RandomState(0)
        for seed, bucket_bytes in ((0, 256), (1, 4096), (2, 1 << 20)):
            shapes = [(3,), (17,), (8, 8), (16, 5), (64,)][: 3 + seed]
            tree = {
                f"l{i}": jnp.asarray(rng.randn(*s), jnp.float32)
                for i, s in enumerate(shapes)
            }
            shardings = {
                k: NamedSharding(
                    mesh,
                    P("fsdp", None)
                    if v.ndim == 2 and v.shape[0] % 4 == 0
                    else P(),
                )
                for k, v in tree.items()
            }
            tree_s = jax.device_put(tree, shardings)

            def hier(t):
                return collectives.psum_hierarchical(
                    t, mesh, shardings=shardings, bucket_bytes=bucket_bytes
                )

            def flat(t):
                return jax.tree_util.tree_map(
                    lambda v: jax.lax.psum(v, AXIS_DP), t
                )

            h = jax.jit(
                shard_map_partial_auto(
                    hier, mesh=mesh, in_specs=P(), out_specs=P(), auto=auto
                )
            )(tree_s)
            f = jax.jit(
                shard_map_partial_auto(
                    flat, mesh=mesh, in_specs=P(), out_specs=P(), auto=auto
                )
            )(tree_s)
            for k in tree:
                np.testing.assert_allclose(
                    np.asarray(h[k]), np.asarray(f[k]), rtol=1e-6, atol=1e-6,
                    err_msg=f"leaf {k} bucket_bytes={bucket_bytes}",
                )

    def test_sync_probe_observes_fabric_labeled_seconds(self):
        from tf_operator_tpu.utils.metrics import Metrics

        mesh = make_mesh({"dp": 2, "fsdp": 4}, slices=2)
        m = Metrics()
        out = collectives.measure_sync_seconds(
            mesh, nbytes=1 << 14, metrics=m, repeats=1
        )
        assert out["dcn_fragment_s"] > 0 and out["ici_reshard_s"] > 0
        assert m.histogram("train_dcn_sync_seconds", fabric="dcn")["count"] == 1
        assert m.histogram("train_dcn_sync_seconds", fabric="ici")["count"] == 1


def _mnist_batch(n=16):
    r = np.random.RandomState(0)
    return {
        "image": jnp.asarray(r.rand(n, 28, 28, 1), jnp.float32),
        "label": jnp.asarray(r.randint(0, 10, size=(n,))),
    }


def _det_mnist_loss(params, state, batch, rng):
    import optax

    logits = state.apply_fn({"params": params}, batch["image"], train=False)
    loss = optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["label"]
    ).mean()
    acc = (logits.argmax(-1) == batch["label"]).mean()
    return loss, {"metrics": {"accuracy": acc}}


@pytest.mark.slow
class TestMultisliceTrainer:
    def test_hierarchical_matches_flat_mnist(self):
        """A/B at the trainer level: same mesh, same data, grad_sync
        hierarchical vs flat — losses and params track to float
        tolerance (deterministic loss; bf16 activations bound the
        schedule-order drift)."""

        from tf_operator_tpu.models import MnistCNN
        from tf_operator_tpu.parallel import Trainer, TrainerConfig
        from tf_operator_tpu.utils.metrics import Metrics, StepSyncLedger

        mesh = make_mesh({"dp": 2, "fsdp": 4}, slices=2)
        batch = _mnist_batch()
        metrics_reg = Metrics()

        def mk(gs, reg=None):
            return Trainer(
                MnistCNN(),
                TrainerConfig(optimizer="sgd", learning_rate=0.05),
                mesh,
                _det_mnist_loss,
                batch,
                grad_sync=gs,
                sync_ledger=StepSyncLedger(metrics=reg) if reg else None,
            )

        th = mk("auto", metrics_reg)
        tf_ = mk("flat")
        assert th.grad_sync == "hierarchical"  # auto picks it on 2 slices
        assert tf_.grad_sync_plan is None
        sb = th.shard_batch(batch)
        sf = tf_.shard_batch(batch)
        for i in range(5):
            mh, mf = th.train_step(sb), tf_.train_step(sf)
            np.testing.assert_allclose(
                float(mh["loss"]), float(mf["loss"]), rtol=2e-3, atol=2e-3
            )
        diffs = jax.tree_util.tree_map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            th.state.params,
            tf_.state.params,
        )
        assert max(jax.tree_util.tree_leaves(diffs)) < 5e-3
        # the byte ledger flowed to /metrics: dcn bytes at 1/4 of flat
        plan = th.grad_sync_plan
        assert plan.dcn_bytes_ratio <= 0.25 + 1e-3
        assert metrics_reg.counter(
            "train_dcn_bytes_total", fabric="dcn"
        ) == pytest.approx(plan.dcn_bytes * 5)
        assert metrics_reg.counter(
            "train_dcn_collectives_total", fabric="dcn"
        ) == pytest.approx(plan.dcn_collectives * 5)

    def test_hierarchical_fused_scan_and_logical_shardings(self):
        """The fused K-step lax.scan path compiles with the shard_map
        body, and logical-sharded transformers (gpt_tiny) ride the
        same hierarchical sync — fsdp-sharded grads go direct (already
        fragments), replicated ones bucket."""

        from tf_operator_tpu.models import gpt_tiny, lm_loss
        from tf_operator_tpu.parallel import Trainer, TrainerConfig

        mesh = make_mesh({"dp": 2, "fsdp": 4}, slices=2)
        r = np.random.RandomState(0)
        ids = jnp.asarray(r.randint(0, 64, size=(8, 16)), jnp.int32)
        batch = {"input_ids": ids}

        def mk(gs):
            return Trainer(
                gpt_tiny(vocab_size=64, max_len=16, dropout=0.0),
                TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
                mesh,
                lm_loss,
                batch,
                init_args=(ids,),
                shardings="logical",
                grad_sync=gs,
            )

        th, tf_ = mk("hierarchical"), mk("flat")
        plan = th.grad_sync_plan
        assert plan.dcn_bytes_ratio <= 0.25 + 1e-3
        # direct routes exist (fsdp-sharded kernels) AND a bucket
        # (replicated norm scales/biases)
        assert any(r_[0] == "direct" and r_[1] > 1 for r_ in plan.routes)
        assert len(plan.buckets) >= 1
        sb = th.shard_batch(batch)
        sf = tf_.shard_batch(batch)
        # fused window: one compiled scan, hierarchical sync inside
        mh = th.train_steps(sb, 3)
        for _ in range(3):
            mf = tf_.train_step(sf)
        np.testing.assert_allclose(
            float(np.asarray(mh["loss"])[-1]), float(mf["loss"]),
            rtol=5e-3, atol=5e-3,
        )

    def test_single_slice_auto_stays_flat(self):
        from tf_operator_tpu.models import MnistCNN
        from tf_operator_tpu.parallel import Trainer, TrainerConfig

        mesh = make_mesh({"dp": 2, "fsdp": 4}, slices=1)
        tr = Trainer(
            MnistCNN(),
            TrainerConfig(optimizer="sgd"),
            mesh,
            _det_mnist_loss,
            _mnist_batch(),
        )
        assert tr.grad_sync == "flat"
        assert tr.grad_sync_plan is None


@pytest.mark.slow
class TestSliceLossElastic:
    """The chaos leg (ISSUE 14 acceptance): capacity shrink kills the
    2-slice gang; the stock slice policy sheds to 1 slice gated on the
    async checkpoint; the survivor world restores that checkpoint on a
    1-slice mesh and trains on; capacity returns, the job grows back
    and ends Succeeded."""

    COOLDOWN = 0.05

    def test_capacity_shrink_resharded_to_survivor_slice(self, tmp_path):
        from tests.testutil import new_job
        from tf_operator_tpu.api.types import (
            AutoscalingSpec,
            JobConditionType,
            PodPhase,
            ReplicaType,
        )
        from tf_operator_tpu.backend.fake import FakeCluster
        from tf_operator_tpu.backend.jobstore import JobStore
        from tf_operator_tpu.controller.autoscaler import (
            Autoscaler,
            default_slice_training_policy,
        )
        from tf_operator_tpu.controller.controller import TPUJobController
        from tf_operator_tpu.models import gpt_tiny, lm_loss
        from tf_operator_tpu.parallel import Trainer, TrainerConfig
        from tf_operator_tpu.parallel.checkpoint import TrainerCheckpointer
        from tf_operator_tpu.utils.metrics import Metrics, StepSyncLedger
        from tf_operator_tpu.utils.summaries import (
            ANNOTATION_SUMMARY_DIR,
            SummaryWriter,
        )

        # ---- a REAL 2-slice trainer writes the checkpoint + summary
        # stamp the resize gate reads (hierarchical grad sync live)
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, 128, size=(8, 32)), jnp.int32
        )
        batch = {"input_ids": ids}
        metrics = Metrics()

        def trainer_on(mesh, **kw):
            return Trainer(
                gpt_tiny(vocab_size=128, max_len=32, mesh=mesh),
                TrainerConfig(learning_rate=1e-2, summary_every=1),
                mesh,
                lm_loss,
                batch,
                init_args=(ids,),
                shardings="logical",
                **kw,
            )

        sdir = str(tmp_path / "summaries")
        writer = SummaryWriter(sdir)
        mesh2 = make_mesh({"dp": 2, "fsdp": 4}, slices=2)
        tr = trainer_on(
            mesh2,
            summary_writer=writer,
            sync_ledger=StepSyncLedger(metrics=metrics),
        )
        assert tr.grad_sync == "hierarchical"
        for _ in range(2):
            tr.train_step(tr.shard_batch(batch))
        ckpt = TrainerCheckpointer(str(tmp_path / "ckpt"), metrics=metrics)
        saved_step = ckpt.save(tr, wait=True)
        loss_before = float(tr.eval_step(tr.shard_batch(batch))["loss"])
        tr.train_step(tr.shard_batch(batch))  # republishes the stamp
        writer.close()
        ckpt.close()

        # ---- control plane: 2-slice gang job under the stock policy
        store = JobStore()
        backend = FakeCluster(delivery="sync")
        autoscaler = Autoscaler(metrics=metrics, alerts=None)
        controller = TPUJobController(
            store, backend, metrics=metrics, autoscaler=autoscaler
        )
        try:
            from tf_operator_tpu.api.types import RestartPolicy

            # ExitCode policy: the capacity shrink kills gang pods with
            # exit 137 (SIGKILL = preemption) — retryable, so the job
            # survives the slice loss instead of going Failed
            job = new_job(
                name="msjob", tpu_slice=2, tpu_topology="v5e-4",
                restart_policy=RestartPolicy.EXIT_CODE,
            )
            job.spec.enable_gang_scheduling = True
            job.metadata.annotations[ANNOTATION_SUMMARY_DIR] = sdir
            pol = default_slice_training_policy(min_slices=1, max_slices=2)
            pol.cooldown_seconds = self.COOLDOWN
            # the anti-flap dwell must dominate the breach-detection
            # latency (~1 synthetic-second ticks here), or a shed would
            # regrow into the still-shrunken pool and oscillate — the
            # recovery leg jumps the clock past it instead
            pol.stabilization_seconds = 30.0
            pol.max_checkpoint_age_seconds = 3600.0
            job.spec.autoscaling = AutoscalingSpec(policies=[pol])
            store.create(job)

            def pump(now):
                autoscaler.evaluate_once(now)
                backend.run_all("default")
                controller.sync_until_quiet()

            def live_slice_pods():
                return [
                    p
                    for p in backend.list_pods(
                        "default", {"tpujob.dist/job-name": "msjob"}
                    )
                    if p.phase in (PodPhase.PENDING, PodPhase.RUNNING)
                ]

            import time as _time

            t0 = _time.time()
            pump(t0)
            assert len(live_slice_pods()) == 2  # v5e-4 = 1 host/slice

            # ---- slice loss: the pool shrinks to ONE slice's chips —
            # the /_capacity semantics: the 2-slice gang is revoked and
            # its pods are killed
            revoked = backend.set_total_chips(4)
            assert revoked == ["msjob"]
            pump(t0 + 1)
            # gang waits (2-slice topology no longer fits) -> gauge up
            assert metrics.gauge(
                "tpujob_gang_waiting_replicas", job="default/msjob"
            ) > 0
            # autoscaler sheds a slice, gated on the fresh checkpoint
            for k in range(2, 30):
                pump(t0 + k)
                blk = (
                    store.get("default", "msjob")
                    .status.observed_health.get("autoscaler", {})
                    .get("TPUSlice", {})
                )
                if blk.get("desiredReplicas") == 1 and len(live_slice_pods()) == 1:
                    break
            else:
                pytest.fail(
                    f"never resharded to 1 slice: {autoscaler.snapshot()}"
                )
            (down,) = [
                d
                for d in autoscaler.decisions()
                if d.direction == "down"
            ]
            assert down.replica_type is ReplicaType.TPU_SLICE
            assert down.reshard and "checkpoint" in down.reason
            events = [
                e.reason
                for e in controller.recorder.for_object("default/msjob")
            ]
            assert "Resharding" in events and "ScaledDown" in events
            # survivor world's bootstrap env: 1 slice -> no MEGASCALE
            # (the degenerate contract bootstrap/tpu_env.py pins)

            # ---- the REAL resume on the survivor topology: restore
            # the 2-slice checkpoint onto a 1-slice mesh and train on
            mesh1 = make_mesh({"fsdp": 8}, slices=1)
            tr1 = trainer_on(mesh1)
            assert tr1.grad_sync == "flat"  # survivor: no DCN anywhere
            ckpt1 = TrainerCheckpointer(str(tmp_path / "ckpt"))
            assert ckpt1.restore_latest(tr1) == saved_step
            loss_after = float(tr1.eval_step(tr1.shard_batch(batch))["loss"])
            np.testing.assert_allclose(loss_after, loss_before, rtol=2e-2)
            m = tr1.train_step(tr1.shard_batch(batch))
            assert np.isfinite(float(m["loss"]))
            ckpt1.close()

            # ---- capacity returns: quiet signals grow the job back to
            # its declared 2 slices, then everything succeeds
            backend.set_total_chips(8)
            t1 = _time.time() + 60  # past cooldown/stabilization
            for k in range(40):
                pump(t1 + k)
                if len(live_slice_pods()) == 2:
                    break
            else:
                pytest.fail(
                    f"never grew back to 2 slices: {autoscaler.snapshot()}"
                )
            for p in live_slice_pods():
                backend.succeed_pod("default", p.metadata.name)
            controller.sync_until_quiet()
            st = store.get("default", "msjob").status
            assert st.has_condition(JobConditionType.SUCCEEDED)
            # terminal path cleared the gang gauge (per-object hygiene)
            assert (
                metrics.gauge(
                    "tpujob_gang_waiting_replicas", job="default/msjob"
                )
                == 0.0
            )
        finally:
            controller.stop()
