"""Spec-layer tests: types, defaults, validation, serde round-trip.

Mirrors the reference's colocated API unit tests (SURVEY.md §4 tier 1).
"""

import pytest

try:
    from hypothesis import given, settings, strategies as st
except ImportError:  # boxes without hypothesis: property tests skip
    from tests.testutil import import_hypothesis_or_stubs

    given, settings, st = import_hypothesis_or_stubs()

from tf_operator_tpu.api.defaults import (
    DEFAULT_CLEAN_POD_POLICY,
    DEFAULT_RESTART_POLICY,
    set_defaults,
)
from tf_operator_tpu.api.serde import job_from_dict, job_to_dict
from tf_operator_tpu.api.types import (
    DEFAULT_COORDINATOR_PORT,
    DEFAULT_PORT,
    DEFAULT_PORT_NAME,
    CleanPodPolicy,
    Container,
    ObjectMeta,
    PodTemplateSpec,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    SuccessPolicy,
    TPUJob,
    TPUJobSpec,
    replica_name,
)
from tf_operator_tpu.api.validation import ValidationError, parse_tpu_topology, validate


def make_job(name="mnist", **replica_counts) -> TPUJob:
    """Builder mirroring the reference testutil's NewTFJob(worker, ps)."""

    specs = {}
    for tname, n in replica_counts.items():
        rtype = ReplicaType.from_str(tname)
        specs[rtype] = ReplicaSpec(
            replicas=n,
            template=PodTemplateSpec(
                containers=[Container(command=["python", "train.py"])]
            ),
        )
    return TPUJob(metadata=ObjectMeta(name=name, uid=f"uid-{name}"), spec=TPUJobSpec(replica_specs=specs))


class TestTypes:
    def test_replica_name_contract(self):
        assert replica_name("mnist", ReplicaType.WORKER, 2) == "mnist-worker-2"
        assert replica_name("j", ReplicaType.PS, 0) == "j-ps-0"
        assert replica_name("j", ReplicaType.TPU_SLICE, 1) == "j-tpuslice-1"

    def test_replica_type_from_str(self):
        assert ReplicaType.from_str("worker") is ReplicaType.WORKER
        assert ReplicaType.from_str("Chief") is ReplicaType.CHIEF
        assert ReplicaType.from_str("TPUSlice") is ReplicaType.TPU_SLICE
        with pytest.raises(ValueError):
            ReplicaType.from_str("gpu")

    def test_ordered_types_deterministic(self):
        job = make_job(worker=2, chief=1, ps=1)
        assert job.spec.ordered_types() == [
            ReplicaType.CHIEF,
            ReplicaType.PS,
            ReplicaType.WORKER,
        ]

    def test_total_replicas(self):
        assert make_job(worker=4, ps=2, chief=1).spec.total_replicas() == 7


class TestDefaults:
    def test_fills_replicas_restart_policy_port(self):
        job = make_job(worker=None)  # replicas unset
        set_defaults(job)
        rs = job.spec.replica_specs[ReplicaType.WORKER]
        assert rs.replicas == 1
        assert rs.restart_policy is DEFAULT_RESTART_POLICY
        port = rs.template.main_container().port_named(DEFAULT_PORT_NAME)
        assert port is not None and port.container_port == DEFAULT_PORT

    def test_existing_port_untouched(self):
        job = make_job(worker=1)
        main = job.spec.replica_specs[ReplicaType.WORKER].template.main_container()
        from tf_operator_tpu.api.types import Port

        main.ports.append(Port(name=DEFAULT_PORT_NAME, container_port=5000))
        set_defaults(job)
        assert main.port_named(DEFAULT_PORT_NAME).container_port == 5000
        assert len(main.ports) == 1

    def test_clean_pod_policy_default(self):
        job = set_defaults(make_job(worker=1))
        assert job.spec.run_policy.clean_pod_policy is DEFAULT_CLEAN_POD_POLICY

    def test_tpu_slice_forces_gang_and_coordinator_port(self):
        job = make_job(tpuslice=2)
        job.spec.replica_specs[ReplicaType.TPU_SLICE].tpu_topology = "v5e-16"
        set_defaults(job)
        assert job.spec.enable_gang_scheduling
        # min_member stays None (resolved to current totals at sync time)
        assert job.spec.run_policy.scheduling_policy.min_member is None
        port = (
            job.spec.replica_specs[ReplicaType.TPU_SLICE]
            .template.main_container()
            .port_named(DEFAULT_PORT_NAME)
        )
        assert port.container_port == DEFAULT_COORDINATOR_PORT

    def test_gang_scheduling_policy_created(self):
        job = make_job(worker=4, chief=1)
        job.spec.enable_gang_scheduling = True
        set_defaults(job)
        assert job.spec.run_policy.scheduling_policy is not None
        assert job.spec.run_policy.scheduling_policy.min_member is None


class TestValidation:
    def test_valid_job_passes(self):
        validate(set_defaults(make_job(worker=2, chief=1, ps=1)))

    def test_empty_replica_specs_rejected(self):
        with pytest.raises(ValidationError, match="at least one replica"):
            validate(TPUJob(metadata=ObjectMeta(name="x")))

    def test_missing_name_rejected(self):
        job = make_job(worker=1)
        job.metadata.name = ""
        with pytest.raises(ValidationError, match="metadata.name"):
            validate(job)

    def test_missing_main_container_rejected(self):
        job = make_job(worker=1)
        job.spec.replica_specs[ReplicaType.WORKER].template.containers[0].name = "other"
        with pytest.raises(ValidationError, match="container named"):
            validate(job)

    def test_two_chiefs_rejected(self):
        job = make_job(chief=2)
        with pytest.raises(ValidationError, match="chief/master"):
            validate(job)

    def test_chief_and_master_rejected(self):
        job = make_job(chief=1, master=1)
        with pytest.raises(ValidationError, match="both Chief and Master"):
            validate(job)

    def test_negative_replicas_rejected(self):
        job = make_job(worker=-1)
        with pytest.raises(ValidationError, match=">= 0"):
            validate(job)

    def test_tpu_slice_needs_topology(self):
        job = make_job(tpuslice=1)
        with pytest.raises(ValidationError, match="tpuTopology"):
            validate(job)

    def test_tpu_slice_plus_ps_rejected(self):
        job = make_job(tpuslice=1, ps=1)
        job.spec.replica_specs[ReplicaType.TPU_SLICE].tpu_topology = "v5e-16"
        with pytest.raises(ValidationError, match="PS"):
            validate(job)

    def test_all_problems_reported(self):
        job = make_job(chief=2, tpuslice=1)
        with pytest.raises(ValidationError) as ei:
            validate(job)
        assert len(ei.value.problems) == 2


class TestTopologyParse:
    @pytest.mark.parametrize(
        "s,n",
        [
            ("v5e-16", 16),
            # v4/v5p accelerator names count TensorCores, 2 per chip
            # (the public convention: v5p-8 is a 4-chip slice)
            ("v5p-8", 4),
            ("v4-32", 16),
            ("2x4", 8),
            ("4x4x4", 64),
            ("v5litepod-4", 4),
        ],
    )
    def test_ok(self, s, n):
        assert parse_tpu_topology(s) == n

    @pytest.mark.parametrize(
        "s", ["", "v5e", "axb", "16", "v4-7", "v4-0", "v5e-0", "0x4"]
    )
    def test_bad(self, s):
        with pytest.raises(ValueError):
            parse_tpu_topology(s)


class TestSerde:
    def test_round_trip(self):
        job = make_job(worker=2, chief=1)
        job.spec.success_policy = SuccessPolicy.ALL_WORKERS
        job.spec.run_policy.clean_pod_policy = CleanPodPolicy.ALL
        job.spec.run_policy.backoff_limit = 3
        set_defaults(job)
        d = job_to_dict(job)
        job2 = job_from_dict(d)
        assert job2.metadata.name == job.metadata.name
        assert set(job2.spec.replica_specs) == set(job.spec.replica_specs)
        assert job2.spec.success_policy is SuccessPolicy.ALL_WORKERS
        assert job2.spec.run_policy.backoff_limit == 3
        assert (
            job2.spec.replica_specs[ReplicaType.WORKER].restart_policy
            is job.spec.replica_specs[ReplicaType.WORKER].restart_policy
        )
        assert job_to_dict(job2) == d

    def test_manifest_shape(self):
        d = job_to_dict(set_defaults(make_job(worker=1)))
        assert d["apiVersion"] == "tpujob.dist/v1"
        assert d["kind"] == "TPUJob"
        assert "Worker" in d["spec"]["tpuReplicaSpecs"]

    def test_status_round_trip(self):
        from tf_operator_tpu.api.types import (
            JobCondition,
            JobConditionType,
            ReplicaStatus,
        )

        job = set_defaults(make_job(worker=2))
        job.metadata.annotations["scheduling.tpujob.dist/group-name"] = "g1"
        job.status.conditions.append(
            JobCondition(type=JobConditionType.RUNNING, status=True, reason="JobRunning")
        )
        job.status.replica_statuses[ReplicaType.WORKER] = ReplicaStatus(active=2)
        job.status.restart_count = 3
        job.status.start_time = 123.0
        job2 = job_from_dict(job_to_dict(job))
        assert job2.status.has_condition(JobConditionType.RUNNING)
        assert job2.status.replica_statuses[ReplicaType.WORKER].active == 2
        assert job2.status.restart_count == 3
        assert job2.status.start_time == 123.0
        assert job2.metadata.uid == job.metadata.uid
        assert job2.metadata.annotations == job.metadata.annotations

    def test_accepts_tf_replica_specs_key(self):
        """TFJob-manifest compatibility: tfReplicaSpecs is accepted."""

        d = {
            "metadata": {"name": "legacy"},
            "spec": {
                "tfReplicaSpecs": {
                    "Worker": {
                        "replicas": 2,
                        "restartPolicy": "OnFailure",
                        "template": {
                            "spec": {
                                "containers": [
                                    {"name": "tensorflow", "command": ["python", "x.py"]}
                                ]
                            }
                        },
                    }
                }
            },
        }
        job = job_from_dict(d)
        assert job.spec.replica_specs[ReplicaType.WORKER].replicas == 2
        assert (
            job.spec.replica_specs[ReplicaType.WORKER].restart_policy
            is RestartPolicy.ON_FAILURE
        )


class TestHostsPerReplicaValidation:
    def test_bad_hosts_per_replica_rejected(self):
        from tests.testutil import new_job
        from tf_operator_tpu.api.types import ReplicaType
        from tf_operator_tpu.api.validation import ValidationError, validate

        for bad in ("abc", 0, -1, 2.5, True):
            job = new_job(tpu_slice=1, tpu_topology="v5e-16")
            job.spec.replica_specs[ReplicaType.TPU_SLICE].hosts_per_replica = bad
            with pytest.raises(ValidationError, match="hostsPerReplica"):
                validate(job)

    def test_hosts_per_replica_wrong_type_rejected(self):
        from tests.testutil import new_job
        from tf_operator_tpu.api.types import ReplicaType
        from tf_operator_tpu.api.validation import ValidationError, validate

        job = new_job(worker=1)
        job.spec.replica_specs[ReplicaType.WORKER].hosts_per_replica = 2
        with pytest.raises(ValidationError, match="only valid for TPUSlice"):
            validate(job)

    def test_valid_hosts_per_replica_accepted(self):
        from tests.testutil import new_job
        from tf_operator_tpu.api.types import ReplicaType
        from tf_operator_tpu.api.validation import validate

        job = new_job(tpu_slice=1, tpu_topology="v5e-16")
        job.spec.replica_specs[ReplicaType.TPU_SLICE].hosts_per_replica = 2
        validate(job)


class TestSerdeRoundTripProperty:
    """Manifest serde must be lossless for every representable job:
    job -> dict -> job -> dict fixes to the same dict (the CRD
    round-trip contract the reference gets from codegen)."""

    @settings(max_examples=150, deadline=None)
    @given(data=st.data())
    def test_round_trip_fixpoint(self, data):
        from tests.testutil import new_job
        from tf_operator_tpu.api.serde import job_from_dict, job_to_dict
        from tf_operator_tpu.api.types import (
            CleanPodPolicy,
            RestartPolicy,
            SuccessPolicy,
        )

        name = data.draw(
            st.from_regex(r"[a-z]([a-z0-9-]{0,10}[a-z0-9])?", fullmatch=True),
            label="name",
        )
        counts = {
            "chief": data.draw(st.integers(0, 1), label="chief"),
            "ps": data.draw(st.integers(0, 3), label="ps"),
            "worker": data.draw(st.integers(0, 4), label="worker"),
            "tpu_slice": data.draw(st.integers(0, 2), label="slice"),
        }
        if not any(counts.values()):
            counts["worker"] = 1
        job = new_job(
            name,
            chief=counts["chief"],
            ps=counts["ps"],
            worker=counts["worker"],
            tpu_slice=counts["tpu_slice"],
            tpu_topology="v5e-8" if counts["tpu_slice"] else "",
        )
        job.spec.success_policy = data.draw(
            st.sampled_from(list(SuccessPolicy)), label="succ"
        )
        job.spec.run_policy.clean_pod_policy = data.draw(
            st.one_of(st.none(), st.sampled_from(list(CleanPodPolicy))), label="cpp"
        )
        job.spec.run_policy.backoff_limit = data.draw(
            st.one_of(st.none(), st.integers(0, 10)), label="backoff"
        )
        job.spec.run_policy.ttl_seconds_after_finished = data.draw(
            st.one_of(st.none(), st.integers(0, 3600)), label="ttl"
        )
        job.spec.enable_gang_scheduling = data.draw(st.booleans(), label="gang")
        for spec in job.spec.replica_specs.values():
            spec.restart_policy = data.draw(
                st.sampled_from(list(RestartPolicy)), label="rp"
            )
            c = spec.template.containers[0]
            c.env = data.draw(
                st.dictionaries(
                    st.from_regex(r"[A-Z][A-Z0-9_]{0,8}", fullmatch=True),
                    st.text(
                        alphabet=st.characters(
                            min_codepoint=32, max_codepoint=126
                        ),
                        max_size=12,
                    ),
                    max_size=3,
                ),
                label="env",
            )
        job.metadata.annotations = data.draw(
            st.dictionaries(
                st.from_regex(r"[a-z][a-z./-]{0,16}", fullmatch=True),
                st.text(max_size=10),
                max_size=2,
            ),
            label="ann",
        )

        d1 = job_to_dict(job)
        job2 = job_from_dict(d1)
        d2 = job_to_dict(job2)
        assert d1 == d2
