"""Chaos soak e2e (ISSUE 7 acceptance): bursty serving traffic + PR 1
fault injection against a serving job and a training job CONCURRENTLY,
with the autoscaler closing the loop end to end.

The scenario ("as many scenarios as you can imagine", ROADMAP item 3):

1. Both jobs run.  A REAL trainer (gpt_tiny on an fsdp-8 CPU mesh)
   writes the training job's summary series and saves an async
   checkpoint whose durability stamp flows registry → summary series →
   operator (the PR 6 scope-gap closure this PR's satellite ships).
2. Burst: the PR 1 injector adds latency to real kubesim HTTP requests
   that a miniature serving loop measures into the queue-wait SLO
   family; the admission-queue gauge spikes.  The burn-rate alert
   fires, the serving job goes Degraded, and the autoscaler scales
   serving 1 → 3 with cooldown respected.  Simultaneously the stall
   counter drives the training alert and the autoscaler sheds a
   training replica — gated on the (fresh) checkpoint — bouncing the
   replica set; the real trainer re-shards onto the 4-device survivor
   mesh by restoring that checkpoint and TRAINS ON.
3. Recovery: faults clear, stalls stop.  Alerts resolve, Degraded
   clears, serving shrinks back to 1 and training grows back to its
   declared size, each after the stabilization dwell.
4. Completion: every pod succeeds; both jobs end Succeeded with live
   health cleared.

Assertions pin the acceptance contract: zero decision flapping (each
job's decision sequence is exactly its planned phases, no
oscillation), cooldown respected between consecutive decisions, every
decision visible as a Normal event AND a GET /autoscaler entry AND an
observedHealth.autoscaler block that round-trips through serde, and
the clean-recovery end state.
"""

import json
import re
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from tests.testutil import new_job
from tf_operator_tpu.api.serde import job_from_dict, job_to_dict
from tf_operator_tpu.api.types import (
    AutoscalingPolicy,
    AutoscalingSpec,
    JobConditionType,
    PodPhase,
    ReplicaType,
    SignalBinding,
)
from tf_operator_tpu.backend.fake import FakeCluster
from tf_operator_tpu.backend.jobstore import JobStore
from tf_operator_tpu.backend.kubesim import MiniApiServer
from tf_operator_tpu.controller.autoscaler import Autoscaler
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.models import gpt_tiny, lm_loss
from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
from tf_operator_tpu.parallel.checkpoint import TrainerCheckpointer
from tf_operator_tpu.server.api import ApiServer
from tf_operator_tpu.utils.alerts import AlertEngine, BurnRateRule, ThresholdRule
from tf_operator_tpu.utils.flight import FlightRecorder
from tf_operator_tpu.utils.metrics import SLO_BUCKETS, Metrics, StepSyncLedger
from tf_operator_tpu.utils.summaries import ANNOTATION_SUMMARY_DIR, SummaryWriter

VOCAB = 128
FAULT_DELAY = 0.12
#: serving SLO under test: p90 of queue wait <= 50 ms (clean local
#: requests are ~2-5 ms, the injected fault adds 120 ms — margin both
#: ways on a loaded CI box)
OBJECTIVE_LE = 0.05
BURN_WINDOWS = (0.5, 1.5)
COOLDOWN = 0.5
STABILIZATION = 2.0


def _trainer(mesh, ids, **kw):
    return Trainer(
        gpt_tiny(vocab_size=VOCAB, max_len=ids.shape[1], mesh=mesh),
        TrainerConfig(learning_rate=1e-2, summary_every=1),
        mesh,
        lm_loss,
        {"input_ids": ids},
        init_args=(ids,),
        shardings="logical",
        **kw,
    )


class SoakRig:
    def __init__(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPUJOB_FLIGHT_DIR", str(tmp_path / "flight"))
        self.sim = MiniApiServer().start()
        self.metrics = Metrics()
        self.metrics.set_buckets("serve_queue_wait_seconds", SLO_BUCKETS)
        self.engine = AlertEngine(
            [
                BurnRateRule(
                    "serve-queue-wait-burn",
                    family="serve_queue_wait_seconds",
                    objective_le=OBJECTIVE_LE,
                    objective_ratio=0.9,
                    windows=BURN_WINDOWS,
                    burn_threshold=3.0,
                ),
                ThresholdRule(
                    "train-stall",
                    metric="watchdog_stall_total",
                    kind="counter_increase",
                    threshold=0.0,
                    window=3.0,
                ),
            ],
            metrics=self.metrics,
            recorder=FlightRecorder(),
        )
        self.autoscaler = Autoscaler(metrics=self.metrics, alerts=self.engine)
        self.store = JobStore()
        self.backend = FakeCluster(delivery="sync")
        self.controller = TPUJobController(
            self.store,
            self.backend,
            metrics=self.metrics,
            alerts=self.engine,
            autoscaler=self.autoscaler,
        )
        self.controller.reconciler.config.health_refresh_seconds = 0.0
        self.api = ApiServer(
            self.store,
            self.backend,
            self.metrics,
            self.controller.recorder,
            alerts=self.engine,
            autoscaler=self.autoscaler,
        )
        self.api.start()

    def http(self, route):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{self.api.port}{route}", timeout=10
        ) as r:
            return json.loads(r.read())

    def add_jobs(self, summary_dir):
        serving = new_job(name="serve", worker=1)
        serving.spec.autoscaling = AutoscalingSpec(policies=[
            AutoscalingPolicy(
                replica_type=ReplicaType.WORKER,
                mode="serving",
                min_replicas=1, max_replicas=3,
                cooldown_seconds=COOLDOWN,
                stabilization_seconds=STABILIZATION,
                signals=[
                    SignalBinding(kind="alert", name="serve-queue-wait-burn"),
                    SignalBinding(
                        kind="gauge", name="serve_admission_queue_depth",
                        threshold=64.0,
                    ),
                ],
            )
        ])
        training = new_job(name="train", worker=4)
        training.metadata.annotations[ANNOTATION_SUMMARY_DIR] = summary_dir
        training.spec.autoscaling = AutoscalingSpec(policies=[
            AutoscalingPolicy(
                replica_type=ReplicaType.WORKER,
                mode="training",
                min_replicas=2, max_replicas=4,
                cooldown_seconds=COOLDOWN,
                stabilization_seconds=STABILIZATION,
                max_checkpoint_age_seconds=600.0,
                signals=[SignalBinding(kind="alert", name="train-stall")],
            )
        ])
        for job in (serving, training):
            self.store.create(job)
        self.pump(0)
        assert self.running_pods("serve") == 1
        assert self.running_pods("train") == 4

    def running_pods(self, name):
        self.backend.run_all("default")
        self.controller.sync_until_quiet()
        return sum(
            1
            for p in self.backend.list_pods(
                "default", {"tpujob.dist/job-name": name}
            )
            if p.phase in (PodPhase.PENDING, PodPhase.RUNNING)
        )

    def pump(self, seconds, traffic=False, until=None):
        """The soak's heartbeat: (optionally) one real HTTP request per
        tick observed into the SLO family, alert + autoscaler
        evaluation, pod scheduling, controller drain."""

        url = f"{self.sim.url}/api/v1/namespaces/default/pods"
        deadline = time.time() + seconds
        while True:
            if traffic:
                t0 = time.perf_counter()
                with urllib.request.urlopen(url, timeout=10) as r:
                    r.read()
                self.metrics.observe_histogram(
                    "serve_queue_wait_seconds",
                    time.perf_counter() - t0,
                    model="soak",
                )
            self.engine.evaluate_once()
            self.autoscaler.evaluate_once()
            self.backend.run_all("default")
            self.controller.sync_until_quiet()
            if until is not None and until():
                return True
            if time.time() >= deadline:
                return until is None
            time.sleep(0.02)

    def desired(self, name):
        st = self.store.get("default", name).status
        blk = (st.observed_health.get("autoscaler") or {}).get("Worker", {})
        return blk.get("desiredReplicas")

    def events(self, name):
        return [
            (e.reason, e.message)
            for e in self.controller.recorder.for_object(f"default/{name}")
        ]

    def decisions(self, name):
        return [
            d for d in self.autoscaler.decisions()
            if d.job_key == f"default/{name}"
        ]

    def stop(self):
        self.api.stop()
        self.controller.stop()
        self.sim.stop()


@pytest.mark.slow
class TestChaosSoak:
    def test_burst_distress_recovery_completion(self, tmp_path, monkeypatch, capsys):
        rig = SoakRig(tmp_path, monkeypatch)
        try:
            self._run(rig, tmp_path, capsys)
        finally:
            rig.stop()

    def _run(self, rig, tmp_path, capsys):
        # ---- phase 0: a REAL trainer backs the training job: its
        # summary series carries the checkpoint durability stamp the
        # autoscaler's resize gate reads (registry → series → operator)
        sdir = str(tmp_path / "summaries")
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, VOCAB, size=(8, 32)), jnp.int32
        )
        mesh_a = make_mesh({"fsdp": 8})
        writer = SummaryWriter(sdir)
        tr = _trainer(
            mesh_a, ids,
            summary_writer=writer,
            sync_ledger=StepSyncLedger(metrics=rig.metrics),
        )
        batch = {"input_ids": ids}
        for _ in range(2):
            tr.train_step(tr.shard_batch(batch))
        ckpt = TrainerCheckpointer(
            str(tmp_path / "ckpt"), metrics=rig.metrics
        )
        saved_step = ckpt.save(tr, wait=True)
        assert saved_step == 2
        # the post-save step's summary write republishes the stamp
        loss_before = float(
            tr.eval_step(tr.shard_batch(batch))["loss"]
        )
        tr.train_step(tr.shard_batch(batch))
        writer.close()
        ckpt.close()
        rig.add_jobs(sdir)

        # ---- phase 1: burst + faults → serving scales up, Degraded.
        # Convergence = the WHOLE phase state: the gauge signal alone
        # scales 1→3 in under a second, so waiting on replicas only
        # would race the burn rule (it needs ~a long-window of traffic
        # history) and the Degraded rollup it drives.
        rig.sim.faults.add(
            path="/pods", methods=["GET"], mode="latency", delay=FAULT_DELAY
        )
        rig.metrics.set("serve_admission_queue_depth", 200.0)

        def burst_converged():
            if rig.desired("serve") != 3:
                return False
            a = rig.engine.alert("serve-queue-wait-burn")
            if a is None or a.state != "firing":
                return False
            deg = rig.store.get("default", "serve").status.condition(
                JobConditionType.DEGRADED
            )
            return deg is not None and deg.status

        assert rig.pump(20.0, traffic=True, until=burst_converged), (
            "serving burst never converged: "
            f"desired={rig.desired('serve')} "
            f"alert={rig.engine.alert('serve-queue-wait-burn').state}"
        )
        assert rig.sim.faults.total_injected() > 0
        assert rig.engine.alert("serve-queue-wait-burn").state == "firing"
        assert rig.running_pods("serve") == 3
        serve_job = rig.store.get("default", "serve")
        deg = serve_job.status.condition(JobConditionType.DEGRADED)
        assert deg is not None and deg.status and deg.reason == "SLOViolation"
        # Running coexists with Degraded — health, not phase
        assert serve_job.status.has_condition(JobConditionType.RUNNING)

        # ---- phase 2: concurrent training distress → gated shed +
        # re-shard bounce, while serving stays scaled up
        rig.metrics.inc("watchdog_stall_total", heartbeat="train.loop")
        assert rig.pump(
            15.0, traffic=True, until=lambda: rig.desired("train") == 3
        ), (
            "training never shed a replica under distress: "
            f"alert={rig.engine.alert('train-stall').state}"
            f":{rig.engine.alert('train-stall').value} "
            f"policies={rig.autoscaler.snapshot()['policies']} "
            f"health={rig.store.get('default', 'train').status.observed_health}"
        )
        assert rig.running_pods("train") == 3
        train_events = [r for r, _ in rig.events("train")]
        assert "ScaledDown" in train_events
        assert "Resharding" in train_events
        (down,) = rig.decisions("train")
        assert down.reshard and "checkpoint" in down.reason

        # the REAL re-shard + resume: restore the checkpoint onto the
        # 4-device survivor mesh and train on (tests/test_elastic.py's
        # contract, exercised here as the autoscaler's consequence)
        mesh_b = make_mesh(
            {"dp": 2, "fsdp": 2}, devices=jax.devices()[:4]
        )
        tr2 = _trainer(mesh_b, ids)
        ckpt2 = TrainerCheckpointer(str(tmp_path / "ckpt"))
        assert ckpt2.restore_latest(tr2) == saved_step
        loss_after = float(tr2.eval_step(tr2.shard_batch(batch))["loss"])
        np.testing.assert_allclose(loss_after, loss_before, rtol=2e-2)
        m = tr2.train_step(tr2.shard_batch(batch))
        assert np.isfinite(float(m["loss"]))
        ckpt2.close()

        # ---- acceptance surfaces mid-storm: every decision shows on
        # GET /autoscaler, the status block round-trips serde, the CLI
        # renders both planes
        snap = rig.http("/autoscaler")
        assert {(d["job"], d["direction"]) for d in snap["decisions"]} >= {
            ("default/serve", "up"), ("default/train", "down"),
        }
        job_d = job_to_dict(rig.store.get("default", "train"))
        assert job_from_dict(job_d).status.observed_health["autoscaler"] == (
            rig.store.get("default", "train").status.observed_health["autoscaler"]
        )
        from tf_operator_tpu.cmd import tpujob as tpujob_cli

        server = f"http://127.0.0.1:{rig.api.port}"
        assert tpujob_cli.main(["--server", server, "alerts"]) == 0
        assert tpujob_cli.main(["--server", server, "autoscaler"]) == 0
        cli_out = capsys.readouterr().out
        assert "serve-queue-wait-burn" in cli_out
        assert re.search(r"default/serve\s+Worker", cli_out)

        # ---- phase 3: recovery — faults clear, stalls stop; alerts
        # resolve, Degraded clears, both policies relax
        rig.sim.faults.clear()
        rig.metrics.set("serve_admission_queue_depth", 0.0)
        assert rig.pump(
            30.0, traffic=True,
            until=lambda: rig.desired("serve") == 1
            and rig.desired("train") == 4,
        ), (
            f"recovery incomplete: serve={rig.desired('serve')} "
            f"train={rig.desired('train')} "
            f"alerts={[ (a.rule.name, a.state) for a in rig.engine.alerts() ]}"
        )
        assert rig.running_pods("serve") == 1
        assert rig.running_pods("train") == 4
        rig.pump(0)
        assert not rig.store.get("default", "serve").status.has_condition(
            JobConditionType.DEGRADED
        )

        # ---- zero flapping: each job's decision sequence is exactly
        # its planned phases — monotone up then down (serving), down
        # then up (training) — and consecutive decisions respect the
        # cooldown floor
        serve_dirs = "".join(d.direction[0] for d in rig.decisions("serve"))
        train_dirs = "".join(d.direction[0] for d in rig.decisions("train"))
        assert re.fullmatch(r"u+d+", serve_dirs), serve_dirs
        assert re.fullmatch(r"d+u+", train_dirs), train_dirs
        for name in ("serve", "train"):
            ds = rig.decisions(name)
            for a, b in zip(ds, ds[1:]):
                assert b.time - a.time >= COOLDOWN * 0.99, (
                    f"{name}: decisions {a.to_dict()} -> {b.to_dict()} "
                    "violate the cooldown"
                )
            reasons = [r for r, _ in rig.events(name)]
            assert reasons.count("ScaledUp") + reasons.count(
                "ScaledDown"
            ) == len(ds), "every decision must be exactly one Normal event"

        # ---- phase 4: completion — all pods succeed, jobs end
        # Succeeded, live health (incl. the autoscaler block) cleared
        for name in ("serve", "train"):
            for p in rig.backend.list_pods(
                "default", {"tpujob.dist/job-name": name}
            ):
                if p.phase in (PodPhase.PENDING, PodPhase.RUNNING):
                    rig.backend.succeed_pod("default", p.metadata.name)
        rig.controller.sync_until_quiet()
        for name in ("serve", "train"):
            st = rig.store.get("default", name).status
            assert st.has_condition(JobConditionType.SUCCEEDED), name
            assert not st.has_condition(JobConditionType.DEGRADED), name
            assert st.observed_health == {}, name
