"""Ulysses (all-to-all) attention vs plain attention on the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.ops import dot_product_attention, ulysses_attention
from tf_operator_tpu.parallel import make_mesh


def _qkv(b=8, h=8, s=32, d=8, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, s, d).astype(np.float32), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ulysses_matches_plain(causal, sp):
    mesh = make_mesh({"sp": sp, "dp": -1})
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal)
    with mesh:
        out = jax.jit(
            lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=causal)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_gradients_match(causal):
    mesh = make_mesh({"sp": 4, "dp": -1})
    q, k, v = _qkv(s=16)

    def loss_ref(q, k, v):
        return (dot_product_attention(q, k, v, causal=causal) ** 2).sum()

    def loss_uly(q, k, v):
        with mesh:
            return (ulysses_attention(q, k, v, mesh, causal=causal) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_uly = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_uly, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


def test_ulysses_with_tp_mesh():
    """sp shards the heads *left over* after tp: h=8 over tp=2 → 4 local
    heads, split across sp=2."""

    mesh = make_mesh({"tp": 2, "sp": 2, "dp": -1})
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=True)
    with mesh:
        out = jax.jit(
            lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=True)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_bf16_close():
    mesh = make_mesh({"sp": 4, "dp": -1})
    q, k, v = _qkv(dtype=jnp.bfloat16)
    ref = dot_product_attention(q, k, v, causal=True)
    with mesh:
        out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2
    )


def test_ulysses_rejects_indivisible_heads():
    mesh = make_mesh({"sp": 8})
    q, k, v = _qkv(h=4)
    with pytest.raises(ValueError, match="heads-per-shard"):
        ulysses_attention(q, k, v, mesh)


def test_sp1_falls_back_to_plain():
    mesh = make_mesh({"dp": 8})
    q, k, v = _qkv()
    out = ulysses_attention(q, k, v, mesh, causal=True)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_gpt_ulysses_matches_no_sp():
    """Ulysses training (sp=4) must match plain attention (sp=1)
    numerically — same model, same data, same init (the ring twin of
    this test is tests/test_models.py::test_gpt_sp_matches_no_sp).

    Tolerance note (ISSUE 2 triage): this compares runs on DIFFERENT
    mesh shapes ({dp:8} vs {dp:2,sp:4}), and XLA re-fuses the whole
    model per sharding layout — a {dp:2,fsdp:4} control (identical
    math, no sequence parallelism at all) shows the same ~2e-3
    relative loss drift vs {dp:8} on CPU f32.  The op-level
    equivalence stays pinned at 2e-5 (tests above); 5e-3 here still
    catches wiring bugs (wrong mask/schedule shifts loss by O(0.1+))
    without failing on cross-mesh fusion noise."""

    from tf_operator_tpu.models import gpt_tiny, lm_loss
    from tf_operator_tpu.parallel import Trainer, TrainerConfig

    rng = np.random.RandomState(2)
    ids = rng.randint(0, 256, size=(8, 32)).astype(np.int32)
    batch = {"input_ids": ids}
    losses = {}
    for label, shape, impl in [
        ("nosp", {"dp": 8}, "ring"),
        ("ulysses", {"dp": 2, "sp": 4}, "ulysses"),
    ]:
        mesh = make_mesh(shape)
        model = gpt_tiny(
            vocab_size=256, max_len=32, mesh=mesh, dropout=0.0, sp_impl=impl
        )
        tr = Trainer(
            model,
            TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
            mesh,
            lm_loss,
            batch,
            init_args=(ids,),
            shardings="logical",
            seed=7,
        )
        losses[label] = [
            float(tr.train_step(tr.shard_batch(batch))["loss"]) for _ in range(3)
        ]
    np.testing.assert_allclose(losses["nosp"], losses["ulysses"], rtol=5e-3, atol=5e-3)


class TestUlyssesGQA:
    """GQA through the all-to-all: native-width K/V a2a when the kv
    head count splits the axis, pre-expand fallback otherwise."""

    def _qkv(self, B=4, H=8, HKV=4, S=32, D=8, seed=31):
        r = np.random.RandomState(seed)
        mk = lambda h: jnp.asarray(r.randn(B, h, S, D).astype(np.float32))
        return mk(H), mk(HKV), mk(HKV)

    @staticmethod
    def _ref(q, k, v, causal):
        g = q.shape[1] // k.shape[1]
        k, v = (jnp.repeat(a, g, axis=1) for a in (k, v))
        return dot_product_attention(q, k, v, causal=causal)

    @pytest.mark.parametrize("hkv,sp", [(4, 2), (2, 4)])
    def test_forward_matches_repeated_reference(self, hkv, sp):
        # (4,2): kv ride the a2a natively; (2,4): 2 % 4 != 0 -> fallback
        mesh = make_mesh({"sp": sp, "dp": -1})
        q, k, v = self._qkv(HKV=hkv)
        ref = self._ref(q, k, v, True)
        with mesh:
            out = jax.jit(
                lambda a, b, c: ulysses_attention(a, b, c, mesh, causal=True)
            )(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_gradients_match_repeated_reference(self):
        mesh = make_mesh({"sp": 2, "dp": -1})
        q, k, v = self._qkv()

        def loss_uly(a, b, c):
            with mesh:
                return (ulysses_attention(a, b, c, mesh, causal=True) ** 2).mean()

        def loss_ref(a, b, c):
            return (self._ref(a, b, c, True) ** 2).mean()

        g_uly = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), g_uly, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5, err_msg=name
            )


@pytest.mark.parametrize("w", [4, 12, 32])
def test_ulysses_window_matches_banded_reference(w):
    """window is free under ulysses: full sequence locally, banded mask
    applies unchanged."""

    mesh = make_mesh({"sp": 4, "dp": -1})
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=True, window=w)
    with mesh:
        out = jax.jit(
            lambda a, b, c: ulysses_attention(a, b, c, mesh, causal=True, window=w)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
