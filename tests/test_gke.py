"""GKE translation layer (backend/gke.py, VERDICT r3 next #6): the
TPUJob → real-Kubernetes compiler, golden-file tested for the five
BASELINE target configs plus the TPU-slice/gang/multi-slice paths no
shipped manifest exercises.

Regenerate goldens after an intentional output change:
    for f in tests/golden/gke/*.yaml; do
      python -m tf_operator_tpu.cmd.tpujob compile \
        -f examples/manifests/$(basename $f) -o $f; done
"""

import os

import pytest
import yaml

from tests.testutil import new_job
from tf_operator_tpu.api.types import ReplicaType, RestartPolicy
from tf_operator_tpu.backend.gke import (
    VOLCANO_GROUP_ANNOTATION,
    compile_job,
    compile_manifest,
    to_yaml,
)

HERE = os.path.dirname(os.path.abspath(__file__))
MANIFESTS = os.path.join(HERE, "..", "examples", "manifests")
GOLDEN = os.path.join(HERE, "golden", "gke")

BASELINE_CONFIGS = [
    "dist_mnist",
    "resnet_mwms",
    "bert_ps_analogue",
    "resnet_horovod_gang",
    "t5_multihost",
    # the untranslated PS topology (real PS replicas, sparse worker
    # cluster specs) — VERDICT r3 weak #8's first-class-topology row
    "dist_mnist_ps",
    # 3-D torus generations: v4/v5p accelerator names count TensorCores
    # and need 3-D gke-tpu-topology grids (VERDICT r4 weak #3)
    "resnet_v4_slice",
    "llama_v5p_slice",
]


class TestGoldenConfigs:
    @pytest.mark.parametrize("name", BASELINE_CONFIGS)
    def test_baseline_manifest_compiles_to_golden(self, name):
        with open(os.path.join(MANIFESTS, f"{name}.yaml")) as f:
            manifest = yaml.safe_load(f)
        compiled = compile_manifest(manifest)
        with open(os.path.join(GOLDEN, f"{name}.yaml")) as f:
            golden = f.read()
        assert compiled == golden, (
            f"{name}: compiler output drifted from the golden; regenerate "
            "deliberately with tpujob compile (see module docstring)"
        )

    @pytest.mark.parametrize("name", BASELINE_CONFIGS)
    def test_golden_is_valid_multi_doc_yaml(self, name):
        with open(os.path.join(GOLDEN, f"{name}.yaml")) as f:
            objs = list(yaml.safe_load_all(f))
        kinds = [o["kind"] for o in objs]
        assert set(kinds) <= {"Pod", "Service", "PodGroup"}
        # one headless service per pod, service applied before its pod
        assert kinds.count("Pod") == kinds.count("Service")
        for o in objs:
            if o["kind"] == "Service":
                assert o["spec"]["clusterIP"] == "None"


class TestCompileSemantics:
    def test_service_precedes_pod_and_group_first(self):
        job = new_job("order", chief=1, worker=2)
        job.spec.enable_gang_scheduling = True
        kinds = [o["kind"] for o in compile_job(job)]
        assert kinds[0] == "PodGroup"
        # alternating service/pod per replica thereafter
        assert kinds[1:] == ["Service", "Pod"] * 3

    def test_env_matches_reconciler_injection(self):
        """The compiled pod env is the same worker_env payload the live
        reconciler injects (same injection point, SURVEY.md §3.2)."""

        job = new_job("envj", chief=1, worker=2)
        objs = compile_job(job)
        pod = next(
            o for o in objs
            if o["kind"] == "Pod" and o["metadata"]["name"] == "envj-worker-1"
        )
        env = {e["name"]: e["value"] for e in pod["spec"]["containers"][0]["env"]}
        import json

        cfg = json.loads(env["TF_CONFIG"])
        assert cfg["task"] == {"type": "worker", "index": 1}
        assert cfg["cluster"]["worker"][1] == "envj-worker-1.default.svc:2222"
        assert env["TPUJOB_NUM_PROCESSES"] == "3"
        assert env["TPUJOB_COORDINATOR_ADDRESS"].startswith("envj-chief-0.")

    def test_user_env_wins_over_injected(self):
        job = new_job("uenv", worker=1)
        job.spec.replica_specs[ReplicaType.WORKER].template.containers[0].env = {
            "TPUJOB_NAME": "overridden"
        }
        pod = next(o for o in compile_job(job) if o["kind"] == "Pod")
        env = {e["name"]: e["value"] for e in pod["spec"]["containers"][0]["env"]}
        assert env["TPUJOB_NAME"] == "overridden"

    def test_exit_code_policy_maps_to_pod_never(self):
        """ExitCode retry is operator-owned: the pod must not
        self-restart (SURVEY.md §3.2 restart-policy mapping)."""

        job = new_job("rp", worker=2)
        job.spec.replica_specs[ReplicaType.WORKER].restart_policy = (
            RestartPolicy.EXIT_CODE
        )
        pods = [o for o in compile_job(job) if o["kind"] == "Pod"]
        assert all(p["spec"]["restartPolicy"] == "Never" for p in pods)
        job.spec.replica_specs[ReplicaType.WORKER].restart_policy = (
            RestartPolicy.ALWAYS
        )
        pods = [o for o in compile_job(job) if o["kind"] == "Pod"]
        assert all(p["spec"]["restartPolicy"] == "OnFailure" for p in pods)

    def test_tpu_slice_node_selectors_chips_and_megascale(self):
        """A 2-slice v5e-16 job: each slice expands to 4 host pods with GKE
        TPU selectors, per-host chip limits, megascale topology env, and
        a gang group spanning all 8 pods."""

        job = new_job("ms", tpu_slice=2, tpu_topology="v5e-16")
        job.spec.enable_gang_scheduling = True
        objs = compile_job(job)
        group = objs[0]
        assert group["kind"] == "PodGroup"
        assert group["spec"]["minMember"] == 8  # 2 slices x 4 hosts
        pods = [o for o in objs if o["kind"] == "Pod"]
        assert len(pods) == 8
        for i, pod in enumerate(pods):
            sel = pod["spec"]["nodeSelector"]
            assert sel["cloud.google.com/gke-tpu-accelerator"] == "tpu-v5-lite-podslice"
            assert sel["cloud.google.com/gke-tpu-topology"] == "4x4"
            limits = pod["spec"]["containers"][0]["resources"]["limits"]
            assert limits["google.com/tpu"] == "4"  # 16 chips / 4 hosts
            env = {
                e["name"]: e["value"]
                for e in pod["spec"]["containers"][0]["env"]
            }
            assert env["MEGASCALE_NUM_SLICES"] == "2"
            assert env["MEGASCALE_SLICE_ID"] == str(i // 4)
            assert env["TPU_WORKER_ID"] == str(i % 4)
            assert (
                pod["metadata"]["annotations"][VOLCANO_GROUP_ANNOTATION] == "ms"
            )
            assert pod["spec"]["schedulerName"] == "volcano"

    @pytest.mark.parametrize(
        "topology,accel,grid,chips_per_host",
        [
            # v4/v5p name TensorCores (2/chip) and take 3-D torus grids
            ("v4-8", "tpu-v4-podslice", "2x2x1", "4"),
            ("v4-16", "tpu-v4-podslice", "2x2x2", "4"),
            ("v5p-8", "tpu-v5p-slice", "2x2x1", "4"),
            ("v5p-128", "tpu-v5p-slice", "4x4x4", "4"),
            # v5e/v6e name chips and take 2-D mesh grids
            ("v5e-8", "tpu-v5-lite-podslice", "2x4", "4"),
            ("v5litepod-16", "tpu-v5-lite-podslice", "4x4", "4"),
            ("v6e-64", "tpu-v6e-slice", "8x8", "4"),
        ],
    )
    def test_topology_grid_per_generation(
        self, topology, accel, grid, chips_per_host
    ):
        """v4/v5p compile to 3-D torus selectors, v5e/v6e to 2-D mesh
        selectors — a 2-D grid on a v4 slice matches no nodepool
        (VERDICT r4 weak #3)."""

        job = new_job("topo", tpu_slice=1, tpu_topology=topology)
        pods = [o for o in compile_job(job) if o["kind"] == "Pod"]
        assert pods
        for pod in pods:
            sel = pod["spec"]["nodeSelector"]
            assert sel["cloud.google.com/gke-tpu-accelerator"] == accel
            assert sel["cloud.google.com/gke-tpu-topology"] == grid
            limits = pod["spec"]["containers"][0]["resources"]["limits"]
            assert limits["google.com/tpu"] == chips_per_host

    def test_unknown_tpu_generation_rejected(self):
        job = new_job("bad", tpu_slice=1, tpu_topology="v9z-16")
        with pytest.raises(ValueError, match="v9z"):
            compile_job(job)

    def test_round_trips_through_yaml(self):
        job = new_job("rt", chief=1, worker=1)
        text = to_yaml(compile_job(job))
        objs = list(yaml.safe_load_all(text))
        assert [o["kind"] for o in objs] == ["Service", "Pod"] * 2
