"""runtime/harness.py train_loop: windowed (sync-free) metric
resolution + the StepSyncLedger invariant (ISSUE 4).

Two tiers, like the rest of the suite:

- the default tier drives a FakeTrainer (host-side arithmetic, no jit)
  through the loop, pinning the windowing/resolution/ledger/guard
  CONTRACT: K=1 resolves per step, K>1 resolves the previous window
  only (0 ``step``-phase syncs per steady-state step — the training
  twin of serving's "1 dispatch per request"), losses come back
  complete and ordered, and the divergence guard still exits non-zero;
- the slow tier runs a real sharded Trainer and pins K=1 losses
  BIT-identical to the pre-windowing per-step reference loop (the
  legacy-debug contract) and the fused scan path close to it.
"""

import numpy as np
import pytest

from tf_operator_tpu.runtime.harness import train_loop
from tf_operator_tpu.utils.metrics import Metrics, StepSyncLedger


class FakeTrainer:
    """Deterministic loss series, harness-trainer protocol.  Losses
    are plain host floats — ledger.resolve() passes them through, so
    the loop logic is exercised without a device in sight."""

    def __init__(self, losses, with_train_steps=True):
        self._losses = list(losses)
        self._i = 0
        self.step_calls = 0
        self.steps_calls = []
        if not with_train_steps:
            # per-step-only trainers (e.g. gpt_pipeline's _Loop
            # adapter) must still work through the windowed loop; the
            # instance attr shadows the class method and train_loop's
            # callable() check routes around it
            self.train_steps = None

    def _next(self):
        v = self._losses[self._i]
        self._i += 1
        return v

    def train_step(self, batch):
        self.step_calls += 1
        return {"loss": self._next()}

    def train_steps(self, batch, k):
        self.steps_calls.append(k)
        return {"loss": np.asarray([self._next() for _ in range(k)])}


def _series(n):
    return [2.0 - 0.05 * i for i in range(n)]


class TestWindowedResolution:
    def test_k1_resolves_every_step(self):
        led = StepSyncLedger()
        t = FakeTrainer(_series(10))
        losses = train_loop(
            t, {"x": 0}, 10, assert_decreasing=False, sync_ledger=led
        )
        assert losses == _series(10)
        assert t.step_calls == 10 and t.steps_calls == []
        assert led.count("step") == 10
        assert led.count("window") == 0 and led.count("final") == 0
        assert led.steps == 10

    def test_k_gt_1_fused_zero_steady_syncs(self):
        """THE acceptance invariant: steady-state steps perform exactly
        0 blocking syncs — every fetch is a deferred previous-window
        (or final) resolve, and the fixed-batch path fuses each window
        into one train_steps call."""

        led = StepSyncLedger()
        t = FakeTrainer(_series(10))
        losses = train_loop(
            t, {"x": 0}, 10, steps_per_sync=4,
            assert_decreasing=False, sync_ledger=led,
        )
        assert losses == _series(10)          # complete and ordered
        assert t.steps_calls == [4, 4, 2]     # fused windows + partial
        assert t.step_calls == 0
        assert led.count("step") == 0         # 0 syncs per steady step
        assert led.count("window") == 2       # deferred: w resolved
        assert led.count("final") == 1        # after w+1 dispatched
        assert led.steps == 10
        assert led.per_step("step") == 0.0

    def test_iterator_batches_window_without_fusing(self):
        """A live pipeline owns its batches: dispatch stays per-step
        but resolution is still windowed — no per-step sync."""

        led = StepSyncLedger()
        t = FakeTrainer(_series(12))
        batches = iter([{"x": i} for i in range(12)])
        losses = train_loop(
            t, batches, 12, steps_per_sync=4,
            assert_decreasing=False, sync_ledger=led,
        )
        assert losses == _series(12)
        assert t.step_calls == 12 and t.steps_calls == []
        assert led.count("step") == 0
        assert led.count("window") == 2 and led.count("final") == 1

    def test_trainer_without_train_steps_still_windows(self):
        led = StepSyncLedger()
        t = FakeTrainer(_series(8), with_train_steps=False)
        losses = train_loop(
            t, {"x": 0}, 8, steps_per_sync=4,
            assert_decreasing=False, sync_ledger=led,
        )
        assert losses == _series(8)
        assert t.step_calls == 8
        assert led.count("step") == 0 and led.count("window") == 1

    def test_metrics_sink_exports_train_sync_counters(self):
        m = Metrics()
        led = StepSyncLedger(metrics=m)
        t = FakeTrainer(_series(8))
        train_loop(
            t, {"x": 0}, 8, steps_per_sync=4,
            assert_decreasing=False, sync_ledger=led,
        )
        assert m.counter("train_sync_total", phase="window") == 1.0
        assert m.counter("train_sync_total", phase="final") == 1.0
        expo = m.exposition()
        assert 'train_sync_total{phase="window"} 1.0' in expo
        assert 'train_sync_seconds_count{phase="final"} 1' in expo

    def test_loop_ledger_attached_to_trainer_and_restored(self):
        """ONE ledger covers the run: the loop temporarily swaps its
        ledger into trainer.sync_ledger (so summary-phase resolves land
        on the same accounting) and restores the trainer's own after."""

        led, own = StepSyncLedger(), StepSyncLedger()
        t = FakeTrainer(_series(4))
        t.sync_ledger = own
        seen = []
        orig = t.train_step
        t.train_step = lambda b: (seen.append(t.sync_ledger), orig(b))[1]
        train_loop(t, {"x": 0}, 4, assert_decreasing=False, sync_ledger=led)
        assert all(s is led for s in seen)
        assert t.sync_ledger is own

    def test_ledger_table_skips_meta_rows(self):
        led = StepSyncLedger()
        led.step(4)
        led.resolve("window", [1.0])
        txt = led.table(wall=0.1)
        assert "| window | 1 |" in txt and "_steps" not in txt


class TestDivergenceGuard:
    """The examples double as e2e workloads: silent divergence must
    exit non-zero — now from the FINAL resolve, on every K."""

    @pytest.mark.parametrize("k", [1, 8])
    def test_divergence_exits_nonzero(self, k):
        t = FakeTrainer([1.0 + 0.1 * i for i in range(24)])
        with pytest.raises(SystemExit) as exc:
            train_loop(t, {"x": 0}, 24, steps_per_sync=k)
        assert exc.value.code == 1

    @pytest.mark.parametrize("k", [1, 8])
    def test_decreasing_loss_passes(self, k):
        t = FakeTrainer(_series(24))
        losses = train_loop(t, {"x": 0}, 24, steps_per_sync=k)
        assert len(losses) == 24

    def test_short_runs_skip_guard(self):
        # < 20 steps: guard never fires (warmup noise)
        t = FakeTrainer([1.0, 2.0, 3.0, 4.0])
        assert len(train_loop(t, {"x": 0}, 4, steps_per_sync=2)) == 4


@pytest.mark.slow
class TestRealTrainerParity:
    """The K=1 legacy contract on a real sharded Trainer: losses
    BIT-identical to the pre-windowing reference loop; the fused scan
    close (its program compiles separately — same math, not bit-pinned,
    see Trainer.train_steps)."""

    def _trainer(self):
        import jax.numpy as jnp

        from tf_operator_tpu.models import MnistCNN
        from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
        from tf_operator_tpu.parallel.trainer import cross_entropy_loss

        r = np.random.RandomState(0)
        batch = {
            "image": jnp.asarray(r.rand(16, 28, 28, 1), jnp.float32),
            "label": jnp.asarray(r.randint(0, 10, size=(16,))),
        }
        mesh = make_mesh({"dp": 8})
        tr = Trainer(
            MnistCNN(), TrainerConfig(learning_rate=1e-3), mesh,
            cross_entropy_loss, batch, seed=0,
        )
        return tr, tr.shard_batch(batch)

    def test_k1_bit_identical_to_reference_loop(self):
        tr_ref, b_ref = self._trainer()
        # the pre-change per-step loop, inlined
        ref = [float(tr_ref.train_step(b_ref)["loss"]) for _ in range(8)]

        tr, b = self._trainer()
        led = StepSyncLedger()
        losses = train_loop(
            tr, b, 8, assert_decreasing=False, sync_ledger=led
        )
        assert losses == ref            # identical, not just close
        assert led.count("step") == 8

    def test_fused_summary_writes_are_deferred_one_window(self):
        """A summary_writer must not re-serialize the fused path: the
        boundary window PARKS its summary and the next train_steps call
        writes it (previous-window discipline) — so writes lag one
        window and the summary fetch never waits on fresh dispatch."""

        import jax

        from tf_operator_tpu.models import MnistCNN
        from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
        from tf_operator_tpu.parallel.trainer import cross_entropy_loss

        writes = []

        class Writer:
            def write(self, step, **scalars):
                writes.append((step, sorted(scalars)))

            def close(self):
                pass

        import jax.numpy as jnp

        r = np.random.RandomState(0)
        batch = {
            "image": jnp.asarray(r.rand(16, 28, 28, 1), jnp.float32),
            "label": jnp.asarray(r.randint(0, 10, size=(16,))),
        }
        tr = Trainer(
            MnistCNN(),
            TrainerConfig(learning_rate=1e-3, summary_every=4),
            make_mesh({"dp": 8}), cross_entropy_loss, batch,
            summary_writer=Writer(),
        )
        b = tr.shard_batch(batch)
        tr.train_steps(b, 4)          # boundary at 4: parked, not written
        assert writes == []
        assert tr._pending_summary is not None
        tr.train_steps(b, 4)          # writes the PARKED step-4 summary
        assert [w[0] for w in writes] == [4]
        tr.train_steps(b, 4)          # writes step-8's parked summary
        assert [w[0] for w in writes] == [4, 8]

    def test_fused_k_matches_reference_closely_and_syncs_zero(self):
        tr_ref, b_ref = self._trainer()
        ref = [float(tr_ref.train_step(b_ref)["loss"]) for _ in range(12)]

        tr, b = self._trainer()
        led = StepSyncLedger()
        losses = train_loop(
            tr, b, 12, steps_per_sync=4,
            assert_decreasing=False, sync_ledger=led,
        )
        np.testing.assert_allclose(losses, ref, rtol=5e-3)
        assert led.count("step") == 0
        assert led.count("window") == 2 and led.count("final") == 1
        # fused windows really went through the scan path
        assert tr._host_step == 12
