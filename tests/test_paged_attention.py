"""ops/paged_attention property suite (ISSUE 10 satellites).

The kernel contract under test (module docstring of
ops/paged_attention.py):

- BLOCK STRADDLE: lengths at block_size±1 (and every boundary in
  between) agree with the gathered-view reference — the straddled
  block's partial tail is masked, not read.
- SCRATCH-BLOCK-0 MASKING: poisoning the scratch block (and every
  block the table maps beyond the length) with huge values changes
  NOTHING — masked positions multiply by exactly zero.
- NEVER READS AN UNPUBLISHED BLOCK: under a prefix-cache-hit-shaped
  table (shared head blocks + fresh tail), poisoning every arena block
  the table does NOT reference leaves the output bit-identical.

Every property runs against BOTH impls: the XLA gather reference
(bit-identical to the contiguous pool's decode math) and the REAL
Pallas kernel through the interpreter (the CI's kernel path; the same
kernel compiles on the TPU backend).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tf_operator_tpu.ops.attention import dot_product_attention
from tf_operator_tpu.ops.paged_attention import (
    _resolve_paged_tile,
    paged_attention,
    paged_attention_multi,
    paged_kernel_available,
)

IMPLS = ("xla", "pallas-interpret")


def _rig(seed=0, s=3, h=4, hkv=2, d=32, nb=None, bs=8, mb=4,
         dtype=jnp.float32):
    if nb is None:
        nb = s * mb + 1  # every seat fully tabled + scratch
    r = np.random.RandomState(seed)
    q = jnp.asarray(r.randn(s, h, d), dtype)
    ka = jnp.asarray(r.randn(nb, hkv, bs, d), dtype)
    va = jnp.asarray(r.randn(nb, hkv, bs, d), dtype)
    # distinct physical blocks per seat (1..nb-1; 0 stays scratch)
    ids = r.permutation(np.arange(1, nb))[: s * mb]
    tables = jnp.asarray(ids.reshape(s, mb), jnp.int32)
    return q, ka, va, tables


def _dense_reference(q, ka, va, tables, lengths):
    """The contiguous pool's decode math: gather the view, mask by
    length, run ops.attention — the exactness anchor."""

    s, mb = tables.shape
    nb, hkv, bs, d = ka.shape

    def view(a):
        g = jnp.take(a, tables, axis=0)
        return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(s, hkv, mb * bs, d)

    mask = (jnp.arange(mb * bs)[None] < lengths[:, None])[:, None, None, :]
    return dot_product_attention(
        q[:, :, None, :], view(ka), view(va), mask=mask
    )[:, :, 0, :]


@pytest.mark.parametrize("impl", IMPLS)
class TestPagedAttentionProperties:
    def test_block_straddle_lengths(self, impl):
        """Every length around every block boundary: bs-1, bs, bs+1 …
        — the straddle satellite.  Mixed per-seat lengths in one call
        (the pool's real shape)."""

        q, ka, va, tables = _rig(seed=1)
        bs = ka.shape[2]
        cases = [1, bs - 1, bs, bs + 1, 2 * bs - 1, 2 * bs + 1, 4 * bs]
        # sweep in groups of S seats so every case runs batched
        for i in range(0, len(cases), tables.shape[0]):
            group = cases[i : i + tables.shape[0]]
            while len(group) < tables.shape[0]:
                group.append(1)
            lengths = jnp.asarray(group, jnp.int32)
            got = paged_attention(q, ka, va, tables, lengths, impl=impl)
            want = _dense_reference(q, ka, va, tables, lengths)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
            )

    def test_scratch_block_masking(self, impl):
        """Poison scratch (block 0) and every position past each
        seat's length with huge garbage: the output must not move —
        masked positions contribute exactly zero weight."""

        q, ka, va, tables = _rig(seed=2)
        bs = ka.shape[2]
        lengths = jnp.asarray([bs + 1, 1, 3 * bs - 1], jnp.int32)
        base = paged_attention(q, ka, va, tables, lengths, impl=impl)
        poison_k = ka.at[0].set(1e9)
        poison_v = va.at[0].set(-1e9)
        # also poison the in-table blocks BEYOND each seat's length
        tb = np.asarray(tables)
        ln = np.asarray(lengths)
        pk = np.array(poison_k, copy=True)
        pv = np.array(poison_v, copy=True)
        for s in range(tb.shape[0]):
            for j in range(tb.shape[1]):
                start = j * bs
                if start >= ln[s]:
                    pk[tb[s, j]] = 1e9
                    pv[tb[s, j]] = -1e9
                elif start + bs > ln[s]:
                    pk[tb[s, j], :, ln[s] - start :] = 1e9
                    pv[tb[s, j], :, ln[s] - start :] = -1e9
        got = paged_attention(
            q, jnp.asarray(pk), jnp.asarray(pv), tables, lengths, impl=impl
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(base))

    def test_never_reads_an_unreferenced_block(self, impl):
        """Prefix-hit shape: two seats share their first block (the
        published prefix), tails are fresh.  Poisoning every arena
        block NOT in any table leaves the output bit-identical — the
        table is the only read path."""

        q, ka, va, _ = _rig(seed=3)
        bs = ka.shape[2]
        tables = jnp.asarray(
            [[1, 2, 0, 0], [1, 3, 0, 0], [4, 5, 6, 0]], jnp.int32
        )  # seats 0/1 share block 1 (the cached prefix)
        lengths = jnp.asarray([bs + 3, bs + 5, 2 * bs + 1], jnp.int32)
        base = paged_attention(q, ka, va, tables, lengths, impl=impl)
        referenced = set(np.asarray(tables).ravel().tolist())
        pk, pv = np.asarray(ka).copy(), np.asarray(va).copy()
        for b in range(ka.shape[0]):
            if b not in referenced:
                pk[b] = 7e8
                pv[b] = -7e8
        got = paged_attention(
            q, jnp.asarray(pk), jnp.asarray(pv), tables, lengths, impl=impl
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(base))

    def test_gqa_and_mha_agree_with_reference(self, impl):
        """GQA-native (h != hkv) and MHA widths both match the dense
        reference; bf16 arenas return bf16."""

        for h, hkv in ((4, 2), (4, 4)):
            q, ka, va, tables = _rig(seed=4, h=h, hkv=hkv)
            lengths = jnp.asarray([5, 17, 30], jnp.int32)
            got = paged_attention(q, ka, va, tables, lengths, impl=impl)
            want = _dense_reference(q, ka, va, tables, lengths)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
            )

    def test_non_pow2_block_size_straddle(self, impl):
        """bs=12 (the pool's non-pow2 regression shape): boundary
        straddles stay exact when the tile resolver has to divide an
        odd block size."""

        q, ka, va, tables = _rig(seed=5, bs=12, nb=7, mb=2)
        lengths = jnp.asarray([11, 13, 24], jnp.int32)
        got = paged_attention(q, ka, va, tables, lengths, impl=impl)
        want = _dense_reference(q, ka, va, tables, lengths)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )


class TestRandomizedAgainstReference:
    def test_random_tables_and_lengths(self):
        """Seeded fuzz: random tables, random lengths (incl. exact
        block multiples ±1), kernel vs gather reference."""

        r = np.random.RandomState(11)
        for trial in range(4):
            s, hkv, group = 2 + trial % 2, 2, 1 + trial % 2
            d, bs, mb = 16, 8, 3
            nb = 1 + s * mb
            q = jnp.asarray(r.randn(s, hkv * group, d), jnp.float32)
            ka = jnp.asarray(r.randn(nb, hkv, bs, d), jnp.float32)
            va = jnp.asarray(r.randn(nb, hkv, bs, d), jnp.float32)
            tables = jnp.asarray(
                r.permutation(np.arange(1, nb))[: s * mb].reshape(s, mb),
                jnp.int32,
            )
            lengths = jnp.asarray(
                [
                    int(np.clip(r.randint(1, mb * bs + 1) + r.choice([-1, 0, 1]),
                                1, mb * bs))
                    for _ in range(s)
                ],
                jnp.int32,
            )
            got = paged_attention(
                q, ka, va, tables, lengths, impl="pallas-interpret"
            )
            want = _dense_reference(q, ka, va, tables, lengths)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5,
                err_msg=f"trial {trial} lengths {np.asarray(lengths)}",
            )


def _multi_band_reference(q, ka, va, tables, lengths):
    """Row-by-row anchor for the verify window: query row t of seat s
    is EXACTLY the single-query math at the truncated length
    lengths[s] - (K-1-t) — the band mask is nothing but K staggered
    single-query calls fused into one dispatch."""

    k_new = q.shape[1]
    rows = []
    for t in range(k_new):
        trunc = lengths - (k_new - 1 - t)
        rows.append(
            paged_attention(q[:, t], ka, va, tables, trunc, impl="xla")
        )
    return jnp.stack(rows, axis=1)  # [S, K, H, D]


@pytest.mark.parametrize("impl", IMPLS)
class TestPagedAttentionMulti:
    """ISSUE 18: the K-token verify primitive.  lengths INCLUDE all K
    appended tokens; row t sees p < lengths[s]-(K-1-t)."""

    def test_k1_slice_is_single_query(self, impl):
        """K=1 reproduces the single-query entry point bit for bit —
        same grid, same block shapes, same mask."""

        q, ka, va, tables = _rig(seed=21)
        lengths = jnp.asarray([7, 16, 25], jnp.int32)
        got = paged_attention_multi(
            q[:, None], ka, va, tables, lengths, impl=impl
        )
        want = paged_attention(q, ka, va, tables, lengths, impl=impl)
        np.testing.assert_array_equal(
            np.asarray(got[:, 0]), np.asarray(want)
        )

    def test_band_rows_match_truncated_single_query(self, impl):
        """Each of the K rows agrees with a single-query call at the
        truncated length — the in-window causal band, pinned per row
        across block straddles (lengths land on bs±1 boundaries)."""

        k_new = 4
        r = np.random.RandomState(22)
        q1, ka, va, tables = _rig(seed=22)
        s, h, d = q1.shape
        q = jnp.asarray(r.randn(s, k_new, h, d), jnp.float32)
        bs = ka.shape[2]
        lengths = jnp.asarray([bs + 1, bs + k_new, 3 * bs - 1], jnp.int32)
        got = paged_attention_multi(q, ka, va, tables, lengths, impl=impl)
        want = _multi_band_reference(q, ka, va, tables, lengths)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    def test_scratch_and_beyond_band_masking(self, impl):
        """Poisoning scratch block 0 and every position at or beyond
        the LAST row's horizon (p >= lengths[s]) moves nothing: the
        rejected-append scratch-routing story depends on this."""

        k_new = 3
        r = np.random.RandomState(23)
        q1, ka, va, tables = _rig(seed=23)
        s, h, d = q1.shape
        q = jnp.asarray(r.randn(s, k_new, h, d), jnp.float32)
        bs = ka.shape[2]
        lengths = jnp.asarray([k_new, bs + 2, 2 * bs + k_new], jnp.int32)
        base = paged_attention_multi(q, ka, va, tables, lengths, impl=impl)
        tb, ln = np.asarray(tables), np.asarray(lengths)
        pk = np.array(ka, copy=True)
        pv = np.array(va, copy=True)
        pk[0], pv[0] = 1e9, -1e9
        for si in range(tb.shape[0]):
            for j in range(tb.shape[1]):
                start = j * bs
                if start >= ln[si]:
                    pk[tb[si, j]], pv[tb[si, j]] = 1e9, -1e9
                elif start + bs > ln[si]:
                    pk[tb[si, j], :, ln[si] - start:] = 1e9
                    pv[tb[si, j], :, ln[si] - start:] = -1e9
        got = paged_attention_multi(
            q, jnp.asarray(pk), jnp.asarray(pv), tables, lengths, impl=impl
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(base))

    def test_gqa_window_fuzz(self, impl):
        """Seeded fuzz over K, GQA group width and straddle lengths:
        one fused dispatch vs the K staggered single-query calls."""

        r = np.random.RandomState(24)
        for trial in range(3):
            k_new = 2 + trial
            hkv, group = 2, 1 + trial % 2
            d, bs, mb, s = 16, 8, 3, 2
            nb = 1 + s * mb
            q = jnp.asarray(
                r.randn(s, k_new, hkv * group, d), jnp.float32
            )
            ka = jnp.asarray(r.randn(nb, hkv, bs, d), jnp.float32)
            va = jnp.asarray(r.randn(nb, hkv, bs, d), jnp.float32)
            tables = jnp.asarray(
                r.permutation(np.arange(1, nb))[: s * mb].reshape(s, mb),
                jnp.int32,
            )
            lengths = jnp.asarray(
                [r.randint(k_new, mb * bs + 1) for _ in range(s)],
                jnp.int32,
            )
            got = paged_attention_multi(
                q, ka, va, tables, lengths, impl=impl
            )
            want = _multi_band_reference(q, ka, va, tables, lengths)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5,
                err_msg=f"trial {trial} K={k_new} "
                        f"lengths {np.asarray(lengths)}",
            )

    def test_bad_layout_raises(self, impl):
        q, ka, va, tables = _rig()
        lengths = jnp.asarray([1, 1, 1], jnp.int32)
        with pytest.raises(ValueError):
            paged_attention_multi(q, ka, va, tables, lengths, impl=impl)


class TestTileAndHonesty:
    def test_tile_divides_block_size(self):
        """resolve_flash_blocks-derived tiles always divide the arena
        block (a tile may never straddle two physically scattered
        blocks) and respect the head-dim-capped class."""

        for bs in (8, 12, 16, 48, 128, 384, 768):
            for d in (32, 64, 128, 256):
                tile = _resolve_paged_tile(bs, d)
                assert tile >= 1 and bs % tile == 0, (bs, d, tile)
        # head-dim cap: big-D tiles never exceed the 512 class the
        # resolver pins (ADVICE r5 #1 — the VMEM ceiling)
        assert _resolve_paged_tile(1024, 256) <= 512

    def test_kernel_availability_is_honest_off_tpu(self):
        """On this CPU box the compiled kernel is unavailable (with a
        reason) while interpret mode is — the fail-don't-downgrade
        contract serve_lm's --paged-kernel on relies on."""

        if jax.default_backend() == "tpu":
            pytest.skip("TPU backend: the compiled kernel applies")
        ok, why = paged_kernel_available(32, 16)
        assert not ok and "backend" in why
        ok, why = paged_kernel_available(32, 16, interpret=True)
        assert ok and why == ""

    def test_bad_impl_and_layout_raise(self):
        q, ka, va, tables = _rig()
        lengths = jnp.asarray([1, 1, 1], jnp.int32)
        with pytest.raises(ValueError):
            paged_attention(q, ka, va, tables, lengths, impl="magic")
        with pytest.raises(ValueError):
            paged_attention(q[0], ka, va, tables, lengths, impl="xla")


class TestXlaReferenceIsContiguousMath:
    def test_bit_identical_to_dense_reference(self):
        """The "xla" impl IS the contiguous pool's math (same einsum,
        same mask): bit-identical, not merely close — the anchor the
        pool's token-identity pins rest on."""

        q, ka, va, tables = _rig(seed=9, dtype=jnp.bfloat16)
        lengths = jnp.asarray([7, 9, 25], jnp.int32)
        got = paged_attention(q, ka, va, tables, lengths, impl="xla")
        want = _dense_reference(q, ka, va, tables, lengths)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
