"""Test fixture builders.

Parity: the reference's ``pkg/common/util/v1/testutil`` builder library
(SURVEY.md §4 tier 2): NewTFJob-style constructors + pod-phase fabricators
that make status-engine tests cheap and exhaustive.
"""

from __future__ import annotations

from typing import Optional, Tuple

from tf_operator_tpu.api.types import (
    Container,
    ObjectMeta,
    PodTemplateSpec,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    TPUJob,
    TPUJobSpec,
    replica_name,
)
from tf_operator_tpu.backend.fake import FakeCluster
from tf_operator_tpu.backend.jobstore import JobStore
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.controller.reconciler import ReconcilerConfig


def new_job(
    name: str = "job",
    namespace: str = "default",
    chief: int = 0,
    master: int = 0,
    ps: int = 0,
    worker: int = 0,
    evaluator: int = 0,
    tpu_slice: int = 0,
    tpu_topology: str = "v5e-16",
    restart_policy: Optional[RestartPolicy] = None,
    command=("python", "train.py"),
) -> TPUJob:
    counts = {
        ReplicaType.CHIEF: chief,
        ReplicaType.MASTER: master,
        ReplicaType.PS: ps,
        ReplicaType.WORKER: worker,
        ReplicaType.EVALUATOR: evaluator,
        ReplicaType.TPU_SLICE: tpu_slice,
    }
    specs = {}
    for rtype, n in counts.items():
        if n <= 0:
            continue
        specs[rtype] = ReplicaSpec(
            replicas=n,
            template=PodTemplateSpec(containers=[Container(command=list(command))]),
            restart_policy=restart_policy,
            tpu_topology=tpu_topology if rtype is ReplicaType.TPU_SLICE else "",
        )
    return TPUJob(metadata=ObjectMeta(name=name, namespace=namespace), spec=TPUJobSpec(replica_specs=specs))


def harness(
    delivery: str = "sync",
    total_chips: Optional[int] = None,
    config: Optional[ReconcilerConfig] = None,
) -> Tuple[JobStore, FakeCluster, TPUJobController]:
    store = JobStore()
    backend = FakeCluster(delivery=delivery, total_chips=total_chips)
    # fresh Metrics per harness: assertions against the process-global
    # default_metrics would be test-order-dependent
    from tf_operator_tpu.utils.metrics import Metrics

    controller = TPUJobController(store, backend, config=config, metrics=Metrics())
    return store, backend, controller


def pod_name(job: TPUJob, rtype: ReplicaType, idx: int) -> str:
    return replica_name(job.metadata.name, rtype, idx)


def run_and_succeed_all(backend: FakeCluster, namespace: str = "default") -> None:
    backend.run_all(namespace)
    for pod in list(backend._pods.values()):
        if pod.metadata.namespace == namespace:
            backend.succeed_pod(namespace, pod.metadata.name)


def load_serve_lm():
    """Import examples/serve_lm.py as a module (it is a script, not a
    package member) — ONE loader for every serving test."""

    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "serve_lm",
        os.path.join(
            os.path.dirname(__file__), "..", "examples", "serve_lm.py"
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
