"""Test fixture builders.

Parity: the reference's ``pkg/common/util/v1/testutil`` builder library
(SURVEY.md §4 tier 2): NewTFJob-style constructors + pod-phase fabricators
that make status-engine tests cheap and exhaustive.
"""

from __future__ import annotations

from typing import Optional, Tuple

from tf_operator_tpu.api.types import (
    Container,
    ObjectMeta,
    PodTemplateSpec,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    TPUJob,
    TPUJobSpec,
    replica_name,
)
from tf_operator_tpu.backend.fake import FakeCluster
from tf_operator_tpu.backend.jobstore import JobStore
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.controller.reconciler import ReconcilerConfig


def new_job(
    name: str = "job",
    namespace: str = "default",
    chief: int = 0,
    master: int = 0,
    ps: int = 0,
    worker: int = 0,
    evaluator: int = 0,
    tpu_slice: int = 0,
    tpu_topology: str = "v5e-16",
    restart_policy: Optional[RestartPolicy] = None,
    command=("python", "train.py"),
) -> TPUJob:
    counts = {
        ReplicaType.CHIEF: chief,
        ReplicaType.MASTER: master,
        ReplicaType.PS: ps,
        ReplicaType.WORKER: worker,
        ReplicaType.EVALUATOR: evaluator,
        ReplicaType.TPU_SLICE: tpu_slice,
    }
    specs = {}
    for rtype, n in counts.items():
        if n <= 0:
            continue
        specs[rtype] = ReplicaSpec(
            replicas=n,
            template=PodTemplateSpec(containers=[Container(command=list(command))]),
            restart_policy=restart_policy,
            tpu_topology=tpu_topology if rtype is ReplicaType.TPU_SLICE else "",
        )
    return TPUJob(metadata=ObjectMeta(name=name, namespace=namespace), spec=TPUJobSpec(replica_specs=specs))


def harness(
    delivery: str = "sync",
    total_chips: Optional[int] = None,
    config: Optional[ReconcilerConfig] = None,
    scheduler=None,
) -> Tuple[JobStore, FakeCluster, TPUJobController]:
    store = JobStore()
    backend = FakeCluster(delivery=delivery, total_chips=total_chips)
    # fresh Metrics per harness: assertions against the process-global
    # default_metrics would be test-order-dependent
    from tf_operator_tpu.utils.metrics import Metrics

    controller = TPUJobController(
        store, backend, config=config, metrics=Metrics(), scheduler=scheduler
    )
    return store, backend, controller


def pod_name(job: TPUJob, rtype: ReplicaType, idx: int) -> str:
    return replica_name(job.metadata.name, rtype, idx)


def run_and_succeed_all(backend: FakeCluster, namespace: str = "default") -> None:
    backend.run_all(namespace)
    for pod in list(backend._pods.values()):
        if pod.metadata.namespace == namespace:
            backend.succeed_pod(namespace, pod.metadata.name)


def load_serve_lm():
    """Import examples/serve_lm.py as a module (it is a script, not a
    package member) — ONE loader for every serving test."""

    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "serve_lm",
        os.path.join(
            os.path.dirname(__file__), "..", "examples", "serve_lm.py"
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def assert_decode_equiv_up_to_ties(model, params, out, ref):
    """Token-exact except argmax flips on near-tied logits: at each
    row's first divergence, replay the reference prefix through the
    decode variant and require the two CONTESTED tokens to be top-3
    ranked and within bf16 cross-program noise of each other (pair
    gap < 0.05 — measured: distinct XLA programs legitimately flip
    decisions whose TRUE f32 margin is <= 0.022 on a 4-layer bf16
    fixture).  After a flip the chains diverge by construction.  A
    real plumbing bug (cache corruption, wrong weights, scale
    misalignment) emits tokens ranked far below the top and fails.
    Shared by the decode/quant/speculative parity tests."""

    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models.decode import _decode_variant, _init_cache_for

    out, ref = np.asarray(out), np.asarray(ref)
    assert out.shape == ref.shape
    dmodel = _decode_variant(model)
    for i in range(out.shape[0]):
        if (out[i] == ref[i]).all():
            continue
        j = int(np.argwhere(out[i] != ref[i])[0][0])
        cache = _init_cache_for(dmodel, 1)
        logits, _ = dmodel.apply(
            {"params": params, "cache": cache},
            jnp.asarray(ref[i : i + 1, :j]),
            mutable=["cache"],
        )
        lg = np.asarray(logits[0, -1], np.float32)
        top3 = set(np.argsort(lg)[::-1][:3].tolist())
        pair_gap = abs(float(lg[out[i, j]] - lg[ref[i, j]]))
        assert out[i, j] in top3 and ref[i, j] in top3 and pair_gap < 0.05, (
            f"row {i} diverges at pos {j} and it is NOT a near-tie: "
            f"{out[i, j]} vs {ref[i, j]}, pair gap {pair_gap:.4f}"
        )


def import_hypothesis_or_stubs():
    """``(given, settings, st)`` — the real hypothesis when installed,
    inert stand-ins otherwise so property-based tests SKIP cleanly (via
    ``pytest.importorskip`` at call time) while the rest of the module
    keeps collecting and running.  Usage, at module top:

        try:
            from hypothesis import given, settings, strategies as st
        except ImportError:
            from tests.testutil import import_hypothesis_or_stubs
            given, settings, st = import_hypothesis_or_stubs()
    """

    try:
        from hypothesis import given, settings, strategies as st

        return given, settings, st
    except ImportError:
        pass

    import pytest

    class _StrategyStub:
        """Absorbs any strategy construction (st.integers(1, 5),
        st.sampled_from(...)) — the values are only ever consumed by
        the @given stub, which never runs the test body."""

        def __getattr__(self, name):
            return lambda *a, **k: None

    def given(*a, **k):
        def deco(fn):
            # NOT functools.wraps: __wrapped__ would make pytest
            # resolve the original signature and hunt for fixtures
            # named after the hypothesis-drawn parameters
            def skipper(*fa, **fk):
                pytest.importorskip("hypothesis")

            skipper.__name__ = fn.__name__
            skipper.__doc__ = fn.__doc__
            return skipper

        return deco

    def settings(*a, **k):
        return lambda fn: fn

    return given, settings, _StrategyStub()
