"""bench.py's driver-artifact contract (VERDICT r5 next #3).

Five rounds of artifact fumbles: r1 rc=1, r3 rc=124, r4
parsed-but-error, r5 rc=0 with `"parsed": null` — the final stdout
line embedded the whole last_measured ledger and outgrew the driver's
bounded tail capture, truncating mid-key.  The contract pinned here:
the FINAL stdout line of `python bench.py` is compact (<
bench.FINAL_LINE_LIMIT = 2 KB), valid JSON with the driver-parsed
fields, and the ledger/overflow detail prints on its own lines
UPSTREAM of it.  `emit_final` enforces this in-process on every exit
path (success, probe failure, budget exhaustion).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def _fat_ledger(n=60):
    return {
        f"metric_{i}": {
            "value": i * 1.5,
            "artifact": "benchmarks/window_out/" + "x" * 60 + ".out",
            "date": "2026-08-03",
        }
        for i in range(n)
    }


def test_emit_final_moves_ledger_upstream_and_stays_compact(capsys):
    result = {
        "metric": bench.METRIC,
        "value": 2600.49,
        "unit": bench.UNIT,
        "vs_baseline": 1.1,
        "mfu_analytic": 0.3156,
        "last_measured": _fat_ledger(),
    }
    bench.emit_final(result)
    lines = [
        ln for ln in capsys.readouterr().out.strip().splitlines()
        if ln.strip()
    ]
    final = lines[-1]
    assert len(final) < bench.FINAL_LINE_LIMIT
    parsed = json.loads(final)
    assert parsed["metric"] == bench.METRIC and parsed["value"] == 2600.49
    assert "last_measured" not in parsed
    # the ledger is still in the artifact — upstream of the final line,
    # itself valid JSON
    upstream = [json.loads(ln) for ln in lines[:-1]]
    assert any("last_measured" in obj for obj in upstream)


def test_emit_final_sheds_noncore_fields_rather_than_overflowing(capsys):
    result = {
        "metric": bench.METRIC,
        "value": 1.0,
        "unit": bench.UNIT,
        "vs_baseline": 1.0,
        "giant_sweep_blob": [{"k": "v" * 50, "i": i} for i in range(100)],
    }
    bench.emit_final(result)
    lines = capsys.readouterr().out.strip().splitlines()
    final = json.loads(lines[-1])
    assert len(lines[-1]) < bench.FINAL_LINE_LIMIT
    assert "giant_sweep_blob" not in final and final["value"] == 1.0
    # the shed detail survives upstream with an explicit marker
    shed = json.loads(lines[-2])
    assert shed["final_line_overflow_dropped"] == ["giant_sweep_blob"]
    assert "giant_sweep_blob" in shed


def test_error_paths_attach_ledger_and_keep_contract(capsys):
    # the dead-tunnel shape: error result carrying the full ledger
    bench.emit_final({
        "metric": bench.METRIC, "value": 0.0, "unit": bench.UNIT,
        "vs_baseline": 0.0, "error": "probe hung: TPU tunnel not answering",
        "last_measured": _fat_ledger(),
    })
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines[-1]) < bench.FINAL_LINE_LIMIT
    assert json.loads(lines[-1])["error"].startswith("probe hung")


@pytest.mark.slow
def test_bench_py_end_to_end_final_line_parses():
    """Run the real binary on the budget-exhausted path (CPU platform,
    tiny budget: the probe answers, then no time remains for children)
    and assert the stdout the driver would capture obeys the contract.
    TPU_CHIP_LOCK_INHERITED short-circuits the chip lock so this test
    can never preempt a live measurement window's claim."""

    env = dict(
        os.environ,
        BENCH_PLATFORM="cpu",
        BENCH_TOTAL_BUDGET="25",
        BENCH_PROBE_TIMEOUT="60",
        BENCH_PROBE_RETRIES="1",
        TPU_CHIP_LOCK_INHERITED="1",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-500:]
    lines = [
        ln for ln in proc.stdout.strip().splitlines() if ln.strip()
    ]
    final = lines[-1]
    assert len(final) < bench.FINAL_LINE_LIMIT
    parsed = json.loads(final)
    assert parsed["metric"] == bench.METRIC
    assert "value" in parsed and "vs_baseline" in parsed
    assert "last_measured" not in parsed
    # the repo ships a non-empty LAST_MEASURED.json, so the ledger
    # line must have printed upstream
    assert any(ln.startswith('{"last_measured"') for ln in lines[:-1])
