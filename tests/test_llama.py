"""Llama family: RoPE, GQA, SwiGLU — training + sp equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# default-tier exclusion (llama family compiles); see README 'Tests run in two tiers'
pytestmark = pytest.mark.slow

from tf_operator_tpu.models import llama_tiny, llama_loss
from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

VOCAB = 256


def _ids(rng, b, s):
    return rng.randint(0, VOCAB, size=(b, s)).astype(np.int32)


def test_rope_reference():
    """apply_rope matches a direct complex-multiplication reference."""

    from tf_operator_tpu.ops.rotary import apply_rope

    r = np.random.RandomState(0)
    q = jnp.asarray(r.randn(1, 2, 8, 16), jnp.float32)
    k = jnp.asarray(r.randn(1, 2, 8, 16), jnp.float32)
    qr, kr = apply_rope(q, k)

    # reference: view as complex pairs (x[:d/2] + i*x[d/2:]) and
    # multiply by e^{i * pos * theta^{-2j/d}}
    d, half = 16, 8
    freq = 10000.0 ** (-np.arange(half) / half)
    ang = np.arange(8)[:, None] * freq[None, :]
    rotor = np.exp(1j * ang)  # [S, d/2]
    qc = np.asarray(q[..., :half]) + 1j * np.asarray(q[..., half:])
    qc = qc * rotor
    expect = np.concatenate([qc.real, qc.imag], axis=-1)
    np.testing.assert_allclose(np.asarray(qr), expect, atol=1e-5, rtol=1e-5)

    # norms preserved (rotation), relative-position property: scores
    # depend only on distance
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(qr), axis=-1),
        np.linalg.norm(np.asarray(q), axis=-1),
        rtol=1e-5,
    )


def test_rope_relative_position_invariance():
    """q·k after RoPE depends only on the position *difference*."""

    from tf_operator_tpu.ops.rotary import apply_rope

    r = np.random.RandomState(1)
    q = jnp.asarray(r.randn(1, 1, 1, 32), jnp.float32)
    k = jnp.asarray(r.randn(1, 1, 1, 32), jnp.float32)

    def score(pq, pk):
        qq, _ = apply_rope(q, q, positions=jnp.array([pq]))
        _, kk = apply_rope(k, k, positions=jnp.array([pk]))
        return float(jnp.einsum("bhqd,bhkd->bhqk", qq, kk)[0, 0, 0, 0])

    np.testing.assert_allclose(score(3, 1), score(10, 8), rtol=1e-4)
    np.testing.assert_allclose(score(7, 7), score(0, 0), rtol=1e-4)


def test_llama_gqa_param_shapes():
    model = llama_tiny(vocab_size=VOCAB, n_kv_heads=2)
    ids = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    attn = params["layer_0"]["self_attn"]
    q_kernel = attn["query"]["kernel"]
    k_kernel = attn["key"]["kernel"]
    qv = getattr(q_kernel, "value", q_kernel)
    kv_ = getattr(k_kernel, "value", k_kernel)
    assert qv.shape == (128, 4, 32)  # n_heads
    assert kv_.shape == (128, 2, 32)  # n_kv_heads
    # no biases anywhere in the network (llama convention)
    for proj in ("query", "key", "value", "out"):
        assert "bias" not in attn[proj], proj
    mlp = params["layer_0"]["mlp"]
    assert set(mlp) == {"wi_gate", "wi_up", "wo"}  # swiglu


def test_llama_training_step():
    mesh = make_mesh({"dp": 8})
    rng = np.random.RandomState(0)
    ids = _ids(rng, 8, 32)
    batch = {"input_ids": ids}
    model = llama_tiny(vocab_size=VOCAB, max_len=32, mesh=mesh)
    tr = Trainer(
        model,
        TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
        mesh,
        llama_loss,
        batch,
        init_args=(ids,),
        shardings="logical",
    )
    first = tr.train_step(tr.shard_batch(batch))
    for _ in range(5):
        last = tr.train_step(tr.shard_batch(batch))
    assert float(last["loss"]) < float(first["loss"])


def test_chunked_loss_matches_full():
    """llama_loss_chunked streams head+xent over seq chunks (never
    materializing full f32 logits) — same math as llama_loss up to
    summation order: loss, metrics AND grads must agree.  Also covers
    a REAL multi-chunk split (seq 33 -> S-1 = 32 tiles n_chunks=8, so
    lax.map runs 8 chunks — the reshape/summation under test), plus
    the divisor fallback and a full Trainer step on the chunked path
    (sharded, jitted, mode= kwargs threading)."""

    import functools

    from tf_operator_tpu.models import llama_loss_chunked

    mesh = make_mesh({"dp": 8})
    rng = np.random.RandomState(0)
    ids = _ids(rng, 8, 33)
    batch = {"input_ids": ids}
    model = llama_tiny(vocab_size=VOCAB, max_len=64, mesh=mesh)
    tr = Trainer(
        model,
        TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
        mesh,
        llama_loss,
        batch,
        init_args=(ids,),
        shardings="logical",
    )
    key = jax.random.PRNGKey(0)
    lf, auxf = llama_loss(tr.state.params, tr.state, batch, key, train=False)
    lc, auxc = llama_loss_chunked(
        tr.state.params, tr.state, batch, key, train=False
    )
    np.testing.assert_allclose(float(lf), float(lc), rtol=1e-4)
    # divisor fallback: n_chunks=7 doesn't tile S-1=32 -> drops to 4
    lc7, _ = llama_loss_chunked(
        tr.state.params, tr.state, batch, key, train=False, n_chunks=7
    )
    np.testing.assert_allclose(float(lf), float(lc7), rtol=1e-4)
    np.testing.assert_allclose(
        float(auxf["metrics"]["token_accuracy"]),
        float(auxc["metrics"]["token_accuracy"]),
        rtol=1e-6,
    )
    gf = jax.grad(
        lambda p: llama_loss(p, tr.state, batch, key, train=False)[0]
    )(tr.state.params)
    gc = jax.grad(
        lambda p: llama_loss_chunked(p, tr.state, batch, key, train=False)[0]
    )(tr.state.params)
    for a, b in zip(jax.tree_util.tree_leaves(gf), jax.tree_util.tree_leaves(gc)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=2e-3, atol=2e-3,
        )
    # the chunked loss must drive a full (jitted, sharded) train step
    tr2 = Trainer(
        model,
        TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
        mesh,
        functools.partial(llama_loss_chunked, n_chunks=4),
        batch,
        init_args=(ids,),
        shardings="logical",
    )
    first = tr2.train_step(tr2.shard_batch(batch))
    for _ in range(5):
        last = tr2.train_step(tr2.shard_batch(batch))
    assert float(last["loss"]) < float(first["loss"])


@pytest.mark.parametrize("sp_impl", ["ring", "ulysses"])
def test_llama_sp_matches_no_sp(sp_impl):
    """RoPE + GQA must compose exactly with both sp schedules."""

    rng = np.random.RandomState(2)
    ids = _ids(rng, 8, 32)
    batch = {"input_ids": ids}
    losses = {}
    for label, shape in [("nosp", {"dp": 8}), ("sp", {"dp": 2, "sp": 4})]:
        mesh = make_mesh(shape)
        # ulysses needs heads_local % sp == 0 -> 4 heads over sp=4; GQA
        # k/v are repeated to n_heads before dispatch so this holds
        model = llama_tiny(
            vocab_size=VOCAB, max_len=32, mesh=mesh, sp_impl=sp_impl
        )
        tr = Trainer(
            model,
            TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
            mesh,
            llama_loss,
            batch,
            init_args=(ids,),
            shardings="logical",
            seed=7,
        )
        losses[label] = [
            float(tr.train_step(tr.shard_batch(batch))["loss"]) for _ in range(3)
        ]
    np.testing.assert_allclose(losses["nosp"], losses["sp"], rtol=2e-4, atol=2e-4)


def test_llama_tp_fsdp_training():
    """The 7B sharding config at tiny scale: fsdp x tp mesh."""

    mesh = make_mesh({"fsdp": 2, "tp": 2, "dp": 2})
    rng = np.random.RandomState(3)
    ids = _ids(rng, 4, 16)
    batch = {"input_ids": ids}
    model = llama_tiny(vocab_size=VOCAB, max_len=16, mesh=mesh)
    tr = Trainer(
        model,
        TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
        mesh,
        llama_loss,
        batch,
        init_args=(ids,),
        shardings="logical",
    )
    first = tr.train_step(tr.shard_batch(batch))
    for _ in range(4):
        last = tr.train_step(tr.shard_batch(batch))
    assert float(last["loss"]) < float(first["loss"])


class TestSlidingWindowModels:
    """Window attention at the model level: train, decode-equivalence,
    sp guard."""

    def test_windowed_llama_trains(self):
        mesh = make_mesh({"dp": 8})
        rng = np.random.RandomState(4)
        ids = _ids(rng, 8, 32)
        batch = {"input_ids": ids}
        model = llama_tiny(vocab_size=VOCAB, max_len=32, mesh=mesh, window=8)
        tr = Trainer(
            model,
            TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
            mesh,
            llama_loss,
            batch,
            init_args=(ids,),
            shardings="logical",
        )
        first = tr.train_step(tr.shard_batch(batch))
        for _ in range(4):
            last = tr.train_step(tr.shard_batch(batch))
        assert float(last["loss"]) < float(first["loss"])

    def test_windowed_decode_matches_full_recompute(self):
        """The decode cache's banded mask must agree with the training
        forward's windowed attention."""

        from tf_operator_tpu.models import generate

        model = llama_tiny(vocab_size=VOCAB, max_len=48, window=6)
        prompt = jnp.asarray(
            np.random.RandomState(0).randint(0, VOCAB, size=(2, 10)), jnp.int32
        )
        params = model.init(jax.random.PRNGKey(1), prompt)["params"]
        out = generate(model, params, prompt, max_new_tokens=12)

        ids = prompt
        for _ in range(12):
            logits = model.apply({"params": params}, ids)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ids))

    @pytest.mark.parametrize("sp_impl", ["ring", "ulysses"])
    def test_windowed_sp_matches_no_sp(self, sp_impl):
        """window x sequence parallelism: both schedules must train
        identically to the unsharded windowed model."""

        rng = np.random.RandomState(6)
        ids = _ids(rng, 8, 32)
        batch = {"input_ids": ids}
        losses = {}
        for label, shape in [("nosp", {"dp": 8}), ("sp", {"dp": 2, "sp": 4})]:
            mesh = make_mesh(shape)
            model = llama_tiny(
                vocab_size=VOCAB, max_len=32, mesh=mesh,
                sp_impl=sp_impl, window=8,
            )
            tr = Trainer(
                model,
                TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
                mesh,
                llama_loss,
                batch,
                init_args=(ids,),
                shardings="logical",
                seed=11,
            )
            losses[label] = [
                float(tr.train_step(tr.shard_batch(batch))["loss"])
                for _ in range(3)
            ]
        np.testing.assert_allclose(
            losses["nosp"], losses["sp"], rtol=2e-4, atol=2e-4
        )

    def test_bad_window_rejected(self):
        with pytest.raises(ValueError, match="window"):
            llama_tiny(vocab_size=VOCAB, window=0)


def test_window_on_encoder_rejected():
    from tf_operator_tpu.models import bert_tiny

    model = bert_tiny(vocab_size=VOCAB, window=8)
    ids = _ids(np.random.RandomState(0), 2, 16)
    with pytest.raises(NotImplementedError, match="causal"):
        model.init(jax.random.PRNGKey(0), ids)
