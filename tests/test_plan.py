"""Decision-core tests: the native C++ planner and the Python twin must
be indistinguishable (property-based equivalence), and the planner's
semantics must match the reference behaviors the reconciler tests pin.
"""

import pytest

try:
    from hypothesis import given, settings, strategies as st
except ImportError:  # boxes without hypothesis: property tests skip
    from tests.testutil import import_hypothesis_or_stubs

    given, settings, st = import_hypothesis_or_stubs()

from tf_operator_tpu import native
from tf_operator_tpu.api.types import (
    PodPhase,
    ReplicaType,
    RestartPolicy,
    SuccessPolicy,
)
from tf_operator_tpu.backend.objects import Pod
from tf_operator_tpu.controller import plan as planmod
from tf_operator_tpu.controller.plan import (
    ReplicaPlan,
    evaluate_success_py,
    plan_replica,
    plan_replica_py,
)
from tests.testutil import new_job

HAVE_NATIVE = native.available()

phases = st.sampled_from(list(PodPhase))
policies = st.sampled_from(list(RestartPolicy))
pod_obs = st.tuples(
    st.integers(min_value=0, max_value=12),
    phases,
    st.one_of(st.none(), st.integers(min_value=0, max_value=255)),
)


class TestPlanReplicaSemantics:
    def test_creates_missing_indices(self):
        p = plan_replica_py(3, RestartPolicy.NEVER, None, 0, [])
        assert p.create == [0, 1, 2]

    def test_scale_in_beyond_want(self):
        obs = [(0, PodPhase.RUNNING, None), (2, PodPhase.RUNNING, None)]
        p = plan_replica_py(1, RestartPolicy.NEVER, None, 0, obs)
        assert p.scale_in == [2] and p.create == []

    def test_exit_code_split(self):
        obs = [(0, PodPhase.FAILED, 1), (1, PodPhase.FAILED, 137)]
        p = plan_replica_py(2, RestartPolicy.EXIT_CODE, None, 0, obs)
        assert p.fatal == [(0, 1)] and p.restart == [(1, 137)]

    def test_backoff_budget_aborts_remaining(self):
        obs = [(0, PodPhase.FAILED, 137), (1, PodPhase.FAILED, 137)]
        p = plan_replica_py(3, RestartPolicy.ALWAYS, 1, 0, obs)
        assert p.restart == [(0, 137)]
        assert p.backoff_exceeded
        # index 2 create decision was aborted by the budget failure
        assert 2 not in p.create

    def test_first_pod_per_index_wins(self):
        obs = [(0, PodPhase.RUNNING, None), (0, PodPhase.FAILED, 1)]
        p = plan_replica_py(1, RestartPolicy.NEVER, None, 0, obs)
        assert p == ReplicaPlan()  # running slot[0]: nothing to do


@pytest.mark.skipif(not HAVE_NATIVE, reason="native planner unavailable")
class TestNativeEquivalence:
    @settings(max_examples=300, deadline=None)
    @given(
        want=st.integers(min_value=0, max_value=8),
        policy=policies,
        limit=st.one_of(st.none(), st.integers(min_value=0, max_value=5)),
        restarts=st.integers(min_value=0, max_value=6),
        observed=st.lists(pod_obs, max_size=16),
    )
    def test_plan_replica_matches_python(
        self, want, policy, limit, restarts, observed
    ):
        py = plan_replica_py(want, policy, limit, restarts, observed)
        nat = planmod.plan_replica(want, policy, limit, restarts, observed)
        assert planmod._native() is not None
        # native keeps scale-in duplicates in pod order; the executor
        # dedupes — compare as the executor sees them
        assert sorted(set(py.scale_in)) == sorted(set(nat.scale_in))
        py.scale_in = nat.scale_in = []
        assert py == nat

    @settings(max_examples=300, deadline=None)
    @given(
        data=st.data(),
        success=st.sampled_from(list(SuccessPolicy)),
    )
    def test_eval_success_matches_python(self, data, success):
        counts = {
            rt: data.draw(st.integers(min_value=0, max_value=3), label=rt.value)
            for rt in (
                ReplicaType.CHIEF,
                ReplicaType.PS,
                ReplicaType.WORKER,
                ReplicaType.EVALUATOR,
                ReplicaType.TPU_SLICE,
            )
        }
        if not any(counts.values()):
            counts[ReplicaType.WORKER] = 1
        job = new_job(
            "prop",
            chief=counts[ReplicaType.CHIEF],
            ps=counts[ReplicaType.PS],
            worker=counts[ReplicaType.WORKER],
            evaluator=counts[ReplicaType.EVALUATOR],
            tpu_slice=counts[ReplicaType.TPU_SLICE],
        )
        job.spec.success_policy = success
        pods_by_type = {}
        for rtype, n in counts.items():
            if n <= 0:
                continue
            pods = []
            npods = data.draw(
                st.integers(min_value=0, max_value=n), label=f"npods-{rtype.value}"
            )
            for i in range(npods):
                pod = Pod()
                pod.metadata.name = f"prop-{rtype.lower_name}-{i}"
                pod.metadata.labels = {
                    "tpujob.dist/replica-index": str(i),
                }
                pod.phase = data.draw(phases, label=f"phase-{rtype.value}-{i}")
                pods.append(pod)
            pods_by_type[rtype] = pods
        py = evaluate_success_py(job, pods_by_type)
        nat = planmod.evaluate_success(job, pods_by_type)
        assert py == nat

    def test_native_rejects_garbage(self):
        p = planmod._native()
        assert p is not None
        with pytest.raises(ValueError):
            p.plan_replica("want=x;policy=Never;limit=-;restarts=0;pods=")
        with pytest.raises(ValueError):
            p.eval_success("policy=Bogus;types=")

    def test_sync_decide_rejects_garbage(self):
        p = planmod._native()
        assert p is not None
        with pytest.raises(ValueError):
            p.sync_decide([2, 0, 0, 0, 0, 0], 16)  # bad version
        with pytest.raises(ValueError):
            p.sync_decide([1, 0, 0, 0, 0, 1, 99, 1, 0, 0], 32)  # bad type id
        with pytest.raises(ValueError):
            p.sync_decide([1, 0, 0, 0, 0, 1], 32)  # truncated type block


def _draw_pods_by_type(data, counts):
    pods_by_type = {}
    for rtype, n in counts.items():
        if n <= 0:
            continue
        pods = []
        npods = data.draw(
            st.integers(min_value=0, max_value=n + 1), label=f"npods-{rtype.value}"
        )
        for i in range(npods):
            pod = Pod()
            pod.metadata.name = f"prop-{rtype.lower_name}-{i}"
            # some pods unindexed, some beyond want (scale-in candidates)
            idx = data.draw(
                st.one_of(st.none(), st.integers(min_value=0, max_value=n + 1)),
                label=f"idx-{rtype.value}-{i}",
            )
            if idx is not None:
                pod.metadata.labels = {"tpujob.dist/replica-index": str(idx)}
            pod.phase = data.draw(phases, label=f"phase-{rtype.value}-{i}")
            if pod.phase is PodPhase.FAILED:
                pod.exit_code = data.draw(
                    st.one_of(st.none(), st.integers(min_value=0, max_value=255)),
                    label=f"exit-{rtype.value}-{i}",
                )
            pods.append(pod)
        pods_by_type[rtype] = pods
    return pods_by_type


@pytest.mark.skipif(not HAVE_NATIVE, reason="native planner unavailable")
class TestSyncDecideEquivalence:
    """The ONE-call batch ABI (syncdecide.cc) must be indistinguishable
    from the sequential Python twin — success verdict, every type's
    plan, and the restart budget threaded across types in spec order."""

    @settings(max_examples=300, deadline=None)
    @given(
        data=st.data(),
        success=st.sampled_from(list(SuccessPolicy)),
        policy=policies,
        limit=st.one_of(st.none(), st.integers(min_value=0, max_value=4)),
        restarts=st.integers(min_value=0, max_value=5),
    )
    def test_matches_python(self, data, success, policy, limit, restarts):
        counts = {
            rt: data.draw(st.integers(min_value=0, max_value=3), label=rt.value)
            for rt in (
                ReplicaType.CHIEF,
                ReplicaType.PS,
                ReplicaType.WORKER,
                ReplicaType.EVALUATOR,
                ReplicaType.TPU_SLICE,
            )
        }
        if not any(counts.values()):
            counts[ReplicaType.WORKER] = 1
        job = new_job(
            "prop",
            chief=counts[ReplicaType.CHIEF],
            ps=counts[ReplicaType.PS],
            worker=counts[ReplicaType.WORKER],
            evaluator=counts[ReplicaType.EVALUATOR],
            tpu_slice=counts[ReplicaType.TPU_SLICE],
        )
        job.spec.success_policy = success
        for spec in job.spec.replica_specs.values():
            spec.restart_policy = policy
        job.spec.run_policy.backoff_limit = limit
        job.status.restart_count = restarts
        pods_by_type = _draw_pods_by_type(data, counts)

        py = planmod.sync_decide_py(job, pods_by_type)
        nat = planmod.sync_decide(job, pods_by_type)
        assert planmod._native() is not None
        assert (py.succeeded, py.reason) == (nat.succeeded, nat.reason)
        assert set(py.plans) == set(nat.plans)
        for rtype, pplan in py.plans.items():
            nplan = nat.plans[rtype]
            assert sorted(set(pplan.scale_in)) == sorted(set(nplan.scale_in))
            pplan.scale_in = nplan.scale_in = []
            assert pplan == nplan, rtype

    def test_budget_threads_across_types(self):
        """A restart consumed by an earlier type exhausts the budget for
        a later type — exactly like the sequential executor."""

        job = new_job("thread", ps=1, worker=1)
        for spec in job.spec.replica_specs.values():
            spec.restart_policy = RestartPolicy.ALWAYS
        job.spec.run_policy.backoff_limit = 1
        pods_by_type = {}
        for rtype, name in (
            (ReplicaType.PS, "thread-ps-0"),
            (ReplicaType.WORKER, "thread-worker-0"),
        ):
            pod = Pod()
            pod.metadata.name = name
            pod.metadata.labels = {"tpujob.dist/replica-index": "0"}
            pod.phase = PodPhase.FAILED
            pod.exit_code = 137
            pods_by_type[rtype] = [pod]

        for decide in (planmod.sync_decide_py, planmod.sync_decide):
            d = decide(job, pods_by_type)
            # PS reconciles first (spec order) and takes the one restart
            assert d.plans[ReplicaType.PS].restart == [(0, 137)]
            assert not d.plans[ReplicaType.PS].backoff_exceeded
            # worker then finds the budget gone
            assert d.plans[ReplicaType.WORKER].restart == []
            assert d.plans[ReplicaType.WORKER].backoff_exceeded
