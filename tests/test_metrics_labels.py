"""Labeled-metrics exposition (ISSUE 5 tentpole, extended by ISSUE 6):
strict Prometheus text-format parse of EVERY line, label-value
escaping, per-family bucket config, labeled histogram families, the
counters snapshot the flight recorder diffs, and — the ISSUE 6 pin —
``# HELP`` / ``# TYPE`` metadata lines required for every family."""

import math
import re

from tf_operator_tpu.utils.metrics import (
    DEFAULT_BUCKETS,
    SLO_BUCKETS,
    Metrics,
)

#: one exposition sample line: metric name, optional {labels}, value.
#: Label values allow any char with " and \ escaped (\\, \", \n).
_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABEL = rf'{_NAME}="(?:[^"\\\n]|\\\\|\\"|\\n)*"'
_LINE = re.compile(
    rf"^({_NAME})(\{{{_LABEL}(?:,{_LABEL})*\}})? (-?[0-9.eE+-]+|[0-9.]+)$"
)
_COMMENT = re.compile(r"^# exemplar \S+ trace_id=\"[^\"]+\"$")
_META = re.compile(rf"^# (HELP|TYPE) ({_NAME}) (.+)$")
_TYPES = {"counter", "gauge", "histogram", "summary"}


def parse_strictly(text: str):
    """Every non-comment line must match the sample shape AND belong to
    a family that declared ``# HELP`` + ``# TYPE`` before its first
    sample; returns {line: value} for exact-line assertions."""

    out = {}
    helps, types = set(), {}
    for line in text.strip().splitlines():
        meta = _META.match(line)
        if meta:
            kind, fam, rest = meta.groups()
            if kind == "HELP":
                helps.add(fam)
            else:
                assert rest in _TYPES, f"bad # TYPE value: {line!r}"
                types[fam] = rest
            continue
        if _COMMENT.match(line):
            continue
        m = _LINE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        name = m.group(1)
        fam = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in types:
                fam = name[: -len(suffix)]
                break
        assert fam in types and fam in helps, (
            f"sample {line!r} has no preceding # HELP/# TYPE for "
            f"family {fam!r}"
        )
        out[line.rsplit(" ", 1)[0]] = float(m.group(3))
    return out


class TestLabeledExposition:
    def test_every_line_parses_strictly(self):
        m = Metrics()
        m.inc("jobs_total")
        m.inc("pods_total", replica_type="worker")
        m.set("depth", 3.0, queue="main")
        m.observe("startup", 1.0)
        m.observe_histogram("lat_seconds", 0.02)
        m.observe_histogram("lat_seconds", 0.2, model="llama", route="/generate")
        m.inc("errs_total", exemplar="tdeadbeef000001")
        parsed = parse_strictly(m.exposition())
        assert parsed["jobs_total"] == 1.0
        assert parsed['pods_total{replica_type="worker"}'] == 1.0
        assert parsed['depth{queue="main"}'] == 3.0
        # labeled histogram series: le merges with the label set
        assert (
            parsed['lat_seconds_bucket{le="+Inf",model="llama",route="/generate"}']
            == 1
        )
        assert parsed['lat_seconds_count{model="llama",route="/generate"}'] == 1
        # the unlabeled series of the same family co-exists
        assert parsed["lat_seconds_count"] == 1

    def test_label_values_escaped(self):
        m = Metrics()
        m.inc("odd_total", path='with "quotes" and \\slash\\ and \nnewline')
        text = m.exposition()
        parse_strictly(text)  # must still parse
        assert '\\"quotes\\"' in text
        assert "\\\\slash\\\\" in text
        assert "\\nnewline" in text
        assert "\nnewline" not in text.replace("\\nnewline", "")

    def test_histogram_labels_roundtrip_reads(self):
        m = Metrics()
        for v in (0.001, 0.01, 0.1):
            m.observe_histogram("ttft_seconds", v, model="a")
        m.observe_histogram("ttft_seconds", 5.0, model="b")
        assert m.histogram("ttft_seconds", model="a")["count"] == 3
        assert m.histogram("ttft_seconds", model="b")["count"] == 1
        assert m.histogram("ttft_seconds")["count"] == 0  # unlabeled distinct
        fam = m.histogram_family("ttft_seconds")
        assert {labels for labels in fam} == {
            (("model", "a"),), (("model", "b"),),
        }
        assert fam[(("model", "a"),)]["count"] == 3
        assert fam[(("model", "b"),)]["p50_le"] >= 5.0 or math.isinf(
            fam[(("model", "b"),)]["p50_le"]
        )

    def test_per_family_bucket_config(self):
        m = Metrics()
        m.set_buckets("slo_seconds", SLO_BUCKETS)
        m.observe_histogram("slo_seconds", 45.0, model="x")  # inside SLO tail
        m.observe_histogram("other_seconds", 45.0)  # default buckets
        text = m.exposition()
        assert 'slo_seconds_bucket{le="60.0",model="x"} 1' in text
        # default family has no 60s bucket: 45s lands in +Inf only
        assert 'other_seconds_bucket{le="60.0"}' not in text
        assert f'other_seconds_bucket{{le="{DEFAULT_BUCKETS[-1]}"}} 0' in text
        # explicit buckets at first observation win over both
        m.observe_histogram("explicit_seconds", 0.5, buckets=(1.0,))
        assert 'explicit_seconds_bucket{le="1.0"} 1' in m.exposition()

    def test_help_and_type_emitted_for_every_family(self):
        """ISSUE 6 satellite: every family gets # HELP and # TYPE, with
        the right TYPE per storage kind, before its first sample."""

        m = Metrics()
        m.inc("c_total")
        m.set("g_depth", 1.0)
        m.observe("s_latency", 0.5)
        m.observe_histogram("h_seconds", 0.1, phase="x")
        text = m.exposition()
        assert "# HELP c_total" in text
        assert "# TYPE c_total counter" in text
        assert "# TYPE g_depth gauge" in text
        assert "# TYPE s_latency summary" in text
        assert "# TYPE h_seconds histogram" in text
        # metadata precedes the family's first sample
        lines = text.splitlines()
        assert lines.index("# TYPE h_seconds histogram") < lines.index(
            'h_seconds_bucket{le="0.001",phase="x"} 0'
        )
        parse_strictly(text)  # the strict pin itself enforces coverage

    def test_describe_sets_help_text_and_escapes(self):
        m = Metrics()
        m.describe("c_total", "requests served\nsince boot \\ total")
        m.inc("c_total")
        text = m.exposition()
        assert "# HELP c_total requests served\\nsince boot \\\\ total" in text
        parse_strictly(text)

    def test_strict_parser_rejects_family_without_metadata(self):
        import pytest

        with pytest.raises(AssertionError, match="HELP"):
            parse_strictly("orphan_total 1\n")

    def test_histogram_exemplar_linkage(self):
        """ISSUE 11 satellite: ``observe_histogram`` records exemplars
        exactly like ``inc`` — a bad SLO quantile deep-links to a
        request's trace id — and the exposition stays strictly
        parseable with the ``# exemplar`` comment lines present."""

        m = Metrics()
        m.observe_histogram(
            "serve_ttft_seconds", 0.2, exemplar="tabc00000001", model="x"
        )
        assert m.exemplar("serve_ttft_seconds") == "tabc00000001"
        text = m.exposition()
        assert '# exemplar serve_ttft_seconds trace_id="tabc00000001"' \
            in text
        parsed = parse_strictly(text)
        # the exemplar kwarg is control, never a label key
        assert parsed['serve_ttft_seconds_count{model="x"}'] == 1
        assert "exemplar=" not in text
        # newest exemplar wins (the freshest reproduction is the one
        # an operator wants), and exemplar=None leaves the last intact
        m.observe_histogram(
            "serve_ttft_seconds", 0.4, exemplar="tabc00000002", model="x"
        )
        m.observe_histogram("serve_ttft_seconds", 0.1, model="x")
        assert m.exemplar("serve_ttft_seconds") == "tabc00000002"
        parse_strictly(m.exposition())

    def test_strict_parser_rejects_malformed_exemplar_comment(self):
        """The exemplar comment shape is part of the contract the
        dashboard's deep-links read — a malformed line must fail the
        strict parse, not slip through as an ignorable comment."""

        import pytest

        m = Metrics()
        m.inc("ok_total")
        good = m.exposition()
        parse_strictly(good)
        with pytest.raises(AssertionError):
            parse_strictly(good + "# exemplar missing_the_trace_id\n")

    def test_counters_snapshot_flat_keys(self):
        m = Metrics()
        m.inc("a_total")
        m.inc("b_total", 2.0, phase="x")
        m.set("g", 7.0)
        snap = m.counters_snapshot()
        assert snap["a_total"] == 1.0
        assert snap['b_total{phase="x"}'] == 2.0
        assert snap["g"] == 7.0


class TestLedgerSharedFamilies:
    def test_dispatch_and_sync_ledgers_share_exposition_shape(self):
        """Training and serving route into the SAME labeled-family
        shape: <prefix>_seconds{phase=...} (the ISSUE-5 'one
        exposition' requirement)."""

        from tf_operator_tpu.utils.metrics import (
            DispatchLedger,
            StepSyncLedger,
        )

        m = Metrics()
        led = DispatchLedger(metrics=m)
        with led.dispatch("step"):
            pass
        sync = StepSyncLedger(metrics=m)
        sync.record("data.load", 0.001)
        sync.resolve("window", [])
        parsed = parse_strictly(m.exposition())
        assert parsed['serving_dispatch_seconds_count{phase="step"}'] == 1
        assert parsed['train_sync_seconds_count{phase="data.load"}'] == 1
        assert parsed['train_sync_seconds_count{phase="window"}'] == 1
        assert parsed['train_sync_total{phase="data.load"}'] == 1.0


class TestMergedFamilies:
    """histogram_family_merged: the /slo read under multi-replica —
    and, since ISSUE 13, disaggregated — serving."""

    def test_replica_and_role_merge_to_one_row(self):
        """Series differing only in {replica} and {role} sum into ONE
        user-facing quantile row: a disaggregated fleet (prefill/
        decode roles across N replicas) still reports one p99 TTFT."""

        m = Metrics()
        m.observe_histogram("serve_ttft_seconds", 0.01, model="t",
                            mode="pool", replica="0", role="prefill")
        m.observe_histogram("serve_ttft_seconds", 0.02, model="t",
                            mode="pool", replica="1", role="decode")
        m.observe_histogram("serve_ttft_seconds", 0.03, model="t",
                            mode="pool", replica="2", role="decode")
        merged = m.histogram_family_merged("serve_ttft_seconds")
        assert len(merged) == 1
        (labels, summary), = merged.items()
        keys = {k for k, _ in labels}
        assert "replica" not in keys and "role" not in keys
        assert summary["count"] == 3

    def test_other_labels_keep_rows_distinct(self):
        """The merge drops ONLY replica/role — {tier} (and any other
        key) still splits rows, so /slo keeps per-tier quantiles."""

        m = Metrics()
        m.observe_histogram("serve_ttft_seconds", 0.01, model="t",
                            tier="interactive", replica="0", role="decode")
        m.observe_histogram("serve_ttft_seconds", 0.02, model="t",
                            tier="batch", replica="1", role="decode")
        merged = m.histogram_family_merged("serve_ttft_seconds")
        assert len(merged) == 2
        tiers = {dict(labels)["tier"] for labels in merged}
        assert tiers == {"interactive", "batch"}
