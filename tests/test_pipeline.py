"""Pipeline parallelism on the virtual mesh: the GPipe schedule must be
indistinguishable from running the stages sequentially — forward, grads,
and a training loop on a pp×dp mesh (SURVEY.md §2b PP row)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

# default-tier exclusion (pipeline schedule compiles); see README 'Tests run in two tiers'
pytestmark = pytest.mark.slow

from tf_operator_tpu.parallel import make_mesh, pipeline_apply, stack_stage_params

D = 16


def stage_fn(p, h):
    return h + jax.nn.relu(h @ p["w"] + p["b"])


def make_stages(n, seed=0):
    r = np.random.RandomState(seed)
    return [
        {
            "w": jnp.asarray(r.randn(D, D) * 0.3, jnp.float32),
            "b": jnp.asarray(r.randn(D) * 0.1, jnp.float32),
        }
        for _ in range(n)
    ]


def sequential(stages, x):
    for p in stages:
        x = stage_fn(p, x)
    return x


class TestPipelineCorrectness:
    @pytest.mark.parametrize("microbatches", [2, 4, 8])
    def test_forward_matches_sequential(self, microbatches):
        mesh = make_mesh({"pp": 4, "dp": 2})
        stages = make_stages(4)
        x = jnp.asarray(np.random.RandomState(1).randn(16, D), jnp.float32)
        with mesh:
            y = jax.jit(
                lambda sp, xx: pipeline_apply(
                    stage_fn, sp, xx, mesh,
                    microbatches=microbatches, batch_axes=("dp", "fsdp"),
                )
            )(stack_stage_params(stages), x)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(sequential(stages, x)), rtol=1e-5, atol=1e-5
        )

    def test_grads_match_sequential(self):
        mesh = make_mesh({"pp": 4, "dp": 2})
        stages = make_stages(4, seed=3)
        x = jnp.asarray(np.random.RandomState(2).randn(8, D), jnp.float32)

        def loss_pp(sp, xx):
            y = pipeline_apply(
                stage_fn, sp, xx, mesh, microbatches=4, batch_axes=("dp", "fsdp")
            )
            return (y**2).mean()

        def loss_seq(ps, xx):
            return (sequential(ps, xx) ** 2).mean()

        with mesh:
            g_pp = jax.jit(jax.grad(loss_pp))(stack_stage_params(stages), x)
        g_seq = stack_stage_params(jax.grad(loss_seq)(stages, x))
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            ),
            g_pp,
            g_seq,
        )

    def test_pp_only_mesh(self):
        """Works without a dp axis (batch replicated)."""

        mesh = make_mesh({"pp": 8})
        stages = make_stages(8, seed=5)
        x = jnp.asarray(np.random.RandomState(4).randn(4, D), jnp.float32)
        with mesh:
            y = jax.jit(
                lambda sp, xx: pipeline_apply(stage_fn, sp, xx, mesh, microbatches=2)
            )(stack_stage_params(stages), x)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(sequential(stages, x)), rtol=1e-5, atol=1e-5
        )

    def test_batch_must_divide(self):
        mesh = make_mesh({"pp": 4, "dp": 2})
        stages = make_stages(4)
        x = jnp.zeros((10, D))
        with pytest.raises(ValueError, match="divisible"):
            pipeline_apply(
                stage_fn, stack_stage_params(stages), x, mesh, microbatches=3
            )


class TestPipelineTraining:
    def test_loss_decreases_on_pp_dp_mesh(self):
        """End-to-end training step over pp×dp: pipelined forward,
        grads through the schedule, sgd — loss goes down."""

        mesh = make_mesh({"pp": 4, "dp": 2})
        stages = stack_stage_params(make_stages(4, seed=7))
        head = jnp.asarray(np.random.RandomState(8).randn(D, 4) * 0.1, jnp.float32)
        r = np.random.RandomState(9)
        x = jnp.asarray(r.randn(32, D), jnp.float32)
        labels = jnp.asarray(r.randint(0, 4, size=(32,)))
        tx = optax.sgd(0.1)
        params = {"stages": stages, "head": head}
        opt = tx.init(params)

        def loss_fn(p, xx, yy):
            h = pipeline_apply(
                stage_fn, p["stages"], xx, mesh,
                microbatches=4, batch_axes=("dp", "fsdp"),
            )
            logits = h @ p["head"]
            return optax.softmax_cross_entropy_with_integer_labels(logits, yy).mean()

        @jax.jit
        def step(p, o, xx, yy):
            loss, grads = jax.value_and_grad(loss_fn)(p, xx, yy)
            updates, o = tx.update(grads, o, p)
            return optax.apply_updates(p, updates), o, loss

        losses = []
        with mesh:
            for _ in range(20):
                params, opt, loss = step(params, opt, x, labels)
                losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8, losses


class TestPipelinedLM:
    """The transformer family over the pp axis (models/pipelined_lm.py):
    pipelined forward == sequential layers, and training converges on a
    pp×dp mesh."""

    def _build(self):
        import jax

        from tf_operator_tpu.models import PipelinedLM
        from tf_operator_tpu.models.transformer import TransformerConfig

        mesh = make_mesh({"pp": 4, "dp": 2})
        cfg = TransformerConfig(
            vocab_size=64, hidden=32, n_heads=2, head_dim=16,
            n_layers=4, mlp_dim=64, max_len=16,
        )
        model = PipelinedLM(cfg, mesh, microbatches=2)
        params = model.shard_params(model.init(jax.random.PRNGKey(0)))
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, size=(8, 16)))
        return mesh, model, params, ids

    def test_matches_sequential_layers(self):
        from tf_operator_tpu.models import lm_reference_apply

        mesh, model, params, ids = self._build()
        with mesh:
            logits_pp = jax.jit(model.apply)(params, ids)
        logits_ref = lm_reference_apply(model, params, ids)
        # bf16 activations: reduction-order noise only
        np.testing.assert_allclose(
            np.asarray(logits_pp), np.asarray(logits_ref), atol=2e-2, rtol=2e-2
        )

    def test_stage_params_live_on_pp(self):
        _, _, params, _ = self._build()
        leaf = jax.tree_util.tree_leaves(params["stages"])[0]
        assert "pp" in leaf.sharding.spec

    def test_training_converges(self):
        mesh, model, params, ids = self._build()
        tx = optax.adamw(1e-3)
        opt = tx.init(params)

        @jax.jit
        def step(p, o, batch):
            loss, g = jax.value_and_grad(model.loss)(p, batch)
            u, o = tx.update(g, o, p)
            return optax.apply_updates(p, u), o, loss

        losses = []
        with mesh:
            for _ in range(15):
                params, opt, loss = step(params, opt, ids)
                losses.append(float(loss))
        assert losses[-1] < losses[0] - 0.1, losses

    def test_layers_must_divide_stages(self):
        from tf_operator_tpu.models import PipelinedLM
        from tf_operator_tpu.models.transformer import TransformerConfig

        mesh = make_mesh({"pp": 4, "dp": 2})
        cfg = TransformerConfig(
            vocab_size=64, hidden=32, n_heads=2, head_dim=16,
            n_layers=3, mlp_dim=64, max_len=16,
        )
        with pytest.raises(ValueError, match="divisible"):
            PipelinedLM(cfg, mesh)


def test_pipelined_llama_blocks_train():
    """PP x modern blocks: rope + GQA + swiglu stages over pp=4, loss
    decreases and matches the sequential reference."""

    import numpy as np
    import optax

    from tf_operator_tpu.models import PipelinedLM, lm_reference_apply
    from tf_operator_tpu.models.transformer import TransformerConfig
    from tf_operator_tpu.parallel import make_mesh

    mesh = make_mesh({"pp": 4, "dp": 2})
    cfg = TransformerConfig(
        vocab_size=64, hidden=32, n_heads=4, head_dim=8,
        n_layers=4, mlp_dim=88, max_len=16,
        rope=True, attn_bias=False, n_kv_heads=2,
    )
    model = PipelinedLM(cfg, mesh, microbatches=2, activation="swiglu")
    params = model.shard_params(model.init(jax.random.PRNGKey(0)))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, size=(8, 16)))

    with mesh:
        logits_pp = jax.jit(model.apply)(params, ids)
    logits_ref = lm_reference_apply(model, params, ids)
    np.testing.assert_allclose(
        np.asarray(logits_pp), np.asarray(logits_ref), atol=2e-2, rtol=2e-2
    )
    # swiglu params really exist in the stage stacks
    assert "wi_gate" in str(jax.tree_util.tree_structure(params["stages"]))

    tx = optax.sgd(0.3)

    @jax.jit
    def step(p, o, batch):
        loss, grads = jax.value_and_grad(model.loss)(p, batch)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    with mesh:
        opt = tx.init(params)
        first = None
        for _ in range(8):
            params, opt, loss = step(params, opt, ids)
            first = float(loss) if first is None else first
    assert float(loss) < first
