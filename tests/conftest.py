"""Test harness config: force CPU JAX with 8 virtual devices.

Tests exercise multi-chip sharding semantics on a virtual CPU mesh
(SURVEY.md §4's rebuild mapping); the single real TPU chip is reserved for
bench.py and explicit @tpu-marked tests.  Must set flags before jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "tpu: needs the real TPU chip (excluded by default)")
    config.addinivalue_line("markers", "slow: long-running e2e test")


def pytest_collection_modifyitems(config, items):
    if os.environ.get("RUN_TPU_TESTS"):
        return
    skip_tpu = pytest.mark.skip(reason="real-TPU test; set RUN_TPU_TESTS=1")
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip_tpu)
