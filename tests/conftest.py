"""Test harness config: force CPU JAX with 8 virtual devices.

Tests exercise multi-chip sharding semantics on a virtual CPU mesh
(SURVEY.md §4's rebuild mapping); the single real TPU chip is reserved for
bench.py and explicit @tpu-marked tests.

Note: this box pins `JAX_PLATFORMS=axon` (TPU) via a sitecustomize that
overrides env-level platform selection, so the override must go through
jax.config *before* any backend initialisation — hence the eager jax
import here.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# RUN_TPU_TESTS=1 runs the @tpu-marked tests in a separate pytest
# invocation against the real chip — don't pin CPU there.
#: the shared warm store sessions SEED from and PUBLISH back to — but
#: never write in place (see _isolated_cache_dir)
_CACHE_BASE = "/tmp/tpujob-test-xla-cache"
_session_cache_dir = None


def _isolated_cache_dir() -> str:
    """Per-SESSION compile-cache dir, seeded from the shared base.

    The old design pointed every pytest run's XLA persistent cache at
    one shared /tmp dir; concurrent runs writing it in place corrupted
    SPMD executables twice (CHANGES.md PR 4 note: elastic NaNs,
    checkpoint snapshot drift — cache-deserialized programs computing
    wrong numerics).  Now each session compiles into its own fresh
    tmpdir — no two XLA processes ever write the same directory — and
    warmth survives two ways: the session dir is seeded by copying the
    base (~10 MB, milliseconds), and new entries publish back at
    session end via copy-to-temp + atomic os.replace (entries are
    content-keyed, so concurrent publishers are last-wins-identical).
    """

    import shutil
    import tempfile

    d = tempfile.mkdtemp(prefix="tpujob-xla-cache-")
    try:
        for name in os.listdir(_CACHE_BASE):
            src = os.path.join(_CACHE_BASE, name)
            if os.path.isfile(src):
                shutil.copy2(src, os.path.join(d, name))
    except OSError:
        pass  # no base yet: cold session, publishes the first warm set
    return d


def _publish_cache(session_dir: str) -> None:
    """Copy entries the session compiled into the shared base,
    atomically (temp file + os.replace), then drop the session dir."""

    import shutil

    try:
        os.makedirs(_CACHE_BASE, exist_ok=True)
        for name in os.listdir(session_dir):
            src = os.path.join(session_dir, name)
            dst = os.path.join(_CACHE_BASE, name)
            if not os.path.isfile(src) or os.path.exists(dst):
                continue
            tmp = os.path.join(_CACHE_BASE, f".tmp-{os.getpid()}-{name}")
            try:
                shutil.copy2(src, tmp)
                os.replace(tmp, dst)
            except OSError:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
    except OSError:
        pass
    shutil.rmtree(session_dir, ignore_errors=True)


if not os.environ.get("RUN_TPU_TESTS"):
    jax.config.update("jax_platforms", "cpu")
    # persistent compilation cache: the suite is dominated by XLA CPU
    # compiles on a cold container (a fresh image turned the 3-minute
    # default tier into 20+ minutes); cache them across runs.  Scoped
    # to CPU runs only so the real-chip tier always measures honest
    # compile times.  TPU_OPERATOR_TEST_CACHE overrides with a fixed
    # dir (no isolation/publish — the caller owns its lifecycle).
    cache_dir = os.environ.get("TPU_OPERATOR_TEST_CACHE")
    if cache_dir is None:
        cache_dir = _session_cache_dir = _isolated_cache_dir()
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "tpu: needs the real TPU chip (excluded by default)")
    config.addinivalue_line("markers", "slow: long-running e2e test")


def pytest_collection_modifyitems(config, items):
    if os.environ.get("RUN_TPU_TESTS"):
        return
    skip_tpu = pytest.mark.skip(reason="real-TPU test; set RUN_TPU_TESTS=1")
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip_tpu)


# ---------------------------------------------------------------------------
# Round-end suite record (VERDICT r5 next #8): every pytest session
# appends its tier's wall clock to benchmarks/SUITE_RECORD.json so the
# round record reports BOTH tiers, and benchmarks/check_tier_budget.py
# can fail the round when the slow tier blows its budget.
# ---------------------------------------------------------------------------

_session_t0 = None

#: extra key/value pairs tests merge into THIS session's tier entry in
#: SUITE_RECORD.json (via record_suite_extra below) — how the scheduler
#: contention soak publishes its decision counts so a silently-wedged
#: soak (zero admissions, zero preemptions) reddens the tier record
#: through benchmarks/check_tier_budget.py instead of passing quietly
_suite_extras = {}


def record_suite_extra(key: str, value) -> None:
    """Merge ``key: value`` into this pytest session's SUITE_RECORD
    tier entry (JSON-serialisable values only).  No-op effect when the
    session's tier is not recorded (targeted runs, ``all`` tier)."""

    _suite_extras[key] = value


def _session_tier(config) -> str:
    """tier1 = the default `-m 'not slow'` run; slow = a `-m slow`
    (or slow-including) run; anything else records as `all`."""

    expr = (config.getoption("-m", default="") or "").strip()
    if "not slow" in expr:
        return "tier1"
    if "slow" in expr:
        return "slow"
    return "all"


def pytest_sessionstart(session):
    global _session_t0
    import time

    _session_t0 = time.time()


def pytest_sessionfinish(session, exitstatus):
    import json
    import time

    if _session_cache_dir is not None:
        # publish this session's new compile-cache entries into the
        # shared base only when pytest ran to completion (0 = green,
        # 1 = test failures — both leave valid artifacts); an
        # interrupted/erroring session may hold partial writes
        if int(exitstatus) in (0, 1):
            _publish_cache(_session_cache_dir)
        else:
            import shutil

            shutil.rmtree(_session_cache_dir, ignore_errors=True)

    if _session_t0 is None or os.environ.get("TPUJOB_NO_SUITE_RECORD"):
        return
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks", "SUITE_RECORD.json",
    )
    record = {}
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, ValueError):
        pass
    tier = _session_tier(session.config)
    if tier == "all":
        # unmarked runs are overwhelmingly targeted local invocations
        # (`pytest tests/test_x.py`): recording them would rewrite a
        # COMMITTED benchmark file on every such run (perpetually
        # dirty trees, meaningless data) — only the round's real
        # tiers (`-m 'not slow'` / `-m slow`) are worth a record
        return
    collected = int(getattr(session, "testscollected", 0) or 0)
    prev = record.get(tier)
    if prev and collected < 0.5 * int(prev.get("collected", 0) or 0):
        # a targeted subset run (`pytest tests/test_x.py -m slow`) must
        # not overwrite the full-tier record — a 2s partial would mask
        # a budget violation the gate exists to catch
        return
    # the device cost plane's process compile counter (ISSUE 20):
    # every CompileLedger registration this session lands here, and
    # check_tier_budget.py reddens on a >25% regression against the
    # committed baseline — width-class fragmentation can't creep in
    try:
        from tf_operator_tpu.utils.costplane import process_compile_count

        _suite_extras.setdefault("compiles", process_compile_count())
    except Exception:
        pass
    record[tier] = {
        "wall_s": round(time.time() - _session_t0, 1),
        "exitstatus": int(exitstatus),
        "collected": collected,
        "when": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        **_suite_extras,
    }
    try:  # atomic-ish: a crashed writer must not corrupt the record
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except OSError:
        pass
