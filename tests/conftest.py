"""Test harness config: force CPU JAX with 8 virtual devices.

Tests exercise multi-chip sharding semantics on a virtual CPU mesh
(SURVEY.md §4's rebuild mapping); the single real TPU chip is reserved for
bench.py and explicit @tpu-marked tests.

Note: this box pins `JAX_PLATFORMS=axon` (TPU) via a sitecustomize that
overrides env-level platform selection, so the override must go through
jax.config *before* any backend initialisation — hence the eager jax
import here.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# RUN_TPU_TESTS=1 runs the @tpu-marked tests in a separate pytest
# invocation against the real chip — don't pin CPU there.
if not os.environ.get("RUN_TPU_TESTS"):
    jax.config.update("jax_platforms", "cpu")
    # persistent compilation cache: the suite is dominated by XLA CPU
    # compiles on a cold container (a fresh image turned the 3-minute
    # default tier into 20+ minutes); cache them across runs.  Scoped
    # to CPU runs only so the real-chip tier always measures honest
    # compile times.
    cache_dir = os.environ.get(
        "TPU_OPERATOR_TEST_CACHE", "/tmp/tpujob-test-xla-cache"
    )
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "tpu: needs the real TPU chip (excluded by default)")
    config.addinivalue_line("markers", "slow: long-running e2e test")


def pytest_collection_modifyitems(config, items):
    if os.environ.get("RUN_TPU_TESTS"):
        return
    skip_tpu = pytest.mark.skip(reason="real-TPU test; set RUN_TPU_TESTS=1")
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip_tpu)
