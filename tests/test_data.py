"""Input pipeline tests (VERDICT r2 item 3): on-disk datasets, grain
loaders with disjoint per-process shards, device prefetch, and a
learnability check proving the procedural data is real signal."""

import numpy as np
import pytest

from tf_operator_tpu.data import (
    NpySource,
    device_prefetch,
    ensure_imagenet_like,
    ensure_mnist,
    make_loader,
)


@pytest.fixture(scope="module")
def mnist_dir(tmp_path_factory):
    return ensure_mnist(str(tmp_path_factory.mktemp("data") / "mnist"), n=512)


class TestDataset:
    def test_generation_idempotent(self, mnist_dir):
        src = NpySource(mnist_dir)
        first = np.asarray(src[3]["image"])
        # second ensure with same meta is a no-op (same bytes)
        ensure_mnist(mnist_dir, n=512)
        assert np.array_equal(np.asarray(NpySource(mnist_dir)[3]["image"]), first)

    def test_shapes_and_dtypes(self, mnist_dir):
        src = NpySource(mnist_dir)
        assert len(src) == 512
        rec = src[0]
        assert rec["image"].shape == (28, 28, 1) and rec["image"].dtype == np.uint8
        assert rec["label"].dtype == np.int32

    def test_imagenet_like_shape(self, tmp_path):
        d = ensure_imagenet_like(str(tmp_path / "inet"), n=4, size=64)
        rec = NpySource(d)[0]
        assert rec["image"].shape == (64, 64, 3)


class TestSharding:
    def test_process_shards_disjoint_and_covering(self, mnist_dir):
        """The per-process shards must partition the dataset — this is
        what makes the global batch a true sample (no duplication)."""

        n_proc = 4
        seen = {}
        for pid in range(n_proc):
            loader = make_loader(
                mnist_dir, 8, process_id=pid, process_count=n_proc,
                shuffle=False, num_epochs=1,
            )
            # with shuffle off the records come in index order; identify
            # them by position via the sequential record count per shard
            count = sum(len(b["label"]) for b in loader)
            seen[pid] = count
        assert all(c == 512 // n_proc for c in seen.values())

        # identify actual record identity via a labels fingerprint:
        # different shards must not all be identical streams
        streams = []
        for pid in range(n_proc):
            loader = make_loader(
                mnist_dir, 8, process_id=pid, process_count=n_proc,
                shuffle=False, num_epochs=1,
            )
            streams.append(tuple(int(x) for b in loader for x in b["label"]))
        assert len(set(streams)) == n_proc

    def test_deterministic_with_seed(self, mnist_dir):
        def stream(seed):
            loader = make_loader(
                mnist_dir, 8, process_id=0, process_count=2, seed=seed,
                num_epochs=1,
            )
            return [tuple(int(x) for x in b["label"]) for b in loader]

        assert stream(7) == stream(7)
        assert stream(7) != stream(8)


class TestDevicePrefetch:
    def test_prefetch_yields_sharded_normalized(self, mnist_dir):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tf_operator_tpu.parallel import make_mesh

        mesh = make_mesh({"dp": min(2, len(jax.devices()))},
                         devices=jax.devices()[: min(2, len(jax.devices()))])
        sh = {
            "image": NamedSharding(mesh, P(("dp", "fsdp"), None, None, None)),
            "label": NamedSharding(mesh, P(("dp", "fsdp"))),
        }
        loader = make_loader(
            mnist_dir, 16, process_id=0, process_count=1, num_epochs=1
        )
        n = 0
        for b in device_prefetch(loader, sh, image_dtype=np.float32):
            assert b["image"].dtype == np.float32
            assert float(b["image"].max()) <= 1.0
            n += 1
        assert n == 512 // 16

    def test_normalize_on_device(self, mnist_dir):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tf_operator_tpu.parallel import make_mesh

        mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
        sh = {
            "image": NamedSharding(mesh, P(("dp", "fsdp"), None, None, None)),
            "label": NamedSharding(mesh, P(("dp", "fsdp"))),
        }
        loader = make_loader(
            mnist_dir, 16, process_id=0, process_count=1, num_epochs=1
        )
        b = next(
            iter(
                device_prefetch(
                    loader, sh, image_dtype=jnp.float32, normalize_on_device=True
                )
            )
        )
        assert b["image"].dtype == jnp.float32
        assert float(b["image"].max()) <= 1.0


@pytest.mark.slow
class TestLearnability:
    def test_mnist_accuracy_climbs(self, mnist_dir):
        """The procedural dataset carries real class signal: a CNN
        reaches far-above-chance accuracy within a few dozen steps of
        the real input pipeline."""

        import jax

        from tf_operator_tpu.models import MnistCNN
        from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
        from tf_operator_tpu.parallel.trainer import cross_entropy_loss

        mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
        loader = make_loader(
            mnist_dir, 64, process_id=0, process_count=1, num_epochs=None
        )
        example = None
        trainer = None
        accs = []
        for i, b in enumerate(
            device_prefetch(loader, _shardings(mesh), image_dtype=np.float32)
        ):
            if trainer is None:
                host = {
                    "image": np.asarray(b["image"]),
                    "label": np.asarray(b["label"]),
                }
                trainer = Trainer(
                    MnistCNN(),
                    TrainerConfig(optimizer="sgd", learning_rate=0.2),
                    mesh,
                    cross_entropy_loss,
                    host,
                )
            m = trainer.train_step(dict(b))
            accs.append(float(m["accuracy"]))
            if i >= 60:
                break
        assert np.mean(accs[-10:]) > 0.5, accs[-10:]  # chance = 0.1


def _shardings(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return {
        "image": NamedSharding(mesh, P(("dp", "fsdp"), None, None, None)),
        "label": NamedSharding(mesh, P(("dp", "fsdp"))),
    }


class TestTextCorpus:
    """Byte-level text data path (data/text.py): idempotent generation,
    disjoint shards, decode round-trip, learnable signal."""

    @pytest.fixture(scope="class")
    def text_dir(self, tmp_path_factory):
        from tf_operator_tpu.data import ensure_text

        return ensure_text(
            str(tmp_path_factory.mktemp("data") / "text"),
            n_chars=1 << 16, seq_len=64,
        )

    def test_idempotent_and_decodable(self, text_dir):
        import os

        from tf_operator_tpu.data import decode_bytes, ensure_text
        from tf_operator_tpu.data.text import TextWindowSource

        mtime = os.path.getmtime(os.path.join(text_dir, "tokens.npy"))
        ensure_text(text_dir, n_chars=1 << 16, seq_len=64)  # no rewrite
        assert os.path.getmtime(os.path.join(text_dir, "tokens.npy")) == mtime
        src = TextWindowSource(text_dir)
        assert len(src) == (1 << 16) // 64
        txt = decode_bytes(src[0]["input_ids"])
        assert " the " in txt  # grammar text, not noise

    def test_shards_disjoint(self, text_dir):
        from tf_operator_tpu.data import as_lm_batches, make_text_loader
        from tf_operator_tpu.data.text import TextWindowSource

        n_proc, per = 4, 8
        seen = set()
        for pid in range(n_proc):
            loader = make_text_loader(
                text_dir, per, process_id=pid, process_count=n_proc,
                shuffle=False, num_epochs=1,
            )
            for batch in as_lm_batches(loader):
                assert batch["input_ids"].dtype == np.int32
                for row in batch["input_ids"]:
                    key = row.tobytes()
                    assert key not in seen  # no duplication across shards
                    seen.add(key)
        # shards cover most of the dataset (drop_remainder trims tails)
        assert len(seen) >= (len(TextWindowSource(text_dir)) // per // n_proc) * per * n_proc * 0.9

    def test_byte_lm_learns(self, text_dir):
        """Loss must fall far below the uniform-bytes floor ln(256)."""

        from tf_operator_tpu.data import as_lm_batches, make_text_loader
        from tf_operator_tpu.models import llama_tiny, llama_loss
        from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

        mesh = make_mesh({"dp": 8})
        loader = make_text_loader(
            text_dir, 16, process_id=0, process_count=1, num_epochs=None
        )
        batches = as_lm_batches(loader)
        first = next(batches)
        tr = Trainer(
            llama_tiny(vocab_size=256, max_len=64, mesh=mesh),
            TrainerConfig(learning_rate=3e-3, warmup_steps=5),
            mesh,
            llama_loss,
            first,
            init_args=(first["input_ids"],),
            shardings="logical",
        )
        loss = None
        for _ in range(40):
            loss = float(tr.train_step(tr.shard_batch(next(batches)))["loss"])
        assert loss < 3.0, loss  # uniform floor is ~5.55
