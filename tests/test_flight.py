"""Flight recorder (utils/flight.py): ring bounds, dump determinism,
and the attach points (tracer / logger / metrics deltas)."""

import io
import json
import logging

from tf_operator_tpu.utils.flight import FlightRecorder
from tf_operator_tpu.utils.metrics import Metrics
from tf_operator_tpu.utils.trace import Tracer


def dump_records(rec):
    buf = io.StringIO()
    rec.dump(fileobj=buf)
    return [json.loads(line) for line in buf.getvalue().strip().splitlines()]


class TestRings:
    def test_span_ring_bounded_oldest_dropped(self):
        rec = FlightRecorder(max_spans=4)
        for i in range(10):
            rec.record_span({"name": f"s{i}", "traceId": "t", "duration": 0.0})
        records = [r for r in dump_records(rec) if r["type"] == "span"]
        assert [r["name"] for r in records] == ["s6", "s7", "s8", "s9"]

    def test_log_ring_bounded(self):
        rec = FlightRecorder(max_logs=3)
        for i in range(7):
            rec.record_log("INFO", "t", f"m{i}")
        logs = [r for r in dump_records(rec) if r["type"] == "log"]
        assert [r["message"] for r in logs] == ["m4", "m5", "m6"]

    def test_dump_order_deterministic(self):
        """meta, then spans, then logs, then metric snapshots — two
        dumps with no intervening activity differ only in the meta
        record's wall clock and prior-dump counter."""

        rec = FlightRecorder()
        rec.record_span({"name": "a", "traceId": "t"})
        rec.record_log("WARN", "x", "boom")
        a = dump_records(rec)
        b = dump_records(rec)
        assert [r["type"] for r in a] == ["meta", "span", "log"]
        strip = lambda rs: [  # noqa: E731
            {k: v for k, v in r.items() if k not in ("unix", "priorDumps")}
            for r in rs
        ]
        assert strip(a) == strip(b)

    def test_dump_to_path_and_reason(self, tmp_path):
        rec = FlightRecorder()
        rec.record_log("INFO", "t", "hello")
        path = rec.dump(path=str(tmp_path / "f.jsonl"), reason="test")
        lines = [json.loads(x) for x in open(path)]
        assert lines[0]["type"] == "meta" and lines[0]["reason"] == "test"
        assert lines[1]["message"] == "hello"


class TestAttachPoints:
    def test_tracer_attach_captures_finished_spans_and_chains(self):
        seen = []
        tracer = Tracer(seed=3)
        tracer.on_finish = seen.append  # pre-existing sink must survive
        rec = FlightRecorder()
        rec.attach_tracer(tracer)
        with tracer.span("op.one"):
            pass
        spans = [r for r in dump_records(rec) if r["type"] == "span"]
        assert [s["name"] for s in spans] == ["op.one"]
        assert [s.name for s in seen] == ["op.one"]

    def test_logger_attach_captures_fielded_records(self):
        rec = FlightRecorder()
        log = logging.getLogger("tpujob-flight-test")
        log.setLevel(logging.INFO)
        rec.attach_logger(log)
        log.warning("stalled", extra={"fields": {"job": "ns/j"}})
        logs = [r for r in dump_records(rec) if r["type"] == "log"]
        assert logs[0]["level"] == "WARNING"
        assert logs[0]["fields"] == {"job": "ns/j"}

    def test_metric_deltas_between_snapshots(self):
        m = Metrics()
        rec = FlightRecorder()
        rec.attach_metrics(m)
        m.inc("x_total", 3.0)
        first = rec.snapshot_metrics("boot")
        assert first == {"x_total": 3.0}
        m.inc("x_total")
        m.inc("y_total", phase="p")
        delta = rec.snapshot_metrics("later")
        assert delta == {"x_total": 1.0, 'y_total{phase="p"}': 1.0}
        snaps = [r for r in dump_records(rec) if r["type"] == "metrics"]
        assert [s["label"] for s in snaps] == ["boot", "later"]

    def test_dump_text_matches_jsonl(self):
        rec = FlightRecorder()
        rec.record_log("INFO", "t", "x")
        text = rec.dump_text()
        assert len(text.strip().splitlines()) == 2
        for line in text.strip().splitlines():
            json.loads(line)
