"""Async checkpointing (parallel/checkpoint.py, ISSUE 4): the save
path runs off the step loop — device snapshot + background writer —
without changing WHAT lands on disk.

Pins:
- async (wait=False, drained later) and sync (wait=True) saves are
  BYTE-identical at the payload level: every restored array's raw
  bytes (dtype + tobytes) match, and the structural metadata files
  (_METADATA, _sharding) match byte-for-byte.  File-level identity is
  unattainable on purpose-built grounds: ocdbt names its chunk files
  with write uuids, so even two SYNC saves of the same state differ in
  file names (measured — see the probe note in
  test_async_and_sync_saves_byte_identical);
- a restore issued while a save is mid-flight WAITS for it (sees the
  new step, not the previous one);
- the in-flight budget bounds queued snapshots (save #budget+1 joins
  the oldest writer first — correctness assert: everything durable);
- the snapshot really is donation-proof: training continues (donating
  the live state) while the writer fetches, and the artifact matches
  the state AT save time, not the advanced one;
- a background write failure surfaces on the next checkpointer call.
"""

import hashlib
import pathlib

import numpy as np
import pytest

# default-tier exclusion (trainer + checkpoint compiles); see README
# 'Tests run in two tiers'
pytestmark = pytest.mark.slow

import jax
import jax.numpy as jnp

from tf_operator_tpu.models import MnistCNN
from tf_operator_tpu.parallel import (
    Trainer,
    TrainerCheckpointer,
    TrainerConfig,
    make_mesh,
)
from tf_operator_tpu.parallel.trainer import cross_entropy_loss

@pytest.fixture(autouse=True)
def _no_persistent_compile_cache():
    """These tests pin BYTE-level checkpoint correctness, and this
    container's persistent XLA compilation cache corrupts re-loaded
    SPMD executables (measured 2026-08-03: a second same-shape trainer
    whose programs come off the cache produces a numerically different
    trajectory — same family of platform lies as hard_sync's,
    PROFILE.md "timing honesty"; also the pre-existing
    test_elastic NaN flake).  Compile fresh, in-memory only, for the
    duration of this module's tests."""

    prev = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    jax.config.update("jax_enable_compilation_cache", prev)


def _batch(n=8):
    r = np.random.RandomState(0)
    return {
        "image": jnp.asarray(r.rand(n, 28, 28, 1), jnp.float32),
        "label": jnp.asarray(r.randint(0, 10, size=(n,))),
    }


def _trainer():
    batch = _batch()
    mesh = make_mesh({"dp": 2, "fsdp": 2}, devices=jax.devices()[:4])
    tr = Trainer(
        MnistCNN(), TrainerConfig(optimizer="sgd", learning_rate=0.05),
        mesh, cross_entropy_loss, batch, seed=0,
    )
    return tr, tr.shard_batch(batch)


def _digests(root):
    out = {}
    for p in sorted(pathlib.Path(root).rglob("*")):
        if p.is_file():
            out[str(p.relative_to(root))] = hashlib.sha256(
                p.read_bytes()
            ).hexdigest()
    return out


class TestAsyncSave:
    def test_async_and_sync_saves_byte_identical(self, tmp_path):
        """Payload-level byte identity.  (File-level identity cannot be
        the bar: ocdbt names chunk files with write uuids, so two SYNC
        saves of the same state already differ in chunk file names —
        measured on this container.  What async must not change is the
        DATA: raw bytes of every restored array, and the structural
        _METADATA/_sharding files.)"""

        tr, sb = _trainer()
        for _ in range(3):
            tr.train_step(sb)

        ck_sync = TrainerCheckpointer(str(tmp_path / "sync"))
        assert ck_sync.save(tr, wait=True) == 3
        ck_sync.close()

        ck_async = TrainerCheckpointer(str(tmp_path / "async"))
        assert ck_async.save(tr, wait=False) == 3
        ck_async.wait()
        ck_async.close()

        # restore both artifacts through the public path and compare
        # every leaf's RAW BYTES
        trees = []
        for d in ("sync", "async"):
            t2, _ = _trainer()
            assert TrainerCheckpointer(str(tmp_path / d)).restore_latest(t2) == 3
            trees.append(jax.device_get(t2.state))
        # leaf-wise comparison (treedefs differ benignly: TrainState's
        # static aux carries each trainer's own bound apply_fn)
        a_leaves = jax.tree_util.tree_leaves(trees[0])
        b_leaves = jax.tree_util.tree_leaves(trees[1])
        assert len(a_leaves) == len(b_leaves)
        assert a_leaves, "empty artifact"
        for x, y in zip(a_leaves, b_leaves):
            xa, ya = np.asarray(x), np.asarray(y)
            assert xa.dtype == ya.dtype and xa.shape == ya.shape
            assert xa.tobytes() == ya.tobytes()
        # every file present in BOTH artifacts matches byte-for-byte
        # except the orbax bookkeeping that embeds timestamps/uuids
        da, db = _digests(tmp_path / "sync"), _digests(tmp_path / "async")
        common = set(da) & set(db)
        assert common, "no common artifact files"
        skip = {"_CHECKPOINT_METADATA", "manifest.ocdbt"}
        diffs = [
            k for k in common
            if da[k] != db[k] and pathlib.PurePath(k).name not in skip
        ]
        assert not diffs, f"common artifact files differ: {diffs}"

    def test_snapshot_survives_continued_training(self, tmp_path):
        """The step loop donates the live state buffers every step; the
        writer must be reading an independent device copy.  Train PAST
        the save point before draining, then restore and compare
        against params captured at save time."""

        tr, sb = _trainer()
        for _ in range(2):
            tr.train_step(sb)
        at_save = jax.device_get(
            jax.tree_util.tree_map(lambda x: x, tr.state.params)
        )

        ck = TrainerCheckpointer(str(tmp_path / "ck"))
        assert ck.save(tr, wait=False) == 2
        for _ in range(4):                      # donates the live state
            tr.train_step(sb)
        tr.train_steps(sb, 4)                   # the fused path donates too
        ck.wait()

        tr2, _ = _trainer()
        assert TrainerCheckpointer(str(tmp_path / "ck")).restore_latest(tr2) == 2
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            at_save,
            jax.device_get(tr2.state.params),
        )
        ck.close()

    def test_restore_mid_flight_waits_for_save(self, tmp_path):
        tr, sb = _trainer()
        ck = TrainerCheckpointer(str(tmp_path / "ck"))
        tr.train_step(sb)
        ck.save(tr, wait=True)                  # step 1 durable
        for _ in range(2):
            tr.train_step(sb)
        ck.save(tr, wait=False)                 # step 3 mid-flight
        tr2, _ = _trainer()
        # restore through the SAME checkpointer must drain the pending
        # write first — step 3, not step 1
        assert ck.restore_latest(tr2) == 3
        assert int(tr2.state.step) == 3
        ck.close()

    def test_in_flight_budget_bounds_and_preserves_all_saves(self, tmp_path):
        tr, sb = _trainer()
        ck = TrainerCheckpointer(
            str(tmp_path / "ck"), max_to_keep=8, max_in_flight=2
        )
        steps = []
        for _ in range(4):
            tr.train_step(sb)
            steps.append(ck.save(tr, wait=False))
            assert len(ck._in_flight) <= 2
        ck.wait()
        assert not ck._in_flight
        assert ck.manager.latest_step() == steps[-1] == 4
        assert set(ck.manager.all_steps()) == set(steps)
        ck.close()

    def test_background_failure_surfaces_on_next_call(self, tmp_path):
        tr, sb = _trainer()
        tr.train_step(sb)
        ck = TrainerCheckpointer(str(tmp_path / "ck"))

        def boom(*a, **kw):
            raise OSError("disk gone")

        ck.manager.save = boom
        ck.save(tr, wait=False)
        with pytest.raises(RuntimeError, match="async checkpoint save"):
            ck.wait()
        ck.manager.close()

    def test_wait_true_matches_legacy_sync_contract(self, tmp_path):
        """save(wait=True) returns with the checkpoint durable and
        restorable — the tests/shutdown contract the examples rely on."""

        tr, sb = _trainer()
        for _ in range(3):
            tr.train_step(sb)
        ck = TrainerCheckpointer(str(tmp_path / "ck"))
        assert ck.save(tr, wait=True) == 3
        assert ck.manager.latest_step() == 3
        tr2, _ = _trainer()
        assert TrainerCheckpointer(str(tmp_path / "ck")).restore_latest(tr2) == 3
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            jax.device_get(tr.state.params),
            jax.device_get(tr2.state.params),
        )
        ck.close()
