"""Fleet telemetry plane (ISSUE 15): pod-side exporters, operator
federation, staleness honesty, and cross-process trace stitching.

Fast tier: the exporter's HTTP surface, the exposition parser
round-trip, federation merge semantics per metric kind (counters
last-seen, gauges instantaneous, histograms bucket-summed), the
TTL sweep, trace folding dedup, the reconciler's injection contract,
and the checkpoint-age rebind (the PR-6 process-scope gap, closed).

Slow tier (the e2e pin): a REAL subprocess trainer pod under kubesim
serves /metrics over HTTP, the scraper federates its
``train_window_steps_per_second`` + ``train_dcn_bytes_total{fabric=}``
into operator /federate, ``tpujob describe`` Health: shows per-pod
rows, the stock checkpoint-age rule fires from the wedged pod's
federated stamp, and ONE trace id links reconcile→pod train spans at
/traces/<id>.
"""

import ast
import io
import json
import os
import pathlib
import sys
import time
import urllib.request

import pytest

import tf_operator_tpu
from tests.testutil import new_job
from tf_operator_tpu.api.types import (
    ANNOTATION_TELEMETRY_PORT,
    LABEL_JOB_NAME,
    LABEL_REPLICA_INDEX,
    LABEL_REPLICA_TYPE,
    PodPhase,
)
from tf_operator_tpu.backend.objects import Pod
from tf_operator_tpu.bootstrap.tpu_env import (
    ENV_PARENT_SPAN_ID,
    ENV_TELEMETRY_PORT,
    ENV_TRACE_ID,
)
from tf_operator_tpu.controller.telemetry import (
    FEDERATED_LABELS,
    ScrapeTarget,
    TelemetryScraper,
    parse_exposition,
    pods_to_targets,
)
from tf_operator_tpu.runtime.telemetry import (
    PodTelemetryServer,
    maybe_start_from_env,
    trace_context_from_env,
)
from tf_operator_tpu.utils.metrics import Metrics
from tf_operator_tpu.utils.trace import Tracer

PKG_ROOT = pathlib.Path(tf_operator_tpu.__file__).parent


def make_pod(
    name="j-worker-0", job="j", rtype="WORKER", index="0", port=None,
    phase=PodPhase.RUNNING, ns="default", slice_id=None,
):
    pod = Pod()
    pod.metadata.name = name
    pod.metadata.namespace = ns
    pod.metadata.labels = {
        LABEL_JOB_NAME: job,
        LABEL_REPLICA_TYPE: rtype,
        LABEL_REPLICA_INDEX: index,
    }
    if port is not None:
        pod.metadata.annotations = {ANNOTATION_TELEMETRY_PORT: str(port)}
    pod.phase = phase
    if slice_id is not None:
        from tf_operator_tpu.api.types import Container

        pod.containers = [
            Container(env={"MEGASCALE_SLICE_ID": str(slice_id)})
        ]
    return pod


class TestPodTelemetryServer:
    def test_serves_metrics_traces_flight_and_healthz(self):
        m, t = Metrics(), Tracer(seed=3)
        m.inc("train_dcn_bytes_total", 512.0, fabric="dcn")
        with t.span("train unit"):
            pass
        srv = PodTelemetryServer(metrics=m, tracer=t).start()
        try:
            def get(route):
                with urllib.request.urlopen(srv.url + route, timeout=5) as r:
                    return r.read().decode()

            assert get("/healthz").startswith("ok")
            exposition = get("/metrics")
            assert 'train_dcn_bytes_total{fabric="dcn"} 512.0' in exposition
            spans = [json.loads(l) for l in get("/traces").splitlines() if l]
            assert any(s["name"] == "train unit" for s in spans)
            flight = get("/debug/flightrecorder").splitlines()
            assert json.loads(flight[0])["type"] == "meta"
            with pytest.raises(urllib.error.HTTPError):
                get("/nope")
        finally:
            srv.stop()

    def test_maybe_start_from_env_is_off_without_env(self):
        # library users: no env, no server, no port bind
        assert maybe_start_from_env(environ={}) is None
        assert maybe_start_from_env(environ={ENV_TELEMETRY_PORT: "0"}) is None
        assert maybe_start_from_env(environ={ENV_TELEMETRY_PORT: "x"}) is None

    def test_trace_context_from_env(self):
        assert trace_context_from_env(environ={}) == (None, None)
        env = {ENV_TRACE_ID: "tabc", ENV_PARENT_SPAN_ID: "sdef"}
        assert trace_context_from_env(environ=env) == ("tabc", "sdef")


class TestExpositionParser:
    def test_round_trip_all_kinds(self):
        m = Metrics()
        m.inc("c_total", 7.0, client="api", error='we"ird\nname')
        m.inc("c_total", 1.0)
        m.set("g_level", 0.75, model="llama-tiny")
        m.observe_histogram("h_seconds", 0.03, phase="window")
        m.observe_histogram("h_seconds", 9.0, phase="window")
        p = parse_exposition(m.exposition())
        assert p["counters"][("c_total", (("client", "api"), ("error", 'we"ird\nname')))] == 7.0
        assert p["counters"][("c_total", ())] == 1.0
        assert p["gauges"][("g_level", (("model", "llama-tiny"),))] == 0.75
        bks, counts, total, n = p["histograms"][
            ("h_seconds", (("phase", "window"),))
        ]
        assert n == 2 and abs(total - 9.03) < 1e-9
        # per-bucket (de-cumulated) counts sum to the series count
        assert sum(counts) == 2 and len(counts) == len(bks) + 1

    def test_garbage_lines_are_skipped(self):
        p = parse_exposition("not metrics\n# HELP x y\nfoo{broken 3\n")
        assert p["counters"] == {} and p["gauges"] == {}


class TestTargetDiscovery:
    def test_running_annotated_pods_become_targets(self):
        pods = [
            make_pod(port=1234),
            make_pod(name="j-worker-1", index="1"),  # no annotation
            make_pod(name="j-worker-2", index="2", port=5, phase=PodPhase.PENDING),
        ]
        (t,) = pods_to_targets(pods)
        assert t.job == "default/j" and t.replica == "worker-0"
        assert t.url == "http://127.0.0.1:1234"
        assert set(t.labels) == set(FEDERATED_LABELS)

    def test_slice_label_comes_from_megascale_env(self):
        pod = make_pod(rtype="tpuslice", port=99, slice_id=1)
        (t,) = pods_to_targets([pod])
        assert t.slice_id == "1"
        assert t.labels["slice"] == "1"
        assert t.replica_type == "tpuslice"


class TestFederation:
    """Merge semantics per metric kind, against a live in-process
    exporter (the HTTP path is real; only the pod process is not)."""

    def setup_method(self):
        self.pod_m = Metrics()
        self.pod_t = Tracer(seed=11)
        self.srv = PodTelemetryServer(
            metrics=self.pod_m, tracer=self.pod_t
        ).start()
        self.pod = make_pod(port=self.srv.port)
        self.op_m = Metrics()
        self.op_t = Tracer(seed=12)
        self.scraper = TelemetryScraper(
            metrics=self.op_m, tracer=self.op_t, stale_after=5.0
        )
        self.scraper.attach(lambda: [self.pod])

    def teardown_method(self):
        self.srv.stop()

    def fed(self, **extra):
        return {
            "job": "default/j", "replica_type": "worker",
            "replica_index": "0", "slice": "", **extra,
        }

    def test_gauges_are_instantaneous(self):
        self.pod_m.set("train_window_steps_per_second", 10.0)
        assert self.scraper.scrape_once() == 1
        assert self.op_m.gauge(
            "train_window_steps_per_second", **self.fed()
        ) == 10.0
        self.pod_m.set("train_window_steps_per_second", 4.0)
        self.scraper.scrape_once()
        assert self.op_m.gauge(
            "train_window_steps_per_second", **self.fed()
        ) == 4.0

    def test_counters_are_last_seen_cumulative(self):
        self.pod_m.inc("train_dcn_bytes_total", 100.0, fabric="dcn")
        self.scraper.scrape_once()
        self.pod_m.inc("train_dcn_bytes_total", 20.0, fabric="dcn")
        self.scraper.scrape_once()
        self.scraper.scrape_once()  # idempotent re-scrape: no double count
        assert self.op_m.counter(
            "train_dcn_bytes_total", **self.fed(fabric="dcn")
        ) == 120.0

    def test_counter_reset_on_pod_restart_reseeds(self):
        self.pod_m.inc("steps_total", 50.0)
        self.scraper.scrape_once()
        # simulate the pod restarting: its cumulative value drops
        with self.pod_m._lock:
            self.pod_m._counters.clear()
        self.pod_m.inc("steps_total", 5.0)
        self.scraper.scrape_once()
        assert self.op_m.counter("steps_total", **self.fed()) == 55.0

    def test_pod_recreated_on_new_port_does_not_double_count(self):
        """A deleted+recreated pod keeps its federated labels but gets
        a fresh port; the old series must be cleared, not stacked on —
        the federated counter is the NEW pod's last-seen value."""

        self.pod_m.inc("steps_total", 100.0)
        self.scraper.scrape_once()
        assert self.op_m.counter("steps_total", **self.fed()) == 100.0
        # recreate: same replica identity, fresh registry, new port
        new_m = Metrics()
        new_m.inc("steps_total", 5.0)
        new_srv = PodTelemetryServer(metrics=new_m, tracer=Tracer(seed=13)).start()
        try:
            self.pod.metadata.annotations[ANNOTATION_TELEMETRY_PORT] = str(
                new_srv.port
            )
            self.scraper.scrape_once()
            assert self.op_m.counter("steps_total", **self.fed()) == 5.0
        finally:
            new_srv.stop()

    def test_histograms_bucket_sum_into_fleet_quantiles(self):
        self.pod_m.observe_histogram("train_sync_seconds", 0.02, phase="window")
        self.scraper.scrape_once()
        self.pod_m.observe_histogram("train_sync_seconds", 0.3, phase="window")
        self.scraper.scrape_once()
        fam = self.op_m.histogram_family_merged(
            "train_sync_seconds",
            drop=("replica_type", "replica_index", "slice", "job"),
        )
        (summary,) = [
            v for k, v in fam.items() if dict(k).get("phase") == "window"
        ]
        assert summary["count"] == 2
        assert abs(summary["sum"] - 0.32) < 1e-9

    def test_federate_text_serves_decorated_series(self):
        self.pod_m.set("train_window_steps_per_second", 2.0)
        self.scraper.scrape_once()
        text = self.scraper.federate_text()
        assert 'job="default/j"' in text
        assert 'replica_type="worker"' in text
        assert "telemetry_scrape_age_seconds" in text
        # the federate body parses as an exposition (the contract)
        parsed = parse_exposition(text)
        assert parsed["gauges"]

    def test_scrape_failure_honesty_and_ttl_sweep(self):
        self.pod_m.set("train_window_steps_per_second", 3.0)
        now = time.time()
        assert self.scraper.scrape_once(now) == 1
        # pod dies: the port stops answering
        self.srv.stop()
        assert self.scraper.scrape_once(now + 1.0) == 0
        assert self.op_m.counter(
            "telemetry_scrape_failures_total",
            job="default/j", replica="worker-0",
        ) >= 1.0
        # inside the TTL the last-seen value still serves (staleness is
        # visible through the age gauge, not by lying about the value)
        assert self.op_m.gauge(
            "train_window_steps_per_second", **self.fed()
        ) == 3.0
        age = self.op_m.gauge(
            "telemetry_scrape_age_seconds",
            job="default/j", replica_type="worker", replica_index="0",
            slice="",
        )
        assert age >= 1.0
        # past the TTL the federated series are SWEPT, not frozen
        self.scraper.scrape_once(now + 30.0)
        assert self.op_m.gauge_series("train_window_steps_per_second") == {}
        snap = self.scraper.targets_snapshot(now + 30.0)
        assert snap["targets"][0]["stale"] is True

    def test_trace_folding_is_deduped_and_stitched(self):
        # the stitching contract: the pod roots its train span under a
        # remote (operator) trace id, the fold lands it in that trace
        with self.pod_t.span(
            "train stitched", trace_id="t-operator-1", parent_id="s-pc-1"
        ):
            pass
        self.scraper.scrape_once()
        self.scraper.scrape_once()  # re-scrape must not duplicate spans
        trace = self.op_t.store.trace("t-operator-1")
        assert trace is not None
        assert [s["name"] for s in trace["spans"]] == ["train stitched"]
        assert trace["spans"][0]["parentId"] == "s-pc-1"

    def test_scraping_never_runs_in_a_sync(self):
        """The reconciler only READS scraper state (job_rows); the
        scrape itself is driven by the scraper's own thread/test
        clock.  Pin: Reconciler never calls scrape_once."""

        src = (PKG_ROOT / "controller" / "reconciler.py").read_text()
        assert "scrape_once" not in src


class TestReconcilerInjection:
    """The injection contract: every created pod carries the telemetry
    port (env + discovery annotation) and the pod.create span context."""

    def _harness(self, pod_telemetry=True):
        from tf_operator_tpu.backend.fake import FakeCluster
        from tf_operator_tpu.backend.jobstore import JobStore
        from tf_operator_tpu.controller.controller import TPUJobController
        from tf_operator_tpu.controller.reconciler import ReconcilerConfig

        store = JobStore()
        backend = FakeCluster()
        controller = TPUJobController(
            store, backend,
            config=ReconcilerConfig(pod_telemetry=pod_telemetry),
            metrics=Metrics(), tracer=Tracer(seed=21),
        )
        return store, backend, controller

    def test_created_pods_carry_port_annotation_and_trace_context(self):
        store, backend, controller = self._harness()
        job = new_job(name="tele", worker=1, command=["sleep", "1"])
        store.create(job)
        controller.sync_until_quiet()
        (pod,) = backend.list_pods("default", {LABEL_JOB_NAME: "tele"})
        env = pod.containers[0].env
        port = env[ENV_TELEMETRY_PORT]
        assert int(port) > 0
        assert pod.metadata.annotations[ANNOTATION_TELEMETRY_PORT] == port
        # the span context the harness roots the train trace under
        assert env[ENV_TRACE_ID] and env[ENV_PARENT_SPAN_ID]
        # ...and it names a REAL pod.create span in the operator store
        trace = controller.tracer.store.trace(env[ENV_TRACE_ID])
        assert trace is not None
        assert any(
            s["name"] == "pod.create tele-worker-0"
            and s["spanId"] == env[ENV_PARENT_SPAN_ID]
            for s in trace["spans"]
        )
        # the pod record is a discoverable scrape target once Running
        running = pod.clone()
        running.phase = PodPhase.RUNNING
        (target,) = pods_to_targets([running])
        assert target.url.endswith(f":{port}")

    def test_pod_telemetry_off_injects_nothing(self):
        store, backend, controller = self._harness(pod_telemetry=False)
        job = new_job(name="quiet", worker=1, command=["sleep", "1"])
        store.create(job)
        controller.sync_until_quiet()
        (pod,) = backend.list_pods("default", {LABEL_JOB_NAME: "quiet"})
        env = pod.containers[0].env
        assert ENV_TELEMETRY_PORT not in env
        assert ENV_TRACE_ID not in env
        assert ANNOTATION_TELEMETRY_PORT not in pod.metadata.annotations

    def test_user_env_wins_over_injection(self):
        from tf_operator_tpu.api.types import ReplicaType

        store, backend, controller = self._harness()
        job = new_job(name="ovr", worker=1, command=["sleep", "1"])
        job.spec.replica_specs[ReplicaType.WORKER].template.containers[
            0
        ].env = {ENV_TELEMETRY_PORT: "0"}
        store.create(job)
        controller.sync_until_quiet()
        (pod,) = backend.list_pods("default", {LABEL_JOB_NAME: "ovr"})
        assert pod.containers[0].env[ENV_TELEMETRY_PORT] == "0"


class TestCheckpointAgeGapClosed:
    """Satellite: the stock checkpoint-age ThresholdRule fires at the
    OPERATOR from a wedged pod's federated stamp — the documented
    'rests at never-breaches' caveat is gone."""

    def test_wedged_pod_drives_rule_pending_to_firing(self):
        from tf_operator_tpu.utils.alerts import AlertEngine, default_rules

        pod_m = Metrics()
        srv = PodTelemetryServer(metrics=pod_m, tracer=Tracer(seed=31)).start()
        try:
            now = time.time()
            # the pod checkpointed once, hours ago, then wedged
            pod_m.set("checkpoint_last_success_unix", now - 7200.0)
            op_m = Metrics()
            scraper = TelemetryScraper(metrics=op_m, tracer=Tracer(seed=32))
            scraper.attach(lambda: [make_pod(port=srv.port)])
            scraper.scrape_once(now)
            engine = AlertEngine(rules=default_rules(), metrics=op_m)
            engine.evaluate_once(now)
            alert = engine.alert("checkpoint-stale")
            assert alert.state == "firing", alert.state
            assert alert.value["age"] > 1800.0
        finally:
            srv.stop()

    def test_rollup_and_gate_read_the_federated_stamp(self):
        from tf_operator_tpu.controller.autoscaler import job_checkpoint_age

        now = time.time()
        op_m = Metrics()
        job = new_job(name="fed", worker=1)
        assert job_checkpoint_age(job, now, metrics=op_m) is None
        op_m.set(
            "checkpoint_last_success_unix", now - 33.0,
            job=job.key, replica_type="worker", replica_index="0", slice="",
        )
        age = job_checkpoint_age(job, now, metrics=op_m)
        assert age is not None and abs(age - 33.0) < 1e-6

    def test_docs_caveat_is_gone(self):
        """The ARCHITECTURE.md caveat this satellite deletes must stay
        deleted: the operator no longer 'rests at never-breaches' for
        subprocess-pod trainers."""

        text = pathlib.Path(
            os.path.join(os.path.dirname(PKG_ROOT), "docs", "ARCHITECTURE.md")
        ).read_text()
        assert "rests at" not in text


class TestHostSideOnly:
    """Satellite: exporter/scraper are pure host-side, and the
    harness's telemetry boot adds no step-loop syncs (the no-hot-sync
    AST gate in test_lint_no_hot_sync.py stays authoritative; this
    pins the telemetry modules specifically)."""

    @pytest.mark.parametrize(
        "rel", ["runtime/telemetry.py", "controller/telemetry.py"]
    )
    def test_telemetry_modules_never_import_jax(self, rel):
        tree = ast.parse((PKG_ROOT / rel).read_text())
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                assert not any(a.name.split(".")[0] == "jax" for a in node.names)
            elif isinstance(node, ast.ImportFrom):
                assert (node.module or "").split(".")[0] != "jax"

    def test_harness_boots_telemetry_outside_the_step_loop(self):
        """The boot call sits before the train span opens — never
        inside the per-step/window bodies the hot-sync gate lints."""

        src = (PKG_ROOT / "runtime" / "harness.py").read_text()
        boot = src.index("_maybe_start_telemetry()")
        first_loop = src.index("if k == 1:")
        assert boot < first_loop


@pytest.mark.slow
class TestFleetE2E:
    """The acceptance pin, against kubesim: a REAL subprocess trainer
    pod serves /metrics over HTTP; the scraper federates it; describe
    shows per-pod rows; one trace id spans reconcile→train."""

    TRAINER = (
        "import os, time\n"
        "import jax.numpy as jnp\n"
        "from tf_operator_tpu.runtime import harness\n"
        "from tf_operator_tpu.utils.metrics import default_metrics\n"
        "class T:\n"
        "    def __init__(self): self.n = 0.0\n"
        "    def train_step(self, batch):\n"
        "        self.n += 1.0\n"
        "        return {'loss': jnp.asarray(1.0 / self.n)}\n"
        "harness.train_loop(T(), {'x': jnp.zeros((1,))}, steps=6,\n"
        "                   steps_per_sync=2, assert_decreasing=False)\n"
        "# the multi-slice grad-sync accounting families (the trainer's\n"
        "# host-side per-dispatch writes — emulated here at the same\n"
        "# literal family/labels) plus a STALE checkpoint stamp: this\n"
        "# pod is about to wedge with a 2h-old checkpoint\n"
        "default_metrics.inc('train_dcn_bytes_total', 4096.0, fabric='dcn')\n"
        "default_metrics.inc('train_dcn_bytes_total', 16384.0, fabric='ici')\n"
        "default_metrics.set('checkpoint_last_success_unix', time.time() - 7200.0)\n"
        "time.sleep(30)\n"  # wedged: keep serving /metrics until killed
    )

    def test_subprocess_pod_federates_into_operator(self, tmp_path):
        from tf_operator_tpu.backend.kube import KubeBackend
        from tf_operator_tpu.backend.kubejobs import KubeJobStore
        from tf_operator_tpu.backend.kubesim import MiniApiServer
        from tf_operator_tpu.controller.controller import TPUJobController
        from tf_operator_tpu.controller.reconciler import ReconcilerConfig
        from tf_operator_tpu.server.api import ApiServer
        from tf_operator_tpu.utils.alerts import AlertEngine, default_rules

        sim = MiniApiServer().start()
        store = KubeJobStore(sim.url)
        backend = KubeBackend(sim.url)
        op_metrics = Metrics()
        scraper = TelemetryScraper(metrics=op_metrics, stale_after=60.0)
        controller = TPUJobController(
            store, backend,
            config=ReconcilerConfig(resolver=backend.resolver),
            metrics=op_metrics, telemetry=scraper,
        )
        api = ApiServer(
            store, backend, op_metrics, controller.recorder,
            telemetry=scraper, tracer=controller.tracer,
        )
        api.start()
        controller.run(threadiness=2)
        try:
            job = new_job(
                name="tele-e2e", worker=1,
                command=[sys.executable, "-c", self.TRAINER],
            )
            from tf_operator_tpu.api.types import ReplicaType

            job.spec.replica_specs[ReplicaType.WORKER].template.containers[
                0
            ].env = {"JAX_PLATFORMS": "cpu"}
            store.create(job)

            # wait for the pod's federated series to land
            deadline = time.time() + 60
            fed = {
                "job": "default/tele-e2e", "replica_type": "worker",
                "replica_index": "0", "slice": "",
            }
            while time.time() < deadline:
                scraper.scrape_once()
                if (
                    op_metrics.counter("train_dcn_bytes_total", fabric="dcn", **fed)
                    and op_metrics.gauge("train_window_steps_per_second", **fed)
                ):
                    break
                time.sleep(0.3)
            assert op_metrics.counter(
                "train_dcn_bytes_total", fabric="dcn", **fed
            ) == 4096.0
            assert op_metrics.gauge("train_window_steps_per_second", **fed) > 0

            base = f"http://127.0.0.1:{api.port}"

            def get(route):
                with urllib.request.urlopen(base + route, timeout=10) as r:
                    return r.read().decode()

            # --- /federate carries the decorated families
            federate = get("/federate")
            assert (
                'train_dcn_bytes_total{fabric="dcn",job="default/tele-e2e"'
                in federate
            )
            assert "train_window_steps_per_second" in federate
            targets = json.loads(get("/federate/targets"))["targets"]
            assert targets and targets[0]["job"] == "default/tele-e2e"

            # --- the stock checkpoint-age rule fires from the wedged
            # pod's federated stamp (the PR-6 process-scope gap, gone)
            engine = AlertEngine(rules=default_rules(), metrics=op_metrics)
            engine.evaluate_once()
            assert engine.alert("checkpoint-stale").state == "firing"

            # --- describe shows per-pod Health rows (retry: the
            # health rollup throttles refreshes to every few seconds)
            from tf_operator_tpu.cmd.tpujob import build_parser

            described = ""
            deadline = time.time() + 30
            while time.time() < deadline:
                controller.resync()
                controller.sync_until_quiet()
                args = build_parser().parse_args(
                    ["--server", base, "describe", "tele-e2e"]
                )
                buf = io.StringIO()
                stdout, sys.stdout = sys.stdout, buf
                try:
                    args.fn(args)
                finally:
                    sys.stdout = stdout
                described = buf.getvalue()
                if "pod/worker-0" in described:
                    break
                time.sleep(1.0)
            assert "pod/worker-0" in described, described

            # --- tpujob telemetry lists the target
            args = build_parser().parse_args(["--server", base, "telemetry"])
            buf = io.StringIO()
            stdout, sys.stdout = sys.stdout, buf
            try:
                args.fn(args)
            finally:
                sys.stdout = stdout
            assert "default/tele-e2e" in buf.getvalue()

            # --- ONE trace id spans reconcile→pod train
            (pod,) = backend.list_pods(
                "default", {LABEL_JOB_NAME: "tele-e2e"}
            )
            tid = pod.containers[0].env[ENV_TRACE_ID]
            deadline = time.time() + 30
            trace = None
            while time.time() < deadline:
                scraper.scrape_once()
                trace = json.loads(get(f"/traces/{tid}"))
                names = {s["name"] for s in trace.get("spans", [])}
                if any(n.startswith("train ") for n in names) and any(
                    n.startswith("pod.create tele-e2e-worker-0")
                    for n in names
                ):
                    break
                time.sleep(0.3)
            names = {s["name"] for s in trace["spans"]}
            assert "pod.create tele-e2e-worker-0" in names, names
            assert any(n.startswith("train ") for n in names), names
            # the train span really is stitched UNDER the pod.create span
            create = next(
                s for s in trace["spans"]
                if s["name"] == "pod.create tele-e2e-worker-0"
            )
            train = next(
                s for s in trace["spans"] if s["name"].startswith("train ")
            )
            assert train["parentId"] == create["spanId"]

            # --- and the job timeline surfaces the stitched vertical
            timeline = json.loads(
                get("/apis/v1/namespaces/default/tpujobs/tele-e2e/timeline")
            )
            assert tid in timeline["traceIds"]
        finally:
            controller.stop()
            api.stop()
            backend.close()
            store.close()
            sim.stop()
