"""Disaggregated serving (ISSUE 13 tentpole): phase-split prefill/
decode replicas with KV-block migration over the prefix-cache fabric.

The load-bearing pins:

- TOKEN IDENTITY: a request served through the disaggregated path
  (prefill replica publishes → fabric → decode replica maps/pulls and
  decodes) is byte-identical to the uniform pool — greedy AND
  temperature, on BOTH step paths (gather emulation and the
  interpret-mode Pallas kernel).  The decode replica's admission runs
  the request's own rng split chain; the prefill replica's internal
  publish prefill is greedy and consumes nothing.
- DISPATCH ACCOUNTING: steady-state decode stays exactly 1 dispatch
  per step window, with migration appearing ONLY as the new
  ``migrate_out`` (prefill side) / ``migrate_in`` (decode side) ledger
  phases — the decode replica never runs a prefill phase.
- ATTRIBUTION: the autopsy names BOTH replicas (prefill_replica /
  decode_replica), counts migrated blocks, and the route spans carry
  phase/role; internal publish prefills never pollute user-facing SLO
  histograms.
- FAILURE SEMANTICS: a prefill replica dying mid-publish degrades to
  the decode replica recomputing the prefix — same tokens, one
  counted failure, no user-visible error.
"""

import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # generation-loop compiles

import jax
import jax.numpy as jnp

from tf_operator_tpu.models import llama_tiny
from tf_operator_tpu.models.batching import PagedContinuousBatchingDecoder
from tf_operator_tpu.models.pool_router import PoolRouter
from tf_operator_tpu.models.prefix_cache import PrefixFabric
from tf_operator_tpu.utils.metrics import Metrics
from tf_operator_tpu.utils.trace import Tracer

VOCAB = 96


def _setup(max_len=64):
    model = llama_tiny(vocab_size=VOCAB, max_len=max_len)
    init = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), init)["params"]
    return model, params


class _Fleet:
    """1 prefill + 1 decode replica over one fabric, with driver
    threads (the router's disaggregated submit BLOCKS on the prefill
    handshake, so somebody must be stepping the pools)."""

    def __init__(self, model, params, kernel="off", metrics=None,
                 tracer=None, slots=4, kv_blocks=None):
        from tf_operator_tpu.utils.metrics import DispatchLedger

        self.metrics = metrics if metrics is not None else Metrics()
        self.fabric = PrefixFabric(metrics=self.metrics, model_label="t")
        # per-pool ledgers (phase counts stay per-replica) sharing the
        # router's tracer, so lifecycle + dispatch spans join the
        # request's trace like serve_lm's wiring
        self.prefill = PagedContinuousBatchingDecoder(
            model, params, slots=slots, kv_block_size=16,
            kv_blocks=kv_blocks, paged_kernel=kernel, metrics=self.metrics,
            ledger=DispatchLedger(metrics=self.metrics, tracer=tracer),
            model_label="t", replica_label="p0", role="prefill",
            fabric=self.fabric,
        )
        self.decode = PagedContinuousBatchingDecoder(
            model, params, slots=slots, kv_block_size=16,
            kv_blocks=kv_blocks, paged_kernel=kernel, metrics=self.metrics,
            ledger=DispatchLedger(metrics=self.metrics, tracer=tracer),
            model_label="t", replica_label="d0", role="decode",
            fabric=self.fabric,
        )
        self.router = PoolRouter([self.prefill, self.decode],
                                 tracer=tracer)
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._drive, args=(p,), daemon=True)
            for p in (self.prefill, self.decode)
        ]

    def _drive(self, pool):
        while not self._stop.is_set():
            if pool.step() == 0:
                time.sleep(0.002)

    def __enter__(self):
        for t in self._threads:
            t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)
        return False


def _mixed_trace(r, n=6):
    """Long prompts (multi-block, 60% sharing a system prefix — the
    fabric's bread and butter) mixed with short single-block ones."""

    sys_prefix = r.randint(0, VOCAB, size=(32,)).astype(np.int32)
    trace = []
    for i in range(n):
        if i % 3 == 2:
            prompt = r.randint(0, VOCAB, size=(6,)).astype(np.int32)
        elif i % 2 == 0:
            tail = r.randint(0, VOCAB, size=(int(r.randint(3, 9)),))
            prompt = np.concatenate([sys_prefix, tail.astype(np.int32)])
        else:
            prompt = r.randint(0, VOCAB, size=(38,)).astype(np.int32)
        trace.append((prompt, int(r.choice([4, 8]))))
    return trace


class TestTokenIdentity:
    @pytest.mark.parametrize("kernel", ["off", "interpret"])
    @pytest.mark.parametrize("temp", [0.0, 0.9])
    def test_disaggregated_path_token_identical_to_uniform(self, kernel,
                                                           temp):
        model, params = _setup()
        r = np.random.RandomState(11)
        trace = _mixed_trace(r, n=4 if kernel == "interpret" else 6)

        def submit_all(target):
            rids = []
            for j, (prompt, budget) in enumerate(trace):
                rids.append(target.submit(
                    prompt, budget, temperature=temp,
                    rng=jax.random.PRNGKey(100 + j) if temp > 0 else None,
                    trace_id=f"ti-{j}",
                ))
            return rids

        with _Fleet(model, params, kernel=kernel) as fleet:
            rids = submit_all(fleet.router)
            outs = [fleet.router.result_wait(rid, timeout=300)
                    for rid in rids]
        assert all(o is not None for o in outs)
        # migration really happened (the trace has publishable blocks)
        assert fleet.fabric.snapshot()["publishes"] > 0
        assert any(
            p["count"] > 0
            for ph, p in fleet.decode.ledger.snapshot().items()
            if ph == "migrate_in"
        )

        uniform = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, paged_kernel=kernel,
        )
        urids = []
        for j, (prompt, budget) in enumerate(trace):
            urids.append(uniform.submit(
                prompt, budget, temperature=temp,
                rng=jax.random.PRNGKey(100 + j) if temp > 0 else None,
            ))
        uniform.run()
        for out, urid in zip(outs, urids):
            ref = uniform.result(urid)
            assert np.array_equal(out, ref), (out, ref)
        fleet.prefill.alloc.check()
        fleet.decode.alloc.check()


class TestDispatchAccounting:
    def test_decode_replica_never_prefills_and_steps_stay_single_dispatch(self):
        """The decode replica's ledger holds ONLY {admission, step,
        retire, migrate_in} — no prefill/sample/scatter phase ever —
        and the step count equals the number of decode windows (the
        PR 10 exactly-1-dispatch/step contract survives migration).
        The prefill replica's ledger shows the mirror image:
        admission + retire (budget-1 publishes) + migrate_out, and no
        step at all for publish-only traffic."""

        model, params = _setup()
        r = np.random.RandomState(3)
        trace = _mixed_trace(r, n=6)
        with _Fleet(model, params) as fleet:
            rids = [fleet.router.submit(p, b, trace_id=f"da-{j}")
                    for j, (p, b) in enumerate(trace)]
            outs = [fleet.router.result_wait(rid, timeout=300)
                    for rid in rids]
        assert all(o is not None for o in outs)
        dec = {ph: v["count"]
               for ph, v in fleet.decode.ledger.snapshot().items()}
        pre = {ph: v["count"]
               for ph, v in fleet.prefill.ledger.snapshot().items()}
        assert set(dec) <= {"admission", "step", "retire", "migrate_in"}, dec
        assert dec.get("migrate_in", 0) > 0
        assert dec["admission"] == len(trace)
        # prefill-side: internal budget-1 admissions retire at
        # admission — publish-only traffic never decodes a window
        assert set(pre) <= {"admission", "retire", "migrate_out"}, pre
        assert pre.get("migrate_out", 0) > 0
        # window accounting: each step dispatch produced one
        # decode.window per then-active seat; the autopsy's per-request
        # share must sum to >= the global step count (shared windows)
        windows = sum(
            fleet.router.request_autopsy(f"da-{j}")["windows"]
            for j in range(len(trace))
        )
        assert windows >= dec["step"]

    def test_internal_publishes_never_pollute_user_slo(self):
        model, params = _setup()
        r = np.random.RandomState(5)
        trace = _mixed_trace(r, n=4)
        with _Fleet(model, params) as fleet:
            rids = [fleet.router.submit(p, b) for p, b in trace]
            for rid in rids:
                assert fleet.router.result_wait(rid, timeout=300) \
                    is not None
        fam = fleet.metrics.histogram_family("serve_ttft_seconds")
        total = sum(s["count"] for s in fam.values())
        # one TTFT observation per USER request — the prefill
        # replica's internal publish prefills observe nothing
        assert total == len(trace)
        for labels, _ in fam.items():
            assert dict(labels)["role"] == "decode"


class TestAttributionAndSpans:
    def test_autopsy_names_both_replicas_and_counts_migration(self):
        model, params = _setup()
        tracer = Tracer(seed=0)
        r = np.random.RandomState(9)
        sys_prefix = r.randint(0, VOCAB, size=(32,)).astype(np.int32)
        long_prompt = np.concatenate(
            [sys_prefix, r.randint(0, VOCAB, size=(5,)).astype(np.int32)]
        )
        short_prompt = r.randint(0, VOCAB, size=(6,)).astype(np.int32)
        with _Fleet(model, params, tracer=tracer) as fleet:
            rid_l = fleet.router.submit(long_prompt, 4, trace_id="long")
            rid_s = fleet.router.submit(short_prompt, 4, trace_id="short")
            assert fleet.router.result_wait(rid_l, timeout=300) is not None
            assert fleet.router.result_wait(rid_s, timeout=300) is not None
        a = fleet.router.request_autopsy("long")
        assert a["prefill_replica"] == "p0"
        assert a["decode_replica"] == "d0"
        assert a["migrated_blocks"] == 2  # (33-1)//16 full chain blocks
        assert a["dispatches"].get("migrate_in") == 1
        # short prompts (no publishable block) skip the handshake: the
        # decode replica IS the prefill replica
        s = fleet.router.request_autopsy("short")
        assert s["prefill_replica"] == "d0"
        assert s["decode_replica"] == "d0"
        assert s["migrated_blocks"] == 0
        # route spans carry phase/role; the long request has BOTH
        trace = tracer.store.trace("long")
        routes = [
            sp for sp in trace["spans"] if sp["name"] == "route"
        ]
        phases = {
            sp["attributes"]["phase"]: sp["attributes"] for sp in routes
        }
        assert set(phases) == {"prefill", "decode"}
        assert phases["prefill"]["role"] == "prefill"
        assert phases["prefill"]["replica"] == "p0"
        assert phases["decode"]["replica"] == "d0"
        # the migrate lifecycle span landed on the same trace
        assert any(sp["name"] == "migrate" for sp in trace["spans"])

    def test_role_labeled_pressure_gauges_split_by_class(self):
        model, params = _setup()
        m = Metrics()
        with _Fleet(model, params, metrics=m) as fleet:
            r = np.random.RandomState(2)
            prompt = r.randint(0, VOCAB, size=(40,)).astype(np.int32)
            rid = fleet.router.submit(prompt, 8)
            assert fleet.router.result_wait(rid, timeout=300) is not None
        for rep, role in (("p0", "prefill"), ("d0", "decode")):
            series = m.gauge_series("kv_blocks_pressure")
            match = [
                v for labels, v in series.items()
                if dict(labels).get("replica") == rep
                and dict(labels).get("role") == role
            ]
            assert match, (rep, role, series)
        # the arena timelines carry the role too (per-role strips)
        snaps = fleet.router.arena_snapshots()
        assert {s["role"] for s in snaps} == {"prefill", "decode"}


class TestFailureSemantics:
    def test_prefill_death_mid_publish_degrades_to_local_recompute(self):
        """The documented failure rule: when the prefill replica dies
        mid-publish, the decode replica recomputes whatever never
        reached the fabric — same tokens, one counted failure, no
        user-visible error."""

        model, params = _setup()
        m = Metrics()
        r = np.random.RandomState(4)
        prompt = r.randint(0, VOCAB, size=(40,)).astype(np.int32)

        uniform = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16,
        )
        urid = uniform.submit(prompt, 6)
        uniform.run()
        ref = uniform.result(urid)

        with _Fleet(model, params, metrics=m) as fleet:
            def dead_publish(*a, **k):
                raise RuntimeError("prefill replica died mid-publish")

            fleet.prefill.publish_to_fabric = dead_publish
            rid = fleet.router.submit(prompt, 6)
            out = fleet.router.result_wait(rid, timeout=300)
        assert out is not None and np.array_equal(out, ref)
        assert m.counter(
            "serve_fabric_publish_failures_total", model="t"
        ) == 1.0
        # nothing migrated — the decode replica computed the prefix
        assert "migrate_in" not in fleet.decode.ledger.snapshot()

    def test_dead_prefill_driver_times_out_into_recompute(self):
        """A WEDGED (not crashed) prefill replica — driver thread
        never steps — must not hang the submit thread forever:
        publish_to_fabric times out, the failure path counts it, and
        the decode replica recomputes (review finding)."""

        model, params = _setup()
        m = Metrics()
        r = np.random.RandomState(8)
        prompt = r.randint(0, VOCAB, size=(40,)).astype(np.int32)

        uniform = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16,
        )
        urid = uniform.submit(prompt, 6)
        uniform.run()
        ref = uniform.result(urid)

        fleet = _Fleet(model, params, metrics=m)
        fleet.router.publish_timeout = 0.5
        # start ONLY the decode driver: the prefill pool accepts the
        # internal submit but nobody ever steps it
        fleet._threads[1].start()
        try:
            rid = fleet.router.submit(prompt, 6)
            out = fleet.router.result_wait(rid, timeout=300)
        finally:
            fleet._stop.set()
            fleet._threads[1].join(timeout=30)
        assert out is not None and np.array_equal(out, ref)
        assert m.counter(
            "serve_fabric_publish_failures_total", model="t"
        ) == 1.0

    def test_evicted_head_with_live_tail_pulls_without_leaking(self):
        """Chain walks refresh LRU head-first, so a pressured local
        cache evicts a chain's HEAD while its tail stays resident.
        The fabric pull must stop at the first still-local link — a
        pull-over would prefix.put over the live entry and leak the
        old block's cache reference (review finding; alloc.check()
        catches the leak)."""

        model, params = _setup()
        r = np.random.RandomState(10)
        prompt = r.randint(0, VOCAB, size=(40,)).astype(np.int32)
        with _Fleet(model, params) as fleet:
            rid = fleet.router.submit(prompt, 6)
            assert fleet.router.result_wait(rid, timeout=300) is not None
            # both full blocks now sit in the decode replica's local
            # cache (refcount 1 each) AND the fabric; evict the HEAD
            with fleet.decode._lock:
                assert fleet.decode.prefix.evict_lru(need=1) == 1
            # same prompt again: the pull re-fetches the head from the
            # fabric but must stop before the still-local tail
            rid2 = fleet.router.submit(prompt, 6)
            out2 = fleet.router.result_wait(rid2, timeout=300)
        assert out2 is not None
        uniform = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16,
        )
        urid = uniform.submit(prompt, 6)
        uniform.run()
        assert np.array_equal(out2, uniform.result(urid))
        # the leak check: conservation still holds and draining the
        # cache releases every block
        fleet.decode.alloc.check()
        while fleet.decode.prefix.evict_lru(need=64):
            pass
        assert fleet.decode.alloc.in_use == 0

    def test_fabric_capacity_eviction_degrades_to_recompute(self):
        """A fabric too small to hold the chain still serves exactly:
        evicted entries are recomputed decode-side (the pull just
        misses)."""

        model, params = _setup()
        r = np.random.RandomState(6)
        trace = _mixed_trace(r, n=4)
        fleet = _Fleet(model, params)
        fleet.fabric.capacity_blocks = 1  # pathological: one block
        with fleet:
            rids = [fleet.router.submit(p, b) for p, b in trace]
            outs = [fleet.router.result_wait(rid, timeout=300)
                    for rid in rids]
        assert all(o is not None for o in outs)
        uniform = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16,
        )
        urids = [uniform.submit(p, b) for p, b in trace]
        uniform.run()
        for out, urid in zip(outs, urids):
            assert np.array_equal(out, uniform.result(urid))


class TestServeLmRoles:
    """serve_lm wiring: --roles parsing and the full HTTP surface of a
    disaggregated fleet."""

    def test_parse_roles(self):
        from tests.testutil import load_serve_lm

        serve_lm = load_serve_lm()
        assert serve_lm.parse_roles("prefill=1,decode=2") == [
            "prefill", "decode", "decode",
        ]
        assert serve_lm.parse_roles("unified=2") == ["unified", "unified"]
        for bad in ("prefill=2", "prefill=1,decode=x", "chef=1", "",
                    "prefill=-1,decode=1", "decode=2"):
            # decode-only is rejected too: it would serve like a
            # uniform fleet while wearing role="decode" labels
            with pytest.raises(ValueError):
                serve_lm.parse_roles(bad)

    def test_disaggregated_fleet_over_http(self):
        import json as _json
        import urllib.request
        from http.server import ThreadingHTTPServer

        from tests.testutil import load_serve_lm

        serve_lm = load_serve_lm()
        model, params = _setup()
        handler = serve_lm.build_handler(
            model, params, max_len=64, batching_slots=2, replicas=2,
            roles=["prefill", "decode"],
        )
        server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            # a multi-block prompt: the decode replica pulls its chain
            # tail through the fabric
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=_json.dumps({
                    "prompt": "x" * 40, "max_new_tokens": 6,
                }).encode(),
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=300) as resp:
                body = _json.loads(resp.read())
            assert len(body["sample"]) == 6
            rid = body["request_id"]

            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/requests/{rid}", timeout=30
            ) as resp:
                autopsy = _json.loads(resp.read())
            assert autopsy["prefill_replica"] == "0"
            assert autopsy["decode_replica"] == "1"
            assert autopsy["migrated_blocks"] == 2  # (40-1)//16

            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/arena", timeout=30
            ) as resp:
                arena = _json.loads(resp.read())
            assert arena["fabric"]["publishes"] >= 2
            assert {r["role"] for r in arena["replicas"]} == {
                "prefill", "decode",
            }

            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30
            ) as resp:
                text = resp.read().decode()
            assert (
                'kv_blocks_pressure{model="unknown",replica="0",'
                'role="prefill"}'
            ) in text
            assert (
                'kv_blocks_pressure{model="unknown",replica="1",'
                'role="decode"}'
            ) in text
            assert "kv_fabric_blocks" in text
            assert (
                'kv_migrate_bytes_total{direction="in",transport="local"}'
            ) in text

            # /slo still reports ONE user-facing TTFT row (role and
            # replica merged away), counting only the USER request
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/slo", timeout=30
            ) as resp:
                slo = _json.loads(resp.read())
            rows = slo["histograms"]["serve_ttft_seconds"]
            assert len(rows) == 1 and rows[0]["count"] == 1
            assert "role" not in rows[0] and "replica" not in rows[0]
        finally:
            server.shutdown()
