"""The advisory TPU chip lock (benchmarks/chiplock.py).

Round-4 incident: the axon tunnel serves one claimant at a time, and a
concurrent background process silently stalled the bench child inside
its timeout.  These tests pin the coordination contract: non-blocking
acquire, holder metadata, bench-priority preemption (kills the
holder's process tree), and crash-safety (a dead holder's flock
vanishes with its fd).
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

from chiplock import ChipLock  # noqa: E402


@pytest.fixture
def lock_path(tmp_path):
    return str(tmp_path / "chip.lock")


def test_acquire_free_lock(lock_path):
    lock = ChipLock("window", path=lock_path)
    assert lock.try_acquire()
    info = lock.holder()
    assert info["pid"] == os.getpid()
    assert info["role"] == "window"
    lock.release()


def test_second_acquire_fails_then_succeeds_after_release(lock_path):
    a = ChipLock("window", path=lock_path)
    b = ChipLock("watch", path=lock_path)
    assert a.try_acquire()
    assert not b.try_acquire()
    a.release()
    assert b.try_acquire()
    b.release()


def test_holder_readable_without_acquiring(lock_path):
    a = ChipLock("window", path=lock_path)
    assert a.try_acquire()
    info = ChipLock("bench", path=lock_path).holder()
    assert info["role"] == "window"
    a.release()


def test_dead_holder_does_not_block(lock_path):
    """flock dies with the process: a crashed holder leaves no stale lock."""
    child = subprocess.Popen(
        [
            sys.executable, "-c",
            "import sys, time; sys.path.insert(0, %r); "
            "from chiplock import ChipLock; "
            "assert ChipLock('window', path=%r).try_acquire(); "
            "print('held', flush=True); time.sleep(60)"
            % (os.path.join(REPO, "benchmarks"), lock_path),
        ],
        stdout=subprocess.PIPE, text=True,
    )
    assert child.stdout.readline().strip() == "held"
    b = ChipLock("bench", path=lock_path)
    assert not b.try_acquire()
    child.kill()
    child.wait()
    deadline = time.time() + 10
    while time.time() < deadline and not b.try_acquire():
        time.sleep(0.1)
    assert b.holder()["role"] == "bench"
    b.release()


def test_bench_preempts_live_holder(lock_path):
    """acquire_or_preempt kills the recorded holder and takes the lock."""
    child = subprocess.Popen(
        [
            sys.executable, "-c",
            "import sys, time; sys.path.insert(0, %r); "
            "from chiplock import ChipLock; "
            "assert ChipLock('window', path=%r).try_acquire(); "
            "print('held', flush=True); time.sleep(120)"
            % (os.path.join(REPO, "benchmarks"), lock_path),
        ],
        stdout=subprocess.PIPE, text=True,
    )
    assert child.stdout.readline().strip() == "held"
    bench = ChipLock("bench", path=lock_path)
    note = bench.acquire_or_preempt(grace_s=15.0)
    assert "preempted" in note and "window" in note
    assert bench.holder()["role"] == "bench"
    assert child.wait(timeout=10) != 0  # holder was killed, not exited
    bench.release()


def test_preempt_kills_term_ignoring_grandchild(lock_path):
    """A descendant that ignores SIGTERM and outlives its parent must
    still be reached by the SIGKILL escalation — an escaped grandchild
    would keep the axon chip claim alive behind the released flock."""
    child = subprocess.Popen(
        [
            sys.executable, "-c",
            "import sys, time, subprocess; sys.path.insert(0, %r); "
            "from chiplock import ChipLock; "
            "assert ChipLock('window', path=%r).try_acquire(); "
            "g = subprocess.Popen([sys.executable, '-c', "
            "'import time, signal; signal.signal(signal.SIGTERM, signal.SIG_IGN); "
            "print(\"g up\", flush=True); time.sleep(120)']); "
            "print('held', g.pid, flush=True); time.sleep(120)"
            % (os.path.join(REPO, "benchmarks"), lock_path),
        ],
        stdout=subprocess.PIPE, text=True,
    )
    line = child.stdout.readline().split()
    assert line[0] == "held"
    gpid = int(line[1])
    # wait for the grandchild to have installed its SIGTERM ignore
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            with open(f"/proc/{gpid}/cmdline", "rb") as f:
                if b"SIG_IGN" in f.read():
                    break
        except OSError:
            pass
        time.sleep(0.1)
    time.sleep(0.5)
    bench = ChipLock("bench", path=lock_path)
    note = bench.acquire_or_preempt(grace_s=5.0)
    assert "preempted" in note
    # the TERM-immune grandchild must be gone (KILLed), not orphaned
    deadline = time.time() + 15
    while time.time() < deadline:
        try:
            os.kill(gpid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.2)
    else:
        os.kill(gpid, 9)
        raise AssertionError("grandchild escaped the kill tree")
    child.wait(timeout=10)
    bench.release()


def test_preempt_on_free_lock_is_silent(lock_path):
    bench = ChipLock("bench", path=lock_path)
    assert bench.acquire_or_preempt() == ""
    bench.release()


def test_inherited_claim_env_skips_bench_locking(lock_path, monkeypatch):
    """bench.py run as a window child must not preempt its own parent:
    the TPU_CHIP_LOCK_INHERITED marker short-circuits locking."""
    src = open(os.path.join(REPO, "bench.py")).read()
    assert "TPU_CHIP_LOCK_INHERITED" in src
    assert "running under parent's chip claim" in src
    # and the window exports the marker for its children
    wsrc = open(os.path.join(REPO, "benchmarks", "tpu_window.py")).read()
    assert 'env["TPU_CHIP_LOCK_INHERITED"] = "1"' in wsrc
