"""LoRA fine-tuning (models/lora.py).

Pinned properties: zero-delta at init (step 0 == base model exactly),
training moves ONLY the adapters (base tree bit-identical after
steps), and merged params flow through the existing generate/serving
paths.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.models import generate, llama_loss, llama_tiny
from tf_operator_tpu.models.lora import LoraModel, lora_init, merge_lora
from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

VOCAB = 128


def _base():
    model = llama_tiny(vocab_size=VOCAB, max_len=64)
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, VOCAB, size=(8, 24)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(1), ids)["params"]
    return model, params, ids


class TestLoraInit:
    def test_zero_delta_at_init(self):
        model, params, ids = _base()
        adapters = lora_init(params, jax.random.PRNGKey(0), rank=4, min_size=1)
        merged = merge_lora(params, adapters)
        base_out = model.apply({"params": params}, ids)
        merged_out = model.apply({"params": merged}, ids)
        np.testing.assert_array_equal(
            np.asarray(base_out), np.asarray(merged_out)
        )

    def test_selects_kernels_and_shapes(self):
        model, params, ids = _base()
        adapters = lora_init(params, jax.random.PRNGKey(0), rank=4, min_size=1)
        assert all("kernel" in k for k in adapters)
        for ab in adapters.values():
            assert ab["a"].shape[-1] == 4 and ab["b"].shape[0] == 4
        # adapter bytes are a small fraction of the base
        a_bytes = sum(
            x.nbytes for x in jax.tree_util.tree_leaves(adapters)
        )
        b_bytes = sum(
            x.nbytes for x in jax.tree_util.tree_leaves(params)
        )
        assert a_bytes < 0.5 * b_bytes

    def test_no_selection_is_loud(self):
        model, params, _ = _base()
        with pytest.raises(ValueError):
            lora_init(params, jax.random.PRNGKey(0), rank=4, min_size=10**9)


class TestLoraTraining:
    @pytest.mark.slow
    def test_trainer_moves_only_adapters(self):
        model, params, ids = _base()
        # meaningful frozen-base check: base-model OUTPUTS before vs
        # after training (a tree-snapshot comparison of the same
        # immutable arrays can never fail; logits catch any corruption
        # path, e.g. donation aliasing the captured base)
        base_logits_before = np.asarray(model.apply({"params": params}, ids))
        mesh = make_mesh({"dp": 8})
        lora = LoraModel(model, params, rank=4, min_size=1)
        batch = {"input_ids": ids}
        trainer = Trainer(
            lora,
            TrainerConfig(optimizer="sgd", learning_rate=0.5),
            mesh,
            llama_loss,
            batch,
            init_args=(ids,),
            shardings="fsdp",
        )
        losses = [
            float(trainer.train_step(trainer.shard_batch(batch))["loss"])
            for _ in range(6)
        ]
        assert losses[-1] < losses[0]  # adapters learn
        base_logits_after = np.asarray(model.apply({"params": params}, ids))
        np.testing.assert_array_equal(
            base_logits_before, base_logits_after
        )  # base frozen: outputs unchanged by adapter training
        # trained state is the {path: {a, b}} adapter dict, nothing else
        flat = jax.tree_util.tree_leaves_with_path(trainer.state.params)
        assert flat
        names = {str(getattr(p[-1], "key", p[-1])) for p, _ in flat}
        assert names <= {"a", "b"}

    @pytest.mark.slow
    def test_export_params_on_lora_trainer_bakes_merged_tree(self, tmp_path):
        # export_params(trainer) on a LoRA trainer must write the
        # MERGED dense tree under the base family's model.json — an
        # adapter-only tree with a llama description would be a
        # silently broken serving artifact
        from tf_operator_tpu.parallel import (
            export_params,
            load_model_description,
            load_params,
        )
        from tf_operator_tpu.models.registry import model_from_description

        model, params, ids = _base()
        mesh = make_mesh({"dp": 8})
        lora = LoraModel(model, params, rank=4, min_size=1)
        batch = {"input_ids": ids}
        trainer = Trainer(
            lora,
            TrainerConfig(optimizer="sgd", learning_rate=0.5),
            mesh,
            llama_loss,
            batch,
            init_args=(ids,),
            shardings="fsdp",
        )
        trainer.train_step(trainer.shard_batch(batch))
        art = str(tmp_path / "tuned")
        export_params(trainer, art)
        desc = load_model_description(art)
        assert desc is not None and desc["family"] == "llama"
        m2 = model_from_description(desc)
        out = generate(
            m2, load_params(art), ids[:1, :5], max_new_tokens=4
        )
        assert out.shape == (1, 9)

    @pytest.mark.slow
    def test_merged_params_generate(self):
        model, params, ids = _base()
        adapters = lora_init(params, jax.random.PRNGKey(2), rank=4, min_size=1)
        # perturb b so the delta is non-zero
        adapters = jax.tree_util.tree_map(
            lambda x: x + 0.01 if x.ndim == 2 and x.shape[0] == 4 else x,
            adapters,
        )
        lora = LoraModel(model, params, rank=4, min_size=1)
        merged = lora.merged_params(adapters)
        prompt = ids[:2, :5]
        out = generate(model, merged, prompt, max_new_tokens=6)
        assert out.shape == (2, 11)
        # and the adapted model really differs from the base
        base_logits = model.apply({"params": params}, prompt)
        lora_logits = model.apply({"params": merged}, prompt)
        assert float(jnp.max(jnp.abs(base_logits - lora_logits))) > 0
