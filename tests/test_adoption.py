"""Adoption/orphaning (ControllerRefManager parity) + round-2 reconciler
hardening: service scale-in expectation balance, cross-replica-type
backoff accounting, standby mutation rejection.

Reference behavior per SURVEY.md §3.2 ClaimPods: label-matching
ownerless pods are adopted, owned pods whose labels stop matching are
released, foreign-owned pods are ignored.
"""

import json
import urllib.error
import urllib.request

import pytest

from tests.testutil import harness, new_job
from tf_operator_tpu.api.types import (
    LABEL_JOB_NAME,
    JobConditionType,
    PodPhase,
    ReplicaType,
    RestartPolicy,
    replica_labels,
)
from tf_operator_tpu.backend.objects import Pod, WatchEventType


def submit(store, controller, job):
    stored = store.create(job)
    controller.sync_until_quiet()
    return stored


def make_pod(name, labels, owner_uid="", namespace="default"):
    pod = Pod()
    pod.metadata.name = name
    pod.metadata.namespace = namespace
    pod.metadata.labels = dict(labels)
    pod.metadata.owner_uid = owner_uid
    return pod


class TestAdoption:
    def test_ownerless_matching_pod_is_adopted(self):
        store, backend, c = harness()
        # a pod with the job's replica labels but no owner (e.g. created
        # before an operator restart that minted a new job uid)
        backend.create_pod(
            make_pod("job-worker-0", replica_labels("job", ReplicaType.WORKER, 0))
        )
        job = submit(store, c, new_job(worker=1))
        pod = backend.get_pod("default", "job-worker-0")
        assert pod.metadata.owner_uid == job.metadata.uid
        # adopted, not duplicated: exactly the one pre-created pod exists
        assert len(backend.list_pods("default")) == 1
        events = [e.reason for e in c.recorder.for_object(job.key)]
        assert "AdoptedPod" in events

    def test_adopted_pod_counts_toward_status(self):
        store, backend, c = harness()
        backend.create_pod(
            make_pod("job-worker-0", replica_labels("job", ReplicaType.WORKER, 0))
        )
        job = submit(store, c, new_job(worker=1))
        backend.run_all("default")
        c.sync_until_quiet()
        st = store.get("default", "job").status
        assert st.replica_statuses[ReplicaType.WORKER].active == 1
        backend.succeed_pod("default", "job-worker-0")
        c.sync_until_quiet()
        assert store.get("default", "job").status.has_condition(
            JobConditionType.SUCCEEDED
        )

    def test_label_mismatch_releases_pod_and_peer_adopts(self):
        """Relabeling a pod to another live job's selector: the original
        owner releases it (orphan), then the other job adopts it — the
        full ControllerRefManager handoff.  (Relabeling to a NONEXISTENT
        job instead gets the pod GC'd by the orphan-GC path — also
        correct, covered by controller GC tests.)"""

        store, backend, c = harness()
        job = submit(store, c, new_job(worker=1))
        job2 = submit(store, c, new_job(name="job2", worker=1))
        pod = backend._pods["default/job-worker-0"]
        assert pod.metadata.owner_uid == job.metadata.uid
        pod.metadata.labels[LABEL_JOB_NAME] = "job2"
        backend._emit(WatchEventType.MODIFIED, "Pod", pod)
        c.sync_until_quiet()
        # released by job, adopted by job2
        assert backend.get_pod("default", "job-worker-0").metadata.owner_uid == job2.metadata.uid
        assert "OrphanedPod" in [e.reason for e in c.recorder.for_object(job.key)]
        assert "AdoptedPod" in [e.reason for e in c.recorder.for_object(job2.key)]

    def test_foreign_owned_pod_ignored(self):
        store, backend, c = harness()
        intruder = make_pod(
            "intruder", replica_labels("job", ReplicaType.WORKER, 0), owner_uid="other-uid"
        )
        backend.create_pod(intruder)
        job = submit(store, c, new_job(worker=1))
        # reconciler created its own pod for index 0 and left the intruder
        assert backend.get_pod("default", "job-worker-0") is not None
        assert backend.get_pod("default", "intruder").metadata.owner_uid == "other-uid"
        # intruder's phase (PENDING) must not leak into replica statuses
        backend.run_all("default")
        c.sync_until_quiet()
        backend.succeed_pod("default", "job-worker-0")
        backend.fail_pod("default", "intruder", exit_code=1)
        c.sync_until_quiet()
        st = store.get("default", "job").status
        assert st.has_condition(JobConditionType.SUCCEEDED)
        assert st.replica_statuses[ReplicaType.WORKER].failed == 0


class TestServiceScaleInExpectations:
    def test_failed_service_delete_balances_expectation(self):
        store, backend, c = harness()
        job = submit(store, c, new_job(worker=2))
        key = job.key

        calls = {"n": 0}
        orig = backend.delete_service

        def flaky_delete(ns, name):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("backend hiccup")
            return orig(ns, name)

        backend.delete_service = flaky_delete
        stored = store.get("default", "job")
        stored.spec.replica_specs[ReplicaType.WORKER].replicas = 1
        store.update_spec(stored)
        c.sync_until_quiet()
        # first delete raised — the expectation must NOT stay pending
        # (a leaked expected-deletion would stall the job for the whole
        # expectations timeout)
        assert c.svc_exp.satisfied(key)
        # retry path eventually removes the service
        c.sync_until_quiet()
        names = {s.metadata.name for s in backend.list_services("default")}
        assert "job-worker-1" not in names


class TestBackoffAccounting:
    def test_restart_budget_is_job_global_across_types(self):
        """Pins the documented semantics: backoff_limit is a JOB-level
        budget (reference: RunPolicy.BackoffLimit), so restarts in one
        replica type consume another type's headroom within the same
        sync — chief restarts first (ordered_types), worker then trips
        the exhausted budget."""

        store, backend, c = harness()
        job = new_job(chief=1, worker=1, restart_policy=RestartPolicy.ON_FAILURE)
        job.spec.run_policy.backoff_limit = 1
        submit(store, c, job)
        backend.run_all("default")
        c.sync_until_quiet()
        backend.fail_pod("default", "job-chief-0", exit_code=1)
        backend.fail_pod("default", "job-worker-0", exit_code=1)
        c.sync_until_quiet()
        st = store.get("default", "job").status
        # chief consumed the single restart; the worker's failure then
        # exceeded the job-global budget
        assert st.restart_count == 1
        assert st.has_condition(JobConditionType.FAILED)
        failed = [
            cond for cond in st.conditions if cond.type is JobConditionType.FAILED
        ]
        assert failed[-1].reason == "BackoffLimitExceeded"


class TestStandbyRejectsMutations:
    @pytest.fixture()
    def standby(self):
        from tf_operator_tpu.server.api import ApiServer

        store, backend, c = harness()
        api = ApiServer(
            store,
            backend,
            c.metrics,
            c.recorder,
            port=0,
            leadership=lambda: (False, "pid-leader-42"),
        )
        api.start()
        yield api
        api.stop()

    def test_post_rejected_503_with_holder(self, standby):
        manifest = {
            "apiVersion": "tpu-operator/v1",
            "kind": "TPUJob",
            "metadata": {"name": "j1"},
            "spec": {
                "replicaSpecs": {
                    "Worker": {
                        "replicas": 1,
                        "template": {
                            "containers": [{"command": ["python", "x.py"]}]
                        },
                    }
                }
            },
        }
        req = urllib.request.Request(
            f"http://127.0.0.1:{standby.port}/apis/v1/namespaces/default/tpujobs",
            data=json.dumps(manifest).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 503
        body = json.loads(ei.value.read())
        assert body["leader"] == "pid-leader-42"

    def test_delete_and_reads_rejected_health_open(self, standby):
        req = urllib.request.Request(
            f"http://127.0.0.1:{standby.port}/apis/v1/namespaces/default/tpujobs/x",
            method="DELETE",
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 503
        # job-API reads 503 too: the standby's own store is EMPTY, so a
        # 200 would report running jobs as deleted (wrong, not stale)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{standby.port}/apis/v1/tpujobs", timeout=10
            )
        assert ei.value.code == 503
        # liveness surfaces stay open on standbys
        with urllib.request.urlopen(
            f"http://127.0.0.1:{standby.port}/healthz", timeout=10
        ) as r:
            assert r.status == 200
        with urllib.request.urlopen(
            f"http://127.0.0.1:{standby.port}/metrics", timeout=10
        ) as r:
            assert r.status == 200


class TestGcOwnerCheck:
    def test_gc_spares_live_foreign_owned_pods_on_name_reuse(self):
        """VERDICT r2 weak #5: deleting job A must not collect a
        label-matching pod that belongs to a different, still-live
        controller — the adoption pass ignored it, GC must too."""

        store, backend, c = harness()
        job_b = submit(store, c, new_job("job-b", worker=1))
        # a pod carrying job A's name label but owned by live job B
        # (name collision / relabeled pod)
        backend.create_pod(
            make_pod(
                "stray-a-worker-9",
                replica_labels("job-a", ReplicaType.WORKER, 9),
                owner_uid=job_b.metadata.uid,
            )
        )
        job_a = submit(store, c, new_job("job-a", worker=1))
        # A ignored the foreign pod and created its own
        own = [
            p
            for p in backend.list_pods("default", {LABEL_JOB_NAME: "job-a"})
            if p.metadata.owner_uid == job_a.metadata.uid
        ]
        assert len(own) == 1

        store.delete("default", "job-a")
        c.sync_until_quiet()
        remaining = {p.metadata.name for p in backend.list_pods("default")}
        # A's own pod collected; B's label-matching pod survives
        assert "job-a-worker-0" not in remaining
        assert "stray-a-worker-9" in remaining

    def test_gc_collects_ownerless_and_dead_owner_pods(self):
        store, backend, c = harness()
        job = submit(store, c, new_job("gone", worker=1))
        backend.create_pod(
            make_pod(
                "gone-extra",
                replica_labels("gone", ReplicaType.WORKER, 7),
                owner_uid="uid-of-a-job-that-no-longer-exists",
            )
        )
        store.delete("default", "gone")
        c.sync_until_quiet()
        names = {p.metadata.name for p in backend.list_pods("default")}
        assert "gone-worker-0" not in names
        assert "gone-extra" not in names


class TestAdoptionReentrancy:
    """Round-2 review note: `update_pod_owner` emits MODIFIED
    synchronously under the reconcile call stack, so adoption
    re-enqueues the job mid-sync.  Pin that this is benign: the queue
    dedupes, the follow-up sync is a no-op, and nothing duplicates."""

    def test_sync_reentrant_enqueue_is_benign(self):
        store, backend, c = harness()
        # two ownerless pods so adoption fires twice in one sync
        for i in range(2):
            backend.create_pod(
                make_pod(
                    f"job-worker-{i}", replica_labels("job", ReplicaType.WORKER, i)
                )
            )
        job = submit(store, c, new_job(worker=2))
        # both adopted, nothing re-created by the re-entrant syncs
        pods = backend.list_pods("default")
        assert len(pods) == 2
        assert all(p.metadata.owner_uid == job.metadata.uid for p in pods)
        # the queue fully drained (sync_until_quiet returned) and the
        # next manual sync is a no-op: same pods, same resource state
        before = sorted(p.metadata.name for p in pods)
        c.sync_until_quiet()
        after = sorted(p.metadata.name for p in backend.list_pods("default"))
        assert before == after
        # adoption produced exactly one event per pod — the re-entrant
        # passes did not re-adopt
        events = [e.reason for e in c.recorder.for_object(job.key)]
        assert events.count("AdoptedPod") == 2
