"""MoE / expert-parallelism tests (VERDICT round 1 item 7: make the ep
axis real).  Runs on the virtual 8-CPU mesh from conftest."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# default-tier exclusion (routed-MoE train compiles); see README 'Tests run in two tiers'
pytestmark = pytest.mark.slow

from tf_operator_tpu.models import moe_lm_loss, moe_tiny
from tf_operator_tpu.models.moe import MoeConfig, MoeMlp
from tf_operator_tpu.models.transformer import TransformerConfig
from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh


def _find(tree, path):
    for p in path:
        tree = tree[p]
    return tree


class TestExpertSharding:
    def test_expert_weights_sharded_over_ep(self):
        mesh = make_mesh({"dp": 2, "ep": 4})
        ids = jnp.zeros((4, 16), jnp.int32)
        model = moe_tiny(vocab_size=64, max_len=16, num_experts=4)
        trainer = Trainer(
            model,
            TrainerConfig(learning_rate=1e-3),
            mesh,
            moe_lm_loss,
            {"input_ids": ids},
            init_args=(ids,),
            shardings="logical",
        )
        wi_sharding = _find(
            trainer.state_sharding.params, ("layer_0", "moe", "wi")
        )
        spec = wi_sharding.spec
        # leading (expert) dim rides the ep mesh axis
        assert spec[0] == "ep"
        # and the actual param is laid out that way on devices
        wi = _find(trainer.state.params, ("layer_0", "moe", "wi"))
        value = getattr(wi, "value", wi)
        assert value.sharding.spec[0] == "ep"

    def test_train_step_runs_and_loss_decreases(self):
        mesh = make_mesh({"dp": 4, "ep": 2})
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, 64, size=(8, 16)))
        model = moe_tiny(vocab_size=64, max_len=16, num_experts=4)
        trainer = Trainer(
            model,
            TrainerConfig(learning_rate=1e-2),
            mesh,
            moe_lm_loss,
            {"input_ids": ids},
            init_args=(ids,),
            shardings="logical",
        )
        batch = trainer.shard_batch({"input_ids": ids})
        first = trainer.train_step(batch)
        assert np.isfinite(float(first["loss"]))
        assert float(first["moe_aux_loss"]) > 0.0
        for _ in range(10):
            last = trainer.train_step(batch)
        assert float(last["loss"]) < float(first["loss"])


class TestRoutingMath:
    def test_single_expert_equals_dense_mlp(self):
        """num_experts=1 collapses routing to identity (gate 1.0, no
        drops at default capacity), so the block must equal the plain
        gelu FFN computed from the same weights."""

        cfg = MoeConfig(
            base=TransformerConfig(
                vocab_size=8, hidden=16, n_heads=2, head_dim=8,
                n_layers=1, mlp_dim=32, max_len=8, dropout=0.0,
                dtype=jnp.float32,
            ),
            num_experts=1,
            capacity_factor=2.0,
        )
        block = MoeMlp(cfg)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
        variables = block.init(jax.random.PRNGKey(1), x)
        out = block.apply(variables, x)
        wi = variables["params"]["wi"]
        wo = variables["params"]["wo"]
        wi = getattr(wi, "value", wi)
        wo = getattr(wo, "value", wo)
        ref = jnp.einsum(
            "bsm,mh->bsh", jax.nn.gelu(jnp.einsum("bsh,hm->bsm", x, wi[0])), wo[0]
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_capacity_drops_tokens_but_stays_finite(self):
        """capacity_factor→0 forces drops; dropped tokens contribute
        zero (residual passthrough), everything stays finite."""

        cfg = MoeConfig(
            base=TransformerConfig(
                vocab_size=8, hidden=16, n_heads=2, head_dim=8,
                n_layers=1, mlp_dim=32, max_len=32, dropout=0.0,
                dtype=jnp.float32,
            ),
            num_experts=2,
            capacity_factor=0.1,
        )
        block = MoeMlp(cfg)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 16))
        variables = block.init(jax.random.PRNGKey(1), x)
        out = block.apply(variables, x)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_aux_loss_balanced_vs_skewed(self):
        """The load-balance loss must be ~1x for uniform routing and
        larger when the router collapses onto one expert."""

        n, e = 4096, 4
        uniform = jnp.ones((1, n, e)) / e
        frac_t = jnp.mean(jax.nn.one_hot(jnp.argmax(uniform, -1), e), (0, 1))
        # analytic check of the Switch formula on the uniform case:
        # argmax breaks ties to expert 0, so this is the worst case for
        # the *token* fraction; use the probs term only as sanity
        probs_term = jnp.mean(uniform, (0, 1))
        assert float(jnp.sum(probs_term)) == pytest.approx(1.0)
        # end-to-end: a trained-from-noise router yields aux > 0
        cfg = MoeConfig(
            base=TransformerConfig(
                vocab_size=8, hidden=16, n_heads=2, head_dim=8,
                n_layers=1, mlp_dim=32, max_len=16, dropout=0.0,
                dtype=jnp.float32,
            ),
            num_experts=e,
        )
        block = MoeMlp(cfg)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 16))
        variables = block.init(jax.random.PRNGKey(1), x)
        _, mutated = block.apply(variables, x, mutable=["losses"])
        aux = float(jax.tree_util.tree_leaves(mutated["losses"])[0])
        assert aux > 0.0


def test_moe_with_sequence_parallelism_matches_no_sp():
    """ep x sp composition: expert-parallel FFNs + ring attention over
    sequence shards must train identically to the unsharded layout
    (routing is a global dense dispatch — sharding cannot change it)."""

    ids = np.random.RandomState(5).randint(0, 128, size=(8, 32)).astype(np.int32)
    losses = {}
    for label, shape in [("nosp", {"dp": 4, "ep": 2}), ("sp", {"dp": 2, "ep": 2, "sp": 2})]:
        mesh = make_mesh(shape)
        model = moe_tiny(vocab_size=128, max_len=32, num_experts=4, mesh=mesh)
        tr = Trainer(
            model,
            TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
            mesh,
            moe_lm_loss,
            {"input_ids": ids},
            init_args=(ids,),
            shardings="logical",
            seed=9,
        )
        losses[label] = [
            float(tr.train_step(tr.shard_batch({"input_ids": ids}))["loss"])
            for _ in range(3)
        ]
    np.testing.assert_allclose(losses["nosp"], losses["sp"], rtol=2e-4, atol=2e-4)
