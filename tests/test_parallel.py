"""Mesh / sharding / trainer tests on the 8-virtual-device CPU mesh.

SURVEY.md §4 rebuild mapping: multi-chip semantics tested without a
multi-chip slice — the mesh is real, the devices are virtual CPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# default-tier exclusion (trainer/sharding compiles); see README 'Tests run in two tiers'
pytestmark = pytest.mark.slow
from jax.sharding import NamedSharding, PartitionSpec

from tf_operator_tpu.models import MnistCNN, resnet18
from tf_operator_tpu.parallel import (
    Trainer,
    TrainerConfig,
    batch_sharding,
    fsdp_shardings,
    make_mesh,
)
from tf_operator_tpu.parallel.mesh import data_parallel_size, local_batch_size
from tf_operator_tpu.parallel.sharding import fsdp_spec
from tf_operator_tpu.parallel.trainer import (
    batchnorm_cross_entropy_loss,
    cross_entropy_loss,
)


def test_make_mesh_default_all_dp():
    mesh = make_mesh()
    assert mesh.shape["dp"] == len(jax.devices())
    assert all(mesh.shape[ax] == 1 for ax in ("fsdp", "tp", "sp", "ep"))


def test_make_mesh_wildcard_and_validation():
    mesh = make_mesh({"dp": 2, "fsdp": -1})
    assert mesh.shape["dp"] == 2 and mesh.shape["fsdp"] == 4
    with pytest.raises(ValueError):
        make_mesh({"dp": 3})
    with pytest.raises(ValueError):
        make_mesh({"bogus": 2})
    with pytest.raises(ValueError):
        make_mesh({"dp": -1, "tp": -1})


def test_data_parallel_size_and_local_batch():
    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    assert data_parallel_size(mesh) == 4
    assert local_batch_size(mesh, 32) == 8
    with pytest.raises(ValueError):
        local_batch_size(mesh, 30)


def test_fsdp_spec_rules():
    # too small -> replicated
    assert fsdp_spec((4, 4), 8) == PartitionSpec()
    # largest divisible dim gets the axis (ties -> later dim)
    assert fsdp_spec((256, 1024), 8, min_size=0) == PartitionSpec(None, "fsdp")
    assert fsdp_spec((1024, 256), 8, min_size=0) == PartitionSpec("fsdp", None)
    # no divisible dim -> replicated
    assert fsdp_spec((25, 31), 8, min_size=0) == PartitionSpec()
    # fsdp axis of 1 -> replicated
    assert fsdp_spec((1024, 1024), 1) == PartitionSpec()


def test_fsdp_shardings_tree():
    mesh = make_mesh({"fsdp": 8})
    params = {
        "dense": {"kernel": jnp.zeros((128, 512)), "bias": jnp.zeros((512,))},
    }
    sh = fsdp_shardings(params, mesh)
    assert sh["dense"]["kernel"].spec == PartitionSpec(None, "fsdp")
    assert sh["dense"]["bias"].spec == PartitionSpec()


def _mnist_batch(n=16):
    rng = np.random.RandomState(0)
    return {
        "image": jnp.asarray(rng.rand(n, 28, 28, 1), jnp.float32),
        "label": jnp.asarray(rng.randint(0, 10, size=(n,))),
    }


def test_mnist_trainer_dp_loss_decreases():
    mesh = make_mesh({"dp": 8})
    batch = _mnist_batch(16)
    tr = Trainer(
        MnistCNN(), TrainerConfig(learning_rate=1e-3), mesh, cross_entropy_loss, batch
    )
    batch = tr.shard_batch(batch)
    first = tr.train_step(batch)
    for _ in range(5):
        last = tr.train_step(batch)
    assert float(last["loss"]) < float(first["loss"])
    # batch really is sharded over dp
    assert tr.shard_batch(batch)["image"].sharding.spec == PartitionSpec(("dp", "fsdp"))


def test_mnist_trainer_fsdp_params_sharded():
    mesh = make_mesh({"dp": 2, "fsdp": 4})
    batch = _mnist_batch(16)
    tr = Trainer(MnistCNN(), TrainerConfig(), mesh, cross_entropy_loss, batch)
    kernel = tr.state.params["Dense_0"]["kernel"]
    assert "fsdp" in jax.tree_util.tree_leaves(
        [ax for ax in kernel.sharding.spec if ax is not None]
    )
    tr.train_step(tr.shard_batch(batch))  # compiles + runs


def test_resnet18_batchnorm_trainer():
    mesh = make_mesh({"dp": 4, "fsdp": 2})
    rng = np.random.RandomState(1)
    batch = {
        "image": jnp.asarray(rng.rand(8, 32, 32, 3), jnp.float32),
        "label": jnp.asarray(rng.randint(0, 10, size=(8,))),
    }
    tr = Trainer(
        resnet18(num_classes=10),
        TrainerConfig(optimizer="sgd", learning_rate=0.1),
        mesh,
        batchnorm_cross_entropy_loss,
        batch,
    )
    before = jax.tree_util.tree_map(
        lambda x: np.asarray(x).copy(), tr.state.model_state["batch_stats"]
    )
    tr.train_step(tr.shard_batch(batch))
    after = tr.state.model_state["batch_stats"]
    # batch_stats updated by the mutable pass
    changed = jax.tree_util.tree_map(
        lambda a, b: bool(np.any(np.asarray(a) != np.asarray(b))), before, after
    )
    assert any(jax.tree_util.tree_leaves(changed))


def test_trainer_benchmark_smoke():
    mesh = make_mesh({"dp": 8})
    batch = _mnist_batch(8)
    tr = Trainer(MnistCNN(), TrainerConfig(), mesh, cross_entropy_loss, batch)
    stats = tr.benchmark(batch, steps=2, warmup=1)
    assert stats["steps_per_sec"] > 0
    assert stats["examples_per_sec"] == pytest.approx(stats["steps_per_sec"] * 8)


def test_trainer_param_dtype_bf16_storage():
    """TrainerConfig.param_dtype=bf16: params AND optimizer moments
    store bf16 (the HBM-traffic probe knob, PROFILE.md r5), and a
    train step updates params while KEEPING them bf16 — the whole
    contract is storage dtype, so moment dtypes and the post-step
    param dtype are asserted, not just the init-time cast."""

    mesh = make_mesh({"dp": 8})
    batch = _mnist_batch(8)
    tr = Trainer(
        MnistCNN(),
        TrainerConfig(param_dtype=jnp.bfloat16),
        mesh,
        cross_entropy_loss,
        batch,
    )

    def float_dtypes(tree):
        return {
            str(l.dtype)
            for l in jax.tree_util.tree_leaves(tree)
            if jnp.issubdtype(l.dtype, jnp.floating)
        }

    assert float_dtypes(tr.state.params) == {"bfloat16"}
    # optax moments inherit the param dtype (the trainer comment's
    # claim — pinned here so an optax default change can't silently
    # reintroduce f32 moment traffic)
    assert float_dtypes(tr.state.opt_state) == {"bfloat16"}
    before = jax.tree_util.tree_map(
        lambda x: np.asarray(x).copy(), tr.state.params
    )
    tr.train_step(tr.shard_batch(batch))
    assert float_dtypes(tr.state.params) == {"bfloat16"}  # no promotion
    changed = jax.tree_util.tree_map(
        lambda a, b: bool(np.any(np.asarray(a) != np.asarray(b))),
        before,
        tr.state.params,
    )
    assert any(jax.tree_util.tree_leaves(changed))


class TestTrainerCheckpointer:
    def test_save_restore_roundtrip_sharded(self, tmp_path):
        """Save a sharded TrainState, restore into a FRESH trainer on
        the same mesh: states identical, training continues from the
        restored step (SURVEY.md §5 checkpoint/resume as a framework
        component, not example plumbing)."""

        import jax
        import jax.numpy as jnp
        import numpy as np

        from tf_operator_tpu.models import MnistCNN
        from tf_operator_tpu.parallel import (
            Trainer,
            TrainerCheckpointer,
            TrainerConfig,
            make_mesh,
        )
        from tf_operator_tpu.parallel.trainer import cross_entropy_loss

        mesh = make_mesh({"dp": 2, "fsdp": 2}, devices=jax.devices()[:4])
        r = np.random.RandomState(0)
        batch = {
            "image": jnp.asarray(r.rand(8, 28, 28, 1), jnp.float32),
            "label": jnp.asarray(r.randint(0, 10, size=(8,))),
        }

        def mk():
            return Trainer(
                MnistCNN(),
                TrainerConfig(optimizer="sgd", learning_rate=0.05),
                mesh,
                cross_entropy_loss,
                batch,
            )

        t1 = mk()
        sb = t1.shard_batch(batch)
        for _ in range(3):
            t1.train_step(sb)
        ck = TrainerCheckpointer(str(tmp_path / "ck"))
        saved = ck.save(t1, wait=True)
        assert saved == 3

        t2 = mk()
        restored = TrainerCheckpointer(str(tmp_path / "ck")).restore_latest(t2)
        assert restored == 3
        assert int(t2.state.step) == 3
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            t1.state.params,
            t2.state.params,
        )
        # restored shardings match the trainer's layout
        leaf = jax.tree_util.tree_leaves(t2.state.params)[0]
        want = jax.tree_util.tree_leaves(t2.state_sharding.params)[0]
        assert leaf.sharding == want
        # training continues
        m = t2.train_step(sb)
        assert int(t2.state.step) == 4 and np.isfinite(float(m["loss"]))

    def test_restore_latest_empty_dir(self, tmp_path):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from tf_operator_tpu.models import MnistCNN
        from tf_operator_tpu.parallel import (
            Trainer,
            TrainerCheckpointer,
            TrainerConfig,
            make_mesh,
        )
        from tf_operator_tpu.parallel.trainer import cross_entropy_loss

        mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
        r = np.random.RandomState(0)
        batch = {
            "image": jnp.asarray(r.rand(4, 28, 28, 1), jnp.float32),
            "label": jnp.asarray(r.randint(0, 10, size=(4,))),
        }
        t = Trainer(
            MnistCNN(), TrainerConfig(optimizer="sgd"), mesh, cross_entropy_loss, batch
        )
        assert TrainerCheckpointer(str(tmp_path / "empty")).restore_latest(t) is None


def test_eval_step_and_evaluate():
    """Forward-only eval: no state mutation, deterministic, mean over
    batches."""

    import numpy as np

    from tf_operator_tpu.models import gpt_tiny, lm_loss
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

    mesh = make_mesh({"dp": 8})
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, size=(8, 16)).astype(np.int32)
    batch = {"input_ids": ids}
    tr = Trainer(
        gpt_tiny(vocab_size=64, max_len=16, dropout=0.0),
        TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
        mesh,
        lm_loss,
        batch,
        init_args=(ids,),
        shardings="logical",
    )
    step_before = int(tr.state.step)
    m1 = tr.eval_step(tr.shard_batch(batch))
    m2 = tr.eval_step(tr.shard_batch(batch))
    assert int(tr.state.step) == step_before  # no update
    assert float(m1["loss"]) == float(m2["loss"])  # deterministic
    # evaluate() means over batches; single batch == eval_step
    mean = tr.evaluate([batch])
    np.testing.assert_allclose(mean["loss"], float(m1["loss"]), rtol=1e-6)
    # train loss on the same batch matches eval loss at the same params
    tm = tr.train_step(tr.shard_batch(batch))
    np.testing.assert_allclose(float(tm["loss"]), float(m1["loss"]), rtol=1e-5)


def test_adafactor_optimizer_trains():
    import numpy as np

    from tf_operator_tpu.models import gpt_tiny, lm_loss
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

    mesh = make_mesh({"dp": 8})
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 64, size=(8, 16)).astype(np.int32)
    batch = {"input_ids": ids}
    tr = Trainer(
        gpt_tiny(vocab_size=64, max_len=16, dropout=0.0),
        TrainerConfig(learning_rate=3e-2, optimizer="adafactor", grad_clip=0.0),
        mesh,
        lm_loss,
        batch,
        init_args=(ids,),
        shardings="logical",
    )
    first = float(tr.train_step(tr.shard_batch(batch))["loss"])
    for _ in range(5):
        last = float(tr.train_step(tr.shard_batch(batch))["loss"])
    assert last < first


def test_clamp_preserves_param_sharding_with_adafactor():
    """clamp_overranked must replicate only the over-ranked factored
    optimizer stats — never the (boxed) 2-d kernels themselves."""

    import numpy as np

    from tf_operator_tpu.models import gpt_tiny, lm_loss
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

    mesh = make_mesh({"fsdp": 2, "tp": 2, "dp": 2})
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, size=(4, 16)).astype(np.int32)
    tr = Trainer(
        gpt_tiny(vocab_size=64, max_len=16, dropout=0.0),
        TrainerConfig(learning_rate=1e-2, optimizer="adafactor", grad_clip=0.0),
        mesh,
        lm_loss,
        {"input_ids": ids},
        init_args=(ids,),
        shardings="logical",
    )
    wi = tr.state.params["layer_0"]["mlp"]["wi"]["kernel"]
    leaf = getattr(wi, "value", wi)
    axes = {ax for axs in leaf.sharding.spec if axs for ax in (axs if isinstance(axs, tuple) else (axs,))}
    assert "tp" in axes, leaf.sharding  # kernel sharding survived the clamp
    first = float(tr.train_step(tr.shard_batch({"input_ids": ids}))["loss"])
    last = first
    for _ in range(4):
        last = float(tr.train_step(tr.shard_batch({"input_ids": ids}))["loss"])
    assert last < first


def test_eval_runs_inference_mode():
    """With dropout active, eval_step (train=False) must differ from the
    train-mode loss and stay deterministic."""

    import numpy as np

    from tf_operator_tpu.models import gpt_tiny, lm_loss
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

    mesh = make_mesh({"dp": 8})
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, size=(8, 16)).astype(np.int32)
    batch = {"input_ids": ids}
    tr = Trainer(
        gpt_tiny(vocab_size=64, max_len=16, dropout=0.3),
        TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
        mesh,
        lm_loss,
        batch,
        init_args=(ids,),
        shardings="logical",
    )
    e1 = float(tr.eval_step(tr.shard_batch(batch))["loss"])
    e2 = float(tr.eval_step(tr.shard_batch(batch))["loss"])
    assert e1 == e2  # deterministic
    t1 = float(tr.train_step(tr.shard_batch(batch))["loss"])
    # dropout noise puts the train-mode loss away from the clean loss
    assert abs(t1 - e1) > 1e-4


def test_gradient_accumulation_matches_big_batch():
    """accum_steps=2 over two half-batches must equal one SGD step on
    the averaged gradient (i.e. the full batch, since loss is a mean)."""

    import numpy as np

    from tf_operator_tpu.models import gpt_tiny, lm_loss
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

    mesh = make_mesh({"dp": 8})
    rng = np.random.RandomState(0)
    full = rng.randint(0, 64, size=(16, 16)).astype(np.int32)
    halves = [full[:8], full[8:]]

    def build(accum):
        return Trainer(
            gpt_tiny(vocab_size=64, max_len=16, dropout=0.0),
            TrainerConfig(
                learning_rate=1e-1, optimizer="sgd", momentum=0.0,
                grad_clip=0.0, accum_steps=accum,
            ),
            mesh,
            lm_loss,
            {"input_ids": halves[0]},
            init_args=(halves[0],),
            shardings="logical",
            seed=3,
        )

    import jax

    tr_acc = build(2)
    p0 = jax.device_get(tr_acc.state.params)
    tr_acc.train_step(tr_acc.shard_batch({"input_ids": np.ascontiguousarray(halves[0])}))
    # mid-window: gradients accumulated, NO update applied yet
    p_mid = jax.device_get(tr_acc.state.params)
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p_mid)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    tr_acc.train_step(tr_acc.shard_batch({"input_ids": np.ascontiguousarray(halves[1])}))

    tr_big = build(1)
    tr_big.train_step(tr_big.shard_batch({"input_ids": full}))

    pa = jax.device_get(tr_acc.state.params)
    pb = jax.device_get(tr_big.state.params)
    moved = False
    for a, b, z in zip(jax.tree.leaves(pa), jax.tree.leaves(pb), jax.tree.leaves(p0)):
        # the update itself must match the big-batch step; bf16
        # activations round differently per batch composition, so
        # near-equal (rounding scale), not bit-equal
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
        moved = moved or not np.array_equal(np.asarray(a), np.asarray(z))
    assert moved  # the end-of-window step really applied an update
