"""Speculative decoding on the paged plane (ISSUE 18 tentpole).

The load-bearing pins:

- TOKEN IDENTITY: a speculating pool emits byte-identical greedy
  tokens to the non-speculative paged pool — across accept AND
  rollback boundaries, under prefix-hit admission, and for mixed
  windows where speculating and plain seats share the arena.  On BOTH
  step paths (gather emulation and the interpret-mode Pallas kernel).
- LEDGER PIN: the speculative steady state is exactly ONE ``draft``
  plus ONE ``verify`` dispatch per window (a mixed window adds the
  plain seats' single ``step``), and with a perfect draft the
  dispatches-per-emitted-token falls below 1.0 — the CPU-honest
  speculation win the serve_lm refusal guard requires measured.
- ARENA SHARING: draft pages come from the SAME BlockAllocator; the
  allocator conserves through speculative admit/decode/retire and the
  draft refs drain with the seats.
- HONESTY: a typo'd tier, an unusable spec_k, or missing draft params
  fail construction loudly — never a silent downgrade to
  non-speculative serving (the PR 10 rule).

Accept/reject boundary behavior is fuzzed with seeded divergent-draft
configs on both kernel paths; preemption/resume of a speculating seat
lives in tests/test_preemption.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tf_operator_tpu.models import llama_tiny
from tf_operator_tpu.models.batching import PagedContinuousBatchingDecoder

VOCAB = 96


def _setup(max_len=64):
    model = llama_tiny(vocab_size=VOCAB, max_len=max_len)
    init = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), init)["params"]
    # a second tiny init IS a different model: divergent proposals
    # exercise the reject path without a second architecture
    draft = llama_tiny(vocab_size=VOCAB, max_len=max_len)
    dparams = draft.init(jax.random.PRNGKey(2), init)["params"]
    return model, params, draft, dparams


def _prompt(r, n):
    return r.randint(0, VOCAB, size=(n,)).astype(np.int32)


@pytest.mark.slow
class TestSpecTokenIdentity:
    @pytest.mark.parametrize("kernel", ["off", "interpret"])
    def test_greedy_identity_across_accept_and_rollback(self, kernel):
        """The acceptance pin: greedy output of the speculating pool is
        byte-identical to the non-speculative paged pool.  The
        divergent draft guarantees both full-accept and mid-window
        rollback boundaries occur; identical bytes across them means
        rollback rewinds EXACTLY (a stale rejected append leaking into
        the next window would change tokens)."""

        model, params, draft, dparams = _setup()
        r = np.random.RandomState(4)
        reqs = [(_prompt(r, n), b) for n, b in [(6, 24), (11, 17), (3, 9)]]

        plain = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, steps_per_sync=8,
            paged_kernel=kernel,
        )
        want = {}
        for p, b in reqs:
            want[len(want)] = (p, b)
        rids = [plain.submit(p, max_new_tokens=b, tier="interactive")
                for p, b in reqs]
        plain.run()
        outs = [plain.result(rid) for rid in rids]

        spec = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, steps_per_sync=8,
            paged_kernel=kernel, draft_model=draft, draft_params=dparams,
            spec_k=3,
        )
        srids = [spec.submit(p, max_new_tokens=b, tier="interactive")
                 for p, b in reqs]
        spec.run()
        for rid, out in zip(srids, outs):
            np.testing.assert_array_equal(spec.result(rid), out)
        snap = spec.spec_snapshot()
        assert snap["spec_rollbacks"] >= 1, (
            "divergent draft never rejected — rollback boundary unexercised"
        )
        assert snap["spec_accepted"] >= 1, (
            "divergent draft never accepted — accept boundary unexercised"
        )
        spec.alloc.check()
        assert not spec._draft_refs

    def test_mixed_tier_window_and_tier_gating(self):
        """Speculation is tier-gated (interactive only by default):
        batch seats in the SAME window step through the plain program,
        and both tiers' tokens match the non-speculative pool — the
        enabled-mask never bleeds one path into the other."""

        model, params, draft, dparams = _setup()
        r = np.random.RandomState(7)
        pi, pb = _prompt(r, 6), _prompt(r, 9)

        plain = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, steps_per_sync=4,
        )
        ri = plain.submit(pi, max_new_tokens=16, tier="interactive")
        rb = plain.submit(pb, max_new_tokens=16)
        plain.run()
        want_i, want_b = plain.result(ri), plain.result(rb)

        spec = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, steps_per_sync=4,
            draft_model=draft, draft_params=dparams, spec_k=3,
        )
        si = spec.submit(pi, max_new_tokens=16, tier="interactive")
        sb = spec.submit(pb, max_new_tokens=16)
        spec.run()
        np.testing.assert_array_equal(spec.result(si), want_i)
        np.testing.assert_array_equal(spec.result(sb), want_b)
        # only the interactive seat speculated
        snap = spec.spec_snapshot()
        assert snap["spec_windows"] >= 1
        assert snap["spec_emitted"] <= 16
        spec.alloc.check()

    @pytest.mark.parametrize("kernel", ["off", "interpret"])
    def test_prefix_hit_admission_identity(self, kernel):
        """A speculating seat admitted THROUGH a prefix-cache hit (its
        target prompt KV partly served from published blocks, its draft
        prefill always computed fresh — the draft never prefix-shares)
        still decodes byte-identically to the non-speculative pool's
        prefix-hit run."""

        model, params, draft, dparams = _setup()
        r = np.random.RandomState(9)
        head = _prompt(r, 32)  # two publishable full blocks
        tail_a, tail_b = _prompt(r, 5), _prompt(r, 7)
        pa = np.concatenate([head, tail_a])
        pb = np.concatenate([head, tail_b])

        outs = {}
        for speculate in (False, True):
            kw = (
                dict(draft_model=draft, draft_params=dparams, spec_k=3)
                if speculate else {}
            )
            pool = PagedContinuousBatchingDecoder(
                model, params, slots=4, kv_block_size=16,
                steps_per_sync=8, paged_kernel=kernel, **kw,
            )
            ra = pool.submit(pa, max_new_tokens=12, tier="interactive")
            pool.run()  # A publishes the shared head blocks
            rb = pool.submit(pb, max_new_tokens=12, tier="interactive")
            pool.run()
            assert pool.prefix.hits >= 1, "scenario failed to prefix-hit"
            outs[speculate] = (pool.result(ra), pool.result(rb))
            pool.alloc.check()
        np.testing.assert_array_equal(outs[True][0], outs[False][0])
        np.testing.assert_array_equal(outs[True][1], outs[False][1])


@pytest.mark.slow
class TestLedgerPins:
    @pytest.mark.parametrize("kernel", ["off", "interpret"])
    def test_steady_state_is_one_draft_one_verify(self, kernel):
        """The dispatch-budget pin on BOTH kernel paths: once admitted,
        every speculative window is exactly ONE ``draft`` + ONE
        ``verify`` dispatch — growth deltas ride the verify dispatch,
        accept/rollback never add a fixup dispatch, and the plain
        ``step`` phase never fires for an all-speculating pool."""

        model, params, draft, dparams = _setup()
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=2, kv_block_size=16,
            paged_kernel=kernel, draft_model=draft,
            draft_params=dparams, spec_k=3,
        )
        rid = pool.submit(
            np.arange(6, dtype=np.int32) % VOCAB, max_new_tokens=40,
            tier="interactive",
        )
        pool.step()  # admission (incl. draft prefill) + window 1

        def _done():  # result() evicts on first read — don't re-read
            with pool._lock:
                return pool._results[rid].done

        grew = False
        for _ in range(40):
            if _done():
                break  # the final window retires in the same step()
            with pool._lock:
                committed0 = len(pool._seat_refs.get(0, ()))
            base = pool.ledger.count()
            drafts0 = pool.ledger.count("draft")
            verifies0 = pool.ledger.count("verify")
            steps0 = pool.ledger.count("step")
            pool.step()
            with pool._lock:
                if 0 in pool._seat_refs and \
                        len(pool._seat_refs[0]) > committed0:
                    grew = True
            if _done():
                break
            assert pool.ledger.count() == base + 2
            assert pool.ledger.count("draft") == drafts0 + 1
            assert pool.ledger.count("verify") == verifies0 + 1
            assert pool.ledger.count("step") == steps0
        assert grew, "scenario never crossed a block boundary"
        pool.run()
        assert pool.result(rid) is not None
        snap = pool.ledger.snapshot()
        assert set(snap) <= {"admission", "draft", "verify", "retire"}, snap
        pool.alloc.check()

    def test_self_draft_beats_one_dispatch_per_token(self):
        """The CPU-honest win: with a perfect draft (draft == target)
        every window accepts all K, so dispatches-per-emitted-token =
        2/(K+1) < 1.0 — the number the refusal guard requires measured
        above parity before --speculative serves."""

        model, params, _, _ = _setup()
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=2, kv_block_size=16,
            draft_model=llama_tiny(vocab_size=VOCAB, max_len=64),
            draft_params=params, spec_k=3,
        )
        rid = pool.submit(
            np.arange(6, dtype=np.int32) % VOCAB, max_new_tokens=24,
            tier="interactive",
        )
        pool.run()
        assert pool.result(rid) is not None
        snap = pool.spec_snapshot()
        assert snap["acceptance_rate"] == 1.0
        assert snap["dispatches_per_token"] < 1.0
        assert snap["spec_rollbacks"] == 0

    def test_mixed_window_is_three_dispatches(self):
        """A window holding BOTH a plain seat and a speculating seat
        costs step + draft + verify — never more (no per-seat
        dispatches, no accept fixups)."""

        model, params, draft, dparams = _setup()
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, steps_per_sync=4,
            draft_model=draft, draft_params=dparams, spec_k=3,
        )
        pool.submit(np.arange(6, dtype=np.int32) % VOCAB,
                    max_new_tokens=48, tier="interactive")
        pool.submit(np.arange(9, dtype=np.int32) % VOCAB,
                    max_new_tokens=48)
        pool.step()  # admissions + window 1
        for _ in range(3):
            base = pool.ledger.count()
            pool.step()
            assert pool.ledger.count() == base + 3
        counts = {p: pool.ledger.count(p)
                  for p in ("step", "draft", "verify")}
        assert counts["step"] >= 3
        # every speculative window paired its draft with its verify
        # (admission prefill adds one unpaired draft per spec seat)
        assert counts["draft"] == counts["verify"] + 1


@pytest.mark.slow
class TestAcceptRejectFuzz:
    @pytest.mark.parametrize("kernel", ["off", "interpret"])
    def test_seeded_boundary_fuzz(self, kernel):
        """Seeded fuzz over accept/reject boundaries on both step
        paths: random prompts, budgets, temperatures and top_ks
        against the divergent draft.  Every request completes at its
        exact budget, the sampled rng chain never desyncs (same seed
        -> same bytes on a rerun pool), accounting stays coherent
        (accepted <= proposed, emitted == windows + accepted when one
        seat runs), and the allocator conserves."""

        model, params, draft, dparams = _setup()
        r = np.random.RandomState(31 + (kernel == "interpret"))
        for trial in range(3):
            n = int(r.randint(3, 20))
            budget = int(r.randint(5, 22))
            temp = float(r.choice([0.0, 0.7, 1.3]))
            top_k = None if r.rand() < 0.5 else 8
            kw = {}
            if temp:
                kw = dict(temperature=temp, top_k=top_k,
                          rng=jax.random.PRNGKey(trial))
            prompt = _prompt(r, n)
            outs = []
            for _rerun in range(2):
                pool = PagedContinuousBatchingDecoder(
                    model, params, slots=2, kv_block_size=16,
                    paged_kernel=kernel, draft_model=draft,
                    draft_params=dparams, spec_k=3,
                )
                rid = pool.submit(prompt, max_new_tokens=budget,
                                  tier="interactive", **kw)
                pool.run()
                out = pool.result(rid)
                assert out.shape == (n + budget,)
                snap = pool.spec_snapshot()
                assert snap["spec_accepted"] <= snap["spec_proposed"]
                # admission prefill emits token 1 outside the spec
                # counters; the budget clip only ever lands on the
                # final window, so for a lone seat emitted is exactly
                # windows + accepted capped at budget - 1
                assert snap["spec_emitted"] == min(
                    budget - 1,
                    snap["spec_windows"] + snap["spec_accepted"],
                )
                pool.alloc.check()
                assert not pool._draft_refs
                outs.append(out)
            np.testing.assert_array_equal(
                outs[0], outs[1],
                err_msg=f"trial {trial} temp={temp} top_k={top_k} "
                        "rng chain desynced across identical runs",
            )


class TestSpecConfigHonesty:
    """Construction-time failures (cheap: nothing compiles) — the
    fail-don't-downgrade contract."""

    def _base(self):
        model = llama_tiny(vocab_size=VOCAB, max_len=64)
        params = model.init(
            jax.random.PRNGKey(1), jnp.zeros((1, 4), jnp.int32)
        )["params"]
        return model, params

    def test_typod_tier_fails_loudly(self):
        model, params = self._base()
        with pytest.raises(ValueError, match="not SLO tiers"):
            PagedContinuousBatchingDecoder(
                model, params, slots=2, kv_block_size=16,
                draft_model=model, draft_params=params,
                spec_tiers=("interactiv",),
            )

    def test_bad_spec_k_fails_loudly(self):
        model, params = self._base()
        with pytest.raises(ValueError, match="spec_k"):
            PagedContinuousBatchingDecoder(
                model, params, slots=2, kv_block_size=16,
                draft_model=model, draft_params=params, spec_k=0,
            )

    def test_missing_draft_params_fails_loudly(self):
        model, params = self._base()
        with pytest.raises(ValueError, match="draft_params"):
            PagedContinuousBatchingDecoder(
                model, params, slots=2, kv_block_size=16,
                draft_model=model,
            )

    def test_mismatched_geometry_fails_loudly(self):
        model, params = self._base()
        short = llama_tiny(vocab_size=VOCAB, max_len=32)
        sparams = short.init(
            jax.random.PRNGKey(2), jnp.zeros((1, 4), jnp.int32)
        )["params"]
        with pytest.raises(ValueError, match="max_len"):
            PagedContinuousBatchingDecoder(
                model, params, slots=2, kv_block_size=16,
                draft_model=short, draft_params=sparams,
            )
