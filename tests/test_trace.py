"""Tracing subsystem tests (ISSUE 2): tracer/store semantics, the
x-trace-id propagation contract through the kubesim apiserver and the
retrying HTTP clients, the /traces read surface, and the acceptance
e2e — one trace id stitching apiserver request → workqueue →
reconcile sync → every backend retry attempt under a ≥10% mixed fault
schedule, with the slow-sync warn log naming the trace.
"""

import json
import logging
import random
import re
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from tests.testutil import new_job
from tf_operator_tpu.api.types import JobConditionType, PodPhase, SuccessPolicy
from tf_operator_tpu.backend.kube import KubeBackend
from tf_operator_tpu.backend.kubejobs import KubeJobStore
from tf_operator_tpu.backend.kubesim import MiniApiServer
from tf_operator_tpu.backend.retry import RetryPolicy
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.controller.reconciler import ReconcilerConfig
from tf_operator_tpu.server.api import ApiServer
from tf_operator_tpu.utils.metrics import Metrics
from tf_operator_tpu.utils.trace import (
    TraceStore,
    Tracer,
    extract_headers,
    inject_headers,
)

EXIT0 = [sys.executable, "-c", "raise SystemExit(0)"]


def fast_policy(seed=0, **kw):
    kw.setdefault("max_attempts", 6)
    kw.setdefault("base_delay", 0.02)
    kw.setdefault("max_delay", 0.2)
    kw.setdefault("deadline", 5.0)
    return RetryPolicy(rng=random.Random(seed), **kw)


def wait_until(cond, timeout=15.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise TimeoutError(what)


class TestTracerCore:
    def test_ids_deterministic_under_seed(self):
        """No wall-clock/random flake: two tracers with the same seed
        mint the same trace and span id sequences."""

        a, b = Tracer(seed=42), Tracer(seed=42)
        ids_a = [a.start_span(f"s{i}", root=True) for i in range(5)]
        ids_b = [b.start_span(f"s{i}", root=True) for i in range(5)]
        assert [s.trace_id for s in ids_a] == [s.trace_id for s in ids_b]
        assert [s.span_id for s in ids_a] == [s.span_id for s in ids_b]
        # a different seed gives a different session prefix
        assert Tracer(seed=43).start_span("x").trace_id != ids_a[0].trace_id

    def test_context_parenting(self):
        tr = Tracer(seed=0)
        with tr.span("parent") as p:
            assert tr.current_trace_id() == p.trace_id
            with tr.span("child") as c:
                assert c.trace_id == p.trace_id
                assert c.parent_id == p.span_id
                with tr.span("grandchild") as g:
                    assert g.parent_id == c.span_id
        assert tr.current_trace_id() is None

    def test_exception_marks_error_and_restores_context(self):
        tr = Tracer(seed=0)
        with pytest.raises(ValueError):
            with tr.span("boom") as sp:
                raise ValueError("nope")
        assert sp.status == "error"
        assert "ValueError" in sp.status_message
        assert tr.current_trace_id() is None
        stored = tr.store.trace(sp.trace_id)
        assert stored is not None and stored["error"]

    def test_explicit_trace_id_joins_remote_trace(self):
        tr = Tracer(seed=0)
        sp = tr.start_span("server", trace_id="tremote", parent_id="sremote")
        assert sp.trace_id == "tremote" and sp.parent_id == "sremote"
        sp.end()
        assert tr.store.trace("tremote") is not None

    def test_header_inject_extract_round_trip(self):
        tr = Tracer(seed=0)
        with tr.span("op") as sp:
            headers = inject_headers({})
        assert headers == {
            "x-trace-id": sp.trace_id, "x-parent-span-id": sp.span_id,
        }
        tid, parent = extract_headers(headers)
        assert (tid, parent) == (sp.trace_id, sp.span_id)
        assert inject_headers({}) == {}  # no active trace: no-op

    def test_explicit_start_end_mono(self):
        """queue.wait-style spans backdate their start to the enqueue
        timestamp so the waterfall shows the real wait."""

        tr = Tracer(seed=0)
        now = time.monotonic()
        sp = tr.start_span("queue.wait", start_mono=now - 2.5)
        sp.end(end_mono=now)
        assert 2.49 <= sp.duration <= 2.51
        sp.end()  # idempotent
        assert 2.49 <= sp.duration <= 2.51


class TestTraceStore:
    def _span(self, tr, name="op", error=False, slow=False):
        sp = tr.start_span(name, root=True)
        if error:
            sp.set_error("x")
        if slow:
            sp.end(end_mono=sp.start_mono + 10.0)
        else:
            sp.end(end_mono=sp.start_mono + 0.001)
        return sp

    def test_eviction_keeps_error_and_slow(self):
        store = TraceStore(max_traces=4, slow_seconds=1.0)
        tr = Tracer(store=store, seed=0)
        err = self._span(tr, error=True)
        slow = self._span(tr, slow=True)
        ok = [self._span(tr) for _ in range(6)]
        assert len(store) == 4
        # tail sampling: the error and slow traces survive; the evicted
        # ones are all ok-and-fast
        assert store.trace(err.trace_id) is not None
        assert store.trace(slow.trace_id) is not None
        assert store.trace(ok[0].trace_id) is None

    def test_eviction_bounded_even_when_all_protected(self):
        """A store full of protected traces keeps accepting NEW traces
        (oldest protected evicted) — it must not wedge on its first
        max_traces errors and silently drop everything after."""

        store = TraceStore(max_traces=3, slow_seconds=1.0)
        tr = Tracer(store=store, seed=0)
        spans = [self._span(tr, error=True) for _ in range(10)]
        assert len(store) == 3
        # the newest error traces survive; the oldest were evicted
        assert store.trace(spans[-1].trace_id) is not None
        assert store.trace(spans[-2].trace_id) is not None
        assert store.trace(spans[0].trace_id) is None

    def test_per_trace_span_cap_counts_drops(self):
        store = TraceStore(max_spans_per_trace=5)
        tr = Tracer(store=store, seed=0)
        with tr.span("root") as root:
            for i in range(9):
                tr.start_span(f"c{i}").end()
        t = store.trace(root.trace_id)
        assert len(t["spans"]) == 5
        assert t["droppedSpans"] == 5  # 9 children + root - 5 kept

    def test_summaries_and_jsonl_export(self, tmp_path):
        store = TraceStore()
        tr = Tracer(store=store, seed=0)
        with tr.span("outer"):
            tr.start_span("queue.wait").end()
        s = store.summaries()
        assert len(s) == 1
        assert s[0]["root"] == "outer" and s[0]["spanCount"] == 2
        out = tmp_path / "spans.jsonl"
        with open(out, "w") as f:
            n = store.export_jsonl(f)
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        assert n == len(lines) == 2
        assert {l["name"] for l in lines} == {"outer", "queue.wait"}


class TestQueueLatencyCapture:
    def test_deduped_readd_keeps_first_enqueue_timestamp(self):
        """client-go workqueue semantics: the queue dedups re-adds of a
        pending key, so the latency clock must run from the FIRST
        unprocessed add — re-adds during a backlog must not reset it."""

        from tf_operator_tpu.backend.fake import FakeCluster
        from tf_operator_tpu.backend.jobstore import JobStore

        c = TPUJobController(
            JobStore(), FakeCluster(), resync_period=0,
            tracer=Tracer(seed=0),
        )
        try:
            c._enqueue("default/j")
            first = c._pending_trace["default/j"]
            time.sleep(0.02)
            c._enqueue("default/j")  # deduped re-add
            assert c._pending_trace["default/j"] == first
        finally:
            c.stop()


class TestSimPropagation:
    """The wire contract: EVERY kubesim apiserver response carries
    x-trace-id — echoed when the caller sent one, minted otherwise —
    and the server records a span per request, tagged with any
    injected fault."""

    @pytest.fixture
    def sim(self):
        tracer = Tracer(seed=5)
        s = MiniApiServer(fault_seed=0, tracer=tracer).start()
        yield s
        s.stop()

    def _get(self, sim, path, headers=None, method="GET", data=None):
        req = urllib.request.Request(
            sim.url + path, headers=headers or {}, method=method, data=data
        )
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                return resp.status, dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers)

    def test_every_response_carries_trace_id(self, sim):
        for path, method, data in [
            ("/api/v1/pods", "GET", None),
            ("/api/v1/namespaces/default/pods/nope", "GET", None),  # 404
            ("/_faults", "GET", None),
            (
                "/api/v1/namespaces/default/pods", "POST",
                json.dumps({"metadata": {"name": "p1"}, "spec": {}}).encode(),
            ),
        ]:
            _, headers = self._get(sim, path, method=method, data=data)
            assert headers.get("x-trace-id"), f"{method} {path}"

    def test_incoming_trace_id_echoed_and_adopted(self, sim):
        code, headers = self._get(
            sim, "/api/v1/pods", headers={"x-trace-id": "tcaller01"}
        )
        assert code == 200
        assert headers["x-trace-id"] == "tcaller01"
        t = sim.tracer.store.trace("tcaller01")
        assert t is not None
        [span] = t["spans"]
        assert span["name"] == "apiserver GET /api/v1/pods"
        assert span["kind"] == "server"

    def test_fault_injected_reply_is_traced_and_tagged(self, sim):
        sim.faults.add(
            path=r"/api/v1/pods", mode="error", status=503, times=1
        )
        code, headers = self._get(
            sim, "/api/v1/pods", headers={"x-trace-id": "tfault01"}
        )
        assert code == 503
        assert headers["x-trace-id"] == "tfault01"
        [span] = sim.tracer.store.trace("tfault01")["spans"]
        assert span["attributes"]["fault"] == "error"
        assert span["status"] == "error"

    def test_watch_response_carries_trace_id(self, sim):
        req = urllib.request.Request(
            sim.url + "/api/v1/pods?watch=true&resourceVersion=0",
            headers={"x-trace-id": "twatch01"},
        )
        resp = urllib.request.urlopen(req, timeout=5)
        try:
            assert resp.headers["x-trace-id"] == "twatch01"
        finally:
            resp.close()
        t = sim.tracer.store.trace("twatch01")
        assert t is not None and t["spans"][0]["attributes"]["watch"] is True


class TestRetryAttemptSpans:
    def test_one_attempt_span_per_retry(self):
        """A fault-injected retry sequence yields one client span per
        attempt — 0-based attempt numbers, failures marked error, the
        final success ok — all under ONE trace id, with matching
        server spans."""

        tracer = Tracer(seed=9)
        m = Metrics()
        sim = MiniApiServer(fault_seed=0, tracer=tracer).start()
        backend = KubeBackend(
            sim.url, retry=fast_policy(), metrics=m, tracer=tracer
        )
        try:
            sim.faults.add(
                path=r"/api/v1/namespaces/default/pods$", methods=["POST"],
                mode="error", status=503, retry_after=0.01, times=2,
            )
            from tf_operator_tpu.api.types import Container, ObjectMeta
            from tf_operator_tpu.backend.objects import Pod

            with tracer.span("test.create") as root:
                backend.create_pod(Pod(
                    metadata=ObjectMeta(name="p1", namespace="default"),
                    containers=[Container(command=list(EXIT0))],
                ))
            trace = tracer.store.trace(root.trace_id)
            attempts = [
                s for s in trace["spans"]
                if s["name"] == "http POST /api/v1/namespaces/default/pods"
            ]
            assert [s["attributes"]["attempt"] for s in attempts] == [0, 1, 2]
            assert [s["status"] for s in attempts] == ["error", "error", "ok"]
            assert all(
                s["attributes"].get("injectedFault") for s in attempts[:2]
            )
            servers = [
                s for s in trace["spans"]
                if s["name"] == "apiserver POST /api/v1/namespaces/default/pods"
            ]
            assert len(servers) == 3  # one server span per client attempt
            # exemplar linkage: the error counter names this trace
            assert m.exemplar("api_client_errors_total") == root.trace_id
        finally:
            backend.close()
            sim.stop()


class TestTraceApi:
    def test_traces_endpoints_and_response_header(self):
        from tf_operator_tpu.backend.fake import FakeCluster
        from tf_operator_tpu.backend.jobstore import JobStore
        from tf_operator_tpu.utils.events import EventRecorder

        tracer = Tracer(seed=3)
        with tracer.span("seeded.op") as sp:
            tracer.start_span("child").end()
        api = ApiServer(
            JobStore(), FakeCluster(), Metrics(), EventRecorder(),
            tracer=tracer,
        )
        api.start()
        base = f"http://127.0.0.1:{api.port}"
        try:
            with urllib.request.urlopen(base + "/traces", timeout=5) as r:
                items = json.loads(r.read())["items"]
            assert any(t["traceId"] == sp.trace_id for t in items)
            with urllib.request.urlopen(
                base + f"/traces/{sp.trace_id}", timeout=5
            ) as r:
                trace = json.loads(r.read())
            assert {s["name"] for s in trace["spans"]} == {
                "seeded.op", "child",
            }
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + "/traces/tmissing", timeout=5)
            assert ei.value.code == 404
            # job-API responses carry x-trace-id (observability routes
            # like /traces itself are deliberately untraced)
            with urllib.request.urlopen(
                base + "/apis/v1/tpujobs", timeout=5
            ) as r:
                assert r.headers["x-trace-id"]
        finally:
            api.stop()


class TestE2EWaterfallUnderFaults:
    """ISSUE 2 acceptance: a multi-replica job reaches Succeeded under
    a ≥10% mixed fault schedule, and ONE trace id links the apiserver
    request spans, the workqueue queue-latency span, the reconcile
    sync, and every backend retry attempt — with /traces/<id> serving
    the waterfall and the slow-sync warn log naming the trace."""

    def test_single_trace_links_the_vertical(self, caplog):
        tracer = Tracer(seed=1234)
        sim = MiniApiServer(fault_seed=1234, tracer=tracer).start()
        # ~13% combined fault probability on every route, plus a
        # deterministic 2-shot 503 on the first pod create so at least
        # one sync provably contains a retry ladder
        sim.faults.add(
            path=r"/api/v1/namespaces/default/pods$", methods=["POST"],
            mode="error", status=503, retry_after=0.01, times=2,
        )
        sim.faults.add(mode="error", status=503, retry_after=0.02,
                       probability=0.05)
        sim.faults.add(mode="error", status=429, probability=0.04)
        sim.faults.add(mode="reset", probability=0.04)

        m = Metrics()
        store = KubeJobStore(
            sim.url, retry=fast_policy(seed=1), metrics=m, tracer=tracer
        )
        backend = KubeBackend(
            sim.url, retry=fast_policy(seed=2), metrics=m, tracer=tracer
        )
        controller = TPUJobController(
            store, backend,
            config=ReconcilerConfig(
                resolver=backend.resolver,
                # every sync "slow"-warns so the exemplar linkage is
                # deterministically exercised
                slow_sync_warn_seconds=0.0,
            ),
            metrics=m, resync_period=0.3, expectations_timeout=0.3,
            tracer=tracer,
        )
        api = ApiServer(
            store, backend, m, controller.recorder, tracer=tracer
        )
        api.start()

        crashes = []
        prev_hook = threading.excepthook
        threading.excepthook = lambda args: crashes.append(args)
        caplog.set_level(logging.WARNING, logger="tpujob")
        try:
            controller.run(threadiness=2)
            job = new_job("traced", worker=3, command=EXIT0)
            job.spec.success_policy = SuccessPolicy.ALL_WORKERS
            store.create(job)

            def succeeded():
                j = store.get("default", "traced")
                return j is not None and j.status.has_condition(
                    JobConditionType.SUCCEEDED
                )

            wait_until(succeeded, timeout=60.0, what="job Succeeded")
            pods = backend.list_pods("default")
            assert all(p.phase is PodPhase.SUCCEEDED for p in pods)

            # ---- find the sync trace that rode out the 503 ladder on
            # the pod-create route (other traces may carry retries on
            # list/status routes; this one provably has the 2-shot rule)
            target = None
            for summary in tracer.store.summaries(limit=250):
                t = tracer.store.trace(summary["traceId"])
                if any(
                    s["kind"] == "client"
                    and s["name"].endswith("/namespaces/default/pods")
                    and s["name"].startswith("http POST")
                    and s["attributes"].get("attempt", 0) >= 1
                    for s in t["spans"]
                ):
                    target = t
                    break
            assert target is not None, "no trace with a retried pod create"
            names = [s["name"] for s in target["spans"]]
            # the full vertical under ONE trace id:
            assert any(n.startswith("sync default/") for n in names)
            assert "queue.wait" in names
            assert any(n.startswith("reconcile default/") for n in names)
            assert any(n.startswith("pod.create") for n in names)
            assert any(n.startswith("apiserver POST") for n in names)
            # ...and every retry attempt is its own span: each
            # pod.create wraps exactly one backend call, so its client
            # children's attempt numbers form a contiguous 0..n ladder
            pod_creates = {
                s["spanId"] for s in target["spans"]
                if s["name"].startswith("pod.create")
            }
            ladders = {}
            for s in target["spans"]:
                if s["kind"] == "client" and s["parentId"] in pod_creates:
                    ladders.setdefault(s["parentId"], []).append(
                        s["attributes"]["attempt"]
                    )
            assert ladders
            for parent, attempts in ladders.items():
                assert sorted(attempts) == list(range(len(attempts))), parent
            assert any(
                max(a) >= 2 for a in ladders.values()
            ), "the 2-shot 503 ladder should show attempts 0,1,2"

            # ---- the slow-sync warn log names this trace
            slow_ids = set()
            for rec in caplog.records:
                msg = rec.getMessage()
                if "slow sync" in msg:
                    found = re.search(r"trace=(\S+?)[),\]]", msg)
                    if found:
                        slow_ids.add(found.group(1))
            assert target["traceId"] in slow_ids

            # ---- /traces/<id> serves the complete waterfall over HTTP
            base = f"http://127.0.0.1:{api.port}"
            with urllib.request.urlopen(
                base + f"/traces/{target['traceId']}", timeout=5
            ) as r:
                served = json.loads(r.read())
            assert {s["spanId"] for s in served["spans"]} == {
                s["spanId"] for s in target["spans"]
            }
            # queue-latency metrics flowed
            assert m.histogram("workqueue_queue_latency_seconds")["count"] > 0
            assert sim.faults.total_injected() > 0
        finally:
            threading.excepthook = prev_hook
            api.stop()
            controller.stop()
            backend.close()
            store.close()
            sim.stop()
        assert not crashes, f"unhandled thread exceptions: {crashes}"
