"""collect_window.py turns window artifacts into BASELINE.md rows.

The collector is the last hop between a measurement window and the
committed evidence; a silent parse failure would lose a round's
numbers, so its parsing and table-rewrite are pinned here (chip-free).
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

import collect_window as cw  # noqa: E402

BENCH_LINE = (
    '{"metric": "resnet50_train_examples_per_sec_per_chip", "value": 2400.5,'
    ' "unit": "examples/sec/chip", "vs_baseline": 1.13, "batch_per_chip": 256,'
    ' "step_ms": 106.6, "mfu_xla": 0.291, "mfu_analytic": 0.274,'
    ' "pipeline_examples_per_sec_per_chip": 2300.1, "pipeline_step_ms": 111.2,'
    ' "llama_train_tokens_per_sec_per_chip": 52000.3, "llama_step_ms": 157.5,'
    ' "llama_mfu_analytic": 0.41, "llama_mfu_xla": 0.44,'
    ' "llama_decode_tokens_per_sec": 2100.7}'
)
TRAIN_LINE = (
    '{"train_backend": "tpu", "mnist_steps_per_sec_per_chip": 95.2,'
    ' "mnist_examples_per_sec_per_chip": 24371.2,'
    ' "bert_base_steps_per_sec_per_chip": 4.1,'
    ' "bert_base_examples_per_sec_per_chip": 131.2}'
)
FLASH_OUT = (
    "some pytest noise\n"
    "flash fwd+bwd @4k: 41.2ms  xla: 70.1ms  speedup 1.70x\n"
    "windowed fwd+bwd @8k/w1k: 30.5ms  full: 61.2ms  speedup 2.01x\n"
    "2 passed\n"
)

TABLE = """# fake baseline

<!-- train:begin -->
| Metric | Value | Setup |
|---|---|---|
| ResNet-50 examples/sec/chip (train, bf16) | old | old |
| ResNet-50 with the input pipeline live | pending | — |
| llama-mini train tokens/sec/chip (~120M) | pending | — |
| llama-mini steady decode tokens/sec (KV-cache greedy, batch 8) | pending | — |
| mnist / BERT-base steps/sec/chip | pending | — |
| Flash vs XLA attention, fwd+bwd @ seq 4096 | pending | — |
| Windowed vs full flash attention, fwd+bwd | pending | — |
<!-- train:end -->

tail prose stays
"""


@pytest.fixture
def artifacts(tmp_path):
    d = tmp_path / "window_out"
    d.mkdir()
    (d / "bench.out").write_text("warmup noise\n" + BENCH_LINE + "\n")
    (d / "train.out").write_text(TRAIN_LINE + "\n")
    (d / "flash.out").write_text(FLASH_OUT)
    (d / "sweep.out").write_text('{"label": "bnbf16", "mfu": 0.31}\n')
    return str(d)


def test_parse_artifacts(artifacts):
    data = cw.parse_artifacts(artifacts)
    assert data["bench"]["value"] == 2400.5
    assert data["train"]["mnist_steps_per_sec_per_chip"] == 95.2
    assert data["flash_fwd_bwd"]["speedup"] == 1.70
    assert data["window_fwd_bwd"]["speedup"] == 2.01
    assert data["sweep"][0]["label"] == "bnbf16"


def test_multiline_json_artifacts_parse(tmp_path):
    # measure.py prints json.dumps(..., indent=1): the train/batching
    # artifacts are MULTI-LINE objects, preceded by log noise — a
    # single-line-only parser silently drops a whole window step
    import json

    d = tmp_path / "window_out"
    d.mkdir()
    (d / "train.out").write_text(
        "WARNING: platform noise\n"
        + json.dumps(json.loads(TRAIN_LINE), indent=1)
        + "\n"
    )
    (d / "batching.out").write_text(
        "noise\n"
        + json.dumps(
            {
                "batching_new_tokens": 64,
                "batching_pool_tokens_per_sec": 9000.0,
                "batching_sequential_tokens_per_sec": 2000.0,
                "batching_speedup": 4.5,
            },
            indent=1,
        )
        + "\n"
    )
    data = cw.parse_artifacts(str(d))
    assert data["train"]["mnist_steps_per_sec_per_chip"] == 95.2
    assert data["batching"]["batching_speedup"] == 4.5
    rows = cw.build_rows(data, "2026-07-31")
    assert "Serving under concurrency" in rows
    # a metric with no pre-authored row APPENDS instead of vanishing
    p = tmp_path / "BASELINE.md"
    p.write_text(TABLE)
    n = cw.rewrite_baseline(rows, str(p))
    text = p.read_text()
    assert "Serving under concurrency" in text
    assert text.index("Serving under concurrency") < text.index("train:end")


def test_train_sync_keys_parse_into_row_and_ledger(tmp_path):
    """r7: the step-sync K sweep + prefetch keys flow from train.out
    into the 'Training sync accounting' BASELINE row and the
    LAST_MEASURED ledger — a window that measures the sync-free step
    must not drop it on the floor."""

    import json

    d = tmp_path / "window_out"
    d.mkdir()
    t = dict(json.loads(TRAIN_LINE))
    t.update(
        {
            "train_sync_k_sweep": {
                "1": {"step_ms": 70.0, "steady_step_syncs": 64},
                "8": {"step_ms": 12.5, "steady_step_syncs": 0},
                "32": {"step_ms": 6.1, "steady_step_syncs": 0},
            },
            "train_k32_step_ms": 6.1,
            "train_steady_syncs_per_step": 0.0,
            "train_prefetch_best_depth": 4,
            "train_prefetch_vs_resident": 0.91,
        }
    )
    (d / "train.out").write_text(json.dumps(t, indent=1) + "\n")
    data = cw.parse_artifacts(str(d))
    rows = cw.build_rows(data, "2026-08-03")
    row = rows["Training sync accounting"]
    assert "K1: 70.0 ms/step" in row and "K32: 6.1 ms/step" in row
    assert "syncs/step **0.0**" in row
    assert "best depth 4" in row

    import unittest.mock as mock

    with mock.patch.object(cw, "HERE", str(tmp_path)):
        cw.write_last_measured(data, "2026-08-03")
        led = json.load(open(tmp_path / "LAST_MEASURED.json"))
    assert led["train_k32_step_ms"]["value"] == 6.1
    assert led["train_steady_syncs_per_step"]["value"] == 0.0
    assert led["train_prefetch_best_depth"]["value"] == 4


def test_multislice_artifact_parses_into_row_and_ledger(tmp_path):
    """ISSUE 14: the --section multislice smoke flows into the
    'Multi-slice training' BASELINE row and the LAST_MEASURED ledger,
    carrying the CPU-smoke backend tag (the byte ratio is the
    platform-independent signal; walls are backend-qualified)."""

    import json

    d = tmp_path / "window_out"
    d.mkdir()
    ms = {
        "multislice_backend": "cpu",
        "multislice_slices": 2,
        "multislice_mesh": {"dp": 2, "fsdp": 4},
        "multislice_axis_fabric": {"dp": "dcn", "fsdp": "ici"},
        "multislice_intra_slice_size": 4,
        "multislice_flat_dcn_bytes_per_step": 13098536,
        "multislice_flat_mesh_dcn_bytes_per_step": 3276768,
        "multislice_hier_dcn_bytes_per_step": 3274636,
        "multislice_dcn_bytes_ratio": 0.25,
        "multislice_dcn_bytes_ratio_vs_flat_mesh": 0.999349,
        "multislice_dcn_collectives_per_step": 4,
        "multislice_allclose_max_loss_err": 0.00035,
        "multislice_flat_step_ms": 585.8,
        "multislice_hierarchical_step_ms": 611.6,
        "multislice_step_wall_ratio": 1.044,
        "multislice_sync_probe": {
            "dcn_fragment_s": 0.002, "ici_reshard_s": 0.0017,
            "flat_full_s": 0.005,
        },
    }
    (d / "multislice.out").write_text(json.dumps(ms, indent=1) + "\n")
    data = cw.parse_artifacts(str(d))
    rows = cw.build_rows(data, "2026-08-04")
    row = rows["Multi-slice training"]
    assert "**0.25×**" in row and "dp2, fsdp4" in row
    # BOTH baselines render: blind full-width (the acceptance number)
    # and the same-mesh flat program (what the walls A/B)
    assert "topology-BLIND" in row and "**0.999349×**" in row
    assert "cpu smoke" in row

    import unittest.mock as mock

    with mock.patch.object(cw, "HERE", str(tmp_path)):
        cw.write_last_measured(data, "2026-08-04")
        led = json.load(open(tmp_path / "LAST_MEASURED.json"))
    assert led["multislice_dcn_bytes_ratio"]["value"] == 0.25
    # byte accounting is platform-independent — UNtagged, so any
    # backend's window may refresh it; only walls carry the tag
    assert "backend" not in led["multislice_dcn_bytes_ratio"]
    assert led["multislice_hierarchical_step_ms"]["backend"] == "cpu"


def test_fabric_artifact_parses_into_row_and_ledger(tmp_path):
    """ISSUE 17: the --section fabric smoke flows into the 'Cross-pod
    prefix fabric' BASELINE row and the LAST_MEASURED ledger — wire
    accounting (hit rate, bytes, migrate_in count) untagged so any
    backend refreshes it; TTFT quantiles and tok/s carry the backend
    tag and defer to chip-grade entries."""

    import json

    d = tmp_path / "window_out"
    d.mkdir()
    fab = {
        "fabric_backend": "cpu",
        "fabric_trace_requests": 16,
        "fabric_prefixes": 4,
        "fabric_prefix_blocks": 3,
        "fabric_local_tokens_per_sec": 1767.7,
        "fabric_fleet_tokens_per_sec": 1743.6,
        "fabric_local_p99_ttft_s": 0.0658,
        "fabric_fleet_p99_ttft_s": 0.0678,
        "fabric_local_cold_p99_ttft_s": 0.0191,
        "fabric_fleet_cold_p99_ttft_s": 0.025,
        "fabric_ttft_p99_speedup": 0.97,
        "fabric_pull_hits": 24,
        "fabric_remote_hit_rate": 1.0,
        "fabric_pull_bytes": 196608,
        "fabric_pull_failures": 0,
        "fabric_migrate_in_dispatches": 8,
        "fabric_publishes": 24,
    }
    (d / "fabric.out").write_text(json.dumps(fab, indent=1) + "\n")
    data = cw.parse_artifacts(str(d))
    rows = cw.build_rows(data, "2026-08-06")
    row = rows["Cross-pod prefix fabric"]
    assert "remote hit rate **1.0**" in row
    assert "24 block pulls" in row and "196608 B over HTTP" in row
    assert "8 migrate_in" in row
    assert "**0.0678 s**" in row and "0.0658 s local-only" in row
    assert "CPU smoke" in row and "box-dependent" in row

    import unittest.mock as mock

    with mock.patch.object(cw, "HERE", str(tmp_path)):
        cw.write_last_measured(data, "2026-08-06")
        led = json.load(open(tmp_path / "LAST_MEASURED.json"))
    # wire/dispatch accounting: platform-independent, UNtagged
    assert led["fabric_remote_hit_rate"]["value"] == 1.0
    assert "backend" not in led["fabric_remote_hit_rate"]
    assert "backend" not in led["fabric_pull_bytes"]
    assert led["fabric_migrate_in_dispatches"]["value"] == 8
    # walls/quantiles: backend-qualified (the paged-row rule)
    assert led["fabric_fleet_p99_ttft_s"]["backend"] == "cpu"
    assert led["fabric_ttft_p99_speedup"]["backend"] == "cpu"
    assert led["fabric_local_tokens_per_sec"]["backend"] == "cpu"
    # config echoes never enter the measured-keys ledger
    assert "fabric_backend" not in led


def test_cpu_smoke_train_artifact_does_not_clobber_chip_model_rows(tmp_path):
    """The backend-aware rule (ISSUE 14 satellite, the PR 13 batching
    precedent generalized): a MEASURE_TRAIN_TINY CPU smoke carries the
    K-sweep/prefetch accounting but no BERT/llama legs — it must
    refresh the 'Training sync accounting' row (cpu-smoke provenance)
    WITHOUT emitting a '?'-riddled mnist/BERT row over the measured
    chip one, and its ledger entries must be backend-tagged."""

    import json

    d = tmp_path / "window_out"
    d.mkdir()
    t = {
        "train_backend": "cpu",
        "mnist_steps_per_sec_per_chip": 12.2,
        "mnist_examples_per_sec_per_chip": 390.4,
        "train_sync_k_sweep": {
            "1": {"step_ms": 70.0, "steady_step_syncs": 48},
            "32": {"step_ms": 6.1, "steady_step_syncs": 0},
        },
        "train_k32_step_ms": 6.1,
        "train_steady_syncs_per_step": 0.0,
    }
    (d / "train.out").write_text(json.dumps(t, indent=1) + "\n")
    data = cw.parse_artifacts(str(d))
    rows = cw.build_rows(data, "2026-08-04")
    assert "mnist / BERT-base steps/sec/chip" not in rows
    assert "cpu smoke" in rows["Training sync accounting"]

    import unittest.mock as mock

    # seed a chip-grade (untagged) mnist entry: the smoke must not
    # replace it — bench.py's error fallback points humans here
    (tmp_path / "LAST_MEASURED.json").write_text(
        json.dumps(
            {
                "mnist_steps_per_sec_per_chip": {
                    "value": 1388.4,
                    "artifact": "benchmarks/window_out/train.out",
                    "date": "2026-08-01",
                }
            }
        )
    )
    with mock.patch.object(cw, "HERE", str(tmp_path)):
        cw.write_last_measured(data, "2026-08-04")
        led = json.load(open(tmp_path / "LAST_MEASURED.json"))
    assert led["train_k32_step_ms"]["backend"] == "cpu"
    assert led["mnist_steps_per_sec_per_chip"]["value"] == 1388.4
    assert "backend" not in led["mnist_steps_per_sec_per_chip"]


def test_fusedbn_artifact_parses_into_row_and_ledger(tmp_path):
    """ISSUE 19: the fused train-mode BN A/B flows into the 'ResNet
    train fusion' BASELINE row and the LAST_MEASURED ledger.  The
    dedicated chip artifact (resnet-fused-chip.out) wins over the
    train.out smoke keys when fresh; walls/MFU/trace-chain shares are
    backend-tagged (a CPU smoke must never displace a chip-grade
    cell), the interpret-kernel numerics probe stays untagged."""

    import json

    d = tmp_path / "window_out"
    d.mkdir()
    # CPU-smoke train.out carrying the measure.py leg's fusedbn keys
    t = dict(json.loads(TRAIN_LINE))
    t["train_backend"] = "cpu"
    t.update(
        {
            "resnet_fusedbn_backend": "cpu",
            "resnet_fusedbn_impl": "xla",
            "resnet_fusedbn_step_ms_stock": 2205.78,
            "resnet_fusedbn_step_ms_fused": 2099.91,
            "resnet_fusedbn_step_wall_ratio": 1.05,
            "resnet_fusedbn_mfu_stock": 0.0001,
            "resnet_fusedbn_mfu_fused": 0.0001,
            "resnet_fusedbn_loss_max_rel_err": 1.04e-05,
            "resnet_fusedbn_interpret_fwd_err": 3.34e-06,
            "resnet_fusedbn_interpret_grad_err": 5.48e-05,
        }
    )
    (d / "train.out").write_text(json.dumps(t, indent=1) + "\n")
    data = cw.parse_artifacts(str(d))
    assert data["fusedbn"]["_artifact"] == "train.out"
    rows = cw.build_rows(data, "2026-08-07")
    row = rows["ResNet train fusion"]
    assert "**2099.91 ms** fused" in row and "**1.05×**" in row
    assert "CPU smoke" in row and "chip-meaningful only" in row

    import unittest.mock as mock

    # seed a chip-grade (untagged) wall: the CPU smoke must not
    # replace it, but the untagged interpret probe may refresh
    (tmp_path / "LAST_MEASURED.json").write_text(
        json.dumps(
            {
                "resnet_fusedbn_step_ms_fused": {
                    "value": 88.1,
                    "artifact": "benchmarks/window_out/resnet-fused-chip.out",
                    "date": "2026-08-01",
                }
            }
        )
    )
    with mock.patch.object(cw, "HERE", str(tmp_path)):
        cw.write_last_measured(data, "2026-08-07")
        led = json.load(open(tmp_path / "LAST_MEASURED.json"))
    assert led["resnet_fusedbn_step_ms_fused"]["value"] == 88.1
    assert "backend" not in led["resnet_fusedbn_step_ms_fused"]
    assert led["resnet_fusedbn_step_wall_ratio"]["backend"] == "cpu"
    assert led["resnet_fusedbn_interpret_fwd_err"]["value"] == 3.34e-06
    assert "backend" not in led["resnet_fusedbn_interpret_fwd_err"]
    # config echoes never enter the measured-keys ledger
    assert "resnet_fusedbn_backend" not in led
    assert "resnet_fusedbn_impl" not in led

    # the dedicated chip artifact (fresh: same window as train.out)
    # shadows the train.out keys and carries the trace-chain diff
    chip = {
        "variant": "fusedbn",
        "batch_per_chip": 256,
        "resnet_fusedbn_backend": "tpu",
        "resnet_fusedbn_impl": "pallas",
        "resnet_fusedbn_step_ms_stock": 106.0,
        "resnet_fusedbn_step_ms_fused": 88.1,
        "resnet_fusedbn_step_wall_ratio": 1.203,
        "resnet_fusedbn_mfu_stock": 0.31,
        "resnet_fusedbn_mfu_fused": 0.37,
        "resnet_fusedbn_loss_max_rel_err": 2.0e-05,
        "resnet_fusedbn_interpret_fwd_err": 3.34e-06,
        "resnet_fusedbn_interpret_grad_err": 5.48e-05,
        "fusedbn_trace_chain_share_stock": 0.55,
        "fusedbn_trace_chain_share_fused": 0.31,
        "fusedbn_trace_chain_share_drop": 0.24,
    }
    (d / "resnet-fused-chip.out").write_text(json.dumps(chip) + "\n")
    data = cw.parse_artifacts(str(d))
    assert data["fusedbn"]["_artifact"] == "resnet-fused-chip.out"
    row = cw.build_rows(data, "2026-08-07")["ResNet train fusion"]
    assert "**88.1 ms** fused" in row and "**1.203×**" in row
    assert "0.37" in row and "drop **0.24**" in row
    assert "CPU smoke" not in row
    with mock.patch.object(cw, "HERE", str(tmp_path)):
        cw.write_last_measured(data, "2026-08-07")
        led = json.load(open(tmp_path / "LAST_MEASURED.json"))
    # tpu rows land untagged (chip-grade) and shadow nothing
    assert led["resnet_fusedbn_step_ms_fused"]["value"] == 88.1
    assert led["resnet_fusedbn_mfu_fused"]["value"] == 0.37
    assert led["fusedbn_trace_chain_share_drop"]["value"] == 0.24
    # artifact-echo keys from the chip JSON stay out of the ledger
    assert "batch_per_chip" not in led


def test_error_bench_line_is_ignored(tmp_path):
    d = tmp_path / "w"
    d.mkdir()
    (d / "bench.out").write_text(
        '{"metric": "m", "value": 0.0, "error": "probe hung"}\n'
    )
    assert "bench" not in cw.parse_artifacts(str(d))


def test_rewrite_replaces_only_fresh_rows(artifacts, tmp_path):
    baseline = tmp_path / "BASELINE.md"
    baseline.write_text(TABLE)
    data = cw.parse_artifacts(artifacts)
    rows = cw.build_rows(data, "2026-07-31")
    n = cw.rewrite_baseline(rows, path=str(baseline))
    assert n == 7
    text = baseline.read_text()
    assert "**2400.5 @ batch 256**" in text
    assert "52000.3 tok/s/chip" in text
    assert "**2100.7 tok/s**" in text
    assert "**1.70×**" in text
    assert "**2.01×**" in text
    assert "mnist **95.2 steps/s**" in text
    assert "pending" not in text.split("train:begin")[1].split("train:end")[0]
    assert "tail prose stays" in text


def test_partial_window_keeps_old_rows(tmp_path):
    d = tmp_path / "w"
    d.mkdir()
    (d / "flash.out").write_text(FLASH_OUT)  # only the flash step ran
    baseline = tmp_path / "BASELINE.md"
    baseline.write_text(TABLE)
    data = cw.parse_artifacts(str(d))
    n = cw.rewrite_baseline(cw.build_rows(data, "2026-07-31"), path=str(baseline))
    assert n == 2
    text = baseline.read_text()
    assert "| old |" in text          # resnet row untouched
    assert "**1.70×**" in text        # flash row refreshed


def test_empty_dir_returns_nothing(tmp_path):
    assert cw.parse_artifacts(str(tmp_path)) == {}
