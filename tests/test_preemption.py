"""Budget-on-demand admission + mid-decode preemption + SLO tiers
(ISSUE 12 tentpole).

The load-bearing pins:

- TOKEN IDENTITY: a preempted-then-resumed request decodes
  byte-identically to an undisturbed run — greedy and temperature, on
  BOTH step paths (gather emulation and the interpret-mode Pallas
  kernel).  The swap round trip (device→host block snapshot, host→
  device re-upload, rng/length/last-token restore) is exact.
- LAZY CAPACITY: at the same arena, budget-on-demand admission admits
  strictly more concurrent requests than the worst-case reservation
  (``reserve="worst-case"`` — PR 8's contract, kept as the measured
  baseline), and both modes produce identical tokens.
- TIER POLICY: interactive preempts batch (admission- and grow-time);
  a batch request under sustained interactive load still completes
  within the age-boost bound (anti-starvation).
- STEADY STATE: a decode window that grows its block tables is still
  exactly ONE ``step`` dispatch (the delta rides the dispatch).
- ACCOUNTING: preemption shows up everywhere it must — autopsy
  ``preempted``/``swapped_blocks``, ``preempt``/``swap_out``/
  ``swap_in`` lifecycle spans, ``serve_preemptions_total{model,tier}``
  / ``kv_swap_bytes_total{direction}``, the arena timeline's
  ``swapped`` series — and the allocator conserves through arbitrary
  preempt/resume interleavings.
"""

import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # generation-loop compiles

import jax
import jax.numpy as jnp

from tf_operator_tpu.models import llama_tiny
from tf_operator_tpu.models.batching import PagedContinuousBatchingDecoder
from tf_operator_tpu.utils.metrics import DispatchLedger, Metrics
from tf_operator_tpu.utils.trace import Tracer

VOCAB = 96


def _setup(max_len=64):
    model = llama_tiny(vocab_size=VOCAB, max_len=max_len)
    init = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), init)["params"]
    return model, params


def _prompt(r, n):
    return r.randint(0, VOCAB, size=(n,)).astype(np.int32)


class TestTokenIdentity:
    @pytest.mark.parametrize("kernel", ["off", "interpret"])
    @pytest.mark.parametrize("temp", [0.0, 0.9])
    def test_preempted_then_resumed_is_token_identical(self, kernel, temp):
        """The acceptance pin: batch request A is preempted mid-decode
        by an interactive admission (its private blocks swap to the
        host arena), resumes later, and its output is byte-identical
        to an undisturbed run — greedy and temperature, emulation and
        interpret-mode kernel paths."""

        model, params = _setup()
        r = np.random.RandomState(3)
        prompt_a = _prompt(r, 6)
        prompt_i = _prompt(r, 33)
        kw = (
            dict(temperature=temp, rng=jax.random.PRNGKey(5))
            if temp else {}
        )

        solo = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, steps_per_sync=8,
            paged_kernel=kernel,
        )
        rid = solo.submit(prompt_a, max_new_tokens=24, **kw)
        solo.run()
        want = solo.result(rid)

        # arena of 4 blocks: A commits 2 and grows; the interactive
        # admission needs 3 -> preempts A (tier policy)
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, kv_blocks=4,
            steps_per_sync=8, paged_kernel=kernel,
        )
        a = pool.submit(prompt_a, max_new_tokens=24, **kw)
        pool.step()  # admit A + window 1
        pool.step()  # window 2 — A's table has grown
        i = pool.submit(prompt_i, max_new_tokens=8, tier="interactive")
        pool.run()
        assert pool.preemptions >= 1, "scenario failed to preempt"
        got_i = pool.result(i)
        assert got_i.shape == (41,)
        np.testing.assert_array_equal(pool.result(a), want)
        pool.alloc.check()
        assert len(pool.swap) == 0 and pool.swap.swapped_blocks == 0

    @pytest.mark.parametrize("kernel", ["off", "interpret"])
    @pytest.mark.parametrize("temp", [0.0, 0.9])
    def test_preempted_speculating_seat_resumes_token_identical(
        self, kernel, temp
    ):
        """ISSUE 18: preemption of a SPECULATING seat swaps the draft
        state too — draft blocks ride the same swap_out dispatch, the
        draft rng chain is snapshotted, and the resumed request decodes
        byte-identically to an undisturbed speculative run (greedy and
        temperature, both step paths).  The draft arena must come back
        exactly: a lost draft page would desync the draft model's
        proposals and (under temperature) the acceptance pattern."""

        model, params = _setup()
        draft = llama_tiny(vocab_size=VOCAB, max_len=64)
        dparams = draft.init(
            jax.random.PRNGKey(2), jnp.zeros((1, 4), jnp.int32)
        )["params"]
        r = np.random.RandomState(3)
        prompt_a = _prompt(r, 6)
        prompt_i = _prompt(r, 33)
        kw = (
            dict(temperature=temp, rng=jax.random.PRNGKey(5))
            if temp else {}
        )
        spec = dict(
            draft_model=draft, draft_params=dparams, spec_k=3,
            spec_tiers=("batch", "interactive"),
        )

        solo = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, steps_per_sync=8,
            paged_kernel=kernel, **spec,
        )
        rid = solo.submit(prompt_a, max_new_tokens=24, **kw)
        solo.run()
        want = solo.result(rid)

        # 8-block arena: A (batch, speculating) commits 2 target + 2
        # draft blocks; the interactive admission needs 3 + 3 ->
        # preempts A, moving BOTH committed sets host-side
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, kv_blocks=8,
            steps_per_sync=8, paged_kernel=kernel, **spec,
        )
        a = pool.submit(prompt_a, max_new_tokens=24, **kw)
        pool.step()  # admit A (draft prefill) + window 1
        pool.step()  # window 2
        i = pool.submit(prompt_i, max_new_tokens=8, tier="interactive")
        pool.run()
        assert pool.preemptions >= 1, "scenario failed to preempt"
        assert pool.result(i).shape == (41,)
        np.testing.assert_array_equal(pool.result(a), want)
        pool.alloc.check()
        assert len(pool.swap) == 0 and pool.swap.swapped_blocks == 0
        assert not pool._draft_refs  # every draft page released

    def test_lazy_and_worst_case_modes_are_token_identical(self):
        """Reservation policy must never change tokens: the same
        request set decodes identically under lazy and worst-case
        admission (scheduling differs, math does not)."""

        model, params = _setup()
        r = np.random.RandomState(11)
        reqs = [(_prompt(r, n), b) for n, b in
                [(6, 30), (20, 14), (9, 24)]]
        outs = {}
        for reserve in ("worst-case", "lazy"):
            pool = PagedContinuousBatchingDecoder(
                model, params, slots=4, kv_block_size=16,
                reserve=reserve,
            )
            rids = [pool.submit(p, max_new_tokens=b) for p, b in reqs]
            pool.run()
            outs[reserve] = [pool.result(rid) for rid in rids]
            pool.alloc.check()
        for a, b in zip(outs["lazy"], outs["worst-case"]):
            np.testing.assert_array_equal(a, b)


class TestLazyCapacity:
    def test_lazy_admits_strictly_more_than_worst_case(self):
        """The capacity acceptance pin: at the same 8-block arena,
        budget-on-demand admission seats strictly more of the same
        long-budget requests than PR 8's worst-case reservation."""

        model, params = _setup()
        r = np.random.RandomState(5)
        prompts = [_prompt(r, 6) for _ in range(5)]

        conc = {}
        for reserve in ("worst-case", "lazy"):
            pool = PagedContinuousBatchingDecoder(
                model, params, slots=8, kv_block_size=16, kv_blocks=8,
                reserve=reserve,
            )
            for p in prompts:
                pool.submit(p, max_new_tokens=40)  # worst case: 3 blocks
            pool._admit()
            with pool._lock:
                conc[reserve] = len(pool._active)
        assert conc["worst-case"] == 2  # floor(8 / 3)
        assert conc["lazy"] == 4       # commit = prompt + 1 = 2 blocks
        assert conc["lazy"] > conc["worst-case"]

    def test_worst_case_mode_never_grows_or_preempts_alone(self):
        """PR 8 parity: worst-case admissions cover the whole budget,
        so a single-tier run has no growth shortfall and no
        preemptions — the no-surprise contract survives as a mode."""

        model, params = _setup()
        r = np.random.RandomState(6)
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, kv_blocks=8,
            reserve="worst-case",
        )
        rids = [
            pool.submit(_prompt(r, 6), max_new_tokens=40)
            for _ in range(3)
        ]
        pool.run()
        for rid in rids:
            assert pool.result(rid) is not None
        assert pool.preemptions == 0
        assert pool.ledger.count("swap_out") == 0
        assert pool.ledger.count("swap_in") == 0
        pool.alloc.check()


class TestTierScheduling:
    def test_interactive_admitted_ahead_of_batch_queue(self):
        """Priority admission replacing blind FIFO: with every seat's
        blocks contended, a later interactive submit is admitted
        before earlier batch submits."""

        model, params = _setup()
        r = np.random.RandomState(8)
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, kv_blocks=3,
        )
        # 3-block arena, every request needs 2 commit blocks: only one
        # fits at a time.  The interactive submit arrives LAST but is
        # seated FIRST — priority admission, not FIFO.
        b1 = pool.submit(_prompt(r, 20), max_new_tokens=8)
        b2 = pool.submit(_prompt(r, 20), max_new_tokens=8)
        i1 = pool.submit(_prompt(r, 20), max_new_tokens=8,
                         tier="interactive")
        pool._admit()
        with pool._lock:
            active = {req.rid for req in pool._active.values()}
            queued = [req.rid for req in pool._queue]
        assert active == {i1}
        assert queued == [b1, b2]  # batch keeps FIFO within its rank
        pool.run()
        for rid in (b1, b2, i1):
            assert pool.result(rid) is not None
        pool.alloc.check()

    def test_batch_never_starves_past_the_age_boost(self):
        """Anti-starvation pin: under a sustained interactive stream
        that always keeps the queue non-empty, a batch request still
        completes once its age boost lifts it — and interactive
        backlog remains when it does (i.e. it did NOT just win by the
        queue draining)."""

        model, params = _setup()
        r = np.random.RandomState(9)
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=2, kv_block_size=16, kv_blocks=2,
            steps_per_sync=4, age_boost_seconds=0.25,
        )
        batch = pool.submit(_prompt(r, 6), max_new_tokens=8)
        interactive = []
        done_at_backlog = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            # keep >= 2 interactive queued at all times
            with pool._lock:
                queued_i = sum(
                    1 for q in pool._queue if q.tier == "interactive"
                )
            while queued_i < 2 and len(interactive) < 200:
                interactive.append(pool.submit(
                    _prompt(r, 20), max_new_tokens=8, tier="interactive",
                ))
                queued_i += 1
            pool.step()
            if pool.result_wait(batch, timeout=0) is not None:
                with pool._lock:
                    done_at_backlog = sum(
                        1 for q in pool._queue
                        if q.tier == "interactive"
                    )
                break
        assert done_at_backlog is not None, (
            "batch request starved past the age boost bound"
        )
        assert done_at_backlog >= 1  # it won THROUGH backlog, not after
        pool.run()  # drain the stream
        pool.alloc.check()


class TestSteadyStateThroughGrowth:
    def test_growth_window_is_still_one_dispatch(self):
        """The ISSUE 12 half of the PR 10 invariant: a decode window
        whose seat crosses a block boundary (lazy allocation fires)
        is still exactly ONE ``step`` dispatch — the table delta rides
        the dispatch, it does not add one."""

        model, params = _setup()
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=2, kv_block_size=16, steps_per_sync=8,
        )
        rid = pool.submit(
            np.arange(6, dtype=np.int32) % VOCAB, max_new_tokens=48,
        )
        pool.step()  # admission + window 1
        grew = False
        for _ in range(4):  # windows 2..5 cross into blocks 3 and 4
            with pool._lock:
                committed0 = len(pool._seat_refs[0])
            base = pool.ledger.count()
            steps0 = pool.ledger.count("step")
            pool.step()
            with pool._lock:
                if 0 in pool._seat_refs and \
                        len(pool._seat_refs[0]) > committed0:
                    grew = True
            # growth or not: every window is exactly ONE dispatch
            assert pool.ledger.count() == base + 1
            assert pool.ledger.count("step") == steps0 + 1
        assert grew, "scenario never crossed a block boundary"
        pool.run()
        assert pool.result(rid) is not None
        snap = pool.ledger.snapshot()
        assert set(snap) <= {"admission", "step", "retire"}, snap
        pool.alloc.check()


class TestPreemptionAccounting:
    def _preempt_scenario(self, metrics=None, tracer=None):
        model, params = _setup()
        ledger = DispatchLedger(metrics=metrics, tracer=tracer)
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, kv_blocks=4,
            steps_per_sync=8, ledger=ledger, metrics=metrics,
            model_label="tiny",
        )
        r = np.random.RandomState(3)
        a = pool.submit(_prompt(r, 6), max_new_tokens=24,
                        trace_id="tpreempt0001")
        pool.step()
        pool.step()
        i = pool.submit(_prompt(r, 33), max_new_tokens=8,
                        tier="interactive")
        pool.run()
        assert pool.preemptions >= 1
        assert pool.result(a) is not None
        assert pool.result(i) is not None
        pool.alloc.check()
        return pool

    def test_autopsy_records_the_leave_and_return(self):
        """ISSUE 12 satellite: the autopsy has vocabulary for a seat
        that leaves and returns — preempted count, swapped blocks,
        swap_out/swap_in dispatch shares — instead of silently
        truncating at the first eviction."""

        pool = self._preempt_scenario(tracer=Tracer(seed=0))
        entry = pool.request_log.get("tpreempt0001")
        assert entry["state"] == "done"
        assert entry["tier"] == "batch"
        assert entry["preempted"] == 1
        assert entry["swapped_blocks"] >= 1
        assert entry["dispatches"]["swap_out"] == 1
        assert entry["dispatches"]["swap_in"] == 1
        assert entry["tokens"] == 24  # complete despite the eviction

    def test_lifecycle_spans_and_metrics(self):
        """preempt/swap_out/swap_in spans land on the victim's trace;
        serve_preemptions_total{model,tier} and
        kv_swap_bytes_total{direction} count the episode; the arena
        timeline's ``swapped`` series shows the host-resident span."""

        m = Metrics()
        tracer = Tracer(seed=0)
        pool = self._preempt_scenario(metrics=m, tracer=tracer)
        trace = tracer.store.trace("tpreempt0001")
        names = {s["name"] for s in trace["spans"]}
        assert {"preempt", "swap_out", "swap_in", "retire"} <= names
        assert m.counter(
            "serve_preemptions_total", model="tiny", tier="batch",
            replica="0",
        ) == pool.preemptions
        out_b = m.counter("kv_swap_bytes_total", direction="out")
        in_b = m.counter("kv_swap_bytes_total", direction="in")
        assert out_b > 0 and out_b == in_b  # full round trip
        swapped = [s["swapped"] for s in pool.timeline.tail()]
        assert max(swapped) >= 1  # the strip shows the spill
        assert swapped[-1] == 0   # ...and its resolution

    def test_swap_exempt_pin_cannot_wedge_the_pool(self):
        """Review regression (the deadlock breaker): a preempted
        QUEUED request holds refs on its prefix-published blocks
        (swap-exempt), which the cache cannot evict (refcount 2) and
        no active seat can free — without demotion, an admission
        needing the whole arena would gate the queue forever with
        zero active seats.  The demotion path copies the queued
        holder's live blocks host-side, the cache entries become
        evictable, the admission proceeds, and the demoted request
        still resumes token-identically."""

        model, params = _setup()
        r = np.random.RandomState(23)
        prompt_a = _prompt(r, 33)  # 2 publishable full blocks
        prompt_b = _prompt(r, 33)  # distinct: no prefix sharing

        solo = PagedContinuousBatchingDecoder(
            model, params, slots=2, kv_block_size=16, steps_per_sync=8,
        )
        sa = solo.submit(prompt_a, max_new_tokens=24)
        solo.run()
        want_a = solo.result(sa)

        pool = PagedContinuousBatchingDecoder(
            model, params, slots=2, kv_block_size=16, kv_blocks=4,
            steps_per_sync=8,
        )
        a = pool.submit(prompt_a, max_new_tokens=24)  # commits all 4
        pool.step()  # progress (victim-eligible) + 2 blocks published
        assert len(pool.prefix) == 2
        # the interactive admission needs the WHOLE arena: preempting
        # A frees only its 2 private blocks; its 2 published blocks
        # are swap-exempt and pinned by A's queued record — only the
        # demotion path can break the pin
        i = pool.submit(prompt_b, max_new_tokens=24, tier="interactive")
        pool.run()
        assert pool.preemptions >= 1
        assert pool.result(i) is not None
        np.testing.assert_array_equal(pool.result(a), want_a)
        pool.alloc.check()
        assert len(pool.swap) == 0 and pool.swap.swapped_blocks == 0
        # A's autopsy saw the demotion: more blocks swapped than the
        # seat eviction alone moved
        entries = {e["rid"]: e for e in pool.request_log.recent(10)}
        assert entries[a]["swapped_blocks"] >= 3

    def test_random_two_tier_churn_conserves_and_completes(self):
        """Churn test: a burst of mixed-tier, mixed-budget requests
        through a tight arena — every request completes, the allocator
        conserves, and the swap arena drains to empty."""

        model, params = _setup()
        r = np.random.RandomState(17)
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=6, kv_block_size=16, kv_blocks=6,
            steps_per_sync=8, age_boost_seconds=0.5,
        )
        rids = []
        for k in range(12):
            tier = "interactive" if k % 4 == 0 else "batch"
            p = _prompt(r, int(r.randint(4, 24)))
            budget = int(r.choice([8, 24, 40]))
            rids.append(pool.submit(p, max_new_tokens=budget, tier=tier))
            if k % 3 == 0:
                pool.step()
        pool.run()
        for rid in rids:
            assert pool.result(rid) is not None
        pool.alloc.check()
        assert len(pool.swap) == 0 and pool.swap.swapped_blocks == 0
        # published prefix blocks are the only live remainder
        assert pool.alloc.in_use == len(pool.prefix)
