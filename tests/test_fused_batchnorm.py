"""Fused train-mode BatchNorm (ISSUE 19): numerics pinned vs the flax
reference on the xla AND pallas-interpret impls, gradients via
jax.grad, running-stats identity, scope-name parity, and the fail-loud
config matrix (the paged_kernel validation-order contract extended to
``ResNet.norm`` / ``norm_impl``).

Tolerances: the xla impl mirrors ``nn.BatchNorm``'s exact op order and
is asserted BITWISE; the interpret impl runs the real kernel whose
tile-sequential f32 accumulation differs from XLA's reduction order —
f32 inputs pin at 1e-5 absolute, bf16 activations at one bf16 ulp of
the O(1) normalized outputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import flax.linen as nn

from tf_operator_tpu.ops.fused_batchnorm import (
    FUSEDBN_IMPLS,
    fused_batchnorm,
    fusedbn_available,
)
from tf_operator_tpu.models.resnet import BatchNorm as FusedBN
from tf_operator_tpu.models.resnet import resnet18

#: NHWC shapes including tile-straddling channel counts (C=5 pads to
#: one lane tile, 130/192 straddle the 128 lane boundary) and a
#: row-count (34·1·1) that straddles the 16-sublane tile
SHAPES = [(2, 3, 3, 5), (2, 4, 4, 128), (3, 5, 5, 192), (34, 1, 1, 7), (1, 9, 5, 130)]


def _inputs(shape, dtype, seed=0):
    r = np.random.RandomState(seed)
    c = shape[-1]
    return (
        jnp.asarray(r.randn(*shape) * 2 + 0.3, dtype),
        jnp.asarray(r.randn(c), jnp.float32),
        jnp.asarray(r.randn(c), jnp.float32),
        jnp.asarray(r.randn(*shape), dtype),
    )


def test_xla_impl_bitwise_matches_flax():
    """impl='xla' IS nn.BatchNorm's train-mode op order: outputs and
    batch moments bit-identical on bf16 activations / f32 params."""

    x, gamma, beta, _ = _inputs((2, 4, 4, 5), jnp.bfloat16)
    bn = nn.BatchNorm(
        use_running_average=False, momentum=0.9, epsilon=1e-5,
        dtype=jnp.bfloat16, param_dtype=jnp.float32,
    )
    v = bn.init(jax.random.PRNGKey(0), x)
    v = {"params": {"scale": gamma, "bias": beta}, "batch_stats": v["batch_stats"]}
    y_ref, upd = bn.apply(v, x, mutable=["batch_stats"])
    y, mean, var = fused_batchnorm(x, gamma, beta, eps=1e-5, impl="xla")
    assert jnp.array_equal(y_ref, y)
    # the moments feed the running-stats update — flax's exact values
    assert jnp.array_equal(
        upd["batch_stats"]["mean"], 0.9 * v["batch_stats"]["mean"] + 0.1 * mean
    )
    assert jnp.array_equal(
        upd["batch_stats"]["var"], 0.9 * v["batch_stats"]["var"] + 0.1 * var
    )


@pytest.mark.parametrize("shape", SHAPES)
def test_interpret_forward_matches_xla(shape):
    """The real kernel (interpreted), jitted, across tile-straddling
    shapes and every epilogue combo."""

    x, gamma, beta, res = _inputs(shape, jnp.float32)
    for relu in (False, True):
        for use_res in (False, True):
            r = res if use_res else None
            y_ref, m_ref, v_ref = fused_batchnorm(
                x, gamma, beta, relu=relu, residual=r, impl="xla"
            )
            f = jax.jit(
                lambda x, g, b, r=r, relu=relu: fused_batchnorm(
                    x, g, b, relu=relu, residual=r, impl="pallas-interpret"
                )
            )
            y, m, v = f(x, gamma, beta)
            np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)
            np.testing.assert_allclose(np.asarray(m), np.asarray(m_ref), atol=1e-5)
            np.testing.assert_allclose(np.asarray(v), np.asarray(v_ref), atol=1e-5)


def test_interpret_mixed_precision_bf16():
    """bf16 activations, f32 stats: y comes back bf16 within one ulp of
    the reference; the moments stay f32 and match the f32-accumulated
    reference (NOT a bf16 accumulation — the convert lives in-register
    before the reduce)."""

    x, gamma, beta, res = _inputs((3, 5, 5, 192), jnp.bfloat16)
    y, mean, var = fused_batchnorm(
        x, gamma, beta, relu=True, residual=res, impl="pallas-interpret"
    )
    y_ref, m_ref, v_ref = fused_batchnorm(
        x, gamma, beta, relu=True, residual=res, impl="xla"
    )
    assert y.dtype == jnp.bfloat16 and mean.dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(y_ref, np.float32), atol=0.0625
    )
    # f32-accumulation proof: the true f32 moments, tight
    xf = np.asarray(x, np.float32).reshape(-1, 192)
    np.testing.assert_allclose(np.asarray(mean), xf.mean(0), atol=1e-5)
    np.testing.assert_allclose(np.asarray(v_ref), np.asarray(var), atol=1e-5)


@pytest.mark.parametrize("relu", [False, True])
@pytest.mark.parametrize("use_res", [False, True])
def test_interpret_grads_match_reference(relu, use_res):
    """jax.grad through the custom_vjp: dx, dγ, dβ — and the residual-
    branch dy split — match autodiff of the reference composition."""

    x, gamma, beta, res = _inputs((3, 3, 3, 7), jnp.float32, seed=3)
    w = jnp.asarray(np.random.RandomState(9).randn(*x.shape), jnp.float32)

    def loss(impl):
        def f(x, g, b, r):
            y, _, _ = fused_batchnorm(
                x, g, b, relu=relu, residual=(r if use_res else None), impl=impl
            )
            return jnp.sum(y * w)

        return f

    g_ref = jax.grad(loss("xla"), argnums=(0, 1, 2, 3))(x, gamma, beta, res)
    g_ker = jax.grad(loss("pallas-interpret"), argnums=(0, 1, 2, 3))(x, gamma, beta, res)
    for a, b in zip(g_ref, g_ker):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    if use_res:
        # the residual branch must see dy post-ReLU-mask (non-trivial)
        assert bool(jnp.any(g_ker[3] != 0))
    else:
        assert not bool(jnp.any(g_ker[3] != 0))


def test_relu_mask_uses_relu_subgradient_convention():
    """The kernel's y>0 mask matches jax.nn.relu's custom JVP (zero at
    the kink), not jnp.maximum's half-split."""

    x = jnp.asarray([[0.0, -1.0, 2.0, 0.0]] * 8, jnp.float32)
    gamma = jnp.ones((4,), jnp.float32)
    beta = jnp.zeros((4,), jnp.float32)
    # constant columns: var=0, y = beta = 0 -> at the kink everywhere
    for impl in ("xla", "pallas-interpret"):
        dx = jax.grad(
            lambda x: jnp.sum(fused_batchnorm(x, gamma, beta, relu=True, impl=impl)[0])
        )(x)
        assert not bool(jnp.any(dx != 0)), impl


def test_module_running_stats_and_scope_parity():
    """The fused module face: same scope/variable tree as nn.BatchNorm
    (class-name trick), identical running-stats update on xla, allclose
    on interpret."""

    x, gamma, beta, _ = _inputs((2, 4, 4, 6), jnp.bfloat16)
    stock = nn.BatchNorm(
        use_running_average=False, momentum=0.9, epsilon=1e-5,
        dtype=jnp.bfloat16, param_dtype=jnp.float32,
    )
    v = stock.init(jax.random.PRNGKey(0), x)
    v = {"params": {"scale": gamma, "bias": beta}, "batch_stats": v["batch_stats"]}
    _, upd_ref = stock.apply(v, x, mutable=["batch_stats"])

    fused = FusedBN(dtype=jnp.bfloat16, impl="xla")
    v_f = fused.init(jax.random.PRNGKey(0), x)
    assert jax.tree_util.tree_structure(v_f) == jax.tree_util.tree_structure(v)
    y, upd = fused.apply(v, x, mutable=["batch_stats"])
    assert jnp.array_equal(upd["batch_stats"]["mean"], upd_ref["batch_stats"]["mean"])
    assert jnp.array_equal(upd["batch_stats"]["var"], upd_ref["batch_stats"]["var"])

    interp = FusedBN(dtype=jnp.bfloat16, impl="pallas-interpret")
    _, upd_i = interp.apply(v, x, mutable=["batch_stats"])
    np.testing.assert_allclose(
        np.asarray(upd_i["batch_stats"]["mean"]),
        np.asarray(upd_ref["batch_stats"]["mean"]),
        atol=1e-6,
    )

    # eval mode: running-stats affine, bitwise vs nn.BatchNorm
    ev_ref = nn.BatchNorm(
        use_running_average=True, momentum=0.9, epsilon=1e-5,
        dtype=jnp.bfloat16, param_dtype=jnp.float32,
    ).apply(v, x)
    ev = FusedBN(use_running_average=True, dtype=jnp.bfloat16, impl="pallas-interpret").apply(v, x)
    assert jnp.array_equal(ev_ref, ev)


def test_resnet_fused_xla_is_bitwise_stock():
    """norm='fused' + impl xla through a whole resnet18: identical init
    trees, bitwise train logits + batch_stats, bitwise eval logits."""

    r = np.random.RandomState(0)
    x = jnp.asarray(r.rand(2, 32, 32, 3), jnp.float32)
    rng = jax.random.PRNGKey(0)
    stock = resnet18(num_classes=10, width=8)
    fused = resnet18(num_classes=10, width=8, norm="fused", norm_impl="xla")
    vs = stock.init(rng, x, train=False)
    vf = fused.init(rng, x, train=False)
    assert jax.tree_util.tree_all(
        jax.tree_util.tree_map(lambda a, b: bool(jnp.array_equal(a, b)), vs, vf)
    )
    ys, us = stock.apply(vs, x, train=True, mutable=["batch_stats"])
    yf, uf = fused.apply(vs, x, train=True, mutable=["batch_stats"])
    assert jnp.array_equal(ys, yf)
    assert jax.tree_util.tree_all(
        jax.tree_util.tree_map(lambda a, b: bool(jnp.array_equal(a, b)), us, uf)
    )
    assert jnp.array_equal(stock.apply(vs, x, train=False), fused.apply(vs, x, train=False))


def test_resnet_fused_interpret_forward_and_grad():
    """The real kernel through every resnet18 BN call site (stem ReLU,
    mid-block ReLU, zero-init + residual epilogue, norm_proj plain):
    forward and full-model grads allclose vs stock at f32."""

    r = np.random.RandomState(0)
    x = jnp.asarray(r.rand(2, 32, 32, 3), jnp.float32)
    stock = resnet18(num_classes=10, width=8, dtype=jnp.float32)
    interp = resnet18(
        num_classes=10, width=8, dtype=jnp.float32, norm="fused", norm_impl="interpret"
    )
    v = stock.init(jax.random.PRNGKey(0), x, train=False)

    def gradof(model):
        def f(p):
            y, _ = model.apply(
                {"params": p, "batch_stats": v["batch_stats"]},
                x, train=True, mutable=["batch_stats"],
            )
            return jnp.mean(y**2)

        return jax.grad(f)(v["params"])

    ys, _ = stock.apply(v, x, train=True, mutable=["batch_stats"])
    yi, _ = interp.apply(v, x, train=True, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(yi), np.asarray(ys), atol=1e-3)
    gs, gi = gradof(stock), gradof(interp)
    flat_s = jnp.concatenate([a.ravel() for a in jax.tree_util.tree_leaves(gs)])
    flat_i = jnp.concatenate([a.ravel() for a in jax.tree_util.tree_leaves(gi)])
    # relative l2 over all params: reduction-order noise compounds
    # through 18 layers; 1e-3 still catches any wrong VJP term
    assert float(jnp.linalg.norm(flat_s - flat_i)) <= 1e-3 * float(
        jnp.linalg.norm(flat_s)
    )


# ---------------------------------------------------------------------------
# trainer composition (the PR 4 fused-scan trainer; slow tier like the
# other full-model train-step compiles in tests/test_models.py)


def _trainer(model, batch):
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
    from tf_operator_tpu.parallel.trainer import batchnorm_cross_entropy_loss

    return Trainer(
        model,
        TrainerConfig(optimizer="sgd", learning_rate=0.05),
        make_mesh({"dp": 1}, devices=jax.devices()[:1]),
        batchnorm_cross_entropy_loss,
        batch,
    )


@pytest.mark.slow
def test_fused_trains_allclose_vs_stock_per_step_and_scanned():
    """ISSUE 19 acceptance: norm='fused' trains through the fused-scan
    trainer — per-step AND train_steps (lax.scan) paths — allclose vs
    the stock flax graph (fwd+grad land in the loss trajectory)."""

    r = np.random.RandomState(0)
    batch = {
        "image": jnp.asarray(r.rand(8, 32, 32, 3), jnp.float32),
        "label": jnp.asarray(r.randint(0, 10, size=(8,))),
    }
    kw = dict(num_classes=10, width=8, dtype=jnp.float32)
    stock = _trainer(resnet18(**kw), batch)
    fused = _trainer(resnet18(norm="fused", norm_impl="xla", **kw), batch)
    losses = {}
    for name, tr in (("stock", stock), ("fused", fused)):
        losses[name] = [float(tr.train_step(batch)["loss"]) for _ in range(3)]
    # impl='xla' is bit-comparable per layer; whole-graph jit fusion
    # differences leave only float noise in the trajectory
    np.testing.assert_allclose(losses["fused"], losses["stock"], rtol=1e-5)
    # the scanned multi-step path (PR 4): its own compiled program,
    # allclose within the documented per-step-vs-scan drift
    m = np.asarray(fused.train_steps(batch, 3)["loss"])
    m2 = np.asarray(stock.train_steps(batch, 3)["loss"])
    np.testing.assert_allclose(m, m2, rtol=1e-3)
    assert np.isfinite(m).all()


@pytest.mark.slow
def test_fused_interpret_trains_through_trainer():
    """The real kernel (interpreted) survives the full Trainer path —
    value_and_grad + optimizer + mutable batch_stats — and tracks the
    stock loss."""

    r = np.random.RandomState(1)
    batch = {
        "image": jnp.asarray(r.rand(4, 32, 32, 3), jnp.float32),
        "label": jnp.asarray(r.randint(0, 10, size=(4,))),
    }
    kw = dict(num_classes=10, width=8, dtype=jnp.float32)
    stock = _trainer(resnet18(**kw), batch)
    interp = _trainer(resnet18(norm="fused", norm_impl="interpret", **kw), batch)
    l_stock = [float(stock.train_step(batch)["loss"]) for _ in range(2)]
    l_interp = [float(interp.train_step(batch)["loss"]) for _ in range(2)]
    np.testing.assert_allclose(l_interp, l_stock, rtol=1e-3, atol=1e-3)


# ---------------------------------------------------------------------------
# fail-loud config matrix (the paged_kernel honesty contract)


def test_functional_fail_loud_matrix():
    x, gamma, beta, res = _inputs((2, 2, 2, 3), jnp.float32)
    with pytest.raises(ValueError, match="impl must be one of"):
        fused_batchnorm(x, gamma, beta, impl="bogus")
    if jax.default_backend() != "tpu":
        ok, why = fusedbn_available()
        assert not ok and "TPU backend" in why
        with pytest.raises(ValueError, match="refused"):
            fused_batchnorm(x, gamma, beta, impl="pallas")
    ok, why = fusedbn_available(interpret=True)
    assert ok and why == ""
    with pytest.raises(ValueError, match="gamma/beta"):
        fused_batchnorm(x, gamma[:2], beta, impl="xla")
    with pytest.raises(ValueError, match="residual shape"):
        fused_batchnorm(x, gamma, beta, residual=res[:1], impl="xla")
    assert FUSEDBN_IMPLS == ("xla", "pallas", "pallas-interpret")


def test_resnet_norm_validation_order_pinned():
    """The paged_kernel contract carried over: (1) a bad norm NAME
    fails as a bad name even when the impl is also unservable, (2) a
    bad impl spelling fails as a bad spelling, (3) semantic conflicts
    (bn_fold, impl-on-stock-norm), (4) availability — and an explicit
    pallas request on CPU REFUSES instead of downgrading to xla."""

    r = np.random.RandomState(0)
    x = jnp.asarray(r.rand(1, 32, 32, 3), jnp.float32)
    rng = jax.random.PRNGKey(0)

    def init(**kw):
        resnet18(num_classes=10, width=8, **kw).init(rng, x, train=False)

    # (1) bad name first, even with an unservable impl alongside
    with pytest.raises(ValueError, match="norm must be"):
        init(norm="bogus", norm_impl="pallas")
    # (2) bad impl spelling
    with pytest.raises(ValueError, match="norm_impl must be"):
        init(norm="fused", norm_impl="bogus")
    # (3) semantic conflicts
    with pytest.raises(ValueError, match="bn_fold"):
        init(norm="fused", bn_fold=True)
    with pytest.raises(ValueError, match="silent downgrade"):
        init(norm="batchnorm", norm_impl="pallas")
    # (4) availability: explicit pallas on a non-TPU backend refuses
    if jax.default_backend() != "tpu":
        with pytest.raises(ValueError, match="refused"):
            init(norm="fused", norm_impl="pallas")
        # ... while auto resolves to the xla composition and runs
        init(norm="fused", norm_impl="auto")
    # interpret is servable everywhere (the CI path)
    init(norm="fused", norm_impl="interpret")
