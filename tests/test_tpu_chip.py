"""Real-TPU tier (SURVEY.md §4 tier 4): compiled-kernel and on-chip
training checks.  Excluded by default; run with

    RUN_TPU_TESTS=1 python -m pytest tests/test_tpu_chip.py -m tpu -q

(VERDICT round 1 item 3: the pallas kernels' real-MXU behavior must be
validated by something reproducible, not only the CPU interpreter.)
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = [
    pytest.mark.tpu,
    pytest.mark.skipif(
        os.environ.get("RUN_TPU_TESTS") != "1", reason="set RUN_TPU_TESTS=1"
    ),
]

TOL = dict(atol=5e-3, rtol=5e-3)  # MXU f32 matmul precision ~1e-3


@pytest.fixture(scope="module")
def tpu():
    devs = jax.devices()
    if devs[0].platform != "tpu":
        pytest.skip(f"default backend is {devs[0].platform}, not tpu")
    return devs[0]


def rand_qkv(rng, b, h, s, d, dtype=jnp.bfloat16):
    r = np.random.RandomState(rng)
    mk = lambda: jnp.asarray(r.normal(size=(b, h, s, d)), dtype)
    return mk(), mk(), mk()


class TestFlashKernelOnChip:
    def test_forward_matches_xla(self, tpu):
        from tf_operator_tpu.ops import dot_product_attention, flash_attention

        q, k, v = rand_qkv(0, 2, 4, 1024, 128)
        got = jax.jit(lambda q, k, v: flash_attention(q, k, v, True))(q, k, v)
        want = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            atol=2e-2, rtol=2e-2,
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_backward_matches_xla(self, tpu, causal):
        from tf_operator_tpu.ops import dot_product_attention, flash_attention

        q, k, v = rand_qkv(1, 1, 2, 512, 128)
        w = jnp.asarray(
            np.random.RandomState(9).normal(size=q.shape), jnp.float32
        )

        def f_flash(q, k, v):
            return (flash_attention(q, k, v, causal).astype(jnp.float32) * w).sum()

        def f_ref(q, k, v):
            return (
                dot_product_attention(q, k, v, causal=causal).astype(jnp.float32) * w
            ).sum()

        g_flash = jax.jit(jax.grad(f_flash, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.jit(jax.grad(f_ref, argnums=(0, 1, 2)))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), g_flash, g_ref):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                err_msg=name, atol=3e-2, rtol=3e-2,
            )

    def test_flash_beats_xla_at_long_seq(self, tpu):
        """Training step (fwd+bwd) with the flash kernel must beat the
        XLA path at seq >= 4k (VERDICT round 1 item 4 done-criterion)."""

        import time

        from tf_operator_tpu.ops import dot_product_attention, flash_attention

        q, k, v = rand_qkv(2, 2, 8, 4096, 128)

        def train_flash(q, k, v):
            return flash_attention(q, k, v, True).astype(jnp.float32).sum()

        def train_xla(q, k, v):
            return dot_product_attention(q, k, v, causal=True).astype(jnp.float32).sum()

        def bench(f):
            # two-point SLOPE timing ending in a data-dependent host
            # fetch (parallel/trainer.benchmark, PROFILE.md "timing
            # honesty"): fixed dispatch/RTT/sync costs appear in both
            # windows and cancel, and a fetch cannot resolve early —
            # block_until_ready alone under-waits pallas programs on
            # the axon tunnel
            g = jax.jit(jax.grad(f, argnums=(0, 1, 2)))

            def window(n):
                t0 = time.perf_counter()
                for _ in range(n):
                    out = g(q, k, v)
                float(jnp.asarray(out[0]).astype(jnp.float32).sum())
                return time.perf_counter() - t0

            window(2)  # compile + settle
            return (window(12) - window(2)) / 10

        # real margin, not noise (VERDICT r2 item 8): the flash path
        # must win by >=10%.  Measured ratio printed for BASELINE.md.
        t_flash, t_xla = bench(train_flash), bench(train_xla)
        print(
            f"\nflash fwd+bwd @4k: {t_flash*1e3:.1f}ms  xla: {t_xla*1e3:.1f}ms  "
            f"speedup {t_xla/t_flash:.2f}x"
        )
        # bar raised with the r5 block autotune: the 1024-block kernel
        # measures 4.3-5.9x here; <2.5x would be a real regression
        # (the pre-autotune 128-block kernel scored 1.17x)
        assert t_flash < 0.4 * t_xla, (
            f"flash {t_flash*1e3:.1f}ms !< 0.4*xla {t_xla*1e3:.1f}ms"
        )


class TestTrainerOnChip:
    def test_one_resnet_step(self, tpu):
        from tf_operator_tpu.models import resnet18
        from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
        from tf_operator_tpu.parallel.trainer import batchnorm_cross_entropy_loss

        mesh = make_mesh({"dp": 1}, devices=[tpu])
        rng = np.random.RandomState(0)
        batch = {
            "image": jnp.asarray(rng.rand(8, 64, 64, 3), jnp.bfloat16),
            "label": jnp.asarray(rng.randint(0, 10, size=(8,))),
        }
        trainer = Trainer(
            resnet18(num_classes=10),
            TrainerConfig(optimizer="sgd", learning_rate=0.1),
            mesh,
            batchnorm_cross_entropy_loss,
            batch,
        )
        m = trainer.train_step(trainer.shard_batch(batch))
        assert np.isfinite(float(m["loss"]))

    def test_one_gpt_step_with_flash(self, tpu, monkeypatch):
        from tf_operator_tpu.models import gpt_tiny, lm_loss
        from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

        # seq 256 sits below the auto-dispatch crossover
        # (TPU_OPERATOR_FLASH_MIN_SEQ): force the kernel so this chip
        # gate actually exercises the flash path it is named for
        monkeypatch.setenv("TPU_OPERATOR_FLASH", "1")
        mesh = make_mesh({"dp": 1}, devices=[tpu])
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, size=(2, 256)))
        trainer = Trainer(
            gpt_tiny(vocab_size=128, max_len=256, mesh=mesh),
            TrainerConfig(learning_rate=1e-3),
            mesh,
            lm_loss,
            {"input_ids": ids},
            init_args=(ids,),
            shardings="logical",
        )
        m = trainer.train_step(trainer.shard_batch({"input_ids": ids}))
        assert np.isfinite(float(m["loss"]))


class TestBenchSmoke:
    def test_bench_emits_number(self, tpu):
        """bench-shaped smoke: tiny config through the same code path the
        driver runs."""

        import json
        import subprocess
        import sys

        env = dict(os.environ)
        env.update(
            BENCH_BATCH_PER_CHIP="16", BENCH_STEPS="3", BENCH_RETRIES="1",
            # tiny smoke: the full-size ~700M wide-decode probe has no
            # place in it
            BENCH_WIDE_DECODE="0",
        )
        out = subprocess.run(
            [sys.executable, os.path.join(os.path.dirname(__file__), "..", "bench.py")],
            env=env, capture_output=True, text=True, timeout=1200,
        )
        line = [l for l in out.stdout.splitlines() if l.strip().startswith("{")][-1]
        result = json.loads(line)
        assert "error" not in result, result
        assert result["value"] > 0
        # the success JSON must carry the measurement-window ledger when
        # one exists (the driver artifact's full field set — r5)
        ledger = os.path.join(
            os.path.dirname(__file__), "..", "benchmarks", "LAST_MEASURED.json"
        )
        if os.path.exists(ledger):
            assert "last_measured" in result


@pytest.mark.tpu
class TestWindowAttentionOnChip:
    """Banded sliding-window kernels on the real chip: correctness vs
    the banded XLA reference, and the O(S*window) banding must beat
    full-attention flash at long seq."""

    def test_windowed_forward_matches_xla(self, tpu):
        from tf_operator_tpu.ops import dot_product_attention
        from tf_operator_tpu.ops.flash_attention import flash_attention

        q, k, v = rand_qkv(7, 2, 4, 4096, 128)
        got = jax.jit(
            lambda q, k, v: flash_attention(q, k, v, True, window=512)
        )(q, k, v)
        want = dot_product_attention(q, k, v, causal=True, window=512)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            atol=2e-2, rtol=2e-2,
        )

    def test_banded_window_beats_full_flash(self, tpu):
        """seq 8k, window 1k: the banded grid does ~1/4 the work of
        full causal flash — demand a real wall-clock win."""

        import time

        from tf_operator_tpu.ops.flash_attention import flash_attention

        q, k, v = rand_qkv(8, 2, 8, 8192, 128)

        def bench(f):
            # slope timing with host-fetch sync — see
            # TestFlashKernelOnChip.bench
            g = jax.jit(jax.grad(f, argnums=(0, 1, 2)))

            def window(n):
                t0 = time.perf_counter()
                for _ in range(n):
                    out = g(q, k, v)
                float(jnp.asarray(out[0]).astype(jnp.float32).sum())
                return time.perf_counter() - t0

            window(2)  # compile + settle
            return (window(12) - window(2)) / 10

        t_win = bench(
            lambda q, k, v: flash_attention(q, k, v, True, window=1024)
            .astype(jnp.float32).sum()
        )
        t_full = bench(
            lambda q, k, v: flash_attention(q, k, v, True)
            .astype(jnp.float32).sum()
        )
        print(
            f"\nwindowed fwd+bwd @8k/w1k: {t_win*1e3:.1f}ms  "
            f"full: {t_full*1e3:.1f}ms  speedup {t_full/t_win:.2f}x"
        )
        # the banding must actually pay; bar raised with the r5 block
        # autotune (measured 2.7-5x depending on full-flash defaults)
        assert t_win < 0.55 * t_full
