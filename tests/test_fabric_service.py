"""Cross-pod KV fabric service (ISSUE 17 tentpole) — unit tier.

The load-bearing pins, none of which compile a model (the arena
template is a synthetic pytree, so this file stays tier-1 fast):

- WIRE TAXONOMY: every way a /fabric/blocks body can be wrong maps to
  exactly one PULL_FAILURE_REASONS entry — bit-flip/version/leaf-count
  → corrupt, lying length prefix → short_read, no arena yet →
  no_template — and the content hash is checked BEFORE the tree is
  rebuilt.
- FLEET RESOLVE: a pull hit lands the block in the LOCAL fabric
  (later gets are local, no transport key), carries transport="http" +
  peer, and meters kv_fabric_pulls_total / kv_fabric_peer_up /
  bytes_pulled; a fleet-wide miss counts miss; a local-only fabric
  counts nothing.
- CHAOS LEGS: a FaultInjector socket reset mid-pull degrades to
  recompute with reason=peer_dead and kv_fabric_peer_up=0 — and the
  same pull succeeds once chaos clears; a stale index (peer evicted
  between index and pull) 404s into reason=not_found WITHOUT marking
  the peer dead, and prunes the cached index.
- DISCOVERY: put() announces to peers (push), handle_publish merges
  unknown senders (discovery) and drops malformed keys.
- PIN LEASES: get(pin=True) leases expire after pin_ttl_seconds — a
  crashed puller can only block eviction for the TTL, never forever.
- CLI: ``tpujob fabric`` renders the pull ledger down-peers-first, and
  ``tpujob fabric JOB`` probes reconciler-stamped fabric-port
  annotations.
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tf_operator_tpu.backend.kubesim import FaultInjector
from tf_operator_tpu.backend.retry import fabric_pull_policy
from tf_operator_tpu.models.fabric_service import (
    PULL_FAILURE_REASONS,
    WIRE_VERSION,
    FabricServer,
    FleetFabric,
    PullError,
    decode_block,
    encode_block,
)
from tf_operator_tpu.models.prefix_cache import PrefixFabric, chain_keys
from tf_operator_tpu.utils.metrics import Metrics

KEY = chain_keys(np.arange(16), 16)[0]
KEY2 = chain_keys(np.arange(32), 16)[1]
KEY3 = chain_keys(np.arange(48), 16)[2]

#: two (1, 2, 4, 4) float32 block-row leaves
NBYTES = 2 * 2 * 4 * 4 * 4


def _arena(num_blocks=8):
    """A synthetic paged arena: two block-row (ndim-4) leaves plus a
    scalar bookkeeping leaf the wire must skip/zero-fill."""

    return {
        "k": np.zeros((num_blocks, 2, 4, 4), np.float32),
        "v": np.zeros((num_blocks, 2, 4, 4), np.float32),
        "step": np.zeros((), np.int32),
    }


def _block(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "k": rng.standard_normal((1, 2, 4, 4)).astype(np.float32),
        "v": rng.standard_normal((1, 2, 4, 4)).astype(np.float32),
        "step": np.zeros((), np.int32),
    }


def _fleet(local=None, peers=(), metrics=None, **kw):
    kw.setdefault("request_timeout", 5.0)
    fab = FleetFabric(
        local if local is not None else PrefixFabric(model_label="t"),
        peers=peers,
        metrics=metrics if metrics is not None else Metrics(),
        model_label="t",
        **kw,
    )
    fab.register_template(_arena())
    return fab


def _fast_policy():
    """Zero-backoff pull policy so chaos legs exhaust the retry budget
    instantly."""

    return fabric_pull_policy(base_delay=0.0, max_delay=0.0)


# ---------------------------------------------------------------- wire codec


class TestWireCodec:
    def test_roundtrip_header_and_payload(self):
        fleet = _fleet()
        body = encode_block(KEY, {"kv": _block(1), "nbytes": NBYTES})
        header = json.loads(body[: body.index(b"\n")])
        assert header["v"] == WIRE_VERSION
        assert header["key"] == KEY.hex()
        assert header["nbytes"] == NBYTES
        assert len(header["leaves"]) == 2  # the scalar leaf rides free
        tree, nbytes = decode_block(body, fleet._template)
        want = _block(1)
        np.testing.assert_array_equal(tree["k"], want["k"])
        np.testing.assert_array_equal(tree["v"], want["v"])
        assert tree["step"].shape == () and nbytes == NBYTES

    def test_bit_flip_is_corrupt(self):
        fleet = _fleet()
        body = bytearray(
            encode_block(KEY, {"kv": _block(1), "nbytes": NBYTES})
        )
        body[-1] ^= 0x40  # one payload bit
        with pytest.raises(PullError) as ei:
            decode_block(bytes(body), fleet._template)
        assert ei.value.reason == "corrupt"

    def test_wire_version_mismatch_is_corrupt(self):
        fleet = _fleet()
        body = encode_block(KEY, {"kv": _block(1), "nbytes": NBYTES})
        nl = body.index(b"\n")
        header = json.loads(body[:nl])
        header["v"] = WIRE_VERSION + 1
        body = json.dumps(header).encode() + body[nl:]
        with pytest.raises(PullError) as ei:
            decode_block(body, fleet._template)
        assert ei.value.reason == "corrupt"

    def test_lying_length_prefix_is_short_read(self):
        # truncate the payload but keep the hash HONEST (recomputed):
        # the hash passes, the second leaf's length prefix lies
        fleet = _fleet()
        body = encode_block(KEY, {"kv": _block(1), "nbytes": NBYTES})
        nl = body.index(b"\n")
        header = json.loads(body[:nl])
        payload = body[nl + 1 :][:200]  # mid-second-leaf
        import hashlib

        header["sha256"] = hashlib.sha256(payload).hexdigest()
        with pytest.raises(PullError) as ei:
            decode_block(
                json.dumps(header).encode() + b"\n" + payload,
                fleet._template,
            )
        assert ei.value.reason == "short_read"

    def test_bad_dtype_is_corrupt_not_a_crash(self):
        fleet = _fleet()
        body = encode_block(KEY, {"kv": _block(1), "nbytes": NBYTES})
        nl = body.index(b"\n")
        header = json.loads(body[:nl])
        header["leaves"][0]["dtype"] = "not-a-dtype!!"
        with pytest.raises(PullError) as ei:
            decode_block(json.dumps(header).encode() + body[nl:],
                         fleet._template)
        assert ei.value.reason == "corrupt"

    def test_leaf_count_mismatch_is_corrupt(self):
        fleet = _fleet()
        body = encode_block(KEY, {"kv": _block(1), "nbytes": NBYTES})
        nl = body.index(b"\n")
        header = json.loads(body[:nl])
        header["leaves"] = header["leaves"][:1]
        with pytest.raises(PullError) as ei:
            decode_block(json.dumps(header).encode() + body[nl:],
                         fleet._template)
        assert ei.value.reason == "corrupt"

    def test_no_template_is_its_own_reason(self):
        with pytest.raises(PullError) as ei:
            decode_block(b"{}\n", None)
        assert ei.value.reason == "no_template"

    def test_taxonomy_is_closed(self):
        # every reason the codec/client can raise is a declared label
        # value — the alert rule and dashboards key off these literals
        for reason in ("corrupt", "short_read", "no_template"):
            assert reason in PULL_FAILURE_REASONS
        assert len(set(PULL_FAILURE_REASONS)) == len(PULL_FAILURE_REASONS)


# ------------------------------------------------------------- fleet resolve


class TestFleetPull:
    def test_remote_pull_hit_lands_locally(self):
        A = _fleet()
        A.local.put(KEY, _block(1), NBYTES)
        srv = FabricServer(A).start()
        try:
            mB = Metrics()
            B = _fleet(peers=[srv.addr], metrics=mB)
            # fleet-wide membership sees the peer's catalog...
            assert KEY in B
            # ...but nothing is local until a pull
            assert KEY not in B.local
            rec = B.get(KEY, pin=True)
            assert rec is not None
            assert rec["transport"] == "http"
            assert rec["peer"] == srv.addr
            assert rec["nbytes"] == NBYTES
            np.testing.assert_array_equal(rec["kv"]["k"], _block(1)["k"])
            assert B.pulls == {"hit": 1, "miss": 0, "failed": 0}
            assert B.bytes_pulled == NBYTES
            assert mB.counter(
                "kv_fabric_pulls_total", model="t", outcome="hit"
            ) == 1
            assert mB.gauge("kv_fabric_peer_up", peer=srv.addr) == 1.0
            # the pull landed in the LOCAL fabric: the next get is a
            # local hit — no transport key, no second pull counted
            B.unpin(KEY)
            again = B.get(KEY)
            assert again is not None and "transport" not in again
            assert B.pulls["hit"] == 1
            snap = B.snapshot()
            assert snap["pulls"]["hit"] == 1
            assert snap["bytes_pulled"] == NBYTES
            assert snap["peers"][0]["up"] is True
        finally:
            srv.stop()

    def test_fleet_wide_miss_counts_miss(self):
        A = _fleet()
        srv = FabricServer(A).start()
        try:
            B = _fleet(peers=[srv.addr])
            assert B.get(KEY) is None
            assert B.pulls == {"hit": 0, "miss": 1, "failed": 0}
        finally:
            srv.stop()

    def test_local_only_fabric_counts_nothing(self):
        B = _fleet()
        assert B.get(KEY) is None
        assert B.pulls == {"hit": 0, "miss": 0, "failed": 0}

    def test_pull_before_template_counts_no_template(self):
        A = _fleet()
        A.local.put(KEY, _block(1), NBYTES)
        srv = FabricServer(A).start()
        try:
            B = FleetFabric(
                PrefixFabric(model_label="t"),
                peers=[srv.addr], metrics=Metrics(), model_label="t",
            )  # pool still booting: no register_template yet
            assert B.get(KEY) is None
            assert B.pull_failures == {"no_template": 1}
            assert B.pulls["failed"] == 1
        finally:
            srv.stop()


# --------------------------------------------------------------- chaos legs


class TestChaosLegs:
    def test_stale_index_404_counts_not_found(self):
        local = PrefixFabric(capacity_blocks=1, model_label="t")
        A = _fleet(local=local)
        A.local.put(KEY, _block(1), NBYTES)
        srv = FabricServer(A).start()
        try:
            mB = Metrics()
            B = _fleet(
                peers=[srv.addr], metrics=mB, index_ttl_seconds=3600.0
            )
            B.refresh_peers()  # cached catalog: peer holds KEY
            # peer evicts KEY between index and pull (stale catalog)
            A.local.put(KEY2, _block(2), NBYTES)
            assert KEY not in A.local
            assert B.get(KEY) is None
            assert B.pulls == {"hit": 0, "miss": 0, "failed": 1}
            assert B.pull_failures == {"not_found": 1}
            assert mB.counter(
                "kv_fabric_pull_failures_total",
                model="t", reason="not_found",
            ) == 1
            snap = B.snapshot()
            # the 404 pruned the stale key from the cached index...
            assert snap["peers"][0]["keys"] == 0
            # ...and a 404 is normal churn, NOT a dead peer
            assert snap["peers"][0]["up"] is True
        finally:
            srv.stop()

    def test_peer_reset_mid_pull_counts_peer_dead_then_recovers(self):
        A = _fleet()
        A.local.put(KEY, _block(1), NBYTES)
        faults = FaultInjector(seed=7)
        srv = FabricServer(A, faults=faults).start()
        try:
            mB = Metrics()
            B = _fleet(
                peers=[srv.addr], metrics=mB, policy=_fast_policy()
            )
            B.refresh_peers()  # index read lands before chaos arms
            faults.add(path="^/fabric/blocks/", mode="reset")
            assert B.get(KEY) is None
            assert B.pulls["failed"] == 1
            assert B.pull_failures == {"peer_dead": 1}
            assert mB.counter(
                "kv_fabric_pull_failures_total",
                model="t", reason="peer_dead",
            ) == 1
            assert mB.gauge("kv_fabric_peer_up", peer=srv.addr) == 0.0
            assert faults.total_injected() >= 1
            # chaos clears → the SAME pull succeeds: degrade, not wedge
            faults.clear()
            rec = B.get(KEY)
            assert rec is not None and rec["transport"] == "http"
            assert mB.gauge("kv_fabric_peer_up", peer=srv.addr) == 1.0
        finally:
            srv.stop()

    def test_http_500_counts_http_error(self):
        A = _fleet()
        A.local.put(KEY, _block(1), NBYTES)
        faults = FaultInjector(seed=7)
        srv = FabricServer(A, faults=faults).start()
        try:
            B = _fleet(peers=[srv.addr], policy=_fast_policy())
            B.refresh_peers()
            faults.add(path="^/fabric/blocks/", mode="error", status=500)
            assert B.get(KEY) is None
            assert B.pull_failures == {"http_error": 1}
        finally:
            srv.stop()


# ---------------------------------------------------------------- discovery


class TestDiscovery:
    def test_handle_publish_merges_and_discovers(self):
        B = _fleet()
        B.set_advertise("127.0.0.1:1")
        B.handle_publish({
            "advertise": "127.0.0.1:2",
            "keys": [KEY.hex(), "zz-not-hex"],  # malformed keys drop
            "generation": 3,
        })
        snap = B.snapshot()
        assert snap["peers"] == [{
            "peer": "127.0.0.1:2", "up": True, "keys": 1, "generation": 3,
        }]
        # own advertise and anonymous senders are ignored
        B.handle_publish({"advertise": "127.0.0.1:1", "keys": [KEY2.hex()]})
        B.handle_publish({"keys": [KEY2.hex()]})
        assert len(B.snapshot()["peers"]) == 1

    def test_put_announces_to_peers_over_the_wire(self):
        B = _fleet()
        srvB = FabricServer(B).start()
        B.set_advertise(srvB.addr)
        A = _fleet(peers=[srvB.addr])
        srvA = FabricServer(A).start()
        A.set_advertise(srvA.addr)
        try:
            A.put(KEY, _block(1), NBYTES)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                peers = {
                    p["peer"]: p for p in B.snapshot()["peers"]
                }
                if peers.get(srvA.addr, {}).get("keys"):
                    break
                time.sleep(0.01)
            else:
                pytest.fail("announcement never reached the peer")
            # B pulls straight off the announced catalog (no index read)
            rec = B.get(KEY)
            assert rec is not None and rec["peer"] == srvA.addr
        finally:
            A.stop()
            srvA.stop()
            srvB.stop()


# ------------------------------------------------------------- fabric server


class TestFabricServer:
    def test_index_block_and_health_routes(self):
        A = _fleet()
        A.local.put(KEY, _block(1), NBYTES)
        srv = FabricServer(A).start()
        A.set_advertise(srv.addr)
        try:
            with urllib.request.urlopen(f"{srv.url}/fabric/index") as r:
                idx = json.loads(r.read())
            assert idx["v"] == WIRE_VERSION
            assert idx["model"] == "t"
            assert idx["advertise"] == srv.addr
            assert idx["keys"] == [KEY.hex()]
            assert idx["generation"] == 1
            with urllib.request.urlopen(
                f"{srv.url}/fabric/blocks/{KEY.hex()}"
            ) as r:
                body = r.read()
            tree, nb = decode_block(body, A._template)
            assert nb == NBYTES
            np.testing.assert_array_equal(tree["k"], _block(1)["k"])
            # the encode-time pin was released (no leaked lease)
            assert A.local.snapshot()["pinned"] == 0
            with urllib.request.urlopen(f"{srv.url}/healthz") as r:
                assert r.read() == b"ok\n"
        finally:
            srv.stop()

    def test_error_statuses(self):
        A = _fleet()
        srv = FabricServer(A).start()
        try:
            for path, code in [
                (f"/fabric/blocks/{KEY.hex()}", 404),  # unknown key
                ("/fabric/blocks/zz", 400),            # bad hex
                ("/nope", 404),
            ]:
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(srv.url + path)
                assert ei.value.code == code
            req = urllib.request.Request(
                f"{srv.url}/fabric/publish", data=b"not json",
                method="POST",
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 400
        finally:
            srv.stop()


# ---------------------------------------------------------------- pin leases


class TestPinLeases:
    def test_live_lease_blocks_eviction_until_ttl(self):
        now = [0.0]
        fab = PrefixFabric(
            capacity_blocks=1, model_label="t",
            pin_ttl_seconds=10.0, clock=lambda: now[0],
        )
        fab.put(KEY, _block(1), NBYTES)
        assert fab.get(KEY, pin=True) is not None
        # live lease: pressure reclaims around the pinned block
        fab.put(KEY2, _block(2), NBYTES)
        assert KEY in fab
        assert fab.snapshot()["pin_expiries"] == 0
        # lease expires → the next pressure pass reclaims it
        now[0] = 11.0
        fab.put(KEY3, _block(3), NBYTES)
        assert KEY not in fab
        snap = fab.snapshot()
        assert snap["pin_expiries"] == 1
        assert snap["pinned"] == 0
        assert snap["blocks"] == 1

    def test_unpin_releases_before_ttl(self):
        now = [0.0]
        fab = PrefixFabric(
            capacity_blocks=1, model_label="t",
            pin_ttl_seconds=10.0, clock=lambda: now[0],
        )
        fab.put(KEY, _block(1), NBYTES)
        fab.get(KEY, pin=True)
        fab.unpin(KEY)
        fab.put(KEY2, _block(2), NBYTES)
        assert KEY not in fab and KEY2 in fab
        assert fab.snapshot()["pin_expiries"] == 0

    def test_index_keys_generation_stamp(self):
        fab = PrefixFabric(model_label="t")
        assert fab.index_keys() == ([], 0)
        fab.put(KEY, _block(1), NBYTES)
        keys, gen = fab.index_keys()
        assert keys == [KEY] and gen == 1
        # idempotent re-publish: no generation bump, no double count
        fab.put(KEY, _block(1), NBYTES)
        assert fab.index_keys()[1] == 1
        assert fab.snapshot()["publishes"] == 1


# ---------------------------------------------------------------------- CLI


class TestFabricCLI:
    def test_cli_fabric_renders_pull_ledger_down_first(
        self, capsys, monkeypatch
    ):
        from tf_operator_tpu.cmd import tpujob as cli

        snap = {
            "model": "t",
            "fabric": {
                "advertise": "127.0.0.1:9",
                "blocks": 3, "generation": 5, "publishes": 4,
                "evictions": 1, "pin_expiries": 0,
                "pulls": {"hit": 2, "miss": 1, "failed": 1},
                "pull_failures": {"peer_dead": 1},
                "bytes_pulled": 512,
                "peers": [
                    {"peer": "127.0.0.1:7", "up": True,
                     "keys": 3, "generation": 5},
                    {"peer": "127.0.0.1:8", "up": False,
                     "keys": 0, "generation": 0},
                ],
            },
        }
        seen = {}

        def fake(method, url, payload=None):
            seen["url"] = url
            return snap

        monkeypatch.setattr(cli, "_request", fake)
        rc = cli.main(["fabric"])
        assert rc == 0
        out = capsys.readouterr().out
        assert seen["url"].endswith("/debug/fabric")
        assert "hit=2" in out and "peer_dead=1" in out
        assert "512 bytes" in out
        assert "DOWN" in out
        # the down peer leads — what-needs-acting-on-first
        assert out.index("127.0.0.1:8") < out.index("127.0.0.1:7")

    def test_cli_fabric_job_probes_annotated_ports(
        self, capsys, monkeypatch
    ):
        from tf_operator_tpu.cmd import tpujob as cli

        A = _fleet()
        A.local.put(KEY, _block(1), NBYTES)
        srv = FabricServer(A).start()
        A.set_advertise(srv.addr)
        try:
            pods = {"items": [
                {"name": "j-0", "annotations": {
                    "tpujob.dist/fabric-port": str(srv.port)}},
                {"name": "j-1", "annotations": {
                    "tpujob.dist/fabric-port": "1"}},  # nothing listens
                {"name": "j-2", "annotations": {}},    # not a fabric pod
            ]}
            seen = {}

            def fake(method, url, payload=None):
                seen["url"] = url
                return pods

            monkeypatch.setattr(cli, "_request", fake)
            rc = cli.main(["fabric", "prod/j"])
            assert rc == 0
            out = capsys.readouterr().out
            assert seen["url"].endswith("/namespaces/prod/tpujobs/j/pods")
            assert "j-0" in out and srv.addr in out
            assert "j-1" in out and "DOWN" in out
            assert "j-2" not in out
        finally:
            srv.stop()
