"""Leader failover e2e: the reference's HA story, executable.

Two operator replicas elect through a coordination.k8s.io/v1 Lease in
the shared apiserver (cmd/leader.py KubeLease), TPUJobs live in the
apiserver as custom resources (backend/kubejobs.py KubeJobStore), and
pods run in the apiserver's kubelet sim — so when the leader is
SIGKILLed mid-job, the standby acquires the expired lease, resyncs
the job AND its still-running pod from the apiserver (adoption by
owner uid, unchanged), and drives the job to Succeeded.  This is what
the in-proc JobStore could never do (docs/TRUST.md's old HA caveat:
each process had its own memory).
"""

import json
import os
import re
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from tf_operator_tpu.backend.kubesim import MiniApiServer

pytestmark = pytest.mark.slow


def _wait(cond, timeout, what):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(0.2)
    raise TimeoutError(what)


def _port_from_log(path):
    try:
        with open(path) as f:
            m = re.search(r"listening on 127\.0\.0\.1:(\d+)", f.read())
        return int(m.group(1)) if m else None
    except OSError:
        return None


def _job_api(port, method="GET", path="/apis/v1/tpujobs", payload=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode() if payload is not None else None,
        method=method,
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())


def _is_leader(port):
    """The job API answers 200 on the leader, 503 on standbys."""

    try:
        _job_api(port)
        return True
    except urllib.error.HTTPError as e:
        if e.code == 503:
            return False
        raise
    except (urllib.error.URLError, ConnectionError, OSError):
        return False


class TestLeaderFailover:
    def test_standby_takes_over_and_finishes_the_job(self, tmp_path):
        sim = MiniApiServer().start()
        procs = []

        def spawn(tag):
            log_path = tmp_path / f"op-{tag}.log"
            lf = open(log_path, "w")
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "tf_operator_tpu.cmd.operator",
                    "--backend", "kube", "--kube-url", sim.url,
                    "--leader-elect", "--lease-duration", "2",
                    "--monitoring-port", "0",
                ],
                stdout=lf, stderr=subprocess.STDOUT, cwd=os.getcwd(),
            )
            procs.append(proc)
            return proc, log_path

        try:
            op_a, log_a = spawn("a")
            op_b, log_b = spawn("b")
            port_a = _wait(
                lambda: _port_from_log(log_a), 30, "operator A port"
            )
            port_b = _wait(
                lambda: _port_from_log(log_b), 30, "operator B port"
            )

            # exactly one leader
            _wait(
                lambda: _is_leader(port_a) != _is_leader(port_b)
                and (_is_leader(port_a) or _is_leader(port_b)),
                30,
                "one elected leader",
            )
            if _is_leader(port_a):
                leader, leader_port, standby_port = op_a, port_a, port_b
            else:
                leader, leader_port, standby_port = op_b, port_b, port_a

            # a job whose worker outlives the leader: sleeps 20s, exit 0
            manifest = {
                "apiVersion": "tpujob.dist/v1",
                "kind": "TPUJob",
                "metadata": {"name": "failover", "namespace": "default"},
                "spec": {
                    "tpuReplicaSpecs": {
                        "Worker": {
                            "replicas": 1,
                            "template": {
                                "spec": {
                                    "containers": [{
                                        "name": "tensorflow",
                                        "command": [
                                            sys.executable, "-c",
                                            "import time; time.sleep(20); "
                                            "print('survived failover')",
                                        ],
                                    }],
                                }
                            },
                        }
                    }
                },
            }
            _job_api(
                leader_port, "POST",
                "/apis/v1/namespaces/default/tpujobs", manifest,
            )

            def job_state(port):
                items = _job_api(port)["items"]
                for j in items:
                    if j["metadata"]["name"] == "failover":
                        conds = [
                            c["type"]
                            for c in j.get("status", {}).get("conditions", [])
                            if c.get("status") in (True, "True")
                        ]
                        return conds
                return None

            _wait(
                lambda: "Running" in (job_state(leader_port) or []),
                60, "job Running under the first leader",
            )
            # the pod really runs in the shared kubelet sim
            assert any(
                key[0] == "Pod" for key in sim.store.objects
            ), "pod must exist in the apiserver"

            # CRASH the leader (no clean release: the lease must EXPIRE)
            leader.send_signal(signal.SIGKILL)
            leader.wait(timeout=10)

            # the standby takes over within a few lease durations...
            _wait(lambda: _is_leader(standby_port), 30, "standby takeover")
            # ...sees the SAME job (it lives in the apiserver)...
            _wait(
                lambda: job_state(standby_port) is not None,
                30, "job visible to the new leader",
            )
            # ...and drives it to completion when the adopted pod exits
            _wait(
                lambda: "Succeeded" in (job_state(standby_port) or []),
                120, "job Succeeded under the new leader",
            )
            # the worker process itself was never restarted: its log
            # (written by the shared kubelet sim) shows one run
            log = sim._log_path("default", "failover-worker-0")
            with open(log) as f:
                assert f.read().count("survived failover") == 1
            # the audit trail SPANS the failover: events live in the
            # apiserver, so the first leader's pod-create and the new
            # leader's completion are one history
            import urllib.parse as _up

            q = _up.quote(
                "involvedObject.name=failover,involvedObject.namespace=default"
            )

            def reasons():
                with urllib.request.urlopen(
                    f"{sim.url}/api/v1/namespaces/default/events"
                    f"?fieldSelector={q}",
                    timeout=5,
                ) as resp:
                    return [
                        e["reason"]
                        for e in json.loads(resp.read())["items"]
                    ]

            # posting is async; poll briefly for the final event
            _wait(
                lambda: "SuccessfulCreatePod" in reasons()  # first leader
                and "JobSucceeded" in reasons(),  # second leader
                15,
                "audit trail spans the failover",
            )
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            sim.stop()

    def test_operator_restart_resumes_the_job(self, tmp_path):
        """Single-replica restart: kill the only operator mid-job and
        start a FRESH process against the same apiserver — it must
        pick the job up from storage (initial-list replay, no resync
        wait), adopt the still-running pod, and finish the job."""

        sim = MiniApiServer().start()
        procs = []

        def spawn(tag):
            log_path = tmp_path / f"op-{tag}.log"
            lf = open(log_path, "w")
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "tf_operator_tpu.cmd.operator",
                    "--backend", "kube", "--kube-url", sim.url,
                    "--monitoring-port", "0",
                ],
                stdout=lf, stderr=subprocess.STDOUT, cwd=os.getcwd(),
            )
            procs.append(proc)
            return proc, log_path

        try:
            op1, log1 = spawn("one")
            port1 = _wait(lambda: _port_from_log(log1), 30, "first port")
            manifest = {
                "apiVersion": "tpujob.dist/v1",
                "kind": "TPUJob",
                "metadata": {"name": "restartme", "namespace": "default"},
                "spec": {
                    "tpuReplicaSpecs": {
                        "Worker": {
                            "replicas": 1,
                            "template": {"spec": {"containers": [{
                                "name": "tensorflow",
                                "command": [
                                    sys.executable, "-c",
                                    "import time; time.sleep(15); "
                                    "print('outlived the operator')",
                                ],
                            }]}},
                        }
                    }
                },
            }
            _job_api(
                port1, "POST", "/apis/v1/namespaces/default/tpujobs", manifest
            )

            def conds(port):
                for j in _job_api(port)["items"]:
                    if j["metadata"]["name"] == "restartme":
                        return [
                            c["type"]
                            for c in j.get("status", {}).get("conditions", [])
                            if c.get("status") in (True, "True")
                        ]
                return None

            _wait(lambda: "Running" in (conds(port1) or []), 60, "Running")
            op1.send_signal(signal.SIGKILL)
            op1.wait(timeout=10)

            op2, log2 = spawn("two")
            port2 = _wait(lambda: _port_from_log(log2), 30, "second port")
            _wait(
                lambda: conds(port2) is not None, 30,
                "job visible after restart",
            )
            _wait(
                lambda: "Succeeded" in (conds(port2) or []), 120,
                "job Succeeded after restart",
            )
            log = sim._log_path("default", "restartme-worker-0")
            with open(log) as f:
                assert f.read().count("outlived the operator") == 1
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            sim.stop()

    def test_apiserver_outage_mid_job_heals(self, tmp_path):
        """Failure-detection at the cluster tier: the apiserver drops
        off the network mid-job (listener closed; kubelet keeps the
        worker running — real kubelets outlive apiserver outages), the
        worker FINISHES during the outage, and when the apiserver
        returns the operator's watch streams re-list, see the
        Succeeded pod, and complete the job.  No restarts, no Failed
        conditions from infrastructure errors."""

        sim = MiniApiServer().start()
        procs = []
        try:
            log_path = tmp_path / "op.log"
            lf = open(log_path, "w")
            op = subprocess.Popen(
                [
                    sys.executable, "-m", "tf_operator_tpu.cmd.operator",
                    "--backend", "kube", "--kube-url", sim.url,
                    "--monitoring-port", "0",
                ],
                stdout=lf, stderr=subprocess.STDOUT, cwd=os.getcwd(),
            )
            procs.append(op)
            port = _wait(lambda: _port_from_log(log_path), 30, "port")
            manifest = {
                "apiVersion": "tpujob.dist/v1",
                "kind": "TPUJob",
                "metadata": {"name": "outage", "namespace": "default"},
                "spec": {
                    "tpuReplicaSpecs": {
                        "Worker": {
                            "replicas": 1,
                            "template": {"spec": {"containers": [{
                                "name": "tensorflow",
                                "command": [
                                    sys.executable, "-c",
                                    "import time; time.sleep(6); "
                                    "print('finished during the outage')",
                                ],
                            }]}},
                        }
                    }
                },
            }
            _job_api(
                port, "POST", "/apis/v1/namespaces/default/tpujobs", manifest
            )

            def conds():
                for j in _job_api(port)["items"]:
                    if j["metadata"]["name"] == "outage":
                        return [
                            c["type"]
                            for c in j.get("status", {}).get("conditions", [])
                            if c.get("status") in (True, "True")
                        ]
                return None

            _wait(lambda: "Running" in (conds() or []), 60, "Running")

            sim.pause()  # the apiserver vanishes from the network...
            time.sleep(8)  # ...spanning the worker's exit
            sim.resume()

            _wait(
                lambda: "Succeeded" in (conds() or []), 90,
                "job Succeeded after the apiserver returned",
            )
            assert "Failed" not in (conds() or [])
            log = sim._log_path("default", "outage-worker-0")
            with open(log) as f:
                assert f.read().count("finished during the outage") == 1
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            sim.stop()
