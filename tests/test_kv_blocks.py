"""Block allocator + prefix cache invariants (ISSUE 8 satellite).

Property-style: random admit/finish/share/evict sequences must
conserve the free list (free + live == usable, no double-free, no id
aliased across live holders) and never reclaim a refcounted shared
block while anything maps it.  Host-only — no jax, runs in tier-1.
"""

import numpy as np
import pytest

from tf_operator_tpu.models.kv_blocks import (
    SCRATCH_BLOCK,
    BlockAllocator,
    BlockError,
    SwapArena,
    blocks_for,
)
from tf_operator_tpu.models.prefix_cache import (
    PrefixCache,
    chain_keys,
    exact_key,
)


class TestBlockAllocator:
    def test_alloc_free_roundtrip_and_conservation(self):
        a = BlockAllocator(9, 16)  # 8 usable + scratch
        assert a.usable == 8 and a.free_count == 8
        ids = a.alloc(5)
        assert len(ids) == 5 and len(set(ids)) == 5
        assert SCRATCH_BLOCK not in ids
        assert a.free_count == 3 and a.in_use == 5
        a.check()
        assert a.release(ids) == 5
        assert a.free_count == 8 and a.in_use == 0
        a.check()

    def test_all_or_nothing_on_shortfall(self):
        a = BlockAllocator(5, 8)  # 4 usable
        first = a.alloc(3)
        assert a.alloc(2) is None  # only 1 free: nothing allocated
        assert a.free_count == 1
        a.check()
        a.release(first)
        assert a.alloc(4) is not None

    def test_refcounted_share_survives_first_release(self):
        a = BlockAllocator(4, 8)
        (bid,) = a.alloc(1)
        a.retain([bid])  # second holder (e.g. the prefix cache)
        assert a.refcount(bid) == 2
        assert a.release([bid]) == 0  # still held: NOT freed
        assert a.refcount(bid) == 1 and a.in_use == 1
        assert a.release([bid]) == 1  # last holder frees it
        assert a.in_use == 0
        a.check()

    def test_double_free_and_bad_retain_raise(self):
        a = BlockAllocator(4, 8)
        (bid,) = a.alloc(1)
        a.release([bid])
        with pytest.raises(BlockError):
            a.release([bid])
        with pytest.raises(BlockError):
            a.retain([bid])
        a.check()

    def test_random_sequences_conserve_the_free_list(self):
        """The property test: random alloc/retain/release interleavings
        never break conservation, never alias, never double-free."""

        r = np.random.RandomState(0)
        a = BlockAllocator(33, 16)  # 32 usable
        live = []  # (ids, extra_refs)
        for _ in range(500):
            op = r.randint(3)
            if op == 0:
                n = int(r.randint(1, 6))
                ids = a.alloc(n)
                if ids is not None:
                    assert len(set(ids)) == len(ids)
                    for held_ids, _ in live:
                        assert not (set(ids) & set(held_ids)), "aliased!"
                    live.append([ids, 0])
            elif op == 1 and live:
                ent = live[r.randint(len(live))]
                a.retain(ent[0])
                ent[1] += 1
            elif op == 2 and live:
                i = r.randint(len(live))
                ids, extra = live[i]
                a.release(ids)
                if extra:
                    live[i][1] -= 1
                else:
                    live.pop(i)
            a.check()
        total_live = set()
        for ids, _ in live:
            total_live |= set(ids)
        assert a.in_use == len(total_live)
        assert a.free_count == a.usable - len(total_live)

    def test_blocks_for(self):
        assert blocks_for(1, 16) == 1
        assert blocks_for(16, 16) == 1
        assert blocks_for(17, 16) == 2
        assert blocks_for(64, 16) == 4

    def test_validation(self):
        with pytest.raises(ValueError):
            BlockAllocator(1, 16)
        with pytest.raises(ValueError):
            BlockAllocator(4, 0)


class TestSwapArena:
    def test_put_pop_accounting_and_caps(self):
        s = SwapArena(capacity_blocks=4)
        assert s.admit(4) and not s.admit(5)
        s.put(1, {"live": [], "blocks": [0, 1, 2]}, n_blocks=3, nbytes=300)
        assert s.swapped_blocks == 3 and len(s) == 1
        assert s.admit(1) and not s.admit(2)
        with pytest.raises(BlockError):
            s.put(1, {}, n_blocks=0, nbytes=0)  # double record
        rec = s.pop(1, nbytes=300)
        assert rec["blocks"] == [0, 1, 2]
        assert s.swapped_blocks == 0 and len(s) == 0
        assert s.bytes_out_total == 300 and s.bytes_in_total == 300
        with pytest.raises(BlockError):
            s.pop(1)
        # unbounded arena admits anything
        assert SwapArena().admit(10**9)

    def test_random_admit_preempt_resume_retire_conserves(self):
        """ISSUE 12 conservation property: across random
        admit/grow/publish/preempt/resume/retire sequences using the
        pool's exact reference discipline, the device side conserves
        (free + live == usable), the host side accounts for every
        preempted request's committed set
        (swapped + swap-exempt live == committed), and the union of
        seat/cache/swap-record holders explains every live block —
        ``free + live + swapped`` covers each logical block exactly
        once."""

        r = np.random.RandomState(7)
        alloc = BlockAllocator(25, 16)  # 24 usable
        swap = SwapArena()
        seats = {}    # rid -> [bid, ...] (logical order)
        records = {}  # rid -> swap record (the pool's shape)
        cache = []    # bids the prefix cache holds (one ref each)
        rid_next = 0

        def check_world():
            alloc.check()
            held = set(b for refs in seats.values() for b in refs)
            held |= set(cache)
            for rec in records.values():
                held |= {b for _, b in rec["live"]}
            assert alloc.in_use == len(held)
            assert alloc.free_count == alloc.usable - len(held)
            for rid, rec in records.items():
                assert rec["n_blocks"] + len(rec["live"]) == rec["committed"]
            assert swap.swapped_blocks == sum(
                rec["n_blocks"] for rec in records.values()
            )

        for _ in range(600):
            op = r.randint(5)
            if op == 0:  # admit: commit a few blocks, maybe publish one
                ids = alloc.alloc(int(r.randint(1, 5)))
                if ids is not None:
                    seats[rid_next] = list(ids)
                    if r.rand() < 0.4:
                        alloc.retain([ids[0]])  # publish to the cache
                        cache.append(ids[0])
                    rid_next += 1
            elif op == 1 and seats:  # lazy grow
                rid = list(seats)[r.randint(len(seats))]
                ids = alloc.alloc(1)
                if ids is not None:
                    seats[rid].extend(ids)
            elif op == 2 and seats:  # preempt: private swap, exempt live
                rid = list(seats)[r.randint(len(seats))]
                refs = seats.pop(rid)
                exempt = [(i, b) for i, b in enumerate(refs)
                          if alloc.refcount(b) > 1]
                private = [(i, b) for i, b in enumerate(refs)
                           if alloc.refcount(b) == 1]
                alloc.release([b for _, b in private])
                swap.put(rid, {"live": exempt,
                               "blocks": [i for i, _ in private],
                               "committed": len(refs)},
                         n_blocks=len(private), nbytes=len(private) * 10)
            elif op == 3 and records:  # resume: re-alloc + pop
                rid = list(records)[r.randint(len(records))]
                rec = records[rid]
                ids = alloc.alloc(rec["n_blocks"])
                if ids is not None:
                    refs = [None] * rec["committed"]
                    for i, b in rec["live"]:
                        refs[i] = b
                    for j, i in enumerate(rec["blocks"]):
                        refs[i] = ids[j]
                    swap.pop(rid, nbytes=rec["n_blocks"] * 10)
                    del records[rid]
                    seats[rid] = refs
            elif op == 4 and seats:  # retire
                rid = list(seats)[r.randint(len(seats))]
                alloc.release(seats.pop(rid))
            # swap.put side: records dict mirrors the arena store
            for rid in list(swap._records):
                if rid not in records:
                    records[rid] = swap._records[rid]
            check_world()
        # drain: resume everything (waiting for space), then retire all
        guard = 0
        while records and guard < 1000:
            guard += 1
            for rid in list(records):
                rec = records[rid]
                ids = alloc.alloc(rec["n_blocks"])
                if ids is None:
                    # pressure: retire a seat, else evict a cold
                    # cache entry (the pool's evict_lru analogue)
                    if seats:
                        alloc.release(seats.pop(list(seats)[0]))
                    elif cache:
                        alloc.release([cache.pop()])
                    continue
                refs = [None] * rec["committed"]
                for i, b in rec["live"]:
                    refs[i] = b
                for j, i in enumerate(rec["blocks"]):
                    refs[i] = ids[j]
                swap.pop(rid)
                del records[rid]
                seats[rid] = refs
            check_world()
        assert not records, "swap arena failed to drain"
        for rid in list(seats):
            alloc.release(seats.pop(rid))
        alloc.release(cache)
        alloc.check()
        assert alloc.in_use == 0 and swap.swapped_blocks == 0

    def test_draft_blocks_ride_the_same_conservation(self):
        """ISSUE 18 conservation property: speculating seats hold a
        SECOND committed set (the draft model's pages) out of the SAME
        allocator — draft blocks are just blocks.  Across random
        admit/grow/preempt/resume/retire sequences using the pool's
        exact discipline (draft blocks always private, preemption swaps
        target+draft all-or-nothing, resume re-allocs both), free +
        live + swapped still covers every logical block exactly once
        and the swap arena's count equals the sum over records of
        target AND draft swapped blocks."""

        r = np.random.RandomState(18)
        alloc = BlockAllocator(33, 16)  # 32 usable
        swap = SwapArena()
        seats = {}    # rid -> ([target bids], [draft bids])
        records = {}  # rid -> swap record with draft_* keys
        rid_next = 0

        def check_world():
            alloc.check()
            held = set()
            for refs, drefs in seats.values():
                held |= set(refs) | set(drefs)
            for rec in records.values():
                held |= {b for _, b in rec["live"]}
            assert alloc.in_use == len(held)
            assert alloc.free_count == alloc.usable - len(held)
            assert swap.swapped_blocks == sum(
                rec["n_blocks"] for rec in records.values()
            )
            for rec in records.values():
                # the record's own split accounting stays coherent
                assert rec["n_blocks"] == rec["target_n"] + rec["draft_n"]

        for _ in range(500):
            op = r.randint(4)
            if op == 0:  # admit a speculating seat: target + draft
                n = int(r.randint(1, 4))
                ids = alloc.alloc(n)
                if ids is not None:
                    dids = alloc.alloc(n)  # draft commit mirrors target
                    if dids is None:
                        alloc.release(ids)  # all-or-nothing rollback
                    else:
                        seats[rid_next] = (list(ids), list(dids))
                        rid_next += 1
            elif op == 1 and seats:  # grow both sets together
                rid = list(seats)[r.randint(len(seats))]
                ids = alloc.alloc(2)
                if ids is not None:
                    seats[rid][0].append(ids[0])
                    seats[rid][1].append(ids[1])
            elif op == 2 and seats:  # preempt: swap target AND draft
                rid = list(seats)[r.randint(len(seats))]
                refs, drefs = seats.pop(rid)
                alloc.release(refs)
                alloc.release(drefs)
                swap.put(
                    rid,
                    {"live": [], "target_n": len(refs),
                     "draft_n": len(drefs)},
                    n_blocks=len(refs) + len(drefs),
                    nbytes=(len(refs) + len(drefs)) * 10,
                )
            elif op == 3 and seats:  # retire frees both sets
                rid = list(seats)[r.randint(len(seats))]
                refs, drefs = seats.pop(rid)
                alloc.release(refs)
                alloc.release(drefs)
            for rid in list(swap._records):
                if rid not in records:
                    records[rid] = swap._records[rid]
            # resume at most one record per tick
            if records:
                rid = list(records)[r.randint(len(records))]
                rec = records[rid]
                ids = alloc.alloc(rec["n_blocks"])
                if ids is not None:
                    swap.pop(rid, nbytes=rec["n_blocks"] * 10)
                    del records[rid]
                    seats[rid] = (
                        list(ids[: rec["target_n"]]),
                        list(ids[rec["target_n"]:]),
                    )
            check_world()
        # drain and verify a clean world
        guard = 0
        while records and guard < 1000:
            guard += 1
            for rid in list(records):
                rec = records[rid]
                ids = alloc.alloc(rec["n_blocks"])
                if ids is None:
                    if seats:
                        refs, drefs = seats.pop(list(seats)[0])
                        alloc.release(refs)
                        alloc.release(drefs)
                    continue
                swap.pop(rid)
                del records[rid]
                seats[rid] = (
                    list(ids[: rec["target_n"]]),
                    list(ids[rec["target_n"]:]),
                )
            check_world()
        assert not records, "swap arena failed to drain"
        for rid in list(seats):
            refs, drefs = seats.pop(rid)
            alloc.release(refs)
            alloc.release(drefs)
        alloc.check()
        assert alloc.in_use == 0 and swap.swapped_blocks == 0


class TestChainKeys:
    def test_chain_addresses_the_whole_prefix(self):
        toks = np.arange(48, dtype=np.int32)
        keys = chain_keys(toks, 16)
        assert len(keys) == 3
        # same prefix -> same chain; divergence at block i changes
        # keys i.. and leaves 0..i-1 intact
        other = toks.copy()
        other[20] += 1
        keys2 = chain_keys(other, 16)
        assert keys2[0] == keys[0]
        assert keys2[1] != keys[1] and keys2[2] != keys[2]
        # partial trailing block gets no key
        assert len(chain_keys(toks[:40], 16)) == 2

    def test_same_block_content_different_prefix_differs(self):
        # content-addressing is CHAINED: block 1 of [A,B] and block 1
        # of [C,B] must not collide even though B's tokens match
        a = np.arange(32, dtype=np.int32)
        b = np.concatenate([np.arange(16, 32, dtype=np.int32),
                            np.arange(16, 32, dtype=np.int32)])
        assert chain_keys(a, 16)[1] != chain_keys(b, 16)[1]

    def test_exact_key_includes_shape_and_dtype(self):
        flat = np.arange(4, dtype=np.int32)
        assert exact_key(flat.reshape(1, 4)) != exact_key(flat.reshape(2, 2))
        assert exact_key(flat) != exact_key(flat.astype(np.int64))


class TestPrefixCache:
    def test_lru_capacity_and_metrics(self):
        from tf_operator_tpu.utils.metrics import Metrics

        m = Metrics()
        c = PrefixCache(capacity=2, metrics=m, mode="pool")
        c.put(b"a", 1)
        c.put(b"b", 2)
        assert c.get(b"a") == 1  # refreshes a
        c.put(b"c", 3)  # evicts b (LRU)
        assert c.get(b"b") is None
        assert c.get(b"c") == 3
        assert (c.hits, c.misses, c.evictions) == (2, 1, 1)
        assert m.counter("serve_prefix_cache_hits_total", mode="pool") == 2
        assert m.counter("serve_prefix_cache_misses_total", mode="pool") == 1
        assert m.counter("serve_prefix_cache_evictions_total", mode="pool") == 1

    def test_referenced_entries_never_evict(self):
        """The aliasing guard: an entry whose block something still
        maps (can_evict False) survives any pressure; eviction takes
        the next LRU candidate instead."""

        alloc = BlockAllocator(5, 8)
        ids = alloc.alloc(3)
        mapped = {ids[0]}  # a seat maps block ids[0]
        for bid in ids:
            alloc.retain([bid])  # the cache's own reference
        freed = []
        c = PrefixCache(
            can_evict=lambda bid: bid not in mapped,
            on_evict=lambda bid: freed.append(alloc.release([bid])),
        )
        for i, bid in enumerate(ids):
            c.put(bytes([i]), bid)
        assert c.evict_lru(need=3) == 2  # the mapped one is skipped
        assert bytes([0]) in c and len(c) == 1
        alloc.check()
        assert alloc.refcount(ids[0]) == 2  # untouched
        # unmap -> now evictable
        mapped.clear()
        assert c.evict_lru(need=1) == 1
        assert len(c) == 0

    def test_peek_does_not_count(self):
        c = PrefixCache()
        c.put(b"k", 7)
        assert c.peek(b"k") == 7 and c.peek(b"x") is None
        assert (c.hits, c.misses) == (0, 0)
        c.record(True)
        c.record(False)
        assert (c.hits, c.misses) == (1, 1)


class TestPrefixFabric:
    """The cross-replica prefix-cache FABRIC (ISSUE 13): the migration
    transport of disaggregated serving.  Host-only — records are plain
    np trees here; the device gather/upload halves are covered by
    tests/test_disaggregated.py."""

    def _rec(self, seed: int = 0):
        return {"k": np.full((1, 2, 16, 4), seed, np.float32)}

    def test_put_get_contains_and_accounting(self):
        from tf_operator_tpu.models.prefix_cache import PrefixFabric

        f = PrefixFabric()
        key = chain_keys(np.arange(16, dtype=np.int32), 16)[0]
        assert key not in f and f.get(key) is None
        f.put(key, self._rec(), nbytes=512)
        assert key in f and len(f) == 1
        assert f.get(key)["nbytes"] == 512
        # idempotent re-publish: no double count
        f.put(key, self._rec(), nbytes=512)
        snap = f.snapshot()
        assert snap["publishes"] == 1 and snap["bytes_published"] == 512
        f.record(True)
        f.record(False)
        assert f.snapshot()["hits"] == 1
        assert f.snapshot()["misses"] == 1

    def test_identical_prefixes_produce_identical_chain_keys_across_replicas(self):
        """The content-addressing property the transport rests on:
        chain keys are a pure function of token content, so two
        DISTINCT replicas (two independent key computations over
        copies of the prompt) address the same fabric entries — and a
        divergent prompt never collides.  300 random prompt pairs."""

        r = np.random.RandomState(7)
        seen = {}  # key -> the prefix token tuple it addresses
        for _ in range(300):
            n = int(r.randint(16, 80))
            a = r.randint(0, 997, size=(n,)).astype(np.int32)
            b = a.copy()  # "the other replica's" copy
            assert chain_keys(a, 16) == chain_keys(b, 16)
            # divergence at a random position kills every key from
            # that block on — and never resurrects an earlier chain
            d = b.copy()
            pos = int(r.randint(0, n))
            d[pos] = (d[pos] + 1) % 997
            ka, kd = chain_keys(a, 16), chain_keys(d, 16)
            for i, (x, y) in enumerate(zip(ka, kd)):
                if i < pos // 16:
                    assert x == y
                else:
                    assert x != y
            # global no-collision: one key = one exact prefix content
            for i, key in enumerate(ka):
                prefix = tuple(a[: (i + 1) * 16].tolist())
                assert seen.setdefault(key, prefix) == prefix

    def test_pinned_entry_never_evicted(self):
        """The never-reclaim-while-referenced rule, fabric edition: an
        entry a migration holds a pin on survives ANY publish
        pressure; unpinning releases it to LRU."""

        from tf_operator_tpu.models.prefix_cache import PrefixFabric

        f = PrefixFabric(capacity_blocks=2)
        keys = chain_keys(np.arange(160, dtype=np.int32), 16)
        f.put(keys[0], self._rec(0), nbytes=8)
        assert f.get(keys[0], pin=True) is not None
        for i in range(1, 9):
            f.put(keys[i], self._rec(i), nbytes=8)
        assert keys[0] in f  # pinned: survived 8 evict-pressure puts
        assert len(f) <= 3  # cap + the one pinned straggler
        f.unpin(keys[0])
        f.put(keys[9], self._rec(9), nbytes=8)
        assert keys[0] not in f  # unpinned -> LRU reclaimed
        assert len(f) <= 2

    def test_pin_is_counted_per_migration(self):
        from tf_operator_tpu.models.prefix_cache import PrefixFabric

        f = PrefixFabric(capacity_blocks=1)
        key = chain_keys(np.arange(16, dtype=np.int32), 16)[0]
        f.put(key, self._rec(), nbytes=8)
        f.get(key, pin=True)
        f.get(key, pin=True)  # two concurrent migrations
        f.unpin(key)
        other = chain_keys(np.arange(16, 32, dtype=np.int32), 16)[0]
        f.put(other, self._rec(1), nbytes=8)
        assert key in f  # still one pin outstanding
        f.unpin(key)
        f.put(chain_keys(np.arange(32, 48, dtype=np.int32), 16)[0],
              self._rec(2), nbytes=8)
        assert key not in f
