"""Elastic restart with RESHARDING: a checkpoint written on one mesh
restores into a different mesh — different partitioning, or a smaller
world — and training continues.

The reference's recovery story is restart-based with a FIXED world
(SURVEY.md §5: "No elastic re-sharding of a running job"); its elastic
workers only resize stateless replicas.  Here the restart contract
composes with sharded checkpoints: `TrainerCheckpointer.restore_latest`
builds its restore target from the NEW trainer's sharding tree, so
orbax redistributes every array (params, optimizer moments, rng, step)
onto whatever mesh the restarted job came up with — scale-out,
scale-in, or a re-partitioned identical world.  That is the TPU-native
upgrade over the reference: a job that loses a slice can resume on a
smaller mesh from the same artifact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# default-tier exclusion (train-step compiles on three meshes); see
# README 'Tests run in two tiers'
pytestmark = pytest.mark.slow

from tf_operator_tpu.models import gpt_tiny, lm_loss
from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
from tf_operator_tpu.parallel.checkpoint import TrainerCheckpointer

VOCAB = 128


def _trainer(mesh, ids):
    return Trainer(
        gpt_tiny(vocab_size=VOCAB, max_len=ids.shape[1], mesh=mesh),
        TrainerConfig(learning_rate=1e-2),
        mesh,
        lm_loss,
        {"input_ids": ids},
        init_args=(ids,),
        shardings="logical",
    )


class TestElasticReshard:
    def _ids(self):
        return jnp.asarray(
            np.random.RandomState(0).randint(0, VOCAB, size=(8, 32)), jnp.int32
        )

    def test_restore_into_repartitioned_and_smaller_meshes(self, tmp_path):
        ids = self._ids()
        batch = {"input_ids": ids}

        # train on dp2 x fsdp4 (8 devices), checkpoint
        mesh_a = make_mesh({"dp": 2, "fsdp": 4})
        tr_a = _trainer(mesh_a, ids)
        for _ in range(3):
            tr_a.train_step(tr_a.shard_batch(batch))
        ckpt = TrainerCheckpointer(str(tmp_path / "ckpt"))
        saved_step = ckpt.save(tr_a, wait=True)
        assert saved_step == 3
        loss_a = float(tr_a.eval_step(tr_a.shard_batch(batch))["loss"])
        ckpt.close()

        # repartitioned identical world: fsdp8
        mesh_b = make_mesh({"fsdp": 8})
        tr_b = _trainer(mesh_b, ids)
        ckpt_b = TrainerCheckpointer(str(tmp_path / "ckpt"))
        assert ckpt_b.restore_latest(tr_b) == 3
        assert int(tr_b.state.step) == 3
        loss_b = float(tr_b.eval_step(tr_b.shard_batch(batch))["loss"])
        np.testing.assert_allclose(loss_b, loss_a, rtol=2e-2)
        ckpt_b.close()

        # scale-IN: the restarted world has HALF the devices
        mesh_c = make_mesh({"dp": 2, "fsdp": 2}, devices=jax.devices()[:4])
        tr_c = _trainer(mesh_c, ids)
        ckpt_c = TrainerCheckpointer(str(tmp_path / "ckpt"))
        assert ckpt_c.restore_latest(tr_c) == 3
        loss_c = float(tr_c.eval_step(tr_c.shard_batch(batch))["loss"])
        np.testing.assert_allclose(loss_c, loss_a, rtol=2e-2)
        # training CONTINUES on the shrunken world
        m = tr_c.train_step(tr_c.shard_batch(batch))
        assert np.isfinite(float(m["loss"]))
        assert int(tr_c.state.step) == 4
        ckpt_c.close()

    def test_legacy_boxed_artifact_restores(self, tmp_path):
        """Checkpoints written before the elastic-reshard change saved
        the state WITH flax partitioning boxes (an extra nesting level
        in the artifact's tree paths).  restore_latest's fallback must
        still resume them — the restart contract holds across the
        upgrade boundary."""

        import orbax.checkpoint as ocp

        ids = self._ids()
        batch = {"input_ids": ids}
        tr = _trainer(make_mesh({"fsdp": 8}), ids)
        for _ in range(2):
            tr.train_step(tr.shard_batch(batch))
        loss_before = float(tr.eval_step(tr.shard_batch(batch))["loss"])
        # simulate the pre-upgrade writer: boxed state saved directly
        mgr = ocp.CheckpointManager(str(tmp_path / "legacy"))
        mgr.save(int(tr.state.step), args=ocp.args.StandardSave({"state": tr.state}))
        mgr.wait_until_finished()
        mgr.close()

        tr2 = _trainer(make_mesh({"dp": 2, "fsdp": 4}), ids)
        ck = TrainerCheckpointer(str(tmp_path / "legacy"))
        assert ck.restore_latest(tr2) == 2
        loss_after = float(tr2.eval_step(tr2.shard_batch(batch))["loss"])
        np.testing.assert_allclose(loss_after, loss_before, rtol=2e-2)
        ck.close()

    def test_optimizer_state_reshards_not_resets(self, tmp_path):
        """The restored optimizer moments are the trained ones, not
        zeros: a post-restore step on the new mesh matches a step on
        the old mesh (same moments -> same update), and produces
        DIFFERENT params than a step taken with reinitialised moments
        — the assertion that catches a graft bug zeroing opt_state."""

        ids = self._ids()
        batch = {"input_ids": ids}
        mesh_a = make_mesh({"fsdp": 8})
        tr_a = _trainer(mesh_a, ids)
        for _ in range(3):
            tr_a.train_step(tr_a.shard_batch(batch))
        ckpt = TrainerCheckpointer(str(tmp_path / "c2"))
        ckpt.save(tr_a, wait=True)
        # continue one step on the ORIGINAL mesh — the reference result
        tr_a.train_step(tr_a.shard_batch(batch))
        loss_ref = float(tr_a.eval_step(tr_a.shard_batch(batch))["loss"])
        ckpt.close()

        mesh_b = make_mesh({"dp": 4, "fsdp": 2})
        tr_b = _trainer(mesh_b, ids)
        ckpt_b = TrainerCheckpointer(str(tmp_path / "c2"))
        ckpt_b.restore_latest(tr_b)
        # cold control: same restored params but RE-INITIALISED moments
        tr_cold = _trainer(mesh_b, ids)
        ckpt_cold = TrainerCheckpointer(str(tmp_path / "c2"))
        ckpt_cold.restore_latest(tr_cold)
        from flax.core import meta

        # boxed params: the moment trees must keep the partitioning-box
        # structure the jitted step was traced with
        tr_cold.state = tr_cold.state.replace(
            opt_state=tr_cold.tx.init(tr_cold.state.params)
        )

        tr_b.train_step(tr_b.shard_batch(batch))
        tr_cold.train_step(tr_cold.shard_batch(batch))
        loss_warm = float(tr_b.eval_step(tr_b.shard_batch(batch))["loss"])
        np.testing.assert_allclose(loss_warm, loss_ref, rtol=2e-2)
        # warm and cold steps move params measurably differently
        diffs = jax.tree_util.tree_map(
            lambda a, b: float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))),
            meta.unbox(tr_b.state.params),
            meta.unbox(tr_cold.state.params),
        )
        max_diff = max(jax.tree_util.tree_leaves(diffs))
        assert max_diff > 1e-4, (
            f"warm-restored and cold-optimizer steps produced near-identical "
            f"params (max diff {max_diff}); moments were probably reset"
        )
        ckpt_b.close()
        ckpt_cold.close()
