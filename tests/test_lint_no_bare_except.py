"""Lint gate: no silent broad-exception swallows in the API layers.

ISSUE 1 removed the `except Exception: pass` swallows from
tf_operator_tpu/backend/ and tf_operator_tpu/cmd/ — every broad
handler there now retries, counts, or logs.  This AST walk keeps it
that way: a NEW bare swallow (``except Exception:``/``except:`` whose
body is only ``pass``/``...``) in those packages fails tier-1.
ISSUE 2 extended the gate over controller/, server/ and utils/ — the
whole control-plane vertical the tracing subsystem instruments (a
silent swallow there would also silently eat span/status recording).

Narrow handlers (``except OSError: pass``) stay allowed — ignoring a
specific expected error is a decision; ignoring *everything* silently
is how watch events and job state got lost before this gate existed.
"""

import ast
import pathlib

import tf_operator_tpu

PKG_ROOT = pathlib.Path(tf_operator_tpu.__file__).parent
CHECKED_PACKAGES = ("backend", "cmd", "controller", "server", "utils")

#: exception names considered "broad" — swallowing these silently
#: hides every bug class at once
BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare `except:`
        return True
    if isinstance(t, ast.Name):
        return t.id in BROAD
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in BROAD for e in t.elts
        )
    return False


def _is_silent(handler: ast.ExceptHandler) -> bool:
    return all(
        isinstance(s, ast.Pass)
        or (
            isinstance(s, ast.Expr)
            and isinstance(s.value, ast.Constant)
            and s.value.value is Ellipsis
        )
        for s in handler.body
    )


def find_silent_broad_excepts(root: pathlib.Path):
    offenders = []
    for pkg in CHECKED_PACKAGES:
        if not (root / pkg).is_dir():
            continue  # planted-offender fixtures build partial trees
        for path in sorted((root / pkg).rglob("*.py")):
            tree = ast.parse(path.read_text(), filename=str(path))
            for node in ast.walk(tree):
                if (
                    isinstance(node, ast.ExceptHandler)
                    and _is_broad(node)
                    and _is_silent(node)
                ):
                    offenders.append(f"{path}:{node.lineno}")
    return offenders


def test_no_silent_broad_excepts_in_api_layers():
    offenders = find_silent_broad_excepts(PKG_ROOT)
    assert not offenders, (
        "silent broad-exception swallows found (retry/log/count instead; "
        "see backend/retry.py):\n  " + "\n  ".join(offenders)
    )


def test_walker_catches_a_planted_swallow(tmp_path):
    """The gate itself works: a planted offender is found, and the
    allowed shapes (narrow except, broad-but-logged) are not."""

    pkg = tmp_path / "backend"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        "try:\n    x = 1\nexcept Exception:\n    pass\n"
    )
    (pkg / "alsobad.py").write_text(
        "try:\n    x = 1\nexcept (ValueError, Exception):\n    ...\n"
    )
    (pkg / "ok.py").write_text(
        "try:\n    x = 1\nexcept OSError:\n    pass\n"
        "try:\n    y = 2\nexcept Exception as e:\n    print(e)\n"
    )
    (tmp_path / "cmd").mkdir()
    offenders = find_silent_broad_excepts(tmp_path)
    assert [o.rsplit("/", 1)[-1] for o in offenders] == [
        "alsobad.py:3", "bad.py:3",
    ]
