"""Tier-3 e2e against the local-process backend (SURVEY.md §4, §7 step 7):
real subprocesses, real jax.distributed over localhost, CPU collectives.

This is the "minimum end-to-end slice": spec → reconcile → subprocess
launch → collective bootstrap → exit 0 → Succeeded → cleanup.
"""

import json
import os
import sys
import time

import pytest

# default-tier exclusion (subprocess jax.distributed worlds); see README 'Tests run in two tiers'
pytestmark = pytest.mark.slow

from tests.testutil import new_job
from tf_operator_tpu.api.types import JobConditionType, ReplicaType, SuccessPolicy
from tf_operator_tpu.backend.jobstore import JobStore
from tf_operator_tpu.backend.local import LocalProcessBackend
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.controller.reconciler import ReconcilerConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "examples", "dist_psum.py")


@pytest.fixture
def local_harness():
    store = JobStore()
    backend = LocalProcessBackend()
    controller = TPUJobController(
        store, backend, config=ReconcilerConfig(resolver=backend.resolver)
    )
    controller.run(threadiness=2)
    yield store, backend, controller
    controller.stop()
    backend.close()


def wait_for(store, ns, name, predicate, timeout=90.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        job = store.get(ns, name)
        if job is not None and predicate(job):
            return job
        time.sleep(0.1)
    job = store.get(ns, name)
    raise TimeoutError(f"condition not reached; status={job.status if job else None}")


def cpu_env():
    return {"JAX_PLATFORMS": "cpu"}


@pytest.mark.slow
class TestLocalE2E:
    def test_single_worker_succeeds(self, local_harness):
        store, backend, c = local_harness
        job = new_job(name="solo", worker=1, command=[sys.executable, EXAMPLE])
        job.spec.replica_specs[ReplicaType.WORKER].template.containers[0].env = cpu_env()
        store.create(job)
        done = wait_for(
            store, "default", "solo",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED),
        )
        assert done.status.replica_statuses[ReplicaType.WORKER].succeeded == 1
        log = backend.pod_log("default", "solo-worker-0")
        assert "allgather ok" in log

    def test_two_workers_real_collectives(self, local_harness):
        """Two real processes form a jax.distributed world and allgather."""

        store, backend, c = local_harness
        job = new_job(name="pair", worker=2, command=[sys.executable, EXAMPLE])
        job.spec.replica_specs[ReplicaType.WORKER].template.containers[0].env = cpu_env()
        job.spec.success_policy = SuccessPolicy.ALL_WORKERS
        store.create(job)
        done = wait_for(
            store, "default", "pair",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED),
        )
        assert done.status.replica_statuses[ReplicaType.WORKER].succeeded == 2
        log0 = backend.pod_log("default", "pair-worker-0")
        log1 = backend.pod_log("default", "pair-worker-1")
        assert "process 0/2: allgather ok -> [0.0, 1.0]" in log0
        assert "process 1/2: allgather ok -> [0.0, 1.0]" in log1

    def test_failing_worker_fails_job(self, local_harness):
        store, backend, c = local_harness
        job = new_job(
            name="boom", worker=1, command=[sys.executable, "-c", "raise SystemExit(3)"]
        )
        store.create(job)
        done = wait_for(
            store, "default", "boom",
            lambda j: j.status.has_condition(JobConditionType.FAILED), timeout=30.0,
        )
        assert done.status.condition(JobConditionType.FAILED).reason == "ReplicaFailed"

    def test_restart_then_succeed(self, local_harness, tmp_path):
        """First attempt exits 137 (retryable); the restarted replica sees
        the marker file and exits 0 — checkpoint-resume contract shape."""

        from tf_operator_tpu.api.types import RestartPolicy

        marker = tmp_path / "attempted"
        script = (
            "import os, sys\n"
            f"m = {str(marker)!r}\n"
            "if not os.path.exists(m):\n"
            "    open(m, 'w').close()\n"
            "    sys.exit(137)\n"
            "sys.exit(0)\n"
        )
        job = new_job(
            name="retry",
            worker=1,
            command=[sys.executable, "-c", script],
            restart_policy=RestartPolicy.EXIT_CODE,
        )
        store, backend, c = local_harness
        store.create(job)
        done = wait_for(
            store, "default", "retry",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED), timeout=30.0,
        )
        assert done.status.restart_count == 1

    def test_delete_running_job_kills_processes(self, local_harness):
        store, backend, c = local_harness
        job = new_job(
            name="sleeper", worker=1,
            command=[sys.executable, "-c", "import time; time.sleep(600)"],
        )
        store.create(job)
        wait_for(
            store, "default", "sleeper",
            lambda j: j.status.has_condition(JobConditionType.RUNNING), timeout=30.0,
        )
        pid = backend._procs["default/sleeper-worker-0"].pid
        store.delete("default", "sleeper")
        # generous: under full-suite load the SIGTERM->wait->SIGKILL
        # escalation plus reconcile can take a while
        deadline = time.time() + 45
        while time.time() < deadline and backend.list_pods("default"):
            time.sleep(0.1)
        assert backend.list_pods("default") == []
        # the subprocess is really gone.  The pod leaves list_pods before
        # the worker thread finishes the SIGTERM->wait reap, so the pid
        # can linger as a zombie briefly (os.kill(pid, 0) succeeds on a
        # zombie) — poll until the reap lands.
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                break
            time.sleep(0.05)
        with pytest.raises(ProcessLookupError):
            os.kill(pid, 0)

    def test_multihost_slice_forms_one_world(self, local_harness):
        """The multi-host expansion contract end-to-end (VERDICT round 1
        item 6 done-criterion): ONE TPU_SLICE replica spanning 2 host
        VMs expands into 2 pods whose processes form a single
        jax.distributed world and allgather across it."""

        store, backend, c = local_harness
        job = new_job(
            name="slice2h", tpu_slice=1, tpu_topology="v5e-8",
            command=[sys.executable, EXAMPLE],
        )
        spec = job.spec.replica_specs[ReplicaType.TPU_SLICE]
        assert spec.slice_host_count() == 2  # v5e-8 = 2 host VMs
        spec.template.containers[0].env = cpu_env()
        store.create(job)
        done = wait_for(
            store, "default", "slice2h",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED),
        )
        # one replica, two pods (one per host), both succeeded
        assert done.status.replica_statuses[ReplicaType.TPU_SLICE].succeeded == 2
        log0 = backend.pod_log("default", "slice2h-tpuslice-0")
        log1 = backend.pod_log("default", "slice2h-tpuslice-1")
        assert "process 0/2: allgather ok -> [0.0, 1.0]" in log0
        assert "process 1/2: allgather ok -> [0.0, 1.0]" in log1
        # (the per-host env rewrite itself is pinned by
        # test_bootstrap.TestTPUEnv.test_multihost_slice_expansion_golden)

    def test_two_slices_multihost_megascale_world(self, local_harness):
        """Two-slice e2e (VERDICT r2 item 7): TPU_SLICE replicas=2 on a
        v5e-8 topology (2 hosts each) -> 4 pods, ONE jax.distributed
        world, with the MEGASCALE/TPU_WORKER env asserted INSIDE each
        worker process (examples/dist_multislice.py), not just in
        golden files."""

        multislice = os.path.join(REPO, "examples", "dist_multislice.py")
        store, backend, c = local_harness
        job = new_job(
            name="twoslice", tpu_slice=2, tpu_topology="v5e-8",
            command=[sys.executable, multislice],
        )
        spec = job.spec.replica_specs[ReplicaType.TPU_SLICE]
        assert spec.slice_host_count() == 2
        spec.template.containers[0].env = cpu_env()
        store.create(job)
        done = wait_for(
            store, "default", "twoslice",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED),
            # four cold JAX worker processes now COMPILE the slice-aware
            # train step (shard_map + gloo collectives), not just an
            # allgather — give the gang compile headroom on a loaded box
            timeout=360.0,
        )
        # 2 slices x 2 hosts = 4 pods, all succeeded
        assert done.status.replica_statuses[ReplicaType.TPU_SLICE].succeeded == 4
        for idx in range(4):
            log = backend.pod_log("default", f"twoslice-tpuslice-{idx}")
            s, h = idx // 2, idx % 2
            assert f"process {idx}/4: slice {s}/2 worker {h} megascale ok" in log, log
        # ISSUE 14: the promoted workload trained on the slice-aware
        # mesh and the MULTICHIP tail carries the hierarchical grad-sync
        # ledger — dp rides DCN, fsdp stays ICI, and only
        # 1/intra_slice_size of the gradient bytes cross the slice
        # boundary
        log0 = backend.pod_log("default", "twoslice-tpuslice-0")
        ledger_lines = [
            line for line in log0.splitlines()
            if line.startswith("MULTISLICE_LEDGER ")
        ]
        assert ledger_lines, log0
        ledger = json.loads(ledger_lines[-1].split(" ", 1)[1])
        assert ledger["grad_sync"] == "hierarchical"
        assert ledger["axis_fabric"] == {"dp": "dcn", "fsdp": "ici"}
        assert ledger["mesh"]["dp"] == 2  # dp extent == slice count
        # intra-slice width is 2 hosts x the per-pod device count (the
        # pods inherit this test env's virtual-device flag), so pin the
        # RATIO contract, not a fixed width
        intra = ledger["intra_slice_size"]
        assert intra >= 2
        assert ledger["dcn_bytes_ratio"] <= 1 / intra + 1e-3

    def test_dist_mnist_real_data_two_workers(self, local_harness, tmp_path):
        """dist-mnist through the REAL data path (VERDICT r2 item 3):
        two processes, each reading a disjoint grain shard of the
        on-disk dataset (coordinator generates it), loss decreases."""

        mnist = os.path.join(REPO, "examples", "dist_mnist.py")
        data_dir = str(tmp_path / "mnist-data")
        store, backend, c = local_harness
        job = new_job(
            name="mnist-data", worker=2,
            command=[
                sys.executable, mnist, "--steps", "25",
                "--batch-size", "64", "--data-dir", data_dir,
            ],
        )
        job.spec.replica_specs[ReplicaType.WORKER].template.containers[0].env = cpu_env()
        job.spec.success_policy = SuccessPolicy.ALL_WORKERS
        store.create(job)
        done = wait_for(
            store, "default", "mnist-data",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED),
            # ~60s serially; parallel workers sharing the box have
            # pushed a 120s deadline over the line (two jax processes +
            # dataset generation + 25 distributed steps)
            timeout=300.0,
        )
        assert done.status.replica_statuses[ReplicaType.WORKER].succeeded == 2
        # dataset generated once by the coordinator, read by both
        assert os.path.exists(os.path.join(data_dir, "meta.json"))
        log0 = backend.pod_log("default", "mnist-data-worker-0")
        assert "loss" in log0

    def test_pipeline_stages_across_two_processes(self, local_harness):
        """Pipeline parallelism over the PROCESS boundary: 2 workers,
        1 device each, pp=2 — each process hosts one transformer stage
        and activations cross processes via the collective backend
        (gloo on CPU, ICI/DCN on TPU)."""

        gpt_pp = os.path.join(REPO, "examples", "gpt_pipeline.py")
        store, backend, c = local_harness
        job = new_job(
            name="ppx", worker=2,
            command=[
                sys.executable, gpt_pp, "--pp", "2", "--steps", "20",
                "--batch-per-device", "2", "--seq-len", "16",
                "--hidden", "32", "--n-layers", "2", "--microbatches", "2",
            ],
        )
        job.spec.success_policy = SuccessPolicy.ALL_WORKERS
        job.spec.replica_specs[ReplicaType.WORKER].template.containers[0].env = {
            **cpu_env(),
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        }
        store.create(job)
        done = wait_for(
            store, "default", "ppx",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED),
            timeout=150.0,
        )
        assert done.status.replica_statuses[ReplicaType.WORKER].succeeded == 2
        log = backend.pod_log("default", "ppx-worker-0")
        assert "pp=2 dp=1" in log and "loss" in log

    def test_llama_pretrain_two_workers_with_generation(self, local_harness, tmp_path):
        """The modern-decoder example end to end under the operator:
        2 processes train byte-level llama (RoPE+GQA+SwiGLU) on the
        shared on-disk corpus (coordinator generates, worker 1 waits on
        the commit record), then the collective params allgather feeds
        cached generation on process 0."""

        script = os.path.join(REPO, "examples", "llama_pretrain.py")
        data_dir = str(tmp_path / "text-data")
        store, backend, c = local_harness
        job = new_job(
            name="llama-pt", worker=2,
            command=[
                sys.executable, script, "--steps", "25",
                "--batch-per-device", "8", "--seq-len", "64",
                "--data-dir", data_dir, "--generate", "16",
            ],
        )
        job.spec.replica_specs[ReplicaType.WORKER].template.containers[0].env = {
            **cpu_env(),
            # one device per worker (a real single-chip host) — without
            # this the workers inherit the test runner's 8-virtual-device
            # XLA_FLAGS and form a needlessly slow 16-rank gloo world
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        }
        job.spec.success_policy = SuccessPolicy.ALL_WORKERS
        store.create(job)
        done = wait_for(
            store, "default", "llama-pt",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED),
            timeout=180.0,
        )
        assert done.status.replica_statuses[ReplicaType.WORKER].succeeded == 2
        log0 = backend.pod_log("default", "llama-pt-worker-0")
        assert "loss" in log0 and "sample:" in log0

    def test_moe_pretrain_two_workers_with_export_and_generation(
        self, local_harness, tmp_path
    ):
        """The routed-expert family under the operator: 2 processes
        train byte-level MoE over a dp x ep mesh on the shared corpus,
        export a SELF-DESCRIBING artifact (model.json says family=moe),
        and decode droplessly on process 0."""

        import json

        script = os.path.join(REPO, "examples", "llama_pretrain.py")
        data_dir = str(tmp_path / "text-data")
        art_dir = str(tmp_path / "moe-art")
        store, backend, c = local_harness
        job = new_job(
            name="moe-pt", worker=2,
            command=[
                sys.executable, script, "--family", "moe", "--experts", "2",
                "--steps", "10", "--batch-per-device", "4", "--seq-len", "64",
                "--data-dir", data_dir, "--generate", "12",
                "--export-dir", art_dir,
            ],
        )
        job.spec.replica_specs[ReplicaType.WORKER].template.containers[0].env = {
            **cpu_env(),
            # TWO devices per worker: ep caps at the per-process device
            # count (disjoint data shards need dp >= processes), so this
            # is the smallest world where expert parallelism actually
            # crosses the process boundary (ep=2 x dp=2)
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        }
        job.spec.success_policy = SuccessPolicy.ALL_WORKERS
        store.create(job)
        done = wait_for(
            store, "default", "moe-pt",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED),
            timeout=300.0,
        )
        assert done.status.replica_statuses[ReplicaType.WORKER].succeeded == 2
        log0 = backend.pod_log("default", "moe-pt-worker-0")
        # expert parallelism really crossed the process boundary
        assert "moe bytes dp=2 ep=2" in log0 and "sample:" in log0
        with open(os.path.join(art_dir, "model.json")) as f:
            desc = json.load(f)
        assert desc["family"] == "moe" and desc["moe"]["num_experts"] == 2
