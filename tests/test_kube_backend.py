"""KubeBackend <-> MiniApiServer: the real-Kubernetes-protocol tier
(VERDICT r4 next #4).

What must hold: the 5 ClusterBackend verbs + watch work over genuine
HTTP — real paths, real JSON shapes, labelSelector filtering, 409/404
error mapping, resourceVersion bookkeeping, chunked watch streams with
replay, and the client-go 410-Gone → re-list recovery.  The tier-3
e2e scenarios then run the whole operator over this pair
(tests/test_e2e_scenarios.py's parametrized harness); this file pins
the protocol itself.
"""

import json
import sys
import time
import urllib.error
import urllib.request

import pytest

from tf_operator_tpu.api.types import Container, ObjectMeta, PodPhase
from tf_operator_tpu.backend.base import AlreadyExistsError, NotFoundError
from tf_operator_tpu.backend.kube import (
    KubeBackend,
    pod_from_json,
    pod_to_json,
)
from tf_operator_tpu.backend.kubesim import MiniApiServer
from tf_operator_tpu.backend.objects import (
    Pod,
    PodGroup,
    PodGroupPhase,
    Service,
    WatchEventType,
)

SLEEP = [sys.executable, "-c", "import time; time.sleep(600)"]
EXIT0 = [sys.executable, "-c", "raise SystemExit(0)"]


@pytest.fixture
def pair():
    sim = MiniApiServer().start()
    backend = KubeBackend(sim.url)
    yield sim, backend
    backend.close()
    sim.stop()


def make_pod(name, command, labels=None, ns="default"):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns, labels=labels or {}),
        containers=[Container(command=command)],
    )


def wait_until(cond, timeout=15.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise TimeoutError(what)


class TestCodec:
    def test_pod_round_trips_through_k8s_json(self):
        pod = Pod(
            metadata=ObjectMeta(
                name="p",
                namespace="ns1",
                labels={"a": "b"},
                annotations={"x": "y"},
                owner_uid="job-1",
            ),
            containers=[
                Container(
                    command=["python3", "train.py"],
                    args=["--steps", "5"],
                    env={"K": "V"},
                )
            ],
            scheduler_name="volcano",
            node_selector={"pool": "tpu"},
            phase=PodPhase.FAILED,
            exit_code=137,
            chip_request=4,
        )
        back = pod_from_json(pod_to_json(pod))
        assert back.metadata.name == "p"
        assert back.metadata.namespace == "ns1"
        assert back.metadata.owner_uid == "job-1"
        assert back.metadata.labels == {"a": "b"}
        assert back.containers[0].command == ["python3", "train.py"]
        assert back.containers[0].env == {"K": "V"}
        assert back.scheduler_name == "volcano"
        assert back.node_selector == {"pool": "tpu"}
        assert back.phase is PodPhase.FAILED
        assert back.exit_code == 137
        assert back.chip_request == 4

    def test_chip_request_rides_tpu_resource_limits(self):
        pod = Pod(
            metadata=ObjectMeta(name="p"),
            containers=[Container(command=["x"])],
            chip_request=8,
        )
        j = pod_to_json(pod)
        limits = j["spec"]["containers"][0]["resources"]["limits"]
        assert limits["google.com/tpu"] == "8"


class TestCrud:
    def test_create_assigns_uid_and_resource_version(self, pair):
        sim, b = pair
        pod = make_pod("p1", SLEEP)
        b.create_pod(pod)
        assert pod.metadata.uid
        assert pod.metadata.resource_version >= 1

    def test_conflict_and_not_found_map_to_backend_errors(self, pair):
        sim, b = pair
        pod = make_pod("p1", SLEEP)
        b.create_pod(pod)
        with pytest.raises(AlreadyExistsError):
            b.create_pod(make_pod("p1", SLEEP))
        with pytest.raises(NotFoundError):
            b.delete_pod("default", "nope")
        with pytest.raises(NotFoundError):
            b.delete_service("default", "nope")
        assert b.get_pod("default", "nope") is None

    def test_label_selector_filters_server_side(self, pair):
        sim, b = pair
        b.create_pod(make_pod("a0", SLEEP, labels={"job": "a", "i": "0"}))
        b.create_pod(make_pod("a1", SLEEP, labels={"job": "a", "i": "1"}))
        b.create_pod(make_pod("b0", SLEEP, labels={"job": "b"}))
        assert {
            p.metadata.name for p in b.list_pods("default", {"job": "a"})
        } == {"a0", "a1"}
        assert {
            p.metadata.name
            for p in b.list_pods("default", {"job": "a", "i": "1"})
        } == {"a1"}
        assert b.list_pods("default", {"job": "zzz"}) == []

    def test_namespaces_isolate(self, pair):
        sim, b = pair
        b.create_pod(make_pod("p", SLEEP, ns="ns-a"))
        assert b.list_pods("ns-a")[0].metadata.name == "p"
        assert b.list_pods("ns-b") == []

    def test_owner_patch_adopts_and_orphans(self, pair):
        sim, b = pair
        b.create_pod(make_pod("p1", SLEEP))
        b.update_pod_owner("default", "p1", "job-uid-9")
        assert b.get_pod("default", "p1").metadata.owner_uid == "job-uid-9"
        b.update_pod_owner("default", "p1", None)
        assert b.get_pod("default", "p1").metadata.owner_uid == ""

    def test_services_and_podgroups_crud(self, pair):
        sim, b = pair
        svc = Service(
            metadata=ObjectMeta(name="s1", labels={"j": "x"}),
            selector={"j": "x"},
            port=2222,
        )
        b.create_service(svc)
        assert b.list_services("default", {"j": "x"})[0].port == 2222
        g = PodGroup(
            metadata=ObjectMeta(name="g1"), min_member=3, chip_request=8
        )
        b.create_pod_group(g)
        got = b.get_pod_group("default", "g1")
        assert (got.min_member, got.chip_request) == (3, 8)
        b.update_pod_group("default", "g1", 5, 16)
        got = b.get_pod_group("default", "g1")
        assert (got.min_member, got.chip_request) == (5, 16)
        b.delete_service("default", "s1")
        b.delete_pod_group("default", "g1")
        assert b.get_pod_group("default", "g1") is None

    def test_snapshot_relists_all_kinds(self, pair):
        sim, b = pair
        b.create_pod(make_pod("p1", SLEEP))
        b.create_service(
            Service(metadata=ObjectMeta(name="s1"), selector={}, port=1)
        )
        b.create_pod_group(PodGroup(metadata=ObjectMeta(name="g1")))
        pods, svcs, groups = b.snapshot()
        assert [p.metadata.name for p in pods] == ["p1"]
        assert [s.metadata.name for s in svcs] == ["s1"]
        assert [g.metadata.name for g in groups] == ["g1"]


class TestKubeletSim:
    def test_pod_runs_exits_and_surfaces_exit_code(self, pair):
        sim, b = pair
        b.create_pod(make_pod("ok", EXIT0))
        b.create_pod(
            make_pod("bad", [sys.executable, "-c", "raise SystemExit(3)"])
        )
        wait_until(
            lambda: (
                (p := b.get_pod("default", "ok")) is not None
                and p.phase is PodPhase.SUCCEEDED
            ),
            what="ok pod success",
        )
        wait_until(
            lambda: (
                (p := b.get_pod("default", "bad")) is not None
                and p.phase is PodPhase.FAILED
            ),
            what="bad pod failure",
        )
        assert b.get_pod("default", "ok").exit_code == 0
        assert b.get_pod("default", "bad").exit_code == 3

    def test_pod_log_served_over_http(self, pair):
        sim, b = pair
        b.create_pod(
            make_pod("talk", [sys.executable, "-c", "print('from the pod')"])
        )
        wait_until(
            lambda: "from the pod" in b.pod_log("default", "talk"),
            what="pod log content",
        )

    def test_delete_kills_running_process(self, pair):
        sim, b = pair
        b.create_pod(make_pod("lived", SLEEP))
        wait_until(
            lambda: (
                (p := b.get_pod("default", "lived")) is not None
                and p.phase is PodPhase.RUNNING
            ),
            what="pod running",
        )
        b.delete_pod("default", "lived")
        assert b.get_pod("default", "lived") is None
        wait_until(lambda: not sim._procs, what="process reaped")


class TestGangAdmission:
    def test_capacity_gates_grants_and_regrants_on_release(self):
        sim = MiniApiServer(total_chips=8).start()
        b = KubeBackend(sim.url)
        try:
            b.create_pod_group(
                PodGroup(metadata=ObjectMeta(name="g1"), chip_request=8)
            )
            b.create_pod_group(
                PodGroup(metadata=ObjectMeta(name="g2"), chip_request=8)
            )
            assert b.get_pod_group("default", "g1").phase is PodGroupPhase.GRANTED
            assert b.get_pod_group("default", "g2").phase is PodGroupPhase.PENDING
            b.delete_pod_group("default", "g1")
            wait_until(
                lambda: b.get_pod_group("default", "g2").phase
                is PodGroupPhase.GRANTED,
                what="g2 regrant",
            )
        finally:
            b.close()
            sim.stop()

    def test_gang_blocked_pod_stays_pending_until_grant(self):
        from tf_operator_tpu.api.types import ANNOTATION_GANG_GROUP

        sim = MiniApiServer(total_chips=4).start()
        b = KubeBackend(sim.url)
        try:
            b.create_pod_group(
                PodGroup(metadata=ObjectMeta(name="big"), chip_request=8)
            )
            pod = make_pod("member", EXIT0)
            pod.metadata.annotations[ANNOTATION_GANG_GROUP] = "big"
            b.create_pod(pod)
            time.sleep(0.6)  # several kubelet ticks
            assert b.get_pod("default", "member").phase is PodPhase.PENDING
            # capacity grows (operator resize): the gang grants and the
            # member finally runs to completion
            b.update_pod_group("default", "big", 1, 4)
            wait_until(
                lambda: b.get_pod("default", "member").phase
                is PodPhase.SUCCEEDED,
                what="member ran after grant",
            )
        finally:
            b.close()
            sim.stop()


class TestWatch:
    def test_events_stream_to_subscribers(self, pair):
        sim, b = pair
        events = []
        b.subscribe(lambda ev: events.append((ev.type, ev.kind, ev.obj.metadata.name)))
        time.sleep(0.3)  # streams up
        b.create_pod(make_pod("w1", EXIT0))
        wait_until(
            lambda: (WatchEventType.ADDED, "Pod", "w1") in events,
            what="ADDED event",
        )
        wait_until(
            lambda: any(
                t is WatchEventType.MODIFIED and n == "w1"
                for t, _, n in events
            ),
            what="MODIFIED events from kubelet phases",
        )
        b.delete_pod("default", "w1")
        wait_until(
            lambda: (WatchEventType.DELETED, "Pod", "w1") in events,
            what="DELETED event",
        )

    def test_watch_replays_from_resource_version(self, pair):
        """A watch opened at rv=N must replay everything after N —
        the informer's no-lost-events contract."""

        sim, b = pair
        pod = make_pod("old", SLEEP)
        b.create_pod(pod)
        rv_after_create = pod.metadata.resource_version
        b.create_pod(make_pod("new", SLEEP))
        # raw protocol: watch from the older rv sees BOTH subsequent
        # events (new's ADDED, old's Running MODIFIED) but not old's ADDED
        conn_url = (
            f"{sim.url}/api/v1/pods?watch=true"
            f"&resourceVersion={rv_after_create}"
        )
        lines = []
        with urllib.request.urlopen(conn_url, timeout=5) as resp:
            deadline = time.time() + 5
            while time.time() < deadline and len(lines) < 2:
                line = resp.readline()
                if line.strip():
                    lines.append(json.loads(line))
        names = [d["object"]["metadata"]["name"] for d in lines]
        assert "new" in names
        assert not any(
            d["type"] == "ADDED" and d["object"]["metadata"]["name"] == "old"
            for d in lines
        )

    def test_expired_resource_version_gets_410_and_client_recovers(self, pair):
        sim, b = pair
        # age the log out: tiny window
        sim.store.log = type(sim.store.log)(maxlen=4)
        for i in range(8):
            b.create_service(
                Service(metadata=ObjectMeta(name=f"s{i}"), selector={}, port=1)
            )
        # raw protocol: rv=1 is long gone -> 410
        req = urllib.request.Request(
            f"{sim.url}/api/v1/services?watch=true&resourceVersion=1"
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 410
        # the client's ListAndWatch recovers: subscribe (internally
        # re-lists) and still sees NEW events
        events = []
        b.subscribe(lambda ev: events.append((ev.kind, ev.obj.metadata.name)))
        time.sleep(0.3)
        b.create_service(
            Service(metadata=ObjectMeta(name="fresh"), selector={}, port=1)
        )
        wait_until(
            lambda: ("Service", "fresh") in events, what="post-410 event"
        )

    def test_concurrent_watchers_all_see_events(self, pair):
        sim, b2 = pair
        b1 = KubeBackend(sim.url)
        try:
            seen1, seen2 = [], []
            b1.subscribe(lambda ev: seen1.append(ev.obj.metadata.name))
            b2.subscribe(lambda ev: seen2.append(ev.obj.metadata.name))
            time.sleep(0.3)
            b2.create_pod(make_pod("shared", SLEEP))
            wait_until(lambda: "shared" in seen1, what="watcher 1")
            wait_until(lambda: "shared" in seen2, what="watcher 2")
        finally:
            b1.close()


class TestKubeLease:
    """coordination.k8s.io/v1 Lease leader election over the sim — the
    client-go resourcelock/leaderelection tier (SURVEY.md §3.1)."""

    def _lease(self, sim, ident, **kw):
        from tf_operator_tpu.cmd.leader import KubeLease

        kw.setdefault("lease_duration", 1.0)
        return KubeLease(sim.url, identity=ident, **kw)

    def test_one_winner_while_lease_is_live(self, pair):
        sim, _ = pair
        a = self._lease(sim, "a")
        b = self._lease(sim, "b")
        assert a.try_acquire()
        assert a.is_leader and a.holder() == "a"
        assert not b.try_acquire()
        assert not b.is_leader
        a.release()

    def test_crashed_leader_expires_and_is_replaced(self, pair):
        sim, _ = pair
        a = self._lease(sim, "a")
        b = self._lease(sim, "b")
        assert a.try_acquire()
        # crash: stop renewing WITHOUT the clean release handoff
        a._stop.set()
        a._leading = False
        assert not b.try_acquire()  # still within the lease duration
        wait_until(lambda: b.try_acquire(), timeout=5.0, what="takeover")
        assert b.holder() == "b"
        b.release()

    def test_release_hands_off_immediately(self, pair):
        sim, _ = pair
        a = self._lease(sim, "a")
        b = self._lease(sim, "b")
        assert a.try_acquire()
        a.release()
        assert b.try_acquire()  # no expiry wait
        assert b.holder() == "b"
        b.release()

    def test_lost_leadership_fires_on_lost_and_demotes(self, pair):
        sim, _ = pair
        lost = []
        a = self._lease(sim, "a", on_lost=lambda: lost.append(True))
        assert a.try_acquire()
        # a rival writes itself into the lease through the REAL
        # protocol (correct resourceVersion precondition)
        status, obj = a._request("GET", a._path)
        assert status == 200
        rv = obj["metadata"]["resourceVersion"]
        spec = dict(obj["spec"])
        spec["holderIdentity"] = "usurper"
        spec["renewTime"] = __import__("time").time()
        status, _ = a._request(
            "PATCH", a._path,
            {"metadata": {"resourceVersion": rv}, "spec": spec},
        )
        assert status == 200
        wait_until(lambda: lost, timeout=5.0, what="on_lost callback")
        assert not a.is_leader

    def test_stale_resource_version_patch_conflicts(self, pair):
        """The optimistic-concurrency precondition itself: a PATCH
        carrying an out-of-date resourceVersion gets the apiserver's
        409, which is what serializes two candidates racing for an
        expired lease."""

        sim, _ = pair
        a = self._lease(sim, "a")
        assert a.try_acquire()
        status, obj = a._request("GET", a._path)
        rv = obj["metadata"]["resourceVersion"]
        spec = dict(obj["spec"])
        # first CAS succeeds and bumps the version...
        status, _ = a._request(
            "PATCH", a._path,
            {"metadata": {"resourceVersion": rv}, "spec": spec},
        )
        assert status == 200
        # ...so replaying against the OLD version must conflict
        status, _ = a._request(
            "PATCH", a._path,
            {"metadata": {"resourceVersion": rv}, "spec": spec},
        )
        assert status == 409
        a.release()


class TestKubeJobStore:
    """TPUJobs as custom resources in the apiserver (backend/kubejobs.py)
    — the reference's TFJob-CRD storage tier."""

    @pytest.fixture
    def jobs(self):
        from tf_operator_tpu.backend.kubejobs import KubeJobStore

        sim = MiniApiServer().start()
        store = KubeJobStore(sim.url)
        yield sim, store
        store.close()
        sim.stop()

    def _job(self, name, **kw):
        from tests.testutil import new_job

        kw.setdefault("worker", 1)
        kw.setdefault("command", EXIT0)
        return new_job(name, **kw)

    def test_create_get_list_delete_round_trip(self, jobs):
        sim, store = jobs
        job = self._job("rt", chief=1, worker=2)
        stored = store.create(job)
        assert stored.metadata.uid.startswith("tpujob-uid-")
        assert job.metadata.uid == stored.metadata.uid  # reflected back
        got = store.get("default", "rt")
        from tf_operator_tpu.api.types import ReplicaType

        assert got.spec.replica_specs[ReplicaType.WORKER].replicas == 2
        assert [j.metadata.name for j in store.list()] == ["rt"]
        store.delete("default", "rt")
        assert store.get("default", "rt") is None

    def test_admission_runs_client_side(self, jobs):
        from tf_operator_tpu.api.validation import ValidationError

        sim, store = jobs
        bad = self._job("Bad_Name!")
        with pytest.raises(ValidationError):
            store.create(bad)
        assert store.list() == []

    def test_status_subresource_persists(self, jobs):
        from tf_operator_tpu.api.types import (
            JobCondition, JobConditionType, TPUJobStatus,
        )

        sim, store = jobs
        store.create(self._job("st"))
        status = TPUJobStatus()
        status.conditions.append(
            JobCondition(
                type=JobConditionType.RUNNING, status=True,
                reason="r", message="m",
            )
        )
        store.update_status("default", "st", status)
        again = store.get("default", "st")
        assert again.status.has_condition(JobConditionType.RUNNING)

    def test_update_spec_replaces_not_merges(self, jobs):
        """A field UNSET by the new spec must really unset (PUT
        replacement, not merge-patch key-keeping)."""

        sim, store = jobs
        job = self._job("gang")
        job.spec.enable_gang_scheduling = True
        store.create(job)
        assert store.get("default", "gang").spec.enable_gang_scheduling
        edited = store.get("default", "gang")
        edited.spec.enable_gang_scheduling = False
        store.update_spec(edited)
        assert not store.get("default", "gang").spec.enable_gang_scheduling

    def test_watch_streams_job_events(self, jobs):
        sim, store = jobs
        events = []
        store.subscribe(lambda ev: events.append((ev.type, ev.obj.metadata.name)))
        time.sleep(0.3)
        store.create(self._job("w"))
        wait_until(
            lambda: (WatchEventType.ADDED, "w") in events, what="job ADDED"
        )
        store.delete("default", "w")
        wait_until(
            lambda: (WatchEventType.DELETED, "w") in events,
            what="job DELETED",
        )

    def test_preexisting_jobs_reach_late_subscribers(self, jobs):
        """ListAndWatch must feed LISTED objects as events: a job that
        existed before this store/operator started (restart, failover)
        reconciles immediately, not at first periodic resync."""

        from tf_operator_tpu.backend.kubejobs import KubeJobStore

        sim, store = jobs
        store.create(self._job("old"))
        late = KubeJobStore(sim.url)
        try:
            seen = []
            late.subscribe(lambda ev: seen.append(ev.obj.metadata.name))
            wait_until(lambda: "old" in seen, what="initial-list replay")
        finally:
            late.close()

    def test_preexisting_pods_reach_late_backend_subscribers(self, jobs):
        """Same ListAndWatch property for the pod watch (KubeBackend):
        without it a restarted reconciler would re-create pods that
        already run."""

        sim, store = jobs
        b1 = KubeBackend(sim.url)
        b1.create_pod(make_pod("preexists", SLEEP))
        b2 = KubeBackend(sim.url)
        try:
            seen = []
            b2.subscribe(lambda ev: seen.append((ev.kind, ev.obj.metadata.name)))
            wait_until(
                lambda: ("Pod", "preexists") in seen,
                what="pod initial-list replay",
            )
        finally:
            b1.close()
            b2.close()


class TestKubeEventRecorder:
    """v1 Events in the apiserver (backend/kubejobs.KubeEventRecorder):
    the reference's audit trail is cluster-side, not operator memory."""

    def test_post_filter_and_cross_process_visibility(self):
        from tf_operator_tpu.backend.kubejobs import KubeEventRecorder

        sim = MiniApiServer().start()
        try:
            rec = KubeEventRecorder(sim.url)
            rec.event("default/job-a", "Normal", "JobCreated", "created")
            rec.event("default/job-a", "Normal", "SuccessfulCreatePod", "p0")
            rec.event("default/job-b", "Warning", "JobFailed", "boom")
            rec.event("ns2/job-a", "Normal", "JobCreated", "other ns")
            rec.flush()  # posting is async (never blocks a reconcile)

            evs = rec.for_object("default/job-a")
            assert [e.reason for e in evs] == [
                "JobCreated", "SuccessfulCreatePod",
            ]
            assert all(e.object_key == "default/job-a" for e in evs)
            assert len(rec.all()) == 4

            # a DIFFERENT recorder (new process / next leader) sees the
            # same history — it lives in the apiserver
            rec2 = KubeEventRecorder(sim.url)
            assert [e.reason for e in rec2.for_object("default/job-b")] == [
                "JobFailed"
            ]
            # wire shape: real v1 Event objects with involvedObject
            raw = rec._request(
                "GET", "/api/v1/namespaces/default/events"
            )["items"]
            assert all(o["kind"] == "Event" for o in raw)
            assert all("involvedObject" in o for o in raw)
        finally:
            sim.stop()

    def test_recorder_is_best_effort_when_apiserver_is_down(self):
        from tf_operator_tpu.backend.kubejobs import KubeEventRecorder

        rec = KubeEventRecorder("http://127.0.0.1:1")  # nothing listens
        rec.event("default/x", "Normal", "JobCreated", "dropped, no raise")
        rec.flush(timeout=3.0)
        assert rec.for_object("default/x") == []
        assert rec.all() == []

    def test_rfc3339_timestamps_parse_and_order(self):
        """Real-apiserver interop: events go out with RFC3339
        firstTimestamp and read back from RFC3339 or epoch floats."""

        from tf_operator_tpu.backend.kubejobs import KubeEventRecorder

        sim = MiniApiServer().start()
        try:
            rec = KubeEventRecorder(sim.url)
            rec.event("default/j", "Normal", "First", "1")
            rec.event("default/j", "Normal", "Second", "2")
            rec.flush()
            raw = rec._request("GET", "/api/v1/namespaces/default/events")[
                "items"
            ]
            for o in raw:
                ts = o["firstTimestamp"]
                assert isinstance(ts, str) and ts.endswith("Z"), ts
                time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ")  # valid RFC3339
            evs = rec.for_object("default/j")
            # same-second events stay in emission order (name tie-break)
            assert [e.reason for e in evs] == ["First", "Second"]
            assert all(e.timestamp > 0 for e in evs)
        finally:
            sim.stop()
