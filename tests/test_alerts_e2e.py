"""Chaos e2e for the alert→status vertical (ISSUE 6 acceptance):

A fault-injected serving run (the PR-1 injector on the kubesim
apiserver adds real latency to real HTTP requests) drives a burn-rate
alert through its full lifecycle:

    pending -> firing -> Degraded condition + Warning event on the
    TPUJob + one flight-recorder dump -> faults cleared -> alert
    resolves -> condition clears + Normal event

plus the clean-soak half: the same run without faults fires ZERO
alerts — a false-positive-free baseline is part of the contract.
"""

import json
import time
import urllib.request

import pytest

from tests.testutil import new_job
from tf_operator_tpu.api.types import JobConditionType, PodPhase
from tf_operator_tpu.backend.fake import FakeCluster
from tf_operator_tpu.backend.jobstore import JobStore
from tf_operator_tpu.backend.kubesim import MiniApiServer
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.utils.alerts import AlertEngine, BurnRateRule
from tf_operator_tpu.utils.flight import FlightRecorder
from tf_operator_tpu.utils.metrics import SLO_BUCKETS, Metrics

#: the serving SLO under test: p90 of request wall <= 50 ms.  The
#: injected fault adds 120 ms, a clean local request takes ~2-5 ms —
#: margin on both sides against a loaded CI box.
OBJECTIVE_LE = 0.05
WINDOWS = (0.5, 1.5)
FAULT_DELAY = 0.12


def _request(url: str) -> float:
    """One real HTTP request; returns its wall seconds."""

    t0 = time.perf_counter()
    with urllib.request.urlopen(url, timeout=10) as r:
        r.read()
    return time.perf_counter() - t0


@pytest.fixture
def rig(tmp_path, monkeypatch):
    """kubesim (the fault-injectable data plane) + a sync-delivery
    controller with an alert engine wired, and one running TPUJob."""

    monkeypatch.setenv("TPUJOB_FLIGHT_DIR", str(tmp_path))
    sim = MiniApiServer().start()
    metrics = Metrics()
    metrics.set_buckets("serve_request_seconds", SLO_BUCKETS)
    recorder = FlightRecorder()
    recorder.attach_metrics(metrics)
    engine = AlertEngine(
        [
            BurnRateRule(
                "serve-burn",
                family="serve_request_seconds",
                objective_le=OBJECTIVE_LE,
                objective_ratio=0.9,
                labels={"route": "/pods"},
                windows=WINDOWS,
                burn_threshold=3.0,
            )
        ],
        metrics=metrics,
        recorder=recorder,
    )
    store = JobStore()
    backend = FakeCluster(delivery="sync")
    controller = TPUJobController(
        store, backend, metrics=metrics, alerts=engine
    )
    job = new_job(name="chaos-job", worker=1)
    store.create(job)
    controller.sync_until_quiet()
    backend.set_pod_phase("default", "chaos-job-worker-0", PodPhase.RUNNING)
    controller.sync_until_quiet()
    assert store.get("default", "chaos-job").status.has_condition(
        JobConditionType.RUNNING
    )
    yield sim, metrics, engine, store, controller
    controller.stop()
    sim.stop()


def _serve_and_evaluate(sim, metrics, engine, seconds: float,
                        until=None) -> None:
    """The miniature serving run: real GETs against the apiserver,
    each observed into the serving SLO family, the engine evaluated
    after every request.  Stops early when ``until()`` is true."""

    url = f"{sim.url}/api/v1/namespaces/default/pods"
    deadline = time.time() + seconds
    while time.time() < deadline:
        dt = _request(url)
        metrics.observe_histogram(
            "serve_request_seconds", dt, route="/pods", model="chaos"
        )
        engine.evaluate_once()
        if until is not None and until():
            return
        time.sleep(0.02)


class TestChaosLifecycle:
    def test_burn_alert_full_lifecycle_through_job_status(self, rig):
        sim, metrics, engine, store, controller = rig
        (alert,) = engine.alerts()

        # ---- inject: every pods GET rides a 120 ms latency fault
        sim.faults.add(
            path="/pods", methods=["GET"], mode="latency",
            delay=FAULT_DELAY,
        )
        _serve_and_evaluate(
            sim, metrics, engine, seconds=10.0,
            until=lambda: alert.state == "firing",
        )
        assert alert.state == "firing", (
            f"alert never fired: state={alert.state} value={alert.value}"
        )
        assert sim.faults.total_injected() > 0

        # ---- firing -> the rollup publishes Degraded + Warning event
        controller.sync_until_quiet()
        job = store.get("default", "chaos-job")
        deg = job.status.condition(JobConditionType.DEGRADED)
        assert deg is not None and deg.status
        assert deg.reason == "SLOViolation"
        assert "serve-burn" in deg.message
        # still Running — Degraded is health, not phase
        assert job.status.has_condition(JobConditionType.RUNNING)
        assert job.status.observed_health["firingAlerts"] == ["serve-burn"]
        events = [
            (e.type, e.reason)
            for e in controller.recorder.for_object("default/chaos-job")
        ]
        assert ("Warning", "SLOViolation") in events

        # ---- the black box captured the episode: exactly one dump,
        # carrying the firing log
        assert len(engine.dumps) == 1
        records = [
            json.loads(line)
            for line in open(engine.dumps[0]).read().splitlines()
        ]
        assert records[0]["reason"] == "alert-serve-burn"
        assert any(
            r["type"] == "log" and "serve-burn" in r.get("message", "")
            for r in records
        )

        # ---- clear the faults: good traffic ages the violation out of
        # both windows and the alert resolves
        sim.faults.clear()
        _serve_and_evaluate(
            sim, metrics, engine, seconds=12.0,
            until=lambda: alert.state == "resolved",
        )
        assert alert.state == "resolved", (
            f"alert never resolved: value={alert.value}"
        )

        # ---- resolved -> condition clears + Normal event
        controller.reconciler.config.health_refresh_seconds = 0.0
        controller.sync_until_quiet()
        job = store.get("default", "chaos-job")
        assert not job.status.has_condition(JobConditionType.DEGRADED)
        assert job.status.observed_health["firingAlerts"] == []
        events = [
            (e.type, e.reason)
            for e in controller.recorder.for_object("default/chaos-job")
        ]
        assert ("Normal", "SLORecovered") in events
        # one Warning + one Normal for the whole episode, not per sync
        assert events.count(("Warning", "SLOViolation")) == 1
        assert events.count(("Normal", "SLORecovered")) == 1
        # still exactly the one dump from the firing transition
        assert len(engine.dumps) == 1

    def test_clean_soak_fires_zero_alerts(self, rig):
        """The false-positive half: the same serving run with NO faults
        must never leave inactive — covering well past the long window
        so every burn evaluation runs fully covered."""

        sim, metrics, engine, store, controller = rig
        fired = []
        engine.subscribe(lambda a, old, new: fired.append((old, new)))
        _serve_and_evaluate(
            sim, metrics, engine, seconds=WINDOWS[1] * 2.5
        )
        (alert,) = engine.alerts()
        assert alert.state == "inactive"
        assert fired == []
        assert metrics.total("alerts_fired_total") == 0.0
        assert engine.dumps == []
        controller.reconciler.config.health_refresh_seconds = 0.0
        controller.sync_until_quiet()
        job = store.get("default", "chaos-job")
        assert not job.status.has_condition(JobConditionType.DEGRADED)
        events = [
            e.reason
            for e in controller.recorder.for_object("default/chaos-job")
        ]
        assert "SLOViolation" not in events
