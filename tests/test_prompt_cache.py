"""Prompt-KV snapshot reuse in ChunkedServingDecoder.

Exactness: a hit must produce the identical tokens a fresh prefill
would — the snapshot holds the same immutable arrays.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # generation-loop compiles

from tf_operator_tpu.models import llama_tiny
from tf_operator_tpu.models.decode import ChunkedServingDecoder

VOCAB = 96


def _setup():
    model = llama_tiny(vocab_size=VOCAB, max_len=64)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, VOCAB, size=(1, 9)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(1), prompt)["params"]
    return model, params, prompt


def test_hit_is_exact_and_skips_prefill():
    model, params, prompt = _setup()
    dec = ChunkedServingDecoder(model, params, prompt_cache=4)
    first = np.asarray(dec.generate(prompt, 6))
    compiles_after_first = dec.compile_count
    assert dec.prompt_cache_hits == 0
    again = np.asarray(dec.generate(prompt, 6))
    np.testing.assert_array_equal(first, again)
    assert dec.prompt_cache_hits == 1
    assert dec.compile_count == compiles_after_first  # no new programs
    # different budget, same prompt: still a hit, budget still honored
    longer = np.asarray(dec.generate(prompt, 11))
    assert dec.prompt_cache_hits == 2
    assert longer.shape == (1, 20)
    np.testing.assert_array_equal(longer[:, :15], first[:, :15])


def test_lru_eviction_and_distinct_prompts():
    model, params, prompt = _setup()
    dec = ChunkedServingDecoder(model, params, prompt_cache=2)
    r = np.random.RandomState(5)
    prompts = [
        jnp.asarray(r.randint(0, VOCAB, size=(1, 7)), jnp.int32)
        for _ in range(3)
    ]
    outs = [np.asarray(dec.generate(p, 4)) for p in prompts]
    assert dec.prompt_cache_hits == 0
    # p2, p1 cached (LRU size 2); p0 evicted
    np.testing.assert_array_equal(
        np.asarray(dec.generate(prompts[2], 4)), outs[2]
    )
    assert dec.prompt_cache_hits == 1
    np.testing.assert_array_equal(
        np.asarray(dec.generate(prompts[0], 4)), outs[0]  # miss, refills
    )
    assert dec.prompt_cache_hits == 1


def test_disabled_by_default():
    model, params, prompt = _setup()
    dec = ChunkedServingDecoder(model, params)
    dec.generate(prompt, 4)
    dec.generate(prompt, 4)
    assert dec.prompt_cache_hits == 0
