"""Weights-only int8 serving quantization (ops/quant.py).

Parity convention: greedy decode with a quantized tree must EXACTLY
match full-recompute greedy run with the dequantized (materialized)
weights — that pins the plumbing with no tolerance, independent of
quantization error, which is bounded separately.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.models import generate, llama_tiny
from tf_operator_tpu.models.decode import ChunkedServingDecoder

import sys as _sys, os as _os
_sys.path.insert(0, _os.path.dirname(__file__))
from testutil import assert_decode_equiv_up_to_ties  # noqa: E402
from tf_operator_tpu.ops.quant import (
    QTensor,
    is_quantized,
    materialize_tree,
    quantize_array,
    quantize_tree,
    tree_bytes,
)

VOCAB = 128


def _tiny():
    model = llama_tiny(vocab_size=VOCAB, max_len=64)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, VOCAB, size=(2, 5)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(1), prompt)["params"]
    return model, params, prompt


class TestQuantizeArray:
    def test_roundtrip_error_bounded_per_channel(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (256, 64), jnp.float32)
        qt = quantize_array(w)
        assert qt.q.dtype == jnp.int8
        assert qt.scale.shape == (1, 64)
        err = jnp.abs(qt.materialize(jnp.float32) - w)
        # symmetric rounding: error <= scale/2 per element (+ bf16 noise)
        assert float(jnp.max(err / qt.scale)) <= 0.51

    def test_constant_column_does_not_divide_by_zero(self):
        w = jnp.zeros((128, 8), jnp.float32)
        qt = quantize_array(w)
        assert np.all(np.asarray(qt.q) == 0)
        assert np.isfinite(np.asarray(qt.scale)).all()


class TestQuantizeTree:
    def test_selects_large_kernels_only(self):
        model, params, _ = _tiny()
        qparams = quantize_tree(params, min_size=1)
        leaves = jax.tree_util.tree_leaves_with_path(
            qparams, is_leaf=lambda l: isinstance(l, QTensor)
        )

        def leaf_name(path):  # boxed params end in .value attr keys
            for entry in reversed(path):
                k = getattr(entry, "key", None)
                if isinstance(k, str):
                    return k
            return ""

        names = {}
        for p, l in leaves:
            names[leaf_name(p)] = names.get(leaf_name(p), False) or isinstance(
                l, QTensor
            )
        assert names.get("kernel", False) is True
        # embedding doubles as the logits head — stays bf16 by default
        assert names.get("embedding", True) is False
        assert is_quantized(qparams) and not is_quantized(params)

    def test_min_size_gate_keeps_small_leaves(self):
        model, params, _ = _tiny()
        qparams = quantize_tree(params, min_size=10**9)
        assert not is_quantized(qparams)

    def test_bytes_shrink(self):
        model, params, _ = _tiny()
        qparams = quantize_tree(params, min_size=1)
        # bf16 2 bytes -> int8 1 byte (+ small scales): kernels halve
        assert tree_bytes(qparams) < 0.75 * tree_bytes(params)


class TestQuantizedDecode:
    @pytest.mark.slow
    def test_decode_logits_match_dequantized_reference(self):
        # Numerical parity at the LOGITS level: the int8-direct path
        # (QDenseGeneral → quant_matmul: int8 matmul with the f32 scale
        # applied to the accumulator) vs the materialized tree (bf16
        # dequantized weights) through the same decode apply.  The two
        # round differently — the direct form is the more accurate one
        # (the scale never gets re-rounded to bf16) — so token
        # sequences may flip on near-ties; logits must still agree to
        # bf16-scale tolerance.
        from tf_operator_tpu.models.decode import _decode_variant, _init_cache_for

        model, params, prompt = _tiny()
        qparams = quantize_tree(params, min_size=1)
        dmodel = _decode_variant(model)
        cache = _init_cache_for(dmodel, prompt.shape[0])
        got, _ = dmodel.apply(
            {"params": qparams, "cache": cache}, prompt, mutable=["cache"]
        )
        want, _ = dmodel.apply(
            {"params": materialize_tree(qparams), "cache": cache},
            prompt,
            mutable=["cache"],
        )
        np.testing.assert_allclose(
            np.asarray(got, np.float32),
            np.asarray(want, np.float32),
            atol=0.08, rtol=0.08,
        )

    @pytest.mark.slow
    def test_generate_runs_quantized_tree_end_to_end(self):
        # plumbing: the int8 tree drives the full fused decode loop and
        # yields the same SHAPES and a valid token stream
        model, params, prompt = _tiny()
        qparams = quantize_tree(params, min_size=1)
        out = np.asarray(generate(model, qparams, prompt, max_new_tokens=8))
        ref = np.asarray(
            generate(model, materialize_tree(qparams), prompt, max_new_tokens=8)
        )
        assert out.shape == ref.shape
        np.testing.assert_array_equal(
            out[:, : prompt.shape[1]], ref[:, : prompt.shape[1]]
        )
        assert_decode_equiv_up_to_ties(model, qparams, out, ref)

    @pytest.mark.slow
    def test_serving_decoder_accepts_quantized_tree(self):
        model, params, prompt = _tiny()
        qparams = quantize_tree(params, min_size=1)
        dec = ChunkedServingDecoder(model, qparams)
        out = np.asarray(dec.generate(prompt, max_new_tokens=6))
        ref = np.asarray(
            ChunkedServingDecoder(model, materialize_tree(qparams)).generate(
                prompt, max_new_tokens=6
            )
        )
        assert out.shape == ref.shape
        assert_decode_equiv_up_to_ties(model, qparams, out, ref)

    @pytest.mark.slow
    def test_generate_jits_with_quantized_tree(self):
        model, params, prompt = _tiny()
        qparams = quantize_tree(params, min_size=1)
        fn = jax.jit(
            lambda q, ids: generate(model, q, ids, max_new_tokens=4)
        )
        out = fn(qparams, prompt)
        assert out.shape == (2, 9)

    @pytest.mark.slow
    def test_moe_quantized_decode_parity(self):
        # expert stacks [E, in, out] quantize with per-expert scales
        from tf_operator_tpu.models import moe_tiny

        model = moe_tiny(vocab_size=VOCAB, max_len=64)
        prompt = jnp.asarray(
            np.random.RandomState(3).randint(0, VOCAB, size=(2, 5)), jnp.int32
        )
        params = model.init(jax.random.PRNGKey(1), prompt)["params"]
        qparams = quantize_tree(params, min_size=1)

        def leaf_names(tree):
            out = set()
            for p, l in jax.tree_util.tree_leaves_with_path(
                tree, is_leaf=lambda l: isinstance(l, QTensor)
            ):
                if isinstance(l, QTensor):
                    for entry in reversed(p):
                        k = getattr(entry, "key", None)
                        if isinstance(k, str):
                            out.add(k)
                            break
            return out

        assert {"wi", "wo"} <= leaf_names(qparams)
        out = generate(model, qparams, prompt, max_new_tokens=6)
        ref = generate(
            model, materialize_tree(qparams), prompt, max_new_tokens=6
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_moe_expert_scales_are_per_expert(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (4, 64, 32), jnp.float32)
        w = w * jnp.asarray([1.0, 2.0, 4.0, 8.0])[:, None, None]
        qt = quantize_array(w, reduce_axes=(1,))
        assert qt.scale.shape == (4, 1, 32)
        err = jnp.abs(qt.materialize(jnp.float32) - w)
        assert float(jnp.max(err / qt.scale)) <= 0.51

    def test_quantization_error_small_on_logits(self):
        model, params, prompt = _tiny()
        qparams = quantize_tree(params, min_size=1)
        base = model.apply({"params": params}, prompt)
        quant = model.apply({"params": materialize_tree(qparams)}, prompt)
        denom = float(jnp.std(base)) or 1.0
        assert float(jnp.max(jnp.abs(quant - base))) / denom < 0.25
