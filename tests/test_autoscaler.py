"""Elastic autoscaler (ISSUE 7 tentpole): signal-driven scaling for
serving and training jobs, closing the alert→act loop.

Covers the decision core with synthetic clocks (the alert-engine test
pattern): serving scale-up on breaching signals with cooldown + bounds,
hysteresis on both the time axis (stabilization) and the level axis
(gauge latch), training elastic resize — shed on distress, recover on
quiet — gated by checkpoint freshness, the reconciler's desired-replica
overlay + re-shard bounce, events, the GET /autoscaler endpoint, the
observedHealth.autoscaler block (serde round-trip), spec validation,
and the kubesim/fake capacity knobs.
"""

import json
import time
import urllib.request

import pytest

from tests.testutil import new_job
from tf_operator_tpu.api.serde import job_from_dict, job_to_dict
from tf_operator_tpu.api.types import (
    AutoscalingPolicy,
    AutoscalingSpec,
    JobConditionType,
    PodPhase,
    ReplicaType,
    SignalBinding,
)
from tf_operator_tpu.api.validation import ValidationError, validate
from tf_operator_tpu.backend.fake import FakeCluster
from tf_operator_tpu.backend.jobstore import JobStore
from tf_operator_tpu.controller.autoscaler import (
    Autoscaler,
    job_checkpoint_age,
)
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.utils.alerts import AlertEngine, ThresholdRule
from tf_operator_tpu.utils.flight import FlightRecorder
from tf_operator_tpu.utils.metrics import Metrics
from tf_operator_tpu.utils.summaries import ANNOTATION_SUMMARY_DIR, SummaryWriter


def serving_policy(**kw):
    defaults = dict(
        replica_type=ReplicaType.WORKER,
        mode="serving",
        min_replicas=1,
        max_replicas=3,
        cooldown_seconds=10.0,
        stabilization_seconds=30.0,
        signals=[
            SignalBinding(kind="gauge", name="serve_admission_queue_depth", threshold=10.0)
        ],
    )
    defaults.update(kw)
    return AutoscalingPolicy(**defaults)


def training_policy(**kw):
    defaults = dict(
        replica_type=ReplicaType.WORKER,
        mode="training",
        min_replicas=1,
        max_replicas=4,
        cooldown_seconds=10.0,
        stabilization_seconds=30.0,
        max_checkpoint_age_seconds=600.0,
        signals=[SignalBinding(kind="alert", name="train-stall")],
    )
    defaults.update(kw)
    return AutoscalingPolicy(**defaults)


class Rig:
    """FakeCluster + sync controller + private metrics/engine/autoscaler."""

    def __init__(self, tmp_path, monkeypatch, rules=None):
        monkeypatch.setenv("TPUJOB_FLIGHT_DIR", str(tmp_path))
        self.metrics = Metrics()
        recorder = FlightRecorder()
        self.engine = AlertEngine(
            rules if rules is not None else [],
            metrics=self.metrics,
            recorder=recorder,
        )
        self.autoscaler = Autoscaler(metrics=self.metrics, alerts=self.engine)
        self.store = JobStore()
        self.backend = FakeCluster(delivery="sync")
        self.controller = TPUJobController(
            self.store,
            self.backend,
            metrics=self.metrics,
            alerts=self.engine,
            autoscaler=self.autoscaler,
        )
        self.controller.reconciler.config.health_refresh_seconds = 0.0

    def add_job(self, policy, name="job", worker=1, annotations=None):
        job = new_job(name=name, worker=worker)
        job.spec.autoscaling = AutoscalingSpec(policies=[policy])
        if annotations:
            job.metadata.annotations.update(annotations)
        self.store.create(job)
        self.controller.sync_until_quiet()
        self.backend.run_all("default")
        self.controller.sync_until_quiet()
        return job

    def events(self, key="default/job"):
        return [
            (e.reason, e.message)
            for e in self.controller.recorder.for_object(key)
        ]

    def worker_pods(self, ns="default"):
        return sorted(
            p.metadata.name
            for p in self.backend.list_pods(ns)
            if p.phase is not PodPhase.FAILED
        )

    def stop(self):
        self.controller.stop()


@pytest.fixture
def rig(tmp_path, monkeypatch):
    r = Rig(tmp_path, monkeypatch)
    yield r
    r.stop()


class TestServingScaling:
    def test_scale_up_cooldown_bounds_then_down_after_quiet(self, rig):
        rig.add_job(serving_policy(), worker=1)
        t0 = time.time()

        # breach: queue depth over threshold → one step up per cooldown
        rig.metrics.set("serve_admission_queue_depth", 50.0)
        (d,) = rig.autoscaler.evaluate_once(t0)
        assert (d.direction, d.from_replicas, d.to_replicas) == ("up", 1, 2)
        assert rig.autoscaler.evaluate_once(t0 + 1) == []  # cooldown
        rig.controller.sync_until_quiet()
        assert rig.worker_pods() == ["job-worker-0", "job-worker-1"]

        (d2,) = rig.autoscaler.evaluate_once(t0 + 11)
        assert (d2.from_replicas, d2.to_replicas) == (2, 3)
        # at max_replicas: breaching signals can no longer scale
        assert rig.autoscaler.evaluate_once(t0 + 22) == []
        rig.controller.sync_until_quiet()
        assert len(rig.worker_pods()) == 3

        # relief: below the hysteresis release level → stabilization
        # must pass before the first down step
        rig.metrics.set("serve_admission_queue_depth", 2.0)
        assert rig.autoscaler.evaluate_once(t0 + 30) == []  # quiet starts
        assert rig.autoscaler.evaluate_once(t0 + 40) == []  # not stabilized
        (d3,) = rig.autoscaler.evaluate_once(t0 + 61)
        assert (d3.direction, d3.to_replicas) == ("down", 2)
        rig.controller.sync_until_quiet()
        assert len(rig.worker_pods()) == 2
        (d4,) = rig.autoscaler.evaluate_once(t0 + 72)
        assert d4.to_replicas == 1
        # at min: quiet signals can no longer shrink
        assert rig.autoscaler.evaluate_once(t0 + 90) == []
        rig.controller.sync_until_quiet()
        assert rig.worker_pods() == ["job-worker-0"]

        # every decision is a Normal event (the acceptance contract)
        reasons = [r for r, _ in rig.events()]
        assert reasons.count("ScaledUp") == 2
        assert reasons.count("ScaledDown") == 2

    def test_gauge_hysteresis_latch_holds_between_levels(self, rig):
        rig.add_job(serving_policy(max_replicas=2), worker=1)
        t0 = time.time()
        rig.metrics.set("serve_admission_queue_depth", 50.0)
        (d,) = rig.autoscaler.evaluate_once(t0)
        assert d.direction == "up"
        # level drops BELOW the threshold (10) but ABOVE the release
        # level (threshold * ratio = 5): the latch holds — still
        # breaching, so no amount of elapsed time starts the quiet
        # clock or sheds the replica
        rig.metrics.set("serve_admission_queue_depth", 7.0)
        assert rig.autoscaler.evaluate_once(t0 + 100) == []  # at max, held
        (pol,) = rig.autoscaler.snapshot()["policies"]
        assert pol["breaching"] is True
        assert rig.autoscaler.evaluate_once(t0 + 500) == []  # still held
        # only dropping below the release level starts the quiet clock
        rig.metrics.set("serve_admission_queue_depth", 4.0)
        assert rig.autoscaler.evaluate_once(t0 + 600) == []  # quiet starts
        (down,) = rig.autoscaler.evaluate_once(t0 + 631)
        assert down.direction == "down"

    def test_spec_stays_untouched_in_store(self, rig):
        rig.add_job(serving_policy(), worker=1)
        rig.metrics.set("serve_admission_queue_depth", 50.0)
        rig.autoscaler.evaluate_once(time.time())
        rig.controller.sync_until_quiet()
        stored = rig.store.get("default", "job")
        # the overlay is operator state; the user's declaration persists
        assert stored.spec.replica_specs[ReplicaType.WORKER].replicas == 1
        assert len(rig.worker_pods()) == 2


class TestAlertSignals:
    def test_alert_binding_scales_on_firing(self, rig):
        # a threshold rule the test drives directly through the engine
        rig.engine = AlertEngine(
            [ThresholdRule("hot", metric="hot_gauge", kind="gauge", threshold=5.0)],
            metrics=rig.metrics,
            recorder=FlightRecorder(),
        )
        rig.autoscaler.alerts = rig.engine
        rig.add_job(
            serving_policy(signals=[SignalBinding(kind="alert", name="hot")]),
            worker=1,
        )
        t0 = time.time()
        assert rig.autoscaler.evaluate_once(t0) == []  # alert inactive
        rig.metrics.set("hot_gauge", 9.0)
        rig.engine.evaluate_once(t0)
        (d,) = rig.autoscaler.evaluate_once(t0)
        assert d.direction == "up"
        assert d.signals["hot"]["state"] == "firing"

    def test_unknown_alert_binding_never_breaches_but_is_visible(self, rig):
        rig.add_job(
            serving_policy(signals=[SignalBinding(kind="alert", name="no-such-rule")]),
            worker=1,
        )
        assert rig.autoscaler.evaluate_once(time.time()) == []
        snap = rig.autoscaler.snapshot()
        (pol,) = snap["policies"]
        assert pol["signals"]["no-such-rule"]["unknown"] is True


class TestTrainingElastic:
    def _stall_rule(self):
        return ThresholdRule(
            "train-stall", metric="watchdog_stall_total",
            kind="counter_increase", threshold=0.0, window=60.0,
        )

    def _rig_with_training_job(self, rig, tmp_path, ckpt_age=10.0, worker=4):
        rig.engine = AlertEngine(
            [self._stall_rule()], metrics=rig.metrics,
            recorder=FlightRecorder(),
        )
        rig.autoscaler.alerts = rig.engine
        sdir = str(tmp_path / "summaries")
        w = SummaryWriter(sdir)
        w.write(step=100, loss=1.0, checkpoint_time_unix=time.time() - ckpt_age)
        w.close()
        rig.add_job(
            training_policy(), name="train", worker=worker,
            annotations={ANNOTATION_SUMMARY_DIR: sdir},
        )
        return sdir

    def _fire_stall(self, rig, t0):
        rig.engine.evaluate_once(t0 - 30)
        rig.metrics.inc("watchdog_stall_total", heartbeat="train.loop")
        rig.engine.evaluate_once(t0)
        assert rig.engine.alert("train-stall").state == "firing"

    def test_distress_sheds_replicas_with_reshard_bounce(self, rig, tmp_path):
        self._rig_with_training_job(rig, tmp_path)
        t0 = time.time()
        self._fire_stall(rig, t0)
        (d,) = rig.autoscaler.evaluate_once(t0)
        assert (d.direction, d.from_replicas, d.to_replicas) == ("down", 4, 3)
        assert d.reshard is True
        assert "checkpoint" in d.reason

        # the resize bounces the WHOLE replica set (world size changes),
        # then the next sync recreates it at the new size
        rig.controller.sync_until_quiet()
        pods = rig.worker_pods()
        assert len(pods) == 3, pods
        reasons = [r for r, _ in rig.events("default/train")]
        assert "Resharding" in reasons
        assert "ScaledDown" in reasons

    def test_stale_checkpoint_refuses_resize(self, rig, tmp_path):
        self._rig_with_training_job(rig, tmp_path, ckpt_age=100_000.0)
        t0 = time.time()
        self._fire_stall(rig, t0)
        assert rig.autoscaler.evaluate_once(t0) == []
        snap = rig.autoscaler.snapshot()
        (pol,) = snap["policies"]
        assert "checkpoint" in pol["lastSkip"]["reason"]
        assert rig.metrics.counter(
            "autoscaler_skipped_total", reason="checkpoint_stale"
        ) == 1.0
        # all four workers still running — nothing was shed
        rig.controller.sync_until_quiet()
        assert len(rig.worker_pods()) == 4

    def test_unknown_checkpoint_age_refuses_resize(self, rig, tmp_path):
        rig.engine = AlertEngine(
            [self._stall_rule()], metrics=rig.metrics,
            recorder=FlightRecorder(),
        )
        rig.autoscaler.alerts = rig.engine
        rig.add_job(training_policy(), name="train", worker=4)  # no summary dir
        t0 = time.time()
        self._fire_stall(rig, t0)
        assert rig.autoscaler.evaluate_once(t0) == []
        (pol,) = rig.autoscaler.snapshot()["policies"]
        assert "unknown" in pol["lastSkip"]["reason"]

    def test_recovery_scales_back_toward_spec(self, rig, tmp_path):
        sdir = self._rig_with_training_job(rig, tmp_path)
        t0 = time.time()
        self._fire_stall(rig, t0)
        (d,) = rig.autoscaler.evaluate_once(t0)
        assert d.to_replicas == 3
        rig.controller.sync_until_quiet()

        # distress clears: the stall counter stops increasing and the
        # window ages it out → resolved → quiet
        rig.engine.evaluate_once(t0 + 120)
        assert rig.engine.alert("train-stall").state in ("resolved", "inactive")
        # keep the checkpoint stamp fresh for the recovery resize
        w = SummaryWriter(sdir)
        w.write(step=200, loss=0.5, checkpoint_time_unix=time.time())
        w.close()
        assert rig.autoscaler.evaluate_once(t0 + 120) == []  # quiet starts
        (up,) = rig.autoscaler.evaluate_once(t0 + 151)
        assert (up.direction, up.to_replicas) == ("up", 4)
        assert up.reshard is True
        rig.controller.sync_until_quiet()
        assert len(rig.worker_pods()) == 4
        # recovery stops AT the spec's declared size
        assert rig.autoscaler.evaluate_once(t0 + 260) == []


class TestHealthRewriteFloor:
    def test_liveness_rewrites_cannot_livelock_the_queue(
        self, rig, tmp_path, monkeypatch
    ):
        """observedHealth carries ``updatedAt``, and every rollup write
        feeds back as a watch event and another sync.  With the refresh
        throttle at 0 and any real per-sync latency (the summary-series
        disk read is enough for round(now, 3) to advance each pass),
        that loop used to rewrite updatedAt until sync_until_quiet's
        10k-iteration cap — one soak pump tick ate a whole phase
        budget.  health_rewrite_floor_seconds bounds liveness-only
        rewrites; material changes still bypass (covered by every
        decision-landing test in this file)."""

        sdir = str(tmp_path / "s")
        w = SummaryWriter(sdir)
        w.write(step=0, loss=1.0, checkpoint_time_unix=time.time())
        w.close()
        rig.add_job(
            training_policy(), name="train", worker=2,
            annotations={ANNOTATION_SUMMARY_DIR: sdir},
        )

        # a clock that visibly advances between time() calls models the
        # slow-sync case deterministically (scoped to the reconciler
        # module — nothing else sees it)
        import tf_operator_tpu.controller.reconciler as rmod

        base = time.time()
        calls = [0]

        class _TickingTime:
            def __getattr__(self, name):  # perf_counter, monotonic, ...
                return getattr(time, name)

            def time(self):
                calls[0] += 1
                return base + 0.002 * calls[0]

        monkeypatch.setattr(rmod, "time", _TickingTime())
        rig.controller._enqueue("default/train")
        n = rig.controller.sync_until_quiet()
        assert n <= 50, (
            f"liveness-only rollup rewrites churned the queue: {n} syncs"
        )


class TestStatusAndEndpoint:
    def test_observed_health_autoscaler_block_roundtrips_serde(self, rig):
        rig.add_job(serving_policy(), worker=1)
        rig.metrics.set("serve_admission_queue_depth", 50.0)
        rig.autoscaler.evaluate_once(time.time())
        rig.controller.sync_until_quiet()
        job = rig.store.get("default", "job")
        blk = job.status.observed_health["autoscaler"]["Worker"]
        assert blk["desiredReplicas"] == 2
        assert blk["specReplicas"] == 1
        assert blk["breaching"] is True
        assert blk["lastDecision"]["direction"] == "up"
        # serde round-trip (the wire format is the acceptance surface)
        d = job_to_dict(job)
        job2 = job_from_dict(d)
        assert job2.status.observed_health["autoscaler"] == (
            job.status.observed_health["autoscaler"]
        )
        # and the status clone must not alias the nested block
        c = job.status.clone()
        c.observed_health["autoscaler"]["Worker"]["desiredReplicas"] = 99
        assert job.status.observed_health["autoscaler"]["Worker"][
            "desiredReplicas"
        ] == 2

    def test_get_autoscaler_endpoint(self, rig):
        from tf_operator_tpu.server.api import ApiServer

        rig.add_job(serving_policy(), worker=1)
        rig.metrics.set("serve_admission_queue_depth", 50.0)
        rig.autoscaler.evaluate_once(time.time())
        api = ApiServer(
            rig.store, rig.backend, rig.metrics,
            rig.controller.recorder, autoscaler=rig.autoscaler,
        )
        api.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{api.port}/autoscaler", timeout=10
            ) as r:
                snap = json.loads(r.read())
        finally:
            api.stop()
        assert snap["decisions"][0]["direction"] == "up"
        assert snap["policies"][0]["job"] == "default/job"

    def test_job_deletion_forgets_state(self, rig):
        rig.add_job(serving_policy(), worker=1)
        rig.metrics.set("serve_admission_queue_depth", 50.0)
        rig.autoscaler.evaluate_once(time.time())
        assert rig.autoscaler.snapshot()["policies"]
        rig.store.delete("default", "job")
        rig.controller.sync_until_quiet()
        assert rig.autoscaler.snapshot()["policies"] == []


class TestValidation:
    def _job_with(self, policy):
        job = new_job(name="v", worker=2)
        job.spec.autoscaling = AutoscalingSpec(policies=[policy])
        return job

    def test_good_policy_passes(self):
        validate(self._job_with(serving_policy()))

    def test_rejects_bad_bounds_mode_signals(self):
        with pytest.raises(ValidationError, match="minReplicas"):
            validate(self._job_with(serving_policy(min_replicas=5, max_replicas=2)))
        with pytest.raises(ValidationError, match="mode"):
            validate(self._job_with(serving_policy(mode="sideways")))
        with pytest.raises(ValidationError, match="signals"):
            validate(self._job_with(serving_policy(signals=[])))
        with pytest.raises(ValidationError, match="kind"):
            validate(self._job_with(serving_policy(
                signals=[SignalBinding(kind="vibes", name="x")]
            )))

    def test_rejects_unscalable_replica_types(self):
        job = new_job(name="v", chief=1, worker=2)
        job.spec.autoscaling = AutoscalingSpec(
            policies=[serving_policy(replica_type=ReplicaType.CHIEF)]
        )
        with pytest.raises(ValidationError, match="chief"):
            validate(job)
        job2 = new_job(name="v", worker=2)
        job2.spec.autoscaling = AutoscalingSpec(
            policies=[serving_policy(replica_type=ReplicaType.EVALUATOR)]
        )
        with pytest.raises(ValidationError, match="no replica spec"):
            validate(job2)

    def test_rejects_duplicate_policies(self):
        job = new_job(name="v", worker=2)
        job.spec.autoscaling = AutoscalingSpec(
            policies=[serving_policy(), serving_policy()]
        )
        with pytest.raises(ValidationError, match="duplicate"):
            validate(job)


class TestCheckpointAgeHelper:
    def test_series_stamp_preferred_over_gauge(self, tmp_path):
        m = Metrics()
        m.set("checkpoint_last_success_unix", time.time() - 5000)
        job = new_job(name="j", worker=1)
        now = time.time()
        # no series: falls back to the process gauge
        age = job_checkpoint_age(job, now, metrics=m)
        assert age == pytest.approx(5000, abs=60)
        # a pod-scope series stamp wins (the PR 6 scope-gap closure)
        sdir = str(tmp_path / "s")
        w = SummaryWriter(sdir)
        w.write(step=1, checkpoint_time_unix=now - 30)
        w.close()
        job.metadata.annotations[ANNOTATION_SUMMARY_DIR] = sdir
        age = job_checkpoint_age(job, now, metrics=m)
        assert age == pytest.approx(30, abs=5)

    def test_unknown_everywhere_is_none(self):
        job = new_job(name="j", worker=1)
        assert job_checkpoint_age(job, time.time(), metrics=Metrics()) is None


class TestCapacityKnobs:
    def test_fake_cluster_shrink_preempts_lifo_and_grow_regrants(self):
        from tf_operator_tpu.backend.objects import PodGroup

        backend = FakeCluster(delivery="sync", total_chips=32)
        for i, chips in enumerate((16, 16)):
            g = PodGroup(min_member=1, chip_request=chips)
            g.metadata.name = f"g{i}"
            g.metadata.namespace = "default"
            backend.create_pod_group(g)
        assert all(
            backend.get_pod_group("default", f"g{i}").phase.value == "Granted"
            for i in (0, 1)
        )
        revoked = backend.set_total_chips(16)
        assert revoked == ["g1"]  # most-recently granted loses (LIFO)
        assert backend.get_pod_group("default", "g0").phase.value == "Granted"
        assert backend.get_pod_group("default", "g1").phase.value == "Pending"
        assert backend.set_total_chips(32) == []
        assert backend.get_pod_group("default", "g1").phase.value == "Granted"

    def test_kubesim_capacity_admin_route(self):
        from tf_operator_tpu.backend.kubesim import MiniApiServer

        sim = MiniApiServer(total_chips=32).start()
        try:
            for i in range(2):
                body = json.dumps({
                    "apiVersion": "scheduling.volcano.sh/v1beta1",
                    "kind": "PodGroup",
                    "metadata": {"name": f"g{i}", "namespace": "default"},
                    "spec": {"minMember": 1,
                             "minResources": {"google.com/tpu": 16}},
                }).encode()
                req = urllib.request.Request(
                    f"{sim.url}/apis/scheduling.volcano.sh/v1beta1/"
                    "namespaces/default/podgroups",
                    data=body, method="POST",
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=10) as r:
                    assert r.status == 201

            def capacity(payload=None):
                req = urllib.request.Request(
                    f"{sim.url}/_capacity",
                    data=json.dumps(payload).encode() if payload else None,
                    method="POST" if payload else "GET",
                )
                with urllib.request.urlopen(req, timeout=10) as r:
                    return json.loads(r.read())

            assert capacity()["grantedChips"] == 32
            out = capacity({"totalChips": 16})
            assert out["revoked"] == ["g1"]
            assert capacity()["grantedChips"] == 16
            out = capacity({"totalChips": 48})
            assert out["revoked"] == []
            assert capacity()["grantedChips"] == 32
        finally:
            sim.stop()


def test_role_filtered_bindings_keep_separate_hysteresis_latches():
    """ISSUE 13 review finding: two label-filtered bindings on ONE
    gauge family in one policy must not share a hysteresis latch — a
    breached {role=prefill} slice would otherwise latch the
    {role=decode} slice breaching while decode sits in the
    between-release-and-threshold band (and their signal keys must
    not collide in the values map either)."""

    from tf_operator_tpu.controller.autoscaler import _PolicyState

    m = Metrics()
    a = Autoscaler(metrics=m)
    pol = serving_policy(signals=[
        SignalBinding(kind="gauge", name="kv_blocks_pressure",
                      threshold=0.85, labels={"role": "prefill"}),
        SignalBinding(kind="gauge", name="kv_blocks_pressure",
                      threshold=0.85, labels={"role": "decode"}),
    ])
    st = _PolicyState()
    m.set("kv_blocks_pressure", 1.0, model="t", replica="0",
          role="prefill")
    # decode sits between the release level (0.85*0.5) and the
    # threshold: with a fresh latch of its own this is NOT breaching
    m.set("kv_blocks_pressure", 0.5, model="t", replica="1",
          role="decode")
    breach, values = a._measure_signals(pol, st)
    assert breach
    assert values["kv_blocks_pressure{role=prefill}"]["breaching"]
    assert not values["kv_blocks_pressure{role=decode}"]["breaching"]
    # and the latches stay separate on release too
    m.set("kv_blocks_pressure", 0.0, model="t", replica="0",
          role="prefill")
    m.set("kv_blocks_pressure", 1.0, model="t", replica="1",
          role="decode")
    _, values = a._measure_signals(pol, st)
    assert not values["kv_blocks_pressure{role=prefill}"]["breaching"]
    assert values["kv_blocks_pressure{role=decode}"]["breaching"]
