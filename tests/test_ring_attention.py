"""Ring attention vs plain attention: exactness on the virtual sp mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.ops import dot_product_attention, ring_attention
from tf_operator_tpu.parallel import make_mesh


def _qkv(b=8, h=4, s=32, d=8, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, s, d).astype(np.float32), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_plain(causal, sp):
    mesh = make_mesh({"sp": sp, "dp": -1})
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal)
    with mesh:
        out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_gradients_match(causal):
    mesh = make_mesh({"sp": 4, "dp": -1})
    q, k, v = _qkv(s=16)

    def loss_ref(q, k, v):
        return (dot_product_attention(q, k, v, causal=causal) ** 2).sum()

    def loss_ring(q, k, v):
        with mesh:
            return (ring_attention(q, k, v, mesh, causal=causal) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


def test_ring_bf16_close():
    mesh = make_mesh({"sp": 4, "dp": -1})
    q, k, v = _qkv(dtype=jnp.bfloat16)
    ref = dot_product_attention(q, k, v, causal=True)
    with mesh:
        out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2
    )


def test_sp1_falls_back_to_plain():
    mesh = make_mesh({"dp": 8})
    q, k, v = _qkv()
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_ring_under_jit_with_sharded_inputs():
    """The real usage: ring attention inside a jitted step with inputs
    already laid out batch-over-dp, seq-over-sp."""

    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh({"dp": 2, "sp": 4})
    q, k, v = _qkv()
    sh = NamedSharding(mesh, P(("dp", "fsdp"), None, "sp", None))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
    with mesh:
        out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=True))(q, k, v)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
