"""Ring attention vs plain attention: exactness on the virtual sp mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# default-tier exclusion (ring schedules in interpret mode); see README 'Tests run in two tiers'
pytestmark = pytest.mark.slow

from tf_operator_tpu.ops import dot_product_attention, ring_attention
from tf_operator_tpu.parallel import make_mesh


def _qkv(b=8, h=4, s=32, d=8, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, s, d).astype(np.float32), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_plain(causal, sp):
    mesh = make_mesh({"sp": sp, "dp": -1})
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal)
    with mesh:
        out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_gradients_match(causal):
    mesh = make_mesh({"sp": 4, "dp": -1})
    q, k, v = _qkv(s=16)

    def loss_ref(q, k, v):
        return (dot_product_attention(q, k, v, causal=causal) ** 2).sum()

    def loss_ring(q, k, v):
        with mesh:
            return (ring_attention(q, k, v, mesh, causal=causal) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


def test_ring_bf16_close():
    mesh = make_mesh({"sp": 4, "dp": -1})
    q, k, v = _qkv(dtype=jnp.bfloat16)
    ref = dot_product_attention(q, k, v, causal=True)
    with mesh:
        out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2
    )


def test_sp1_falls_back_to_plain():
    mesh = make_mesh({"dp": 8})
    q, k, v = _qkv()
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_ring_under_jit_with_sharded_inputs():
    """The real usage: ring attention inside a jitted step with inputs
    already laid out batch-over-dp, seq-over-sp."""

    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh({"dp": 2, "sp": 4})
    q, k, v = _qkv()
    sh = NamedSharding(mesh, P(("dp", "fsdp"), None, "sp", None))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
    with mesh:
        out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=True))(q, k, v)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


class TestFlashRing:
    """flash x sp: each ring block computed by the pallas flash kernel
    (interpret mode on CPU), merged by logsumexp — must match full
    attention exactly, forward and backward."""

    def _qkv(self, B=2, H=2, S=128, D=64, seed=0):
        r = np.random.RandomState(seed)
        return (
            jnp.asarray(r.randn(B, H, S, D), jnp.float32) * 0.3,
            jnp.asarray(r.randn(B, H, S, D), jnp.float32) * 0.3,
            jnp.asarray(r.randn(B, H, S, D), jnp.float32),
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_full_attention(self, causal):
        mesh = make_mesh({"sp": 4, "dp": 2})
        q, k, v = self._qkv()
        ref = dot_product_attention(q, k, v, causal=causal)
        with mesh:
            out = jax.jit(
                lambda a, b, c: ring_attention(
                    a, b, c, mesh, causal=causal, use_flash=True,
                    block_q=16, block_k=16, interpret=True,
                )
            )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_full_attention(self, causal):
        mesh = make_mesh({"sp": 4, "dp": 2})
        q, k, v = self._qkv(seed=3)

        def loss_flash(a, b, c):
            return (
                ring_attention(
                    a, b, c, mesh, causal=causal, use_flash=True,
                    block_q=16, block_k=16, interpret=True,
                )
                ** 2
            ).mean()

        def loss_ref(a, b, c):
            return (dot_product_attention(a, b, c, causal=causal) ** 2).mean()

        with mesh:
            g_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), g_flash, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5, err_msg=name
            )

    def test_auto_dispatch_off_cpu(self):
        """use_flash=None must not pick the pallas path on CPU."""

        mesh = make_mesh({"sp": 4, "dp": 2})
        q, k, v = self._qkv(S=64)
        ref = dot_product_attention(q, k, v, causal=True)
        with mesh:
            out = jax.jit(
                lambda a, b, c: ring_attention(a, b, c, mesh, causal=True)
            )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_applicability_gate(self):
        from tf_operator_tpu.ops.ring_attention import _flash_ring_applicable

        q = jnp.zeros((2, 2, 256, 64))
        assert _flash_ring_applicable(q, 4, 16, 16)
        assert not _flash_ring_applicable(q, 4, 48, 16)  # 64 % 48 != 0
        assert not _flash_ring_applicable(q, 3, 16, 16)  # 256 % 3 != 0

    def test_explicit_use_flash_rejects_non_tiling(self):
        mesh = make_mesh({"sp": 4, "dp": 2})
        q, k, v = self._qkv(S=96)  # 24 per shard, not a multiple of 16
        with pytest.raises(ValueError, match="tile"):
            with mesh:
                ring_attention(
                    q, k, v, mesh, use_flash=True,
                    block_q=16, block_k=16, interpret=True,
                )

    def test_use_flash_short_circuits_on_indivisible_seq(self):
        """ADVICE r5 #3: when S % n != 0 there is NO per-shard length,
        so use_flash resolution must short-circuit — use_flash=True
        raises the divisibility error (not a block-tiling message
        computed against the fictitious global length), and auto mode
        never consults block resolution at all."""

        import importlib

        mesh = make_mesh({"sp": 4, "dp": 2})
        q, k, v = self._qkv(S=98)  # 98 % 4 != 0
        with pytest.raises(ValueError, match="does not divide"):
            with mesh:
                ring_attention(q, k, v, mesh, use_flash=True, interpret=True)
        # auto mode (use_flash=None) must not even resolve blocks
        # against the global length — the resolver is off-limits here
        fa = importlib.import_module("tf_operator_tpu.ops.flash_attention")

        def boom(*a, **kw):  # pragma: no cover - the assertion IS the call
            raise AssertionError(
                "resolve_flash_blocks consulted for an indivisible seq"
            )

        orig = fa.resolve_flash_blocks
        fa.resolve_flash_blocks = boom
        try:
            with mesh:
                # S=98 also doesn't shard over sp=4 for the XLA local
                # path's shard_map — expect the standard shard error,
                # NOT the planted AssertionError
                try:
                    ring_attention(q, k, v, mesh, interpret=True)
                except AssertionError:
                    raise
                except Exception:
                    pass
        finally:
            fa.resolve_flash_blocks = orig


class TestFlashRingBackward:
    """The pallas ring backward (gradient accumulators riding the ring)
    vs the TPU_OPERATOR_FLASH_BWD=0 XLA-recompute escape hatch: same
    gradients, two very different memory profiles."""

    def _qkv(self, B=2, H=2, S=128, D=64, seed=11):
        r = np.random.RandomState(seed)
        return (
            jnp.asarray(r.randn(B, H, S, D), jnp.float32) * 0.3,
            jnp.asarray(r.randn(B, H, S, D), jnp.float32) * 0.3,
            jnp.asarray(r.randn(B, H, S, D), jnp.float32),
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_bwd_matches_xla_recompute(self, causal, monkeypatch):
        mesh = make_mesh({"sp": 4, "dp": 2})
        q, k, v = self._qkv()

        def grads():
            def loss(a, b, c):
                return (
                    ring_attention(
                        a, b, c, mesh, causal=causal, use_flash=True,
                        block_q=16, block_k=16, interpret=True,
                    )
                    ** 2
                ).mean()

            with mesh:
                return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)

        monkeypatch.setenv("TPU_OPERATOR_FLASH_BWD", "1")
        g_pallas = grads()
        monkeypatch.setenv("TPU_OPERATOR_FLASH_BWD", "0")
        g_xla = grads()
        for name, a, b in zip("dq dk dv".split(), g_pallas, g_xla):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5, err_msg=name
            )

    def test_bf16_grads_close(self):
        mesh = make_mesh({"sp": 4, "dp": 2})
        q, k, v = (t.astype(jnp.bfloat16) for t in self._qkv(seed=5))

        def loss_flash(a, b, c):
            return (
                ring_attention(
                    a, b, c, mesh, causal=True, use_flash=True,
                    block_q=16, block_k=16, interpret=True,
                ).astype(jnp.float32)
                ** 2
            ).mean()

        def loss_ref(a, b, c):
            return (
                dot_product_attention(a, b, c, causal=True).astype(jnp.float32) ** 2
            ).mean()

        with mesh:
            g_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), g_flash, g_ref):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                atol=3e-2, rtol=3e-2, err_msg=name,
            )


class TestRingGQA:
    """GQA through the ring: K/V travel at Hkv width, expand per block."""

    def _qkv(self, B=4, H=8, HKV=2, S=32, D=8, seed=21):
        r = np.random.RandomState(seed)
        mk = lambda h: jnp.asarray(r.randn(B, h, S, D).astype(np.float32))
        return mk(H), mk(HKV), mk(HKV)

    @staticmethod
    def _ref(q, k, v, causal):
        g = q.shape[1] // k.shape[1]
        k, v = (jnp.repeat(a, g, axis=1) for a in (k, v))
        return dot_product_attention(q, k, v, causal=causal)

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_repeated_reference(self, causal):
        mesh = make_mesh({"sp": 4, "dp": -1})
        q, k, v = self._qkv()
        ref = self._ref(q, k, v, causal)
        with mesh:
            out = jax.jit(
                lambda a, b, c: ring_attention(a, b, c, mesh, causal=causal)
            )(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_gradients_match_repeated_reference(self):
        mesh = make_mesh({"sp": 4, "dp": -1})
        q, k, v = self._qkv()

        def loss_ring(a, b, c):
            with mesh:
                return (ring_attention(a, b, c, mesh, causal=True) ** 2).mean()

        def loss_ref(a, b, c):
            return (self._ref(a, b, c, True) ** 2).mean()

        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), g_ring, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5, err_msg=name
            )

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_ring_gqa_fwd_and_grads(self, causal):
        mesh = make_mesh({"sp": 4, "dp": 2})
        r = np.random.RandomState(22)
        q = jnp.asarray(r.randn(2, 4, 128, 64), jnp.float32) * 0.3
        k = jnp.asarray(r.randn(2, 2, 128, 64), jnp.float32) * 0.3
        v = jnp.asarray(r.randn(2, 2, 128, 64), jnp.float32)

        def loss_flash(a, b, c):
            return (
                ring_attention(
                    a, b, c, mesh, causal=causal, use_flash=True,
                    block_q=16, block_k=16, interpret=True,
                )
                ** 2
            ).mean()

        def loss_ref(a, b, c):
            return (self._ref(a, b, c, causal) ** 2).mean()

        with mesh:
            out = jax.jit(
                lambda a, b, c: ring_attention(
                    a, b, c, mesh, causal=causal, use_flash=True,
                    block_q=16, block_k=16, interpret=True,
                )
            )(q, k, v)
            g_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._ref(q, k, v, causal)),
            atol=2e-5, rtol=2e-5,
        )
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), g_flash, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5, err_msg=name
            )

    def test_rejects_indivisible_heads(self):
        mesh = make_mesh({"sp": 4, "dp": -1})
        q, k, v = self._qkv(H=8, HKV=3)
        with pytest.raises(ValueError, match="multiple"):
            ring_attention(q, k, v, mesh)


@pytest.mark.parametrize("w", [4, 12, 32])
def test_ring_window_matches_banded_reference(w):
    """Sliding window across chunk boundaries: the ring's global-offset
    mask must equal the single-device banded reference."""

    mesh = make_mesh({"sp": 4, "dp": -1})
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=True, window=w)
    with mesh:
        out = jax.jit(
            lambda a, b, c: ring_attention(a, b, c, mesh, causal=True, window=w)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_window_grads_match():
    mesh = make_mesh({"sp": 4, "dp": -1})
    q, k, v = _qkv(s=32)

    def loss_ref(a, b, c):
        return (dot_product_attention(a, b, c, causal=True, window=8) ** 2).sum()

    def loss_ring(a, b, c):
        with mesh:
            return (ring_attention(a, b, c, mesh, causal=True, window=8) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


class TestWindowFlashRing:
    """window x flash-ring (ADVICE r3 #1): hop classification — banded
    diagonal kernel, plain kernel for fully-in-band hops, XLA
    global-offset blocks for the <=2 boundary hops, skipped band-out
    hops — must equal the single-device banded reference exactly."""

    def _qkv(self, B=2, H=2, HKV=None, S=128, D=64, seed=9):
        r = np.random.RandomState(seed)
        hkv = HKV or H
        return (
            jnp.asarray(r.randn(B, H, S, D), jnp.float32) * 0.3,
            jnp.asarray(r.randn(B, hkv, S, D), jnp.float32) * 0.3,
            jnp.asarray(r.randn(B, hkv, S, D), jnp.float32),
        )

    # S=128 over sp=4 -> sq=32, past-hop deltas {32, 64, 96}.
    # w=8: banded diagonal + ONE boundary hop (delta 32 < 8+31) whose
    #   kept rows are 0-6; deltas 64/96 band-out.
    # w=40: two boundary hops (32, 64); 96 band-out.
    # w=70: delta 32 fully in band (plain kernel), 64 and 96 boundary.
    # w=120: deltas 32/64 fully in, 96 boundary (96+31 >= 120).
    # w=128: ALL past hops fully in band (96+31 < 128) — the all-plain-
    #   kernel class, equivalent to unwindowed causal.
    @pytest.mark.parametrize("w", [8, 40, 70, 120, 128])
    def test_forward_matches_banded_reference(self, w):
        mesh = make_mesh({"sp": 4, "dp": 2})
        q, k, v = self._qkv()
        ref = dot_product_attention(q, k, v, causal=True, window=w)
        with mesh:
            out = jax.jit(
                lambda a, b, c: ring_attention(
                    a, b, c, mesh, causal=True, window=w, use_flash=True,
                    block_q=16, block_k=16, interpret=True,
                )
            )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    @pytest.mark.parametrize("pallas_bwd", ["1", "0"])
    def test_grads_match_banded_reference(self, pallas_bwd, monkeypatch):
        """w=40 exercises every hop class in the BACKWARD too, on both
        the pallas ring backward and the XLA-recompute escape hatch."""

        monkeypatch.setenv("TPU_OPERATOR_FLASH_BWD", pallas_bwd)
        mesh = make_mesh({"sp": 4, "dp": 2})
        q, k, v = self._qkv(seed=13)

        def loss_flash(a, b, c):
            return (
                ring_attention(
                    a, b, c, mesh, causal=True, window=40, use_flash=True,
                    block_q=16, block_k=16, interpret=True,
                )
                ** 2
            ).mean()

        def loss_ref(a, b, c):
            return (dot_product_attention(a, b, c, causal=True, window=40) ** 2).mean()

        with mesh:
            g_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), g_flash, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5, err_msg=name
            )

    def test_gqa_window_flash_ring(self):
        """GQA: hkv-width K/V ride the ring; the boundary blocks expand
        per hop and fold gradients back to Hkv width."""

        mesh = make_mesh({"sp": 4, "dp": 2})
        q, k, v = self._qkv(H=4, HKV=2, seed=17)
        ref = dot_product_attention(q, k, v, causal=True, window=40)

        def loss_flash(a, b, c):
            return (
                ring_attention(
                    a, b, c, mesh, causal=True, window=40, use_flash=True,
                    block_q=16, block_k=16, interpret=True, heads_axis=None,
                )
                ** 2
            ).mean()

        def loss_ref(a, b, c):
            return (dot_product_attention(a, b, c, causal=True, window=40) ** 2).mean()

        with mesh:
            out = jax.jit(
                lambda a, b, c: ring_attention(
                    a, b, c, mesh, causal=True, window=40, use_flash=True,
                    block_q=16, block_k=16, interpret=True, heads_axis=None,
                )
            )(q, k, v)
            g_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), g_flash, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5, err_msg=name
            )


def test_ring_window_zero_rejected():
    mesh = make_mesh({"sp": 4, "dp": -1})
    q, k, v = _qkv()
    with pytest.raises(ValueError, match=">= 1"):
        ring_attention(q, k, v, mesh, causal=True, window=0)


@pytest.mark.parametrize("w", [8, 9, 10, 16, 17, 18])
def test_ring_window_hop_skip_boundaries(w):
    """Band edges landing exactly on chunk boundaries (sq=8 per shard):
    w=9 puts the chunk 2 hops back at min qpos-kpos = 8 = w-1 (exactly
    one visible diagonal), w=17 likewise 3 hops back — an off-by-one in
    the hop-skip threshold corrupts these and nothing else."""

    mesh = make_mesh({"sp": 4, "dp": -1})
    q, k, v = _qkv()  # s=32 -> 8 per shard
    ref = dot_product_attention(q, k, v, causal=True, window=w)
    with mesh:
        out = jax.jit(
            lambda a, b, c: ring_attention(a, b, c, mesh, causal=True, window=w)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def loss_ring(a, b, c):
        with mesh:
            return (ring_attention(a, b, c, mesh, causal=True, window=w) ** 2).sum()

    def loss_ref(a, b, c):
        return (dot_product_attention(a, b, c, causal=True, window=w) ** 2).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)
