"""DispatchLedger (utils/metrics.py): serving dispatch accounting.

Dispatch COUNTS are the load-bearing artifact — platform-independent
program-call counts that turn "tunnel overhead" into `count x RTT`
arithmetic (PROFILE.md "dispatch ledger").  These tests pin the
counting, the metrics sink (`serving_dispatch_*` on /metrics), and the
tracer sink (dispatch child spans in a request waterfall).  The
decoder-level invariants — the pool's exactly-one-admission-dispatch,
the chunked decoder's per-request counts — live with their decoders in
test_batching.py / test_decode.py.
"""

from tf_operator_tpu.utils.metrics import DispatchLedger, Metrics
from tf_operator_tpu.utils.trace import Tracer


def test_counts_and_seconds_accumulate():
    led = DispatchLedger()
    with led.dispatch("step"):
        pass
    led.record("step", 0.5, n=2)
    led.record("admission", 0.1)
    assert led.count("step") == 3
    assert led.count("admission") == 1
    assert led.count() == 4
    assert led.count("never") == 0
    snap = led.snapshot()
    assert snap["step"]["count"] == 3
    assert snap["step"]["seconds"] >= 0.5
    assert led.seconds("admission") == 0.1
    led.reset()
    assert led.count() == 0 and led.snapshot() == {}


def test_dispatch_records_on_exception_too():
    # a failing device call still consumed a round trip; the ledger
    # must not undercount the expensive path — and its span must be
    # marked FAILED (error status is what tail sampling protects)
    tracer = Tracer(seed=3)
    led = DispatchLedger(tracer=tracer)
    with tracer.span("serve.generate") as root:
        try:
            with led.dispatch("prefill"):
                raise RuntimeError("device OOM")
        except RuntimeError:
            pass
    assert led.count("prefill") == 1
    t = tracer.store.trace(root.trace_id)
    sp = next(s for s in t["spans"] if s["name"] == "dispatch.prefill")
    assert sp["status"] == "error"
    assert t["error"] is True


def test_metrics_sink_exports_counters_and_histograms():
    m = Metrics()
    led = DispatchLedger(metrics=m)
    with led.dispatch("admission"):
        pass
    with led.dispatch("admission"):
        pass
    with led.dispatch("step"):
        pass
    assert m.counter("serving_dispatch_total", phase="admission") == 2.0
    assert m.total("serving_dispatch_total") == 3.0
    expo = m.exposition()
    assert 'serving_dispatch_total{phase="admission"} 2.0' in expo
    assert 'serving_dispatch_seconds_count{phase="step"} 1' in expo


def test_tracer_sink_nests_dispatch_spans_under_request_span():
    tracer = Tracer(seed=7)
    led = DispatchLedger(tracer=tracer)
    with tracer.span("serve.generate") as root:
        with led.dispatch("decode", rid=3):
            pass
    t = tracer.store.trace(root.trace_id)
    assert t is not None
    spans = {s["name"]: s for s in t["spans"]}
    assert "dispatch.decode" in spans
    assert spans["dispatch.decode"]["parentId"] == root.span_id
    assert spans["dispatch.decode"]["attributes"]["rid"] == 3


def test_table_accounts_against_wall():
    led = DispatchLedger()
    led.record("step", 0.2)
    led.record("admission", 0.1)
    txt = led.table(wall=0.5)
    assert "| admission | 1 |" in txt
    assert "of 0.5 s wall" in txt
    # without a wall the totals row still renders
    assert "**all** | 2" in led.table()
