"""Round-end suite record + slow-tier budget gate (ISSUE 5 satellite,
VERDICT r5 next #8): the conftest tier classifier and the
check_tier_budget gate logic, on synthetic records."""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_tier_budget",
        os.path.join(REPO, "benchmarks", "check_tier_budget.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestTierClassifier:
    def test_markexpr_maps_to_tier(self):
        from tests.conftest import _session_tier

        class Cfg:
            def __init__(self, expr):
                self._expr = expr

            def getoption(self, name, default=None):
                return self._expr

        assert _session_tier(Cfg("not slow")) == "tier1"
        assert _session_tier(Cfg("slow")) == "slow"
        assert _session_tier(Cfg("slow and not tpu")) == "slow"
        assert _session_tier(Cfg("")) == "all"
        assert _session_tier(Cfg(None)) == "all"


class TestBudgetGate:
    def test_no_slow_record_passes(self):
        mod = _load_checker()
        ok, msg = mod.check({"tier1": {"wall_s": 150.0, "collected": 300,
                                       "exitstatus": 0, "when": "x"}})
        assert ok and "gate skipped" in msg

    def test_slow_within_budget_passes(self):
        mod = _load_checker()
        ok, msg = mod.check({"slow": {"wall_s": 900.0, "collected": 200,
                                      "exitstatus": 0, "when": "x"}})
        assert ok and "within budget" in msg

    def test_slow_over_budget_fails(self):
        mod = _load_checker()
        ok, msg = mod.check({"slow": {"wall_s": 5400.0, "collected": 200,
                                      "exitstatus": 0, "when": "x"}})
        assert not ok and "OVER BUDGET" in msg

    def test_red_tier_record_fails(self):
        """A failing tier (nonzero exitstatus) must not pass the gate
        on wall clock alone, even with no slow record."""

        mod = _load_checker()
        ok, msg = mod.check({"tier1": {"wall_s": 150.0, "collected": 300,
                                       "exitstatus": 1, "when": "x"}})
        assert not ok and "RED TIER RECORD" in msg and "exited 1" in msg

    def test_red_slow_record_fails_despite_budget(self):
        mod = _load_checker()
        ok, msg = mod.check({"slow": {"wall_s": 900.0, "collected": 200,
                                      "exitstatus": 2, "when": "x"}})
        assert not ok and "RED TIER RECORD" in msg

    def test_scheduler_soak_counts_gate(self):
        """ISSUE 16 satellite: when the slow record carries the
        contention soak's decision counts, zero admissions or zero
        preemptions reddens the gate — a soak that wedged silently must
        not pass on wall clock."""

        mod = _load_checker()
        base = {"wall_s": 900.0, "collected": 200, "exitstatus": 0,
                "when": "x"}
        ok, msg = mod.check({"slow": {
            **base,
            "schedulerSoak": {"admitted": 0, "preemptions": 0, "sweeps": 40},
        }})
        assert not ok and "SCHEDULER SOAK WEDGED" in msg
        ok, msg = mod.check({"slow": {
            **base,
            "schedulerSoak": {"admitted": 7, "preemptions": 0, "sweeps": 40},
        }})
        assert not ok and "SCHEDULER SOAK WEDGED" in msg
        ok, msg = mod.check({"slow": {
            **base,
            "schedulerSoak": {"admitted": 7, "preemptions": 3, "sweeps": 40},
        }})
        assert ok and "scheduler soak: 7 admissions" in msg
        # no soak key (older records, soak-less subsets): gate silent
        ok, msg = mod.check({"slow": base})
        assert ok and "scheduler soak" not in msg

    def test_record_suite_extra_merges_into_entry(self):
        """The conftest extras hook: record_suite_extra keys land in
        the tier entry dict shape sessionfinish writes."""

        from tests import conftest

        saved = dict(conftest._suite_extras)
        try:
            conftest._suite_extras.clear()
            conftest.record_suite_extra(
                "schedulerSoak", {"admitted": 3, "preemptions": 1}
            )
            entry = {"wall_s": 1.0, "exitstatus": 0, "collected": 1,
                     "when": "t", **conftest._suite_extras}
            assert entry["schedulerSoak"] == {
                "admitted": 3, "preemptions": 1
            }
        finally:
            conftest._suite_extras.clear()
            conftest._suite_extras.update(saved)

    def test_cli_exit_codes(self, tmp_path):
        """The gate as tooling: exit 0 without a record file."""

        env = dict(os.environ)
        env["TPUJOB_NO_SUITE_RECORD"] = "1"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "benchmarks",
                                          "check_tier_budget.py")],
            capture_output=True, text=True, cwd=str(tmp_path), env=env,
        )
        # record may or may not exist in the repo; either way the exit
        # code must reflect check()'s verdict, never crash
        assert proc.returncode in (0, 1)
        assert proc.stdout.strip()


class TestRecordWriting:
    def test_sessionfinish_merges_tiers(self, tmp_path, monkeypatch):
        """Drive the conftest hook body shape via a real JSON merge:
        a tier1 record then a slow record must coexist in the file."""

        path = tmp_path / "SUITE_RECORD.json"
        for tier, wall in (("tier1", 140.0), ("slow", 800.0)):
            record = {}
            if path.exists():
                record = json.loads(path.read_text())
            record[tier] = {"wall_s": wall, "exitstatus": 0,
                            "collected": 10, "when": "t"}
            path.write_text(json.dumps(record))
        final = json.loads(path.read_text())
        assert set(final) == {"tier1", "slow"}
        mod = _load_checker()
        ok, msg = mod.check(final)
        assert ok and "tier1" in msg and "slow" in msg
