"""Cross-pod KV fabric — slow tier (ISSUE 17 acceptance + chaos soak).

Two legs:

- CHAOS SOAK: a publisher pool's fabric server flakes (deterministic
  FaultInjector schedule: a burst of socket resets, then probabilistic
  resets/500s/index 503s) under a shared-prefix request stream on a
  puller pool.  Nothing wedges: every request completes, tokens stay
  byte-identical to a fabric-less reference pool (every failed pull
  degrades to recompute), the allocator balances, and the decision
  counts (pulls by outcome, failures by reason, bytes, injected
  faults) publish into SUITE_RECORD.
- LIVE E2E: two REAL serve_lm pods as kubesim subprocesses.  Pod A is
  fleet-entered by the reconciler-injected TPUJOB_FABRIC_PORT; its
  fabric address is discovered off the ``tpujob.dist/fabric-port``
  pod annotation (the PR 15 telemetry-port mechanics); pod B joins
  with --fabric-peers.  A prompt prefilled on pod A admits on pod B
  with a remote fabric pull: ZERO local prefill for the pulled prefix
  (ledger-pinned — migrated_blocks covers every full prefix block,
  exactly one migrate_in dispatch), steady-state decode exactly 1
  dispatch/step, and the tokens byte-identical to pod A's.
"""

import json
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # pool compiles + subprocess pods

import jax
import jax.numpy as jnp

from tests.conftest import record_suite_extra
from tests.testutil import new_job
from tf_operator_tpu.backend.kube import KubeBackend
from tf_operator_tpu.backend.kubejobs import KubeJobStore
from tf_operator_tpu.backend.kubesim import FaultInjector, MiniApiServer
from tf_operator_tpu.backend.retry import fabric_pull_policy
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.controller.reconciler import (
    ANNOTATION_FABRIC_PORT,
    ReconcilerConfig,
)
from tf_operator_tpu.models import llama_tiny
from tf_operator_tpu.models.batching import PagedContinuousBatchingDecoder
from tf_operator_tpu.models.fabric_service import (
    PULL_FAILURE_REASONS,
    FabricServer,
    FleetFabric,
)
from tf_operator_tpu.models.prefix_cache import PrefixFabric
from tf_operator_tpu.utils.metrics import Metrics

VOCAB = 96


def _setup(max_len=64):
    model = llama_tiny(vocab_size=VOCAB, max_len=max_len)
    init = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), init)["params"]
    return model, params


class _Drivers:
    """Step threads for pools whose submit/publish paths block."""

    def __init__(self, *pools):
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._drive, args=(p,), daemon=True)
            for p in pools
        ]

    def _drive(self, pool):
        while not self._stop.is_set():
            if pool.step() == 0:
                time.sleep(0.002)

    def __enter__(self):
        for t in self._threads:
            t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)
        return False


def test_chaos_soak_flaky_peer_never_wedges():
    model, params = _setup()
    r = np.random.RandomState(17)

    # publisher pod: local fabric + its wire server, chaos-injected
    mA = Metrics()
    fabA = FleetFabric(
        PrefixFabric(metrics=mA, model_label="t"),
        metrics=mA, model_label="t",
    )
    poolA = PagedContinuousBatchingDecoder(
        model, params, slots=4, kv_block_size=16, paged_kernel="off",
        metrics=mA, model_label="t", replica_label="a", fabric=fabA,
    )
    faults = FaultInjector(seed=23)
    srvA = FabricServer(fabA, faults=faults).start()

    # puller pod: knows the prefixes only through the wire
    mB = Metrics()
    fabB = FleetFabric(
        PrefixFabric(metrics=mB, model_label="t"),
        peers=[srvA.addr], metrics=mB, model_label="t",
        policy=fabric_pull_policy(base_delay=0.0, max_delay=0.0),
    )
    poolB = PagedContinuousBatchingDecoder(
        model, params, slots=4, kv_block_size=16, paged_kernel="off",
        metrics=mB, model_label="t", replica_label="b", fabric=fabB,
    )
    # fabric-less reference: the token-identity oracle under chaos
    poolC = PagedContinuousBatchingDecoder(
        model, params, slots=4, kv_block_size=16, paged_kernel="off",
        metrics=Metrics(), model_label="t", replica_label="c",
    )

    prefixes = [
        r.randint(0, VOCAB, size=(32,)).astype(np.int32)  # 2 blocks
        for _ in range(4)
    ]
    trace = []
    for i in range(16):
        tail = r.randint(0, VOCAB, size=(int(r.randint(3, 9)),))
        trace.append((
            np.concatenate([prefixes[i % 4], tail.astype(np.int32)]),
            int(r.choice([4, 8])),
        ))

    try:
        with _Drivers(poolA, poolB, poolC):
            # publish every prefix on A (internal prefill + migrate_out)
            for p in prefixes:
                pub = poolA.publish_to_fabric(p, timeout=300.0)
                assert pub["published"] == 2
            # chaos schedule: a deterministic reset burst first (one
            # whole retry budget dies → reason=peer_dead, guaranteed),
            # then seeded probabilistic flakiness for the stream
            faults.add(path="^/fabric/blocks/", mode="reset", times=3)
            faults.add(path="^/fabric/blocks/", mode="reset",
                       probability=0.25)
            faults.add(path="^/fabric/blocks/", mode="error",
                       status=500, probability=0.2)
            faults.add(path="^/fabric/index", mode="error",
                       status=503, probability=0.3)

            rids = []
            for j, (prompt, budget) in enumerate(trace):
                rids.append((
                    poolB.submit(prompt, budget, trace_id=f"soak-{j}"),
                    poolC.submit(prompt, budget),
                ))
            outs = [
                (poolB.result_wait(rb, timeout=300),
                 poolC.result_wait(rc, timeout=300))
                for rb, rc in rids
            ]
    finally:
        fabB.stop()
        fabA.stop()
        srvA.stop()

    # nothing wedged, nothing diverged
    for j, (ob, oc) in enumerate(outs):
        assert ob is not None and oc is not None, f"request {j} wedged"
        np.testing.assert_array_equal(
            np.asarray(ob), np.asarray(oc),
            err_msg=f"request {j}: chaos changed tokens",
        )
    poolB.alloc.check()
    poolA.alloc.check()

    snap = fabB.snapshot()
    assert snap["pulls"]["hit"] >= 1, "no pull ever landed"
    # the deterministic reset burst consumed one full retry budget
    assert snap["pull_failures"].get("peer_dead", 0) >= 1
    assert set(snap["pull_failures"]) <= set(PULL_FAILURE_REASONS)
    assert faults.total_injected() >= 4
    # remote-pulled bytes really crossed the wire meter
    assert mB.counter(
        "kv_migrate_bytes_total", direction="in", transport="http"
    ) > 0

    record_suite_extra("fabricChaosSoak", {
        "requests": len(trace),
        "pulls": snap["pulls"],
        "pullFailures": snap["pull_failures"],
        "bytesPulled": snap["bytes_pulled"],
        "faultsInjected": faults.total_injected(),
    })


# ---------------------------------------------------------------- live e2e


def _export_artifact(tmp_path):
    """Train one step of the byte-level tiny llama and export — a real
    artifact for the serve_lm subprocesses."""

    from tf_operator_tpu.models import llama_loss
    from tf_operator_tpu.parallel import (
        Trainer, TrainerConfig, export_params, make_mesh,
    )

    mesh = make_mesh({"dp": 8})  # conftest's 8-device CPU mesh
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, 256, size=(8, 16)), jnp.int32
    )
    tr = Trainer(
        llama_tiny(vocab_size=256, max_len=128, mesh=mesh),
        TrainerConfig(optimizer="sgd", learning_rate=1e-2),
        mesh,
        llama_loss,
        {"input_ids": ids},
        init_args=(ids,),
        shardings="logical",
    )
    tr.train_step(tr.shard_batch({"input_ids": ids}))
    art = str(tmp_path / "artifact")
    export_params(tr, art)
    return art


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _post(port, payload, timeout=300):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(payload).encode(),
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _wait_healthz(port, backend, pod, deadline_s=240.0):
    deadline = time.time() + deadline_s
    while True:
        try:
            if _get(f"http://127.0.0.1:{port}/healthz", timeout=2)["ok"]:
                return
        except Exception:
            if time.time() > deadline:
                raise AssertionError(
                    f"{pod}: healthz never came up; log tail: "
                    + backend.pod_log("default", pod)[-800:]
                )
            time.sleep(1.0)


def test_two_pod_fleet_remote_pull_e2e(tmp_path):
    """The acceptance path over a REAL wire: serve_lm pod A prefills
    and publishes, serve_lm pod B (peered via the reconciler-stamped
    fabric-port annotation) serves the same prompt with a remote pull
    instead of a local prefill — byte-identical tokens, ledger-pinned
    dispatch accounting."""

    art = _export_artifact(tmp_path)
    port_a, port_b = _free_port(), _free_port()
    serve = [
        sys.executable,
        str(__import__("pathlib").Path(__file__).resolve().parent.parent
            / "examples" / "serve_lm.py"),
        "--artifact", art, "--platform", "cpu", "--batching", "2",
    ]

    sim = MiniApiServer().start()
    store = KubeJobStore(sim.url)
    backend = KubeBackend(sim.url)
    controller = TPUJobController(
        store, backend, config=ReconcilerConfig(resolver=backend.resolver)
    )
    controller.run(threadiness=2)

    def pods(job):
        return backend.list_pods(
            "default", {"tpujob.dist/job-name": job}
        )

    try:
        # pod A: fleet-entered by the reconciler-injected
        # TPUJOB_FABRIC_PORT env (announce-only — no peers yet)
        store.create(new_job(
            name="fab-a", worker=1,
            command=serve + ["--port", str(port_a)],
        ))

        deadline = time.time() + 30
        while time.time() < deadline and len(pods("fab-a")) < 1:
            time.sleep(0.1)
        (pod_a,) = pods("fab-a")
        fabric_port = pod_a.metadata.annotations[ANNOTATION_FABRIC_PORT]
        _wait_healthz(port_a, backend, "fab-a-worker-0")

        # 65 tokens: 4 FULL publishable blocks + the always-computed
        # final token (the (len-1)//16 rule)
        prompt = ("the fleet-wide shared system prompt rides the kv "
                  "fabric wire" + "!" * 65)[:65]
        assert len(prompt) == 65
        out_a = _post(port_a, {"prompt": prompt, "max_new_tokens": 8})
        assert len(out_a["sample"]) == 8

        # the annotation is truthful: pod A's fabric server answers on
        # the stamped port with the published chain
        idx = _get(f"http://127.0.0.1:{fabric_port}/fabric/index")
        assert len(idx["keys"]) >= 4
        assert idx["generation"] >= 4

        # pod B: same artifact, peered at pod A's DISCOVERED address
        store.create(new_job(
            name="fab-b", worker=1,
            command=serve + [
                "--port", str(port_b),
                "--fabric-peers", f"127.0.0.1:{fabric_port}",
            ],
        ))
        deadline = time.time() + 30
        while time.time() < deadline and len(pods("fab-b")) < 1:
            time.sleep(0.1)
        _wait_healthz(port_b, backend, "fab-b-worker-0")

        out_b = _post(port_b, {"prompt": prompt, "max_new_tokens": 8})
        # TOKEN IDENTITY: the pulled prefix decodes byte-identically
        assert out_b["sample"] == out_a["sample"]

        # DISPATCH ACCOUNTING (ledger-pinned): every full prefix block
        # arrived via ONE migrate_in — zero local prefill for it — and
        # steady-state decode stayed exactly 1 dispatch/step
        a = _get(
            f"http://127.0.0.1:{port_b}/requests/{out_b['request_id']}"
        )
        assert a["migrated_blocks"] == (len(prompt) - 1) // 16 == 4
        assert a["pulled_blocks"] == 4
        assert a["fabric_peer"] == f"127.0.0.1:{fabric_port}"
        assert a["dispatches"].get("migrate_in") == 1
        assert a["dispatches"].get("admission", 0) <= 1  # tail token only
        assert "prefill" not in a["dispatches"]
        assert a["windows"] == a["dispatches"]["step"]

        # METRICS: remote hits + bytes by transport on pod B's /metrics
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port_b}/metrics", timeout=10
        ) as r:
            text = r.read().decode()
        assert 'kv_fabric_pulls_total{model="llama",outcome="hit"} 4.0' \
            in text
        assert 'kv_migrate_bytes_total{direction="in",transport="http"}' \
            in text
        assert 'kv_fabric_peer_up{peer="127.0.0.1:' in text

        # /debug/fabric: the CLI/dashboard read shows the peer up and
        # the pull ledger
        fab = _get(f"http://127.0.0.1:{port_b}/debug/fabric")["fabric"]
        assert fab["pulls"]["hit"] == 4
        assert fab["bytes_pulled"] > 0
        assert [p["up"] for p in fab["peers"]] == [True]

        # pod A never pulled anything — it is the publisher
        fab_a = _get(f"http://127.0.0.1:{port_a}/debug/fabric")["fabric"]
        assert fab_a["pulls"]["hit"] == 0
        assert fab_a["publishes"] >= 4
    finally:
        for job in ("fab-a", "fab-b"):
            try:
                store.delete("default", job)
            except Exception:
                pass
        deadline = time.time() + 20
        while time.time() < deadline and (
            pods("fab-a") or pods("fab-b")
        ):
            time.sleep(0.2)
        controller.stop()
        backend.close()
        store.close()
        sim.stop()
        # belt and braces: a leaked serving subprocess would outlive
        # the suite
        for port in (port_a, port_b):
            subprocess.run(
                ["pkill", "-9", "-f", f"serve_lm.py.*--port {port}"],
                check=False,
            )
