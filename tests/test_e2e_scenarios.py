"""Tier-3 e2e scenario suite against the local-process backend.

Parity: the reference's Python e2e harness scenario list (SURVEY.md §4
tier 3: simple/shutdown/cleanpod/restart/invalid/pod-names/runconfig/
distributed-training), run 1:1 against real subprocesses instead of a
GKE cluster.  test_e2e_local.py covers simple + restart; this file adds
the rest.
"""

import sys
import time

import pytest

from tests.testutil import new_job
from tf_operator_tpu.api.types import (
    CleanPodPolicy,
    JobConditionType,
    PodPhase,
    ReplicaType,
    SuccessPolicy,
)
from tf_operator_tpu.backend.jobstore import JobStore
from tf_operator_tpu.backend.local import LocalProcessBackend
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.controller.reconciler import ReconcilerConfig

from tests.test_e2e_local import EXAMPLE, cpu_env, wait_for  # noqa: F401

import os

DIST_MNIST = os.path.join(os.path.dirname(EXAMPLE), "dist_mnist.py")

SLEEP = [sys.executable, "-c", "import time; time.sleep(600)"]
EXIT0 = [sys.executable, "-c", "raise SystemExit(0)"]

RUNCONFIG_CHECK = [
    sys.executable,
    "-c",
    (
        "import os, json\n"
        "cfg = json.loads(os.environ['TF_CONFIG'])\n"
        "assert len(cfg['cluster']['chief']) == 1, cfg\n"
        "assert len(cfg['cluster']['worker']) == 2, cfg\n"
        "assert cfg['task']['type'] in ('chief', 'worker'), cfg\n"
        "assert cfg['environment'] == 'cloud'\n"
        "assert int(os.environ['TPUJOB_NUM_PROCESSES']) == 3\n"
        "assert 'TPUJOB_COORDINATOR_ADDRESS' in os.environ\n"
        "print('runconfig ok', cfg['task'], flush=True)\n"
    ),
]


@pytest.fixture(params=["local", "kube-sim"])
def local_harness(request):
    """Every scenario runs twice: against the in-proc local-process
    backend AND against the kube-sim pair — KubeBackend speaking real
    Kubernetes HTTP (CRUD + labelSelector + chunked watch) to the
    embedded mini apiserver whose kubelet sim runs the same
    subprocesses (VERDICT r4 next #4: the client-go tier, executable)."""

    sim = None
    if request.param == "local":
        store = JobStore()
        backend = LocalProcessBackend()
    else:
        from tf_operator_tpu.backend.kube import KubeBackend
        from tf_operator_tpu.backend.kubejobs import KubeJobStore
        from tf_operator_tpu.backend.kubesim import MiniApiServer

        sim = MiniApiServer().start()
        # the FULL kube stack: jobs as apiserver custom resources, pods
        # through the protocol backend — every scenario then exercises
        # the async watch-fed path end to end
        store = KubeJobStore(sim.url)
        backend = KubeBackend(sim.url)
    controller = TPUJobController(
        store, backend, config=ReconcilerConfig(resolver=backend.resolver)
    )
    controller.run(threadiness=2)
    yield store, backend, controller
    controller.stop()
    backend.close()
    store_close = getattr(store, "close", None)
    if store_close:
        store_close()
    if sim is not None:
        sim.stop()


def wait_no_pods(backend, ns="default", timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if not backend.list_pods(ns):
            return
        time.sleep(0.1)
    raise TimeoutError(f"pods remain: {[p.metadata.name for p in backend.list_pods(ns)]}")


@pytest.mark.slow
class TestShutdownPolicy:
    """shutdown_policy_tests parity: which replica's exit finishes the job."""

    def test_shutdown_policies_share_one_harness(self, local_harness):
        """Both shutdown scenarios ride ONE harness boot (VERDICT r5
        next #8: many subprocess scenarios booted the same harness —
        independent jobs can share it): chief-exit-succeeds and
        all-workers-policy run as two concurrent jobs."""

        store, backend, c = local_harness
        # scenario A: chief exit finishes the job while workers run
        job = new_job(name="sd-chief", chief=1, worker=2, command=EXIT0)
        job.spec.replica_specs[ReplicaType.WORKER].template.containers[0].command = list(SLEEP)
        store.create(job)
        # scenario B: ALL_WORKERS success waits for every worker
        job2 = new_job(name="sd-all", worker=2, command=EXIT0)
        job2.spec.success_policy = SuccessPolicy.ALL_WORKERS
        # worker-1 sleeps briefly so success requires more than worker-0
        job2.spec.replica_specs[ReplicaType.WORKER].template.containers[0].command = [
            sys.executable,
            "-c",
            "import os, time; time.sleep(1.5 * int(os.environ['TPUJOB_REPLICA_INDEX'])); raise SystemExit(0)",
        ]
        store.create(job2)

        done = wait_for(
            store, "default", "sd-chief",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED), timeout=30.0,
        )
        assert done.status.condition(JobConditionType.SUCCEEDED).reason == "JobSucceeded"
        done2 = wait_for(
            store, "default", "sd-all",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED), timeout=30.0,
        )
        assert done2.status.replica_statuses[ReplicaType.WORKER].succeeded == 2
        # CleanPodPolicy default (Running): sd-chief's sleeping workers
        # get killed; the already-terminal chief pod is kept for
        # inspection (sd-all's pods are terminal and also kept)
        want = {"sd-chief-chief-0", "sd-all-worker-0", "sd-all-worker-1"}
        deadline = time.time() + 15
        while time.time() < deadline:
            names = {p.metadata.name for p in backend.list_pods("default")}
            if names == want:
                break
            time.sleep(0.1)
        names = {p.metadata.name for p in backend.list_pods("default")}
        assert names == want
        assert backend.get_pod("default", "sd-chief-chief-0").phase is PodPhase.SUCCEEDED


@pytest.mark.slow
class TestCleanPodPolicy:
    """cleanpod_policy_tests parity on real processes."""

    def test_none_and_all_policies_share_one_harness(self, local_harness):
        """NONE-keeps-pods and ALL-removes-pods ride one harness boot
        as two concurrent jobs (VERDICT r5 next #8 boot collapse)."""

        store, backend, c = local_harness
        job = new_job(name="cp-none", chief=1, worker=1, command=EXIT0)
        job.spec.replica_specs[ReplicaType.WORKER].template.containers[0].command = list(SLEEP)
        job.spec.run_policy.clean_pod_policy = CleanPodPolicy.NONE
        store.create(job)
        job2 = new_job(name="cp-all", chief=1, worker=1, command=EXIT0)
        job2.spec.run_policy.clean_pod_policy = CleanPodPolicy.ALL
        store.create(job2)

        wait_for(
            store, "default", "cp-none",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED), timeout=30.0,
        )
        wait_for(
            store, "default", "cp-all",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED), timeout=30.0,
        )
        # ALL: every cp-all pod (terminal included) is removed
        deadline = time.time() + 15
        while time.time() < deadline:
            if not any(
                p.metadata.name.startswith("cp-all-")
                for p in backend.list_pods("default")
            ):
                break
            time.sleep(0.1)
        names = {p.metadata.name for p in backend.list_pods("default")}
        assert not any(n.startswith("cp-all-") for n in names)
        # NONE: the sleeping worker stays alive
        assert "cp-none-worker-0" in names
        store.delete("default", "cp-none")  # owner GC still collects
        wait_no_pods(backend)


@pytest.mark.slow
class TestPodNames:
    """pod_names_validation_tests parity: the naming contract."""

    def test_expected_pod_and_service_names(self, local_harness):
        store, backend, c = local_harness
        job = new_job(name="names", chief=1, ps=2, worker=2, command=SLEEP)
        store.create(job)
        wait_for(
            store, "default", "names",
            lambda j: j.status.has_condition(JobConditionType.RUNNING), timeout=30.0,
        )
        pods = {p.metadata.name for p in backend.list_pods("default")}
        assert pods == {
            "names-chief-0",
            "names-ps-0",
            "names-ps-1",
            "names-worker-0",
            "names-worker-1",
        }
        svcs = {s.metadata.name for s in backend.list_services("default")}
        assert svcs == pods
        store.delete("default", "names")
        wait_no_pods(backend)


def wait_for_log(backend, pod, needle, ns="default", timeout=20.0):
    """Poll a pod's log until `needle` appears.  Asserting logs right at
    job-success time races slow-starting peers (VERDICT r3 weak #4: under
    parallel load the chief can finish before a worker ever prints)."""

    deadline = time.time() + timeout
    last = ""
    while time.time() < deadline:
        try:
            last = backend.pod_log(ns, pod)
        except Exception:
            last = ""
        if needle in last:
            return last
        time.sleep(0.1)
    raise AssertionError(f"{needle!r} never appeared in {pod} log: {last!r}")


@pytest.mark.slow
class TestRunConfig:
    """estimator_runconfig_tests parity: training code sees a coherent
    TF_CONFIG + TPUJOB_* env.

    Success-policy note (reference semantics, pinned by the plan truth
    table): when a chief exists, the CHIEF's exit decides the job —
    ALL_WORKERS applies to worker-only jobs.  So the job here can
    Succeed while a slow-starting worker is still booting; the log
    asserts therefore *wait* for each worker's output instead of
    reading at success time, and CleanPodPolicy None keeps the
    still-running workers alive to produce it (the round-3 parallel-run
    flake was exactly this race)."""

    def test_tf_config_visible_and_consistent(self, local_harness):
        store, backend, c = local_harness
        job = new_job(name="runcfg", chief=1, worker=2, command=RUNCONFIG_CHECK)
        job.spec.run_policy.clean_pod_policy = CleanPodPolicy.NONE
        store.create(job)
        wait_for(
            store, "default", "runcfg",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED), timeout=30.0,
        )
        for pod in ("runcfg-chief-0", "runcfg-worker-0", "runcfg-worker-1"):
            assert "runconfig ok" in wait_for_log(backend, pod, "runconfig ok")
        store.delete("default", "runcfg")
        wait_no_pods(backend)


EVALUATOR_CHECK = [
    sys.executable,
    "-c",
    (
        "import os, json\n"
        "cfg = json.loads(os.environ['TF_CONFIG'])\n"
        "assert cfg['task']['type'] == 'evaluator', cfg\n"
        "assert len(cfg['cluster']['evaluator']) == 1, cfg\n"
        "assert len(cfg['cluster']['chief']) == 1, cfg\n"
        "assert 'TPUJOB_COORDINATOR_ADDRESS' in os.environ\n"
        "print('evaluator ok', flush=True)\n"
        "import time; time.sleep(600)\n"
    ),
]


@pytest.mark.slow
class TestEvaluatorReplica:
    """estimator_runconfig_tests parity for the EVALUATOR replica type
    (VERDICT r3 next #5): it runs alongside chief/workers with its own
    TF_CONFIG task, and the success policy ignores it — the chief's
    exit finishes the job while the evaluator is still running
    (reference semantics: evaluators observe training; they never gate
    job completion)."""

    def test_evaluator_env_and_success_policy_ignores_it(self, local_harness):
        store, backend, c = local_harness
        job = new_job(name="ev", chief=1, worker=1, evaluator=1, command=EXIT0)
        job.spec.replica_specs[ReplicaType.EVALUATOR].template.containers[
            0
        ].command = list(EVALUATOR_CHECK)
        job.spec.run_policy.clean_pod_policy = CleanPodPolicy.NONE
        store.create(job)
        # the evaluator sees its own role in TF_CONFIG, inside the pod
        wait_for_log(backend, "ev-evaluator-0", "evaluator ok")
        done = wait_for(
            store, "default", "ev",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED), timeout=30.0,
        )
        # success came from the chief; the evaluator is STILL running
        ev_pod = backend.get_pod("default", "ev-evaluator-0")
        assert ev_pod.phase is PodPhase.RUNNING
        ev_status = done.status.replica_statuses[ReplicaType.EVALUATOR]
        assert ev_status.active == 1 and ev_status.succeeded == 0
        store.delete("default", "ev")
        wait_no_pods(backend)


PS_WORKER_CHECK = [
    sys.executable,
    "-c",
    (
        "import os, json\n"
        "cfg = json.loads(os.environ['TF_CONFIG'])\n"
        "assert len(cfg['cluster']['ps']) == 2, cfg\n"
        "assert len(cfg['cluster']['worker']) == 1, cfg  # sparse: own entry only\n"
        "assert cfg['task'] == {'type': 'worker', 'index': 0}, cfg\n"
        "print('ps-spec ok', cfg['cluster']['worker'][0], flush=True)\n"
    ),
]

PS_SERVER = [
    sys.executable,
    "-c",
    (
        "import os, json\n"
        "cfg = json.loads(os.environ['TF_CONFIG'])\n"
        "assert cfg['task']['type'] == 'ps', cfg\n"
        "assert len(cfg['cluster']['worker']) == 2, cfg  # PS keeps the full view\n"
        "print('ps-server up', flush=True)\n"
        "import time; time.sleep(600)\n"  # server.join() analogue
    ),
]


@pytest.mark.slow
class TestPSTopology:
    """A PS-topology job (2 PS + 2 workers) actually running through the
    local backend (VERDICT r3 next #5 / weak #8): PS pods hold a
    server-join loop, workers see the SPARSE cluster spec in-process
    (full ps list, own-entry worker list — bootstrap/cluster_spec.py),
    and worker-0's exit finishes the job per the no-chief default
    policy, tearing the parameter servers down."""

    def test_ps_job_runs_with_sparse_spec(self, local_harness):
        store, backend, c = local_harness
        job = new_job(name="psjob", ps=2, worker=2, command=PS_WORKER_CHECK)
        job.spec.replica_specs[ReplicaType.PS].template.containers[0].command = list(
            PS_SERVER
        )
        job.spec.run_policy.clean_pod_policy = CleanPodPolicy.NONE
        store.create(job)
        for pod in ("psjob-ps-0", "psjob-ps-1"):
            wait_for_log(backend, pod, "ps-server up")
        own_addrs = set()
        for pod in ("psjob-worker-0", "psjob-worker-1"):
            log = wait_for_log(backend, pod, "ps-spec ok")
            own_addrs.add(log.split("ps-spec ok", 1)[1].split()[0])
        # each worker's single sparse entry is its OWN address
        assert len(own_addrs) == 2, own_addrs
        done = wait_for(
            store, "default", "psjob",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED), timeout=30.0,
        )
        # no chief → default policy: worker-0's exit decided the job
        # while the parameter servers were still serving
        assert done.status.replica_statuses[ReplicaType.PS].active == 2
        store.delete("default", "psjob")
        wait_no_pods(backend)


@pytest.mark.slow
class TestInvalidJobs:
    """invalid_tfjob_tests parity: admission rejects bad specs."""

    def test_rejected_at_admission(self, local_harness):
        store, _, _ = local_harness
        bad = new_job(name="inv", worker=1)
        bad.spec.replica_specs[ReplicaType.WORKER].template.containers = []
        with pytest.raises(ValueError):
            store.create(bad)
        bad2 = new_job(name="inv2", chief=1, master=1, worker=1)
        with pytest.raises(ValueError):
            store.create(bad2)
        assert store.list() == []


class TestManifests:
    """The five BASELINE target-config manifests parse, default, and
    validate (the CRD-admission path for every shipped example)."""

    MANIFEST_DIR = os.path.join(os.path.dirname(EXAMPLE), "manifests")

    @pytest.mark.parametrize(
        "fname",
        [
            "dist_mnist.yaml",
            "dist_mnist_ps.yaml",
            "resnet_mwms.yaml",
            "bert_ps_analogue.yaml",
            "resnet_horovod_gang.yaml",
            "t5_multihost.yaml",
        ],
    )
    def test_manifest_admission(self, fname):
        import yaml

        from tf_operator_tpu.api.defaults import set_defaults
        from tf_operator_tpu.api.serde import job_from_dict, job_to_dict
        from tf_operator_tpu.api.validation import validate

        with open(os.path.join(self.MANIFEST_DIR, fname)) as f:
            manifest = yaml.safe_load(f)
        job = job_from_dict(manifest)
        set_defaults(job)
        validate(job)
        # round-trips through the wire shape
        again = job_from_dict(job_to_dict(job))
        assert again.spec.total_replicas() == job.spec.total_replicas()

    def test_gang_manifest_requests_gang(self):
        import yaml

        from tf_operator_tpu.api.serde import job_from_dict

        with open(os.path.join(self.MANIFEST_DIR, "resnet_horovod_gang.yaml")) as f:
            job = job_from_dict(yaml.safe_load(f))
        assert job.spec.enable_gang_scheduling
        assert int(job.spec.replica_specs[ReplicaType.WORKER].replicas) == 8


@pytest.mark.slow
class TestMultiHostSharding:
    """The PS-analogue (BASELINE config 3): params fully sharded across
    two real processes; XLA reduce-scatter/all-gather over gloo stand in
    for PS push/pull."""

    def test_bert_fsdp_across_two_processes(self, local_harness):
        store, backend, c = local_harness
        cmd = [
            sys.executable, os.path.join(os.path.dirname(EXAMPLE), "bert_pretrain.py"),
            "--model", "bert_tiny", "--steps", "6",
            "--batch-per-device", "2", "--seq-len", "32",
        ]
        job = new_job(name="bertfsdp", worker=2, command=cmd)
        job.spec.success_policy = SuccessPolicy.ALL_WORKERS
        # one device per process (don't inherit conftest's 8-device flag):
        # the mesh must span the two processes, not 16 virtual devices
        job.spec.replica_specs[ReplicaType.WORKER].template.containers[0].env = {
            **cpu_env(),
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        }
        store.create(job)
        done = wait_for(
            store, "default", "bertfsdp",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED), timeout=120.0,
        )
        assert done.status.replica_statuses[ReplicaType.WORKER].succeeded == 2
        log = backend.pod_log("default", "bertfsdp-worker-0")
        assert "fsdp=2" in log and "loss" in log

    def test_t5_tensor_parallel_across_two_processes(self, local_harness):
        """BASELINE config 5 shape: tp spans the two processes, so the
        batch replicates across tp replicas — shard_global_batch must
        keep them bit-identical (identical losses on both ranks)."""

        store, backend, c = local_harness
        cmd = [
            sys.executable, os.path.join(os.path.dirname(EXAMPLE), "t5_multihost.py"),
            "--model", "t5_tiny", "--steps", "6", "--batch-per-device", "2",
            "--enc-len", "16", "--dec-len", "8", "--tp", "2",
        ]
        job = new_job(name="t5tp", worker=2, command=cmd)
        job.spec.success_policy = SuccessPolicy.ALL_WORKERS
        job.spec.replica_specs[ReplicaType.WORKER].template.containers[0].env = {
            **cpu_env(),
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        }
        store.create(job)
        done = wait_for(
            store, "default", "t5tp",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED), timeout=120.0,
        )
        assert done.status.replica_statuses[ReplicaType.WORKER].succeeded == 2
        logs = [
            backend.pod_log("default", f"t5tp-worker-{i}") for i in (0, 1)
        ]
        assert all("tp=2" in log for log in logs)
        # both ranks print the same replicated loss trajectory
        import re

        pairs = [re.search(r"loss ([\d.]+) -> ([\d.]+)", log).groups() for log in logs]
        assert pairs[0] == pairs[1]

    def test_shard_batch_guard_fires_on_replicating_mesh(self, local_harness):
        """The footgun the guard exists for: a tp-spanning mesh with
        NO data axis across the two processes.  shard_batch must raise
        (disjoint local data would be treated as bit-identical
        replicas — silently wrong gradients); shard_global_batch with
        an identical batch then trains fine in the same world."""

        script = (
            "from tf_operator_tpu.runtime import initialize\n"
            "initialize()\n"
            "import jax, numpy as np, jax.numpy as jnp\n"
            "from tf_operator_tpu.models import gpt_tiny, lm_loss\n"
            "from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh\n"
            "mesh = make_mesh({'tp': 2})\n"
            "ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4, 16)))\n"
            "tr = Trainer(gpt_tiny(vocab_size=64, max_len=16, mesh=mesh),\n"
            "             TrainerConfig(), mesh, lm_loss, {'input_ids': ids},\n"
            "             init_args=(ids,), shardings='logical')\n"
            "local = jnp.asarray(np.random.RandomState(jax.process_index())\n"
            "                    .randint(0, 64, (4, 16)))\n"
            "try:\n"
            "    tr.shard_batch({'input_ids': local})\n"
            "    raise SystemExit('guard did not fire')\n"
            "except ValueError as e:\n"
            "    assert 'shard_global_batch' in str(e), e\n"
            "    print('guard ok', flush=True)\n"
            "m = tr.train_step(tr.shard_global_batch({'input_ids': ids}))\n"
            "print('tp step ok', float(m['loss']), flush=True)\n"
        )
        store, backend, c = local_harness
        job = new_job(
            name="guard", worker=2, command=[sys.executable, "-c", script]
        )
        job.spec.success_policy = SuccessPolicy.ALL_WORKERS
        job.spec.replica_specs[ReplicaType.WORKER].template.containers[0].env = {
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        }
        store.create(job)
        done = wait_for(
            store, "default", "guard",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED),
            timeout=120.0,
        )
        assert done.status.replica_statuses[ReplicaType.WORKER].succeeded == 2
        for i in (0, 1):
            log = backend.pod_log("default", f"guard-worker-{i}")
            assert "guard ok" in log and "tp step ok" in log


@pytest.mark.slow
class TestDistributedTraining:
    """distributed_training_tests parity: a real multi-process training
    run (dist-mnist, BASELINE config 1: 1 chief + 2 workers, CPU)."""

    def test_dist_mnist_1chief_2workers(self, local_harness):
        store, backend, c = local_harness
        cmd = [sys.executable, DIST_MNIST, "--steps", "8", "--batch-size", "24"]
        job = new_job(name="mnist", chief=1, worker=2, command=cmd)
        for rt in (ReplicaType.CHIEF, ReplicaType.WORKER):
            job.spec.replica_specs[rt].template.containers[0].env = cpu_env()
        store.create(job)
        # chief-decides semantics (reference parity): the chief's exit 0
        # marks the job Succeeded even if workers are a beat behind.
        # 240s: a 3-process jax.distributed world on a 1-core box under
        # full-suite load needs the headroom (120s flaked on kube-sim)
        done = wait_for(
            store, "default", "mnist",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED), timeout=240.0,
        )
        st = done.status.replica_statuses
        assert st[ReplicaType.CHIEF].succeeded == 1
        log = backend.pod_log("default", "mnist-chief-0")
        assert "loss" in log and "0/3" in log


@pytest.mark.slow
class TestSummariesManifest:
    def test_mnist_summaries_manifest_end_to_end(self, local_harness, tmp_path):
        """Submit the mnist_summaries manifest (summary-dir annotation
        rewritten to tmp), run to Succeeded, and read the series back
        through the same path the dashboard/CLI use."""

        import glob as _glob

        import yaml

        from tf_operator_tpu.api.serde import job_from_dict
        from tf_operator_tpu.utils.summaries import (
            ANNOTATION_SUMMARY_DIR,
            read_series,
        )

        repo = os.path.dirname(os.path.dirname(EXAMPLE))
        manifest = os.path.join(repo, "examples", "manifests", "mnist_summaries.yaml")
        with open(manifest) as f:
            doc = yaml.safe_load(f)
        sdir = str(tmp_path / "series")
        doc["metadata"]["annotations"][ANNOTATION_SUMMARY_DIR] = sdir
        spec = doc["spec"]["tpuReplicaSpecs"]["Worker"]["template"]["spec"]
        cmd = spec["containers"][0]["command"]
        cmd[0] = sys.executable
        cmd[cmd.index("--summary-dir") + 1] = sdir
        cmd[cmd.index("examples/mnist_with_summaries.py")] = os.path.join(
            repo, "examples", "mnist_with_summaries.py"
        )

        store, backend, c = local_harness
        job = job_from_dict(doc)
        store.create(job)
        wait_for(
            store, "default", "mnist-summaries",
            lambda j: j.status.has_condition(JobConditionType.SUCCEEDED),
            timeout=120.0,
        )
        series = read_series(sdir)
        assert series, "no step series written"
        assert all("loss" in m for m in series)
        # both worker processes wrote their own file
        assert len(_glob.glob(os.path.join(sdir, "metrics-*.jsonl"))) == 2


def _export_serving_artifact(tmp_path):
    """Train one step of the byte-level tiny llama and export it — a
    real artifact for serve_lm to load (shared by the serving e2e
    scenarios)."""

    import numpy as np

    import jax.numpy as jnp
    from tf_operator_tpu.models import llama_loss, llama_tiny
    from tf_operator_tpu.parallel import (
        Trainer, TrainerConfig, export_params, make_mesh,
    )

    mesh = make_mesh({"dp": 8})  # conftest's 8-device CPU mesh
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, 256, size=(8, 16)), jnp.int32
    )
    tr = Trainer(
        llama_tiny(vocab_size=256, max_len=64, mesh=mesh),
        TrainerConfig(optimizer="sgd", learning_rate=1e-2),
        mesh,
        llama_loss,
        {"input_ids": ids},
        init_args=(ids,),
        shardings="logical",
    )
    tr.train_step(tr.shard_batch({"input_ids": ids}))
    art = str(tmp_path / "artifact")
    export_params(tr, art)
    return art


def _serving_manifest(art: str, port: int):
    """The serving.yaml manifest rewritten for a local run: absolute
    interpreter/paths, the exported artifact, a collision-free port."""

    import yaml

    repo = os.path.dirname(os.path.dirname(EXAMPLE))
    with open(os.path.join(repo, "examples", "manifests", "serving.yaml")) as f:
        doc = yaml.safe_load(f)
    spec = doc["spec"]["tpuReplicaSpecs"]["Worker"]["template"]["spec"]
    cmd = spec["containers"][0]["command"]
    cmd[0] = sys.executable
    cmd[cmd.index("examples/serve_lm.py")] = os.path.join(
        repo, "examples", "serve_lm.py"
    )
    cmd[cmd.index("--artifact") + 1] = art
    cmd[cmd.index("--port") + 1] = str(port)
    cmd += ["--platform", "cpu"]
    return doc


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_healthz(base: str, store, backend, deadline_s: float = 120.0):
    import json as _json
    import urllib.request

    deadline = time.time() + deadline_s
    while True:
        try:
            with urllib.request.urlopen(base + "/healthz", timeout=2) as r:
                if _json.loads(r.read())["ok"]:
                    return
        except Exception:
            j = store.get("default", "serve-lm")
            if j is not None and j.status.has_condition(JobConditionType.FAILED):
                raise AssertionError(
                    "serving job FAILED: "
                    + backend.pod_log("default", "serve-lm-worker-0")[-500:]
                )
            if time.time() > deadline:
                raise AssertionError(
                    "healthz never came up; pod log tail: "
                    + backend.pod_log("default", "serve-lm-worker-0")[-500:]
                )
            time.sleep(1.0)


@pytest.mark.slow
class TestServingJob:
    """Operator-managed serving: the SAME control plane that runs
    training jobs deploys the inference binary as a long-running
    single-replica job (examples/manifests/serving.yaml), and job
    deletion tears the server down (cleanPodPolicy All)."""

    def test_serving_manifest_runs_and_answers_http(self, local_harness, tmp_path):
        import json as _json
        import socket
        import urllib.error
        import urllib.request

        import jax
        import numpy as np
        import yaml

        import jax.numpy as jnp
        from tf_operator_tpu.api.serde import job_from_dict
        from tf_operator_tpu.models import llama_loss, llama_tiny
        from tf_operator_tpu.parallel import (
            Trainer, TrainerConfig, export_params, make_mesh,
        )

        # a real artifact for the server to load (byte-level, vocab 256)
        mesh = make_mesh({"dp": 8})  # conftest's 8-device CPU mesh
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, size=(8, 16)), jnp.int32
        )
        tr = Trainer(
            llama_tiny(vocab_size=256, max_len=64, mesh=mesh),
            TrainerConfig(optimizer="sgd", learning_rate=1e-2),
            mesh,
            llama_loss,
            {"input_ids": ids},
            init_args=(ids,),
            shardings="logical",
        )
        tr.train_step(tr.shard_batch({"input_ids": ids}))
        art = str(tmp_path / "artifact")
        export_params(tr, art)

        with socket.socket() as s:  # collision-free port
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        repo = os.path.dirname(os.path.dirname(EXAMPLE))
        with open(os.path.join(repo, "examples", "manifests", "serving.yaml")) as f:
            doc = yaml.safe_load(f)
        spec = doc["spec"]["tpuReplicaSpecs"]["Worker"]["template"]["spec"]
        cmd = spec["containers"][0]["command"]
        cmd[0] = sys.executable
        cmd[cmd.index("examples/serve_lm.py")] = os.path.join(
            repo, "examples", "serve_lm.py"
        )
        cmd[cmd.index("--artifact") + 1] = art
        cmd[cmd.index("--port") + 1] = str(port)
        cmd += ["--platform", "cpu"]

        store, backend, c = local_harness
        job = job_from_dict(doc)
        store.create(job)
        wait_for(
            store, "default", "serve-lm",
            lambda j: j.status.has_condition(JobConditionType.RUNNING),
            timeout=60.0,
        )
        base = f"http://127.0.0.1:{port}"
        deadline = time.time() + 120
        while True:  # model load + first compile happen in-pod
            try:
                with urllib.request.urlopen(base + "/healthz", timeout=2) as r:
                    if _json.loads(r.read())["ok"]:
                        break
            except Exception:
                # diagnosable flake-out: surface a failed job / pod log
                # instead of an opaque URLError after 120s (e.g. a
                # TOCTOU loss of the ephemeral port -> EADDRINUSE)
                j = store.get("default", "serve-lm")
                if j is not None and j.status.has_condition(
                    JobConditionType.FAILED
                ):
                    raise AssertionError(
                        "serving job FAILED: "
                        + backend.pod_log("default", "serve-lm-worker-0")[-500:]
                    )
                if time.time() > deadline:
                    raise AssertionError(
                        "healthz never came up; pod log tail: "
                        + backend.pod_log("default", "serve-lm-worker-0")[-500:]
                    )
                time.sleep(1.0)
        req = urllib.request.Request(
            base + "/generate",
            data=_json.dumps(
                {"prompt": "operator ", "max_new_tokens": 4}
            ).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=240) as resp:
            out = _json.loads(resp.read())
        assert len(out["sample"]) == 4
        # deletion tears the server down (cleanPodPolicy All)
        store.delete("default", "serve-lm")
        wait_no_pods(backend, timeout=30.0)
        try:
            urllib.request.urlopen(base + "/healthz", timeout=2)
            raise AssertionError("server still answering after job delete")
        except (urllib.error.URLError, ConnectionError, OSError):
            pass

    def test_serving_crash_restarts_and_answers_again(
        self, local_harness, tmp_path
    ):
        """VERDICT r4 next #8: a serving pod killed mid-flight under
        RestartPolicy Always must be restarted by the operator, come
        back with a FRESH process (/metrics counters reset), and
        answer requests again."""

        import json as _json
        import subprocess as _subprocess
        import urllib.request

        from tf_operator_tpu.api.serde import job_from_dict

        art = _export_serving_artifact(tmp_path)
        port = _free_port()
        doc = _serving_manifest(art, port)
        doc["spec"]["tpuReplicaSpecs"]["Worker"]["restartPolicy"] = "Always"
        doc["spec"]["runPolicy"]["backoffLimit"] = 4

        store, backend, c = local_harness
        store.create(job_from_dict(doc))
        wait_for(
            store, "default", "serve-lm",
            lambda j: j.status.has_condition(JobConditionType.RUNNING),
            timeout=60.0,
        )
        base = f"http://127.0.0.1:{port}"
        _wait_healthz(base, store, backend)

        # drive one request so the pre-crash metrics are non-zero
        req = urllib.request.Request(
            base + "/generate",
            data=_json.dumps(
                {"prompt": "crash ", "max_new_tokens": 2}
            ).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=240) as resp:
            assert len(_json.loads(resp.read())["sample"]) == 2
        with urllib.request.urlopen(base + "/metrics", timeout=5) as resp:
            before = resp.read().decode()
        assert 'serve_requests_total{status="200"} ' in before

        # CRASH: SIGKILL the serving process (backend-agnostic — match
        # the unique port in the command line), the e2e equivalent of
        # the reference's shutdown_policy pod kills
        _subprocess.run(
            ["pkill", "-9", "-f", f"serve_lm.py.*--port {port}"], check=False
        )

        # the operator must notice the Failed pod (exit 137, signal
        # death) and, under RestartPolicy Always, recreate the replica;
        # the fresh process binds the same --port from the manifest.
        # Wait for the restart to be COUNTED first so the metrics
        # assertions below can't race the dying process.
        wait_for(
            store, "default", "serve-lm",
            lambda j: j.status.restart_count >= 1,
            timeout=60.0,
        )
        _wait_healthz(base, store, backend)
        with urllib.request.urlopen(base + "/metrics", timeout=5) as resp:
            after = resp.read().decode()
        # fresh process: labeled counters mint on first use, so the
        # pre-crash request count is GONE (not carried over)
        assert 'serve_requests_total{status="200"}' not in after

        # and the restarted server serves real traffic — after which
        # its counter reads exactly 1 (this restart's own request)
        with urllib.request.urlopen(req, timeout=240) as resp:
            assert len(_json.loads(resp.read())["sample"]) == 2
        with urllib.request.urlopen(base + "/metrics", timeout=5) as resp:
            final = resp.read().decode()
        assert 'serve_requests_total{status="200"} 1' in final
        store.delete("default", "serve-lm")
        wait_no_pods(backend, timeout=30.0)
