"""ViT family: patch-embed math, forward contract, sharded training.

The patch embedding is a reshape+matmul rather than a conv; its
equivalence to the standard stride-p conv formulation is pinned here —
that identity is the correctness argument for the MXU-friendly layout.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.models import vit_b16, vit_loss, vit_tiny
from tf_operator_tpu.models.vit import PatchEmbed
from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh


def _images(n=4, size=32, c=3, seed=0):
    r = np.random.RandomState(seed)
    return jnp.asarray(r.rand(n, size, size, c), jnp.float32)


class TestPatchEmbed:
    def test_matches_conv_formulation(self):
        """reshape+dense == stride-p conv with the same kernel."""
        import flax.linen as nn

        from tf_operator_tpu.models.transformer import TransformerConfig

        cfg = TransformerConfig(hidden=16, dtype=jnp.float32)
        pe = PatchEmbed(cfg, patch=8)
        imgs = _images(2, 32)
        params = pe.init(jax.random.PRNGKey(0), imgs)
        out = pe.apply(params, imgs)
        assert out.shape == (2, 16, 16)  # (32/8)^2 = 16 patches

        # same math as a conv: kernel [p, p, C, hidden] built from the
        # dense kernel [p*p*C, hidden] (unbox the logical-axis metadata)
        raw = nn.meta.unbox(params)["params"]["proj"]
        kernel = raw["kernel"].reshape(8, 8, 3, 16)
        conv_out = jax.lax.conv_general_dilated(
            imgs, kernel, window_strides=(8, 8), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + raw["bias"]
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(conv_out).reshape(2, 16, 16),
            atol=1e-5, rtol=1e-5,
        )

    def test_rejects_non_divisible(self):
        from tf_operator_tpu.models.transformer import TransformerConfig

        pe = PatchEmbed(TransformerConfig(hidden=8), patch=8)
        with pytest.raises(ValueError, match="not divisible"):
            pe.init(jax.random.PRNGKey(0), _images(1, 36))


class TestViT:
    def test_forward_shape_and_dtype(self):
        model = vit_tiny()
        imgs = _images(3, 32)
        params = model.init(jax.random.PRNGKey(0), imgs)
        logits = model.apply(params, imgs)
        assert logits.shape == (3, 10)
        assert logits.dtype == jnp.float32

    def test_b16_param_count(self):
        """ViT-Base/16 must land at the published ~86M scale."""
        model = vit_b16()
        params = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)))
        )
        n = sum(np.prod(x.shape) for x in jax.tree.leaves(params))
        assert 85e6 < n < 88e6, f"got {n/1e6:.1f}M params"

    def test_too_many_patches_raises(self):
        model = vit_tiny()  # max_len = 16 patches at 32^2/p8
        with pytest.raises(ValueError, match="patches"):
            model.init(jax.random.PRNGKey(0), _images(1, 64))


class TestViTTraining:
    def test_loss_decreases_on_dp_fsdp_mesh(self):
        mesh = make_mesh({"dp": len(jax.devices())})
        model = vit_tiny()
        batch = {"image": _images(8, 32), "label": jnp.arange(8) % 10}
        trainer = Trainer(
            model,
            TrainerConfig(optimizer="adamw", learning_rate=3e-3),
            mesh,
            vit_loss,
            batch,
            shardings="logical",
        )
        first = last = None
        for step in range(8):
            metrics = trainer.train_step(batch)
            loss = float(metrics["loss"])
            first = loss if first is None else first
            last = loss
        assert last < first, f"loss did not decrease: {first} -> {last}"
