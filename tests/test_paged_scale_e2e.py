"""Closed loop (ISSUE 8 acceptance): blocks-free pressure from a REAL
paged pool drives a 1→N serving scale-up through the PR 7 autoscaler
against kubesim — per-replica gauges visible on /metrics, merged
quantiles on /slo.

The chain under test: paged pool admissions consume arena blocks →
``kv_blocks_pressure`` gauge (worst replica) → the STOCK serving
policy's rebound gauge binding breaches → Autoscaler decision → the
kubesim-backed reconciler creates worker pods.  Relief drains the pool
and the hysteresis latch + stabilization shed the replicas back.
"""

import json
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # pool compiles + kubesim round trips

import jax
import jax.numpy as jnp

from tests.testutil import new_job
from tf_operator_tpu.api.types import AutoscalingSpec
from tf_operator_tpu.backend.kube import KubeBackend
from tf_operator_tpu.backend.kubejobs import KubeJobStore
from tf_operator_tpu.backend.kubesim import MiniApiServer
from tf_operator_tpu.controller.autoscaler import (
    Autoscaler,
    default_serving_policy,
)
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.controller.reconciler import ReconcilerConfig
from tf_operator_tpu.models import llama_tiny
from tf_operator_tpu.models.batching import PagedContinuousBatchingDecoder
from tf_operator_tpu.utils.alerts import AlertEngine, default_rules
from tf_operator_tpu.utils.flight import FlightRecorder
from tf_operator_tpu.utils.metrics import Metrics

VOCAB = 256


def test_blocks_free_pressure_scales_serving_one_to_three():
    sim = MiniApiServer().start()
    store = KubeJobStore(sim.url)
    backend = KubeBackend(sim.url)
    metrics = Metrics()
    engine = AlertEngine(
        default_rules(), metrics=metrics, recorder=FlightRecorder()
    )
    autoscaler = Autoscaler(metrics=metrics, alerts=engine)
    controller = TPUJobController(
        store, backend, metrics=metrics, alerts=engine,
        autoscaler=autoscaler,
        config=ReconcilerConfig(resolver=backend.resolver),
    )
    controller.run(threadiness=2)
    try:
        # THE STOCK POLICY, unmodified except bounds/cadence: its gauge
        # binding is kv_blocks_pressure (the ISSUE 8 rebind) at 0.85
        pol = default_serving_policy(min_replicas=1, max_replicas=3)
        pol.cooldown_seconds = 5.0
        pol.stabilization_seconds = 20.0
        # kubesim RUNS pod commands as subprocesses: serving replicas
        # must be long-lived or the job goes terminal under us
        job = new_job(
            name="pool", worker=1,
            command=[sys.executable, "-c", "import time; time.sleep(120)"],
        )
        job.spec.autoscaling = AutoscalingSpec(policies=[pol])
        store.create(job)

        def pods():
            return sorted(
                p.metadata.name
                for p in backend.list_pods(
                    "default", {"tpujob.dist/job-name": "pool"}
                )
            )

        deadline = time.time() + 20
        while time.time() < deadline and len(pods()) < 1:
            time.sleep(0.1)
        assert pods() == ["pool-worker-0"]

        # REAL pressure: a paged pool whose arena fills past 85%
        model = llama_tiny(vocab_size=VOCAB, max_len=64)
        init = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), init)["params"]
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=6, kv_block_size=16, kv_blocks=8,
            metrics=metrics, model_label="tiny",
        )
        r = np.random.RandomState(1)
        rids = [
            pool.submit(
                r.randint(0, VOCAB, size=(6,)).astype(np.int32),
                max_new_tokens=26,  # 2 blocks per request
            )
            for _ in range(4)
        ]
        pool._admit()  # 8/8 blocks live -> pressure 1.0
        assert metrics.gauge(
            "kv_blocks_pressure", model="tiny", replica="0",
            role="unified",
        ) == 1.0

        t0 = time.time()
        (d1,) = autoscaler.evaluate_once(t0)
        assert (d1.direction, d1.from_replicas, d1.to_replicas) == (
            "up", 1, 2,
        )
        assert "kv_blocks_pressure" in d1.reason
        assert autoscaler.evaluate_once(t0 + 1) == []  # cooldown
        (d2,) = autoscaler.evaluate_once(t0 + 6)
        assert d2.to_replicas == 3

        # the decision callback re-enqueues the job; the running
        # controller creates the new workers against kubesim
        deadline = time.time() + 30
        while time.time() < deadline and len(pods()) < 3:
            time.sleep(0.2)
        assert pods() == [
            "pool-worker-0", "pool-worker-1", "pool-worker-2",
        ]  # the 1 -> 3 scale-up landed in kubesim

        # relief: drain the pool; pressure collapses below the
        # hysteresis release (0.85 * 0.5), stabilization passes, and
        # the policy sheds back down
        pool.run()
        for rid in rids:
            assert pool.result(rid) is not None
        assert metrics.gauge(
            "kv_blocks_pressure", model="tiny", replica="0",
            role="unified",
        ) < 0.85 * pol.hysteresis_ratio
        assert autoscaler.evaluate_once(t0 + 12) == []  # quiet starts
        (down,) = autoscaler.evaluate_once(t0 + 40)
        assert down.direction == "down" and down.to_replicas == 2
    finally:
        controller.stop()
        backend.close()
        store.close()
        sim.stop()


def test_preemption_rate_scales_serving_out():
    """ISSUE 12 stock-policy refresh, e2e through the PR 8 pattern:
    REAL preemptions from a thrashing paged pool (budget-on-demand
    oversubscription losing its gamble) increment
    ``serve_preemptions_total`` → the stock ``serve-preemption-rate``
    threshold rule fires in the alert engine → the STOCK serving
    policy's alert binding breaches → the autoscaler scales the
    worker set out before interactive TTFT burns."""

    metrics = Metrics()
    engine = AlertEngine(
        default_rules(short=5.0, long=30.0), metrics=metrics,
        recorder=FlightRecorder(),
    )
    autoscaler = Autoscaler(metrics=metrics, alerts=engine)
    pol = default_serving_policy(min_replicas=1, max_replicas=3)
    pol.cooldown_seconds = 5.0
    job = new_job(name="thrash", worker=1)
    job.spec.autoscaling = AutoscalingSpec(policies=[pol])
    autoscaler.attach(lambda: [job])

    t0 = time.time()
    engine.evaluate_once(t0)  # baseline counter sample
    assert autoscaler.evaluate_once(t0) == []  # quiet: no decision

    # REAL thrash: a tight arena, long budgets, competing batch seats
    # — growth keeps preempting until the preemption-rate threshold
    # (8 per window) is crossed
    model = llama_tiny(vocab_size=VOCAB, max_len=64)
    init = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), init)["params"]
    pool = PagedContinuousBatchingDecoder(
        model, params, slots=4, kv_block_size=16, kv_blocks=4,
        steps_per_sync=8, metrics=metrics, model_label="tiny",
    )
    r = np.random.RandomState(1)
    rids = []
    deadline = time.time() + 120
    while pool.preemptions <= 8 and time.time() < deadline:
        # sustained oversubscription: keep ~6 long-budget requests in
        # flight so growth contention never drains
        with pool._lock:
            backlog = len(pool._queue) + len(pool._active)
        while backlog < 6 and len(rids) < 64:
            rids.append(pool.submit(
                r.randint(0, VOCAB, size=(6,)).astype(np.int32),
                max_new_tokens=40,
            ))
            backlog += 1
        pool.step()
    assert pool.preemptions > 8, "scenario failed to thrash"
    assert metrics.counter(
        "serve_preemptions_total", model="tiny", tier="batch",
        replica="0",
    ) == pool.preemptions

    engine.evaluate_once(t0 + 2)  # increase lands inside the window
    alert = engine.alert("serve-preemption-rate")
    assert alert is not None and alert.state == "firing"
    (up,) = autoscaler.evaluate_once(t0 + 3)
    assert (up.direction, up.from_replicas, up.to_replicas) == ("up", 1, 2)
    assert "serve-preemption-rate" in up.reason

    # drain; every preempted request still completed (never crashed)
    pool.run()
    for rid in rids:
        assert pool.result(rid) is not None
    pool.alloc.check()


def test_disaggregated_roles_scale_independently():
    """ISSUE 13 acceptance: a phase-split fleet's two replica classes
    scale INDEPENDENTLY off ``kv_blocks_pressure{role=}`` through the
    stock disaggregated policy pair — prefill pressure scales only the
    PS set, decode pressure only the WORKER set — against kubesim,
    with both decisions visible on GET /autoscaler (operator API over
    real HTTP)."""

    import urllib.request as _rq

    from tf_operator_tpu.controller.autoscaler import (
        default_disaggregated_policies,
    )
    from tf_operator_tpu.models.prefix_cache import PrefixFabric
    from tf_operator_tpu.server.api import ApiServer

    sim = MiniApiServer().start()
    store = KubeJobStore(sim.url)
    backend = KubeBackend(sim.url)
    metrics = Metrics()
    engine = AlertEngine(
        default_rules(), metrics=metrics, recorder=FlightRecorder()
    )
    autoscaler = Autoscaler(metrics=metrics, alerts=engine)
    controller = TPUJobController(
        store, backend, metrics=metrics, alerts=engine,
        autoscaler=autoscaler,
        config=ReconcilerConfig(resolver=backend.resolver),
    )
    controller.run(threadiness=2)
    api = ApiServer(
        store, backend, metrics, controller.recorder,
        autoscaler=autoscaler, alerts=engine,
    )
    api.start()
    try:
        pols = default_disaggregated_policies(
            min_replicas=1, max_replicas=3
        )
        for pol in pols:
            pol.cooldown_seconds = 5.0
            pol.stabilization_seconds = 60.0
        job = new_job(
            name="disagg", ps=1, worker=1,
            command=[sys.executable, "-c",
                     "import time; time.sleep(120)"],
        )
        job.spec.autoscaling = AutoscalingSpec(policies=pols)
        store.create(job)

        def pods(rtype):
            return sorted(
                p.metadata.name
                for p in backend.list_pods(
                    "default", {"tpujob.dist/job-name": "disagg"}
                )
                if f"-{rtype}-" in p.metadata.name
            )

        deadline = time.time() + 20
        while time.time() < deadline and (
            len(pods("ps")) < 1 or len(pods("worker")) < 1
        ):
            time.sleep(0.1)
        assert pods("ps") == ["disagg-ps-0"]
        assert pods("worker") == ["disagg-worker-0"]

        # REAL role-labeled pressure from a real phase-split fleet:
        # the prefill replica's arena fills (a long-prompt burst), the
        # decode replica stays idle
        model = llama_tiny(vocab_size=VOCAB, max_len=64)
        init = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), init)["params"]
        fabric = PrefixFabric(metrics=metrics, model_label="tiny")
        pre = PagedContinuousBatchingDecoder(
            model, params, slots=6, kv_block_size=16, kv_blocks=8,
            metrics=metrics, model_label="tiny", replica_label="p0",
            role="prefill", fabric=fabric,
        )
        dec = PagedContinuousBatchingDecoder(
            model, params, slots=6, kv_block_size=16, kv_blocks=8,
            metrics=metrics, model_label="tiny", replica_label="d0",
            role="decode", fabric=fabric,
        )
        r = np.random.RandomState(1)

        def fill(pool):
            rids = [
                pool.submit(
                    r.randint(0, VOCAB, size=(6,)).astype(np.int32),
                    max_new_tokens=26,  # 2 committed blocks each
                )
                for _ in range(4)
            ]
            pool._admit()  # 8/8 blocks live -> pressure 1.0
            return rids

        pre_rids = fill(pre)
        assert metrics.gauge(
            "kv_blocks_pressure", model="tiny", replica="p0",
            role="prefill",
        ) == 1.0
        assert metrics.gauge(
            "kv_blocks_pressure", model="tiny", replica="d0",
            role="decode",
        ) == 0.0

        # ONLY the PS (prefill) policy breaches
        t0 = time.time()
        (d1,) = autoscaler.evaluate_once(t0)
        assert d1.replica_type.value == "PS"
        assert (d1.direction, d1.from_replicas, d1.to_replicas) == (
            "up", 1, 2,
        )
        assert "kv_blocks_pressure{role=prefill}" in d1.reason
        deadline = time.time() + 30
        while time.time() < deadline and len(pods("ps")) < 2:
            time.sleep(0.2)
        assert pods("ps") == ["disagg-ps-0", "disagg-ps-1"]
        assert pods("worker") == ["disagg-worker-0"]  # untouched

        # relieve prefill, load decode: ONLY the WORKER policy acts
        pre.run()
        for rid in pre_rids:
            assert pre.result(rid) is not None
        assert metrics.gauge(
            "kv_blocks_pressure", model="tiny", replica="p0",
            role="prefill",
        ) < 0.85 * pols[0].hysteresis_ratio
        dec_rids = fill(dec)
        (d2,) = autoscaler.evaluate_once(t0 + 6)
        assert d2.replica_type.value == "Worker"
        assert (d2.direction, d2.to_replicas) == ("up", 2)
        assert "kv_blocks_pressure{role=decode}" in d2.reason
        deadline = time.time() + 30
        while time.time() < deadline and len(pods("worker")) < 2:
            time.sleep(0.2)
        assert pods("worker") == ["disagg-worker-0", "disagg-worker-1"]
        assert pods("ps") == ["disagg-ps-0", "disagg-ps-1"]

        # both decisions on GET /autoscaler over real HTTP
        with _rq.urlopen(
            f"http://127.0.0.1:{api.port}/autoscaler", timeout=10
        ) as resp:
            snap = json.loads(resp.read())
        kinds = [
            (d["replicaType"], d["direction"], d["to"])
            for d in snap["decisions"]
        ]
        assert ("PS", "up", 2) in kinds
        assert ("Worker", "up", 2) in kinds
        assert {p["replicaType"] for p in snap["policies"]} == {
            "PS", "Worker",
        }

        dec.run()
        for rid in dec_rids:
            assert dec.result(rid) is not None
        pre.alloc.check()
        dec.alloc.check()
    finally:
        api.stop()
        controller.stop()
        backend.close()
        store.close()
        sim.stop()


def test_multi_replica_metrics_and_merged_slo_over_http():
    """The visibility half: N pool replicas behind one admission queue
    export per-replica serve_admission_queue_depth / kv_blocks_free on
    /metrics while GET /slo reports ONE merged quantile row per
    {model, mode} (no replica key) — multi-replica serving has one
    user-facing p99 TTFT."""

    from http.server import ThreadingHTTPServer

    from tests.testutil import load_serve_lm

    serve_lm = load_serve_lm()
    model = llama_tiny(vocab_size=256, max_len=64)
    prompt = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    handler = serve_lm.build_handler(
        model, params, max_len=64, batching_slots=2, replicas=2
    )
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        results = {}

        def post(i):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps(
                    {"prompt": f"req {i} ", "max_new_tokens": 6}
                ).encode(),
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=300) as resp:
                results[i] = json.loads(resp.read())

        threads = [
            threading.Thread(target=post, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert set(results) == {0, 1, 2, 3}
        for i in range(4):
            assert len(results[i]["sample"]) == 6

        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as resp:
            text = resp.read().decode()
        for rep in ("0", "1"):
            assert (
                f'kv_blocks_free{{model="unknown",replica="{rep}",'
                'role="unified"}'
            ) in text
            assert (
                "serve_admission_queue_depth"
                f'{{model="unknown",replica="{rep}"}}'
            ) in text

        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/slo", timeout=10
        ) as resp:
            slo = json.loads(resp.read())
        rows = slo["histograms"]["serve_ttft_seconds"]
        assert len(rows) == 1, rows  # merged across replicas
        assert rows[0]["count"] == 4 and "replica" not in rows[0]
        assert slo["replicas"] == 2
        assert slo["gauges"]["kv_blocks_free"] == 16.0  # fleet sum
    finally:
        server.shutdown()
