"""The device cost plane (ISSUE 20): compile ledger, HBM accountant,
step-time sentinel — units plus the acceptance e2es.

The load-bearing pins:

- STORM: an adversarial-width client (every request a new admission
  width class) drives the ``compile-storm`` stock rule
  pending→firing→Degraded→resolved with exactly ONE flight-recorder
  dump, and the ledger attributes EVERY compile to its ``width=``
  trigger.
- CLEAN SOAK: a normal boot's handful of compiles plus a healthy
  sentinel stream fires ZERO alerts over the full long window — the
  false-positive-free baseline is part of the contract.
- VETO: the autoscaler refuses to act on a breaching scale signal
  while the storm fires, and acts again once it resolves.
- COVERAGE (CPU smoke, subprocess): ``/debug/memory`` accounts >= 95%
  of what the backend says is live after a paged pool boots.
- STEADY STATE (slow): a warmed paged pool replaying same-shaped
  traffic registers ZERO new compiles — the ledger is the proof.
"""

import json
import os
import subprocess
import sys
import textwrap
import time
import types

import numpy as np
import pytest

from tests.testutil import new_job
from tf_operator_tpu.api.types import JobConditionType, PodPhase
from tf_operator_tpu.backend.fake import FakeCluster
from tf_operator_tpu.backend.jobstore import JobStore
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.utils.alerts import AlertEngine, default_rules
from tf_operator_tpu.utils.costplane import (
    HBM_COMPONENTS,
    CompileLedger,
    CostPlane,
    HBMAccountant,
    StepTimeSentinel,
    process_compile_count,
)
from tf_operator_tpu.utils.flight import FlightRecorder
from tf_operator_tpu.utils.metrics import Metrics

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the storm client: every admission a prime width — no two requests
#: share a class, the pathological case the rule exists for
STORM_WIDTHS = (3, 5, 7, 9, 11, 13, 17, 19, 23, 29)


def _gauge(metrics, family, **labels):
    for lab, v in metrics.gauge_series(family).items():
        if dict(lab) == labels:
            return v
    return None


# ---------------------------------------------------------------------------
# units: CompileLedger
# ---------------------------------------------------------------------------


class TestCompileLedger:
    def test_wrap_registers_exactly_the_first_call(self):
        m = Metrics()
        led = CompileLedger(metrics=m)
        calls = []

        def fn(x):
            calls.append(1)
            return x

        timed = led.wrap(fn, "pool.admit", trigger="width=4")
        arg = np.zeros((2, 3), np.float32)
        for _ in range(3):
            timed(arg)
        assert len(calls) == 3  # the wrap never swallows calls
        assert led.total() == 1  # ...but registers only the cache miss
        (ev,) = led.snapshot()["events"]
        assert ev["program"] == "pool.admit"
        assert ev["trigger"] == "width=4"
        assert ev["first_call_seconds"] >= 0.0
        assert "f32[2,3]" in ev["shapes"]
        assert timed.__wrapped__ is fn

    def test_note_counts_with_honestly_absent_wall(self):
        led = CompileLedger(metrics=Metrics())
        ev = led.note("paged.swap_gather", trigger="ids=8")
        assert ev["first_call_seconds"] == 0.0
        assert led.total() == 1

    def test_ring_is_bounded_and_newest_first(self):
        led = CompileLedger(metrics=Metrics(), ring=4)
        for i in range(6):
            led.record("p", trigger=f"width={i}")
        snap = led.snapshot()
        assert snap["total"] == 6  # the TOTAL survives ring eviction
        assert [e["seq"] for e in snap["events"]] == [6, 5, 4, 3]
        assert len(led.snapshot(limit=2)["events"]) == 2

    def test_snapshot_groups_by_program_and_trigger(self):
        led = CompileLedger(metrics=Metrics())
        led.record("paged.admit", trigger="width=8")
        led.record("paged.admit", trigger="width=8")
        led.record("paged.admit", trigger="width=16")
        led.record("paged.step", trigger="K=8")
        by = led.snapshot()["byProgram"]
        assert by["paged.admit"]["total"] == 3
        assert by["paged.admit"]["byTrigger"] == {"width=8": 2, "width=16": 1}
        assert by["paged.step"] == {"total": 1, "byTrigger": {"K=8": 1}}

    def test_every_ledger_feeds_the_process_counter(self):
        before = process_compile_count()
        a = CompileLedger(metrics=Metrics())
        b = CompileLedger(metrics=Metrics())
        a.record("x")
        b.record("y")
        b.note("z")
        assert process_compile_count() == before + 3
        assert a.snapshot()["processTotal"] >= before + 3

    def test_compile_metrics_emitted_with_pinned_labels(self):
        m = Metrics()
        led = CompileLedger(metrics=m)
        led.record("paged.admit", trigger="width=8", seconds=0.25)
        series = m.counter_series("compile_total")
        assert {dict(lab)["program"] for lab in series} == {"paged.admit"}
        assert {dict(lab)["trigger"] for lab in series} == {"width=8"}


# ---------------------------------------------------------------------------
# units: HBMAccountant
# ---------------------------------------------------------------------------


class TestHBMAccountant:
    def test_unknown_component_is_a_programming_error(self):
        acc = HBMAccountant(metrics=Metrics())
        with pytest.raises(ValueError):
            acc.set_component("activations", 1)
        with pytest.raises(ValueError):
            acc.add_component("scratch", 1)

    def test_snapshot_sorts_worst_headroom_first(self):
        acc = HBMAccountant(metrics=Metrics(), limit_bytes=1000)
        acc.set_component("weights", 600, device="dev:a")
        acc.add_component("kv_arena", 100, device="dev:b")
        acc.add_component("kv_arena", 50, device="dev:b")  # add accumulates
        snap = acc.snapshot()
        rows = snap["devices"]
        # dev:a (headroom 400) before dev:b (850); the backend's real
        # devices carry zero accounted bytes and sink behind both
        assert [r["device"] for r in rows[:2]] == ["dev:a", "dev:b"]
        assert rows[0]["headroom_bytes"] == 400
        assert rows[1]["components"]["kv_arena"] == 150
        # the component table is the CLOSED taxonomy, zero-filled
        assert set(rows[0]["components"]) == set(HBM_COMPONENTS)
        assert snap["accounted_bytes"] >= 750

    def test_gauges_emitted_per_device_and_component(self):
        m = Metrics()
        acc = HBMAccountant(metrics=m, limit_bytes=1000)
        acc.set_component("weights", 600, device="dev:a")
        assert _gauge(
            m, "hbm_component_bytes", device="dev:a", component="weights"
        ) == 600.0
        assert _gauge(m, "hbm_device_limit_bytes", device="dev:a") == 1000.0
        assert _gauge(m, "hbm_headroom_bytes", device="dev:a") == 400.0

    def test_register_tree_accounts_host_leaves(self):
        acc = HBMAccountant(metrics=Metrics())
        tree = {"w": np.zeros(10, np.float32), "b": np.zeros(4, np.float32)}
        acc.register_tree("weights", tree)
        rows = {d["device"]: d for d in acc.snapshot()["devices"]}
        assert rows["host"]["components"]["weights"] == 56

    def test_note_compiled_keeps_the_peak_not_the_sum(self):
        acc = HBMAccountant(metrics=Metrics())

        def compiled(tmp):
            return types.SimpleNamespace(
                memory_analysis=lambda: types.SimpleNamespace(
                    temp_size_in_bytes=tmp
                )
            )

        assert acc.note_compiled("a", compiled(100)) == 100
        assert acc.note_compiled("b", compiled(60)) == 60
        rows = acc.snapshot()["devices"]
        # scratch HBM is reused across programs: the ledger holds max
        assert max(r["components"]["program_tmp"] for r in rows) == 100
        # no memory_analysis (the CPU backend) = honestly absent
        assert acc.note_compiled("c", object()) is None


# ---------------------------------------------------------------------------
# units: StepTimeSentinel
# ---------------------------------------------------------------------------


class TestStepTimeSentinel:
    def test_reference_freezes_at_warmup(self):
        s = StepTimeSentinel(metrics=Metrics(), window=8, warmup=4)
        for _ in range(3):
            s.observe("decode.window", 0.1)
        assert s.reference("decode.window") is None
        s.observe("decode.window", 0.1)
        assert s.reference("decode.window") == (0.1, 0.1)
        for _ in range(10):
            s.observe("decode.window", 0.4)
        assert s.reference("decode.window") == (0.1, 0.1)  # frozen

    def test_drift_ratio_tracks_the_rolling_median(self):
        m = Metrics()
        s = StepTimeSentinel(metrics=m, window=8, warmup=4)
        for _ in range(4):
            s.observe("decode.window", 0.1)
        for _ in range(8):  # fill the whole window with the regression
            s.observe("decode.window", 0.2)
        assert _gauge(
            m, "step_time_drift_ratio", signal="decode.window"
        ) == pytest.approx(2.0)
        snap = s.snapshot()["decode.window"]
        assert snap["drift_ratio"] == pytest.approx(2.0)
        assert snap["reference_p50_seconds"] == pytest.approx(0.1)

    def test_p99_jitter_cannot_move_the_drift_gauge(self):
        """The stock rule binds the p50-based drift ratio precisely so
        CI-box tail spikes page nobody: a minority of 50x outliers
        maxes the p99 gauge while drift stays at 1.0."""

        m = Metrics()
        s = StepTimeSentinel(metrics=m, window=8, warmup=4)
        for _ in range(4):
            s.observe("decode.window", 0.1)
        for dt in (0.1, 0.1, 5.0, 0.1, 0.1, 5.0, 0.1, 0.1):
            s.observe("decode.window", dt)
        assert _gauge(
            m, "step_time_p99_seconds", signal="decode.window"
        ) == 5.0
        drift = _gauge(m, "step_time_drift_ratio", signal="decode.window")
        assert drift == pytest.approx(1.0)
        assert drift < 1.5  # the step-time-regression threshold

    def test_reset_rebaselines_a_signal(self):
        s = StepTimeSentinel(metrics=Metrics(), window=8, warmup=4)
        for _ in range(6):
            s.observe("train_sync", 0.1)
            s.observe("decode.window", 0.2)
        s.reset("train_sync")
        assert s.reference("train_sync") is None
        assert "train_sync" not in s.snapshot()
        assert s.reference("decode.window") is not None  # untouched


# ---------------------------------------------------------------------------
# the storm e2e + clean soak (ISSUE 20 acceptance)
# ---------------------------------------------------------------------------


@pytest.fixture
def cost_rig(tmp_path, monkeypatch):
    """The alert→status vertical with the FULL stock rule set (shrunk
    windows) and one running TPUJob — the test_alerts_e2e rig minus the
    HTTP data plane (compiles are injected through a real ledger)."""

    monkeypatch.setenv("TPUJOB_FLIGHT_DIR", str(tmp_path))
    metrics = Metrics()
    recorder = FlightRecorder()
    recorder.attach_metrics(metrics)
    engine = AlertEngine(
        default_rules(short=0.5, long=1.5), metrics=metrics,
        recorder=recorder,
    )
    store = JobStore()
    backend = FakeCluster(delivery="sync")
    controller = TPUJobController(
        store, backend, metrics=metrics, alerts=engine
    )
    job = new_job(name="storm-job", worker=1)
    store.create(job)
    controller.sync_until_quiet()
    backend.set_pod_phase("default", "storm-job-worker-0", PodPhase.RUNNING)
    controller.sync_until_quiet()
    assert store.get("default", "storm-job").status.has_condition(
        JobConditionType.RUNNING
    )
    yield metrics, engine, store, controller
    controller.stop()


class TestCompileStorm:
    def test_adversarial_widths_drive_the_full_lifecycle(self, cost_rig):
        metrics, engine, store, controller = cost_rig
        ledger = CompileLedger(metrics=metrics)
        storm = engine.alert("compile-storm")
        t0 = time.time()
        engine.evaluate_once(now=t0)  # seeds the counter history
        assert storm.state == "inactive"

        # ---- the storm: every request a fresh prime width class,
        # through the real wrap() path (cache miss = ledger event)
        for w in STORM_WIDTHS:
            timed = ledger.wrap(
                lambda ids: ids, "paged.admit", trigger=f"width={w}"
            )
            timed(np.zeros((w,), np.int32))
        engine.evaluate_once(now=t0 + 0.05)
        assert storm.state == "firing", (
            f"storm never fired: state={storm.state} value={storm.value}"
        )

        # ---- firing -> Degraded + Warning event on the TPUJob
        controller.sync_until_quiet()
        job = store.get("default", "storm-job")
        deg = job.status.condition(JobConditionType.DEGRADED)
        assert deg is not None and deg.status
        # ThresholdRules roll up as HealthDegraded (SLOViolation is
        # reserved for the burn-rate SLO rules)
        assert deg.reason == "HealthDegraded"
        assert "compile-storm" in deg.message
        assert job.status.has_condition(JobConditionType.RUNNING)
        assert "compile-storm" in job.status.observed_health["firingAlerts"]
        events = [
            (e.type, e.reason)
            for e in controller.recorder.for_object("default/storm-job")
        ]
        assert ("Warning", "HealthDegraded") in events

        # ---- exactly one flight-recorder dump, naming the rule
        assert len(engine.dumps) == 1
        records = [
            json.loads(line)
            for line in open(engine.dumps[0]).read().splitlines()
        ]
        assert records[0]["reason"] == "alert-compile-storm"

        # ---- the ledger attributes EVERY compile to its trigger
        snap = ledger.snapshot()
        prog = snap["byProgram"]["paged.admit"]
        assert prog["total"] == len(STORM_WIDTHS)
        assert prog["byTrigger"] == {
            f"width={w}": 1 for w in STORM_WIDTHS
        }
        assert all(
            ev["trigger"].startswith("width=") for ev in snap["events"]
        )

        # ---- the storm stops; the burst ages out of the short window
        engine.evaluate_once(now=t0 + 2.0)
        assert storm.state == "resolved", f"value={storm.value}"
        controller.reconciler.config.health_refresh_seconds = 0.0
        controller.sync_until_quiet()
        job = store.get("default", "storm-job")
        assert not job.status.has_condition(JobConditionType.DEGRADED)
        assert job.status.observed_health["firingAlerts"] == []
        # still exactly the one dump from the firing transition
        assert len(engine.dumps) == 1

    def test_clean_soak_with_boot_compiles_fires_nothing(self, cost_rig):
        """The false-positive half: a normal boot's handful of compile
        classes plus a healthy sentinel stream, evaluated past the
        LONG window — every stock rule must stay inactive."""

        metrics, engine, store, controller = cost_rig
        fired = []
        engine.subscribe(lambda a, old, new: fired.append((a.rule.name, new)))
        ledger = CompileLedger(metrics=metrics)
        t0 = time.time()
        engine.evaluate_once(now=t0)
        # a normal pool boot: admission widths + step + retire — at
        # most a handful, under the storm threshold of 8
        for prog, trig in (
            ("paged.admit", "width=8"),
            ("paged.admit", "width=16"),
            ("paged.step", "K=8"),
            ("paged.retire", "singleton"),
            ("pool.prefill", "width=8"),
        ):
            ledger.record(prog, trig, seconds=0.02)
        # a healthy sentinel: steady walls, drift pinned at 1.0
        sentinel = StepTimeSentinel(metrics=metrics, window=8, warmup=4)
        for _ in range(16):
            sentinel.observe("decode.window", 0.01)
        for dt in (0.1, 0.3, 0.6, 1.0, 2.0, 4.0):  # spans long=1.5
            engine.evaluate_once(now=t0 + dt)
        assert all(a.state == "inactive" for a in engine.alerts())
        assert fired == []
        assert metrics.total("alerts_fired_total") == 0.0
        assert engine.dumps == []
        controller.reconciler.config.health_refresh_seconds = 0.0
        controller.sync_until_quiet()
        job = store.get("default", "storm-job")
        assert not job.status.has_condition(JobConditionType.DEGRADED)


class TestAutoscalerVeto:
    def test_scaling_refused_while_storm_fires_resumes_after(
        self, tmp_path, monkeypatch
    ):
        """The cost-plane gate end to end: a genuinely breaching scale
        signal produces NO decision while compile-storm fires (the
        refusal is metered), then scales once the storm resolves."""

        from tests.test_autoscaler import Rig, serving_policy

        rig = Rig(
            tmp_path, monkeypatch,
            rules=default_rules(short=0.5, long=1.5),
        )
        try:
            rig.add_job(serving_policy(), worker=1)
            rig.metrics.set("serve_admission_queue_depth", 50.0)  # breach
            ledger = CompileLedger(metrics=rig.metrics)
            t0 = time.time()
            rig.engine.evaluate_once(now=t0)
            for w in STORM_WIDTHS:
                ledger.record("paged.admit", f"width={w}", seconds=0.01)
            rig.engine.evaluate_once(now=t0 + 0.05)
            assert rig.engine.alert("compile-storm").state == "firing"

            assert rig.autoscaler.evaluate_once(t0 + 0.1) == []
            assert rig.metrics.total("autoscaler_skipped_total") >= 1.0

            rig.engine.evaluate_once(now=t0 + 2.0)
            assert rig.engine.alert("compile-storm").state == "resolved"
            decisions = rig.autoscaler.evaluate_once(t0 + 2.1)
            assert decisions, (
                "breaching queue gauge must scale once the veto clears"
            )
        finally:
            rig.stop()


# ---------------------------------------------------------------------------
# the CPU coverage smoke (ISSUE 20 acceptance: >= 95%)
# ---------------------------------------------------------------------------


class TestCoverageSmoke:
    def test_debug_memory_accounts_95_percent_of_live_bytes(self):
        """A paged pool boots in a FRESH process (so jax.live_arrays is
        exactly this workload), the pool registers its arena and the
        caller its weights — the accountant's coverage against the
        backend's live bytes must be >= 0.95 on every device the
        backend reports."""

        script = textwrap.dedent(
            """
            import gc, json
            import jax
            # sitecustomize pins the TPU plugin and OVERRIDES env-level
            # selection — the config update must come before any
            # backend init (the tests/conftest.py caveat)
            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp

            from tf_operator_tpu.models import llama_tiny
            from tf_operator_tpu.models.batching import (
                PagedContinuousBatchingDecoder,
            )
            from tf_operator_tpu.utils.costplane import CostPlane
            from tf_operator_tpu.utils.metrics import Metrics

            model = llama_tiny(vocab_size=96, max_len=64)
            params = model.init(
                jax.random.PRNGKey(1), jnp.zeros((1, 4), jnp.int32)
            )["params"]
            cp = CostPlane(metrics=Metrics())
            pool = PagedContinuousBatchingDecoder(
                model, params, slots=4, kv_block_size=16, costplane=cp
            )
            cp.hbm.register_tree("weights", params)
            gc.collect()  # init temporaries must not count as live
            snap = cp.hbm.snapshot()
            rows = [
                d for d in snap["devices"] if d["coverage"] is not None
            ]
            assert rows, "no device with backend-reported live bytes"
            worst = min(d["coverage"] for d in rows)
            assert worst >= 0.95, (
                f"coverage {worst} < 0.95: " + json.dumps(snap)
            )
            print("COVERAGE_OK", worst)
            """
        )
        proc = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, timeout=300,
            cwd=REPO_ROOT,
        )
        assert proc.returncode == 0, proc.stdout + "\n" + proc.stderr
        assert "COVERAGE_OK" in proc.stdout


# ---------------------------------------------------------------------------
# steady-state pin (slow): ZERO new compiles after warmup
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestSteadyStatePin:
    def test_warmed_paged_pool_replays_with_zero_new_compiles(self):
        """The acceptance pin the ledger exists to enforce: after a
        warm batch establishes the width/K classes, a second batch of
        the SAME shapes (fresh random content, so no prefix-cache path
        change) must register ZERO new compiles — any event here is a
        width-classing bug, and the ledger names it."""

        import jax
        import jax.numpy as jnp

        from tf_operator_tpu.models import llama_tiny
        from tf_operator_tpu.models.batching import (
            PagedContinuousBatchingDecoder,
        )

        model = llama_tiny(vocab_size=96, max_len=64)
        params = model.init(
            jax.random.PRNGKey(1), jnp.zeros((1, 4), jnp.int32)
        )["params"]
        cp = CostPlane(metrics=Metrics())
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, costplane=cp
        )
        r = np.random.RandomState(3)
        lens = [5, 17, 33]

        def batch():
            prompts = [
                r.randint(0, 96, size=(n,)).astype(np.int32) for n in lens
            ]
            rids = [pool.submit(p, max_new_tokens=6) for p in prompts]
            pool.run()
            return [pool.result(rid) for rid in rids]

        for out in batch():
            assert len(out) > 0
        warm = cp.compiles.total()
        assert warm > 0  # the warm batch really went through the ledger

        for out in batch():
            assert len(out) > 0
        snap = cp.compiles.snapshot()
        assert cp.compiles.total() == warm, (
            "steady-state recompiles, newest first: "
            + json.dumps(snap["events"][:8], default=str)
        )
