"""Gang scheduling / atomic slice admission (SURVEY.md §3.4).

The reference's volcano PodGroup semantics generalised: a TPU slice is
whole-or-nothing; contending jobs queue; capacity freed by completion
re-admits pending gangs.
"""

from tests.testutil import harness, new_job
from tf_operator_tpu.api.types import JobConditionType, PodPhase, ReplicaType
from tf_operator_tpu.backend.objects import PodGroupPhase


def submit(store, controller, job):
    stored = store.create(job)
    controller.sync_until_quiet()
    return stored


class TestGangAdmission:
    def test_pod_group_created_with_min_member(self):
        store, backend, c = harness()
        job = new_job(chief=1, worker=3)
        job.spec.enable_gang_scheduling = True
        submit(store, c, job)
        group = backend.get_pod_group("default", "job")
        assert group is not None
        assert group.min_member == 4
        assert group.phase is PodGroupPhase.GRANTED  # unlimited capacity

    def test_pods_carry_gang_annotation_and_scheduler(self):
        store, backend, c = harness()
        job = new_job(worker=2)
        job.spec.enable_gang_scheduling = True
        submit(store, c, job)
        pod = backend.get_pod("default", "job-worker-0")
        from tf_operator_tpu.api.types import ANNOTATION_GANG_GROUP

        assert pod.metadata.annotations[ANNOTATION_GANG_GROUP] == "job"
        assert pod.scheduler_name == "tpu-gang"

    def test_all_or_nothing_over_capacity(self):
        store, backend, c = harness(total_chips=16)
        # 2 slices × 16 chips = 32 > 16: must NOT be partially granted
        job = new_job(tpu_slice=2, tpu_topology="v5e-16")
        submit(store, c, job)
        group = backend.get_pod_group("default", "job")
        assert group.phase is PodGroupPhase.PENDING
        # scheduler refuses to run gang-blocked pods
        assert backend.run_all("default") == 0
        pod = backend.get_pod("default", "job-tpuslice-0")
        assert pod.phase is PodPhase.PENDING

    def test_contending_jobs_queue_and_release(self):
        store, backend, c = harness(total_chips=4)
        a = new_job(name="job-a", tpu_slice=1, tpu_topology="v5e-4")
        b = new_job(name="job-b", tpu_slice=1, tpu_topology="v5e-4")
        submit(store, c, a)
        submit(store, c, b)
        assert backend.get_pod_group("default", "job-a").phase is PodGroupPhase.GRANTED
        assert backend.get_pod_group("default", "job-b").phase is PodGroupPhase.PENDING

        # only job-a's slice can run
        backend.run_all("default")
        assert backend.get_pod("default", "job-a-tpuslice-0").phase is PodPhase.RUNNING
        assert backend.get_pod("default", "job-b-tpuslice-0").phase is PodPhase.PENDING

        # job-a finishes; terminal cleanup releases its gang group
        backend.succeed_pod("default", "job-a-tpuslice-0")
        c.sync_until_quiet()
        assert store.get("default", "job-a").status.has_condition(JobConditionType.SUCCEEDED)
        assert backend.get_pod_group("default", "job-a") is None

        # job-b now granted and runnable
        assert backend.get_pod_group("default", "job-b").phase is PodGroupPhase.GRANTED
        backend.run_all("default")
        assert backend.get_pod("default", "job-b-tpuslice-0").phase is PodPhase.RUNNING

    def test_tpu_slice_success_requires_all_members(self):
        store, backend, c = harness()
        job = submit(store, c, new_job(tpu_slice=2, tpu_topology="v5e-4"))
        backend.run_all("default")
        backend.succeed_pod("default", "job-tpuslice-0")
        c.sync_until_quiet()
        st = store.get("default", "job").status
        assert not st.has_condition(JobConditionType.SUCCEEDED)
        backend.succeed_pod("default", "job-tpuslice-1")
        c.sync_until_quiet()
        st = store.get("default", "job").status
        assert st.has_condition(JobConditionType.SUCCEEDED)

    def test_chip_accounting_frees_on_group_delete(self):
        store, backend, c = harness(total_chips=32)
        a = new_job(name="a", tpu_slice=2, tpu_topology="v5e-16")
        submit(store, c, a)
        assert backend.get_pod_group("default", "a").phase is PodGroupPhase.GRANTED
        b = new_job(name="b", tpu_slice=1, tpu_topology="v5e-16")
        submit(store, c, b)
        assert backend.get_pod_group("default", "b").phase is PodGroupPhase.PENDING
        store.delete("default", "a")
        c.sync_until_quiet()
        assert backend.get_pod_group("default", "b").phase is PodGroupPhase.GRANTED
