"""Speculative decoding (models/speculative.py).

The invariant under test: output equals plain greedy `generate` on
the target for ANY draft — a perfect draft (the target itself), an
int8-quantized sibling, and an adversarial random draft.  The draft
only moves speed (acceptance), never content.

Numerics caveat the fixture controls for: verification applies the
target at width k while plain generate applies width 1 — analytically
identical, but matmul tiling differs, so an UNTRAINED model's
near-tied logits can argmax-flip on rounding noise.  The fixture
therefore trains the tiny target a few steps on a periodic byte
pattern; with separated logits the equality is robust (and seeded, so
deterministic).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # generation-loop compiles

from tf_operator_tpu.models import generate, llama_tiny
from tf_operator_tpu.models.speculative import SpeculativeDecoder

VOCAB = 96


_CACHE = {}


import sys as _sys, os as _os
_sys.path.insert(0, _os.path.dirname(__file__))
from testutil import assert_decode_equiv_up_to_ties  # noqa: E402

# width-k verify vs width-1 decode are distinct programs: exact up to
# sub-noise argmax ties (the module's documented scope)
assert_greedy_equiv = assert_decode_equiv_up_to_ties


def _setup(seed=0):
    model = _CACHE.get("model")
    if model is None:
        import optax

        model = llama_tiny(vocab_size=VOCAB, max_len=64)
        # periodic pattern -> confident (well-separated) logits
        seq = np.tile(np.arange(12, dtype=np.int32), 6)[None, :64]
        batch = jnp.asarray(np.repeat(seq, 4, axis=0))
        params = model.init(jax.random.PRNGKey(1), batch)["params"]
        opt = optax.sgd(0.5)
        opt_state = opt.init(params)

        def loss_fn(p):
            logits = model.apply({"params": p}, batch)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], batch[:, 1:]
            ).mean()

        @jax.jit
        def step(params, opt_state):
            upd, opt_state = opt.update(jax.grad(loss_fn)(params), opt_state)
            return optax.apply_updates(params, upd), opt_state

        for _ in range(8):
            params, opt_state = step(params, opt_state)
        _CACHE["model"], _CACHE["params"] = model, params
    prompt = jnp.asarray(
        np.random.RandomState(seed).randint(0, VOCAB, size=(2, 5)), jnp.int32
    )
    return _CACHE["model"], _CACHE["params"], prompt


class TestExactness:
    def test_perfect_draft_accepts_everything(self):
        model, params, prompt = _setup()
        ref = np.asarray(generate(model, params, prompt, max_new_tokens=12))
        dec = SpeculativeDecoder(model, params, model, params, k=4)
        out = dec.generate(prompt, max_new_tokens=12)
        assert_greedy_equiv(model, params, out, ref)
        # a sub-ulp tie between the width-k verify and the width-1
        # draft can reject a proposal without breaking equivalence
        assert dec.acceptance_rate >= 0.9

    def test_adversarial_draft_is_still_exact(self):
        model, params, prompt = _setup()
        draft_params = model.init(jax.random.PRNGKey(99), prompt)["params"]
        ref = np.asarray(generate(model, params, prompt, max_new_tokens=12))
        dec = SpeculativeDecoder(model, params, model, draft_params, k=4)
        out = dec.generate(prompt, max_new_tokens=12)
        assert_greedy_equiv(model, params, out, ref)

    def test_quantized_draft_is_exact_with_high_acceptance(self):
        from tf_operator_tpu.ops.quant import quantize_tree

        model, params, prompt = _setup()
        qparams = quantize_tree(params, min_size=1)
        ref = np.asarray(generate(model, params, prompt, max_new_tokens=10))
        dec = SpeculativeDecoder(model, params, model, qparams, k=4)
        out = dec.generate(prompt, max_new_tokens=10)
        assert_greedy_equiv(model, params, out, ref)

    def test_budget_is_exact_near_max_len(self):
        # prompt 5 + 59 new = 64 = max_len: the final rounds degrade to
        # capped chunks then plain greedy; still exact to the last token
        model, params, prompt = _setup()
        ref = np.asarray(generate(model, params, prompt, max_new_tokens=59))
        dec = SpeculativeDecoder(model, params, model, params, k=4)
        out = dec.generate(prompt, max_new_tokens=59)
        assert_greedy_equiv(model, params, out, ref)


class TestScanDriver:
    """The opt-in chunked-scan fused driver (fused_driver="scan") must
    be token-equivalent to the default while driver — it runs the SAME
    round body, so these exercise the chunk threading: device-resident
    state between chunks, the optimistic-first-chunk + top-up
    schedule, and the packed final fetch."""

    def test_greedy_parity_with_topups(self):
        # adversarial (untrained) draft keeps acceptance low, so the
        # optimistic first chunk (bucket // k rounds) cannot finish
        # and the top-up loop must run
        model, params, prompt = _setup()
        draft_params = model.init(jax.random.PRNGKey(99), prompt)["params"]
        dec = SpeculativeDecoder(model, params, model, draft_params, k=4)
        dec.fused_driver = "while"
        ref = np.asarray(dec.generate(prompt, max_new_tokens=24))
        dec2 = SpeculativeDecoder(model, params, model, draft_params, k=4)
        dec2.fused_driver = "scan"
        out = np.asarray(dec2.generate(prompt, max_new_tokens=24))
        assert_greedy_equiv(model, params, out, ref)
        # the scan driver must not have fallen back to the host loop
        assert any(k[0] == "fused-scan" for k in dec2._fns)

    def test_sampled_parity_same_key(self):
        model, params, prompt = _setup()
        dec = SpeculativeDecoder(model, params, model, params, k=4)
        dec.fused_driver = "while"
        rng = jax.random.PRNGKey(7)
        ref = np.asarray(
            dec.generate(prompt, max_new_tokens=16, temperature=0.8, rng=rng)
        )
        dec2 = SpeculativeDecoder(model, params, model, params, k=4)
        dec2.fused_driver = "scan"
        out = np.asarray(
            dec2.generate(prompt, max_new_tokens=16, temperature=0.8, rng=rng)
        )
        # identical round sequence + identical per-row rng stream:
        # the two drivers run the same draws in the same order
        assert np.array_equal(out, ref)


class TestPerRowRollback:
    def test_batch4_mediocre_draft_beats_min_alignment(self):
        """VERDICT r4 next #6: each row keeps its OWN accepted length
        (per-row cache_index in the stacked caches), so a batch commits
        Σ_r m_r — strictly more than the pre-r5 min-alignment rule's
        B·min(m_r) whenever rows disagree.  `accepted_min_aligned` is
        that counterfactual, tracked per round.  Exactness must hold
        per row at the same time."""

        model, params, _ = _setup()
        # mediocre draft: target weights + enough noise that rows
        # disagree with the target at DIFFERENT positions, but agree
        # often enough that acceptance stays well above zero
        # seeds chosen tie-free: the fixture's trained logits are well
        # separated, but near-ties between the width-k verify and the
        # batched width-1 reference tiling can still argmax-flip (see
        # module docstring caveat) — prompt seed 5 sits on one such
        # tie; seed 11 does not (scanned 0.02-0.04 x seeds {5,6,7,11})
        noise = jax.tree_util.tree_map(
            lambda p, k: p + 0.03 * jax.random.normal(k, p.shape, p.dtype),
            params,
            jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(params),
                list(jax.random.split(
                    jax.random.PRNGKey(3),
                    len(jax.tree_util.tree_leaves(params)),
                )),
            ),
        )
        prompt = jnp.asarray(
            np.random.RandomState(11).randint(0, VOCAB, size=(4, 5)),
            jnp.int32,
        )
        ref = np.asarray(generate(model, params, prompt, max_new_tokens=24))
        dec = SpeculativeDecoder(model, params, model, noise, k=4)
        out = dec.generate(prompt, max_new_tokens=24)
        assert_greedy_equiv(model, params, out, ref)
        # the draft was mediocre, not perfect or useless
        assert 0.05 < dec.acceptance_rate < 1.0
        # per-row rollback accepted strictly more than alignment would
        assert dec.accepted > dec.accepted_min_aligned, (
            dec.accepted, dec.accepted_min_aligned,
        )

    def test_tight_budget_with_asymmetric_rows_stays_exact(self):
        """Freeze-path regression: with per-row rollback, a
        fast-accepting row reaches its budget rounds before a slow one
        and must FREEZE in-graph (stop moving its cache index) rather
        than burn the remaining max_len room.  Tight budget + mediocre
        draft exercises the masked rounds; exactness pins that frozen
        lanes never corrupt active ones."""

        model, params, _ = _setup()
        noise = jax.tree_util.tree_map(
            lambda p, k: p + 0.05 * jax.random.normal(k, p.shape, p.dtype),
            params,
            jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(params),
                list(jax.random.split(
                    jax.random.PRNGKey(4),
                    len(jax.tree_util.tree_leaves(params)),
                )),
            ),
        )
        prompt = jnp.asarray(
            np.random.RandomState(7).randint(0, VOCAB, size=(4, 5)),
            jnp.int32,
        )
        # 5 + 55 = 60 of max_len 64: only 4 tokens of slack
        ref = np.asarray(generate(model, params, prompt, max_new_tokens=55))
        dec = SpeculativeDecoder(model, params, model, noise, k=4)
        out = dec.generate(prompt, max_new_tokens=55)
        assert_greedy_equiv(model, params, out, ref)

    def test_rows_advance_independently(self):
        """A perfect-draft row batched with adversarial-draft-like
        content still reaches full speed: per-row m values differ
        within a round (observable via the aligned counterfactual
        falling behind)."""

        model, params, _ = _setup()
        draft = model.init(
            jax.random.PRNGKey(99), jnp.zeros((1, 4), jnp.int32)
        )["params"]
        prompt = jnp.asarray(
            np.random.RandomState(6).randint(0, VOCAB, size=(3, 5)),
            jnp.int32,
        )
        ref = np.asarray(generate(model, params, prompt, max_new_tokens=16))
        dec = SpeculativeDecoder(model, params, model, draft, k=3)
        out = dec.generate(prompt, max_new_tokens=16)
        assert_greedy_equiv(model, params, out, ref)
        # telemetry consistency: aligned counterfactual can never
        # exceed the per-row total
        assert dec.accepted_min_aligned <= dec.accepted <= dec.proposed


class TestServeLmSpeculativeMode:
    def test_speculative_serves_through_the_paged_pool(self):
        """ISSUE 18: --speculative IS a paged-pool mode — greedy,
        sampling, and top_k requests all serve through the pool;
        interactive-tier requests speculate (the default gate), batch
        ones decode plainly, and the draft lives in the SAME arena."""

        import json
        import threading
        import urllib.request
        from http.server import ThreadingHTTPServer

        from tests.testutil import load_serve_lm

        serve_lm = load_serve_lm()
        model = llama_tiny(vocab_size=256, max_len=64)
        prompt = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        handler = serve_lm.build_handler(
            model, params, max_len=64, speculative=True
        )
        assert handler.pool is not None and handler.pool.spec_enabled
        server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            for payload in (
                # greedy interactive -> speculates (tier-gated default)
                {"prompt": "abc", "max_new_tokens": 6,
                 "tier": "interactive"},
                # sampling -> exact via the in-graph rejection rule
                {"prompt": "abc", "max_new_tokens": 6,
                 "temperature": 0.8, "tier": "interactive"},
                # top_k + default batch tier -> plain pool decode
                {"prompt": "abc", "max_new_tokens": 6,
                 "temperature": 0.8, "top_k": 4},
            ):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/generate",
                    data=json.dumps(payload).encode(),
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=300) as resp:
                    out = json.loads(resp.read())
                assert len(out["sample"]) == 6
        finally:
            server.shutdown()
        snap = handler.pool.spec_snapshot()
        assert snap["spec_windows"] >= 1, (
            "interactive requests never took the speculative path"
        )

    def test_batching_composes_and_typod_tier_fails_startup(self):
        """--speculative composes with --batching (it rides the pool),
        and a typo'd --spec-tiers fails handler construction instead
        of silently serving non-speculatively (PR 10 honesty rule)."""

        from tests.testutil import load_serve_lm

        serve_lm = load_serve_lm()
        model = llama_tiny(vocab_size=256, max_len=64)
        prompt = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        handler = serve_lm.build_handler(
            model, params, max_len=64, batching_slots=2, speculative=True
        )
        assert handler.pool.spec_enabled
        assert handler.pool.slots == 2
        with pytest.raises(ValueError, match="not SLO tiers"):
            serve_lm.build_handler(
                model, params, max_len=64, speculative=True,
                spec_tiers=("interactiv",),
            )
        with pytest.raises(ValueError, match="spec_k"):
            serve_lm.build_handler(
                model, params, max_len=64, speculative=True, spec_k=0,
            )

    def test_speculative_guard_reads_measured_ledger(self, tmp_path):
        """serve_lm --speculative reads the PAGED-PLANE row (ISSUE 18:
        spec_paged_speedup — the configuration it actually serves) and
        refuses while it is a slowdown; the dead pre-paged rows
        (speculative_speedup / speculative_wide_speedup) must neither
        fence NOR unfence it; an unmeasured box stays permissive (no
        claim to enforce)."""

        import json as _json

        from tests.testutil import load_serve_lm

        serve_lm = load_serve_lm()
        row = {"artifact": "a.out", "date": "2026-08-07"}
        p = tmp_path / "LAST_MEASURED.json"
        p.write_text(_json.dumps(
            {"spec_paged_speedup": {"value": 0.8, **row}}
        ))
        best, meta = serve_lm.speculative_slowdown(str(p))
        assert best == 0.8 and meta["metric"] == "spec_paged_speedup"
        # the dead pre-paged rows are ignored in BOTH directions: a
        # 1.2x legacy row can't unfence the paged path...
        p.write_text(_json.dumps({
            "speculative_wide_speedup": {"value": 1.2, **row},
            "spec_paged_speedup": {"value": 0.8, **row},
        }))
        best, meta = serve_lm.speculative_slowdown(str(p))
        assert best == 0.8 and meta["metric"] == "spec_paged_speedup"
        # ...and a 0.1x legacy row can't fence a measured paged win
        p.write_text(_json.dumps({
            "speculative_speedup": {"value": 0.1, **row},
            "spec_paged_speedup": {
                "value": 7.4, "config": "int8 self-draft, k=4", **row
            },
        }))
        best, meta = serve_lm.speculative_slowdown(str(p))
        assert best == 7.4 and meta["config"] == "int8 self-draft, k=4"
        # legacy-only ledger = the paged config is UNMEASURED -> permissive
        p.write_text(_json.dumps(
            {"speculative_speedup": {"value": 0.1, **row}}
        ))
        assert serve_lm.speculative_slowdown(str(p)) == (None, None)
        assert serve_lm.speculative_slowdown(
            str(tmp_path / "missing.json")
        ) == (None, None)

    def test_serve_lm_binary_refuses_measured_slowdown(self):
        """End to end on the real binary + the repo's real ledger: as
        long as the committed LAST_MEASURED.json shows every measured
        speculative config < 1x, `serve_lm --speculative` must exit
        with the measured-slowdown message BEFORE touching the
        artifact (skipped automatically once a window measures a
        config >= 1x — then the guard SHOULD let it serve)."""

        import os
        import subprocess
        import sys

        from tests.testutil import load_serve_lm

        serve_lm = load_serve_lm()
        best, _ = serve_lm.speculative_slowdown()
        if best is None or best >= 1.0:
            pytest.skip("measured ledger shows no slowdown; guard inactive")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "examples", "serve_lm.py"),
             "--speculative", "--artifact", "/nonexistent"],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode != 0
        assert "--speculative refused" in proc.stderr
        assert "--speculative-force" in proc.stderr


class TestScanDriverBound:
    def test_runaway_round_body_raises_instead_of_looping(self, monkeypatch):
        """ADVICE r5: a regression that stops rows from committing must
        surface as an error after the worst-case round budget, not as
        an infinite host loop of device dispatches.  Simulated by
        freezing the n vector the driver's done-check reads."""

        model, params, prompt = _setup()
        dec = SpeculativeDecoder(model, params, model, params, k=2)
        dec.fused_driver = "scan"
        real = dec._fused_scan

        def stuck(k, bucket, b, sampled, r):
            fn = real(k, bucket, b, sampled, r)

            def wrapper(tp, dp, state, n0, limit, temp):
                new_state, packed = fn(tp, dp, state, n0, limit, temp)
                new_state = dict(new_state)
                new_state["n"] = state["n"]  # rows never advance
                return new_state, packed

            return wrapper

        monkeypatch.setattr(dec, "_fused_scan", stuck)
        with pytest.raises(RuntimeError, match="act/freeze"):
            dec.generate(prompt, max_new_tokens=8)


class TestSampling:
    def test_identical_draft_accepts_everything_when_sampling(self):
        # p == q makes the acceptance ratio exactly 1: every proposal
        # accepted, regardless of temperature
        model, params, prompt = _setup()
        dec = SpeculativeDecoder(model, params, model, params, k=4)
        out = dec.generate(
            prompt, max_new_tokens=12, temperature=0.9,
            rng=jax.random.PRNGKey(5),
        )
        assert out.shape == (2, 17)
        assert dec.acceptance_rate == 1.0

    def test_sampling_deterministic_per_key(self):
        model, params, prompt = _setup()
        draft = model.init(jax.random.PRNGKey(42), prompt)["params"]
        outs = []
        for _ in range(2):
            dec = SpeculativeDecoder(model, params, model, draft, k=3)
            outs.append(
                dec.generate(
                    prompt, max_new_tokens=8, temperature=0.8,
                    rng=jax.random.PRNGKey(11),
                )
            )
        np.testing.assert_array_equal(outs[0], outs[1])

    def test_sampled_distribution_matches_target_law(self):
        # The exactness claim, tested against the ANALYTIC law: with
        # vocab 8 the joint distribution of the first two tokens is
        # enumerable exactly — p(a)·p(b|a) — so only the speculative
        # side carries sampling noise (E[TV] ~ 0.05 at ~3.8k draws; a
        # missing-residual bug shifts TV by ~0.1+).  Draft is
        # ADVERSARIAL (random independent weights).
        model = llama_tiny(vocab_size=8, max_len=16)
        prompt = jnp.zeros((64, 3), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        draft = model.init(jax.random.PRNGKey(123), prompt)["params"]

        l1 = model.apply({"params": params}, prompt[:1])[0, -1]
        p1 = np.asarray(jax.nn.softmax(l1), np.float64)
        law_exact = np.zeros((8, 8))
        for a in range(8):
            seq = jnp.concatenate(
                [prompt[:1], jnp.full((1, 1), a, jnp.int32)], axis=1
            )
            l2 = model.apply({"params": params}, seq)[0, -1]
            law_exact[a] = p1[a] * np.asarray(
                jax.nn.softmax(l2), np.float64
            )

        spec = SpeculativeDecoder(model, params, model, draft, k=3)
        counts = np.zeros((8, 8), np.int64)
        for c in range(60):
            out = np.asarray(
                spec.generate(
                    prompt, max_new_tokens=2, temperature=1.0,
                    rng=jax.random.PRNGKey(1000 + c),
                )
            )
            for a, b in out[:, 3:5]:
                counts[a, b] += 1
        law_spec = counts / counts.sum()
        tv = 0.5 * np.abs(law_spec - law_exact).sum()
        assert tv < 0.08, f"total variation {tv:.3f} too large"
        # the adversarial draft really was adversarial (rejections seen)
        assert spec.acceptance_rate < 1.0


class TestValidation:
    def test_rolling_window_rejected(self):
        model = llama_tiny(vocab_size=VOCAB, max_len=64, window=8)
        prompt = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        with pytest.raises(NotImplementedError):
            SpeculativeDecoder(model, params, model, params)

    def test_vocab_mismatch_rejected(self):
        model, params, prompt = _setup()
        other = llama_tiny(vocab_size=VOCAB * 2, max_len=64)
        oparams = other.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )["params"]
        with pytest.raises(ValueError):
            SpeculativeDecoder(model, params, other, oparams)

    def test_overflow_rejected(self):
        model, params, prompt = _setup()
        dec = SpeculativeDecoder(model, params, model, params)
        with pytest.raises(ValueError):
            dec.generate(prompt, max_new_tokens=60)  # 5 + 60 > 64
