"""API fault tolerance, end to end over the HTTP transport.

tests/test_chaos.py proves the level-triggered convergence property for
the in-proc FakeCluster by dropping *watch events*; this suite extends
it to the production-shaped path — controller → retrying HTTP clients
(backend/kube.py, backend/kubejobs.py, cmd/leader.py) → MiniApiServer
with a FaultInjector (backend/kubesim.py) throwing 5xx/429/Retry-After,
connection resets, latency, and watch 410 storms at every layer.

Everything here is deterministic: seeded fault schedules, seeded retry
jitter.  The convergence test is the acceptance gate from ISSUE 1: a
≥10% fault rate on ALL routes must not lose a job, a pod, or an
exception.
"""

import json
import random
import sys
import threading
import time
import urllib.request

import pytest

from tests.testutil import new_job
from tf_operator_tpu.api.types import JobConditionType, PodPhase, SuccessPolicy
from tf_operator_tpu.backend.kube import ApiError, KubeBackend, http_json
from tf_operator_tpu.backend.kubejobs import KubeEventRecorder, KubeJobStore
from tf_operator_tpu.backend.kubesim import MiniApiServer
from tf_operator_tpu.backend.retry import RetryPolicy
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.controller.reconciler import ReconcilerConfig
from tf_operator_tpu.utils.metrics import Metrics

EXIT0 = [sys.executable, "-c", "raise SystemExit(0)"]
SLEEP = [sys.executable, "-c", "import time; time.sleep(600)"]


def fast_policy(seed=0, **kw):
    kw.setdefault("max_attempts", 6)
    kw.setdefault("base_delay", 0.02)
    kw.setdefault("max_delay", 0.2)
    kw.setdefault("deadline", 5.0)
    return RetryPolicy(rng=random.Random(seed), **kw)


def wait_until(cond, timeout=15.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise TimeoutError(what)


class TestFaultInjector:
    """The injector itself: per-route/per-verb targeting, shot counts,
    Retry-After on the wire, latency, resets, and the admin endpoint."""

    @pytest.fixture
    def sim(self):
        s = MiniApiServer(fault_seed=0).start()
        yield s
        s.stop()

    def _get_status(self, sim, path):
        req = urllib.request.Request(sim.url + path)
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                return resp.status, dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers)

    def test_error_mode_targets_route_and_verb(self, sim):
        sim.faults.add(
            path=r"/api/v1/pods", methods=["GET"], mode="error",
            status=503, retry_after=1.5,
        )
        code, headers = self._get_status(sim, "/api/v1/pods")
        assert code == 503
        assert headers.get("Retry-After") == "1.5"
        # other routes and other verbs are untouched
        code, _ = self._get_status(sim, "/api/v1/services")
        assert code == 200
        out = http_json(
            sim._httpd.server_address[0], sim._httpd.server_address[1],
            "POST", "/api/v1/namespaces/default/pods",
            {"metadata": {"name": "p1"}, "spec": {}},
        )
        assert out["metadata"]["name"] == "p1"

    def test_shot_count_bounds_injection(self, sim):
        sim.faults.add(path=r"/api/v1/pods", mode="error", status=500, times=2)
        assert self._get_status(sim, "/api/v1/pods")[0] == 500
        assert self._get_status(sim, "/api/v1/pods")[0] == 500
        assert self._get_status(sim, "/api/v1/pods")[0] == 200
        assert sim.faults.total_injected() == 2

    def test_latency_mode_delays_then_serves(self, sim):
        sim.faults.add(path=r"/api/v1/pods", mode="latency", delay=0.3, times=1)
        t0 = time.time()
        code, _ = self._get_status(sim, "/api/v1/pods")
        assert code == 200
        assert time.time() - t0 >= 0.3

    def test_reset_mode_breaks_the_connection(self, sim):
        sim.faults.add(path=r"/api/v1/pods", mode="reset", times=1)
        host, port = sim._httpd.server_address[:2]
        with pytest.raises(OSError):
            # ConnectionResetError or a half-closed-socket HTTPException
            # subclassing OSError — either way, a transport failure
            http_json(host, port, "GET", "/api/v1/pods")
        # next request is clean
        assert self._get_status(sim, "/api/v1/pods")[0] == 200

    def test_watch_gone_storm_rule(self, sim):
        sim.faults.add(
            path=r"watch=true", mode="error", status=410, times=1
        )
        code, _ = self._get_status(
            sim, "/api/v1/pods?watch=true&resourceVersion=1"
        )
        assert code == 410
        # plain (non-watch) list is untouched by the storm rule
        assert self._get_status(sim, "/api/v1/pods")[0] == 200

    def test_admin_endpoint_add_list_clear(self, sim):
        req = urllib.request.Request(
            sim.url + "/_faults",
            data=json.dumps(
                {"path": r"/api/v1/pods", "mode": "error", "status": 503,
                 "retryAfter": 0.5, "times": 1}
            ).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 201
            rule = json.loads(resp.read())
        assert rule["status"] == 503 and rule["retryAfter"] == 0.5
        assert self._get_status(sim, "/api/v1/pods")[0] == 503
        with urllib.request.urlopen(sim.url + "/_faults", timeout=5) as resp:
            rules = json.loads(resp.read())["rules"]
        assert len(rules) == 1 and rules[0]["injected"] == 1
        req = urllib.request.Request(
            sim.url + "/_faults", method="DELETE"
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 200
        with urllib.request.urlopen(sim.url + "/_faults", timeout=5) as resp:
            assert json.loads(resp.read())["rules"] == []

    def test_admin_coerces_string_retry_after(self, sim):
        """JSON clients send numbers as strings; the rule must coerce
        at admission so the fault fires with a well-formed header."""

        req = urllib.request.Request(
            sim.url + "/_faults",
            data=json.dumps(
                {"path": r"/api/v1/pods", "mode": "error", "status": 429,
                 "retryAfter": "1.5", "times": 1}
            ).encode(),
            method="POST",
        )
        assert urllib.request.urlopen(req, timeout=5).status == 201
        code, headers = self._get_status(sim, "/api/v1/pods")
        assert code == 429
        assert headers.get("Retry-After") == "1.5"

    def test_admin_rejects_bad_rule(self, sim):
        req = urllib.request.Request(
            sim.url + "/_faults",
            data=json.dumps({"mode": "nonsense"}).encode(),
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 400


class TestRetrySmoke:
    """Tier-1-safe fast smoke (deterministic seeds, sub-second): each
    client layer rides out a short injected fault burst."""

    def test_backend_rides_out_503_burst_with_retry_after(self):
        sim = MiniApiServer(fault_seed=0).start()
        m = Metrics()
        b = KubeBackend(sim.url, retry=fast_policy(), metrics=m)
        try:
            sim.faults.add(
                path=r"/api/v1/namespaces/default/pods", methods=["POST"],
                mode="error", status=503, retry_after=0.01, times=2,
            )
            from tf_operator_tpu.api.types import Container, ObjectMeta
            from tf_operator_tpu.backend.objects import Pod

            b.create_pod(Pod(
                metadata=ObjectMeta(name="p1", namespace="default"),
                containers=[Container(command=list(SLEEP))],
            ))
            assert b.get_pod("default", "p1") is not None
            assert m.counter(
                "api_client_retries_total", client="kube-backend"
            ) >= 2
        finally:
            b.close()
            sim.stop()

    def test_backend_rides_out_connection_resets(self):
        sim = MiniApiServer(fault_seed=0).start()
        m = Metrics()
        b = KubeBackend(sim.url, retry=fast_policy(), metrics=m)
        try:
            sim.faults.add(
                path=r"/api/v1/pods", methods=["GET"], mode="reset", times=2,
            )
            assert b.list_pods(None) == []  # /api/v1/pods, retried
            assert m.counter(
                "api_client_retries_total", client="kube-backend"
            ) >= 2
        finally:
            b.close()
            sim.stop()

    def test_jobstore_rides_out_faults_and_exports_counters(self):
        sim = MiniApiServer(fault_seed=0).start()
        m = Metrics()
        store = KubeJobStore(sim.url, retry=fast_policy(), metrics=m)
        try:
            sim.faults.add(
                path=r"/apis/tpujob.dist", mode="error", status=429,
                retry_after=0.01, times=3,
            )
            job = new_job("smoke", worker=1, command=EXIT0)
            store.create(job)
            assert store.get("default", "smoke") is not None
            assert m.counter(
                "api_client_retries_total", client="kube-jobs"
            ) >= 3
            # counters flow into the Prometheus exposition
            assert "api_client_retries_total" in m.exposition()
        finally:
            store.close()
            sim.stop()


class TestCreateReplayAmbiguity:
    def test_replayed_create_409_resolves_as_success_when_spec_matches(self):
        """Against a real apiserver a create can commit while its
        response is lost; the retry layer's replay then lands 409.
        KubeJobStore.create must recognise 'the stored object is
        exactly what I posted' as success — and still surface a
        genuine conflict for a different pre-existing job."""

        from tf_operator_tpu.backend.base import AlreadyExistsError

        sim = MiniApiServer(fault_seed=0).start()
        store = KubeJobStore(sim.url, retry=fast_policy())
        try:
            POST_RULE = dict(
                path=r"/apis/tpujob\.dist/v1/namespaces/default/tpujobs$",
                methods=["POST"], times=1,
            )
            job = new_job("dup", worker=2, command=EXIT0)
            stored = store.create(job)
            # a FIRST-ATTEMPT 409 (no replay) is a genuine duplicate
            # submission and must stay a conflict, even spec-identical
            with pytest.raises(AlreadyExistsError):
                store.create(new_job("dup", worker=2, command=EXIT0))
            # a retry after a DEFINITIVE error response (503 = the
            # server answered, nothing committed) is not ambiguous
            # either: the replayed 409 is still a real conflict
            sim.faults.add(mode="error", status=503, **POST_RULE)
            with pytest.raises(AlreadyExistsError):
                store.create(new_job("dup", worker=2, command=EXIT0))
            # the committed-but-response-LOST shape (connection reset,
            # no response): the replay lands 409 and the stored spec
            # matches what we posted → resolves as our own create
            sim.faults.add(mode="reset", **POST_RULE)
            replay = new_job("dup", worker=2, command=EXIT0)
            again = store.create(replay)
            assert again.metadata.uid == stored.metadata.uid
            assert replay.metadata.uid == stored.metadata.uid
            # lost-response replay against a DIFFERENT stored spec
            # still surfaces the conflict
            sim.faults.add(mode="reset", **POST_RULE)
            with pytest.raises(AlreadyExistsError):
                store.create(new_job("dup", worker=3, command=EXIT0))
        finally:
            store.close()
            sim.stop()


class TestWatchGoneRelist:
    def test_kubejobs_watch_410_storm_relists_and_recovers(self):
        """The untested path from ISSUE 1: KubeJobStore's ListAndWatch
        must treat a watch-stream 410 as 'window expired', re-list,
        and keep delivering — under a storm of them."""

        sim = MiniApiServer(fault_seed=0).start()
        m = Metrics()
        store = KubeJobStore(sim.url, retry=fast_policy(), metrics=m)
        try:
            store.create(new_job("old", worker=1, command=SLEEP))
            # every watch attempt 410s three times before one connects
            sim.faults.add(
                path=r"/apis/tpujob\.dist/v1/tpujobs\?watch=true",
                mode="error", status=410, times=3,
            )
            seen = []
            store.subscribe(lambda ev: seen.append(ev.obj.metadata.name))
            # the pre-existing job arrives via the re-list replay...
            wait_until(lambda: "old" in seen, what="relist replay")
            # ...and once the storm is spent, the live stream delivers
            wait_until(
                lambda: sim.faults.total_injected() >= 3, what="storm spent"
            )
            store.create(new_job("fresh", worker=1, command=SLEEP))
            wait_until(lambda: "fresh" in seen, what="post-storm live event")
            assert m.counter("api_watch_gone_total", kind="TPUJob") >= 1
        finally:
            store.close()
            sim.stop()


class TestLeaseUnderFaults:
    def _lease(self, sim, ident, m, **kw):
        from tf_operator_tpu.cmd.leader import KubeLease

        kw.setdefault("lease_duration", 1.0)
        kw.setdefault("metrics", m)
        kw.setdefault(
            "retry",
            RetryPolicy(
                max_attempts=3, base_delay=0.02, max_delay=0.1,
                deadline=0.3, rng=random.Random(1),
            ),
        )
        return KubeLease(sim.url, identity=ident, **kw)

    def test_renewal_survives_bounded_500_burst(self):
        """A burst shorter than the lease deadline must NOT demote:
        the retrying client + the renew loop's transient-vs-fatal
        policy absorb it."""

        sim = MiniApiServer(fault_seed=0).start()
        m = Metrics()
        lost = []
        lease = self._lease(sim, "a", m, on_lost=lambda: lost.append(True))
        try:
            assert lease.try_acquire()
            # 4 shots ≈ one whole renew tick's calls all failing
            sim.faults.add(
                path=r"/apis/coordination\.k8s\.io", mode="error",
                status=500, times=4,
            )
            time.sleep(1.6)  # several renew periods (duration/3 = 0.33s)
            assert lease.is_leader, "bounded burst must not demote"
            assert not lost
            assert m.counter(
                "api_client_retries_total", client="kube-lease"
            ) >= 1
            assert lease.holder() == "a"
        finally:
            lease.release()
            sim.stop()

    def test_total_outage_still_demotes_within_lease_deadline(self):
        """Retries must not MASK a real outage: when the apiserver
        stays down past the lease duration, on_lost fires (the
        split-brain guard keeps working under the retry layer)."""

        sim = MiniApiServer(fault_seed=0).start()
        m = Metrics()
        lost = []
        lease = self._lease(sim, "a", m, on_lost=lambda: lost.append(True))
        try:
            assert lease.try_acquire()
            sim.faults.add(
                path=r"/apis/coordination\.k8s\.io", mode="error", status=500,
            )
            wait_until(lambda: lost, timeout=5.0, what="on_lost under outage")
            assert not lease.is_leader
        finally:
            lease.release()
            sim.stop()


class TestConvergenceUnderFaults:
    """ISSUE 1 acceptance: ≥10% injected 5xx/429/reset on ALL apiserver
    routes; a controller + KubeJobStore drive a multi-replica job to
    Succeeded with no lost pods, no unhandled exceptions, and non-zero
    exported retry counters."""

    def test_multi_replica_job_succeeds_under_fault_schedule(self):
        sim = MiniApiServer(fault_seed=1234).start()
        # combined ~13% fault probability across every route — resets,
        # 503+Retry-After, and naked 429s
        sim.faults.add(mode="error", status=503, retry_after=0.02,
                       probability=0.05)
        sim.faults.add(mode="error", status=429, probability=0.04)
        sim.faults.add(mode="reset", probability=0.04)

        m = Metrics()
        store = KubeJobStore(sim.url, retry=fast_policy(seed=1), metrics=m)
        backend = KubeBackend(sim.url, retry=fast_policy(seed=2), metrics=m)
        recorder = KubeEventRecorder(sim.url, metrics=m)
        controller = TPUJobController(
            store, backend,
            config=ReconcilerConfig(resolver=backend.resolver),
            metrics=m, recorder=recorder,
            resync_period=0.3, expectations_timeout=0.3,
        )

        crashes = []
        prev_hook = threading.excepthook
        threading.excepthook = lambda args: crashes.append(args)
        try:
            controller.run(threadiness=2)
            # ALL_WORKERS success: the job is terminal only when every
            # one of the 3 replicas ran to completion — so Succeeded
            # proves no pod was lost to the fault schedule
            job = new_job("chaos-http", worker=3, command=EXIT0)
            job.spec.success_policy = SuccessPolicy.ALL_WORKERS
            store.create(job)

            def succeeded():
                j = store.get("default", "chaos-http")
                return j is not None and j.status.has_condition(
                    JobConditionType.SUCCEEDED
                )

            wait_until(succeeded, timeout=60.0, what="job Succeeded")
            pods = backend.list_pods("default")
            assert {p.metadata.name for p in pods} == {
                f"chaos-http-worker-{i}" for i in range(3)
            }
            assert all(p.phase is PodPhase.SUCCEEDED for p in pods)
        finally:
            threading.excepthook = prev_hook
            controller.stop()
            recorder.close()
            backend.close()
            store.close()
            sim.stop()

        assert not crashes, f"unhandled thread exceptions: {crashes}"
        assert sim.faults.total_injected() > 0, "schedule never fired"
        # the observability story: retries happened and are exported
        assert m.total("api_client_retries_total") > 0
        exposition = m.exposition()
        assert "api_client_retries_total" in exposition
        assert "api_client_errors_total" in exposition
