"""Paged KV-cache serving (ISSUE 8 tentpole): block-granular
admission, shared prefix cache, multi-replica router.

The load-bearing pins:

- EXACTNESS: paged + prefix-cached decode produces token-identical
  output to the contiguous pool for a seeded mixed request set (the
  gather/scatter is an identity re-layout feeding the same compiled
  math).
- ZERO-PREFILL FULL HIT: a request whose prompt's full blocks are all
  cached admits in exactly ONE fused dispatch with 0 prefill-phase
  dispatches and the admission width collapsed to the remainder class
  (DispatchLedger-pinned — extending the PR-3 single-dispatch
  contract).
- CAPACITY: at an equal HBM arena budget the paged pool admits
  strictly more concurrent mixed-length requests than the slot pool.
- No aliasing: allocator conservation holds after every scenario and
  shared blocks are never reclaimed while mapped.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # generation-loop compiles

import jax
import jax.numpy as jnp

from tf_operator_tpu.models import llama_tiny
from tf_operator_tpu.models.batching import (
    ContinuousBatchingDecoder,
    PagedContinuousBatchingDecoder,
)
from tf_operator_tpu.models.pool_router import PoolRouter
from tf_operator_tpu.utils.metrics import Metrics

VOCAB = 96


def _setup(max_len=64):
    model = llama_tiny(vocab_size=VOCAB, max_len=max_len)
    init = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.PRNGKey(1), init)["params"]
    return model, params


def _prompts(r, lens):
    return [r.randint(0, VOCAB, size=(l,)).astype(np.int32) for l in lens]


class TestPagedExactness:
    def test_token_identical_to_contiguous_for_seeded_mix(self):
        """The acceptance exactness pin: a seeded mixed-length request
        set — greedy and temperature, short and multi-block prompts,
        a repeated prompt that takes the prefix-cache hit path —
        produces byte-identical rows through the paged pool and the
        contiguous pool."""

        model, params = _setup()
        r = np.random.RandomState(7)
        sys_prompt = r.randint(0, VOCAB, size=(35,)).astype(np.int32)
        reqs = [
            (_p, kw)
            for _p, kw in [
                (sys_prompt, dict(max_new_tokens=5)),
                # shares sys_prompt's first two full blocks
                (np.concatenate([sys_prompt[:32],
                                 r.randint(0, VOCAB, size=(6,))
                                 .astype(np.int32)]),
                 dict(max_new_tokens=6)),
                (_prompts(r, [3])[0], dict(max_new_tokens=9)),
                # full-hit repeat, sampled
                (sys_prompt, dict(max_new_tokens=7, temperature=0.8,
                                  rng=jax.random.PRNGKey(9))),
                (_prompts(r, [17])[0],
                 dict(max_new_tokens=4, temperature=1.1, top_k=8,
                      rng=jax.random.PRNGKey(3))),
            ]
        ]

        base = ContinuousBatchingDecoder(model, params, slots=4)
        want = []
        for p, kw in reqs:
            rid = base.submit(p, **kw)
            base.run()
            want.append(base.result(rid))

        paged = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16
        )
        rids = []
        for p, kw in reqs:
            rids.append(paged.submit(p, **kw))
            paged.step()  # staggered: hit paths see published blocks
        paged.run()
        for rid, w in zip(rids, want):
            np.testing.assert_array_equal(paged.result(rid), w)
        # every scenario ends with the arena conserved: live blocks
        # are exactly the prefix cache's published ones
        paged.alloc.check()
        assert paged.alloc.in_use == len(paged.prefix)
        assert paged.prefix.hits >= 1  # the repeat really hit

    def test_slot_isolation_under_occupancy(self):
        model, params = _setup()
        r = np.random.RandomState(11)
        prompts = _prompts(r, [5, 9, 3])
        solo = []
        for p in prompts:
            dec = PagedContinuousBatchingDecoder(
                model, params, slots=4, kv_block_size=16
            )
            rid = dec.submit(p, max_new_tokens=6)
            dec.run()
            solo.append(dec.result(rid))
        dec = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16
        )
        rids = [dec.submit(p, max_new_tokens=6) for p in prompts]
        dec.run()
        for rid, w in zip(rids, solo):
            np.testing.assert_array_equal(dec.result(rid), w)

    def test_overshoot_at_max_len_cannot_corrupt_published_blocks(self):
        """A request ending exactly at max_len overshoots its final
        K-window past the cache edge (the in-view writes clamp, like
        the contiguous pool's documented dead-row writes).  The
        clamped positions land only in the seat's OWN tail block —
        a later request mapping the retiree's published prefix blocks
        must still decode token-identically."""

        model, params = _setup(max_len=64)
        r = np.random.RandomState(9)
        prompt = r.randint(0, VOCAB, size=(34,)).astype(np.int32)
        tail = r.randint(0, VOCAB, size=(5,)).astype(np.int32)
        follow = np.concatenate([prompt[:32], tail])

        base = ContinuousBatchingDecoder(model, params, slots=2,
                                         steps_per_sync=8)
        b1 = base.submit(prompt, max_new_tokens=30)  # 34 + 30 == 64
        base.run()
        base.result(b1)
        b2 = base.submit(follow, max_new_tokens=6)
        base.run()
        want = base.result(b2)

        paged = PagedContinuousBatchingDecoder(
            model, params, slots=2, kv_block_size=16, steps_per_sync=8
        )
        p1 = paged.submit(prompt, max_new_tokens=30)
        paged.run()
        assert paged.result(p1) is not None
        p2 = paged.submit(follow, max_new_tokens=6)  # maps published blocks
        paged.run()
        np.testing.assert_array_equal(paged.result(p2), want)
        assert paged.prefix.hits == 1
        paged.alloc.check()

    def test_non_pow2_block_size_straddle_is_exact(self):
        """Review regression: a block size that divides max_len but
        NOT the pow2 width class (48, bs=12: a 13-token prompt pads to
        width 16, straddling two blocks) — the admission scatter must
        CEIL its block count or the straddle block is dropped (and the
        never-written block could even publish into the prefix
        cache)."""

        model, params = _setup(max_len=48)
        r = np.random.RandomState(13)
        reqs = [
            (r.randint(0, VOCAB, size=(13,)).astype(np.int32),
             dict(max_new_tokens=6)),
            (r.randint(0, VOCAB, size=(25,)).astype(np.int32),
             dict(max_new_tokens=5)),
        ]
        base = ContinuousBatchingDecoder(model, params, slots=2)
        want = []
        for p, kw in reqs:
            rid = base.submit(p, **kw)
            base.run()
            want.append(base.result(rid))
        paged = PagedContinuousBatchingDecoder(
            model, params, slots=2, kv_block_size=12
        )
        for (p, kw), w in zip(reqs, want):
            rid = paged.submit(p, **kw)
            paged.run()
            np.testing.assert_array_equal(paged.result(rid), w)
        # repeat the first prompt: its published straddle-adjacent
        # block must hold REAL prefill content
        rid = paged.submit(reqs[0][0], **reqs[0][1])
        paged.run()
        np.testing.assert_array_equal(paged.result(rid), want[0])
        assert paged.prefix.hits == 1
        paged.alloc.check()

    def test_rolling_window_models_are_refused(self):
        from tf_operator_tpu.models.kv_blocks import NotPageableError

        model = llama_tiny(vocab_size=VOCAB, max_len=48, window=8)
        init = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), init)["params"]
        with pytest.raises(NotPageableError):
            PagedContinuousBatchingDecoder(model, params, slots=2)
        # config errors are NOT NotPageableError: serve_lm's fallback
        # must not swallow them (review regression)
        model2, params2 = _setup(max_len=64)
        with pytest.raises(ValueError) as ei:
            PagedContinuousBatchingDecoder(
                model2, params2, slots=2, kv_block_size=24  # !| 64
            )
        assert not isinstance(ei.value, NotPageableError)


class TestFullPrefixHit:
    def test_full_hit_admits_with_zero_prefill_dispatches(self):
        """Ledger pin: a repeat of a multi-block prompt maps its full
        blocks copy-free and admits in ONE 'admission' dispatch — 0
        prefill-phase dispatches ever, the legacy prefill jit caches
        stay empty, and the fused program runs at the REMAINDER width
        class (<= one block), not the prompt's."""

        model, params = _setup()
        r = np.random.RandomState(5)
        prompt = r.randint(0, VOCAB, size=(33,)).astype(np.int32)
        dec = PagedContinuousBatchingDecoder(
            model, params, slots=2, kv_block_size=16
        )
        r1 = dec.submit(prompt, max_new_tokens=4)
        dec.run()
        assert dec.result(r1) is not None
        assert len(dec.prefix) == 2  # both full blocks published
        first_widths = sorted(dec._admit_fns)  # the miss compiled 64

        r2 = dec.submit(prompt, max_new_tokens=6)  # full hit
        dec.run()
        assert dec.result(r2) is not None
        assert dec.prefix.hits == 1
        # exactly one admission per request, zero prefill/sample/
        # scatter dispatches, legacy machinery never constructed
        assert dec.ledger.count("admission") == 2
        assert dec.ledger.count("prefill") == 0
        assert dec.ledger.count("sample") == 0
        assert dec.ledger.count("scatter") == 0
        assert dec._prefill_fns == {} and dec._scatter_fn is None
        # the full hit compiled/ran the remainder class: 33 - 32
        # cached = 1 token -> width 1, vs the miss's width-64 program
        new_widths = sorted(set(dec._admit_fns) - set(first_widths))
        assert new_widths == [1]
        dec.alloc.check()

    def test_shared_blocks_never_reclaimed_while_mapped(self):
        """A seat decoding over shared prefix blocks pins them: arena
        pressure may evict every cold cache entry but the mapped
        blocks survive until the seat retires."""

        model, params = _setup()
        r = np.random.RandomState(6)
        prompt = r.randint(0, VOCAB, size=(33,)).astype(np.int32)
        # arena of 6 blocks: the long-lived request holds 2 shared + 2
        # fresh; pressure then forces eviction attempts
        dec = PagedContinuousBatchingDecoder(
            model, params, slots=3, kv_block_size=16, kv_blocks=6
        )
        warm = dec.submit(prompt, max_new_tokens=4)
        dec.run()
        dec.result(warm)
        shared_bids = [dec.prefix.peek(k) for k in list(
            dec.prefix._entries)]
        assert len(shared_bids) == 2
        # long-runner maps the shared blocks and stays active
        long_rid = dec.submit(prompt, max_new_tokens=25)
        dec._admit()
        for bid in shared_bids:
            assert dec.alloc.refcount(bid) == 2  # cache + seat
        # now a burst that wants more blocks than are free: eviction
        # pressure must NOT reclaim the mapped shared blocks
        burst = dec.submit(r.randint(0, VOCAB, size=(20,)).astype(np.int32),
                           max_new_tokens=12)
        dec.run()
        assert dec.result(long_rid) is not None
        assert dec.result(burst) is not None
        dec.alloc.check()


class TestBlockGatedAdmission:
    def test_admission_gates_on_blocks_not_slots(self):
        """The capacity acceptance pin: at the SAME HBM arena budget
        (2 max_len slots' worth of KV), the paged pool concurrently
        admits every short request while the slot pool caps at 2."""

        model, params = _setup()
        r = np.random.RandomState(3)
        prompts = _prompts(r, [6, 6, 6, 6, 6])

        slot_pool = ContinuousBatchingDecoder(model, params, slots=2)
        for p in prompts:
            slot_pool.submit(p, max_new_tokens=10)
        slot_pool._admit()
        with slot_pool._lock:
            slot_concurrent = len(slot_pool._active)
        assert slot_concurrent == 2  # seats are the cap

        # same budget: 2 slots x (64/16) blocks = 8 blocks
        paged = PagedContinuousBatchingDecoder(
            model, params, slots=8, kv_block_size=16, kv_blocks=8
        )
        rids = [paged.submit(p, max_new_tokens=10) for p in prompts]
        paged._admit()
        with paged._lock:
            paged_concurrent = len(paged._active)
        assert paged_concurrent == 5  # strictly more, same memory
        paged.run()
        slot_pool.run()
        for rid in rids:
            assert paged.result(rid) is not None
        paged.alloc.check()

    def test_queue_holds_until_blocks_free(self):
        model, params = _setup()
        r = np.random.RandomState(4)
        big = _prompts(r, [20, 20, 20])
        dec = PagedContinuousBatchingDecoder(
            model, params, slots=6, kv_block_size=16, kv_blocks=4
        )
        rids = [dec.submit(p, max_new_tokens=14) for p in big]  # 3 blocks ea
        dec._admit()
        with dec._lock:
            assert len(dec._active) == 1 and len(dec._queue) == 2
        dec.run()  # retires free blocks; the queue drains
        for rid, p in zip(rids, big):
            out = dec.result(rid)
            assert out.shape == (p.size + 14,)
            np.testing.assert_array_equal(out[: p.size], p)
        dec.alloc.check()

    def test_submit_rejects_requests_larger_than_the_arena(self):
        model, params = _setup()
        dec = PagedContinuousBatchingDecoder(
            model, params, slots=2, kv_block_size=16, kv_blocks=3
        )
        with pytest.raises(ValueError):
            dec.submit(np.zeros((40,), np.int32), max_new_tokens=24)

    def test_pressure_evicts_cold_cache_entries(self):
        """Staging-backpressure satellite: queued work never pins
        device memory (submit is host-only under paging), and arena
        pressure reclaims UNMAPPED prefix-cache blocks LRU-first
        instead of blocking admission."""

        model, params = _setup()
        r = np.random.RandomState(8)
        prompt = r.randint(0, VOCAB, size=(33,)).astype(np.int32)
        dec = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, kv_blocks=4
        )
        x = dec.submit(prompt, max_new_tokens=4)
        dec.run()
        dec.result(x)
        assert len(dec.prefix) == 2 and dec.alloc.in_use == 2
        # 4-block request: only 2 free -> evicts both cold entries
        y = dec.submit(r.randint(0, VOCAB, size=(30,)).astype(np.int32),
                       max_new_tokens=20)
        dec.run()
        assert dec.result(y) is not None
        # both cold entries reclaimed; the new prompt's own full block
        # is published in their place
        assert dec.prefix.evictions == 2 and len(dec.prefix) == 1
        dec.alloc.check()

    def test_gauges_track_blocks_and_pressure(self):
        model, params = _setup()
        m = Metrics()
        dec = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16, kv_blocks=8,
            metrics=m, model_label="t",
        )
        # the {role=} key rides every kv_blocks_* series (ISSUE 13);
        # unified pools export role="unified"
        assert m.gauge(
            "kv_blocks_free", model="t", replica="0", role="unified"
        ) == 8.0
        assert m.gauge(
            "kv_blocks_total", model="t", replica="0", role="unified"
        ) == 8.0
        rid = dec.submit(np.arange(20, dtype=np.int32) % VOCAB,
                         max_new_tokens=20)  # 3 blocks
        dec._admit()
        assert m.gauge(
            "kv_blocks_free", model="t", replica="0", role="unified"
        ) == 5.0
        assert m.gauge(
            "kv_blocks_pressure", model="t", replica="0", role="unified"
        ) == pytest.approx(3 / 8)
        dec.run()
        dec.result(rid)
        # retire frees the non-published blocks; the published prompt
        # block stays under the cache's reference
        assert m.gauge(
            "kv_blocks_free", model="t", replica="0", role="unified"
        ) == 7.0


class TestFusedKernelStep:
    """ISSUE 10: the Pallas paged-attention decode step (run through
    the interpreter — the same kernel path that compiles on TPU)."""

    def test_kernel_step_token_identical_to_contiguous(self):
        """The acceptance pin: greedy, temperature+top_k, and a
        prefix-hit repeat decode token-identically to the contiguous
        pool when the steady-state step reads KV straight off the
        arena (no gather, no scatter-back, in-place appends)."""

        model, params = _setup()
        r = np.random.RandomState(21)
        sys_prompt = r.randint(0, VOCAB, size=(33,)).astype(np.int32)
        reqs = [
            (sys_prompt, dict(max_new_tokens=5)),
            # straddle: 17 tokens end one past a block boundary
            (r.randint(0, VOCAB, size=(17,)).astype(np.int32),
             dict(max_new_tokens=6, temperature=0.9, top_k=8,
                  rng=jax.random.PRNGKey(5))),
            (sys_prompt, dict(max_new_tokens=4)),  # full-block hit
        ]
        base = ContinuousBatchingDecoder(model, params, slots=4)
        want = []
        for p, kw in reqs:
            rid = base.submit(p, **kw)
            base.run()
            want.append(base.result(rid))

        paged = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16,
            paged_kernel="interpret",
        )
        assert paged._kernel_impl == "pallas-interpret"
        rids = []
        for p, kw in reqs:
            rids.append(paged.submit(p, **kw))
            paged.step()  # staggered: the repeat sees published blocks
        paged.run()
        for rid, w in zip(rids, want):
            np.testing.assert_array_equal(paged.result(rid), w)
        assert paged.prefix.hits >= 1
        paged.alloc.check()

    def test_paged_kernel_on_fails_off_tpu_instead_of_downgrading(self):
        """The honesty rule: an explicit --paged-kernel on must FAIL
        where the kernel cannot serve — as a config-class ValueError
        (serve_lm's NotPageableError fallback must NOT swallow it)."""

        from tf_operator_tpu.models.kv_blocks import NotPageableError

        if jax.default_backend() == "tpu":
            pytest.skip("TPU backend: the compiled kernel applies")
        model, params = _setup()
        with pytest.raises(ValueError) as ei:
            PagedContinuousBatchingDecoder(
                model, params, slots=2, kv_block_size=16,
                paged_kernel="on",
            )
        assert not isinstance(ei.value, NotPageableError)
        assert "backend" in str(ei.value)
        with pytest.raises(ValueError):
            PagedContinuousBatchingDecoder(
                model, params, slots=2, kv_block_size=16,
                paged_kernel="sideways",
            )
        # auto on CPU quietly serves the emulation (documented)
        dec = PagedContinuousBatchingDecoder(
            model, params, slots=2, kv_block_size=16, paged_kernel="auto",
        )
        assert dec._kernel_impl is None
        # an UNPAGEABLE model turns an explicit kernel request into a
        # config error too (ValueError, not the NotPageableError that
        # serve_lm's model-shape fallback would quietly swallow) —
        # and a typo'd mode fails before pageability is even checked
        win_model = llama_tiny(vocab_size=VOCAB, max_len=48, window=8)
        win_params = win_model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )["params"]
        for bad_mode in ("interpret", "sideways"):
            with pytest.raises(ValueError) as ei:
                PagedContinuousBatchingDecoder(
                    win_model, win_params, slots=2,
                    paged_kernel=bad_mode,
                )
            assert not isinstance(ei.value, NotPageableError)


class TestDeviceResidentState:
    def test_steady_state_is_one_dispatch_per_step_and_no_uploads(self):
        """The ISSUE 10 ledger pin: a decode window is exactly ONE
        ``step`` dispatch — no per-step table uploads, host gathers,
        prefill or scatter phases ever appear; the only non-step
        dispatches are the once-per-request ``admission`` (which
        writes the device table delta in-graph) and the batched
        ``retire`` reset."""

        model, params = _setup()
        dec = PagedContinuousBatchingDecoder(
            model, params, slots=2, kv_block_size=16, steps_per_sync=4
        )
        rid = dec.submit(
            np.arange(9, dtype=np.int32) % VOCAB, max_new_tokens=13,
            temperature=0.7, rng=jax.random.PRNGKey(2),
        )
        dec.step()  # admission + window 1
        assert dec.ledger.count("admission") == 1
        assert dec.ledger.count("step") == 1
        assert dec.ledger.count("retire") == 0
        mid = dec.ledger.count()
        dec.step()  # steady state: window 2, nothing else
        assert dec.ledger.count() == mid + 1
        assert dec.ledger.count("step") == 2
        dec.run()
        assert dec.result(rid) is not None
        snap = dec.ledger.snapshot()
        assert set(snap) <= {"admission", "step", "retire"}, snap
        assert dec.ledger.count("prefill") == 0
        assert dec.ledger.count("sample") == 0
        assert dec.ledger.count("scatter") == 0
        assert dec.ledger.count("retire") == 1  # batched, once
        # the retired seat's device row went back to scratch/zero (its
        # freed blocks may re-allocate immediately); never-admitted
        # slots keep their harmless scratch-routed drift
        assert int(np.asarray(dec._tables_dev).max()) == 0  # all scratch
        assert int(np.asarray(dec._lengths_dev)[0]) == 0  # seat 0 retired
        dec.alloc.check()

    def test_pressure_ramps_with_queued_demand(self):
        """ISSUE 10 satellite: kv_blocks_pressure includes queued
        block demand and refreshes per decode window — a burst the
        arena cannot admit ramps the signal ABOVE occupancy (and past
        1.0 under backlog) instead of step-functioning at admission."""

        model, params = _setup()
        m = Metrics()
        dec = PagedContinuousBatchingDecoder(
            model, params, slots=6, kv_block_size=16, kv_blocks=4,
            metrics=m, model_label="t",
        )
        g = lambda name: m.gauge(name, model="t", replica="0",
                                 role="unified")
        r = np.random.RandomState(3)
        first = dec.submit(r.randint(0, VOCAB, size=(20,)).astype(np.int32),
                           max_new_tokens=14)  # 3 of 4 blocks
        dec._admit()
        assert g("kv_blocks_pressure") == pytest.approx(3 / 4)
        # two more queue (the head needs 3 blocks, only 1 free): the
        # gauge now carries demand, not just occupancy
        more = [
            dec.submit(r.randint(0, VOCAB, size=(20,)).astype(np.int32),
                       max_new_tokens=14)
            for _ in range(2)
        ]
        dec.step()  # decode window refreshes the gauges
        assert g("kv_blocks_queued_demand") == 6.0
        assert g("kv_blocks_pressure") == pytest.approx((3 + 6) / 4)
        dec.run()
        for rid in [first] + more:
            assert dec.result(rid) is not None
        assert g("kv_blocks_queued_demand") == 0.0
        dec.alloc.check()


class TestPoolRouter:
    def test_least_blocks_routing_and_result_surface(self):
        model, params = _setup()
        pools = [
            PagedContinuousBatchingDecoder(
                model, params, slots=4, kv_block_size=16, kv_blocks=8,
                replica_label=str(i),
            )
            for i in range(2)
        ]
        router = PoolRouter(pools)
        r = np.random.RandomState(2)
        prompts = _prompts(r, [6, 6, 6, 6])
        rids = [router.submit(p, max_new_tokens=10) for p in prompts]
        # least-loaded routing alternates while nothing drains
        with pools[0]._lock, pools[1]._lock:
            q0 = len(pools[0]._queue)
            q1 = len(pools[1]._queue)
        assert (q0, q1) == (2, 2)
        router.run()
        for rid, p in zip(rids, prompts):
            out = router.result_wait(rid, timeout=60)
            assert out is not None
            np.testing.assert_array_equal(out[: p.size], p)
        # evict-on-read + unknown rid contract matches the pool's
        with pytest.raises(KeyError):
            router.result(rids[0])

    def test_replica_outputs_match_single_pool(self):
        """Routing must not change tokens: each replica is the same
        compiled math, so a request's row is identical whichever
        replica served it."""

        model, params = _setup()
        solo = PagedContinuousBatchingDecoder(
            model, params, slots=4, kv_block_size=16
        )
        p = np.arange(9, dtype=np.int32) % VOCAB
        rid = solo.submit(p, max_new_tokens=6)
        solo.run()
        want = solo.result(rid)

        router = PoolRouter([
            PagedContinuousBatchingDecoder(
                model, params, slots=4, kv_block_size=16,
                replica_label=str(i),
            )
            for i in range(3)
        ])
        rids = [router.submit(p, max_new_tokens=6) for _ in range(3)]
        router.run()
        for rid in rids:
            np.testing.assert_array_equal(router.result(rid), want)
