"""Fleet scheduler (ISSUE 16): priority quota queues + cross-job gang
preemption with graceful shed.

Unit tier for controller/scheduler.py — queue ordering (priority × age
with the anti-starvation boost), per-namespace quota accounting, victim
policy (lowest class → youngest grant → smallest checkpoint debt), the
checkpoint-freshness gate, shed-vs-revoke mechanics — plus the
reconciler integration (Queued/Preempted/Resumed conditions, teardown
and re-admission), backend victim routing (FakeCluster capacity shrink
through ``choose_victims`` instead of blind LIFO), and the
``GET /scheduler`` / ``tpujob queue`` read surface.  The contention
soak lives in tests/test_scheduler_soak.py (slow tier).
"""

import json
import time
import urllib.request

import pytest

from tests.testutil import harness, new_job, run_and_succeed_all
from tf_operator_tpu.api.defaults import set_defaults
from tf_operator_tpu.api.serde import job_from_dict, job_to_dict
from tf_operator_tpu.api.types import (
    JobConditionType,
    PodPhase,
    PRIORITY_CLASSES,
    ReplicaType,
    SchedulingSpec,
    priority_rank,
)
from tf_operator_tpu.api.validation import ValidationError, validate
from tf_operator_tpu.controller.scheduler import (
    Scheduler,
    gang_demand,
    slice_chips,
)
from tf_operator_tpu.utils.metrics import Metrics


def sjob(
    name="job",
    prio="standard",
    group="",
    slices=1,
    topo="v5e-8",
    namespace="default",
):
    j = new_job(
        name=name, namespace=namespace, tpu_slice=slices, tpu_topology=topo
    )
    j.spec.scheduling = SchedulingSpec(
        priority_class=prio, quota_group=group
    )
    return j


class Rig:
    """Pure-scheduler rig: a mutable job list as the lister, a settable
    capacity, a synthetic clock — no backend, no reconciler."""

    def __init__(self, capacity=None, **kw):
        self.metrics = Metrics()
        kw.setdefault("preemption_cooldown_seconds", 0.0)
        # rig tests simulate completion by dropping jobs from the
        # lister, so the absent-job grace is off unless under test
        kw.setdefault("missing_grace_seconds", 0.0)
        self.sched = Scheduler(metrics=self.metrics, **kw)
        self.jobs = []
        self.capacity = capacity
        self.decisions = []
        self.sched.attach(
            lambda: list(self.jobs),
            self.decisions.append,
            capacity=lambda: self.capacity,
        )

    def checkpoint(self, job, at):
        self.metrics.set(
            "checkpoint_last_success_unix", at, job=job.key
        )


# ---------------------------------------------------------------- api layer


class TestSpecSurface:
    def test_serde_round_trip_camel_case(self):
        j = sjob(prio="high", group="ml-research")
        d = job_to_dict(j)
        blk = d["spec"]["scheduling"]
        assert blk == {"priorityClass": "high", "quotaGroup": "ml-research"}
        back = job_from_dict(d)
        assert back.spec.scheduling.priority_class == "high"
        assert back.spec.scheduling.quota_group == "ml-research"

    def test_serde_omits_absent_scheduling_and_empty_fields(self):
        j = new_job(worker=1)
        assert "scheduling" not in job_to_dict(j)["spec"]
        j2 = sjob(prio="", group="")
        assert job_to_dict(j2)["spec"]["scheduling"] == {}
        assert job_from_dict(job_to_dict(j2)).spec.scheduling is not None

    def test_validation_rejects_unknown_class_and_bad_group(self):
        j = sjob(prio="urgent")
        with pytest.raises(ValidationError, match="priorityClass"):
            validate(j)
        j2 = sjob(group="Not_DNS")
        with pytest.raises(ValidationError, match="quotaGroup"):
            validate(j2)
        validate(sjob(prio="critical", group="team-a"))  # ok

    def test_defaults_scheduling_implies_gang(self):
        j = sjob()
        assert not j.spec.enable_gang_scheduling
        set_defaults(j)
        assert j.spec.enable_gang_scheduling  # whole-gang admission

    def test_priority_rank_order_and_unknown(self):
        ranks = [priority_rank(c) for c in PRIORITY_CLASSES]
        assert ranks == sorted(ranks) and len(set(ranks)) == len(ranks)
        assert priority_rank("bogus") == priority_rank("standard")

    def test_gang_demand_units(self):
        assert gang_demand(sjob(slices=2, topo="v5e-16")) == 32
        assert slice_chips(sjob(topo="v5e-16")) == 16
        j = new_job(worker=4)
        j.spec.scheduling = SchedulingSpec()
        assert gang_demand(j) == 0  # CPU-only gang: never contends


# ------------------------------------------------------------- queue order


class TestQueueOrder:
    def test_priority_then_age(self):
        r = Rig(capacity=0)  # nothing admits: pure ordering
        t0 = 1000.0
        r.jobs = [sjob("old-low", "low"), sjob("new-high", "high")]
        r.sched.evaluate_once(t0)
        q = [e["job"] for e in r.sched.snapshot()["queue"]]
        assert q == ["default/new-high", "default/old-low"]

    def test_age_boost_promotes_but_ties_break_by_age(self):
        r = Rig(capacity=0, age_boost_seconds=300.0)
        t0 = 1000.0
        r.jobs = [sjob("low", "low")]
        r.sched.evaluate_once(t0)
        r.jobs.append(sjob("high", "high"))
        # low has waited 700s -> boost 2, ties high's true rank 2;
        # the tie breaks by queued_since (older first)
        r.sched.evaluate_once(t0 + 700.0)
        q = [e["job"] for e in r.sched.snapshot()["queue"]]
        assert q == ["default/low", "default/high"]

    def test_positions_published_as_gauges(self):
        r = Rig(capacity=0)
        r.jobs = [sjob("a", "low"), sjob("b", "critical")]
        r.sched.evaluate_once(1000.0)
        g = r.metrics
        assert g.gauge("scheduler_queue_position", job="default/b") == 1.0
        assert g.gauge("scheduler_queue_position", job="default/a") == 2.0
        assert g.gauge(
            "scheduler_queued_since_unix", job="default/a"
        ) == 1000.0

    def test_admit_clears_queue_gauges_and_counts(self):
        r = Rig(capacity=16)
        r.jobs = [sjob("a")]
        r.sched.evaluate_once(1000.0)
        assert r.metrics.counter("scheduler_admitted_total") == 1.0
        assert (
            r.metrics.gauge_series("scheduler_queue_position") == {}
        )
        assert [d.action for d in r.decisions] == ["admit"]

    def test_lister_blip_does_not_forget_state(self):
        """A broken-watch re-list can briefly return a snapshot missing
        live jobs; the scheduler must ride it out (grace window) rather
        than forget the gang — forgetting resets queue age and double
        counts the re-admission (the contention soak caught this)."""

        r = Rig(capacity=16, missing_grace_seconds=10.0)
        r.jobs = [sjob("a")]
        r.sched.evaluate_once(1000.0)
        held = r.jobs
        r.jobs = []  # the blip
        r.sched.evaluate_once(1001.0)
        assert [
            e["job"] for e in r.sched.snapshot()["admitted"]
        ] == ["default/a"]
        r.jobs = held  # cache recovers
        r.sched.evaluate_once(1002.0)
        assert r.metrics.counter("scheduler_admitted_total") == 1.0
        assert [d.action for d in r.decisions] == ["admit"]
        # a REAL disappearance outlives the grace and is forgotten
        r.jobs = []
        r.sched.evaluate_once(1003.0)
        r.sched.evaluate_once(1020.0)
        assert r.sched.snapshot()["admitted"] == []

    def test_observed_terminal_job_forgotten_immediately(self):
        """The grace window only covers ABSENT jobs — one listed as
        terminal frees its chips on the very next sweep."""

        r = Rig(capacity=8, missing_grace_seconds=10.0)
        from tf_operator_tpu.controller.status import set_condition

        a = sjob("a")
        r.jobs = [a, sjob("b")]
        r.sched.evaluate_once(1000.0)
        set_condition(a, JobConditionType.SUCCEEDED, "JobSucceeded", "m")
        r.sched.evaluate_once(1001.0)
        assert [
            e["job"] for e in r.sched.snapshot()["admitted"]
        ] == ["default/b"]

    def test_stale_relist_cannot_resurrect_finished_job(self):
        """Terminal is sticky per uid: a stale informer re-list handing
        back an old pre-Succeeded copy of a finished job must not
        re-register (and re-admit) it.  A genuine recreation — same
        name, new uid — schedules normally."""

        from tf_operator_tpu.controller.status import set_condition

        r = Rig(capacity=8)
        done = sjob("a")
        done.metadata.uid = "uid-1"
        stale = sjob("a")  # the pre-terminal cached copy
        stale.metadata.uid = "uid-1"
        r.jobs = [done]
        r.sched.evaluate_once(1000.0)
        set_condition(done, JobConditionType.SUCCEEDED, "JobSucceeded", "m")
        r.sched.evaluate_once(1001.0)
        r.jobs = [stale]
        r.sched.evaluate_once(1002.0)
        assert r.sched.snapshot()["admitted"] == []
        assert r.metrics.counter("scheduler_admitted_total") == 1.0
        recreated = sjob("a")
        recreated.metadata.uid = "uid-2"
        r.jobs = [recreated]
        r.sched.evaluate_once(1003.0)
        assert [
            e["job"] for e in r.sched.snapshot()["admitted"]
        ] == ["default/a"]

    def test_decisions_only_on_transitions(self):
        """Anti-flap: a parked gang re-evaluated every sweep emits ONE
        queue decision, not one per sweep."""

        r = Rig(capacity=0)
        r.jobs = [sjob("a")]
        for i in range(5):
            r.sched.evaluate_once(1000.0 + i)
        assert [d.action for d in r.decisions] == ["queue"]


# ------------------------------------------------------------------- quota


class TestQuota:
    def test_group_at_limit_queues_with_reason(self):
        r = Rig(capacity=64)
        r.sched.set_quota("default", "team-a", 8)
        r.jobs = [sjob("a", group="team-a"), sjob("b", group="team-a")]
        r.sched.evaluate_once(1000.0)
        snap = r.sched.snapshot()
        assert [e["job"] for e in snap["admitted"]] == ["default/a"]
        (q,) = snap["queue"]
        assert q["reason"] == "QuotaExceeded"
        assert snap["quotas"]["default/team-a"] == {
            "limitChips": 8.0, "usedChips": 8.0,
        }
        assert r.metrics.gauge(
            "scheduler_quota_used_chips", quota="default/team-a"
        ) == 8.0
        # anti-flap: further sweeps add no decisions
        n = len(r.decisions)
        r.sched.evaluate_once(1001.0)
        assert len(r.decisions) == n

    def test_quota_is_never_helped_by_preemption(self):
        """A high-priority gang over ITS OWN quota must not evict
        anyone — quota is a hard cap, not a priority."""

        r = Rig(capacity=16)
        r.sched.set_quota("default", "team-a", 8)
        low = sjob("low", "low", group="team-a")
        r.jobs = [low]
        r.sched.evaluate_once(1000.0)
        r.checkpoint(low, 999.0)
        r.jobs.append(sjob("high", "high", group="team-a"))
        r.sched.evaluate_once(1001.0)
        snap = r.sched.snapshot()
        assert [e["job"] for e in snap["admitted"]] == ["default/low"]
        assert snap["queue"][0]["reason"] == "QuotaExceeded"
        assert r.metrics.counter(
            "scheduler_preemptions_total",
            victim_priority="low", reason="revoke",
        ) == 0.0

    def test_quota_frees_when_member_finishes(self):
        r = Rig(capacity=64)
        r.sched.set_quota("default", "team-a", 8)
        r.jobs = [sjob("a", group="team-a"), sjob("b", group="team-a")]
        r.sched.evaluate_once(1000.0)
        r.jobs = [r.jobs[1]]  # a finished (lister stops returning it)
        r.sched.evaluate_once(1001.0)
        assert [
            e["job"] for e in r.sched.snapshot()["admitted"]
        ] == ["default/b"]


# ----------------------------------------------------------- victim policy


class TestVictimPolicy:
    def test_choose_victims_lowest_class_then_youngest_grant(self):
        r = Rig(capacity=64)
        r.jobs = [sjob("lo", "low"), sjob("hi", "critical")]
        r.sched.evaluate_once(1000.0)
        order = r.sched.choose_victims([
            {"key": "default/hi", "chips": 8},      # oldest grant
            {"key": "default/unmanaged", "chips": 8},
            {"key": "default/lo", "chips": 8},      # newest grant
        ])
        # fleet "low" first, unmanaged ranks as the default class,
        # fleet "critical" last
        assert order == ["default/lo", "default/unmanaged", "default/hi"]

    def test_elective_preemption_picks_youngest_low(self):
        r = Rig(capacity=16)
        a, b = sjob("a", "low"), sjob("b", "low")
        r.jobs = [a]
        r.sched.evaluate_once(1000.0)
        r.jobs.append(b)
        r.sched.evaluate_once(1010.0)  # b admitted later (younger)
        r.checkpoint(a, 1010.0)
        r.checkpoint(b, 1010.0)
        r.jobs.append(sjob("h", "high"))
        r.sched.evaluate_once(1020.0)
        revoked = [d for d in r.decisions if d.action == "revoke"]
        assert [d.job_key for d in revoked] == ["default/b"]

    def test_checkpoint_gate_skips_stale_and_unknown(self):
        r = Rig(capacity=8, max_victim_checkpoint_age_seconds=900.0)
        low = sjob("low", "low")
        r.jobs = [low]
        r.sched.evaluate_once(1000.0)
        r.jobs.append(sjob("h", "high"))
        # no checkpoint at all -> skipped, high stays queued
        r.sched.evaluate_once(1010.0)
        assert r.metrics.counter(
            "scheduler_skipped_total", reason="checkpoint_stale"
        ) == 1.0
        assert [
            e["job"] for e in r.sched.snapshot()["queue"]
        ] == ["default/h"]
        # stale checkpoint -> still skipped
        r.checkpoint(low, 10_000.0)
        r.sched.evaluate_once(12_000.0)
        assert [
            e["job"] for e in r.sched.snapshot()["queue"]
        ] == ["default/h"]
        # fresh checkpoint -> gate opens, victim revoked
        r.checkpoint(low, 12_100.0)
        r.sched.evaluate_once(12_110.0)
        assert [
            e["job"] for e in r.sched.snapshot()["admitted"]
        ] == ["default/h"]

    def test_boosted_rank_never_evicts_higher_true_class(self):
        """The age boost reorders the QUEUE; it must never let a "low"
        evict an admitted "standard" (elective preemption compares
        TRUE class rank only)."""

        r = Rig(capacity=8, age_boost_seconds=100.0)
        std = sjob("std", "standard")
        r.jobs = [std]
        r.sched.evaluate_once(1000.0)
        r.checkpoint(std, 1000.0)
        r.jobs.append(sjob("low", "low"))
        # low has boost rank 5 >> standard's 1, but true rank 0 < 1
        r.sched.evaluate_once(1500.0)
        snap = r.sched.snapshot()
        assert [e["job"] for e in snap["admitted"]] == ["default/std"]
        assert [e["job"] for e in snap["queue"]] == ["default/low"]

    def test_all_or_nothing_preemption(self):
        """Victims that cannot cover the need free nothing — a
        half-preemption would kill work without admitting anyone."""

        r = Rig(capacity=8)
        low = sjob("low", "low", slices=1)  # 8 chips
        r.jobs = [low]
        r.sched.evaluate_once(1000.0)
        r.checkpoint(low, 1000.0)
        r.jobs.append(sjob("big", "high", slices=2))  # needs 16
        r.sched.evaluate_once(1010.0)
        snap = r.sched.snapshot()
        assert [e["job"] for e in snap["admitted"]] == ["default/low"]
        assert not [d for d in r.decisions if d.action == "revoke"]

    def test_preemption_cooldown_and_admit_grace(self):
        r = Rig(capacity=8, preemption_cooldown_seconds=30.0)
        low = sjob("low", "low")
        r.jobs = [low]
        r.sched.evaluate_once(1000.0)
        r.checkpoint(low, 1005.0)
        r.jobs.append(sjob("h", "high"))
        # within the fresh-admit grace: low may not be victimised yet
        r.sched.evaluate_once(1010.0)
        assert [
            e["job"] for e in r.sched.snapshot()["queue"]
        ] == ["default/h"]
        r.checkpoint(low, 1030.0)
        r.sched.evaluate_once(1031.0)  # grace over
        assert [
            e["job"] for e in r.sched.snapshot()["admitted"]
        ] == ["default/h"]


# ------------------------------------------------------------ shed/revoke


class TestShedAndRevoke:
    def _rig_with_big_low(self):
        r = Rig(capacity=24)
        big = sjob("big", "low", slices=2)  # 16 chips, 8/slice
        r.jobs = [big]
        r.sched.evaluate_once(1000.0)
        r.checkpoint(big, 1000.0)
        return r, big

    def test_multi_slice_victim_sheds_only_what_is_needed(self):
        r, big = self._rig_with_big_low()
        r.jobs.append(sjob("h1", "high"))  # 8 free of 24: admits clean
        r.sched.evaluate_once(1010.0)
        r.jobs.append(sjob("h2", "high"))  # full: big sheds one slice
        r.sched.evaluate_once(1020.0)
        (shed,) = [d for d in r.decisions if d.action == "shed"]
        assert shed.job_key == "default/big"
        assert shed.details["toSlices"] == 1
        assert r.sched.take_preemption("default/big") == 1
        blk = next(
            a for a in r.sched.snapshot()["admitted"]
            if a["job"] == "default/big"
        )
        assert blk["shedTo"] == 1 and blk["demandChips"] == 8
        assert r.metrics.counter(
            "scheduler_preemptions_total",
            victim_priority="low", reason="shed",
        ) == 1.0

    def test_apply_clamps_working_copy_to_shed_target(self):
        r, big = self._rig_with_big_low()
        r.jobs += [sjob("h1", "high"), sjob("h2", "high")]
        r.sched.evaluate_once(1010.0)
        r.sched.evaluate_once(1045.0)  # past h1's admit grace
        clone = big.clone()
        r.sched.apply(clone)
        assert clone.spec.replica_specs[
            ReplicaType.TPU_SLICE
        ].replicas == 1
        # the cached object is untouched
        assert big.spec.replica_specs[ReplicaType.TPU_SLICE].replicas == 2

    def test_single_slice_victim_revoked_whole(self):
        r = Rig(capacity=8)
        low = sjob("low", "low", slices=1)
        r.jobs = [low]
        r.sched.evaluate_once(1000.0)
        r.checkpoint(low, 1000.0)
        r.jobs.append(sjob("h", "high", slices=1))
        r.sched.evaluate_once(1010.0)
        (rev,) = [d for d in r.decisions if d.action == "revoke"]
        assert rev.job_key == "default/low"
        assert r.sched.take_revocation("default/low")["mode"] == "revoke"
        (q,) = r.sched.snapshot()["queue"]
        assert q["job"] == "default/low" and q["reason"] == "Preempted"

    def test_note_revoked_parks_synchronously(self):
        r = Rig(capacity=16)
        low = sjob("low", "low")
        r.jobs = [low]
        r.sched.evaluate_once(1000.0)
        r.sched.note_revoked("default/low", by="capacity-shrink")
        # parked immediately — no sweep needed
        assert r.sched.take_revocation("default/low") is not None
        (q,) = r.sched.snapshot()["queue"]
        assert q["reason"] == "Preempted"
        (rev,) = [d for d in r.decisions if d.action == "revoke"]
        assert "capacity-shrink" in rev.reason

    def test_health_block_is_stable_while_parked(self):
        """Throttle safety: the queued block carries the STABLE
        queuedSinceUnix stamp, so identical state compares equal across
        sweeps and cannot livelock the status-write throttle."""

        r = Rig(capacity=0)
        j = sjob("a")
        r.jobs = [j]
        r.sched.evaluate_once(1000.0)
        b1 = r.sched.health_block(j)
        r.sched.evaluate_once(1250.0)
        b2 = r.sched.health_block(j)
        assert b1 == b2
        assert b1["queuedSinceUnix"] == 1000.0


# ----------------------------------------------------- anti-starvation


class TestAntiStarvation:
    def test_low_priority_gang_admits_under_high_churn(self):
        """Satellite: sustained high-priority churn — a fresh high
        arrival every round, each finishing before the next — must not
        starve a parked low gang; the age boost eventually wins the
        tie and the low gang admits."""

        r = Rig(capacity=8, age_boost_seconds=300.0)
        low = sjob("low", "low")
        r.jobs = [low]
        t, admitted_at_round = 1000.0, None
        high = None
        for round_no in range(12):
            if high is not None:
                r.jobs.remove(high)  # previous high finished
            high = sjob(f"h{round_no}", "high")
            r.jobs.append(high)
            r.sched.evaluate_once(t)
            admitted = [
                e["job"] for e in r.sched.snapshot()["admitted"]
            ]
            if "default/low" in admitted:
                admitted_at_round = round_no
                break
            # high outranked low this round and took the pool
            assert admitted == [high.key]
            t += 120.0
        assert admitted_at_round is not None, "low gang starved"
        # and the boost needed real waiting: not the first rounds
        assert admitted_at_round >= 3
        # the displaced high queues behind the fact — visible, not lost
        assert [
            e["job"] for e in r.sched.snapshot()["queue"]
        ] == [high.key]


# ------------------------------------------------- reconciler integration


def sweep(c, sched, n=2):
    for _ in range(n):
        sched.evaluate_once()
        c.sync_until_quiet()


class TestReconcilerIntegration:
    def rig(self, total_chips=16, **kw):
        kw.setdefault("preemption_cooldown_seconds", 0.0)
        m = Metrics()
        sched = Scheduler(metrics=m, **kw)
        store, backend, c = harness(
            total_chips=total_chips, scheduler=sched
        )
        return store, backend, c, sched, m

    def test_queued_job_creates_nothing_and_shows_queued(self):
        store, backend, c, sched, m = self.rig(total_chips=0)
        store.create(sjob("a"))
        c.sync_until_quiet()
        sweep(c, sched)
        assert backend.created_pods == []
        st = store.get("default", "a").status
        cond = next(
            cd for cd in st.conditions
            if cd.type is JobConditionType.QUEUED
        )
        assert cond.status and cond.reason == "WaitingForCapacity"
        blk = st.observed_health["scheduler"]
        assert blk["phase"] == "queued" and blk["queuePosition"] == 1
        assert c.metrics.gauge(
            "tpujob_gang_waiting_replicas", job="default/a"
        ) == 2.0

    def test_admission_creates_pods_and_clears_queued(self):
        store, backend, c, sched, m = self.rig()
        store.create(sjob("a"))
        c.sync_until_quiet()
        sweep(c, sched)
        assert len(backend.created_pods) == 2  # v5e-8: 2 hosts/slice
        st = store.get("default", "a").status
        cond = next(
            cd for cd in st.conditions
            if cd.type is JobConditionType.QUEUED
        )
        assert not cond.status and cond.reason == "Admitted"
        # the controller relayed the decision as an event
        reasons = [
            e.reason for e in c.recorder.for_object("default/a")
        ]
        assert "Admitted" in reasons

    def test_elective_revoke_tears_down_and_resumes(self):
        store, backend, c, sched, m = self.rig()
        store.create(sjob("low", "low"))
        c.sync_until_quiet()
        sweep(c, sched)
        m.set(
            "checkpoint_last_success_unix", time.time(),
            job="default/low",
        )
        store.create(sjob("hi", "high", slices=2))  # needs whole pool
        c.sync_until_quiet()
        sweep(c, sched)
        st = store.get("default", "low").status
        assert any(
            cd.type is JobConditionType.PREEMPTED and cd.status
            and cd.reason == "GangRevoked"
            for cd in st.conditions
        )
        assert not [
            p for p in backend._pods.values()
            if p.metadata.name.startswith("low")
        ]
        events = c.recorder.for_object("default/low")
        assert any(
            e.reason == "Preempted" and e.type == "Warning"
            for e in events
        )
        # chips actually freed: hi runs
        assert len([
            p for p in backend._pods.values()
            if p.metadata.name.startswith("hi")
        ]) == 4
        # hi finishes -> low re-admits and resumes from checkpoint
        backend.run_all("default")
        for p in list(backend._pods.values()):
            backend.succeed_pod("default", p.metadata.name)
        c.sync_until_quiet()
        sweep(c, sched)
        backend.run_all("default")
        c.sync_until_quiet()
        st = store.get("default", "low").status
        assert any(
            cd.type is JobConditionType.RESUMED and cd.status
            and cd.reason == "ResumedFromCheckpoint"
            for cd in st.conditions
        )
        run_and_succeed_all(backend)
        c.sync_until_quiet()
        st = store.get("default", "low").status
        assert st.has_condition(JobConditionType.SUCCEEDED)

    def test_shed_bounces_slice_set_to_smaller_world(self):
        store, backend, c, sched, m = self.rig(total_chips=24)
        store.create(sjob("big", "low", slices=2))
        c.sync_until_quiet()
        sweep(c, sched)
        m.set(
            "checkpoint_last_success_unix", time.time(),
            job="default/big",
        )
        store.create(sjob("h1", "high"))
        c.sync_until_quiet()
        sweep(c, sched)
        store.create(sjob("h2", "high"))
        c.sync_until_quiet()
        sweep(c, sched)
        big_pods = [
            p.metadata.name
            for p in backend._pods.values()
            if p.metadata.name.startswith("big")
        ]
        assert sorted(big_pods) == ["big-tpuslice-0", "big-tpuslice-1"]
        st = store.get("default", "big").status
        assert any(
            cd.type is JobConditionType.PREEMPTED
            and cd.reason == "SliceShed"
            for cd in st.conditions
        )
        assert c.metrics.counter("tpujob_reshards_total") >= 1.0
        assert st.observed_health["scheduler"]["shedTo"] == 1

    def test_terminal_job_forgotten_and_gauges_cleared(self):
        store, backend, c, sched, m = self.rig()
        store.create(sjob("a"))
        c.sync_until_quiet()
        sweep(c, sched)
        run_and_succeed_all(backend)
        c.sync_until_quiet()
        sched.evaluate_once()
        assert sched.snapshot()["admitted"] == []
        assert m.gauge_series("scheduler_queue_position") == {}
        assert m.gauge_series("scheduler_queued_since_unix") == {}

    def test_unmanaged_jobs_bypass_the_queue_entirely(self):
        store, backend, c, sched, m = self.rig(total_chips=0)
        store.create(new_job("plain", worker=2))
        c.sync_until_quiet()
        assert len(backend.created_pods) == 2
        assert sched.snapshot()["queue"] == []


# ------------------------------------------------------ backend routing


class TestBackendVictimRouting:
    def test_capacity_shrink_revokes_by_class_not_lifo(self):
        """FakeCluster shrink with the scheduler attached revokes the
        LOWEST class even when it was granted first — blind LIFO would
        have killed the newest (high) gang."""

        m = Metrics()
        sched = Scheduler(metrics=m, preemption_cooldown_seconds=0.0)
        store, backend, c = harness(total_chips=16, scheduler=sched)
        store.create(sjob("low", "low"))   # granted FIRST (oldest)
        c.sync_until_quiet()
        sweep(c, sched)
        store.create(sjob("hi", "high"))   # granted second
        c.sync_until_quiet()
        sweep(c, sched)
        revoked = backend.set_total_chips(8)
        assert revoked == ["low"]
        c.sync_until_quiet()
        st = store.get("default", "low").status
        assert any(
            cd.type is JobConditionType.QUEUED and cd.status
            for cd in st.conditions
        )
        # the high gang never noticed
        st = store.get("default", "hi").status
        assert not any(
            cd.type is JobConditionType.PREEMPTED for cd in st.conditions
        )
        # attributed audit trail names the victim and the change
        events = c.recorder.for_object("default/low")
        assert any(
            e.reason == "Preempted" and "shrunk to 8" in e.message
            for e in events
        )
        assert m.counter(
            "scheduler_preemptions_total",
            victim_priority="low", reason="revoke",
        ) == 1.0

    def test_shrink_race_does_not_fail_the_victim(self):
        """The corpse race: syncs run between the backend's kill and
        the next scheduler sweep.  The synchronous note_revoked park
        means the victim reads Queued, never Failed."""

        m = Metrics()
        sched = Scheduler(metrics=m, preemption_cooldown_seconds=0.0)
        store, backend, c = harness(total_chips=16, scheduler=sched)
        store.create(sjob("a", "low"))
        store.create(sjob("b", "high"))
        c.sync_until_quiet()
        sweep(c, sched)
        backend.set_total_chips(8)
        c.sync_until_quiet()  # NO evaluate_once first — the race
        st = store.get("default", "a").status
        assert not st.has_condition(JobConditionType.FAILED)
        assert any(
            cd.type is JobConditionType.QUEUED and cd.status
            for cd in st.conditions
        )
        # capacity returns: the victim re-admits and succeeds
        backend.set_total_chips(16)
        sweep(c, sched)
        run_and_succeed_all(backend)
        c.sync_until_quiet()
        for name in ("a", "b"):
            st = store.get("default", name).status
            assert st.has_condition(JobConditionType.SUCCEEDED), name


# ------------------------------------------------------- read surfaces


class TestReadSurfaces:
    def test_get_scheduler_route(self):
        from tf_operator_tpu.server.api import ApiServer

        m = Metrics()
        sched = Scheduler(metrics=m)
        store, backend, c = harness(total_chips=0, scheduler=sched)
        server = ApiServer(
            store, backend, c.metrics, c.recorder, scheduler=sched
        )
        server.start()
        try:
            store.create(sjob("a", "high"))
            c.sync_until_quiet()
            sched.evaluate_once()
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/scheduler", timeout=10
            ) as r:
                snap = json.loads(r.read().decode())
            assert snap["queue"][0]["job"] == "default/a"
            assert snap["queue"][0]["priorityClass"] == "high"
            assert snap["decisions"][0]["action"] == "queue"
        finally:
            server.stop()

    def test_kubesim_debug_route(self):
        from tf_operator_tpu.backend.kubesim import MiniApiServer

        sim = MiniApiServer().start()
        try:
            with urllib.request.urlopen(
                f"{sim.url}/scheduler", timeout=10
            ) as r:
                snap = json.loads(r.read().decode())
            assert set(snap) >= {"queue", "admitted", "quotas", "decisions"}
        finally:
            sim.stop()

    def test_kubesim_admission_validates_scheduling(self):
        """Server-side admission covers the new block: an unknown
        priorityClass is rejected at POST time."""

        from tf_operator_tpu.backend.kubesim import MiniApiServer

        sim = MiniApiServer().start()
        try:
            bad = job_to_dict(sjob("bad", prio="urgent"))
            req = urllib.request.Request(
                f"{sim.url}/apis/tpujob.dist/v1/namespaces/default/tpujobs",
                data=json.dumps(bad).encode(),
                method="POST",
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code in (400, 422)
        finally:
            sim.stop()

    def test_cli_queue_renders_snapshot(self, capsys, monkeypatch):
        from tf_operator_tpu.cmd import tpujob as cli

        snap = {
            "queue": [{
                "job": "default/low", "priorityClass": "low",
                "quotaGroup": "default/default", "position": 1,
                "waitSeconds": 42.0, "demandChips": 8,
                "reason": "WaitingForCapacity",
            }],
            "admitted": [{
                "job": "default/hi", "priorityClass": "high",
                "quotaGroup": "default/default", "demandChips": 8,
                "admittedAt": 1.0, "shedTo": 1,
            }],
            "quotas": {"default/default": {
                "limitChips": None, "usedChips": 8.0,
            }},
            "decisions": [{
                "time": 1.0, "job": "default/hi", "action": "admit",
                "priorityClass": "high", "quotaGroup": "default/default",
                "reason": "rank 2 (high), waited 0s", "details": {},
            }],
        }
        monkeypatch.setattr(cli, "_request", lambda m, u, payload=None: snap)
        rc = cli.main(["queue"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "default/low" in out and "WaitingForCapacity" in out
        assert "shed to 1 replicas" in out or "shed to 1" in out
        assert "admit" in out

    def test_cli_describe_shows_scheduling_block(self, capsys, monkeypatch):
        from tf_operator_tpu.cmd import tpujob as cli

        job = job_to_dict(sjob("a", "low"))
        job["status"] = {
            "conditions": [],
            "replicaStatuses": {},
            "observedHealth": {
                "scheduler": {
                    "phase": "queued", "priorityClass": "low",
                    "quotaGroup": "default/default", "queuePosition": 2,
                    "queuedSinceUnix": time.time() - 30,
                    "reason": "WaitingForCapacity", "preemptions": 1,
                    "lastPreemption": {
                        "mode": "revoke", "by": "default/hi",
                        "action": "revoke", "reason": "gang revoked",
                    },
                },
            },
        }

        def fake_request(method, url, payload=None):
            if url.endswith("/events"):
                return {"items": []}
            if url.endswith("/metrics"):
                return {"items": []}
            return job

        monkeypatch.setattr(cli, "_request", fake_request)
        rc = cli.main(["describe", "a"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "Scheduling:" in out
        assert "position 2" in out
        assert "preemptions:      1" in out


# ------------------------------------------------------- serde of status


class TestConditionSerde:
    def test_new_condition_types_round_trip(self):
        from tf_operator_tpu.controller.status import set_condition

        j = sjob("a")
        set_condition(j, JobConditionType.QUEUED, "WaitingForCapacity", "m")
        set_condition(j, JobConditionType.PREEMPTED, "GangRevoked", "m")
        set_condition(j, JobConditionType.RESUMED, "ResumedFromCheckpoint", "m")
        back = job_from_dict(job_to_dict(j))
        types = {c.type for c in back.status.conditions}
        assert {
            JobConditionType.QUEUED,
            JobConditionType.PREEMPTED,
            JobConditionType.RESUMED,
        } <= types
