"""Chaos convergence: random operation sequences with randomly LOST
watch events must still converge once resync + expectation expiry run.

This is the strongest form of the race-correctness story (SURVEY.md §5
"Race detection"): the Expectations mechanism covers the in-flight
window, the informer resync covers lost events, and level-triggered
syncs make any intermediate state recoverable.  The property: after
arbitrary chaos, a few stabilization rounds leave every job either
terminal or fully materialised, and no deleted job leaves pods behind.
"""

import time

import pytest

try:
    from hypothesis import given, settings, strategies as st
except ImportError:  # boxes without hypothesis: property tests skip
    from tests.testutil import import_hypothesis_or_stubs

    given, settings, st = import_hypothesis_or_stubs()

from tests.testutil import new_job
from tf_operator_tpu.api.types import (
    LABEL_JOB_NAME,
    JobConditionType,
    PodPhase,
    RestartPolicy,
)
from tf_operator_tpu.backend.fake import FakeCluster
from tf_operator_tpu.backend.jobstore import JobStore
from tf_operator_tpu.controller.controller import TPUJobController


def chaos_harness():
    store = JobStore()
    backend = FakeCluster(delivery="manual")
    controller = TPUJobController(
        store,
        backend,
        resync_period=0,  # driven explicitly
        expectations_timeout=0.15,  # expire fast so lost ADDs heal in-test
    )
    return store, backend, controller


OPS = ("create", "run_all", "succeed", "fail", "delete", "pump", "drop", "sync")


class TestChaosConvergence:
    @settings(max_examples=20, deadline=None)
    @given(data=st.data())
    def test_converges_despite_lost_events(self, data):
        store, backend, c = chaos_harness()
        n_ops = data.draw(st.integers(min_value=5, max_value=30), label="n_ops")
        created = []
        deleted = set()

        for i in range(n_ops):
            op = data.draw(st.sampled_from(OPS), label=f"op-{i}")
            if op == "create" and len(created) < 5:
                name = f"chaos-{len(created)}"
                workers = data.draw(
                    st.integers(min_value=1, max_value=3), label=f"w-{i}"
                )
                job = new_job(name, worker=workers)
                # ON_FAILURE keeps failures non-terminal (restart loop)
                for spec in job.spec.replica_specs.values():
                    spec.restart_policy = RestartPolicy.ON_FAILURE
                store.create(job)
                created.append(name)
            elif op == "run_all":
                backend.run_all("default")
            elif op in ("succeed", "fail") and created:
                pods = backend.list_pods("default")
                if pods:
                    pod = pods[
                        data.draw(
                            st.integers(min_value=0, max_value=len(pods) - 1),
                            label=f"pick-{i}",
                        )
                    ]
                    if op == "succeed":
                        backend.succeed_pod("default", pod.metadata.name)
                    else:
                        backend.fail_pod("default", pod.metadata.name, exit_code=137)
            elif op == "delete" and created:
                name = created[
                    data.draw(
                        st.integers(min_value=0, max_value=len(created) - 1),
                        label=f"del-{i}",
                    )
                ]
                if name not in deleted:
                    try:
                        store.delete("default", name)
                        deleted.add(name)
                    except KeyError:
                        pass
            elif op == "pump":
                backend.pump(data.draw(st.integers(min_value=1, max_value=5)))
            elif op == "drop":
                # LOSE up to 3 pending watch events
                n = data.draw(st.integers(min_value=1, max_value=3), label=f"n-{i}")
                for _ in range(min(n, len(backend._pending_events))):
                    backend._pending_events.popleft()
            elif op == "sync":
                c.sync_until_quiet()

        # ---- stabilize: deliver what's left, resync, let expectations
        # expire, drain — repeatedly
        deadline = time.time() + 10.0
        while time.time() < deadline:
            backend.pump()
            c.resync()
            c.sync_until_quiet()
            if self._converged(store, backend):
                break
            time.sleep(0.16)  # expectation expiry window
        assert self._converged(store, backend), self._diagnose(store, backend)

    @staticmethod
    def _converged(store, backend) -> bool:
        jobs = {j.metadata.name: j for j in store.list("default")}
        pods = backend.list_pods("default")
        by_job = {}
        for p in pods:
            by_job.setdefault(p.metadata.labels.get(LABEL_JOB_NAME), []).append(p)
        # no pods for jobs that no longer exist
        for jname in by_job:
            if jname not in jobs:
                return False
        for name, job in jobs.items():
            if job.is_terminal():
                continue
            want = job.spec.total_pods()
            have = {
                p.replica_index
                for p in by_job.get(name, [])
                if p.phase is not PodPhase.FAILED
            }
            if have != set(range(want)):
                return False
        return True

    @staticmethod
    def _diagnose(store, backend) -> str:
        lines = []
        for j in store.list("default"):
            conds = [c.type.value for c in j.status.conditions if c.status]
            lines.append(f"job {j.metadata.name}: conds={conds}")
        for p in backend.list_pods("default"):
            lines.append(
                f"pod {p.metadata.name}: {p.phase.value} owner={p.metadata.owner_uid}"
            )
        return "\n".join(lines)


class TestThreadedSoak:
    def test_threaded_controller_churn(self):
        """Threaded workers + churn: many jobs created/completed/deleted
        concurrently with the resync loop running — no deadlocks, every
        job reaches a consistent end state."""

        store, backend, c = None, None, None
        store = JobStore()
        backend = FakeCluster(delivery="sync")
        c = TPUJobController(store, backend, resync_period=0.2)
        c.run(threadiness=4)
        try:
            n = 30
            for i in range(n):
                store.create(new_job(f"soak-{i}", chief=1, worker=2))
            deadline = time.time() + 20
            while time.time() < deadline:
                if all(
                    len(backend.list_pods("default", {LABEL_JOB_NAME: f"soak-{i}"})) == 3
                    for i in range(n)
                ):
                    break
                time.sleep(0.05)
            backend.run_all("default")
            for i in range(0, n, 3):
                backend.succeed_pod("default", f"soak-{i}-chief-0")
            for i in range(1, n, 3):
                store.delete("default", f"soak-{i}")

            def settled():
                for i in range(0, n, 3):
                    j = store.get("default", f"soak-{i}")
                    if j is None or not j.status.has_condition(
                        JobConditionType.SUCCEEDED
                    ):
                        return False
                for i in range(1, n, 3):
                    if backend.list_pods("default", {LABEL_JOB_NAME: f"soak-{i}"}):
                        return False
                for i in range(2, n, 3):
                    j = store.get("default", f"soak-{i}")
                    if j is None or not j.status.has_condition(
                        JobConditionType.RUNNING
                    ):
                        return False
                return True

            deadline = time.time() + 30
            while time.time() < deadline and not settled():
                time.sleep(0.1)
            assert settled()
        finally:
            c.stop()
            backend.close()
